"""Headline benchmark: GroupBy + TopN rows/sec on one TPU chip, plus the
batched-vs-per-segment dispatch-amortization comparison.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...,
"per_segment_rate", "batched_rate", "batch_speedup",
"sharded_decoded_rate", "sharded_packed_rate", "sharded_merge_host_ms",
"sharded_merge_device_ms", "packed_rate",
"filter_host_rate", "filter_device_rate", "filter_cache_hit_rate",
"decoded_rate", "pack_ratio", "fused_rate", "staged_rate",
"dispatch_count_fused", "dispatch_count_staged", "donated_tick_rate",
"rle_rate", "packed_only_rate", "cascade_ratio", "code_domain_rate",
"v1_load_rate", "v2_load_rate", "disk_ratio", "wire_bytes_v1",
"wire_bytes_v2", "hll_log2m12_rate",
"untraced_rate", "traced_rate", "trace_overhead"} — sharded_* compare
compressed-resident vs decoded cold-stack mesh execution plus the warm
device-merged vs host-merged tail; packed_* compare
compressed-domain vs decoded staging on the cold-miss H2D path; fused_*
compare the one-dispatch megakernel path vs the staged fill-wave path on
cold queries (dispatch_count_fused must be exactly 1); traced_* track
qtrace span overhead across BENCH_r* runs.

Config mirrors BASELINE.json: TPC-H-style GroupBy (2 dims, 3 aggs, numeric
bound filter) + TopN (1 dim, metric-ordered) over synthetic segments.
Baseline comparator: the reference whitepaper's per-core scan-aggregate rate
(36,246,530 rows/sec/core for sum-over-interval, druid.tex:882) — the Java
engine's upper bound; its GroupBy path is strictly slower.

Backend bring-up mirrors __graft_entry__.py: the chosen platform is pinned
UNCONDITIONALLY through both the env and the jax config before any backend
init (the environment's sitecustomize may pre-import jax with a TPU plugin),
and init runs under a hard watchdog. A wedged/unavailable accelerator
re-execs the benchmark once on the CPU backend instead of zeroing the run —
numbers on CPU beat no numbers at all.

Environment:
  DRUID_TPU_BENCH_PLATFORM  pin a jax platform (default: JAX_PLATFORMS/auto)
  DRUID_TPU_BENCH_ROWS      total headline rows (default 100_000_000)
  DRUID_TPU_BENCH_SEGMENTS  headline segment count (default 8)
  DRUID_TPU_BENCH_ITERS     timed iterations per query (default 5)
  DRUID_TPU_BENCH_BATCH_SEGMENTS  segments in the batch comparison (default 16)
  DRUID_TPU_BENCH_BATCH_ROWS      rows PER SEGMENT there (default 4096)
  DRUID_TPU_BENCH_INIT_TIMEOUT    backend-init watchdog seconds (default 600)
  DRUID_TPU_BENCH_CASCADE_SEGMENTS  cascade-comparison segments (default 8)
  DRUID_TPU_BENCH_CASCADE_ROWS      rows PER SEGMENT there (default 8192)
  DRUID_TPU_BENCH_SEGIO_ROWS        segment-io comparison rows (default 65536)
  DRUID_TPU_BENCH_CLIENTS         concurrent closed-loop clients (default 8)
  DRUID_TPU_BENCH_CLIENT_QUERIES  queries per client per mode (default 12)
  DRUID_TPU_BENCH_SCHED_ROWS      rows per segment in that mode (default 4096)
  DRUID_TPU_BENCH_SOAK            opt-in soak mode: N query waves + server
                                  start/stop cycles, reporting rss/fd/thread
                                  drift in the JSON line (default off)
"""
import json
import os
import sys
import time

import numpy as np


def log(msg):
    print(msg, file=sys.stderr, flush=True)


# -- headline configuration, shared with tools/chip_suite.py and
#    tools/chip_pallas_test.py so tuning/validation and the gate measure
#    the SAME shape -------------------------------------------------------

HEADLINE_SEED = 1234


def headline_interval():
    from druid_tpu.utils.intervals import Interval
    return Interval.of("2026-01-01", "2026-01-02")


def headline_segments(rows: int, n_segments: int):
    from druid_tpu.data.generator import ColumnSpec, DataGenerator
    schema = (
        ColumnSpec("dimA", "string", cardinality=100, distribution="uniform"),
        ColumnSpec("dimB", "string", cardinality=1000, distribution="zipf"),
        ColumnSpec("metLong", "long", low=0, high=10_000),
        ColumnSpec("metFloat", "float", distribution="normal", mean=100.0,
                   std=25.0),
    )
    gen = DataGenerator(schema, seed=HEADLINE_SEED)
    return gen.segments(n_segments, rows // n_segments, headline_interval(),
                        datasource="bench")


def headline_groupby():
    from druid_tpu.query.aggregators import (CountAggregator,
                                             FloatMaxAggregator,
                                             LongSumAggregator)
    from druid_tpu.query.filters import BoundFilter
    from druid_tpu.query.model import DefaultDimensionSpec, GroupByQuery
    return GroupByQuery.of(
        "bench", [headline_interval()],
        [DefaultDimensionSpec("dimA"), DefaultDimensionSpec("dimB")],
        [CountAggregator("rows"), LongSumAggregator("lsum", "metLong"),
         FloatMaxAggregator("fmax", "metFloat")],
        granularity="all",
        filter=BoundFilter("metLong", lower=100, upper=9_900,
                           ordering="numeric"))


def headline_topn(segments):
    from druid_tpu.query.aggregators import (CountAggregator,
                                             LongSumAggregator)
    from druid_tpu.query.filters import InFilter
    from druid_tpu.query.model import TopNQuery
    # filter on REAL dictionary values (half of dimA) — a padded-format
    # mismatch here would silently benchmark an empty-result query
    dimA_vals = list(segments[0].dims["dimA"].dictionary.values)
    assert len(dimA_vals) >= 100, "unexpected dimA cardinality"
    return TopNQuery.of(
        "bench", [headline_interval()], "dimB", "lsum", 100,
        [CountAggregator("rows"), LongSumAggregator("lsum", "metLong")],
        granularity="all",
        filter=InFilter("dimA", dimA_vals[0:100:2]))


def _fail(cause: str):
    # backend down/wedged: still emit ONE parseable JSON line so the
    # recorded failure carries its cause
    print(json.dumps({"metric": "groupby+topn_scan_rate", "value": 0,
                      "unit": "rows/sec/chip", "vs_baseline": 0,
                      "error": cause[:300]}), flush=True)


def _reexec_on_cpu(reason: str):
    """One-shot fallback: replace this process with a CPU-pinned retry.
    exec (not in-process re-init) because a wedged plugin thread is stuck
    in C and jax backends cannot be re-initialized once touched."""
    log(f"bench: {reason}; retrying once on the cpu backend")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               DRUID_TPU_BENCH_PLATFORM="cpu",
               _DRUID_TPU_BENCH_CPU_RETRY="1")
    os.execve(sys.executable,
              [sys.executable, os.path.abspath(__file__)] + sys.argv[1:],
              env)


def _init_backend():
    """Unconditional platform pin + backend-init watchdog
    (__graft_entry__._init_cpu_backend's discipline, generalized to the
    benchmark's chosen platform). Returns the device list or exits."""
    plat = os.environ.get("DRUID_TPU_BENCH_PLATFORM") \
        or os.environ.get("JAX_PLATFORMS")
    if plat:
        # belt: env pin for any jax import after this point
        os.environ["JAX_PLATFORMS"] = plat
    import jax
    if plat:
        # suspenders: backends initialize lazily, so flipping the config
        # before the first jax op wins even when jax was pre-imported with
        # a TPU plugin registered (same strategy as __graft_entry__.py)
        try:
            jax.config.update("jax_platforms", plat)
        except Exception:  # druidlint: disable=swallowed-exception
            pass          # backends already initialized: watchdog still guards

    # the TPU tunnel has two failure modes: fast "UNAVAILABLE" errors and
    # an indefinite hang inside backend init — watchdog both
    import threading
    init: dict = {}

    def _init():
        try:
            init["devices"] = jax.devices()
        except Exception as e:   # ANY init failure must reach the JSON line
            init["error"] = f"{type(e).__name__}: {e}"

    t = threading.Thread(target=_init, daemon=True,
                         name="jax-backend-init-watchdog")
    t.start()
    t.join(timeout=float(os.environ.get("DRUID_TPU_BENCH_INIT_TIMEOUT",
                                        600)))
    can_fall_back = (plat or "") != "cpu" \
        and not os.environ.get("_DRUID_TPU_BENCH_CPU_RETRY")
    if t.is_alive():
        if can_fall_back:
            _reexec_on_cpu("backend init hung (TPU tunnel wedged)")
        _fail("backend init hung (TPU tunnel wedged)")
        os._exit(1)          # the init thread is stuck in C — hard exit
    if "devices" not in init:
        cause = f"backend unavailable: {init.get('error', 'no devices')}"
        if can_fall_back:
            _reexec_on_cpu(cause)
        _fail(cause)
        sys.exit(1)
    log(f"devices: {init['devices']}")
    return init["devices"]


def batch_groupby():
    """The batch-comparison query: 1 dim / 3 aggs / numeric filter. A SMALL
    group space (cardinality 100) on purpose — per-segment device compute
    is tiny there, so the measurement isolates what batching amortizes
    (dispatch round-trips + per-call overheads), not scatter throughput."""
    from druid_tpu.query.aggregators import (CountAggregator,
                                             FloatMaxAggregator,
                                             LongSumAggregator)
    from druid_tpu.query.filters import BoundFilter
    from druid_tpu.query.model import DefaultDimensionSpec, GroupByQuery
    return GroupByQuery.of(
        "bench", [headline_interval()], [DefaultDimensionSpec("dimA")],
        [CountAggregator("rows"), LongSumAggregator("lsum", "metLong"),
         FloatMaxAggregator("fmax", "metFloat")],
        granularity="all",
        filter=BoundFilter("metLong", lower=100, upper=9_900,
                           ordering="numeric"))


def _bench_batching(iters: int):
    """Per-path comparison at many small same-schema segments: the
    dispatch-amortization story in one number. Runs batch_groupby()
    meshless, once with batching forced off (one device dispatch per
    segment) and once on (one dispatch per shape bucket)."""
    from druid_tpu.engine import batching
    from druid_tpu.engine.executor import QueryExecutor

    n_segments = int(os.environ.get("DRUID_TPU_BENCH_BATCH_SEGMENTS", 16))
    rows_per_seg = int(os.environ.get("DRUID_TPU_BENCH_BATCH_ROWS", 4096))
    segments = headline_segments(rows_per_seg * n_segments, n_segments)
    total_rows = sum(s.n_rows for s in segments)
    query = batch_groupby()
    executor = QueryExecutor(segments)    # meshless: the batched path's home

    rates = {}
    prev = batching.enabled()
    before = batching.stats().snapshot()
    try:
        for label, on in (("per_segment", False), ("batched", True)):
            batching.set_enabled(on)
            t = time.time()
            executor.run(query)
            log(f"batch-bench warmup {label}: {time.time() - t:.2f}s")
            times = []
            for _ in range(max(iters, 3)):
                t = time.time()
                executor.run(query)
                times.append(time.time() - t)
            best = min(times)
            rates[label] = total_rows / best
            log(f"batch-bench {label}: best {best * 1e3:.1f}ms over "
                f"{len(times)} iters -> {rates[label] / 1e6:.1f}M rows/s")
    finally:
        batching.set_enabled(prev)
    # fill ratio over THIS comparison's dispatches only — the headline
    # queries may themselves have batched into the process-wide stats
    after = batching.stats().snapshot()
    d_rows = after["stackedRows"] - before["stackedRows"]
    d_slots = after["stackedSlots"] - before["stackedSlots"]
    fill = d_rows / d_slots if d_slots else 0.0
    log(f"batch-bench stats: +{after['batches'] - before['batches']} "
        f"dispatches, fill {fill:.3f}")
    return {
        "per_segment_rate": round(rates["per_segment"], 0),
        "batched_rate": round(rates["batched"], 0),
        "batch_speedup": round(rates["batched"] / rates["per_segment"], 2),
        "batch_segments": n_segments,
        "batch_fill_ratio": round(fill, 3),
    }


def _bench_sharded(iters: int):
    """Pod-scale mesh comparison over the batch-shape segments: the
    compressed-resident sharded path (one shard_map dispatch, partials
    merged in-program with collectives) on whatever mesh the backend
    offers. The rate pair is COLD-STACK: the stacked block is released
    before every timed iteration so each run pays the full stack-build +
    H2D tax — once compressed-resident (packed words + cascade
    descriptors ride the mesh and decode in-program) and once decoded.
    The merge pair is WARM and times the two tail disciplines over
    identical segments: the meshless path (per-segment/batched dispatch,
    partials merged on the host — the broker tail the sharded path
    replaced) vs the single sharded dispatch."""
    import jax

    from druid_tpu.data import cascade as cascade_mod
    from druid_tpu.data import packed as packed_mod
    from druid_tpu.data.devicepool import device_pool
    from druid_tpu.engine.executor import QueryExecutor
    from druid_tpu.parallel import distributed, make_mesh, use_mesh

    n_dev = len(jax.devices())
    n_segments = int(os.environ.get("DRUID_TPU_BENCH_BATCH_SEGMENTS", 16))
    rows_per_seg = int(os.environ.get("DRUID_TPU_BENCH_BATCH_ROWS", 4096))
    segments = headline_segments(rows_per_seg * n_segments, n_segments)
    total_rows = sum(s.n_rows for s in segments)
    query = batch_groupby()
    executor = QueryExecutor(segments)
    mesh = make_mesh()
    before = distributed.sharded_stats().snapshot()

    def timed_sharded(label, cold_stack):
        with use_mesh(mesh):
            t = time.time()
            executor.run(query)
            log(f"sharded-bench warmup {label}: {time.time() - t:.2f}s")
            times = []
            for _ in range(max(iters, 3)):
                if cold_stack:
                    distributed.clear_stack_cache()
                t = time.time()
                executor.run(query)
                times.append(time.time() - t)
        return min(times)

    rates = {}
    for label, on in (("packed", True), ("decoded", False)):
        prev_p = packed_mod.set_enabled(on)
        prev_c = cascade_mod.set_enabled(on)
        try:
            distributed.clear_stack_cache()
            best = timed_sharded(label, cold_stack=True)
        finally:
            packed_mod.set_enabled(prev_p)
            cascade_mod.set_enabled(prev_c)
        rates[label] = total_rows / best
        log(f"sharded-bench {label}: best {best * 1e3:.1f}ms cold-stack "
            f"over {n_dev} device(s) -> {rates[label] / 1e6:.1f}M rows/s")

    # merge tails, warm: device = one sharded dispatch (collective merge
    # in-program, the host only converts representations); host = the
    # meshless path over the same segments (partials host-merged)
    t_dev = timed_sharded("merge-device", cold_stack=False)
    t = time.time()
    executor.run(query)
    log(f"sharded-bench warmup merge-host: {time.time() - t:.2f}s")
    host_times = []
    for _ in range(max(iters, 3)):
        t = time.time()
        executor.run(query)
        host_times.append(time.time() - t)
    t_host = min(host_times)
    log(f"sharded-bench merge tails: device {t_dev * 1e3:.1f}ms vs "
        f"host {t_host * 1e3:.1f}ms warm")

    after = distributed.sharded_stats().snapshot()
    if after[0] <= before[0]:
        raise RuntimeError("sharded path never dispatched — fell back to "
                           "the host-merged path")
    snap = device_pool().snapshot()
    return {
        "sharded_decoded_rate": round(rates["decoded"], 0),
        "sharded_packed_rate": round(rates["packed"], 0),
        "sharded_merge_host_ms": round(t_host * 1e3, 2),
        "sharded_merge_device_ms": round(t_dev * 1e3, 2),
        "sharded_devices": n_dev,
        "sharded_stack_ratio": round(snap.stacked_ratio, 3),
    }


def _bench_packed(iters: int):
    """Compressed-domain cold-miss comparison: the batch query over the
    small-segment shape with the device pool CLEARED before every timed
    run, so each run pays the full H2D staging tax — once with bit-packed
    staging (data/packed.py) and once decoded. The packed win is the
    smaller bus transfer + the pool holding pack-ratio more segments;
    pack_ratio reports the measured decoded/actual byte ratio of the
    packed run's pool residency."""
    from druid_tpu.data import packed
    from druid_tpu.data.devicepool import device_pool
    from druid_tpu.engine.executor import QueryExecutor

    n_segments = int(os.environ.get("DRUID_TPU_BENCH_BATCH_SEGMENTS", 16))
    rows_per_seg = int(os.environ.get("DRUID_TPU_BENCH_BATCH_ROWS", 4096))
    segments = headline_segments(rows_per_seg * n_segments, n_segments)
    total_rows = sum(s.n_rows for s in segments)
    query = batch_groupby()
    executor = QueryExecutor(segments)
    pool = device_pool()

    rates = {}
    pack_ratio = 0.0
    for label, on in (("decoded", False), ("packed", True)):
        prev = packed.set_enabled(on)
        try:
            t = time.time()
            executor.run(query)          # warm: compile once per mode
            log(f"packed-bench warmup {label}: {time.time() - t:.2f}s")
            times = []
            for _ in range(max(iters, 3)):
                pool.clear()             # force the cold-miss H2D path
                t = time.time()
                executor.run(query)
                times.append(time.time() - t)
            if on:
                pack_ratio = pool.snapshot().packed_ratio
        finally:
            packed.set_enabled(prev)
        best = min(times)
        rates[label] = total_rows / best
        log(f"packed-bench {label}: best {best * 1e3:.1f}ms over "
            f"{len(times)} cold iters -> {rates[label] / 1e6:.1f}M rows/s")
    log(f"packed-bench pool pack ratio: {pack_ratio:.2f}x")
    return {
        "packed_rate": round(rates["packed"], 0),
        "decoded_rate": round(rates["decoded"], 0),
        "pack_ratio": round(pack_ratio, 3),
    }


def _bench_filter(iters: int):
    """Selective-filter comparison (filter passes ~5% of 16×4096 rows,
    groupBy on a different dim): the device-bitmap filter path
    (engine/filters.py — resident packed words + in-program bit test) vs
    the LUT/column path, COLD (pool cleared before every timed iter, so
    each run pays full staging: the device path ships 1 bit/row of filter
    state instead of a 4-byte/row id column), plus the WARM
    filter_cache_hit_rate (resident filter results skipping the algebra)."""
    from druid_tpu.data.devicepool import device_pool
    from druid_tpu.engine import filters as filters_mod
    from druid_tpu.engine.executor import QueryExecutor
    from druid_tpu.query.aggregators import CountAggregator, LongSumAggregator
    from druid_tpu.query.filters import InFilter
    from druid_tpu.query.model import DefaultDimensionSpec, GroupByQuery

    n_segments = int(os.environ.get("DRUID_TPU_BENCH_BATCH_SEGMENTS", 16))
    rows_per_seg = int(os.environ.get("DRUID_TPU_BENCH_BATCH_ROWS", 4096))
    segments = headline_segments(rows_per_seg * n_segments, n_segments)
    total_rows = sum(s.n_rows for s in segments)
    dimA_vals = list(segments[0].dims["dimA"].dictionary.values)
    query = GroupByQuery.of(
        "bench", [headline_interval()], [DefaultDimensionSpec("dimB")],
        [CountAggregator("rows"), LongSumAggregator("lsum", "metLong")],
        granularity="all",
        # uniform dimA: k of 100 values ≈ k% selectivity; dimA is
        # filter-ONLY, so the device path never stages its id column
        filter=InFilter("dimA", dimA_vals[: max(len(dimA_vals) // 20, 1)]))
    executor = QueryExecutor(segments)
    pool = device_pool()

    rates = {}
    for label, on in (("host", False), ("device", True)):
        prev = filters_mod.set_device_bitmap_enabled(on)
        try:
            t = time.time()
            executor.run(query)
            log(f"filter-bench warmup {label}: {time.time() - t:.2f}s")
            times = []
            for _ in range(max(iters, 3)):
                pool.clear()             # cold: full staging every iter
                t = time.time()
                executor.run(query)
                times.append(time.time() - t)
        finally:
            filters_mod.set_device_bitmap_enabled(prev)
        rates[label] = total_rows / min(times)
        log(f"filter-bench {label}: best {min(times) * 1e3:.1f}ms over "
            f"{len(times)} cold iters -> {rates[label] / 1e6:.1f}M rows/s")

    # warm: resident filter results — two uncleared device-mode runs, hit
    # rate over the second run's probes
    prev = filters_mod.set_device_bitmap_enabled(True)
    try:
        executor.run(query)
        s0 = filters_mod.filter_bitmap_stats().snapshot()
        executor.run(query)
        s1 = filters_mod.filter_bitmap_stats().snapshot()
    finally:
        filters_mod.set_device_bitmap_enabled(prev)
    d_hits = s1["hits"] - s0["hits"]
    probes = d_hits + (s1["misses"] - s0["misses"])
    hit_rate = d_hits / probes if probes else 0.0
    log(f"filter-bench warm cache hit rate: {hit_rate:.3f} "
        f"({d_hits}/{probes} probes)")
    return {
        "filter_host_rate": round(rates["host"], 0),
        "filter_device_rate": round(rates["device"], 0),
        "filter_speedup": round(rates["device"] / rates["host"], 2),
        "filter_cache_hit_rate": round(hit_rate, 3),
    }


def _bench_fused(iters: int):
    """Megakernel comparison: a bitmap-eligible filter on a filter-only
    dim, groupBy on another dim, per-segment execution (batching off) —
    the shape where the staged path pays a bitmap fill dispatch PLUS the
    aggregation dispatch per cold segment and the fused path
    (engine/megakernel.py) pays exactly one program per segment. The pool
    is cleared before every timed iteration so each run is a true cold
    query (full staging both modes; the delta is the fill-dispatch work),
    and rounds INTERLEAVE the modes so machine-load drift cancels.
    dispatch_count_* come from a dedicated single-segment cold run per
    mode via the obs dispatch counter — the megakernel's one-dispatch
    contract as a recorded number. donated_tick_rate is the WARM
    repeated-execution rate through the fused path (the scheduler-tick
    shape whose partial buffers donate in place on accelerator
    backends)."""
    from druid_tpu.data.devicepool import device_pool
    from druid_tpu.engine import batching, megakernel
    from druid_tpu.engine.executor import QueryExecutor
    from druid_tpu.obs import dispatch as dispatch_mod
    from druid_tpu.query.aggregators import CountAggregator, LongSumAggregator
    from druid_tpu.query.filters import InFilter
    from druid_tpu.query.model import DefaultDimensionSpec, GroupByQuery

    # many SMALL segments: per-query fixed cost amortizes over 2N staged
    # dispatches vs N fused ones, so the fused margin is structural
    n_segments = int(os.environ.get("DRUID_TPU_BENCH_FUSED_SEGMENTS", 8))
    rows_per_seg = int(os.environ.get("DRUID_TPU_BENCH_FUSED_ROWS", 2048))
    segments = headline_segments(rows_per_seg * n_segments, n_segments)
    total_rows = sum(s.n_rows for s in segments)
    dimA_vals = list(segments[0].dims["dimA"].dictionary.values)
    query = GroupByQuery.of(
        "bench", [headline_interval()], [DefaultDimensionSpec("dimB")],
        [CountAggregator("rows"), LongSumAggregator("lsum", "metLong")],
        granularity="all",
        filter=InFilter("dimA", dimA_vals[: max(len(dimA_vals) // 20, 1)]))
    executor = QueryExecutor(segments)
    single = QueryExecutor(segments[:1])
    pool = device_pool()

    modes = (("staged", False), ("fused", True))
    dispatches = {}
    pb = batching.set_enabled(False)     # per-segment: the megaize path
    try:
        for label, on in modes:
            prev = megakernel.set_enabled(on)
            try:
                t = time.time()
                executor.run(query)      # warm: compile both programs
                log(f"fused-bench warmup {label}: {time.time() - t:.2f}s")
                single.run(query)
                pool.clear()             # dedicated cold dispatch count:
                d0 = dispatch_mod.count()    # ONE segment, ONE cold query
                single.run(query)
                dispatches[label] = dispatch_mod.count() - d0
            finally:
                megakernel.set_enabled(prev)
        times = {label: [] for label, _ in modes}
        for _ in range(max(iters, 5)):
            for label, on in modes:
                prev = megakernel.set_enabled(on)
                try:
                    pool.clear()         # cold: full staging every iter
                    t = time.time()
                    executor.run(query)
                    times[label].append(time.time() - t)
                finally:
                    megakernel.set_enabled(prev)
    finally:
        batching.set_enabled(pb)
    rates = {label: total_rows / min(ts) for label, ts in times.items()}
    for label, _ in modes:
        log(f"fused-bench {label}: best {min(times[label]) * 1e3:.1f}ms "
            f"over {len(times[label])} cold iters "
            f"(single-segment cold = {dispatches[label]} dispatch(es)) "
            f"-> {rates[label] / 1e6:.1f}M rows/s")

    # warm repeated execution through the fused path — the scheduler-tick
    # shape; on accelerator backends the partial grids donate in place.
    # Batching stays OFF here too: the batched path never megaizes, so
    # re-enabling it would time the wrong code path.
    prev = megakernel.set_enabled(True)
    pb = batching.set_enabled(False)
    d0 = megakernel.stats().snapshot()["donatedBytes"]
    try:
        executor.run(query)
        ticks = max(iters, 3)
        t0 = time.time()
        for _ in range(ticks):
            executor.run(query)
        tick_rate = total_rows * ticks / (time.time() - t0)
    finally:
        batching.set_enabled(pb)
        megakernel.set_enabled(prev)
    d_donated = megakernel.stats().snapshot()["donatedBytes"] - d0
    log(f"fused-bench donated ticks: {ticks} warm run(s) "
        f"-> {tick_rate / 1e6:.1f}M rows/s (donated {d_donated}B)")
    return {
        "fused_rate": round(rates["fused"], 0),
        "staged_rate": round(rates["staged"], 0),
        "fused_speedup": round(rates["fused"] / rates["staged"], 2),
        "dispatch_count_fused": dispatches["fused"],
        "dispatch_count_staged": dispatches["staged"],
        "donated_tick_rate": round(tick_rate, 0),
    }


def cascade_segments(n_segments: int, rows: int):
    """Rollup-shaped RLE-friendly segments: dimension-sorted rows,
    near-constant time, a constant rollup count metric and a run-aligned
    small-range value metric — the skewed-real-data shape the cascade
    rungs (data/cascade.py) exist for."""
    from druid_tpu.data.segment import SegmentBuilder
    iv = headline_interval()
    card = 64
    reps = -(-rows // card)
    segs = []
    for si in range(n_segments):
        b = SegmentBuilder("cascade", iv, version="v0", partition=si)
        dim_a = np.repeat([f"a{i:04d}" for i in range(card)], reps)[:rows]
        dim_b = np.repeat([f"b{i:04d}" for i in range(card)], reps)[:rows]
        time = iv.start + (np.arange(rows, dtype=np.int64) // 64)
        val = np.repeat((np.arange(card) * 37) % 1000, reps)[:rows]
        b.add_columns(time, {"dimA": dim_a.tolist(), "dimB": dim_b.tolist()},
                      {"cnt": np.ones(rows, dtype=np.int64),
                       "val": val.astype(np.int64)})
        segs.append(b.build())
    return segs


def _bench_cascade(iters: int):
    """Cascaded-encodings comparison (data/cascade.py) on the RLE-friendly
    rollup shape, pool CLEARED before every timed iteration:

      rle_rate          cold rate with the cascade rungs on, through the
                        ROW program (run-domain pinned off — since the
                        uniform-granularity rung even the hour query
                        would ride run space), vs packed-only (logged);
      cascade_ratio     decoded-equivalent / actual bytes of the
                        cascade-encoded pool entries after the cold run;
      code_domain_rate  WARM rate of the run-domain-eligible variant
                        (granularity all): the whole aggregation over run
                        metadata, zero unpack, zero row-width staging.
    """
    from druid_tpu.data import cascade
    from druid_tpu.data.devicepool import device_pool
    from druid_tpu.engine.executor import QueryExecutor
    from druid_tpu.query.aggregators import (CountAggregator,
                                             LongSumAggregator)
    from druid_tpu.query.filters import InFilter
    from druid_tpu.query.model import DefaultDimensionSpec, GroupByQuery

    n_segments = int(os.environ.get("DRUID_TPU_BENCH_CASCADE_SEGMENTS", 8))
    rows_per_seg = int(os.environ.get("DRUID_TPU_BENCH_CASCADE_ROWS", 8192))
    segments = cascade_segments(n_segments, rows_per_seg)
    total_rows = sum(s.n_rows for s in segments)
    dim_b_vals = list(segments[0].dims["dimB"].dictionary.values)
    aggs = [CountAggregator("rows"), LongSumAggregator("c", "cnt"),
            LongSumAggregator("v", "val")]
    flt = InFilter("dimB", dim_b_vals[::2])
    row_query = GroupByQuery.of(
        "cascade", [headline_interval()], [DefaultDimensionSpec("dimA")],
        aggs, granularity="hour", filter=flt)
    run_query = GroupByQuery.of(
        "cascade", [headline_interval()], [DefaultDimensionSpec("dimA")],
        aggs, granularity="all", filter=flt)
    executor = QueryExecutor(segments)
    pool = device_pool()

    rates = {}
    cascade_ratio = 0.0
    # rle_rate/cascade_ratio measure the ROW program's STAGED bytes: the
    # uniform-granularity run-domain rung would serve this hour-aligned
    # shape from run tables with no column staging at all, so it is
    # pinned off here (code_domain_rate below measures it on)
    prev_rd = cascade.set_run_domain_enabled(False)
    try:
        for label, on in (("packed_only", False), ("cascade", True)):
            prev = cascade.set_enabled(on)
            try:
                t = time.time()
                executor.run(row_query)  # warm: compile once per mode
                log(f"cascade-bench warmup {label}: "
                    f"{time.time() - t:.2f}s")
                times = []
                for _ in range(max(iters, 3)):
                    pool.clear()         # force the cold-miss H2D path
                    t = time.time()
                    executor.run(row_query)
                    times.append(time.time() - t)
                if on:
                    cascade_ratio = pool.snapshot().cascade_ratio
            finally:
                cascade.set_enabled(prev)
            rates[label] = total_rows / min(times)
            log(f"cascade-bench {label}: best {min(times) * 1e3:.1f}ms "
                f"over {len(times)} cold iters -> "
                f"{rates[label] / 1e6:.1f}M rows/s")
    finally:
        # restored in a finally: main() swallows bench-section failures,
        # and leaving run-domain off would silently poison every later
        # section's numbers in the same JSON line
        cascade.set_run_domain_enabled(prev_rd)
    log(f"cascade-bench pool cascade ratio: {cascade_ratio:.2f}x")

    # code-domain: warm repeated execution of the run-space variant
    prev = cascade.set_enabled(True)
    try:
        executor.run(run_query)          # warm: run tables + compile
        h0 = cascade.code_domain_stats().snapshot()["hits"]
        ticks = max(iters, 3)
        t0 = time.time()
        for _ in range(ticks):
            executor.run(run_query)
        code_rate = total_rows * ticks / (time.time() - t0)
        hits = cascade.code_domain_stats().snapshot()["hits"] - h0
    finally:
        cascade.set_enabled(prev)
    log(f"cascade-bench code-domain: {ticks} warm run(s), {hits} run-space "
        f"executions -> {code_rate / 1e6:.1f}M rows/s")
    return {
        "rle_rate": round(rates["cascade"], 0),
        "packed_only_rate": round(rates["packed_only"], 0),
        "cascade_ratio": round(cascade_ratio, 3),
        "code_domain_rate": round(code_rate, 0),
    }


def _bench_segment_io(iters: int):
    """Segment format V1 vs V2 (storage/format_v2.py) on the RLE-friendly
    rollup shape:

      v1_load_rate / v2_load_rate  rows/s of a cold load_segment() from a
                                   freshly persisted directory (V2 is mmap
                                   + descriptor reconstruction — the block
                                   codec never runs for eligible columns);
      disk_ratio                   V1 on-disk bytes / V2 on-disk bytes;
      wire_bytes_v1 / wire_bytes_v2  dumps_partials payload size for the
                                   same AggregatePartials, raw (version-1)
                                   vs compressed (version-2) wire mode.
    """
    import shutil
    import tempfile

    from druid_tpu.cluster import wire
    from druid_tpu.cluster.view import DataNode
    from druid_tpu.query.aggregators import (CountAggregator,
                                             LongSumAggregator)
    from druid_tpu.query.model import DefaultDimensionSpec, GroupByQuery
    from druid_tpu.storage.format import load_segment, persist_segment
    from druid_tpu.storage.format_v2 import persist_segment_v2

    rows = int(os.environ.get("DRUID_TPU_BENCH_SEGIO_ROWS", 65536))
    seg = cascade_segments(1, rows)[0]
    tmp = tempfile.mkdtemp(prefix="bench-segio-")
    try:
        d1 = os.path.join(tmp, "v1")
        d2 = os.path.join(tmp, "v2")
        b1 = persist_segment(seg, d1)
        b2 = persist_segment_v2(seg, d2)

        def load_rate(d):
            times = []
            for _ in range(max(iters, 3)):
                t = time.time()
                s = load_segment(d)
                times.append(time.time() - t)
                del s  # V2 holds mmaps via its mapper; drop before rmtree
            return rows / min(times)

        r1 = load_rate(d1)
        r2 = load_rate(d2)
        log(f"segio-bench load: v1 {r1 / 1e6:.1f}M rows/s, "
            f"v2 {r2 / 1e6:.1f}M rows/s "
            f"(disk {b1} -> {b2} bytes, {b1 / b2:.2f}x)")

        # wire: partials for a granularity-hour groupBy over the rollup
        # shape — the per-bucket states are heavily repeated, the shape
        # the wire rle/narrow encodings exist for
        node = DataNode("bench-segio")
        node.load_segment(seg)
        query = GroupByQuery.of(
            "cascade", [headline_interval()], [DefaultDimensionSpec("dimA")],
            [CountAggregator("rows"), LongSumAggregator("c", "cnt")],
            granularity="hour")
        ap, served = node.run_partials(query, [str(seg.id)])
        w1 = len(wire.dumps_partials(ap, served, compress=False))
        w2 = len(wire.dumps_partials(ap, served, compress=True))
        log(f"segio-bench wire: raw {w1} -> compressed {w2} bytes "
            f"({w1 / max(w2, 1):.2f}x)")
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return {
        "v1_load_rate": round(r1, 0),
        "v2_load_rate": round(r2, 0),
        "disk_ratio": round(b1 / b2, 3),
        "wire_bytes_v1": w1,
        "wire_bytes_v2": w2,
    }


def _bench_hll(iters: int):
    """hyperUnique/cardinality at a NON-default register count (log2m=12;
    the ROADMAP-carried rider): per-core rate of a groupBy carrying a
    4096-register sketch, so sketch-width regressions show up in BENCH_r*
    instead of only at the default 2048 registers."""
    from druid_tpu.engine.executor import QueryExecutor

    n_segments = int(os.environ.get("DRUID_TPU_BENCH_BATCH_SEGMENTS", 16))
    rows_per_seg = int(os.environ.get("DRUID_TPU_BENCH_BATCH_ROWS", 4096))
    segments = headline_segments(rows_per_seg * n_segments, n_segments)
    total_rows = sum(s.n_rows for s in segments)
    iv = headline_interval()
    q = {"queryType": "groupBy", "dataSource": "bench",
         "intervals": [str(iv)], "granularity": "all",
         "dimensions": ["dimA"],
         "aggregations": [
             {"type": "count", "name": "rows"},
             {"type": "hyperUnique", "name": "u", "fieldName": "dimB",
              "log2m": 12}]}
    executor = QueryExecutor(segments)
    t = time.time()
    executor.run_json(q)
    log(f"hll-bench warmup: {time.time() - t:.2f}s")
    times = []
    for _ in range(max(iters, 3)):
        t = time.time()
        executor.run_json(q)
        times.append(time.time() - t)
    rate = total_rows / min(times)
    log(f"hll-bench log2m=12: best {min(times) * 1e3:.1f}ms "
        f"-> {rate / 1e6:.1f}M rows/s")
    return {"hll_log2m12_rate": round(rate, 0)}


def _bench_tracing(iters: int):
    """qtrace overhead in one number pair: the batch-comparison query at
    many small segments (the worst case for per-dispatch span overhead —
    tiny device programs, many dispatch boundaries), run with a trace root
    open (every span live) vs without (every span a no-op thread-local
    read). Tracked across BENCH_r* runs so a regression in span cost shows
    up as traced_rate falling away from untraced_rate."""
    from druid_tpu.engine.executor import QueryExecutor
    from druid_tpu.obs import trace as qtrace

    n_segments = int(os.environ.get("DRUID_TPU_BENCH_BATCH_SEGMENTS", 16))
    rows_per_seg = int(os.environ.get("DRUID_TPU_BENCH_BATCH_ROWS", 4096))
    segments = headline_segments(rows_per_seg * n_segments, n_segments)
    total_rows = sum(s.n_rows for s in segments)
    query = batch_groupby()
    executor = QueryExecutor(segments)

    executor.run(query)                  # warm: compile + staging
    rates = {}
    for label in ("untraced", "traced"):
        times = []
        for _ in range(max(iters, 3)):
            t = time.time()
            if label == "traced":
                with qtrace.root_span("bench/query", service="bench"):
                    executor.run(query)
            else:
                executor.run(query)
            times.append(time.time() - t)
        rates[label] = total_rows / min(times)
        log(f"trace-bench {label}: best {min(times) * 1e3:.1f}ms "
            f"-> {rates[label] / 1e6:.1f}M rows/s")
    return {
        "untraced_rate": round(rates["untraced"], 0),
        "traced_rate": round(rates["traced"], 0),
        "trace_overhead": round(
            1.0 - rates["traced"] / rates["untraced"], 4),
    }


def _bench_scheduler():
    """Closed-loop concurrent-client mode: N clients each issue M SMALL
    queries (one segment apiece — too small for within-query batching, the
    'thousands of small concurrent queries on one hot datasource' shape)
    against a data node, once through the admission-control scheduler
    (cross-query fusion) and once direct. Reports aggregate rows/s and
    per-query p50/p99 latency for both modes — the scheduler's win is the
    cross-query dispatch amortization, its cost is the batching window."""
    import threading

    from druid_tpu.cluster.view import DataNode
    from druid_tpu.server.scheduler import (DataNodeScheduler,
                                            SchedulerConfig)

    n_clients = int(os.environ.get("DRUID_TPU_BENCH_CLIENTS", 8))
    n_queries = int(os.environ.get("DRUID_TPU_BENCH_CLIENT_QUERIES", 12))
    rows_per_seg = int(os.environ.get("DRUID_TPU_BENCH_SCHED_ROWS", 4096))
    n_segments = max(n_clients, 8)
    segments = headline_segments(rows_per_seg * n_segments, n_segments)
    node = DataNode("bench-node")
    for s in segments:
        node.load_segment(s)
    sids = [str(s.id) for s in segments]
    query = batch_groupby()

    def run_mode(use_sched: bool):
        sched = None
        if use_sched:
            sched = DataNodeScheduler(
                node, SchedulerConfig(batch_window_ms=3.0,
                                      max_queue_depth=4 * n_clients,
                                      lane_depths={})).start()
        lat_ms = [[] for _ in range(n_clients)]
        barrier = threading.Barrier(n_clients)

        def client(ci: int, record: bool):
            barrier.wait()
            for k in range(n_queries):
                sid = [sids[(ci + k) % n_segments]]
                t = time.time()
                if sched is not None:
                    sched.submit(query, sid)
                else:
                    node.run_partials(query, sid)
                if record:
                    lat_ms[ci].append((time.time() - t) * 1e3)

        def wave(record: bool) -> float:
            threads = [threading.Thread(target=client, args=(ci, record))
                       for ci in range(n_clients)]
            t0 = time.time()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            return time.time() - t0

        try:
            # warm waves: flush composition is timing-dependent (chunk
            # size K is a compile key), so no warmup can GUARANTEE every
            # shape the recorded wave will hit — two waves cover the
            # common ones and a stray compile shows up as a p99 outlier,
            # not a shifted p50
            wave(record=False)
            wave(record=False)
            wall = wave(record=True)
        finally:
            if sched is not None:
                sched.stop()
        lats = sorted(x for per in lat_ms for x in per)
        seg_rows = {str(s.id): s.n_rows for s in segments}
        total_rows = sum(seg_rows[sids[(ci + k) % n_segments]]
                         for ci in range(n_clients)
                         for k in range(n_queries))
        return {
            "rate": total_rows / wall,
            "p50_ms": lats[len(lats) // 2],
            "p99_ms": lats[min(len(lats) - 1, int(len(lats) * 0.99))],
        }

    off = run_mode(use_sched=False)
    on = run_mode(use_sched=True)
    for label, r in (("off", off), ("on", on)):
        log(f"sched-bench {label}: {r['rate'] / 1e6:.1f}M rows/s "
            f"p50 {r['p50_ms']:.1f}ms p99 {r['p99_ms']:.1f}ms")
    return {
        "sched_clients": n_clients,
        "sched_off_rate": round(off["rate"], 0),
        "sched_on_rate": round(on["rate"], 0),
        "sched_speedup": round(on["rate"] / off["rate"], 2),
        "sched_off_p50_ms": round(off["p50_ms"], 2),
        "sched_off_p99_ms": round(off["p99_ms"], 2),
        "sched_on_p50_ms": round(on["p50_ms"], 2),
        "sched_on_p99_ms": round(on["p99_ms"], 2),
    }


def _bench_standing():
    """Standing queries over streaming ingest: per-wave tick cost of the
    incremental standing program vs a from-scratch re-scan of every sink
    (rates are cumulative rows SERVED per second of serving work), plus
    the fan-out story — N subscribers on one hub (ONE standing program)
    vs N independent queries."""
    import numpy as np

    from druid_tpu.cluster.metadata import MetadataStore
    from druid_tpu.engine.standing import StandingQuery
    from druid_tpu.ingest import (Appenderator, RowBatch, SegmentAllocator,
                                  StreamAppenderatorDriver)
    from druid_tpu.query import aggregators as A
    from druid_tpu.query.model import TimeseriesQuery, query_from_json
    from druid_tpu.server.subscriptions import SubscriptionHub
    from druid_tpu.utils.intervals import Interval

    rows = int(os.environ.get("DRUID_TPU_BENCH_STANDING_ROWS", 400_000))
    waves = int(os.environ.get("DRUID_TPU_BENCH_STANDING_WAVES", 8))
    n_subs = int(os.environ.get("DRUID_TPU_BENCH_STANDING_SUBS", 64))
    per_wave = max(rows // waves, 1)

    iv = Interval.of("2026-03-01", "2026-03-02")
    rng = np.random.default_rng(7)
    app = Appenderator(
        "bench_rt",
        [A.CountAggregator("rows"), A.LongSumAggregator("v", "value")],
        query_granularity="none", max_rows_per_hydrant=per_wave)
    driver = StreamAppenderatorDriver(
        app, SegmentAllocator(MetadataStore(), "day"), MetadataStore())
    q = query_from_json({
        "queryType": "timeseries", "dataSource": "bench_rt",
        "intervals": [str(iv)], "granularity": "hour",
        "aggregations": [
            {"type": "longSum", "name": "rows", "fieldName": "rows"},
            {"type": "longSum", "name": "v", "fieldName": "v"}]})
    assert isinstance(q, TimeseriesQuery)
    sq = StandingQuery(q, [app])

    def wave_batch():
        ts = iv.start + rng.integers(0, 24 * 3_600_000, size=per_wave)
        return RowBatch(ts.astype(np.int64), {
            "page": [f"p{int(x)}" for x in rng.integers(16, size=per_wave)],
            "value": rng.integers(0, 100, size=per_wave)})

    served = 0
    t_standing = 0.0
    t_rescan = 0.0
    total = 0
    for w in range(waves):
        driver.add_batch(wave_batch())
        total += per_wave
        if w % 2 == 1:
            app.persist_all()
        t = time.time()
        sq.tick()
        sq.rows()
        t_standing += time.time() - t
        t = time.time()
        sq.rescan_rows()
        t_rescan += time.time() - t
        served += total
    sq.close()
    standing_rate = served / max(t_standing, 1e-9)
    rescan_rate = served / max(t_rescan, 1e-9)
    log(f"standing-bench: {waves} waves x {per_wave} rows — standing "
        f"{t_standing * 1e3:.1f}ms vs rescan {t_rescan * 1e3:.1f}ms "
        f"({standing_rate / rescan_rate:.2f}x)")

    # fan-out: N subscribers dedupe onto ONE standing program; the
    # comparison is N independent executor runs over the same sinks
    hub = SubscriptionHub(idle_timeout_s=0)
    hub.attach(app)
    subs = [hub.subscribe(q) for _ in range(n_subs)]
    driver.add_batch(wave_batch())
    hub.tick()                            # warm: compile + first fold
    driver.add_batch(wave_batch())
    t = time.time()
    hub.tick()
    for sid, _ in subs:
        hub.poll(sid)
    t_hub = time.time() - t
    n_programs = hub.active_programs()

    from druid_tpu.engine import QueryExecutor
    world = app.query_segments()
    QueryExecutor().run(q, segments=world)   # warm
    t = time.time()
    for _ in range(n_subs):
        QueryExecutor().run(q, segments=world)
    t_ind = time.time() - t
    hub.stop()
    log(f"standing-bench fanout x{n_subs}: hub {t_hub * 1e3:.1f}ms vs "
        f"independent {t_ind * 1e3:.1f}ms "
        f"({t_ind / max(t_hub, 1e-9):.1f}x), {n_programs} program(s)")
    return {
        "standing_rate": round(standing_rate, 0),
        "rescan_rate": round(rescan_rate, 0),
        "standing_speedup": round(standing_rate / rescan_rate, 3),
        "standing_fanout_subs": n_subs,
        "standing_fanout_hub_ms": round(t_hub * 1e3, 2),
        "standing_fanout_independent_ms": round(t_ind * 1e3, 2),
        "standing_fanout_speedup": round(t_ind / max(t_hub, 1e-9), 3),
        "standing_programs": n_programs,
    }


def _bench_soak():
    """Opt-in (DRUID_TPU_BENCH_SOAK=<waves>) resource-drift mode: repeated
    query waves + full server start/stop cycles, reporting rss/fd/thread
    drift between a post-warmup baseline and the end state. Zero drift is
    the contract a months-long serving process needs; any linear growth
    here is the wedged-run (rc=124) failure class in miniature."""
    import gc
    import threading

    from druid_tpu.cluster.dataserver import DataNodeServer
    from druid_tpu.cluster.view import DataNode

    waves = int(os.environ.get("DRUID_TPU_BENCH_SOAK", 0))
    if waves <= 0:
        return {}
    rows_per_seg = int(os.environ.get("DRUID_TPU_BENCH_SCHED_ROWS", 4096))
    n_segments = 4
    segments = headline_segments(rows_per_seg * n_segments, n_segments)
    sids = [str(s.id) for s in segments]
    query = batch_groupby()

    def rss_kb() -> int:
        try:
            with open("/proc/self/status") as fh:
                for line in fh:
                    if line.startswith("VmRSS:"):
                        return int(line.split()[1])
        except OSError:
            pass
        return 0

    def fd_count() -> int:
        try:
            return len(os.listdir("/proc/self/fd"))
        except OSError:
            return 0

    def cycle():
        node = DataNode("soak-node")
        for s in segments:
            node.load_segment(s)
        srv = DataNodeServer(node).start()
        try:
            for _ in range(3):
                node.run_partials(query, sids)
        finally:
            srv.stop()

    cycle()                               # warmup: lazy init + compiles
    gc.collect()
    base = (rss_kb(), fd_count(), threading.active_count())
    t0 = time.time()
    for _ in range(waves):
        cycle()
    gc.collect()
    end = (rss_kb(), fd_count(), threading.active_count())
    log(f"soak: {waves} wave(s) in {time.time() - t0:.1f}s — rss drift "
        f"{end[0] - base[0]}KB, fd drift {end[1] - base[1]}, thread "
        f"drift {end[2] - base[2]}")
    return {
        "soak_waves": waves,
        "soak_rss_drift_kb": end[0] - base[0],
        "soak_fd_drift": end[1] - base[1],
        "soak_thread_drift": end[2] - base[2],
    }


def main():
    rows = int(os.environ.get("DRUID_TPU_BENCH_ROWS", 100_000_000))
    n_segments = int(os.environ.get("DRUID_TPU_BENCH_SEGMENTS", 8))
    iters = int(os.environ.get("DRUID_TPU_BENCH_ITERS", 5))

    _init_backend()

    from druid_tpu.engine import QueryExecutor
    from druid_tpu.parallel import make_mesh

    t0 = time.time()
    segments = headline_segments(rows, n_segments)
    total_rows = sum(s.n_rows for s in segments)
    log(f"generated {total_rows:,} rows in {n_segments} segments "
        f"({time.time() - t0:.1f}s)")

    groupby = headline_groupby()
    topn = headline_topn(segments)

    executor = QueryExecutor(segments, mesh=make_mesh(1))

    def timed(query, label):
        t = time.time()
        n = len(executor.run(query))
        log(f"warmup {label}: {time.time() - t:.2f}s ({n} rows) "
            "[compile + H2D staging]")
        times = []
        for _ in range(iters):
            t = time.time()
            executor.run(query)
            times.append(time.time() - t)
        best = min(times)
        log(f"{label}: best {best * 1e3:.1f}ms over {iters} iters "
            f"-> {total_rows / best / 1e6:.0f}M rows/s")
        return best, times

    t_gb, gb_times = timed(groupby, "groupBy 2dim/3agg+filter")
    t_tn, tn_times = timed(topn, "topN dimB/2agg+filter")

    # warm-latency story (BASELINE.json's metric includes p50 latency)
    lat = sorted(gb_times + tn_times)
    p50 = lat[len(lat) // 2] * 1e3
    p95 = lat[min(len(lat) - 1, int(len(lat) * 0.95))] * 1e3
    log(f"warm latency: p50 {p50:.0f}ms  p95 {p95:.0f}ms "
        f"(over {len(lat)} timed queries @ {total_rows:,} rows)")

    # the add-on comparisons must never cost the already-measured headline
    # its ONE JSON line — degrade to an error field instead
    try:
        batch = _bench_batching(iters)
    except Exception as e:  # druidlint: disable=swallowed-exception
        log(f"batch-bench failed: {type(e).__name__}: {e}")
        batch = {"batch_error": f"{type(e).__name__}: {e}"[:200]}
    try:
        sharded = _bench_sharded(iters)
    except Exception as e:  # druidlint: disable=swallowed-exception
        log(f"sharded-bench failed: {type(e).__name__}: {e}")
        sharded = {"sharded_error": f"{type(e).__name__}: {e}"[:200]}
    try:
        packed_cmp = _bench_packed(iters)
    except Exception as e:  # druidlint: disable=swallowed-exception
        log(f"packed-bench failed: {type(e).__name__}: {e}")
        packed_cmp = {"packed_error": f"{type(e).__name__}: {e}"[:200]}
    try:
        filt = _bench_filter(iters)
    except Exception as e:  # druidlint: disable=swallowed-exception
        log(f"filter-bench failed: {type(e).__name__}: {e}")
        filt = {"filter_error": f"{type(e).__name__}: {e}"[:200]}
    try:
        fused = _bench_fused(iters)
    except Exception as e:  # druidlint: disable=swallowed-exception
        log(f"fused-bench failed: {type(e).__name__}: {e}")
        fused = {"fused_error": f"{type(e).__name__}: {e}"[:200]}
    try:
        casc = _bench_cascade(iters)
    except Exception as e:  # druidlint: disable=swallowed-exception
        log(f"cascade-bench failed: {type(e).__name__}: {e}")
        casc = {"cascade_error": f"{type(e).__name__}: {e}"[:200]}
    try:
        segio = _bench_segment_io(iters)
    except Exception as e:  # druidlint: disable=swallowed-exception
        log(f"segio-bench failed: {type(e).__name__}: {e}")
        segio = {"segio_error": f"{type(e).__name__}: {e}"[:200]}
    try:
        hll = _bench_hll(iters)
    except Exception as e:  # druidlint: disable=swallowed-exception
        log(f"hll-bench failed: {type(e).__name__}: {e}")
        hll = {"hll_error": f"{type(e).__name__}: {e}"[:200]}
    try:
        traced = _bench_tracing(iters)
    except Exception as e:  # druidlint: disable=swallowed-exception
        log(f"trace-bench failed: {type(e).__name__}: {e}")
        traced = {"trace_error": f"{type(e).__name__}: {e}"[:200]}
    try:
        sched = _bench_scheduler()
    except Exception as e:  # druidlint: disable=swallowed-exception
        log(f"sched-bench failed: {type(e).__name__}: {e}")
        sched = {"sched_error": f"{type(e).__name__}: {e}"[:200]}
    try:
        standing = _bench_standing()
    except Exception as e:  # druidlint: disable=swallowed-exception
        log(f"standing-bench failed: {type(e).__name__}: {e}")
        standing = {"standing_error": f"{type(e).__name__}: {e}"[:200]}
    try:
        soak = _bench_soak()
    except Exception as e:  # druidlint: disable=swallowed-exception
        log(f"soak-bench failed: {type(e).__name__}: {e}")
        soak = {"soak_error": f"{type(e).__name__}: {e}"[:200]}

    value = 2 * total_rows / (t_gb + t_tn)
    baseline = 36_246_530.0  # Java rows/sec/core scan-aggregate upper bound
    out = {
        "metric": "groupby+topn_scan_rate",
        "value": round(value, 0),
        "unit": "rows/sec/chip",
        "vs_baseline": round(value / baseline, 2),
        "p50_ms": round(p50, 1),
        "p95_ms": round(p95, 1),
    }
    out.update(batch)
    out.update(sharded)
    out.update(packed_cmp)
    out.update(filt)
    out.update(fused)
    out.update(casc)
    out.update(segio)
    out.update(hll)
    out.update(traced)
    out.update(sched)
    out.update(standing)
    out.update(soak)
    print(json.dumps(out), flush=True)


if __name__ == "__main__":
    main()
