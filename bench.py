"""Headline benchmark: GroupBy + TopN rows/sec on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Config mirrors BASELINE.json: TPC-H-style GroupBy (2 dims, 3 aggs, numeric
bound filter) + TopN (1 dim, metric-ordered) over synthetic segments.
Baseline comparator: the reference whitepaper's per-core scan-aggregate rate
(36,246,530 rows/sec/core for sum-over-interval, druid.tex:882) — the Java
engine's upper bound; its GroupBy path is strictly slower.

Environment:
  DRUID_TPU_BENCH_ROWS   total rows (default 100_000_000)
  DRUID_TPU_BENCH_SEGMENTS  segment count (default 8)
  DRUID_TPU_BENCH_ITERS  timed iterations per query (default 5)
"""
import json
import os
import sys
import time

import numpy as np


def log(msg):
    print(msg, file=sys.stderr, flush=True)


# -- headline configuration, shared with tools/chip_suite.py and
#    tools/chip_pallas_test.py so tuning/validation and the gate measure
#    the SAME shape -------------------------------------------------------

HEADLINE_SEED = 1234


def headline_interval():
    from druid_tpu.utils.intervals import Interval
    return Interval.of("2026-01-01", "2026-01-02")


def headline_segments(rows: int, n_segments: int):
    from druid_tpu.data.generator import ColumnSpec, DataGenerator
    schema = (
        ColumnSpec("dimA", "string", cardinality=100, distribution="uniform"),
        ColumnSpec("dimB", "string", cardinality=1000, distribution="zipf"),
        ColumnSpec("metLong", "long", low=0, high=10_000),
        ColumnSpec("metFloat", "float", distribution="normal", mean=100.0,
                   std=25.0),
    )
    gen = DataGenerator(schema, seed=HEADLINE_SEED)
    return gen.segments(n_segments, rows // n_segments, headline_interval(),
                        datasource="bench")


def headline_groupby():
    from druid_tpu.query.aggregators import (CountAggregator,
                                             FloatMaxAggregator,
                                             LongSumAggregator)
    from druid_tpu.query.filters import BoundFilter
    from druid_tpu.query.model import DefaultDimensionSpec, GroupByQuery
    return GroupByQuery.of(
        "bench", [headline_interval()],
        [DefaultDimensionSpec("dimA"), DefaultDimensionSpec("dimB")],
        [CountAggregator("rows"), LongSumAggregator("lsum", "metLong"),
         FloatMaxAggregator("fmax", "metFloat")],
        granularity="all",
        filter=BoundFilter("metLong", lower=100, upper=9_900,
                           ordering="numeric"))


def headline_topn(segments):
    from druid_tpu.query.aggregators import (CountAggregator,
                                             LongSumAggregator)
    from druid_tpu.query.filters import InFilter
    from druid_tpu.query.model import TopNQuery
    # filter on REAL dictionary values (half of dimA) — a padded-format
    # mismatch here would silently benchmark an empty-result query
    dimA_vals = list(segments[0].dims["dimA"].dictionary.values)
    assert len(dimA_vals) >= 100, "unexpected dimA cardinality"
    return TopNQuery.of(
        "bench", [headline_interval()], "dimB", "lsum", 100,
        [CountAggregator("rows"), LongSumAggregator("lsum", "metLong")],
        granularity="all",
        filter=InFilter("dimA", dimA_vals[0:100:2]))


def main():
    rows = int(os.environ.get("DRUID_TPU_BENCH_ROWS", 100_000_000))
    n_segments = int(os.environ.get("DRUID_TPU_BENCH_SEGMENTS", 8))
    iters = int(os.environ.get("DRUID_TPU_BENCH_ITERS", 5))

    import jax

    def _fail(cause: str):
        # backend down/wedged: still emit ONE parseable JSON line so the
        # recorded failure carries its cause
        print(json.dumps({"metric": "groupby+topn_scan_rate", "value": 0,
                          "unit": "rows/sec/chip", "vs_baseline": 0,
                          "error": cause[:300]}), flush=True)

    # the TPU tunnel has two failure modes: fast "UNAVAILABLE" errors and
    # an indefinite hang inside backend init — watchdog both
    import threading
    init: dict = {}

    def _init():
        try:
            init["devices"] = jax.devices()
        except Exception as e:   # ANY init failure must reach the JSON line
            init["error"] = f"{type(e).__name__}: {e}"

    t = threading.Thread(target=_init, daemon=True)
    t.start()
    t.join(timeout=float(os.environ.get("DRUID_TPU_BENCH_INIT_TIMEOUT",
                                        600)))
    if t.is_alive():
        _fail("backend init hung (TPU tunnel wedged)")
        os._exit(1)          # the init thread is stuck in C — hard exit
    if "devices" not in init:
        _fail(f"backend unavailable: {init.get('error', 'no devices')}")
        sys.exit(1)
    log(f"devices: {init['devices']}")

    from druid_tpu.engine import QueryExecutor
    from druid_tpu.parallel import make_mesh

    t0 = time.time()
    segments = headline_segments(rows, n_segments)
    total_rows = sum(s.n_rows for s in segments)
    log(f"generated {total_rows:,} rows in {n_segments} segments "
        f"({time.time() - t0:.1f}s)")

    groupby = headline_groupby()
    topn = headline_topn(segments)

    executor = QueryExecutor(segments, mesh=make_mesh(1))

    def timed(query, label):
        t = time.time()
        n = len(executor.run(query))
        log(f"warmup {label}: {time.time() - t:.2f}s ({n} rows) "
            "[compile + H2D staging]")
        times = []
        for _ in range(iters):
            t = time.time()
            executor.run(query)
            times.append(time.time() - t)
        best = min(times)
        log(f"{label}: best {best * 1e3:.1f}ms over {iters} iters "
            f"-> {total_rows / best / 1e6:.0f}M rows/s")
        return best, times

    t_gb, gb_times = timed(groupby, "groupBy 2dim/3agg+filter")
    t_tn, tn_times = timed(topn, "topN dimB/2agg+filter")

    # warm-latency story (BASELINE.json's metric includes p50 latency)
    lat = sorted(gb_times + tn_times)
    p50 = lat[len(lat) // 2] * 1e3
    p95 = lat[min(len(lat) - 1, int(len(lat) * 0.95))] * 1e3
    log(f"warm latency: p50 {p50:.0f}ms  p95 {p95:.0f}ms "
        f"(over {len(lat)} timed queries @ {total_rows:,} rows)")

    value = 2 * total_rows / (t_gb + t_tn)
    baseline = 36_246_530.0  # Java rows/sec/core scan-aggregate upper bound
    print(json.dumps({
        "metric": "groupby+topn_scan_rate",
        "value": round(value, 0),
        "unit": "rows/sec/chip",
        "vs_baseline": round(value / baseline, 2),
        "p50_ms": round(p50, 1),
        "p95_ms": round(p95, 1),
    }), flush=True)


if __name__ == "__main__":
    main()
