// druid_native — host-side native kernels for the TPU analytics framework.
//
// Role in the system: the reference implements its performance-critical
// storage path on the JVM with off-heap ByteBuffers + lz4-java block
// compression (reference: processing/.../segment/data/CompressionStrategy.java:48-108,
// java-util/.../io/smoosh/FileSmoosher.java). Here the equivalent staging
// path — decompressing mmapped column blocks into dense numpy arrays bound
// for HBM — is real C++ invoked via ctypes, so segment→device staging is not
// bottlenecked by the Python interpreter.
//
// Contents:
//   * LZ4 block-format compressor/decompressor (format-compatible with the
//     standard LZ4 block spec; implemented from the public format
//     description, no code copied).
//   * Multi-threaded batch decompression for column block arrays.
//   * Bit-unpacking of bitmap words into byte masks (filter mask staging).
//   * Fused multi-column group-key packing (host-side fallback path).
//
// Build: see native/Makefile (g++ -O3 -shared -fPIC). Loaded with ctypes by
// druid_tpu/native/__init__.py; the Python layer falls back to zlib if this
// library is unavailable.

#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>
#include <algorithm>

extern "C" {

// ---------------------------------------------------------------------------
// LZ4 block format
// ---------------------------------------------------------------------------

int64_t druid_lz4_compress_bound(int64_t n) {
  return n + n / 255 + 16;
}

// Compress src[0..n) into dst (capacity dst_cap). Returns compressed size,
// or -1 on overflow. Greedy hash-table matcher over 4-byte windows.
int64_t druid_lz4_compress(const uint8_t* src, int64_t n, uint8_t* dst,
                           int64_t dst_cap) {
  const int HASH_LOG = 16;
  const int64_t MIN_MATCH = 4;
  const int64_t MFLIMIT = 12;   // last match must start before n-MFLIMIT
  const int64_t LAST_LITERALS = 5;

  uint8_t* op = dst;
  uint8_t* const oend = dst + dst_cap;
  int64_t anchor = 0;

  auto emit_sequence = [&](int64_t lit_start, int64_t lit_len, int64_t offset,
                           int64_t match_len) -> bool {
    // token
    int64_t ml = match_len >= MIN_MATCH ? match_len - MIN_MATCH : 0;
    uint8_t tok_lit = lit_len >= 15 ? 15 : (uint8_t)lit_len;
    uint8_t tok_ml = (match_len > 0) ? (ml >= 15 ? 15 : (uint8_t)ml) : 0;
    if (op >= oend) return false;
    *op++ = (uint8_t)((tok_lit << 4) | tok_ml);
    if (lit_len >= 15) {
      int64_t rest = lit_len - 15;
      while (rest >= 255) { if (op >= oend) return false; *op++ = 255; rest -= 255; }
      if (op >= oend) return false;
      *op++ = (uint8_t)rest;
    }
    if (op + lit_len > oend) return false;
    std::memcpy(op, src + lit_start, (size_t)lit_len);
    op += lit_len;
    if (match_len > 0) {
      if (op + 2 > oend) return false;
      *op++ = (uint8_t)(offset & 0xFF);
      *op++ = (uint8_t)((offset >> 8) & 0xFF);
      if (ml >= 15) {
        int64_t rest = ml - 15;
        while (rest >= 255) { if (op >= oend) return false; *op++ = 255; rest -= 255; }
        if (op >= oend) return false;
        *op++ = (uint8_t)rest;
      }
    }
    return true;
  };

  if (n >= MFLIMIT + 1) {
    std::vector<int64_t> table((size_t)1 << HASH_LOG, -1);
    const int64_t match_limit = n - LAST_LITERALS;
    int64_t p = 0;
    while (p < n - MFLIMIT) {
      uint32_t seq;
      std::memcpy(&seq, src + p, 4);
      uint32_t h = (seq * 2654435761u) >> (32 - HASH_LOG);
      int64_t cand = table[h];
      table[h] = p;
      uint32_t cand_seq = 0;
      if (cand >= 0 && p - cand <= 0xFFFF) {
        std::memcpy(&cand_seq, src + cand, 4);
      }
      if (cand >= 0 && p - cand <= 0xFFFF && cand_seq == seq) {
        // extend match
        int64_t m = 4;
        while (p + m < match_limit && src[cand + m] == src[p + m]) m++;
        if (!emit_sequence(anchor, p - anchor, p - cand, m)) return -1;
        p += m;
        anchor = p;
      } else {
        p++;
      }
    }
  }
  // final literals
  if (!emit_sequence(anchor, n - anchor, 0, 0)) return -1;
  return op - dst;
}

// Decompress src[0..src_len) into dst (exact capacity dst_cap).
// Returns decompressed size, or -1 on malformed input.
int64_t druid_lz4_decompress(const uint8_t* src, int64_t src_len, uint8_t* dst,
                             int64_t dst_cap) {
  const uint8_t* ip = src;
  const uint8_t* const iend = src + src_len;
  uint8_t* op = dst;
  uint8_t* const oend = dst + dst_cap;

  while (ip < iend) {
    unsigned token = *ip++;
    int64_t lit_len = token >> 4;
    if (lit_len == 15) {
      uint8_t s;
      do {
        if (ip >= iend) return -1;
        s = *ip++;
        lit_len += s;
      } while (s == 255);
    }
    // compare against remaining space, NOT `ip + lit_len` — a crafted
    // multi-byte length (~2^40) would overflow the pointer sum into UB
    if (lit_len > iend - ip || lit_len > oend - op) return -1;
    std::memcpy(op, ip, (size_t)lit_len);
    ip += lit_len;
    op += lit_len;
    if (ip >= iend) break;  // last sequence: literals only
    if (ip + 2 > iend) return -1;
    int64_t offset = (int64_t)ip[0] | ((int64_t)ip[1] << 8);
    ip += 2;
    if (offset == 0 || op - dst < offset) return -1;
    int64_t match_len = (int64_t)(token & 15) + 4;
    if ((token & 15) == 15) {
      uint8_t s;
      do {
        if (ip >= iend) return -1;
        s = *ip++;
        match_len += s;
      } while (s == 255);
    }
    if (match_len > oend - op) return -1;
    const uint8_t* match = op - offset;
    for (int64_t i = 0; i < match_len; i++) op[i] = match[i];  // overlap-safe
    op += match_len;
  }
  return op - dst;
}

// Decompress k blocks (possibly in parallel) from a concatenated source blob
// into a contiguous destination. Returns 0 on success, -(i+1) if block i
// failed. The per-block layout arrays are int64.
int64_t druid_lz4_decompress_batch(const uint8_t* src,
                                   const int64_t* src_offsets,
                                   const int64_t* src_sizes,
                                   uint8_t* dst,
                                   const int64_t* dst_offsets,
                                   const int64_t* dst_sizes,
                                   int64_t k, int64_t n_threads) {
  if (n_threads <= 1 || k <= 1) {
    for (int64_t i = 0; i < k; i++) {
      int64_t got = druid_lz4_decompress(src + src_offsets[i], src_sizes[i],
                                         dst + dst_offsets[i], dst_sizes[i]);
      if (got != dst_sizes[i]) return -(i + 1);
    }
    return 0;
  }
  int64_t nt = std::min<int64_t>(n_threads, k);
  std::vector<std::thread> threads;
  std::vector<int64_t> status((size_t)nt, 0);
  for (int64_t t = 0; t < nt; t++) {
    threads.emplace_back([&, t]() {
      for (int64_t i = t; i < k; i += nt) {
        int64_t got = druid_lz4_decompress(src + src_offsets[i], src_sizes[i],
                                           dst + dst_offsets[i], dst_sizes[i]);
        if (got != dst_sizes[i]) { status[(size_t)t] = -(i + 1); return; }
      }
    });
  }
  for (auto& th : threads) th.join();
  for (int64_t t = 0; t < nt; t++) if (status[(size_t)t] != 0) return status[(size_t)t];
  return 0;
}

// ---------------------------------------------------------------------------
// Bitmap word unpack: packed MSB-first uint8 words -> byte mask (0/1),
// the staging step that turns a host bitmap-planner result into a device
// row mask.
// ---------------------------------------------------------------------------
void druid_unpack_bits(const uint8_t* words, int64_t n_rows, uint8_t* out) {
  int64_t full = n_rows / 8;
  for (int64_t w = 0; w < full; w++) {
    uint8_t v = words[w];
    uint8_t* o = out + w * 8;
    o[0] = (v >> 7) & 1; o[1] = (v >> 6) & 1; o[2] = (v >> 5) & 1;
    o[3] = (v >> 4) & 1; o[4] = (v >> 3) & 1; o[5] = (v >> 2) & 1;
    o[6] = (v >> 1) & 1; o[7] = v & 1;
  }
  int64_t rem = n_rows - full * 8;
  if (rem) {
    uint8_t v = words[full];
    for (int64_t i = 0; i < rem; i++) out[full * 8 + i] = (v >> (7 - i)) & 1;
  }
}

// ---------------------------------------------------------------------------
// Fused group-key packing: key = ((ids0*card1)+ids1)*card2+... over int32
// columns. Host-side fallback for the device fused-key kernel; also used by
// the ingest rollup path.
// ---------------------------------------------------------------------------
void druid_pack_keys(const int32_t** cols, const int64_t* cards,
                     int64_t n_cols, int64_t n_rows, int64_t* out) {
  for (int64_t r = 0; r < n_rows; r++) out[r] = 0;
  for (int64_t c = 0; c < n_cols; c++) {
    const int32_t* col = cols[c];
    int64_t card = cards[c];
    for (int64_t r = 0; r < n_rows; r++) out[r] = out[r] * card + col[r];
  }
}

}  // extern "C"
