"""Aux subsystems: emitter/monitors, config, query lifecycle, HTTP
endpoints, CLI tools (reference: emitter core, JsonConfigProvider,
QueryLifecycle, QueryResource/SqlResource, DumpSegment)."""
import json
import urllib.request

import numpy as np
import pytest

from druid_tpu.engine import QueryExecutor
from druid_tpu.query.aggregators import CountAggregator
from druid_tpu.query.model import TimeseriesQuery
from druid_tpu.server import QueryHttpServer, QueryLifecycle, RequestLogger
from druid_tpu.server.lifecycle import Unauthorized
from druid_tpu.sql import SqlExecutor
from druid_tpu.utils.config import Config
from druid_tpu.utils.emitter import (BatchingEmitter, CacheMonitor,
                                     ComposingEmitter, Event, FileEmitter,
                                     InMemoryEmitter, MonitorScheduler,
                                     ProcessMonitor, QueryCountStatsMonitor,
                                     ServiceEmitter, SysMonitor)
from tests.conftest import DAY


# ---------------------------------------------------------------------------
# Emitter + monitors
# ---------------------------------------------------------------------------

def test_service_emitter_stamps_dims():
    sink = InMemoryEmitter()
    em = ServiceEmitter("druid-tpu/test", "h1", sink)
    em.metric("query/time", 12.5, dataSource="wiki")
    e = sink.metrics("query/time")[0]
    assert e.dims == {"dataSource": "wiki", "service": "druid-tpu/test",
                      "host": "h1"}
    j = e.to_json()
    assert j["feed"] == "metrics" and j["value"] == 12.5


def test_batching_emitter():
    batches = []
    be = BatchingEmitter(batches.append, batch_size=3)
    try:
        em = ServiceEmitter("s", "h", be)
        for i in range(7):
            em.metric("m", i)
        assert len(batches) == 2 and all(len(b) == 3 for b in batches)
        be.flush()
        assert sum(len(b) for b in batches) == 7
    finally:
        be.close()                 # the flush timer is a real thread


def test_file_emitter(tmp_path):
    path = str(tmp_path / "metrics.log")
    em = ServiceEmitter("s", "h", FileEmitter(path))
    em.metric("a", 1)
    em.metric("b", 2)
    em.flush()
    lines = [json.loads(l) for l in open(path)]
    assert [l["metric"] for l in lines] == ["a", "b"]


def test_monitors_emit():
    sink = InMemoryEmitter()
    em = ServiceEmitter("s", "h", sink)
    qc = QueryCountStatsMonitor()
    qc.on_query(True)
    qc.on_query(False)
    from druid_tpu.cluster import LruCache
    cache = LruCache()
    cache.put("x", "k", 1)
    cache.get("x", "k")
    sched = MonitorScheduler(em, [SysMonitor(), ProcessMonitor(), qc,
                                  CacheMonitor(cache)], 999)
    sched.tick()
    sched.tick()   # SysMonitor cpu needs two samples
    names = {e.metric for e in sink.metrics()}
    assert {"proc/rss", "query/count", "query/success/count",
            "query/cache/total/hits"} <= names
    assert sink.metrics("query/success/count")[0].value == 1


# ---------------------------------------------------------------------------
# Config
# ---------------------------------------------------------------------------

def test_config_layers(tmp_path):
    f = tmp_path / "runtime.properties"
    f.write_text("server.port=8082\n# comment\nquery.cache=true\n")
    cfg = Config.load(str(f), env={"DRUID_TPU_SERVER_PORT": "9000"},
                      overrides={"metadata.path": ":memory:"})
    assert cfg.get_int("server.port") == 9000      # env beats file
    assert cfg.get_bool("query.cache")
    assert cfg.get("metadata.path") == ":memory:"


def test_config_json_and_select(tmp_path):
    f = tmp_path / "conf.json"
    f.write_text(json.dumps({"storage": {"type": "local", "dir": "/x"}}))
    cfg = Config.load(str(f), env={})
    assert cfg.get("storage.type") == "local"
    assert cfg.subtree("storage") == {"type": "local", "dir": "/x"}
    made = cfg.select("storage.type",
                      {"local": lambda: "L", "memory": lambda: "M"},
                      default="memory")
    assert made == "L"
    with pytest.raises(ValueError):
        cfg.with_overrides({"storage.type": "bogus"}).select(
            "storage.type", {"local": lambda: 1}, default="local")


# ---------------------------------------------------------------------------
# Query lifecycle
# ---------------------------------------------------------------------------

@pytest.fixture()
def lifecycle_parts(segment):
    sink = InMemoryEmitter()
    em = ServiceEmitter("broker", "h", sink)
    logger = RequestLogger()
    qc = QueryCountStatsMonitor()
    lc = QueryLifecycle(QueryExecutor([segment]), em, logger,
                        authorizer=lambda ident, q: ident != "evil",
                        on_result=qc.on_query)
    return lc, sink, logger, qc


def test_lifecycle_metrics_and_logs(lifecycle_parts, segment):
    lc, sink, logger, qc = lifecycle_parts
    rows = lc.run(TimeseriesQuery.of("test", [DAY], [CountAggregator("n")]))
    assert rows[0]["result"]["n"] == segment.n_rows
    m = sink.metrics("query/time")[0]
    assert m.dims["dataSource"] == "test" and m.dims["success"] == "true"
    assert logger.entries[0]["queryType"] == "timeseries"
    assert logger.entries[0]["success"] is True
    assert qc.success == 1


def test_lifecycle_auth_and_errors(lifecycle_parts):
    lc, sink, logger, qc = lifecycle_parts
    q = TimeseriesQuery.of("test", [DAY], [CountAggregator("n")])
    with pytest.raises(Unauthorized):
        lc.run(q, identity="evil")
    assert logger.entries[-1]["error"] == "unauthorized"
    with pytest.raises(Exception):
        lc.run_json({"queryType": "timeseries", "dataSource": "test",
                     "intervals": [str(DAY)], "granularity": "all",
                     "aggregations": [{"type": "nope", "name": "x"}]})
    assert qc.failed >= 1


# ---------------------------------------------------------------------------
# HTTP endpoints
# ---------------------------------------------------------------------------

def _post(url, payload):
    req = urllib.request.Request(
        url, json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


@pytest.fixture()
def http_server(segment):
    ex = QueryExecutor([segment])
    lc = QueryLifecycle(ex)
    srv = QueryHttpServer(lc, SqlExecutor(ex), port=0).start()
    yield srv
    srv.stop()


def test_http_native_query(http_server, segment):
    base = f"http://127.0.0.1:{http_server.port}"
    status, rows = _post(f"{base}/druid/v2", {
        "queryType": "timeseries", "dataSource": "test",
        "intervals": [str(DAY)], "granularity": "all",
        "aggregations": [{"type": "count", "name": "n"}]})
    assert status == 200 and rows[0]["result"]["n"] == segment.n_rows


def test_http_sql(http_server, segment):
    base = f"http://127.0.0.1:{http_server.port}"
    status, rows = _post(f"{base}/druid/v2/sql",
                         {"query": "SELECT COUNT(*) n FROM test"})
    assert status == 200 and rows == [{"n": segment.n_rows}]
    status, rows = _post(f"{base}/druid/v2/sql",
                         {"query": "SELECT COUNT(*) FROM test",
                          "resultFormat": "array"})
    assert status == 200 and rows == [[segment.n_rows]]


def test_http_status_and_errors(http_server):
    base = f"http://127.0.0.1:{http_server.port}"
    with urllib.request.urlopen(f"{base}/status") as r:
        assert json.loads(r.read())["version"].startswith("druid-tpu")
    with urllib.request.urlopen(f"{base}/druid/v2/datasources") as r:
        assert json.loads(r.read()) == ["test"]
    status, err = _post(f"{base}/druid/v2", {"queryType": "bogus"})
    assert status == 400 and "error" in err
    status, err = _post(f"{base}/druid/v2/sql", {"query": "SELECT x FROM"})
    assert status == 400


# ---------------------------------------------------------------------------
# CLI tools
# ---------------------------------------------------------------------------

def test_cli_dump_and_validate(tmp_path, segment, capsys):
    from druid_tpu.cli import main
    from druid_tpu.storage.format import persist_segment
    d = str(tmp_path / "seg")
    persist_segment(segment, d)
    assert main(["validate-segment", d]) == 0
    out = capsys.readouterr().out
    assert "OK" in out and f"rows={segment.n_rows}" in out
    assert main(["dump-segment", d, "--full", "--rows", "2"]) == 0
    dump = json.loads(capsys.readouterr().out)
    assert dump["numRows"] == segment.n_rows
    assert dump["columns"]["dimA"]["cardinality"] == \
        segment.dims["dimA"].cardinality
    assert len(dump["rows"]) == 2
    assert main(["version"]) == 0


def test_http_serializes_extension_values(segment):
    import druid_tpu.ext  # noqa: F401
    ex = QueryExecutor([segment])
    srv = QueryHttpServer(QueryLifecycle(ex), port=0).start()
    try:
        base = f"http://127.0.0.1:{srv.port}"
        status, rows = _post(f"{base}/druid/v2", {
            "queryType": "timeseries", "dataSource": "test",
            "intervals": [str(DAY)], "granularity": "all",
            "aggregations": [
                {"type": "bloom", "name": "b", "fieldName": "dimA"},
                {"type": "approxHistogram", "name": "h",
                 "fieldName": "metLong", "numBuckets": 8,
                 "lowerLimit": 0.0, "upperLimit": 101.0}]})
        assert status == 200
        r = rows[0]["result"]
        assert isinstance(r["b"], str)                  # base64 bloom
        assert sum(r["h"]["counts"]) == segment.n_rows  # structured hist
    finally:
        srv.stop()


def test_variance_field_handling(segment):
    from druid_tpu.ext import VarianceAggregator
    ex = QueryExecutor([segment])
    with pytest.raises(ValueError):
        ex.run(TimeseriesQuery.of("test", [DAY],
                                  [VarianceAggregator("v", "dimA")]))
    rows = ex.run(TimeseriesQuery.of("test", [DAY],
                                     [VarianceAggregator("v", "__time")]))
    t = segment.time_ms.astype(np.float64)
    assert rows[0]["result"]["v"] == pytest.approx(t.var(), rel=1e-9)


def test_config_env_camelcase(tmp_path):
    cfg = Config.load(env={"DRUID_TPU_SERVER_DATANODES": "4"})
    assert cfg.get_int("server.dataNodes", 1) == 4


def test_cli_node_builders_compose_a_cluster(tmp_path, segment):
    """historical (preloading persisted segments from disk) + broker
    (discovering it over /status sync) built exactly as the per-node CLI
    commands build them, then queried over HTTP."""
    import json
    import urllib.request
    from druid_tpu.cli import build_broker, build_historical
    from druid_tpu.storage.format import persist_segment
    seg_dir = tmp_path / "segments" / "s0"
    persist_segment(segment, str(seg_dir))
    node, hist_srv, loaded = build_historical(
        "h0", str(tmp_path / "segments"), port=0)
    assert loaded == 1
    view, broker, http = build_broker([hist_srv.url], port=0)
    try:
        body = json.dumps({
            "queryType": "timeseries", "dataSource": "test",
            "intervals": ["2026-01-01/2026-01-02"], "granularity": "all",
            "aggregations": [{"type": "count", "name": "n"}]}).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{http.port}/druid/v2", data=body,
            headers={"Content-Type": "application/json"}, method="POST")
        rows = json.loads(urllib.request.urlopen(req, timeout=60).read())
        assert rows[0]["result"]["n"] == segment.n_rows
        # SQL rides the same broker
        sq = urllib.request.Request(
            f"http://127.0.0.1:{http.port}/druid/v2/sql",
            data=json.dumps({"query":
                             "SELECT COUNT(*) c FROM test"}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        out = json.loads(urllib.request.urlopen(sq, timeout=60).read())
        assert out[0]["c"] == segment.n_rows
    finally:
        http.stop()
        hist_srv.stop()


def test_cli_validate_rejects_garbage(tmp_path, capsys):
    from druid_tpu.cli import main
    d = tmp_path / "bad"
    d.mkdir()
    (d / "meta.smoosh").write_text("garbage")
    assert main(["validate-segment", str(d)]) == 1


# ---------------------------------------------------------------------------
# Ordered service lifecycle (java-util Lifecycle.java)
# ---------------------------------------------------------------------------

def test_lifecycle_stage_order_and_reverse_stop():
    from druid_tpu.utils.lifecycle import Lifecycle, Stage
    events = []

    def h(name):
        return dict(start=lambda: events.append(f"+{name}"),
                    stop=lambda: events.append(f"-{name}"))

    lc = Lifecycle()
    # registered out of stage order on purpose
    lc.add(**h("announce"), stage=Stage.ANNOUNCEMENTS)
    lc.add(**h("http"), stage=Stage.SERVER)
    lc.add(**h("meta"), stage=Stage.INIT)
    lc.add(**h("monitorA"), stage=Stage.NORMAL)
    lc.add(**h("monitorB"), stage=Stage.NORMAL)
    with lc:
        assert events == ["+meta", "+monitorA", "+monitorB", "+http",
                          "+announce"]
    assert events[5:] == ["-announce", "-http", "-monitorB", "-monitorA",
                          "-meta"]


def test_lifecycle_failed_start_unwinds_started_prefix():
    from druid_tpu.utils.lifecycle import Lifecycle, Stage
    events = []
    lc = Lifecycle()
    lc.add(start=lambda: events.append("+a"),
           stop=lambda: events.append("-a"), stage=Stage.INIT)
    lc.add(start=lambda: (_ for _ in ()).throw(RuntimeError("boom")),
           stop=lambda: events.append("-b"), stage=Stage.NORMAL)
    lc.add(start=lambda: events.append("+c"),
           stop=lambda: events.append("-c"), stage=Stage.SERVER)
    import pytest as _pytest
    with _pytest.raises(RuntimeError, match="boom"):
        lc.start()
    # only the started prefix unwound; the never-started c is untouched
    assert events == ["+a", "-a"]
    assert not lc.running


def test_lifecycle_rejects_late_registration_and_double_start():
    from druid_tpu.utils.lifecycle import Lifecycle
    import pytest as _pytest
    lc = Lifecycle()
    lc.add(start=lambda: None, stop=lambda: None)
    lc.start()
    with _pytest.raises(RuntimeError, match="already started"):
        lc.add(start=lambda: None, stop=lambda: None)
    lc.start()                      # idempotent
    lc.stop()
    lc.stop()                       # idempotent


def test_lifecycle_stop_keeps_going_past_bad_handler():
    from druid_tpu.utils.lifecycle import Lifecycle
    events = []
    lc = Lifecycle()
    lc.add(start=lambda: None, stop=lambda: events.append("-a"))
    lc.add(start=lambda: None,
           stop=lambda: (_ for _ in ()).throw(RuntimeError("bad stop")))
    lc.add(start=lambda: None, stop=lambda: events.append("-c"))
    lc.start()
    lc.stop()
    assert events == ["-c", "-a"]


def test_keepalive_connection_survives_401(segment):
    """HTTP/1.1 keep-alive: a 401 reply must drain the request body, or
    the next request on the same connection parses the stale body as its
    request line."""
    import http.client
    from druid_tpu.server.security import (AuthChain, AuthenticationResult)

    class HeaderGate:
        """Authenticates only requests carrying X-Magic."""
        def authenticate(self, headers):
            if any(k.lower() == "x-magic" for k in headers):
                return AuthenticationResult("alice", "allowAll")
            return None

    ex = QueryExecutor([segment])
    chain = AuthChain(authenticators=[HeaderGate()])
    srv = QueryHttpServer(QueryLifecycle(ex), SqlExecutor(ex),
                          auth_chain=chain, port=0).start()
    try:
        c = http.client.HTTPConnection("127.0.0.1", srv.port)
        body = json.dumps({"query": "SELECT COUNT(*) FROM test"})
        c.request("POST", "/druid/v2/sql", body,
                  {"Content-Type": "application/json"})
        r1 = c.getresponse()
        assert r1.status == 401
        r1.read()
        # same connection, now authenticated: must succeed, not 400
        c.request("POST", "/druid/v2/sql", body,
                  {"Content-Type": "application/json", "X-Magic": "1"})
        r2 = c.getresponse()
        assert r2.status == 200, r2.status
        assert json.loads(r2.read())[0]["EXPR$0"] == segment.n_rows
    finally:
        srv.stop()


def test_lifecycle_join_blocks_again_after_restart():
    from druid_tpu.utils.lifecycle import Lifecycle
    lc = Lifecycle()
    lc.add(start=lambda: None, stop=lambda: None)
    lc.start()
    lc.stop()
    lc.start()
    assert not lc.join(timeout=0.05)     # must block: not stopped yet
    lc.stop()
    assert lc.join(timeout=0.05)


def test_lifecycle_stop_during_start_leaks_nothing():
    """A stop() racing start() must not leave later-stage handlers running
    forever (the starting thread owns the unwind)."""
    import threading
    import time as _time
    from druid_tpu.utils.lifecycle import Lifecycle, Stage
    events = []
    gate = threading.Event()

    def slow_start():
        events.append("+slow")
        gate.set()
        _time.sleep(0.15)

    lc = Lifecycle()
    lc.add(start=slow_start, stop=lambda: events.append("-slow"),
           stage=Stage.INIT)
    lc.add(start=lambda: events.append("+http"),
           stop=lambda: events.append("-http"), stage=Stage.SERVER)
    t = threading.Thread(target=lc.start)
    t.start()
    gate.wait(2.0)
    lc.stop()               # arrives while slow_start is still running
    t.join(5.0)
    assert not lc.running
    # everything that started was stopped; nothing leaked
    started = {e[1:] for e in events if e.startswith("+")}
    stopped = {e[1:] for e in events if e.startswith("-")}
    assert started == stopped


# ---------------------------------------------------------------------------
# Prioritized query scheduler (PrioritizedExecutorService analog)
# ---------------------------------------------------------------------------

def test_scheduler_priority_order_and_capacity():
    import threading
    import time as _time
    from druid_tpu.server.querymanager import QueryScheduler
    sched = QueryScheduler(total_slots=1)
    assert sched.acquire(priority=0)
    admitted = []

    def waiter(name, prio):
        sched.acquire(priority=prio)
        admitted.append(name)
        sched.release()

    threads = [threading.Thread(target=waiter, args=("low", -1))]
    threads[0].start()
    _time.sleep(0.05)
    threads.append(threading.Thread(target=waiter, args=("high", 10)))
    threads[1].start()
    _time.sleep(0.05)
    assert admitted == []               # slot still held
    sched.release()
    for t in threads:
        t.join(5.0)
    # the later-arriving high-priority query was admitted first
    assert admitted == ["high", "low"]


def test_scheduler_lane_cap_does_not_block_other_lanes():
    from druid_tpu.server.querymanager import QueryScheduler
    sched = QueryScheduler(total_slots=4, lanes={"heavy": 1})
    assert sched.acquire(lane="heavy")
    # heavy lane full: a second heavy query times out...
    assert not sched.acquire(lane="heavy", timeout=0.1)
    # ...but an unlaned query sails through
    assert sched.acquire(timeout=0.1)
    sched.release("heavy")
    assert sched.acquire(lane="heavy", timeout=0.5)


def test_lifecycle_scheduler_admission_timeout(segment):
    from druid_tpu.server.querymanager import (QueryScheduler,
                                               QueryTimeoutError)
    sched = QueryScheduler(total_slots=1)
    lc = QueryLifecycle(QueryExecutor([segment]), scheduler=sched)
    q = TimeseriesQuery.of("test", [DAY], [CountAggregator("n")])
    rows = lc.run(q)
    assert rows[0]["result"]["n"] == segment.n_rows
    # slot freed after the run: a held slot + timeout context -> 504 path
    assert sched.stats()["running"] == 0
    sched.acquire()
    from dataclasses import replace
    q2 = replace(q, context=(("timeout", 100),))
    with pytest.raises(QueryTimeoutError, match="slot"):
        lc.run(q2)
    sched.release()
    assert lc.run(q)[0]["result"]["n"] == segment.n_rows


def test_cancel_while_queued_frees_waiter(segment):
    """DELETE on a query waiting for a slot aborts the wait — it must not
    consume a slot and run later."""
    import threading
    import time as _time
    from druid_tpu.server.querymanager import (QueryInterruptedError,
                                               QueryManager, QueryScheduler)
    sched = QueryScheduler(total_slots=1)
    qm = QueryManager()
    lc = QueryLifecycle(QueryExecutor([segment]), scheduler=sched,
                        query_manager=qm)
    sched.acquire()                      # hold the only slot
    from dataclasses import replace
    q = TimeseriesQuery.of("test", [DAY], [CountAggregator("n")])
    q = replace(q, context=(("queryId", "waiting-q"),))
    errs = []

    def run():
        try:
            lc.run(q)
        except QueryInterruptedError as e:
            errs.append(e)

    t = threading.Thread(target=run)
    t.start()
    _time.sleep(0.2)
    assert lc.cancel("waiting-q")
    t.join(5.0)
    assert errs and "cancelled" in str(errs[0])
    assert sched.stats() == {"running": 1, "waiting": 0}
    sched.release()


def test_scheduler_timeout_budget_is_total(segment):
    """`timeout` covers queue wait + execution: time spent waiting for a
    slot is deducted from the execution deadline."""
    from druid_tpu.server.querymanager import QueryScheduler
    seen = {}

    class Probe:
        def run(self, query):
            seen["timeout"] = query.context_map.get("timeout")
            return []

    import threading
    import time as _time
    sched = QueryScheduler(total_slots=1)
    lc = QueryLifecycle(Probe(), scheduler=sched)
    from dataclasses import replace
    q = TimeseriesQuery.of("test", [DAY], [CountAggregator("n")])
    q = replace(q, context=(("timeout", 5000),))
    sched.acquire()
    t = threading.Thread(target=lambda: lc.run(q))
    t.start()
    _time.sleep(0.4)                     # make it wait ~400ms
    sched.release()
    t.join(5.0)
    assert seen["timeout"] is not None
    assert seen["timeout"] <= 4800       # wait time deducted


def test_query_wait_time_metric(segment):
    from druid_tpu.server.querymanager import QueryScheduler
    sink = InMemoryEmitter()
    em = ServiceEmitter("broker", "h", sink)
    lc = QueryLifecycle(QueryExecutor([segment]), em,
                        scheduler=QueryScheduler(total_slots=2))
    lc.run(TimeseriesQuery.of("test", [DAY], [CountAggregator("n")]))
    waits = sink.metrics("query/wait/time")
    assert waits and waits[0].dims["dataSource"] == "test"


def test_cancel_beats_racing_admission(segment, monkeypatch):
    """A cancel that lands just as a slot frees must win: should_abort is
    consulted before the admission event is honored."""
    from druid_tpu.server.querymanager import (QueryInterruptedError,
                                               QueryScheduler)
    sched = QueryScheduler(total_slots=1)
    sched.acquire()
    cancelled = {"on": False}

    def abort():
        if cancelled["on"]:
            raise QueryInterruptedError("cancelled")

    import threading
    import time as _time
    result = {}

    def waiter():
        try:
            result["ok"] = sched.acquire(should_abort=abort)
        except QueryInterruptedError:
            result["aborted"] = True

    t = threading.Thread(target=waiter)
    t.start()
    _time.sleep(0.15)
    # cancel, THEN free the slot: the waiter must abort, not run
    cancelled["on"] = True
    sched.release()
    t.join(5.0)
    assert result.get("aborted") is True
    # the slot given back by the aborting waiter is acquirable again
    assert sched.acquire(timeout=1.0)
    assert sched.stats()["running"] == 1
    sched.release()


def test_cli_scheduler_config():
    from druid_tpu.cli import _scheduler_from_config
    from druid_tpu.utils.config import Config
    cfg = Config.load(None, env={}, overrides={
        "server.querySlots": "4", "server.lanes": "reports=1,adhoc=2"})
    sched = _scheduler_from_config(cfg)
    assert sched.total_slots == 4
    assert sched.lane_caps == {"reports": 1, "adhoc": 2}
    assert _scheduler_from_config(Config.load(None, env={})) is None


def test_cli_server_subprocess_smoke(tmp_path):
    """`python -m druid_tpu server` brings the whole single-process stack
    up through the staged Lifecycle, serves native + SQL queries, and
    shuts down cleanly on SIGINT. One retry: subprocess jax startup under
    full-suite load can exceed the wait (assertions AND timeout-class
    failures alike)."""
    for attempt in range(2):
        try:
            _run_server_smoke(tmp_path)
            return
        except Exception:
            if attempt == 1:
                raise


def _run_server_smoke(tmp_path):
    import os
    import re as _re
    import signal
    import subprocess
    import sys
    import time as _time
    import urllib.request

    cfg = tmp_path / "runtime.properties"
    cfg.write_text("server.port=0\nmetadata.path=:memory:\n"
                   f"storage.dir={tmp_path}/deep\n"
                   "server.querySlots=4\nserver.lanes=reports=1\n"
                   "coordinator.period=1\n")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"        # subprocess: no axon plugin
    env["PYTHONPATH"] = os.pathsep.join(
        [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
         if p and "axon" not in p] or [])
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = os.pathsep.join(
        [repo] + ([env["PYTHONPATH"]] if env["PYTHONPATH"] else []))
    p = subprocess.Popen(
        [sys.executable, "-m", "druid_tpu", "server", "--config", str(cfg)],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)
    try:
        import queue
        import threading
        lines: "queue.Queue[str]" = queue.Queue()

        def pump():
            for ln in p.stdout:
                lines.put(ln)
            lines.put("")                    # EOF marker

        threading.Thread(target=pump, daemon=True).start()
        seen, line = [], ""
        deadline = _time.time() + 300
        while _time.time() < deadline:
            try:
                line = lines.get(timeout=max(0.1, deadline - _time.time()))
            except queue.Empty:
                break
            if line == "":
                break                        # child exited
            seen.append(line)
            if "listening on" in line:
                break
        m = _re.search(r"listening on :(\d+)", line)
        assert m, f"no listen line; child output: {''.join(seen)!r}"
        port = int(m.group(1))
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/status", timeout=30) as r:
            assert json.loads(r.read())["version"].startswith("druid-tpu")
        req = urllib.request.Request(
            f"http://127.0.0.1:{port}/druid/v2/sql",
            json.dumps({"query": "SELECT TABLE_NAME FROM "
                        "INFORMATION_SCHEMA.TABLES"}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=30) as r:
            json.loads(r.read())            # empty cluster: no tables, 200
        p.send_signal(signal.SIGINT)
        assert p.wait(timeout=30) == 0
    finally:
        if p.poll() is None:
            p.kill()
