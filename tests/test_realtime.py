"""Appenderator + streaming exactly-once tests (reference: §3.4 Kafka
exactly-once call stack; AppenderatorImpl/StreamAppenderatorDriver tests)."""
import numpy as np
import pytest

from druid_tpu.cluster import (Broker, DataNode, InventoryView, MetadataStore,
                               descriptor_for)
from druid_tpu.cluster.metadata import SegmentDescriptor
from druid_tpu.engine import QueryExecutor
from druid_tpu.ingest import (Appenderator, RowBatch, SegmentAllocator,
                              SimulatedStream, StreamAppenderatorDriver,
                              StreamSupervisor, StreamSupervisorSpec,
                              StreamTuningConfig)
from druid_tpu.query.aggregators import CountAggregator, LongSumAggregator
from druid_tpu.query.model import TimeseriesQuery
from druid_tpu.utils.intervals import Interval

SPECS = [CountAggregator("rows"), LongSumAggregator("v", "value")]
# querying rolled-up data uses the combining form over STORED metric columns
# (reference: AggregatorFactory.getCombiningFactory — count re-queries as
# longSum of the stored row-count column)
QSPECS = [LongSumAggregator("rows", "rows"), LongSumAggregator("v", "v")]
DAY = Interval.of("2026-03-01", "2026-03-02")
T0 = DAY.start


def _records(n, t_start=T0, dim_card=5, seed=0):
    rng = np.random.default_rng(seed)
    return [{"timestamp": int(t_start + i * 1000),
             "page": f"p{int(rng.integers(dim_card))}",
             "value": int(rng.integers(0, 10))} for i in range(n)]


def _batch(records):
    return RowBatch([r["timestamp"] for r in records],
                    {"page": [r["page"] for r in records],
                     "value": [r["value"] for r in records]})


# ---------------------------------------------------------------------------
# Appenderator
# ---------------------------------------------------------------------------

def test_allocator_partitions_and_versions():
    md = MetadataStore()
    alloc = SegmentAllocator(md, "hour")
    a = alloc.allocate("ds", T0)
    b = alloc.allocate("ds", T0)          # same bucket → next partition
    c = alloc.allocate("ds", T0 + 3_600_000)
    assert a.interval == b.interval and a.partition == 0 and b.partition == 1
    assert c.interval.start == T0 + 3_600_000 and c.partition == 0
    # allocation continues from published partitions after restart
    md.publish_segments([SegmentDescriptor("ds", a.interval, a.version, 5)])
    alloc2 = SegmentAllocator(md, "hour")
    d = alloc2.allocate("ds", T0, version=a.version)
    assert d.partition == 6


def test_concurrent_allocators_share_version():
    """Two independent allocators (= two task groups) hitting one bucket
    must get the SAME version with distinct partitions — different versions
    would let MVCC overshadow one task's data (the overlord-side
    SegmentAllocateAction guarantee)."""
    md = MetadataStore()
    a1 = SegmentAllocator(md, "hour")
    a2 = SegmentAllocator(md, "hour")
    x = a1.allocate("ds", T0)
    y = a2.allocate("ds", T0)
    z = a1.allocate("ds", T0)
    assert x.version == y.version == z.version
    assert sorted([x.partition, y.partition, z.partition]) == [0, 1, 2]


def test_allocation_refuses_conflicting_granularity():
    """Allocating an hour bucket inside a committed day segment must fail —
    a newer version there would partially overshadow the day's data."""
    from druid_tpu.cluster.metadata import SegmentAllocationError
    md = MetadataStore()
    md.publish_segments([SegmentDescriptor("ds", DAY, "v1", 0)])
    alloc = SegmentAllocator(md, "hour")
    with pytest.raises(SegmentAllocationError):
        alloc.allocate("ds", T0)
    # same-granularity appends still work
    alloc_day = SegmentAllocator(md, "day")
    ident = alloc_day.allocate("ds", T0)
    assert ident.version == "v1" and ident.partition == 1


def test_pending_segments_cleanup():
    md = MetadataStore()
    alloc = SegmentAllocator(md, "hour")
    a = alloc.allocate("ds", T0)
    b = alloc.allocate("ds", T0)
    # publish consumes a's pending row; kill clears the rest
    md.publish_segments([SegmentDescriptor("ds", a.interval, a.version,
                                           a.partition)])
    assert md.kill_pending_segments("ds") == 1
    assert md.kill_pending_segments("ds") == 0


def test_appenderator_rollup_and_query():
    app = Appenderator("rt", SPECS, query_granularity="none",
                       max_rows_per_hydrant=300)
    alloc = SegmentAllocator(MetadataStore(), "day")
    ident = alloc.allocate("rt", T0)
    recs = _records(1000)
    for i in range(0, 1000, 100):   # incremental adds → hydrant persists
        app.add(ident, _batch(recs[i:i + 100]))
    sink = app._sinks[ident.id]
    assert len(sink.hydrants) >= 3
    # in-flight data queryable with the standard engines
    ex = QueryExecutor(app.query_segments())
    rows = ex.run(TimeseriesQuery.of("rt", [DAY], QSPECS))
    assert rows[0]["result"]["rows"] == 1000
    assert rows[0]["result"]["v"] == sum(r["value"] for r in recs)
    # push merges hydrants into one segment with rollup preserved
    pushed = app.push([ident])
    assert len(pushed) == 1
    desc, seg = pushed[0]
    assert desc.id == ident.id
    ex2 = QueryExecutor([seg])
    assert ex2.run(TimeseriesQuery.of("rt", [DAY], QSPECS)) == rows


def test_driver_routes_by_segment_granularity():
    md = MetadataStore()
    app = Appenderator("rt", SPECS)
    driver = StreamAppenderatorDriver(app, SegmentAllocator(md, "hour"), md)
    recs = _records(100) + _records(100, t_start=T0 + 2 * 3_600_000)
    driver.add_batch(_batch(recs))
    idents = app.sink_ids()
    assert len(idents) == 2
    assert {i.interval.start for i in idents} == {T0, T0 + 2 * 3_600_000}


def test_driver_publish_cas():
    md = MetadataStore()
    app = Appenderator("rt", SPECS)
    driver = StreamAppenderatorDriver(app, SegmentAllocator(md, "day"), md)
    driver.add_batch(_batch(_records(50)))
    assert driver.publish_all(None, {"offset": 50})
    assert md.datasource_metadata("rt") == {"offset": 50}
    assert len(md.used_segments("rt")) == 1
    # a stale publisher (expected None again) must be rejected atomically
    app2 = Appenderator("rt", SPECS)
    d2 = StreamAppenderatorDriver(app2, SegmentAllocator(md, "day"), md)
    d2.add_batch(_batch(_records(50)))
    assert not d2.publish_all(None, {"offset": 50})
    assert len(md.used_segments("rt")) == 1


# ---------------------------------------------------------------------------
# Streaming supervisor: exactly-once under failure
# ---------------------------------------------------------------------------

def _supervisor(md, stream, handoff=None, task_count=1,
                max_rows=10**9):
    spec = StreamSupervisorSpec(
        "stream_ds", SPECS, dimensions=["page"], task_count=task_count,
        max_rows_per_task=max_rows,
        tuning=StreamTuningConfig(segment_granularity="day"))
    return StreamSupervisor(spec, stream, md, handoff=handoff)


def test_stream_ingest_end_to_end():
    md = MetadataStore()
    stream = SimulatedStream(n_partitions=2)
    stream.append(0, _records(500, seed=1))
    stream.append(1, _records(300, t_start=T0 + 1000, seed=2))
    sup = _supervisor(md, stream, task_count=2)
    sup.run_once()
    # in-flight rows queryable before publish
    ex = QueryExecutor(sup.query_segments())
    rows = ex.run(TimeseriesQuery.of("stream_ds", [DAY], QSPECS))
    assert rows[0]["result"]["rows"] == 800
    assert sup.checkpoint_all()
    meta = md.datasource_metadata("stream_ds")
    assert meta["partitions"] == {"0": 500, "1": 300}
    total = sum(d.num_rows for d in md.used_segments("stream_ds"))
    assert total > 0


def test_stream_exactly_once_on_task_failure():
    """Task dies after reading but before publish → replacement re-reads
    from committed offsets; no loss, no duplicates."""
    md = MetadataStore()
    published = []
    stream = SimulatedStream(n_partitions=1)
    stream.append(0, _records(400, seed=3))
    sup = _supervisor(md, stream,
                      handoff=lambda pushed: published.extend(pushed))
    sup.run_once()
    assert sup.checkpoint_all()          # commit offset 400

    stream.append(0, _records(200, t_start=T0 + 500_000, seed=4))
    sup.run_once()                       # task reads 200 more, NOT committed
    task = list(sup.tasks.values())[0]
    assert task.current_offsets[0] == 600
    task.status = "FAILED"               # simulated crash before publish

    sup.run_once()                       # replacement resumes at 400
    new_task = list(sup.tasks.values())[0]
    assert new_task is not task
    assert new_task.start_offsets[0] == 400
    assert sup.checkpoint_all()
    assert md.datasource_metadata("stream_ds")["partitions"] == {"0": 600}

    # every appended record lands in the published segments EXACTLY once
    ex = QueryExecutor([seg for _, seg in published])
    rows = ex.run(TimeseriesQuery.of("stream_ds", [DAY], QSPECS))
    all_recs = _records(400, seed=3) + _records(200, t_start=T0 + 500_000,
                                                seed=4)
    assert rows[0]["result"]["rows"] == 600
    assert rows[0]["result"]["v"] == sum(r["value"] for r in all_recs)


def test_stream_duplicate_publish_rejected():
    """Two replica tasks over the same offsets: only one CAS wins."""
    md = MetadataStore()
    stream = SimulatedStream(n_partitions=1)
    stream.append(0, _records(100, seed=5))
    sup_a = _supervisor(md, stream)
    sup_b = _supervisor(md, stream)
    sup_a.run_once()
    sup_b.run_once()
    assert sup_a.checkpoint_all()
    assert not sup_b.checkpoint_all()    # loser discarded
    assert md.datasource_metadata("stream_ds")["partitions"] == {"0": 100}
    # each record published exactly once (distinct timestamps → no rollup)
    assert sum(d.num_rows for d in md.used_segments("stream_ds")) == 100


def test_realtime_queryable_through_broker_before_publish():
    """Druid's signature capability: rows are queryable through the NORMAL
    broker path seconds after ingest, before any checkpoint/handoff
    (SinkQuerySegmentWalker)."""
    from druid_tpu.cluster import RealtimeServer
    md = MetadataStore()
    view = InventoryView()
    rt = RealtimeServer("peon0", view)
    stream = SimulatedStream(n_partitions=1)
    recs = _records(300, seed=7)
    stream.append(0, recs)
    spec = StreamSupervisorSpec(
        "stream_ds", SPECS, dimensions=["page"], task_count=1,
        max_rows_per_task=10**9,
        tuning=StreamTuningConfig(segment_granularity="day"))
    sup = StreamSupervisor(spec, stream, md, realtime=rt)
    sup.run_once()

    # NO publish yet — the broker must still see the rows via the announced
    # in-flight sink
    assert md.datasource_metadata("stream_ds") is None
    broker = Broker(view)
    assert "stream_ds" in broker.datasources
    rows = broker.run(TimeseriesQuery.of("stream_ds", [DAY], QSPECS))
    assert rows[0]["result"]["rows"] == 300
    assert rows[0]["result"]["v"] == sum(r["value"] for r in recs)

    # more rows arrive: the SAME sink serves the larger count (no caching)
    more = _records(100, t_start=T0 + 50_000_000, seed=8)
    stream.append(0, more)
    sup.run_once()
    rows = broker.run(TimeseriesQuery.of("stream_ds", [DAY], QSPECS))
    assert rows[0]["result"]["rows"] == 400

    # row-path queries work against the sink too
    from druid_tpu.query.model import TimeBoundaryQuery
    tb = broker.run(TimeBoundaryQuery.of("stream_ds", [DAY]))
    assert tb[0]["result"]["minTime"] == T0


def test_realtime_handoff_is_seamless(monkeypatch):
    """Publish + handoff: the historical replica joins the sink's replica
    set under the same segment id, the sink unannounces, and the broker
    keeps returning identical results throughout."""
    from druid_tpu.cluster import RealtimeServer
    md = MetadataStore()
    view = InventoryView()
    rt = RealtimeServer("peon0", view)
    node = DataNode("historical0")
    view.register(node)

    def handoff(pushed):
        for desc, seg in pushed:
            node.load_segment(seg)
            view.announce(node.name, desc)

    stream = SimulatedStream(n_partitions=1)
    recs = _records(250, seed=9)
    stream.append(0, recs)
    spec = StreamSupervisorSpec(
        "stream_ds", SPECS, dimensions=["page"], task_count=1,
        max_rows_per_task=10**9,
        tuning=StreamTuningConfig(segment_granularity="day"))
    sup = StreamSupervisor(spec, stream, md, handoff=handoff, realtime=rt)
    sup.run_once()
    broker = Broker(view)
    q = TimeseriesQuery.of("stream_ds", [DAY], QSPECS)
    before = broker.run(q)
    assert before[0]["result"]["rows"] == 250

    assert sup.checkpoint_all()
    # sink dropped: realtime serves nothing, historical serves everything
    assert rt.served_segment_ids() == set()
    assert node.segment_count() == 1
    after = broker.run(q)
    assert after == before
    sids = [rs for rs in [view.replica_set(str(s.id))
                          for s in node.segments()] if rs]
    assert all(rs.servers == {"historical0"} for rs in sids)


def test_stream_handoff_to_cluster():
    """Published segments hand off to a data node and serve via broker."""
    md = MetadataStore()
    view = InventoryView()
    node = DataNode("historical0")
    view.register(node)

    def handoff(pushed):
        for desc, seg in pushed:
            node.load_segment(seg)
            view.announce(node.name, desc)

    stream = SimulatedStream(n_partitions=1)
    recs = _records(250, seed=6)
    stream.append(0, recs)
    sup = _supervisor(md, stream, handoff=handoff)
    sup.run_once()
    assert sup.checkpoint_all()
    broker = Broker(view)
    rows = broker.run(TimeseriesQuery.of("stream_ds", [DAY], QSPECS))
    assert rows[0]["result"]["rows"] == 250
    assert rows[0]["result"]["v"] == sum(r["value"] for r in recs)
