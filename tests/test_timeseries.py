"""Timeseries engine vs numpy golden results (the reference's
TimeseriesQueryRunnerTest pattern over generated segments)."""
import numpy as np
import pytest

from druid_tpu.engine.executor import QueryExecutor
from druid_tpu.query import (BoundFilter, CountAggregator, DoubleSumAggregator,
                             FirstAggregator, LastAggregator, LongMaxAggregator,
                             LongSumAggregator, SelectorFilter,
                             FloatMinAggregator)
from druid_tpu.query.model import ExpressionVirtualColumn, TimeseriesQuery
from druid_tpu.query.postaggs import ArithmeticPostAgg, FieldAccessPostAgg
from druid_tpu.utils.granularity import Granularity
from druid_tpu.utils.intervals import Interval

from conftest import DAY, rows_as_frame


AGGS = [CountAggregator("rows"),
        LongSumAggregator("sumLong", "metLong"),
        DoubleSumAggregator("sumDouble", "metDouble"),
        LongMaxAggregator("maxLong", "metLong"),
        FloatMinAggregator("minFloat", "metFloat")]


def golden(frame, mask, aggs_only=False):
    out = {
        "rows": int(mask.sum()),
        "sumLong": int(frame["metLong"][mask].sum()),
        "sumDouble": float(frame["metDouble"][mask].sum()),
        "maxLong": int(frame["metLong"][mask].max()) if mask.any() else None,
        "minFloat": float(frame["metFloat"][mask].min()) if mask.any() else None,
    }
    return out


def check(result_vals, expected):
    assert result_vals["rows"] == expected["rows"]
    assert result_vals["sumLong"] == expected["sumLong"]
    assert result_vals["sumDouble"] == pytest.approx(expected["sumDouble"], rel=1e-9)
    if expected["rows"]:
        assert result_vals["maxLong"] == expected["maxLong"]
        assert result_vals["minFloat"] == pytest.approx(expected["minFloat"], rel=1e-6)


def test_timeseries_all_granularity(segment):
    ex = QueryExecutor([segment])
    q = TimeseriesQuery.of("test", DAY, AGGS)
    rows = ex.run(q)
    assert len(rows) == 1
    frame = rows_as_frame(segment)
    mask = np.ones(segment.n_rows, dtype=bool)
    check(rows[0]["result"], golden(frame, mask))
    assert rows[0]["timestamp"] == DAY.start


def test_timeseries_hour_granularity_with_filter(segment):
    ex = QueryExecutor([segment])
    flt = SelectorFilter("dimA", "v00000003")
    q = TimeseriesQuery.of("test", DAY, AGGS, granularity="hour", filter=flt)
    rows = ex.run(q)
    assert len(rows) == 24
    frame = rows_as_frame(segment)
    g = Granularity.of("hour")
    for row in rows:
        st = row["timestamp"]
        mask = ((frame["__time"] >= st) & (frame["__time"] < st + 3600_000)
                & (frame["dimA"] == "v00000003"))
        check(row["result"], golden(frame, mask))


def test_timeseries_numeric_bound_filter(segment):
    ex = QueryExecutor([segment])
    flt = BoundFilter("metLong", lower="10", upper="50", upper_strict=True,
                      ordering="numeric")
    q = TimeseriesQuery.of("test", DAY, AGGS, filter=flt)
    rows = ex.run(q)
    frame = rows_as_frame(segment)
    mask = (frame["metLong"] >= 10) & (frame["metLong"] < 50)
    check(rows[0]["result"], golden(frame, mask))


def test_timeseries_sub_interval(segment):
    ex = QueryExecutor([segment])
    iv = Interval.of("2026-01-01T06:00:00Z", "2026-01-01T12:00:00Z")
    q = TimeseriesQuery.of("test", iv, AGGS)
    rows = ex.run(q)
    assert len(rows) == 1
    frame = rows_as_frame(segment)
    mask = (frame["__time"] >= iv.start) & (frame["__time"] < iv.end)
    check(rows[0]["result"], golden(frame, mask))


def test_timeseries_multi_segment(segments):
    ex = QueryExecutor(segments)
    iv = Interval.of("2026-01-01", "2026-01-05")
    q = TimeseriesQuery.of("test", iv, AGGS, granularity="day")
    rows = ex.run(q)
    assert len(rows) == 4
    for row, seg in zip(rows, segments):
        frame = rows_as_frame(seg)
        mask = np.ones(seg.n_rows, dtype=bool)
        check(row["result"], golden(frame, mask))


def test_timeseries_first_last(segment):
    ex = QueryExecutor([segment])
    q = TimeseriesQuery.of("test", DAY, [
        FirstAggregator("firstD", "metDouble", "double"),
        LastAggregator("lastD", "metDouble", "double"),
    ])
    rows = ex.run(q)
    frame = rows_as_frame(segment)
    i_first = int(np.argmin(frame["__time"]))
    i_last = int(np.argmax(frame["__time"]))
    # ties broken by row order: first row at min time, exact values may differ
    # under ties, so compare against the value at the first/last time instant
    tmin, tmax = frame["__time"][i_first], frame["__time"][i_last]
    first_candidates = frame["metDouble"][frame["__time"] == tmin]
    last_candidates = frame["metDouble"][frame["__time"] == tmax]
    assert rows[0]["result"]["firstD"] == pytest.approx(first_candidates[0])
    assert rows[0]["result"]["lastD"] in [pytest.approx(v) for v in last_candidates]


def test_timeseries_postaggs(segment):
    ex = QueryExecutor([segment])
    pa = ArithmeticPostAgg("avgLong", "/", (
        FieldAccessPostAgg("s", "sumLong"), FieldAccessPostAgg("c", "rows")))
    q = TimeseriesQuery.of("test", DAY, AGGS, post_aggregations=[pa])
    rows = ex.run(q)
    r = rows[0]["result"]
    assert r["avgLong"] == pytest.approx(r["sumLong"] / r["rows"])


def test_timeseries_virtual_column(segment):
    ex = QueryExecutor([segment])
    vc = ExpressionVirtualColumn("v", "metLong * 2 + 1", "long")
    q = TimeseriesQuery.of("test", DAY, [LongSumAggregator("sv", "v")],
                           virtual_columns=[vc])
    rows = ex.run(q)
    frame = rows_as_frame(segment)
    assert rows[0]["result"]["sv"] == int((frame["metLong"] * 2 + 1).sum())


def test_virtual_column_string_dim_comparison(segment):
    """A CASE-style expression over a STRING dim must use true string
    semantics on the device path (plan-time LUT rewrite), not raw
    dictionary ids."""
    ex = QueryExecutor([segment])
    frame = rows_as_frame(segment)
    val = frame["dimA"][0]
    vc = ExpressionVirtualColumn(
        "v", f"if(dimA == '{val}', metLong, 0)", "long")
    q = TimeseriesQuery.of("test", DAY, [LongSumAggregator("sv", "v")],
                           virtual_columns=[vc])
    rows = ex.run(q)
    want = int(frame["metLong"][frame["dimA"] == val].sum())
    assert want > 0 and rows[0]["result"]["sv"] == want
    # ordering comparison (lexicographic over dictionary values)
    vc2 = ExpressionVirtualColumn(
        "w", f"if(dimA <= '{val}', 1, 0)", "long")
    q2 = TimeseriesQuery.of("test", DAY, [LongSumAggregator("sw", "w")],
                            virtual_columns=[vc2])
    want2 = int((frame["dimA"].astype(str) <= val).sum())
    assert ex.run(q2)[0]["result"]["sw"] == want2


def test_expression_filter_string_dim(segment):
    from druid_tpu.query.filters import ExpressionFilter
    ex = QueryExecutor([segment])
    frame = rows_as_frame(segment)
    val = frame["dimB"][1]
    q = TimeseriesQuery.of(
        "test", DAY, [CountAggregator("rows")],
        filter=ExpressionFilter(f"dimB == '{val}' && metLong > 10"))
    want = int(((frame["dimB"] == val) & (frame["metLong"] > 10)).sum())
    assert ex.run(q)[0]["result"]["rows"] == want


def test_virtual_column_string_dim_sharded(segments):
    """Same semantics through the stacked sharded program (LUTs ride the
    replicated aux stream)."""
    from druid_tpu.parallel import make_mesh
    frames = [rows_as_frame(s) for s in segments]
    val = frames[0]["dimA"][0]
    vc = ExpressionVirtualColumn(
        "v", f"if(dimA == '{val}', metLong, 0)", "long")
    q = TimeseriesQuery.of("test", Interval.of("2026-01-01", "2026-01-05"),
                           [LongSumAggregator("sv", "v")],
                           virtual_columns=[vc])
    want = sum(int(f["metLong"][f["dimA"] == val].sum()) for f in frames)
    got = QueryExecutor(segments, mesh=make_mesh(2)).run(q)
    assert want > 0 and got[0]["result"]["sv"] == want


def test_timeseries_empty_interval(segment):
    ex = QueryExecutor([segment])
    q = TimeseriesQuery.of("test", "2027-01-01/2027-01-02", AGGS)
    assert ex.run(q) == []


def test_timeseries_descending(segment):
    ex = QueryExecutor([segment])
    q = TimeseriesQuery.of("test", DAY, [CountAggregator("rows")],
                           granularity="hour", descending=True)
    rows = ex.run(q)
    ts = [r["timestamp"] for r in rows]
    assert ts == sorted(ts, reverse=True)
