"""One-dispatch megakernel (engine/megakernel.py): random-tree parity vs
the staged path and the numpy host-mask oracle (n_rows % 32 != 0
included), the exactly-ONE-cold-dispatch contract (obs/dispatch deltas),
the fused pallas projection variant (in-kernel word-mask unpack) with
donated-carry ticks (no per-tick pool growth, donated reuse bit-identical
to fresh buffers), perm-keyed bitmap cache entries for the projection
layout, filtered aggregators planning bitmap words, the unify-remap TTL
sweep, and the new obs metrics."""
import warnings

import numpy as np
import pytest

import druid_tpu.engine  # noqa: F401  (x64 on before jax numerics)
from druid_tpu.data.devicepool import device_pool
from druid_tpu.data.generator import ColumnSpec, DataGenerator
from druid_tpu.engine import engines, filters as filters_mod, grouping
from druid_tpu.engine import megakernel, pallas_agg
from druid_tpu.engine.executor import QueryExecutor
from druid_tpu.engine.filters import (DeviceBitmapNode, collect_bitmap_nodes,
                                      host_mask)
from druid_tpu.engine.kernels import FilteredKernel, make_kernel
from druid_tpu.obs import dispatch as dispatch_mod
from druid_tpu.query import filters as F
from druid_tpu.query.aggregators import (CountAggregator, FilteredAggregator,
                                         LongSumAggregator)
from druid_tpu.utils.intervals import Interval

IV = Interval.of("2026-05-01", "2026-05-05")

SCHEMA = (
    ColumnSpec("dLo", "string", cardinality=8),
    ColumnSpec("dMid", "string", cardinality=60),
    ColumnSpec("dHi", "string", cardinality=800),
    ColumnSpec("metLong", "long", low=0, high=1000),
    ColumnSpec("metDouble", "double", low=0.0, high=1.0),
)


@pytest.fixture(scope="module")
def mk_segments():
    # 3333 rows: n_rows % 32 != 0, so word-boundary rows are exercised
    return DataGenerator(SCHEMA, seed=21).segments(
        2, 3333, IV, datasource="mk")


@pytest.fixture(autouse=True)
def _mega_on():
    prev = megakernel.set_enabled(True)
    prev_b = filters_mod.set_device_bitmap_enabled(True)
    yield
    megakernel.set_enabled(prev)
    filters_mod.set_device_bitmap_enabled(prev_b)


def _rand_leaf(rng, seg):
    dim = ("dLo", "dMid", "dHi")[rng.integers(3)]
    vals = list(seg.dims[dim].dictionary.values)
    kind = rng.integers(3)
    if kind == 0:
        v = vals[rng.integers(len(vals))] if rng.random() < 0.85 \
            else "zzz-missing"
        return F.SelectorFilter(dim, v)
    if kind == 1:
        k = int(rng.integers(1, 5))
        return F.InFilter(dim, tuple(vals[rng.integers(len(vals))]
                                     for _ in range(k)))
    lo = vals[rng.integers(len(vals))]
    hi = vals[rng.integers(len(vals))]
    lo, hi = (lo, hi) if lo <= hi else (hi, lo)
    return F.BoundFilter(dim, lower=lo, upper=hi,
                         lower_strict=bool(rng.integers(2)))


def _rand_tree(rng, seg, depth):
    if depth == 0 or rng.random() < 0.35:
        return _rand_leaf(rng, seg)
    op = rng.integers(3)
    if op == 0:
        return F.NotFilter(_rand_tree(rng, seg, depth - 1))
    kids = tuple(_rand_tree(rng, seg, depth - 1)
                 for _ in range(int(rng.integers(2, 4))))
    return F.AndFilter(kids) if op == 1 else F.OrFilter(kids)


def _query(flt, aggs=None):
    q = {"queryType": "timeseries", "dataSource": "mk",
         "intervals": [str(IV)], "granularity": "all",
         "aggregations": aggs or [
             {"type": "count", "name": "n"},
             {"type": "longSum", "name": "s", "fieldName": "metLong"},
             {"type": "doubleSum", "name": "d", "fieldName": "metDouble"}]}
    if flt is not None:
        q["filter"] = flt.to_json()
    return q


def _oracle_count(flt, segs):
    return sum(int(host_mask(flt, s).sum()) for s in segs)


# ---------------------------------------------------------------------------
# parity: randomized filter trees × aggregators, fused vs staged vs oracle
# ---------------------------------------------------------------------------

def test_random_tree_fused_parity_gate(mk_segments):
    """The PR 9 discipline for the fused path: random trees evaluated
    through the megakernel (per-segment, batching off) must EXACTLY match
    the staged path — floats included — with counts pinned to the numpy
    host-mask oracle."""
    from druid_tpu.engine import batching
    rng = np.random.default_rng(5)
    ex = QueryExecutor(mk_segments)
    pb = batching.set_enabled(False)     # per-segment: the megaize path
    try:
        for i in range(12):
            flt = _rand_tree(rng, mk_segments[0], depth=3 if i % 2 else 2)
            q = _query(flt)
            device_pool().clear()        # cold: the one-shot fused shape
            fused = ex.run_json(q)
            prev = megakernel.set_enabled(False)
            try:
                device_pool().clear()
                staged = ex.run_json(q)
            finally:
                megakernel.set_enabled(prev)
            assert fused == staged, f"tree {i}: {flt}"
            got_n = fused[0]["result"]["n"] if fused else 0
            assert got_n == _oracle_count(flt, mk_segments), f"tree {i}"
    finally:
        batching.set_enabled(pb)


def test_cold_query_is_exactly_one_dispatch(mk_segments):
    """The tentpole contract: a cold bitmap-filtered query through the
    fused path costs exactly ONE device dispatch; the staged path pays the
    bitmap fill wave too."""
    seg = mk_segments[0]
    ex = QueryExecutor([seg])
    flt = F.NotFilter(F.SelectorFilter(
        "dLo", seg.dims["dLo"].dictionary.values[0]))
    q = _query(flt)
    device_pool().clear()
    d0 = dispatch_mod.count()
    fused = ex.run_json(q)
    assert dispatch_mod.count() - d0 == 1
    prev = megakernel.set_enabled(False)
    try:
        device_pool().clear()
        d0 = dispatch_mod.count()
        staged = ex.run_json(q)
        assert dispatch_mod.count() - d0 == 2     # fill wave + aggregation
    finally:
        megakernel.set_enabled(prev)
    assert fused == staged


def test_resident_combined_words_keep_cached_path(mk_segments):
    """Hot dashboards: when the combined words are ALREADY resident the
    planner keeps the cached bit-test path (one dispatch, no algebra) and
    counts it as a megakernel fallback, not a hit."""
    seg = DataGenerator(SCHEMA, seed=33).segments(
        1, 3333, IV, datasource="mk")[0]
    ex = QueryExecutor([seg])
    flt = F.SelectorFilter("dMid", seg.dims["dMid"].dictionary.values[1])
    q = _query(flt)
    prev = megakernel.set_enabled(False)
    try:
        warm = ex.run_json(q)            # builds + caches combined words
    finally:
        megakernel.set_enabled(prev)
    s0 = megakernel.stats().snapshot()
    d0 = dispatch_mod.count()
    again = ex.run_json(q)               # mega on, words resident
    s1 = megakernel.stats().snapshot()
    assert dispatch_mod.count() - d0 == 1
    assert s1["fallbacks"] == s0["fallbacks"] + 1
    assert s1["hits"] == s0["hits"]
    assert again == warm


# ---------------------------------------------------------------------------
# the fused pallas variant: in-kernel word mask + donated carries
# ---------------------------------------------------------------------------

def _proj_setup(monkeypatch):
    monkeypatch.setattr(grouping, "PROJECTION_MIN_ROWS", 0)
    monkeypatch.setattr(pallas_agg, "_FORCE_INTERPRET", True)
    schema = (
        ColumnSpec("dimA", "string", cardinality=30),
        ColumnSpec("dimB", "string", cardinality=200, distribution="zipf"),
        ColumnSpec("metLong", "long", low=-500, high=9000),
        ColumnSpec("metFloat", "float", distribution="normal", mean=10.0,
                   std=400.0),
    )
    segs = DataGenerator(schema, seed=77).segments(2, 20000, IV,
                                                   datasource="pj")
    vals = list(segs[0].dims["dimA"].dictionary.values)
    q = {"queryType": "groupBy", "dataSource": "pj",
         "intervals": [str(IV)], "granularity": "all",
         "dimensions": ["dimA", "dimB"],
         "aggregations": [
             {"type": "count", "name": "rows"},
             {"type": "longSum", "name": "lsum", "fieldName": "metLong"},
             {"type": "floatSum", "name": "fsum", "fieldName": "metFloat"},
             {"type": "longMin", "name": "lmin", "fieldName": "metLong"}],
         "filter": {"type": "in", "dimension": "dimA", "values": vals[:20]}}
    return segs, q


def test_mega_pallas_strategy_selected_and_bit_identical(monkeypatch,
                                                         mk_segments):
    """On the sorted-projection path the fused variant upgrades "pallas" to
    "megakernel" (mask rides into the kernel as words) and stays
    bit-identical to the staged pallas kernel — floats included, since the
    block/accumulation order is the same."""
    segs, q = _proj_setup(monkeypatch)
    ex = QueryExecutor(segs)
    seen = []
    orig = grouping.fuse_filter_update

    def spy(*a, **k):
        seen.append(k.get("strategy"))
        return orig(*a, **k)
    monkeypatch.setattr(grouping, "fuse_filter_update", spy)
    fused = ex.run_json(q)
    monkeypatch.setattr(grouping, "fuse_filter_update", orig)
    assert "megakernel" in seen, seen
    prev = megakernel.set_enabled(False)
    try:
        staged = ex.run_json(q)          # staged pallas kernel
    finally:
        megakernel.set_enabled(prev)
    assert fused == staged               # exact, floats included


def test_mega_carry_ticks_no_pool_growth_and_parity(monkeypatch):
    """Repeated (scheduler-tick-style) execution cycles ONE carry entry
    through the pool — no per-tick HBM growth, asserted under the leak
    witness — and donated-carry reuse is bit-identical to fresh buffers
    (the kernel re-inits at grid step 0). The carry handoff follows
    donation support (off on CPU), so the test forces it on."""
    import sys
    from pathlib import Path
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    from tools.druidlint.leakwitness import LeakWitness
    segs, q = _proj_setup(monkeypatch)
    ex = QueryExecutor(segs)
    prev_c = megakernel.set_force_carry(True)
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            first = ex.run_json(q)       # cold: fresh zero carries
            with LeakWitness(
                    str(Path(__file__).resolve().parent.parent)) as w:
                base = w.snapshot()      # post-first-tick resource state
                ticks = [ex.run_json(q) for _ in range(3)]
                residue = w.leaks(base, grace_s=2.0)
        assert all(t == first for t in ticks)     # carried ≡ fresh, bitwise
        assert not residue, residue               # zero per-tick growth
        # the carry entries really exist (one per (segment, program))
        carry_keys = [k for s in segs
                      for k in s._pool._entries
                      if "megacarry" in k]
        assert carry_keys
        device_pool().clear()
        again = ex.run_json(q)                    # cold again: same results
        assert again == first
    finally:
        megakernel.set_force_carry(prev_c)
    # CPU default: no donation support ⇒ carryless execution parks NOTHING
    # in the budgeted pool (the grids would only evict useful entries)
    device_pool().clear()
    ex.run_json(q)
    leftover = [k for s in segs
                for k in s._pool._entries
                if "megacarry" in k]
    assert not leftover


def test_mega_carry_failed_dispatch_discards_ownership(monkeypatch):
    """A dispatch failure AFTER the carry take (the Mosaic-compile window)
    must DISCARD the popped grids, not re-park them — donation may have
    invalidated the buffers mid-flight — and leave the pool's byte
    accounting truthful: resident bytes must equal the entries actually
    held, with no megacarry entry surviving the failure (donorguard
    take-without-repark, enforced on grouping's exception path)."""
    import collections
    segs, q = _proj_setup(monkeypatch)
    ex = QueryExecutor(segs)
    prev_c = megakernel.set_force_carry(True)
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            first = ex.run_json(q)          # parks one carry per segment
            assert [k for s in segs for k in s._pool._entries
                    if "megacarry" in k]
            discards = []
            real_discard = megakernel.discard_carries

            def spy_discard(carries):
                discards.append(len(carries))
                return real_discard(carries)

            monkeypatch.setattr(megakernel, "discard_carries", spy_discard)
            # fresh program cache + a builder whose megakernel product
            # raises: the dispatch fails between the take and the re-park
            monkeypatch.setattr(grouping, "_JIT_CACHE",
                                collections.OrderedDict())
            real_build = grouping._build_device_fn

            def broken_build(spec, *a, **k):
                fn = real_build(spec, *a, **k)
                if spec.strategy != "megakernel":
                    return fn

                def boom(arrays, aux, carries=()):
                    raise RuntimeError("synthetic Mosaic failure")

                return boom

            monkeypatch.setattr(grouping, "_build_device_fn", broken_build)
            fallback = ex.run_json(q)       # fails mid-carry, falls back
        # XLA fallback stays correct (floats to tolerance: the windowed
        # path accumulates in a different block order than the kernel)
        assert len(fallback) == len(first)
        for got, want in zip(fallback, first):
            assert got["event"].keys() == want["event"].keys()
            for name, v in got["event"].items():
                if isinstance(v, float):
                    assert v == pytest.approx(want["event"][name],
                                              rel=1e-5)
                else:
                    assert v == want["event"][name]
        assert discards                     # popped grids were discharged
        pool = device_pool()
        with pool._lock:
            leftover = [k for k in pool._entries if "megacarry" in k]
            drift = pool._resident - sum(v[1]
                                         for v in pool._entries.values())
        assert not leftover                 # discarded, NOT re-parked
        assert drift == 0                   # books match held entries
    finally:
        megakernel.set_force_carry(prev_c)
        pallas_agg._BROKEN = None           # un-latch for later tests
        device_pool().clear()


def test_mega_pallas_packed_columns_parity(monkeypatch, mk_segments):
    """Packed value columns ride the fused kernel as words (the PR 9
    in-kernel unpack) — parity against decoded staging through the same
    fused path."""
    from druid_tpu.data import packed
    segs, q = _proj_setup(monkeypatch)
    ex = QueryExecutor(segs)
    prev = packed.set_enabled(True)
    try:
        device_pool().clear()
        with_packed = ex.run_json(q)
    finally:
        packed.set_enabled(prev)
    prev = packed.set_enabled(False)
    try:
        device_pool().clear()
        decoded = ex.run_json(q)
    finally:
        packed.set_enabled(prev)
    assert with_packed == decoded


# ---------------------------------------------------------------------------
# perm-keyed bitmap cache entries (projection layout)
# ---------------------------------------------------------------------------

def test_projection_bitmap_words_perm_keyed(monkeypatch):
    """The projection path stages PERMUTED bitmap words under its own
    permutation digest instead of re-planning onto the column path: the
    planned tree keeps its bitmap nodes, results stay exact, and the
    second run hits the perm-keyed entries."""
    monkeypatch.setenv("DRUID_TPU_PALLAS", "0")   # projection → windowed
    segs, q = _proj_setup(monkeypatch)
    prev = megakernel.set_enabled(False)  # the staged (resident-words) path
    try:
        ex = QueryExecutor(segs)
        device_pool().clear()
        got = ex.run_json(q)
        s0 = filters_mod.filter_bitmap_stats().snapshot()
        again = ex.run_json(q)
        s1 = filters_mod.filter_bitmap_stats().snapshot()
        assert again == got
        assert s1["hits"] > s0["hits"]           # perm-keyed entries hit
        assert s1["misses"] == s0["misses"]
        # parity against the un-projected mixed path
        monkeypatch.setattr(grouping, "PROJECTION_MIN_ROWS", 1 << 60)
        want = ex.run_json(q)
        assert {r["event"]["dimA"] + "|" + r["event"]["dimB"]:
                (r["event"]["rows"], r["event"]["lsum"]) for r in got} == \
               {r["event"]["dimA"] + "|" + r["event"]["dimB"]:
                (r["event"]["rows"], r["event"]["lsum"]) for r in want}
    finally:
        megakernel.set_enabled(prev)


# ---------------------------------------------------------------------------
# filtered aggregators plan bitmap words
# ---------------------------------------------------------------------------

def test_filtered_agg_plans_bitmap_words(mk_segments):
    seg = mk_segments[0]
    spec = FilteredAggregator(
        "fsum", delegate=LongSumAggregator("fsum", "metLong"),
        filter=F.SelectorFilter("dHi", seg.dims["dHi"].dictionary.values[2]))
    k = make_kernel(spec, seg)
    assert isinstance(k, FilteredKernel)
    assert collect_bitmap_nodes(k.filter_node), \
        "filtered aggregator's filter must compile to bitmap words"
    # the filter-only dim stops staging: the kernel's planned needs carry
    # no filter columns at all
    assert k.required_device_columns() == {"metLong"}


def test_filtered_agg_parity_fused_vs_column_path(mk_segments):
    ex = QueryExecutor(mk_segments)
    dHi_vals = mk_segments[0].dims["dHi"].dictionary.values
    aggs = [{"type": "count", "name": "n"},
            {"type": "filtered", "name": "fs",
             "aggregator": {"type": "longSum", "name": "fs",
                            "fieldName": "metLong"},
             "filter": {"type": "in", "dimension": "dHi",
                        "values": list(dHi_vals[:40])}}]
    q = _query(None, aggs=aggs)
    device_pool().clear()
    fused = ex.run_json(q)
    prev = filters_mod.set_device_bitmap_enabled(False)
    try:
        device_pool().clear()
        column = ex.run_json(q)          # the old decoded-column path
    finally:
        filters_mod.set_device_bitmap_enabled(prev)
    assert fused == column
    # oracle on the filtered sum
    want = 0
    for s in mk_segments:
        m = host_mask(F.InFilter("dHi", tuple(dHi_vals[:40])), s)
        want += int(s.metrics["metLong"].values[m].sum())
    assert fused[0]["result"]["fs"] == want


def test_filtered_agg_slots_do_not_collide_with_query_filter(mk_segments):
    """The query filter AND a filtered aggregator both carry bitmap
    subtrees: global slot assignment keeps their staged word arrays
    distinct, and results match the all-column path exactly."""
    ex = QueryExecutor(mk_segments)
    dLo_vals = mk_segments[0].dims["dLo"].dictionary.values
    dMid_vals = mk_segments[0].dims["dMid"].dictionary.values
    aggs = [{"type": "count", "name": "n"},
            {"type": "filtered", "name": "fs",
             "aggregator": {"type": "longSum", "name": "fs",
                            "fieldName": "metLong"},
             "filter": {"type": "selector", "dimension": "dMid",
                        "value": dMid_vals[3]}}]
    q = _query(F.NotFilter(F.SelectorFilter("dLo", dLo_vals[1])), aggs=aggs)
    device_pool().clear()
    fused = ex.run_json(q)
    prev_b = filters_mod.set_device_bitmap_enabled(False)
    prev_m = megakernel.set_enabled(False)
    try:
        device_pool().clear()
        column = ex.run_json(q)
    finally:
        filters_mod.set_device_bitmap_enabled(prev_b)
        megakernel.set_enabled(prev_m)
    assert fused == column


# ---------------------------------------------------------------------------
# unify_query_dims TTL sweep (carried-over ROADMAP rider)
# ---------------------------------------------------------------------------

def test_unidim_remap_ttl_sweeps_stale_slots():
    # few rows over a wide value range: the two segments' query-time
    # numeric dictionaries differ, so unify_query_dims really unions
    schema = (ColumnSpec("dimA", "string", cardinality=4),
              ColumnSpec("metLong", "long", low=0, high=100_000))
    segs = DataGenerator(schema, seed=3).segments(2, 64, IV,
                                                  datasource="un")
    from druid_tpu.query.model import DefaultDimensionSpec, GroupByQuery
    q = GroupByQuery.of("un", [IV], [DefaultDimensionSpec("metLong")],
                        [CountAggregator("n")], granularity="all")
    kds, vals = engines._keydims_for_query(q, segs)
    slots = [s._aux_cache[k] for s in segs
             for k in s._aux_cache if k[0] == "unidim"]
    assert slots and all(len(sl) == 1 for sl in slots)
    prev = engines.set_unidim_ttl(1e-9)
    try:
        import time as _time
        _time.sleep(0.01)
        # any subsequent unify pass sweeps stale slots, whoever owns them
        other = DataGenerator(schema, seed=9).segments(2, 64, IV,
                                                       datasource="un2")
        engines._keydims_for_query(q, other)
        assert all(len(sl) == 0 for sl in slots), "stale remaps must clear"
    finally:
        engines.set_unidim_ttl(prev)


# ---------------------------------------------------------------------------
# observability
# ---------------------------------------------------------------------------

def test_mega_and_dispatch_metrics_declared_and_emitting(mk_segments):
    from druid_tpu.obs import catalog
    from druid_tpu.obs.dispatch import DispatchMonitor

    class Rec:
        def __init__(self):
            self.seen = {}

        def metric(self, name, value, **dims):
            self.seen[name] = value

    ex = QueryExecutor([mk_segments[0]])
    mega_mon = megakernel.MegakernelMonitor()
    disp_mon = DispatchMonitor()
    device_pool().clear()
    ex.run_json(_query(F.SelectorFilter(
        "dLo", mk_segments[0].dims["dLo"].dictionary.values[4])))
    rec = Rec()
    mega_mon.do_monitor(rec)
    disp_mon.do_monitor(rec)
    assert not catalog.validate_emitted(rec.seen)
    assert set(rec.seen) == {"query/megakernel/hits",
                             "query/megakernel/fallbacks",
                             "query/megakernel/donatedBytes",
                             "query/dispatch/count"}
    assert rec.seen["query/dispatch/count"] >= 1
    assert rec.seen["query/megakernel/hits"] >= 1


def test_disabled_megakernel_records_fallbacks(mk_segments):
    seg = mk_segments[0]
    ex = QueryExecutor([seg])
    q = _query(F.SelectorFilter("dLo",
                                seg.dims["dLo"].dictionary.values[5]))
    prev = megakernel.set_enabled(False)
    try:
        s0 = megakernel.stats().snapshot()
        device_pool().clear()
        ex.run_json(q)
        s1 = megakernel.stats().snapshot()
    finally:
        megakernel.set_enabled(prev)
    assert s1["fallbacks"] > s0["fallbacks"]
    assert s1["hits"] == s0["hits"]
