"""leakguard unit battery: each resource-lifecycle rule must fire on its
positive shape, stay quiet on the released/escaped/suppressed shapes, and
the dynamic leak witness must detect (and clear) a real runtime leak.

Pattern mirrors tests/test_raceguard.py: check_source with a root-less
config analyzes each snippet standalone through the real rule registry, so
suppression/baseline behavior is exactly the shipped one.
"""
import os
import sys
import threading
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from tools.druidlint.core import LintConfig, check_source  # noqa: E402


def cfg(*rules) -> LintConfig:
    c = LintConfig(rules=list(rules) if rules else [])
    c.root = "/nonexistent-leakguard-root"
    return c


def findings_of(source: str, rule: str, path: str = "druid_tpu/mod.py"):
    return [f for f in check_source(source, path, cfg(rule))
            if f.rule == rule]


# ---------------------------------------------------------------------------
# unjoined-thread
# ---------------------------------------------------------------------------

def test_started_thread_never_joined_fires():
    src = """\
import threading

class Pump:
    def __init__(self):
        self._t = threading.Thread(target=self._run)
        self._t.start()

    def _run(self):
        pass

    def stop(self):
        pass
"""
    got = findings_of(src, "unjoined-thread")
    assert len(got) == 1
    assert "never joined" in got[0].message


def test_thread_joined_with_timeout_on_stop_is_quiet():
    src = """\
import threading

class Pump:
    def __init__(self):
        self._t = threading.Thread(target=self._run)
        self._t.start()

    def _run(self):
        pass

    def stop(self):
        self._t.join(timeout=5.0)
"""
    assert findings_of(src, "unjoined-thread") == []


def test_join_off_the_shutdown_surface_fires():
    src = """\
import threading

class Pump:
    def __init__(self):
        self._t = threading.Thread(target=self._run)
        self._t.start()

    def _run(self):
        pass

    def wait(self):
        self._t.join(timeout=5.0)

    def stop(self):
        pass
"""
    got = findings_of(src, "unjoined-thread")
    assert len(got) == 1
    assert "not on any shutdown path" in got[0].message


def test_join_without_timeout_on_stop_fires():
    src = """\
import threading

class Pump:
    def __init__(self):
        self._t = threading.Thread(target=self._run)
        self._t.start()

    def _run(self):
        pass

    def stop(self):
        self._t.join()
"""
    got = findings_of(src, "unjoined-thread")
    assert len(got) == 1
    assert "without a timeout" in got[0].message


def test_unstarted_thread_is_quiet():
    """A constructed-but-never-started Thread pins no OS resource."""
    src = """\
import threading

class Lazy:
    def __init__(self):
        self._t = threading.Thread(target=self._run)

    def _run(self):
        pass

    def stop(self):
        pass
"""
    assert findings_of(src, "unjoined-thread") == []


def test_container_threads_joined_via_snapshot_idiom_quiet():
    """`ts = list(self._threads.values())` under the lock, join outside —
    the exact shape the lock-scope rule forces — must count as a join."""
    src = """\
import threading

class Runner:
    def __init__(self):
        self._lock = threading.Lock()
        self._threads = {}

    def launch(self, key):
        t = threading.Thread(target=self._run)
        self._threads[key] = t
        t.start()

    def _run(self):
        pass

    def stop(self):
        with self._lock:
            ts = list(self._threads.values())
        for t in ts:
            t.join(timeout=5.0)
"""
    assert findings_of(src, "unjoined-thread") == []


def test_container_threads_never_joined_fires():
    src = """\
import threading

class Runner:
    def __init__(self):
        self._threads = {}

    def launch(self, key):
        t = threading.Thread(target=self._run)
        self._threads[key] = t
        t.start()

    def _run(self):
        pass

    def stop(self):
        self._threads.clear()
"""
    got = findings_of(src, "unjoined-thread")
    assert len(got) == 1


def test_unjoined_thread_suppression():
    src = """\
import threading

class Pump:
    def __init__(self):
        self._t = threading.Thread(target=self._run)  # druidlint: disable=unjoined-thread  # daemon heartbeat, dies with process
        self._t.start()

    def _run(self):
        pass

    def stop(self):
        pass
"""
    assert findings_of(src, "unjoined-thread") == []


# ---------------------------------------------------------------------------
# unreleased-resource
# ---------------------------------------------------------------------------

def test_executor_without_shutdown_fires():
    src = """\
from concurrent.futures import ThreadPoolExecutor

class Fan:
    def __init__(self):
        self._pool = ThreadPoolExecutor(4)

    def stop(self):
        pass
"""
    got = findings_of(src, "unreleased-resource")
    assert len(got) == 1
    assert "no release" in got[0].message


def test_executor_shutdown_on_stop_is_quiet():
    src = """\
from concurrent.futures import ThreadPoolExecutor

class Fan:
    def __init__(self):
        self._pool = ThreadPoolExecutor(4)

    def stop(self):
        self._pool.shutdown(wait=True)
"""
    assert findings_of(src, "unreleased-resource") == []


def test_release_reachable_through_helper_is_quiet():
    """stop() -> self._teardown() -> close(): the release is reachable
    through the self-call closure, not just textually in stop()."""
    src = """\
class Holder:
    def __init__(self, path):
        self._fh = open(path)

    def _teardown(self):
        self._fh.close()

    def stop(self):
        self._teardown()
"""
    assert findings_of(src, "unreleased-resource") == []


def test_release_off_the_shutdown_surface_fires():
    src = """\
class Holder:
    def __init__(self, path):
        self._fh = open(path)

    def rotate(self, path):
        self._fh.close()
        self._fh = open(path)

    def stop(self):
        pass
"""
    got = findings_of(src, "unreleased-resource")
    assert got, "release only in rotate() must not satisfy stop()"
    assert "outside the shutdown surface" in got[0].message


def test_escaped_attribute_transfers_ownership():
    """Passing self._pool to a registrar hands off the stop obligation."""
    src = """\
from concurrent.futures import ThreadPoolExecutor

class Fan:
    def __init__(self, lifecycle):
        self._pool = ThreadPoolExecutor(4)
        lifecycle.register(self._pool)

    def stop(self):
        pass
"""
    assert findings_of(src, "unreleased-resource") == []


def test_points_to_keeps_obligation_when_callee_cannot_close():
    """The PR 14 rider: bare `self.X` as an argument transfers ownership
    ONLY when the callee can actually close it. A resolvable program
    function that merely READS the handle (no release call, no store, no
    return, no re-escape) does not take the obligation — the missing
    release is still flagged."""
    src = """\
from concurrent.futures import ThreadPoolExecutor

def describe(pool):
    return f"pool with {pool._max_workers} workers"

class Fan:
    def __init__(self):
        self._pool = ThreadPoolExecutor(4)
        self.label = describe(self._pool)

    def stop(self):
        pass
"""
    got = findings_of(src, "unreleased-resource")
    assert got, "an inert read-only callee must not transfer ownership"


def test_points_to_transfer_when_callee_really_closes():
    """A program callee that releases (or stores) its parameter IS an
    ownership transfer — exactly the registrar shape that must stay
    quiet, now proven instead of assumed."""
    src = """\
from concurrent.futures import ThreadPoolExecutor

def drain_and_close(pool):
    pool.shutdown()

class Fan:
    def __init__(self):
        self._pool = ThreadPoolExecutor(4)
        drain_and_close(self._pool)

    def stop(self):
        pass
"""
    assert findings_of(src, "unreleased-resource") == []


def test_points_to_bound_method_reference_transfers():
    """A callee that stashes a RELEASE bound method (`c.shutdown` as a
    value) or captures the parameter in a closure can close it later —
    both must count as ownership transfer (stay quiet)."""
    src = """\
from concurrent.futures import ThreadPoolExecutor

_SINKS = {}

def defer_close(pool):
    _SINKS["x"] = pool.shutdown

class Fan:
    def __init__(self):
        self._pool = ThreadPoolExecutor(4)
        defer_close(self._pool)

    def stop(self):
        pass
"""
    assert findings_of(src, "unreleased-resource") == []
    src2 = """\
from concurrent.futures import ThreadPoolExecutor

_CBS = []

def defer(pool):
    _CBS.append(lambda: pool.shutdown())

class Fan:
    def __init__(self):
        self._pool = ThreadPoolExecutor(4)
        defer(self._pool)

    def stop(self):
        pass
"""
    assert findings_of(src2, "unreleased-resource") == []


def test_points_to_global_store_and_tuple_return_transfer():
    """A callee storing the parameter into a declared global, or
    returning it inside a tuple, hands ownership onward — both quiet."""
    src = """\
from concurrent.futures import ThreadPoolExecutor

_POOL = None

def install(pool):
    global _POOL
    _POOL = pool

class Fan:
    def __init__(self):
        self._pool = ThreadPoolExecutor(4)
        install(self._pool)

    def stop(self):
        pass
"""
    assert findings_of(src, "unreleased-resource") == []
    src2 = """\
from concurrent.futures import ThreadPoolExecutor

def wrap(pool):
    return (pool, "label")

class Fan:
    def __init__(self):
        self._pool = ThreadPoolExecutor(4)
        self.handle = wrap(self._pool)

    def stop(self):
        pass
"""
    assert findings_of(src2, "unreleased-resource") == []


def test_points_to_transitive_escape_stays_conservative():
    """The callee hands the parameter onward to something unresolvable:
    the pass must stay conservative (transfer assumed, no finding)."""
    src = """\
import json
from concurrent.futures import ThreadPoolExecutor

def register(pool, registry):
    registry.add(pool)

class Fan:
    def __init__(self, registry):
        self._pool = ThreadPoolExecutor(4)
        register(self._pool, registry)

    def stop(self):
        pass
"""
    assert findings_of(src, "unreleased-resource") == []


def test_held_threaded_service_needs_stop():
    """A class whose ctor starts a thread is itself a resource: holding
    one without stopping it strands the worker."""
    src = """\
import threading

class Emitter:
    def __init__(self):
        self._t = threading.Thread(target=self._loop)
        self._t.start()

    def _loop(self):
        pass

    def close(self):
        self._t.join(timeout=5.0)

class Server:
    def __init__(self):
        self.emitter = Emitter()

    def stop(self):
        pass
"""
    got = findings_of(src, "unreleased-resource")
    assert len(got) == 1
    assert "Server.emitter" in got[0].message


def test_held_service_stopped_is_quiet():
    src = """\
import threading

class Emitter:
    def __init__(self):
        self._t = threading.Thread(target=self._loop)
        self._t.start()

    def _loop(self):
        pass

    def close(self):
        self._t.join(timeout=5.0)

class Server:
    def __init__(self):
        self.emitter = Emitter()

    def stop(self):
        self.emitter.close()
"""
    assert findings_of(src, "unreleased-resource") == []


def test_startable_service_only_owed_when_started():
    """A held start()/stop() object the owner never start()s is inert —
    constructing one in a test owes nothing."""
    quiet = """\
class Sched:
    def start(self):
        pass

    def stop(self):
        pass

class Owner:
    def __init__(self):
        self.sched = Sched()
"""
    assert findings_of(quiet, "unreleased-resource") == []
    noisy = quiet + """\

class Starter:
    def __init__(self):
        self.sched = Sched()
        self.sched.start()

    def stop(self):
        pass
"""
    got = findings_of(noisy, "unreleased-resource")
    assert len(got) == 1
    assert "Starter.sched" in got[0].message


# ---------------------------------------------------------------------------
# leak-on-error-path
# ---------------------------------------------------------------------------

def test_acquire_then_raising_call_fires():
    src = """\
import json

def load(path, meta):
    fh = open(path)
    parsed = json.loads(meta)
    return fh, parsed
"""
    got = findings_of(src, "leak-on-error-path")
    assert len(got) == 1
    assert "`fh`" in got[0].message


def test_context_manager_is_quiet():
    src = """\
import json

def load(path, meta):
    with open(path) as fh:
        parsed = json.loads(meta)
        return fh.read(), parsed
"""
    assert findings_of(src, "leak-on-error-path") == []


def test_try_finally_is_quiet():
    src = """\
import json

def load(path, meta):
    fh = open(path)
    try:
        parsed = json.loads(meta)
        return fh.read(), parsed
    finally:
        fh.close()
"""
    assert findings_of(src, "leak-on-error-path") == []


def test_immediate_ownership_transfer_is_quiet():
    """`self._fh = fh` right after the open: the owner's release rules
    take over; later raise-capable calls are not THIS function's leak."""
    src = """\
import json

class Holder:
    def __init__(self, path, meta):
        fh = open(path)
        self._fh = fh
        self.meta = json.loads(meta)

    def close(self):
        self._fh.close()
"""
    assert findings_of(src, "leak-on-error-path") == []


def test_methods_on_the_resource_itself_are_quiet():
    """fh.write() raising still leaks fh, but flagging the universal
    open-write-close shape would be noise — only FOREIGN calls count."""
    src = """\
def dump(path, payload):
    fh = open(path, "w")
    fh.write(payload)
    return fh
"""
    assert findings_of(src, "leak-on-error-path") == []


# ---------------------------------------------------------------------------
# finalizer-unsafe
# ---------------------------------------------------------------------------

def test_finalizer_taking_lock_fires():
    src = """\
import threading
import weakref

class Pool:
    def __init__(self):
        self._lock = threading.Lock()

    def _purge(self):
        with self._lock:
            pass

    def track(self, obj):
        weakref.finalize(obj, self._purge)
"""
    got = findings_of(src, "finalizer-unsafe")
    assert len(got) == 1
    assert "self-deadlock" in got[0].message


def test_finalizer_lock_via_transitive_call_fires():
    src = """\
import threading
import weakref

class Pool:
    def __init__(self):
        self._lock = threading.Lock()

    def _evict(self):
        with self._lock:
            pass

    def _purge(self):
        self._evict()

    def track(self, obj):
        weakref.finalize(obj, self._purge)
"""
    assert len(findings_of(src, "finalizer-unsafe")) == 1


def test_del_taking_lock_fires():
    src = """\
import threading

class Handle:
    def __init__(self):
        self._lock = threading.Lock()

    def __del__(self):
        with self._lock:
            pass
"""
    got = findings_of(src, "finalizer-unsafe")
    assert len(got) == 1
    assert "__del__" in got[0].message


def test_lock_free_finalizer_is_quiet():
    """The devicepool idiom: finalizers only append to an atomic deque."""
    src = """\
import collections
import weakref

class Pool:
    def __init__(self):
        self._dead = collections.deque()

    def _note_dead(self, token):
        self._dead.append(token)

    def track(self, obj, token):
        weakref.finalize(obj, self._note_dead, token)
"""
    assert findings_of(src, "finalizer-unsafe") == []


# ---------------------------------------------------------------------------
# stop-start-pairing
# ---------------------------------------------------------------------------

def test_unrestored_foreign_wiring_fires():
    src = """\
class Lifecycle:
    def __init__(self):
        self.on_result = None

class Chainer:
    def __init__(self, life: Lifecycle):
        self.life = life

    def start(self):
        self.life.on_result = self._cb

    def _cb(self):
        pass

    def stop(self):
        pass
"""
    got = findings_of(src, "stop-start-pairing")
    assert len(got) == 1
    assert "Lifecycle.on_result" in got[0].message


def test_restored_wiring_is_quiet():
    src = """\
class Lifecycle:
    def __init__(self):
        self.on_result = None

class Chainer:
    def __init__(self, life: Lifecycle):
        self.life = life
        self._prev = None

    def start(self):
        self._prev = self.life.on_result
        self.life.on_result = self._cb

    def _cb(self):
        pass

    def stop(self):
        self.life.on_result = self._prev
"""
    assert findings_of(src, "stop-start-pairing") == []


def test_restore_closure_at_wiring_site_is_quiet():
    """The compose_sink idiom: the undo lives in a nested closure created
    by the wiring function itself."""
    src = """\
class Emitter:
    def __init__(self):
        self.sink = None

class Composer:
    def __init__(self, emitter: Emitter):
        self.emitter = emitter
        self._restore = None

    def start(self):
        emitter = self.emitter
        prev = emitter.sink

        def restore():
            emitter.sink = prev

        emitter.sink = self._sink
        self._restore = restore

    def _sink(self):
        pass

    def stop(self):
        self._restore()
"""
    assert findings_of(src, "stop-start-pairing") == []


def test_own_state_and_owned_objects_are_not_wiring():
    """Writes to self.* and to objects this class itself constructs die
    with the class — no pairing obligation."""
    src = """\
class Worker:
    def __init__(self):
        self.running = False

class Owner:
    def __init__(self):
        self.worker = Worker()
        self.running = False

    def start(self):
        self.running = True
        self.worker.running = True

    def stop(self):
        pass
"""
    assert findings_of(src, "stop-start-pairing") == []


# ---------------------------------------------------------------------------
# leak witness (dynamic)
# ---------------------------------------------------------------------------

def _witness_for(tmp_path):
    from tools.druidlint.leakwitness import LeakWitness
    pkg = tmp_path / "druid_tpu"
    pkg.mkdir(exist_ok=True)
    src_path = pkg / "leaky.py"
    src_path.write_text("""\
import threading


def start_worker(event):
    t = threading.Thread(target=event.wait, daemon=True)
    t.start()
    return t
""")
    ns = {}
    code = compile(src_path.read_text(), str(src_path), "exec")
    exec(code, ns)
    return LeakWitness(str(tmp_path)), ns["start_worker"]


def test_witness_attributes_and_clears_thread_leak(tmp_path):
    witness, start_worker = _witness_for(tmp_path)
    release = threading.Event()
    with witness:
        base = witness.snapshot()
        t = start_worker(release)
        try:
            leaks = witness.leaks(base, grace_s=0.2)
            assert any("druid_tpu/leaky.py" in l and "thread leak" in l
                       for l in leaks), leaks
            release.set()
            t.join(timeout=5.0)
            assert witness.leaks(base, grace_s=5.0) == []
        finally:
            release.set()


def test_witness_ignores_foreign_threads(tmp_path):
    """Threads started with no project frame on the stack (pytest, jax)
    are never attributed."""
    witness, _ = _witness_for(tmp_path)
    release = threading.Event()
    with witness:
        base = witness.snapshot()
        t = threading.Thread(target=release.wait, daemon=True)
        t.start()
        try:
            assert witness.leaks(base, grace_s=0.2) == []
        finally:
            release.set()
            t.join(timeout=5.0)


def test_witness_detects_fd_leak(tmp_path):
    witness, _ = _witness_for(tmp_path)
    with witness:
        base = witness.snapshot()
        if not base.fds:
            return                   # platform without /proc/self/fd
        fh = open(tmp_path / "leaked.txt", "w")
        try:
            leaks = witness.leaks(base, grace_s=0.2)
            assert any("fd leak" in l and "leaked.txt" in l
                       for l in leaks), leaks
        finally:
            fh.close()
        assert witness.leaks(base, grace_s=2.0) == []


def test_witness_fd_axis_counts_targets_not_fd_numbers(tmp_path):
    """The fd axis is a multiset of readlink targets: re-opening a
    baseline file on a DIFFERENT fd number is not growth (log-rotation
    shape), while a second concurrent open of the same target is a leak
    even though the baseline fd number may have been reused."""
    witness, _ = _witness_for(tmp_path)
    path = tmp_path / "rotated.log"
    with witness:
        held = open(path, "w")
        try:
            base = witness.snapshot()
            if not base.fds:
                return               # platform without /proc/self/fd
            # close + re-open: lands on some fd (often the same number,
            # sometimes not) — either way the target count is unchanged
            held.close()
            held = open(path, "w")
            assert witness.leaks(base, grace_s=0.2) == []
            # a SECOND open of the same target is real growth
            extra = open(path, "r")
            try:
                leaks = witness.leaks(base, grace_s=0.2)
                assert any("fd leak" in l and "rotated.log" in l
                           for l in leaks), leaks
            finally:
                extra.close()
            assert witness.leaks(base, grace_s=2.0) == []
        finally:
            held.close()


def test_witness_fd_axis_degrades_without_procfs(tmp_path, monkeypatch,
                                                 caplog):
    """Non-procfs platforms: the fd axis is SKIPPED with a one-line note
    — the thread and pool axes stay active, nothing errors."""
    import logging
    import tools.druidlint.leakwitness as lw
    witness, start_worker = _witness_for(tmp_path)
    real_listdir = os.listdir

    def no_procfs(path, *a, **k):
        if str(path).startswith("/proc/self/fd"):
            raise FileNotFoundError(path)
        return real_listdir(path, *a, **k)

    monkeypatch.setattr(lw.os, "listdir", no_procfs)
    monkeypatch.setitem(lw._FD_AXIS_NOTE, "emitted", False)
    release = threading.Event()
    with witness:
        with caplog.at_level(logging.INFO,
                             logger="tools.druidlint.leakwitness"):
            base = witness.snapshot()
        assert base.fd_axis is False and base.fds == ()
        assert any("fd axis" in r.message for r in caplog.records)
        t = start_worker(release)
        try:
            leaks = witness.leaks(base, grace_s=0.2)
            # thread axis still fires; the degraded fd axis never does
            assert any("thread leak" in l for l in leaks), leaks
            assert not any("fd leak" in l for l in leaks)
        finally:
            release.set()
            t.join(timeout=5.0)


def test_witness_fd_axis_skips_when_procfs_vanishes_mid_run(tmp_path,
                                                            monkeypatch):
    """A baseline WITH an fd table compared after procfs becomes
    unavailable must skip the axis (no phantom findings, no error) —
    comparing real-vs-degraded tables would only manufacture noise."""
    import tools.druidlint.leakwitness as lw
    witness, _ = _witness_for(tmp_path)
    with witness:
        base = witness.snapshot()
        if not base.fd_axis:
            return                   # platform without /proc/self/fd
        real_listdir = os.listdir

        def no_procfs(path, *a, **k):
            if str(path).startswith("/proc/self/fd"):
                raise FileNotFoundError(path)
            return real_listdir(path, *a, **k)

        monkeypatch.setattr(lw.os, "listdir", no_procfs)
        held = open(tmp_path / "would-be-leak.txt", "w")
        try:
            assert witness.leaks(base, grace_s=0.2) == []
        finally:
            held.close()


def test_witness_detects_pool_growth(tmp_path, monkeypatch):
    from druid_tpu.data import devicepool

    class FakeBlock:
        nbytes = 4096

    pool = devicepool.DeviceSegmentPool(budget_bytes=1 << 20)
    monkeypatch.setattr(devicepool, "_POOL", pool)
    witness, _ = _witness_for(tmp_path)

    class Owner:
        pass

    owner_obj = Owner()
    with witness:
        base = witness.snapshot()
        token = pool.register_owner(owner_obj)
        pool.get_or_build(token, ("blk",), FakeBlock)
        leaks = witness.leaks(base, grace_s=0.2)
        assert any("device pool leak" in l for l in leaks), leaks
        pool.purge_owner(token)
        assert witness.leaks(base, grace_s=2.0) == []


@pytest.mark.skipif(
    os.environ.get("DRUID_TPU_LEAK_WITNESS") == "1",
    reason="the session-wide witness owns the singleton slot")
def test_witness_session_singleton():
    from tools.druidlint import leakwitness
    try:
        w1 = leakwitness.session_witness(str(Path(__file__).parent.parent))
        w2 = leakwitness.session_witness(str(Path(__file__).parent.parent))
        assert w1 is w2
        assert w1.baseline is not None
    finally:
        leakwitness.end_session_witness()
    assert leakwitness.session_witness() is None
