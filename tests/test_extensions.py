"""Extension tests: theta/quantiles sketches, histogram, variance, bloom
(reference: extensions-core datasketches/histogram/stats/bloom test suites)."""
import numpy as np
import pytest

import druid_tpu.ext  # noqa: F401  (registers everything)
from druid_tpu.engine import QueryExecutor
from druid_tpu.ext import (ApproximateHistogramAggregator,
                           BloomFilterAggregator, BloomFilterValue,
                           BloomDimFilter, HistogramQuantilePostAgg,
                           QuantilePostAgg, QuantilesSketchAggregator,
                           ThetaSketchAggregator, ThetaSketchEstimatePostAgg,
                           ThetaSketchSetOpPostAgg, ThetaSketchValue,
                           VarianceAggregator, StandardDeviationPostAgg)
from druid_tpu.query.aggregators import agg_from_json
from druid_tpu.query.filters import filter_from_json
from druid_tpu.query.model import (DefaultDimensionSpec, GroupByQuery,
                                   TimeseriesQuery, query_from_json)
from druid_tpu.query.postaggs import FieldAccessPostAgg, postagg_from_json
from tests.conftest import DAY, rows_as_frame


@pytest.fixture(scope="module")
def ex(segment):
    return QueryExecutor([segment])


def test_variance_and_stddev(ex, segment):
    frame = rows_as_frame(segment)
    q = TimeseriesQuery.of(
        "test", [DAY],
        [VarianceAggregator("var", "metFloat"),
         VarianceAggregator("vars", "metFloat", "sample")],
        post_aggregations=[StandardDeviationPostAgg("sd", "var")])
    r = ex.run(q)[0]["result"]
    x = frame["metFloat"].astype(np.float64)
    assert r["var"] == pytest.approx(x.var(), rel=1e-6)
    assert r["vars"] == pytest.approx(x.var(ddof=1), rel=1e-6)
    assert r["sd"] == pytest.approx(x.std(), rel=1e-6)


def test_variance_grouped(ex, segment):
    frame = rows_as_frame(segment)
    q = GroupByQuery.of("test", [DAY], [DefaultDimensionSpec("dimA")],
                        [VarianceAggregator("var", "metLong")])
    rows = ex.run(q)
    for r in rows:
        sel = frame["dimA"] == r["event"]["dimA"]
        want = frame["metLong"][sel].astype(np.float64).var()
        assert r["event"]["var"] == pytest.approx(want, rel=1e-6)


def test_theta_fractional_doubles_distinct():
    """Distinct fractional values must count distinctly (bit-pattern hash,
    not integer truncation)."""
    from druid_tpu.data.generator import ColumnSpec, DataGenerator
    from druid_tpu.utils.intervals import Interval
    iv = Interval.of("2026-01-01", "2026-01-02")
    gen = DataGenerator((ColumnSpec("m", "double", low=0.0, high=1.0),),
                        seed=1)
    seg = gen.segment(20_000, iv, datasource="frac")
    exact = len(set(seg.metrics["m"].values.tolist()))
    q = TimeseriesQuery.of("frac", [iv], [ThetaSketchAggregator("u", "m")])
    r = QueryExecutor([seg]).run(q)[0]["result"]
    assert r["u"] == pytest.approx(exact, rel=0.06)
    # HLL kernel shares the fix
    from druid_tpu.query.aggregators import CardinalityAggregator
    q2 = TimeseriesQuery.of("frac", [iv],
                            [CardinalityAggregator("u", ("m",), by_row=True)])
    r2 = QueryExecutor([seg]).run(q2)[0]["result"]
    assert r2["u"] == pytest.approx(exact, rel=0.08)


def test_theta_estimate(ex, segment):
    frame = rows_as_frame(segment)
    q = TimeseriesQuery.of(
        "test", [DAY], [ThetaSketchAggregator("u", "dimHi")])
    r = ex.run(q)[0]["result"]
    exact = len(set(frame["dimHi"]))
    assert r["u"] == pytest.approx(exact, rel=0.06)


def test_theta_set_ops(ex, segment):
    frame = rows_as_frame(segment)
    from druid_tpu.query.filters import BoundFilter
    from druid_tpu.query.aggregators import FilteredAggregator
    lo = FilteredAggregator(
        "lo", ThetaSketchAggregator("lo", "dimHi", should_finalize=False),
        BoundFilter("metLong", upper="60", ordering="numeric"))
    hi = FilteredAggregator(
        "hi", ThetaSketchAggregator("hi", "dimHi", should_finalize=False),
        BoundFilter("metLong", lower="40", ordering="numeric"))
    q = TimeseriesQuery.of(
        "test", [DAY], [lo, hi],
        post_aggregations=[
            ThetaSketchSetOpPostAgg("u", "UNION",
                                    (FieldAccessPostAgg("lo", "lo"),
                                     FieldAccessPostAgg("hi", "hi"))),
            ThetaSketchSetOpPostAgg("i", "INTERSECT",
                                    (FieldAccessPostAgg("lo", "lo"),
                                     FieldAccessPostAgg("hi", "hi")))])
    r = ex.run(q)[0]["result"]
    m = frame["metLong"]
    a = set(frame["dimHi"][m <= 60])
    b = set(frame["dimHi"][m >= 40])
    assert r["u"] == pytest.approx(len(a | b), rel=0.08)
    assert r["i"] == pytest.approx(len(a & b), rel=0.15)


def test_quantiles_sketch(ex, segment):
    frame = rows_as_frame(segment)
    q = TimeseriesQuery.of(
        "test", [DAY], [QuantilesSketchAggregator("qs", "metFloat")],
        post_aggregations=[
            QuantilePostAgg("p50", FieldAccessPostAgg("qs", "qs"), 0.5),
            QuantilePostAgg("p95", FieldAccessPostAgg("qs", "qs"), 0.95)])
    r = ex.run(q)[0]["result"]
    x = np.sort(frame["metFloat"].astype(np.float64))
    assert r["p50"] == pytest.approx(np.quantile(x, 0.5), rel=0.05)
    assert r["p95"] == pytest.approx(np.quantile(x, 0.95), rel=0.05)


def test_quantiles_negative_values():
    from druid_tpu.data.generator import ColumnSpec, DataGenerator
    from druid_tpu.utils.intervals import Interval
    iv = Interval.of("2026-01-01", "2026-01-02")
    gen = DataGenerator((ColumnSpec("m", "double", distribution="normal",
                                    mean=0.0, std=100.0),), seed=3)
    seg = gen.segment(50_000, iv, datasource="neg")
    q = TimeseriesQuery.of(
        "neg", [iv], [QuantilesSketchAggregator("qs", "m")],
        post_aggregations=[
            QuantilePostAgg("p10", FieldAccessPostAgg("qs", "qs"), 0.10),
            QuantilePostAgg("p90", FieldAccessPostAgg("qs", "qs"), 0.90)])
    r = QueryExecutor([seg]).run(q)[0]["result"]
    x = seg.metrics["m"].values.astype(np.float64)
    assert r["p10"] == pytest.approx(np.quantile(x, 0.10), rel=0.06)
    assert r["p90"] == pytest.approx(np.quantile(x, 0.90), rel=0.06)


def test_histogram(ex, segment):
    frame = rows_as_frame(segment)
    q = TimeseriesQuery.of(
        "test", [DAY],
        [ApproximateHistogramAggregator("h", "metLong", 50, 0.0, 101.0)],
        post_aggregations=[
            HistogramQuantilePostAgg("med", FieldAccessPostAgg("h", "h"),
                                     0.5)])
    r = ex.run(q)[0]["result"]
    x = frame["metLong"].astype(np.float64)
    assert r["h"].count == len(x)
    assert r["h"].min == x.min() and r["h"].max == x.max()
    assert r["med"] == pytest.approx(np.quantile(x, 0.5), abs=3.0)
    j = r["h"].to_json()
    assert sum(j["counts"]) == len(x) and len(j["breaks"]) == 51


def test_bloom_aggregator_and_filter(ex, segment):
    frame = rows_as_frame(segment)
    q = TimeseriesQuery.of(
        "test", [DAY], [BloomFilterAggregator("b", "dimA")])
    blm = ex.run(q)[0]["result"]["b"]
    for v in set(frame["dimA"]):
        assert blm.test(v)
    misses = sum(blm.test(f"nope{i}") for i in range(1000))
    assert misses < 30                      # ~1% target fpp
    # serde round trip + filter usage
    b64 = blm.serialize()
    restored = BloomFilterValue.deserialize(b64, blm.m_bits)
    assert np.array_equal(restored.bits, blm.bits)
    some = sorted(set(frame["dimA"]))[:3]
    partial = TimeseriesQuery.of(
        "test", [DAY], [BloomFilterAggregator("b", "dimA")],
        filter=filter_from_json({"type": "in", "dimension": "dimA",
                                 "values": some}))
    blm2 = ex.run(partial)[0]["result"]["b"]
    flt = BloomDimFilter("dimA", blm2.serialize(), blm2.m_bits)
    from druid_tpu.query.aggregators import CountAggregator
    n = ex.run(TimeseriesQuery.of("test", [DAY], [CountAggregator("n")],
                                  filter=flt))[0]["result"]["n"]
    want = int(np.isin(frame["dimA"], some).sum())
    assert n == want


def test_extension_json_serde(segment):
    for j in [
        {"type": "variance", "name": "v", "fieldName": "m"},
        {"type": "thetaSketch", "name": "t", "fieldName": "d"},
        {"type": "quantilesDoublesSketch", "name": "q", "fieldName": "m"},
        {"type": "approxHistogram", "name": "h", "fieldName": "m",
         "numBuckets": 10, "lowerLimit": 0.0, "upperLimit": 1.0},
        {"type": "bloom", "name": "b", "fieldName": "d"},
    ]:
        spec = agg_from_json(j)
        j2 = spec.to_json()
        assert agg_from_json(j2).to_json() == j2
    pa = postagg_from_json({
        "type": "quantilesDoublesSketchToQuantile", "name": "p",
        "field": {"type": "fieldAccess", "fieldName": "q"}, "fraction": 0.9})
    assert pa.to_json()["fraction"] == 0.9
    # full query through JSON wire with extension aggs
    q = query_from_json({
        "queryType": "timeseries", "dataSource": "test",
        "intervals": [str(DAY)], "granularity": "all",
        "aggregations": [{"type": "variance", "name": "v",
                          "fieldName": "metFloat"}]})
    r = QueryExecutor([segment]).run(q)
    assert r[0]["result"]["v"] > 0


def test_extension_sql(segment):
    from druid_tpu.sql import SqlExecutor
    frame = rows_as_frame(segment)
    sq = SqlExecutor(QueryExecutor([segment]))
    _, rows = sq.execute(
        "SELECT STDDEV(metFloat) sd, STDDEV_POP(metFloat) sdp, "
        "VARIANCE(metFloat) v, APPROX_QUANTILE(metFloat, 0.5) med, "
        "APPROX_QUANTILE(metFloat, 0.9) p90, DS_THETA(dimHi) u FROM test")
    x = frame["metFloat"].astype(np.float64)
    sd, sdp, v, med, p90, u = rows[0]
    # SQL STDDEV/VARIANCE are the SAMPLE estimators (Druid parity)
    assert sd == pytest.approx(x.std(ddof=1), rel=1e-6)
    assert sdp == pytest.approx(x.std(), rel=1e-6)
    assert v == pytest.approx(x.var(ddof=1), rel=1e-6)
    assert med == pytest.approx(np.quantile(x, 0.5), rel=0.05)
    assert p90 == pytest.approx(np.quantile(x, 0.9), rel=0.05)
    assert u == pytest.approx(len(set(frame["dimHi"])), rel=0.06)
    # the two quantiles share ONE sketch aggregator
    plan = sq.explain("SELECT APPROX_QUANTILE(metFloat, 0.5), "
                      "APPROX_QUANTILE(metFloat, 0.9) FROM test")
    assert len(plan["aggregations"]) == 1


def test_extension_sharded_merge(segments):
    """Extension states must merge across segments (and the broker path)."""
    from druid_tpu.cluster import Broker, DataNode, InventoryView, descriptor_for
    from druid_tpu.utils.intervals import Interval
    week = Interval.of("2026-01-01", "2026-01-08")
    frames = [rows_as_frame(s) for s in segments]
    allf = np.concatenate([f["metFloat"] for f in frames]).astype(np.float64)
    q = TimeseriesQuery.of(
        "test", [week],
        [VarianceAggregator("v", "metFloat"),
         QuantilesSketchAggregator("qs", "metFloat"),
         ThetaSketchAggregator("u", "dimHi")],
        post_aggregations=[
            QuantilePostAgg("p50", FieldAccessPostAgg("qs", "qs"), 0.5)])
    local = QueryExecutor(segments).run(q)[0]["result"]
    assert local["v"] == pytest.approx(allf.var(), rel=1e-6)
    assert local["p50"] == pytest.approx(np.quantile(allf, 0.5), rel=0.05)
    view = InventoryView()
    nodes = [DataNode(f"n{i}") for i in range(2)]
    for n in nodes:
        view.register(n)
    for i, s in enumerate(segments):
        nodes[i % 2].load_segment(s)
        view.announce(nodes[i % 2].name, descriptor_for(s))
    remote = Broker(view).run(q)[0]["result"]
    assert remote["v"] == pytest.approx(local["v"], rel=1e-12)
    assert remote["p50"] == local["p50"]
    assert remote["u"] == local["u"]       # exact state merge across nodes


def test_hllsketch_build_and_estimate(ex, segment):
    """datasketches HLLSketch JSON surface (HLLSketchBuild +
    HLLSketchToEstimate) over the shared HLL register kernel."""
    frame = rows_as_frame(segment)
    rows = ex.run_json({
        "queryType": "timeseries", "dataSource": "test",
        "intervals": ["2026-01-01/2026-01-02"], "granularity": "all",
        "aggregations": [{"type": "HLLSketchBuild", "name": "u",
                          "fieldName": "dimHi", "lgK": 12}],
        "postAggregations": [{"type": "HLLSketchToEstimate", "name": "est",
                              "round": True,
                              "field": {"type": "fieldAccess",
                                        "fieldName": "u"}}]})
    exact = len(np.unique(frame["dimHi"]))
    est = rows[0]["result"]["est"]
    assert abs(est - exact) / exact < 0.1
    # merge type parses + rounds
    from druid_tpu.query.aggregators import agg_from_json as afj
    m = afj({"type": "HLLSketchMerge", "name": "u", "fieldName": "dimHi",
             "lgK": 11, "round": True})
    assert m.log2m == 11 and m.round
    assert m.to_json()["type"] == "HLLSketchMerge"


def test_hllsketch_grouped_matches_hyperunique(ex, segment):
    got = ex.run_json({
        "queryType": "groupBy", "dataSource": "test",
        "intervals": ["2026-01-01/2026-01-02"], "granularity": "all",
        "dimensions": ["dimA"],
        "aggregations": [{"type": "HLLSketchBuild", "name": "u",
                          "fieldName": "dimB", "lgK": 11,
                          "round": True}]})
    want = ex.run_json({
        "queryType": "groupBy", "dataSource": "test",
        "intervals": ["2026-01-01/2026-01-02"], "granularity": "all",
        "dimensions": ["dimA"],
        "aggregations": [{"type": "hyperUnique", "name": "u",
                          "fieldName": "dimB", "round": True}]})
    key = lambda rows: {r["event"]["dimA"]: r["event"]["u"] for r in rows}
    assert key(got) == key(want)


# ---------------------------------------------------------------------------
# Protobuf input parser (reference: extensions-core/protobuf-extensions)
# ---------------------------------------------------------------------------

def _event_descriptor_set():
    """Build a FileDescriptorSet in-process (what `protoc
    --descriptor_set_out` would emit for a proto3 Event message)."""
    from google.protobuf import descriptor_pb2 as dp
    f = dp.FileDescriptorProto()
    f.name, f.package, f.syntax = "event.proto", "t", "proto3"
    m = f.message_type.add()
    m.name = "Event"
    for i, (name, ftype) in enumerate([
            ("ts", dp.FieldDescriptorProto.TYPE_STRING),
            ("page", dp.FieldDescriptorProto.TYPE_STRING),
            ("clicks", dp.FieldDescriptorProto.TYPE_INT64)], start=1):
        fld = m.field.add()
        fld.name, fld.number, fld.type = name, i, ftype
        fld.label = dp.FieldDescriptorProto.LABEL_OPTIONAL
    nested = f.message_type.add()
    nested.name = "Wrapped"
    inner = nested.field.add()
    inner.name, inner.number = "event", 1
    inner.type = dp.FieldDescriptorProto.TYPE_MESSAGE
    inner.type_name = ".t.Event"
    inner.label = dp.FieldDescriptorProto.LABEL_OPTIONAL
    return dp.FileDescriptorSet(file=[f]).SerializeToString()


def test_protobuf_parser_roundtrip():
    from druid_tpu.ext import ProtobufInputRowParser
    from druid_tpu.ingest.input import InputRowParser, TimestampSpec
    desc = _event_descriptor_set()
    parser = ProtobufInputRowParser(desc, "t.Event",
                                    TimestampSpec("ts", "iso"))
    msgs = []
    for i in range(5):
        m = parser._msg_cls()
        m.ts = f"2026-07-0{i + 1}T00:00:00Z"
        m.page = f"p{i % 2}"
        m.clicks = i * 10
        msgs.append(m.SerializeToString())
    batch = parser.parse_batch(msgs)
    assert len(batch) == 5
    assert batch.columns["page"][:2] == ["p0", "p1"]
    # proto3 JSON maps int64 to string; the ingest side coerces numerics
    assert [int(v) for v in batch.columns["clicks"]] == [0, 10, 20, 30, 40]

    # wire-format roundtrip through the registered "protobuf" type
    rt = InputRowParser.from_json(parser.to_json())
    assert isinstance(rt, ProtobufInputRowParser)
    assert rt.parse_batch(msgs).columns["page"] == batch.columns["page"]


def test_protobuf_nested_flattening():
    from druid_tpu.ext import ProtobufInputRowParser
    from druid_tpu.ingest.input import TimestampSpec
    desc = _event_descriptor_set()
    parser = ProtobufInputRowParser(desc, "t.Wrapped",
                                    TimestampSpec("event.ts", "iso"))
    w = parser._msg_cls()
    w.event.ts = "2026-07-01T00:00:00Z"
    w.event.page = "home"
    w.event.clicks = 7
    batch = parser.parse_batch([w.SerializeToString()])
    assert batch.columns["event.page"] == ["home"]
    assert int(batch.columns["event.clicks"][0]) == 7


def test_unknown_parser_type_raises():
    from druid_tpu.ingest.input import InputRowParser
    import pytest
    with pytest.raises(ValueError, match="unknown parser type"):
        InputRowParser.from_json({"type": "thrift", "parseSpec": {}})


def test_time_min_max_grouped(ex, segment):
    """timeMin/timeMax (extensions-contrib time-min-max): earliest/latest
    event time per group, matching a host recompute."""
    frame = rows_as_frame(segment)
    rows = ex.run_json({
        "queryType": "groupBy", "dataSource": "test",
        "intervals": ["2026-01-01/2026-01-02"], "granularity": "all",
        "dimensions": ["dimA"],
        "aggregations": [{"type": "timeMin", "name": "tmin"},
                         {"type": "timeMax", "name": "tmax"}]})
    t = frame["__time"]
    for r in rows:
        sel = frame["dimA"] == r["event"]["dimA"]
        assert r["event"]["tmin"] == int(t[sel].min())
        assert r["event"]["tmax"] == int(t[sel].max())


def test_time_min_max_filtered_timeseries(ex, segment):
    frame = rows_as_frame(segment)
    rows = ex.run_json({
        "queryType": "timeseries", "dataSource": "test",
        "intervals": ["2026-01-01/2026-01-02"], "granularity": "all",
        "filter": {"type": "bound", "dimension": "metLong",
                   "lower": "50", "ordering": "numeric"},
        "aggregations": [{"type": "timeMin", "name": "tmin"},
                         {"type": "timeMax", "name": "tmax"}]})
    sel = frame["metLong"] >= 50
    assert rows[0]["result"]["tmin"] == int(frame["__time"][sel].min())
    assert rows[0]["result"]["tmax"] == int(frame["__time"][sel].max())


def test_time_min_max_multi_segment_merge(segments):
    """Cross-segment merge keeps absolute-time semantics."""
    from tests.conftest import rows_as_frame as raf
    ex2 = QueryExecutor(segments)
    rows = ex2.run_json({
        "queryType": "groupBy", "dataSource": "test",
        "intervals": ["2026-01-01/2026-01-08"], "granularity": "all",
        "dimensions": ["dimA"],
        "aggregations": [{"type": "timeMin", "name": "tmin"},
                         {"type": "timeMax", "name": "tmax"}]})
    frames = [raf(s) for s in segments]
    for r in rows:
        lo = min(int(f["__time"][f["dimA"] == r["event"]["dimA"]].min())
                 for f in frames
                 if (f["dimA"] == r["event"]["dimA"]).any())
        hi = max(int(f["__time"][f["dimA"] == r["event"]["dimA"]].max())
                 for f in frames
                 if (f["dimA"] == r["event"]["dimA"]).any())
        assert r["event"]["tmin"] == lo and r["event"]["tmax"] == hi


# ---------------------------------------------------------------------------
# URI namespace lookups (extensions-core/lookups-cached-global)
# ---------------------------------------------------------------------------

def test_uri_namespace_lookup_sync_and_repoll(tmp_path):
    import json as _json
    import time as _time
    from druid_tpu.cluster import MetadataStore
    from druid_tpu.cluster.lookups import (LookupCoordinatorManager,
                                           LookupNodeSync)
    from druid_tpu.query.lookup import LookupReferencesManager
    path = tmp_path / "map.json"
    path.write_text(_json.dumps({"a": "Alpha", "b": "Beta"}))
    mgr = LookupCoordinatorManager(MetadataStore())
    mgr.set_namespace_lookup("_default", "codes", {
        "type": "uri", "uri": f"file://{path}",
        "namespaceParseSpec": {"format": "json"}, "pollPeriod": 0.05})
    reg = LookupReferencesManager()
    sync = LookupNodeSync(mgr, "_default", reg)
    assert sync.poll() == 1
    assert reg.get("codes").mapping == {"a": "Alpha", "b": "Beta"}
    # file changes; repoll after pollPeriod picks it up
    path.write_text(_json.dumps({"a": "Alpha", "c": "Gamma"}))
    _time.sleep(0.06)
    assert sync.poll() == 1
    assert reg.get("codes").mapping == {"a": "Alpha", "c": "Gamma"}
    # a broken file keeps the last good mapping
    path.write_text("{not json")
    _time.sleep(0.06)
    assert sync.poll() == 0
    assert reg.get("codes").mapping == {"a": "Alpha", "c": "Gamma"}
    # spec bump (new version) forces reload immediately
    path.write_text(_json.dumps({"z": "Zed"}))
    mgr.set_namespace_lookup("_default", "codes", {
        "type": "uri", "uri": f"file://{path}",
        "namespaceParseSpec": {"format": "json"}, "pollPeriod": 3600})
    assert sync.poll() == 1
    assert reg.get("codes").mapping == {"z": "Zed"}
    # deletion drops it
    mgr.delete_lookup("_default", "codes")
    assert sync.poll() == 1
    assert reg.get("codes") is None


def test_uri_namespace_csv_and_customjson(tmp_path):
    import json as _json
    from druid_tpu.ext import load_uri_namespace
    c = tmp_path / "m.csv"
    c.write_text("code,name\nus,United States\nde,Germany\n")
    got = load_uri_namespace({"uri": str(c),
                              "namespaceParseSpec": {"format": "csv"}})
    assert got == {"us": "United States", "de": "Germany"}
    j = tmp_path / "m.json"
    j.write_text(_json.dumps([{"k": "x", "v": "X"}, {"k": "y", "v": "Y"}]))
    got = load_uri_namespace({"uri": f"file://{j}", "namespaceParseSpec": {
        "format": "customJson", "keyFieldName": "k", "valueFieldName": "v"}})
    assert got == {"x": "X", "y": "Y"}


def test_uri_namespace_lookup_queryable(tmp_path, segment):
    """End to end: a URI lookup resolves through LOOKUP() in a query."""
    import json as _json
    from druid_tpu.cluster import MetadataStore
    from druid_tpu.cluster.lookups import (LookupCoordinatorManager,
                                           LookupNodeSync)
    from druid_tpu.query.lookup import lookup_manager
    vals = list(segment.dims["dimA"].dictionary.values)
    path = tmp_path / "dimmap.json"
    path.write_text(_json.dumps({vals[0]: "FIRST"}))
    mgr = LookupCoordinatorManager(MetadataStore())
    mgr.set_namespace_lookup("_default", "dimmap", {
        "type": "uri", "uri": str(path),
        "namespaceParseSpec": {"format": "json"}})
    LookupNodeSync(mgr, "_default", lookup_manager()).poll()
    try:
        rows = QueryExecutor([segment]).run_json({
            "queryType": "groupBy", "dataSource": "test",
            "intervals": ["2026-01-01/2026-01-02"], "granularity": "all",
            "dimensions": [{"type": "extraction", "dimension": "dimA",
                            "outputName": "d",
                            "extractionFn": {"type": "registeredLookup",
                                             "lookup": "dimmap",
                                             "retainMissingValue": True}}],
            "aggregations": [{"type": "count", "name": "n"}]})
        got = {r["event"]["d"] for r in rows}
        assert "FIRST" in got and vals[0] not in got
    finally:
        lookup_manager().remove("dimmap")


def test_namespace_to_map_conversion_and_foreign_lookups(tmp_path):
    """Converting a namespace lookup back to a plain map takes effect, and
    poll() never deletes process-local register_lookup() entries."""
    import json as _json
    from druid_tpu.cluster import MetadataStore
    from druid_tpu.cluster.lookups import (LookupCoordinatorManager,
                                           LookupNodeSync)
    from druid_tpu.query.lookup import LookupReferencesManager
    path = tmp_path / "m.json"
    path.write_text(_json.dumps({"a": "FromUri"}))
    mgr = LookupCoordinatorManager(MetadataStore())
    mgr.set_namespace_lookup("_default", "conv", {
        "type": "uri", "uri": str(path),
        "namespaceParseSpec": {"format": "json"}})
    reg = LookupReferencesManager()
    reg.add("local_only", {"k": "v"}, version="v0")     # not ours
    sync = LookupNodeSync(mgr, "_default", reg)
    sync.poll()
    assert reg.get("conv").mapping == {"a": "FromUri"}
    # convert to a plain map: must take effect despite the stamped version
    mgr.set_lookup("_default", "conv", {"a": "Inline"})
    sync.poll()
    assert reg.get("conv").mapping == {"a": "Inline"}
    # foreign lookup survives every poll
    assert reg.get("local_only") is not None
    # fresh sync over a pre-populated registry still honors pollPeriod
    path.write_text(_json.dumps({"a": "Reloaded"}))
    mgr.set_namespace_lookup("_default", "conv", {
        "type": "uri", "uri": str(path),
        "namespaceParseSpec": {"format": "json"}, "pollPeriod": 0.01})
    sync.poll()
    import time as _time
    _time.sleep(0.02)
    sync2 = LookupNodeSync(mgr, "_default", reg)
    path.write_text(_json.dumps({"a": "Reloaded2"}))
    assert sync2.poll() == 1
    assert reg.get("conv").mapping == {"a": "Reloaded2"}


def test_customjson_object_payload_is_a_failure(tmp_path):
    from druid_tpu.ext import load_uri_namespace
    p = tmp_path / "bad.json"
    p.write_text('{"x": "X"}')
    with pytest.raises(ValueError, match="list of objects"):
        load_uri_namespace({"uri": str(p), "namespaceParseSpec": {
            "format": "customJson", "keyFieldName": "k",
            "valueFieldName": "v"}})


def test_recreated_sync_still_deletes_map_lookups(tmp_path):
    """Restart convergence: a NEW sync instance over the same registry can
    delete coordinator map lookups it merely re-observed; ISO pollPeriods
    parse; unchanged reload content doesn't churn the registry."""
    import json as _json
    import time as _time
    from druid_tpu.cluster import MetadataStore
    from druid_tpu.cluster.lookups import (LookupCoordinatorManager,
                                           LookupNodeSync, _period_seconds)
    from druid_tpu.query.lookup import LookupReferencesManager
    assert _period_seconds("PT5M") == 300.0
    assert _period_seconds(2.5) == 2.5
    assert _period_seconds("garbage") == 0.0
    mgr = LookupCoordinatorManager(MetadataStore())
    mgr.set_lookup("_default", "m", {"a": "1"})
    reg = LookupReferencesManager()
    LookupNodeSync(mgr, "_default", reg).poll()
    assert reg.get("m") is not None
    # fresh sync re-observes (add returns False) then the spec vanishes
    sync2 = LookupNodeSync(mgr, "_default", reg)
    sync2.poll()
    mgr.delete_lookup("_default", "m")
    assert sync2.poll() == 1
    assert reg.get("m") is None
    # a user version merely containing '+' is NOT treated as sync-owned
    reg.add("mine", {"k": "v"}, version="1.2+build7")
    sync2.poll()
    assert reg.get("mine") is not None
    # unchanged namespace content: no churn on periodic reload
    p = tmp_path / "n.json"
    p.write_text(_json.dumps({"x": "X"}))
    mgr.set_namespace_lookup("_default", "ns", {
        "type": "uri", "uri": str(p),
        "namespaceParseSpec": {"format": "json"}, "pollPeriod": 0.01})
    assert sync2.poll() == 1
    v1 = reg.get("ns").version
    _time.sleep(0.02)
    assert sync2.poll() == 0            # reloaded, identical → no change
    assert reg.get("ns").version == v1


def test_local_lookup_with_conflicting_name_never_deleted(tmp_path):
    """A coordinator spec sharing a name with a LOCAL register_lookup()
    entry it could not overwrite must not claim ownership — spec deletion
    leaves the local entry; and a local version sharing the stamp prefix
    never crashes the namespace reload counter."""
    import json as _json
    from druid_tpu.cluster import MetadataStore
    from druid_tpu.cluster.lookups import (LookupCoordinatorManager,
                                           LookupNodeSync)
    from druid_tpu.query.lookup import LookupReferencesManager
    mgr = LookupCoordinatorManager(MetadataStore())
    reg = LookupReferencesManager()
    # local entry with a HIGHER version than the coordinator will use
    reg.add("x", {"local": "yes"}, version="zzzzzzzzzzzz")
    mgr.set_lookup("_default", "x", {"coord": "yes"}, version="v1")
    sync = LookupNodeSync(mgr, "_default", reg)
    sync.poll()
    assert reg.get("x").mapping == {"local": "yes"}   # version-gated no-op
    mgr.delete_lookup("_default", "x")
    sync.poll()
    assert reg.get("x") is not None                   # NOT ours to delete
    # namespace spec colliding with a local entry: first writer wins —
    # the sync neither overwrites nor loads, and never deletes it
    p = tmp_path / "ns.json"
    p.write_text(_json.dumps({"a": "A"}))
    reg.add("y", {"loc": "1"}, version="1.2+build7")
    mgr.set_namespace_lookup("_default", "y", {
        "type": "uri", "uri": str(p),
        "namespaceParseSpec": {"format": "json"}, "pollPeriod": 0.01},
        version="1.2")
    sync.poll()                                       # must not raise
    assert reg.get("y").mapping == {"loc": "1"}       # untouched
    mgr.delete_lookup("_default", "y")
    sync.poll()
    assert reg.get("y") is not None                   # still not ours
    # namespace→map conversion under the SAME version string applies
    p2 = tmp_path / "same.json"
    p2.write_text(_json.dumps({"k": "FromUri"}))
    mgr.set_namespace_lookup("_default", "same", {
        "type": "uri", "uri": str(p2),
        "namespaceParseSpec": {"format": "json"}}, version="v7")
    sync.poll()
    assert reg.get("same").mapping == {"k": "FromUri"}
    mgr.set_lookup("_default", "same", {"k": "Inline"}, version="v7")
    sync.poll()
    assert reg.get("same").mapping == {"k": "Inline"}


def test_map_spec_with_plus_version_converges():
    """A coordinator map spec whose version itself contains '+' must
    converge (no perpetual remove/re-add churn)."""
    from druid_tpu.cluster import MetadataStore
    from druid_tpu.cluster.lookups import (LookupCoordinatorManager,
                                           LookupNodeSync)
    from druid_tpu.query.lookup import LookupReferencesManager
    mgr = LookupCoordinatorManager(MetadataStore())
    mgr.set_lookup("_default", "x", {"a": "1"}, version="1.0+hotfix")
    reg = LookupReferencesManager()
    sync = LookupNodeSync(mgr, "_default", reg)
    assert sync.poll() == 1
    assert sync.poll() == 0
    assert sync.poll() == 0
    assert reg.get("x").mapping == {"a": "1"}


# ---------------------------------------------------------------------------
# distinctCount (extensions-contrib/distinctcount)
# ---------------------------------------------------------------------------

def test_distinct_count_single_segment_exact(ex, segment):
    frame = rows_as_frame(segment)
    rows = ex.run_json({
        "queryType": "groupBy", "dataSource": "test",
        "intervals": ["2026-01-01/2026-01-02"], "granularity": "all",
        "dimensions": ["dimA"],
        "aggregations": [{"type": "distinctCount", "name": "u",
                          "fieldName": "dimB"}]})
    for r in rows:
        sel = frame["dimA"] == r["event"]["dimA"]
        assert r["event"]["u"] == len(set(frame["dimB"][sel])), \
            r["event"]["dimA"]


def test_distinct_count_filtered_timeseries(ex, segment):
    frame = rows_as_frame(segment)
    rows = ex.run_json({
        "queryType": "timeseries", "dataSource": "test",
        "intervals": ["2026-01-01/2026-01-02"], "granularity": "all",
        "filter": {"type": "bound", "dimension": "metLong",
                   "lower": "50", "ordering": "numeric"},
        "aggregations": [{"type": "distinctCount", "name": "u",
                          "fieldName": "dimB"}]})
    sel = frame["metLong"] >= 50
    assert rows[0]["result"]["u"] == len(set(frame["dimB"][sel]))


def test_distinct_count_partitioned_segments_exact():
    """The contrib accuracy contract: exact across segments when each
    dimension value lives in ONE segment (dim-partitioned data)."""
    from druid_tpu.data.segment import SegmentBuilder
    from druid_tpu.utils.intervals import Interval, parse_ts
    t0 = parse_ts("2026-05-01")
    iv = Interval.of("2026-05-01", "2026-05-02")
    segs = []
    for part, vals in enumerate((["u1", "u2", "u3"], ["u4", "u5"])):
        # distinct segment IDs: partition goes into SegmentId via the
        # builder constructor
        b = SegmentBuilder("pd", iv, version="v1", partition=part)
        rows = [vals[i % len(vals)] for i in range(30)]
        b.add_columns([t0 + i for i in range(30)], dims={"user": rows},
                      metrics={})
        segs.append(b.build())
    rows = QueryExecutor(segs).run_json({
        "queryType": "timeseries", "dataSource": "pd",
        "intervals": [str(iv)], "granularity": "all",
        "aggregations": [{"type": "distinctCount", "name": "u",
                          "fieldName": "user"}]})
    assert rows[0]["result"]["u"] == 5


def test_distinct_count_schema_evolution_contributes_zero():
    """A segment missing the dimension contributes zero, never a query
    failure (matches every other kernel's missing-column behavior)."""
    from druid_tpu.data.segment import SegmentBuilder
    from druid_tpu.utils.intervals import Interval, parse_ts
    t0 = parse_ts("2026-05-01")
    iv = Interval.of("2026-05-01", "2026-05-03")
    a = SegmentBuilder("se", Interval(t0, t0 + 86_400_000), version="v1")
    a.add_columns([t0, t0 + 1], dims={"user": ["u1", "u2"]}, metrics={})
    b = SegmentBuilder("se", Interval(t0 + 86_400_000, t0 + 2 * 86_400_000),
                       version="v1")
    b.add_columns([t0 + 86_400_000], dims={"other": ["x"]}, metrics={})
    rows = QueryExecutor([a.build(), b.build()]).run_json({
        "queryType": "timeseries", "dataSource": "se",
        "intervals": [str(iv)], "granularity": "all",
        "aggregations": [{"type": "distinctCount", "name": "u",
                          "fieldName": "user"}]})
    assert rows[0]["result"]["u"] == 2
