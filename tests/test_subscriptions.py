"""Subscription-hub tests (server/subscriptions.py): dedupe onto one
standing program, long-poll + ETag/304 fan-out, refcounted teardown, the
scheduler-flush-loop tick driver, and lifecycle leak hygiene."""
import json
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from druid_tpu.cluster.metadata import MetadataStore
from druid_tpu.engine import QueryExecutor
from druid_tpu.ingest import (Appenderator, RowBatch, SegmentAllocator,
                              StreamAppenderatorDriver)
from druid_tpu.obs import dispatch as dispatch_mod
from druid_tpu.query.aggregators import CountAggregator, LongSumAggregator
from druid_tpu.query.model import TimeseriesQuery
from druid_tpu.server.subscriptions import (SubscriptionHub,
                                            SubscriptionMetricsMonitor,
                                            UnknownSubscriptionError)
from druid_tpu.utils.intervals import Interval

REPO_ROOT = Path(__file__).resolve().parent.parent

SPECS = [CountAggregator("rows"), LongSumAggregator("v", "value")]
QSPECS = [LongSumAggregator("rows", "rows"), LongSumAggregator("v", "v")]
DAY = Interval.of("2026-03-01", "2026-03-02")
T0 = DAY.start


def _batch(rng, n, off=0):
    ts = [int(T0 + (off + i) * 1000) for i in range(n)]
    return RowBatch(ts, {
        "page": [f"p{int(x)}" for x in rng.integers(5, size=n)],
        "value": [int(x) for x in rng.integers(10, size=n)]})


def _rig():
    md = MetadataStore()
    app = Appenderator("rt", SPECS, query_granularity="none")
    driver = StreamAppenderatorDriver(app, SegmentAllocator(md, "day"), md)
    return md, app, driver


def _query(granularity="all", **ctx):
    return TimeseriesQuery.of("rt", [DAY], QSPECS, granularity=granularity,
                              context=ctx or None)


def test_identical_subscriptions_share_one_program_one_dispatch():
    """THE fan-out acceptance: N structurally identical subscriptions run
    ONE standing program — the tick's device dispatch count is independent
    of N (dispatch-counter assertion)."""
    rng = np.random.default_rng(0)
    md, app, driver = _rig()
    hub = SubscriptionHub(idle_timeout_s=0)
    hub.attach(app)
    try:
        subs = [hub.subscribe(_query()) for _ in range(64)]
        assert hub.active_subscriptions() == 64
        assert hub.active_programs() == 1

        driver.add_batch(_batch(rng, 400))
        hub.tick()                        # warm: compiles + first fold
        driver.add_batch(_batch(rng, 400, off=400))
        d0 = dispatch_mod.count()
        hub.tick()
        fan64 = dispatch_mod.count() - d0
        assert fan64 == 1, \
            f"64 identical subscriptions cost {fan64} dispatches per tick"

        # every subscriber sees the same rows/etag (one merge, N deliveries)
        rows0, etag0, changed = hub.poll(subs[0][0], etag=subs[0][1])
        assert changed and rows0[0]["result"]["rows"] == 800
        for sid, etag in subs[1:]:
            rows, new_etag, ch = hub.poll(sid, etag=etag)
            assert ch and rows == rows0 and new_etag == etag0

        # context differences do NOT split programs (structure signature
        # excludes context); a different granularity DOES — and so does a
        # different EMISSION POLICY (standingEmit is context, but changes
        # what a program delivers: it must not dedupe across policies)
        sid_ctx, _ = hub.subscribe(_query(queryId="abc"))
        assert hub.active_programs() == 1
        sid_g, _ = hub.subscribe(_query(granularity="hour"))
        assert hub.active_programs() == 2
        sid_b, _ = hub.subscribe(_query(granularity="hour",
                                        standingEmit="bucket"))
        assert hub.active_programs() == 3
        hub.unsubscribe(sid_ctx)
        hub.unsubscribe(sid_g)
        hub.unsubscribe(sid_b)
    finally:
        hub.stop()
    assert hub.active_subscriptions() == 0
    assert hub.active_programs() == 0
    assert app._listeners == []


def test_long_poll_304_and_wakeup():
    rng = np.random.default_rng(1)
    md, app, driver = _rig()
    hub = SubscriptionHub(idle_timeout_s=0)
    hub.attach(app)
    try:
        sid, etag = hub.subscribe(_query())
        # unchanged within the window → the 304 path
        t0 = time.monotonic()
        rows, new_etag, changed = hub.poll(sid, etag=etag, timeout_s=0.15)
        assert not changed and rows is None and new_etag == etag
        assert time.monotonic() - t0 >= 0.14

        # a tick that emits wakes a parked long-poll before its deadline
        got = {}

        def parked():
            got["r"] = hub.poll(sid, etag=etag, timeout_s=30.0)

        t = threading.Thread(target=parked)
        t.start()
        time.sleep(0.05)
        driver.add_batch(_batch(rng, 100))
        hub.tick()
        t.join(timeout=10)
        assert not t.is_alive()
        rows, _, changed = got["r"]
        assert changed and rows[0]["result"]["rows"] == 100

        # an unsubscribe mid-poll raises, not hangs
        def parked_dead():
            with pytest.raises(UnknownSubscriptionError):
                hub.poll(sid, etag=hub.poll(sid)[1], timeout_s=30.0)

        t2 = threading.Thread(target=parked_dead)
        t2.start()
        time.sleep(0.05)
        hub.unsubscribe(sid)
        t2.join(timeout=10)
        assert not t2.is_alive()
    finally:
        hub.stop()


def test_idle_subscriptions_swept():
    """A client that silently disconnected (stopped polling) is torn down
    by the tick sweep — refcounted state cannot leak forever."""
    md, app, driver = _rig()
    hub = SubscriptionHub(idle_timeout_s=0.05)
    hub.attach(app)
    try:
        sid, _ = hub.subscribe(_query())
        assert hub.active_subscriptions() == 1
        time.sleep(0.1)
        hub.tick()
        assert hub.active_subscriptions() == 0
        assert hub.active_programs() == 0
        with pytest.raises(UnknownSubscriptionError):
            hub.poll(sid)
    finally:
        hub.stop()


def test_scheduler_flush_loop_drives_ticks():
    """drive_with(scheduler): the data-node scheduler's dispatcher loop is
    the tick driver — appended data surfaces to a subscriber without
    anyone calling hub.tick()."""
    from druid_tpu.cluster.view import DataNode
    from druid_tpu.server.scheduler import (DataNodeScheduler,
                                            SchedulerConfig)

    rng = np.random.default_rng(2)
    md, app, driver = _rig()
    node = DataNode("n0")
    sched = DataNodeScheduler(node, SchedulerConfig()).start()
    hub = SubscriptionHub(idle_timeout_s=0).drive_with(sched)
    hub.attach(app)
    try:
        sid, etag = hub.subscribe(_query())
        driver.add_batch(_batch(rng, 50))
        rows, _, changed = hub.poll(sid, etag=etag, timeout_s=30.0)
        assert changed and rows[0]["result"]["rows"] == 50
    finally:
        hub.stop()
        sched.stop()
    assert sched._tick_hooks == []


def test_http_subscription_surface_end_to_end():
    """POST subscribe → GET long-poll (200 + X-Druid-ETag, then 304 via
    If-None-Match, then 200 again after new data) → DELETE teardown; an
    ineligible query is a 400, an unknown id a 404."""
    from druid_tpu.server import QueryHttpServer, QueryLifecycle

    rng = np.random.default_rng(3)
    md, app, driver = _rig()
    hub = SubscriptionHub(idle_timeout_s=0)
    hub.attach(app)
    ex = QueryExecutor(app.query_segments())
    srv = QueryHttpServer(QueryLifecycle(ex), subscription_hub=hub,
                          port=0).start()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        q = {"queryType": "timeseries", "dataSource": "rt",
             "intervals": [str(DAY)], "granularity": "all",
             "aggregations": [{"type": "longSum", "name": "rows",
                               "fieldName": "rows"}]}
        req = urllib.request.Request(
            f"{base}/druid/v2/subscriptions", data=json.dumps(q).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req) as r:
            body = json.loads(r.read())
        sub_id, etag = body["subscriptionId"], body["etag"]

        # unconditional GET: current snapshot (empty world yet)
        with urllib.request.urlopen(
                f"{base}/druid/v2/subscriptions/{sub_id}") as r:
            assert r.status == 200
            assert r.headers["X-Druid-ETag"] == etag

        # If-None-Match on the current etag: 304 within the window
        req = urllib.request.Request(
            f"{base}/druid/v2/subscriptions/{sub_id}?timeoutMs=100",
            headers={"If-None-Match": etag})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req)
        assert ei.value.code == 304

        # new data: the same conditional GET now ships rows + a new etag
        driver.add_batch(_batch(rng, 75))
        hub.tick()
        req = urllib.request.Request(
            f"{base}/druid/v2/subscriptions/{sub_id}?timeoutMs=5000",
            headers={"If-None-Match": etag})
        with urllib.request.urlopen(req) as r:
            rows = json.loads(r.read())
            new_etag = r.headers["X-Druid-ETag"]
        assert new_etag != etag
        assert rows[0]["result"]["rows"] == 75

        # ineligible query shape → 400
        bad = dict(q, queryType="scan", columns=[])
        req = urllib.request.Request(
            f"{base}/druid/v2/subscriptions",
            data=json.dumps(bad).encode(),
            headers={"Content-Type": "application/json"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req)
        assert ei.value.code == 400

        # DELETE tears down; a later poll is a 404 (client re-subscribes)
        req = urllib.request.Request(
            f"{base}/druid/v2/subscriptions/{sub_id}", method="DELETE")
        with urllib.request.urlopen(req) as r:
            assert r.status == 202
            assert json.loads(r.read())["active"] is True
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"{base}/druid/v2/subscriptions/{sub_id}")
        assert ei.value.code == 404
    finally:
        srv.stop()
        hub.stop()


def test_hub_stress_returns_to_baseline():
    """Subscription-lifecycle leak hygiene under the leak witness:
    subscribe/poll/tick/unsubscribe churn plus hub start/stop cycles leave
    no thread, fd, or device-pool residue (the ISSUE's leakguard
    satellite; DRUID_TPU_LEAK_WITNESS=1 additionally runs the whole suite
    under the session witness)."""
    import sys
    sys.path.insert(0, str(REPO_ROOT))
    from tools.druidlint.leakwitness import LeakWitness

    rng = np.random.default_rng(4)

    def cycle():
        md, app, driver = _rig()
        hub = SubscriptionHub(idle_timeout_s=0,
                              tick_period_s=0.01).start()
        hub.attach(app)
        subs = [hub.subscribe(_query()) for _ in range(8)]
        driver.add_batch(_batch(rng, 64))
        for sid, etag in subs:
            rows, _, changed = hub.poll(sid, etag=etag, timeout_s=10.0)
            assert changed and rows
        for sid, _ in subs[:4]:
            hub.unsubscribe(sid)
        hub.stop()                        # sweeps the rest
        assert hub.active_subscriptions() == 0
        assert app._listeners == []

    w = LeakWitness(str(REPO_ROOT)).install()
    try:
        cycle()                           # warmup: lazy init + compiles
        base = w.snapshot()
        for _ in range(3):
            cycle()
        assert w.leaks(base, grace_s=10.0) == []
    finally:
        w.uninstall()


def test_subscription_monitor_names_in_catalog():
    from druid_tpu.obs.catalog import validate_emitted
    from druid_tpu.utils.emitter import InMemoryEmitter, ServiceEmitter

    hub = SubscriptionHub(idle_timeout_s=0)
    try:
        sink = InMemoryEmitter()
        SubscriptionMetricsMonitor(hub).do_monitor(
            ServiceEmitter("t", "h", sink))
        names = {e.metric for e in sink.events}
        assert names and validate_emitted(names) == []
    finally:
        hub.stop()
