"""Storage layer tests: codec roundtrips, smoosh container, segment
persist/load parity — the analog of the reference's format-level tests
(CompressedColumnarIntsSupplierTest, IndexMergerTestBase round-trips)."""
import numpy as np
import pytest

from druid_tpu import native
from druid_tpu.data.bitmap import BitmapIndex
from druid_tpu.storage import codec as codecs
from druid_tpu.storage.format import (LazyBitmapIndex, _decode_dictionary,
                                      _encode_bitmap_index,
                                      _encode_dictionary, load_segment,
                                      persist_segment, read_segment_meta)
from druid_tpu.storage.smoosh import FileSmoosher, SmooshedFileMapper
from druid_tpu.data.dictionary import Dictionary

from conftest import rows_as_frame


def test_native_available():
    # the toolchain is baked into the image; the native path must be live
    assert native.available()


@pytest.mark.parametrize("codec", [codecs.LZ4, codecs.ZLIB, codecs.NONE])
@pytest.mark.parametrize("dtype", [np.int32, np.int64, np.float32,
                                   np.float64, np.uint8])
def test_codec_roundtrip(codec, dtype):
    rng = np.random.default_rng(3)
    for n in [0, 1, 7, 1000, 65536 // np.dtype(dtype).itemsize, 200_001]:
        if np.issubdtype(dtype, np.integer):
            arr = rng.integers(0, 50, n).astype(dtype)
        else:
            arr = rng.normal(size=n).astype(dtype)
        buf = codecs.compress_array(arr, codec)
        out = codecs.decompress_array(buf)
        assert out.dtype == arr.dtype
        np.testing.assert_array_equal(out, arr)


def test_codec_incompressible_falls_back_to_raw():
    rng = np.random.default_rng(5)
    arr = rng.integers(0, 2**63 - 1, 50_000).astype(np.int64)
    buf = codecs.compress_array(arr, codecs.LZ4)
    # random data must not blow up more than the block headers
    assert len(buf) < arr.nbytes * 1.01 + 1024
    np.testing.assert_array_equal(codecs.decompress_array(buf), arr)


def test_smoosh_roundtrip(tmp_path):
    d = str(tmp_path / "sm")
    parts = {f"part{i}": bytes([i]) * (1000 * (i + 1)) for i in range(5)}
    with FileSmoosher(d, chunk_size=2500) as sm:
        for k, v in parts.items():
            sm.add(k, v)
    with SmooshedFileMapper(d) as m:
        assert set(m.names()) == set(parts)
        for k, v in parts.items():
            assert bytes(m.part(k)) == v
    # multiple chunks must have been created (parts never span chunks)
    import os
    chunks = [f for f in os.listdir(d) if f.startswith("chunk_")]
    assert len(chunks) > 1


def test_smoosh_duplicate_name(tmp_path):
    with FileSmoosher(str(tmp_path / "sm")) as sm:
        sm.add("a", b"x")
        with pytest.raises(ValueError):
            sm.add("a", b"y")


def test_dictionary_roundtrip():
    d = Dictionary(sorted(["", "a", "héllo", "zz", "中文", "a b,c"]))
    out = _decode_dictionary(_encode_dictionary(d))
    assert out.values == d.values


def test_delta_encoding_roundtrip_and_wins():
    """Monotonic int columns (sorted __time) store delta-encoded
    (CompressionFactory LongEncodingStrategy.AUTO capability): exact
    round-trip, markedly smaller than raw epoch millis."""
    from druid_tpu.storage.codec import compress_array, decompress_array
    t0 = 1_750_000_000_000
    ts = t0 + np.cumsum(np.random.default_rng(1).integers(
        0, 2000, 500_000)).astype(np.int64)
    enc = compress_array(ts)
    assert np.array_equal(decompress_array(enc), ts)
    raw = compress_array(ts, encoding="none")
    assert np.array_equal(decompress_array(raw), ts)
    assert len(enc) < len(raw) * 0.75, (len(enc), len(raw))
    # non-monotonic ints pass through unencoded but exact
    vals = np.random.default_rng(2).integers(-(2**62), 2**62, 10_000)
    assert np.array_equal(decompress_array(compress_array(vals)), vals)
    # overflow-wrapping deltas still reconstruct exactly
    edge = np.asarray([-(2**63), 2**63 - 1, -(2**63) + 5], dtype=np.int64)
    assert np.array_equal(
        decompress_array(compress_array(edge, encoding="delta")), edge)
    # sorted unsigned round-trips through the modular limbs
    u = np.sort(np.random.default_rng(4).integers(
        0, 2**64, 10_000, dtype=np.uint64))
    assert np.array_equal(decompress_array(compress_array(u)), u)
    # non-monotonic unsigned must NOT delta-encode (wrapped deltas look
    # falsely monotonic) — auto may byte-pack it instead, which is exact
    from druid_tpu.storage.codec import ENC_DELTA, _pick_encoding
    nm = np.asarray([10, 3, 7, 1], dtype=np.uint64)
    assert _pick_encoding(nm, "auto") != ENC_DELTA
    assert np.array_equal(decompress_array(compress_array(nm)), nm)
    with pytest.raises(ValueError):
        compress_array(ts, encoding="tabel")   # typo'd encodings reject
    # table on >256 distinct values silently falls back to none but exact
    assert np.array_equal(
        decompress_array(compress_array(ts, encoding="table")), ts)
    # floats / 2-D untouched
    f = np.random.default_rng(3).normal(size=1000).astype(np.float32)
    assert np.array_equal(decompress_array(compress_array(f)), f)
    m = np.arange(64, dtype=np.int64).reshape(8, 8)
    assert np.array_equal(decompress_array(compress_array(m)), m)


def test_tmpfile_writeout_byte_identical(tmp_path, segment):
    """FileWriteOutMedium path: streamed persist must produce the same
    bytes as the in-memory path and reload identically."""
    from druid_tpu.storage.format import load_segment, persist_segment
    d_mem, d_wo = str(tmp_path / "mem"), str(tmp_path / "wo")
    persist_segment(segment, d_mem)
    persist_segment(segment, d_wo, writeout="tmpfile")
    import os
    files_mem = sorted(f for f in os.listdir(d_mem))
    assert files_mem == sorted(f for f in os.listdir(d_wo))
    for f in files_mem:
        with open(os.path.join(d_mem, f), "rb") as a, \
                open(os.path.join(d_wo, f), "rb") as b:
            assert a.read() == b.read(), f
    back = load_segment(d_wo)
    assert back.n_rows == segment.n_rows
    assert np.array_equal(back.time_ms, segment.time_ms)
    # no writeout temp dirs left behind
    assert not [f for f in files_mem if f.startswith("writeout_")]


def test_bitmap_index_roundtrip():
    rng = np.random.default_rng(11)
    ids = rng.integers(0, 17, 5000).astype(np.int32)
    idx = BitmapIndex.build(ids, 17)
    buf = _encode_bitmap_index(idx, codecs.LZ4)
    lazy = LazyBitmapIndex(buf)
    assert lazy.n_rows == idx.n_rows and lazy.cardinality == idx.cardinality
    for vid in [0, 5, 16]:
        np.testing.assert_array_equal(lazy.bitmap(vid).to_bool(),
                                      idx.bitmap(vid).to_bool())
    np.testing.assert_array_equal(
        lazy.union_of(np.array([1, 3, 9])).to_bool(),
        idx.union_of(np.array([1, 3, 9])).to_bool())


def test_segment_persist_load_roundtrip(tmp_path, segment):
    d = str(tmp_path / "seg")
    size = persist_segment(segment, d)
    assert size > 0
    loaded = load_segment(d)
    assert loaded.id == segment.id
    assert loaded.n_rows == segment.n_rows
    np.testing.assert_array_equal(loaded.time_ms, segment.time_ms)
    for name, col in segment.dims.items():
        np.testing.assert_array_equal(loaded.dims[name].ids, col.ids)
        assert loaded.dims[name].dictionary == col.dictionary
        # lazy bitmaps match rebuilt ones
        np.testing.assert_array_equal(
            loaded.dims[name].bitmap_index().bitmap(1).to_bool(),
            col.bitmap_index().bitmap(1).to_bool())
    for name, m in segment.metrics.items():
        assert loaded.metrics[name].type == m.type
        np.testing.assert_array_equal(loaded.metrics[name].values, m.values)
    meta = read_segment_meta(d)
    assert meta["n_rows"] == segment.n_rows


def test_segment_load_column_subset(tmp_path, segment):
    d = str(tmp_path / "seg2")
    persist_segment(segment, d, build_bitmaps=False)
    first_dim = next(iter(segment.dims))
    first_met = next(iter(segment.metrics))
    loaded = load_segment(d, columns=[first_dim, first_met])
    assert list(loaded.dims) == [first_dim]
    assert list(loaded.metrics) == [first_met]


def test_loaded_segment_queries_match(tmp_path, segment):
    """Query results over a loaded segment must equal in-memory results —
    the multi-representation pattern of QueryRunnerTestHelper.makeQueryRunners
    (reference: processing/src/test/.../QueryRunnerTestHelper.java:338)."""
    from druid_tpu.engine.engines import run_timeseries
    from druid_tpu.query.aggregators import CountAggregator, LongSumAggregator
    from druid_tpu.query.filters import SelectorFilter
    from druid_tpu.query.model import TimeseriesQuery

    d = str(tmp_path / "seg3")
    persist_segment(segment, d)
    loaded = load_segment(d)
    dim = next(iter(segment.dims))
    val = segment.dims[dim].dictionary.values[1]
    q = TimeseriesQuery.of(
        "test", [segment.interval],
        [CountAggregator("rows"), LongSumAggregator("s", "metLong")],
        granularity="hour", filter=SelectorFilter(dim, val))
    a = run_timeseries(q, [segment])
    b = run_timeseries(q, [loaded])
    assert a == b


def test_vsize_packing_roundtrip_and_shrink():
    """Small-range int64 columns byte-pack (VSizeLongSerde): exact
    roundtrip, and the part is materially smaller than unpacked."""
    from druid_tpu.storage.codec import (ENC_VSIZE8, ENC_VSIZE16,
                                         _pick_encoding, compress_array,
                                         decompress_array)
    rng = np.random.default_rng(5)
    for hi, enc in ((250, ENC_VSIZE8), (60_000, ENC_VSIZE16)):
        arr = rng.integers(0, hi, size=200_000).astype(np.int64)
        assert _pick_encoding(arr, "auto") == enc
        buf = compress_array(arr, encoding="auto")
        assert np.array_equal(decompress_array(buf), arr)
        raw = compress_array(arr, encoding="none")
        assert len(buf) < len(raw) * 0.7
    # negative values cannot byte-pack
    neg = rng.integers(-5, 5, size=1000).astype(np.int64)
    assert _pick_encoding(neg, "auto") == 0
    assert np.array_equal(
        decompress_array(compress_array(neg, encoding="auto")), neg)


def test_table_encoding_roundtrip():
    """≤256 distinct values store the table once + u8 indexes
    (CompressionFactory TABLE)."""
    from druid_tpu.storage.codec import (ENC_TABLE, _pick_encoding,
                                         compress_array, decompress_array)
    rng = np.random.default_rng(6)
    vals = np.array([10**12 + v * 10**9 for v in range(40)], dtype=np.int64)
    arr = vals[rng.integers(0, 40, size=100_000)]
    assert _pick_encoding(arr, "table") == ENC_TABLE
    buf = compress_array(arr, encoding="table")
    assert np.array_equal(decompress_array(buf), arr)
    # too many distinct values: table refused, falls back to none
    wide = rng.integers(0, 10**12, size=5000).astype(np.int64)
    assert _pick_encoding(wide, "table") == 0


def test_vsize_writeout_file_byte_identical(tmp_path):
    from druid_tpu.storage.codec import (compress_array,
                                         compress_array_to_file)
    rng = np.random.default_rng(7)
    arr = rng.integers(0, 200, size=300_000).astype(np.int64)
    p = str(tmp_path / "part.bin")
    compress_array_to_file(arr, p, encoding="auto")
    with open(p, "rb") as f:
        assert f.read() == compress_array(arr, encoding="auto")
