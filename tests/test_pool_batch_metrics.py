"""Device-pool + batched-execution metrics wired through the data node
server (cluster/dataserver.py): the server owns the MonitorScheduler that
emits segment/devicePool/* and query/batch/* counters."""
import pytest

from druid_tpu.cluster.dataserver import DataNodeServer
from druid_tpu.cluster.view import DataNode
from druid_tpu.data import devicepool
from druid_tpu.data.generator import ColumnSpec, DataGenerator
from druid_tpu.engine import batching
from druid_tpu.query.model import query_from_json
from druid_tpu.utils.emitter import InMemoryEmitter, ServiceEmitter
from druid_tpu.utils.intervals import Interval

IV = Interval.of("2026-05-01", "2026-05-02")
SCHEMA = (ColumnSpec("dimA", "string", cardinality=6),
          ColumnSpec("metLong", "long", low=0, high=100))

QUERY = {"queryType": "groupBy", "dataSource": "metrics",
         "intervals": [str(IV)], "granularity": "all",
         "dimensions": ["dimA"],
         "aggregations": [{"type": "count", "name": "n"},
                          {"type": "longSum", "name": "s",
                           "fieldName": "metLong"}]}


@pytest.fixture
def served(monkeypatch):
    """Fresh pool + batching stats, a loaded DataNode, and its server with
    the metrics monitors wired."""
    pool = devicepool.DeviceSegmentPool(budget_bytes=1 << 40)
    monkeypatch.setattr(devicepool, "_POOL", pool)
    monkeypatch.setattr(batching, "_ENABLED", True)
    stats = batching.BatchStats()
    monkeypatch.setattr(batching, "_STATS", stats)
    segments = DataGenerator(SCHEMA, seed=11).segments(
        4, 1500, IV, datasource="metrics")
    node = DataNode("dn1")
    for s in segments:
        node.load_segment(s)
    sink = InMemoryEmitter()
    emitter = ServiceEmitter("historical", "dn1", sink)
    # monitors must read the patched pool/stats singletons
    server = DataNodeServer(node, emitter=emitter,
                            device_pool_bytes=1 << 40,
                            monitor_period_seconds=3600.0)
    monkeypatch.setattr(
        server._monitors, "monitors",
        [devicepool.DevicePoolMonitor(pool),
         batching.BatchMetricsMonitor(stats)])
    try:
        yield node, server, sink, segments
    finally:
        server._httpd.server_close()


def test_server_tick_emits_pool_and_batch_metrics(served):
    node, server, sink, segments = served
    sids = [str(s.id) for s in segments]
    query = query_from_json(QUERY)
    node.run_partials(query, sids)           # cold: stage + batch
    node.run_partials(query, sids)           # warm: pool hits
    server.metrics_tick()
    names = {e.metric for e in sink.metrics()}
    assert "segment/devicePool/hitRate" in names
    assert "segment/devicePool/evictedBytes" in names
    assert "query/batch/segments" in names
    assert "query/batch/fillRatio" in names
    # every dispatch stacked all 4 same-shape segments
    segs_per_batch = [e.value for e in sink.metrics("query/batch/segments")]
    assert segs_per_batch and all(v == 4 for v in segs_per_batch)
    for e in sink.metrics("query/batch/fillRatio"):
        assert 0.0 < e.value <= 1.0
    # service dims stamped by the ServiceEmitter wrapper
    e = sink.metrics("query/batch/segments")[0]
    assert e.dims["service"] == "historical"


def test_batch_events_drain_once(served):
    node, server, sink, segments = served
    sids = [str(s.id) for s in segments]
    node.run_partials(query_from_json(QUERY), sids)
    server.metrics_tick()
    n = len(sink.metrics("query/batch/segments"))
    assert n >= 1
    server.metrics_tick()                    # no new dispatches: no new events
    assert len(sink.metrics("query/batch/segments")) == n


def test_check_probe_still_enforced_around_fused_run(served):
    """Cancellation collapses to dispatch boundaries, not silently dropped:
    a pre-cancelled probe aborts before any result is produced."""
    node, server, sink, segments = served

    class Cancelled(Exception):
        pass

    def probe():
        raise Cancelled()

    with pytest.raises(Cancelled):
        node.run_partials(query_from_json(QUERY),
                          [str(s.id) for s in segments], check=probe)


def test_check_fires_between_per_segment_dispatches(served, monkeypatch):
    """With batching off, the probe still fires between per-segment device
    dispatches (threaded through make_aggregate_partials), so a cancel
    aborts at the next dispatch boundary instead of after the whole set."""
    node, server, sink, segments = served
    monkeypatch.setattr(batching, "_ENABLED", False)

    class Cancelled(Exception):
        pass

    calls = []

    def probe():
        calls.append(1)
        if len(calls) >= 2:
            raise Cancelled()

    with pytest.raises(Cancelled):
        node.run_partials(query_from_json(QUERY),
                          [str(s.id) for s in segments], check=probe)
    assert len(calls) == 2
