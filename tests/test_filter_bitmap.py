"""Device-side bitmap algebra (ROADMAP item 5): filter bitmaps as resident
packed words, combined in-program, cached like jit programs.

The exhaustive parity gate: random filter trees (depth ≤ 4 over
selector/in/bound/not) evaluated host-mask (device bitmaps off) vs
device-bitmap vs per-segment vs batched must agree EXACTLY — floats
included — across sparse/dense/boundary densities (n_rows not divisible by
32). Plus: the filter-result cache (hits skip leaf staging + algebra), the
no-column-staging contract, the batching widenings the in-program mask
unblocks (2-D HLL metric columns, per-segment query-time dictionaries),
and cross-filter chunk fusion.
"""
import numpy as np
import pytest

import druid_tpu.engine  # noqa: F401  (x64 on before jax numerics)
from druid_tpu.data.bitmap import SparseBitmap
from druid_tpu.data.generator import ColumnSpec, DataGenerator
from druid_tpu.engine import batching
from druid_tpu.engine import filters as filters_mod
from druid_tpu.engine.executor import QueryExecutor
from druid_tpu.engine.filters import (DeviceBitmapNode, collect_bitmap_nodes,
                                      filter_bitmap_stats, host_mask,
                                      plan_filter, simplify_node)
from druid_tpu.query import filters as F
from druid_tpu.utils.intervals import Interval

IV = Interval.of("2026-05-01", "2026-05-05")

SCHEMA = (
    ColumnSpec("dLo", "string", cardinality=8),       # dense leaves
    ColumnSpec("dMid", "string", cardinality=60),
    ColumnSpec("dHi", "string", cardinality=800),     # sparse leaves
    ColumnSpec("metLong", "long", low=0, high=1000),
    ColumnSpec("metDouble", "double", low=0.0, high=1.0),
)


@pytest.fixture(scope="module")
def fb_segments():
    # 3333 rows/segment: n_rows not divisible by 32 (word-boundary rows)
    return DataGenerator(SCHEMA, seed=13).segments(
        4, 3333, IV, datasource="fb")


@pytest.fixture(autouse=True)
def _bitmap_on():
    # this module tests the STAGED device-bitmap path (fill wave + resident
    # combined words); the megakernel would fuse cold per-segment filters
    # inline and skip the combined-words cache entirely — its own behavior
    # is covered by tests/test_megakernel.py
    from druid_tpu.engine import megakernel
    prev = filters_mod.set_device_bitmap_enabled(True)
    prev_mega = megakernel.set_enabled(False)
    yield
    filters_mod.set_device_bitmap_enabled(prev)
    megakernel.set_enabled(prev_mega)


def _rand_leaf(rng, seg):
    dim = ("dLo", "dMid", "dHi")[rng.integers(3)]
    vals = list(seg.dims[dim].dictionary.values)
    kind = rng.integers(3)
    if kind == 0:
        v = vals[rng.integers(len(vals))] if rng.random() < 0.85 \
            else "zzz-missing"
        return F.SelectorFilter(dim, v)
    if kind == 1:
        k = int(rng.integers(1, 5))
        picks = [vals[rng.integers(len(vals))] for _ in range(k)]
        return F.InFilter(dim, tuple(picks))
    lo = vals[rng.integers(len(vals))]
    hi = vals[rng.integers(len(vals))]
    lo, hi = (lo, hi) if lo <= hi else (hi, lo)
    return F.BoundFilter(dim, lower=lo, upper=hi,
                         lower_strict=bool(rng.integers(2)))


def _rand_tree(rng, seg, depth):
    if depth == 0 or rng.random() < 0.35:
        return _rand_leaf(rng, seg)
    op = rng.integers(3)
    if op == 0:
        return F.NotFilter(_rand_tree(rng, seg, depth - 1))
    kids = tuple(_rand_tree(rng, seg, depth - 1)
                 for _ in range(int(rng.integers(2, 4))))
    return F.AndFilter(kids) if op == 1 else F.OrFilter(kids)


def _query(flt):
    q = {"queryType": "timeseries", "dataSource": "fb",
         "intervals": [str(IV)], "granularity": "all",
         "aggregations": [
             {"type": "count", "name": "n"},
             {"type": "longSum", "name": "s", "fieldName": "metLong"},
             {"type": "doubleSum", "name": "d", "fieldName": "metDouble"}]}
    if flt is not None:
        q["filter"] = flt.to_json()
    return q


def _oracle_count(flt, segs):
    return sum(int(host_mask(flt, s).sum()) for s in segs)


def test_random_tree_parity_gate(fb_segments):
    """host-mask vs device-bitmap vs per-segment vs batched: exact equality
    including float aggregates, counts pinned to the numpy host-mask oracle."""
    rng = np.random.default_rng(99)
    ex = QueryExecutor(fb_segments)
    for i in range(14):
        flt = _rand_tree(rng, fb_segments[0], depth=4 if i % 2 else 2)
        q = _query(flt)
        batched = ex.run_json(q)                     # device bitmap + batch
        pb = batching.set_enabled(False)
        try:
            per_segment = ex.run_json(q)             # device bitmap, no batch
            prev = filters_mod.set_device_bitmap_enabled(False)
            try:
                host = ex.run_json(q)                # LUT/host-mask path
            finally:
                filters_mod.set_device_bitmap_enabled(prev)
        finally:
            batching.set_enabled(pb)
        assert batched == per_segment == host, f"tree {i}: {flt}"
        got_n = batched[0]["result"]["n"] if batched else 0
        assert got_n == _oracle_count(flt, fb_segments), f"tree {i}"


def test_mixed_tree_partial_rewrite_parity(fb_segments):
    """AND of a bitmap subtree and a numeric (non-bitmap) predicate: only
    the eligible branch compiles to words; results stay exact."""
    vals = fb_segments[0].dims["dMid"].dictionary.values
    flt = F.AndFilter((
        F.OrFilter((F.SelectorFilter("dLo",
                                     fb_segments[0].dims["dLo"]
                                     .dictionary.values[2]),
                    F.InFilter("dMid", tuple(vals[:4])))),
        F.BoundFilter("metLong", lower=100, upper=900, ordering="numeric"),
    ))
    node = simplify_node(plan_filter(flt, fb_segments[0]))
    bns = collect_bitmap_nodes(node)
    assert len(bns) == 1                    # the string branch, not the root
    assert node.required_device_columns() == {"metLong"}
    ex = QueryExecutor(fb_segments)
    q = _query(flt)
    on = ex.run_json(q)
    prev = filters_mod.set_device_bitmap_enabled(False)
    try:
        off = ex.run_json(q)
    finally:
        filters_mod.set_device_bitmap_enabled(prev)
    assert on == off
    assert on[0]["result"]["n"] == _oracle_count(flt, fb_segments)


def test_filter_only_dims_are_not_staged(fb_segments):
    """The staging win: a dim referenced ONLY by the filter compiles to
    resident words (1 bit/row) — no id column staging at all."""
    seg = fb_segments[0]
    flt = F.InFilter("dHi", tuple(seg.dims["dHi"].dictionary.values[:5]))
    node = simplify_node(plan_filter(flt, seg))
    assert isinstance(node, DeviceBitmapNode)
    assert node.required_device_columns() == set()
    from druid_tpu.engine.grouping import needed_columns
    _, columns = needed_columns(seg, [], [], flt, (), filter_node=node)
    assert "dHi" not in columns


def test_result_cache_hits_skip_rebuild():
    """Warm queries hit resident words: the filter structural signature +
    segment identity + aux digest key the pool like the jit caches.
    A DEDICATED segment: the pool is session-global and owner-keyed, so a
    shared fixture segment could already hold entries from earlier tests."""
    seg = DataGenerator(SCHEMA, seed=77).segments(
        1, 3333, IV, datasource="fb")[0]
    vals = seg.dims["dLo"].dictionary.values
    flt = F.NotFilter(F.SelectorFilter("dLo", vals[0]))
    ex = QueryExecutor([seg])
    q = _query(flt)
    ex.run_json(q)
    s0 = filter_bitmap_stats().snapshot()
    r1 = ex.run_json(q)
    s1 = filter_bitmap_stats().snapshot()
    assert s1["hits"] == s0["hits"] + 1          # resident words reused
    assert s1["misses"] == s0["misses"]
    assert s1["builtBytes"] == s0["builtBytes"]
    # a DIFFERENT value set (same structure) is a different aux digest
    flt2 = F.NotFilter(F.SelectorFilter("dLo", vals[1]))
    ex.run_json(_query(flt2))
    s2 = filter_bitmap_stats().snapshot()
    assert s2["misses"] == s1["misses"] + 1
    assert r1 == ex.run_json(q)


def test_opt_out_plans_column_path(fb_segments):
    seg = fb_segments[0]
    flt = F.SelectorFilter("dLo", seg.dims["dLo"].dictionary.values[0])
    prev = filters_mod.set_device_bitmap_enabled(False)
    try:
        node = simplify_node(plan_filter(flt, seg))
    finally:
        filters_mod.set_device_bitmap_enabled(prev)
    assert not collect_bitmap_nodes(node)
    # and the explicit arg overrides the process default both ways
    assert collect_bitmap_nodes(simplify_node(
        plan_filter(flt, seg, device_bitmap=True)))
    assert not collect_bitmap_nodes(simplify_node(
        plan_filter(flt, seg, device_bitmap=False)))


def test_fill_program_sparse_scatter_and_xor(fb_segments):
    """The word-wise algebra program directly: sparse id lists scatter into
    words on device, dense words pass through, AND/OR/NOT/XOR combine
    word-wise — against the numpy truth."""
    import jax
    from druid_tpu.data.bitmap import Bitmap, device_repr
    from druid_tpu.engine.filters import _build_fill_fn
    padded = 2048
    rng = np.random.default_rng(4)
    a = rng.random(padded) < 0.004                  # sparse
    b = rng.random(padded) < 0.5                    # dense
    ka, pa = device_repr(SparseBitmap(
        np.flatnonzero(a).astype(np.int32), padded), padded)
    kb, pb = device_repr(Bitmap.from_bool(b), padded)
    assert (ka, kb) == ("sparse", "dense")
    for op, truth in (("and", a & b), ("or", a | b), ("xor", a ^ b),
                      ("not", ~a)):
        structure = ("not", ("leaf", 0)) if op == "not" \
            else (op, (("leaf", 0), ("leaf", 1)))
        kinds = ((ka, pa.shape[0]),) if op == "not" \
            else ((ka, pa.shape[0]), (kb, pb.shape[0]))
        leaves = (jax.device_put(pa),) if op == "not" \
            else (jax.device_put(pa), jax.device_put(pb))
        words = np.asarray(_build_fill_fn(structure, kinds, padded // 32)(
            leaves))
        rows = np.arange(padded)
        bits = (words[rows // 32] >> (rows % 32).astype(np.uint32)) & 1
        assert np.array_equal(bits.astype(bool), truth), op


# ---------------------------------------------------------------------------
# batching widenings: the workload classes the host-mask path excluded
# ---------------------------------------------------------------------------

def _parity_on_off_batching(ex, q):
    before = batching.stats().snapshot()
    on = ex.run_json(q)
    after = batching.stats().snapshot()
    pb = batching.set_enabled(False)
    try:
        off = ex.run_json(q)
    finally:
        batching.set_enabled(pb)
    assert on == off
    return after["batches"] - before["batches"], \
        after["batchedSegments"] - before["batchedSegments"]


def _hll_segments(n_segments=4, log2m=6):
    """Rolled-up segments carrying a REAL 2-D complex metric column (HLL
    registers) — the workload class `m.values.ndim != 1` used to exclude
    from batching."""
    from druid_tpu.ingest.incremental import IncrementalIndex
    from druid_tpu.query.aggregators import (CountAggregator,
                                             HyperUniqueAggregator)
    specs = [CountAggregator("count"),
             HyperUniqueAggregator("uu", "user", log2m=log2m)]
    t0 = IV.start
    segs = []
    for p in range(n_segments):
        idx = IncrementalIndex("hll", IV, specs, dimensions=["d"],
                               query_granularity="hour")
        for i in range(300):
            idx.add({"timestamp": t0 + i * 1000, "d": f"x{i % 5}",
                     "user": f"u{p}_{i % 40}"})
        segs.append(idx.to_segment(partition=p))
    return segs


def test_complex_2d_metric_columns_take_batched_path():
    """A pre-aggregated HLL register column (ndim == 2) stacks fine now
    that the mask is in-program: the hyperUnique query over rolled-up
    segments batches with exact parity."""
    segs = _hll_segments()
    assert np.asarray(segs[0].metrics["uu"].values).ndim == 2
    q = {"queryType": "groupBy", "dataSource": "hll",
         "intervals": [str(IV)], "granularity": "all",
         "dimensions": ["d"],
         "filter": {"type": "not", "field": {"type": "selector",
                                             "dimension": "d",
                                             "value": "x0"}},
         "aggregations": [
             {"type": "hyperUnique", "name": "u", "fieldName": "uu",
              "log2m": 6},
             {"type": "longSum", "name": "n", "fieldName": "count"}]}
    ex = QueryExecutor(segs)
    batches, n_batched = _parity_on_off_batching(ex, q)
    assert batches >= 1 and n_batched == len(segs)


def test_register_width_is_a_shape_bucket_key():
    """The 2-D column's width is a compile shape: two segments differing
    only in register width must land in DIFFERENT shape buckets (a fused
    chunk would stack mismatched shapes). hyperUnique itself rejects a
    width-mismatched query outright, so this pins the digest directly."""
    from druid_tpu.engine.batching import _plan_for
    from druid_tpu.query.aggregators import HyperUniqueAggregator
    from druid_tpu.query.model import query_from_json
    a = _hll_segments(1, log2m=6)[0]
    b = _hll_segments(1, log2m=7)[0]
    assert np.asarray(a.metrics["uu"].values).shape[1] != \
        np.asarray(b.metrics["uu"].values).shape[1]
    plans = [_plan_for(s, [], 0, [IV], query_from_json(
        {"queryType": "timeseries", "dataSource": "hll",
         "intervals": [str(IV)], "granularity": "all",
         "aggregations": []}).granularity,
        [HyperUniqueAggregator("u", "uu", log2m=lg)], None, ())
        for s, lg in ((a, 6), (b, 7))]
    assert all(p.eligible for p in plans)
    assert plans[0].digest != plans[1].digest


def test_query_time_dictionaries_take_batched_path(fb_segments):
    """Numeric dimensions (per-segment query-time dictionaries) batch: id
    spaces unify across the query's segments (engines.unify_query_dims),
    with exact parity against the per-segment path."""
    q = {"queryType": "groupBy", "dataSource": "fb",
         "intervals": [str(IV)], "granularity": "all",
         "dimensions": ["metLong"],
         "filter": {"type": "bound", "dimension": "metLong", "lower": 0,
                    "upper": 40, "ordering": "numeric"},
         "aggregations": [{"type": "count", "name": "n"},
                          {"type": "doubleSum", "name": "d",
                           "fieldName": "metDouble"}]}
    ex = QueryExecutor(fb_segments)
    batches, segs = _parity_on_off_batching(ex, q)
    assert batches >= 1 and segs == len(fb_segments)


def test_different_bitmap_filters_fuse_into_one_chunk(fb_segments):
    """Two queries with DIFFERENT bitmap filters share one program
    structure (resident words + bit test) and therefore one fused chunk —
    per-slot words carry each query's own filter."""
    from druid_tpu.engine.engines import make_aggregate_partials_multi
    vals = fb_segments[0].dims["dLo"].dictionary.values
    from druid_tpu.query.model import query_from_json
    q1 = query_from_json(_query(F.SelectorFilter("dLo", vals[0])))
    q2 = query_from_json(_query(
        F.NotFilter(F.InFilter("dLo", tuple(vals[1:3])))))
    seen = []
    out = make_aggregate_partials_multi(
        [(q1, fb_segments, None), (q2, fb_segments, None)],
        on_batch=lambda nq, ns, fill: seen.append((nq, ns)))
    assert not any(isinstance(o, BaseException) for o in out)
    assert any(nq == 2 and ns == 2 * len(fb_segments) for nq, ns in seen), \
        seen
    # parity of the fused results against serial single-query execution
    from druid_tpu.engine.engines import make_aggregate_partials
    serial1 = make_aggregate_partials(q1, fb_segments, clamp=False)
    assert len(out[0].partials) == len(serial1.partials)
    for a, b in zip(out[0].partials, serial1.partials):
        assert np.array_equal(a.counts, b.counts)
        for k in a.states:
            assert np.array_equal(np.asarray(a.states[k]),
                                  np.asarray(b.states[k]))


def test_staging_wave_dedups_identical_filters():
    """N fused copies of the same dashboard query build the words ONCE:
    duplicates in one wave count as hits and share the resident array."""
    from druid_tpu.engine.filters import stage_device_bitmaps_multi
    seg = DataGenerator(SCHEMA, seed=88).segments(
        1, 2048, IV, datasource="fbd")[0]
    flt = F.InFilter("dLo", tuple(seg.dims["dLo"].dictionary.values[:2]))
    node = simplify_node(plan_filter(flt, seg))
    s0 = filter_bitmap_stats().snapshot()
    out = stage_device_bitmaps_multi([(seg, node)] * 3, 2048)
    s1 = filter_bitmap_stats().snapshot()
    assert s1["misses"] - s0["misses"] == 1
    assert s1["hits"] - s0["hits"] == 2
    assert s1["builtBytes"] - s0["builtBytes"] == 2048 // 8
    assert out[0][node.col] is out[1][node.col] is out[2][node.col]


def test_monitor_names_declared_and_emitting(fb_segments):
    from druid_tpu.obs import catalog
    from druid_tpu.engine.filters import FilterBitmapMonitor

    class Rec:
        def __init__(self):
            self.seen = {}

        def metric(self, name, value, **dims):
            self.seen[name] = value

    ex = QueryExecutor([fb_segments[0]])
    ex.run_json(_query(F.SelectorFilter(
        "dLo", fb_segments[0].dims["dLo"].dictionary.values[3])))
    mon = FilterBitmapMonitor()
    rec = Rec()
    mon.do_monitor(rec)
    assert not catalog.validate_emitted(rec.seen)
    assert set(rec.seen) == {"query/filter/deviceBitmapHits",
                             "query/filter/deviceBitmapMisses",
                             "query/filter/bytes"}


def test_pool_peek_does_not_touch_stats(fb_segments):
    seg = fb_segments[0]
    pool = seg._pool
    base = pool.snapshot()
    assert seg.device_contains(("nope", 1)) is False
    s = pool.snapshot()
    assert (s.hits, s.misses) == (base.hits, base.misses)
