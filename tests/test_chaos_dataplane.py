"""Data-plane chaos scenario suite (cluster/chaos.py) — the acceptance
gate of the fault-tolerance layer: under every injected fault class, a
query returns EXACT results (bit-identical to the fault-free oracle), a
TYPED partial with an accurate missingSegments report, or a TYPED error —
inside its deadline, never a hang, never a silently wrong answer. Plus
the hedge parity gate: hedged execution is bit-identical to unhedged and
no segment's partial merges twice, with the loser's cancellation observed
through the remote-cancel hook."""
import threading
import time

import pytest

from druid_tpu.cluster.chaos import (TYPED_ERRORS, ChaosDataNode,
                                     DataPlaneChaosHarness, FaultSpec)
from druid_tpu.cluster.resilience import ResiliencePolicy
from druid_tpu.cluster.view import DataNode
from druid_tpu.query.aggregators import (CountAggregator,
                                         DoubleSumAggregator,
                                         LongSumAggregator)
from druid_tpu.query.model import (DefaultDimensionSpec, GroupByQuery,
                                   ScanQuery, TimeseriesQuery)
from druid_tpu.server.querymanager import (QueryCapacityError,
                                           QueryTimeoutError)
from druid_tpu.utils.intervals import Interval

WEEK = Interval.of("2026-01-01", "2026-01-08")
#: float aggregation keeps the bit-parity gate honest — a double-merged
#: partial or reordered merge shows up in the double sum bits
AGGS = [CountAggregator("rows"), LongSumAggregator("ls", "metLong"),
        DoubleSumAggregator("ds", "metDouble")]

_QID = [0]


def _ctx(**extra):
    _QID[0] += 1
    return {"timeout": 15_000, "queryId": f"chaos-{_QID[0]}", **extra}


def _ts(**extra):
    return TimeseriesQuery.of("test", [WEEK], AGGS, granularity="day",
                              context=_ctx(**extra))


def _gb(**extra):
    return GroupByQuery.of("test", [WEEK],
                           [DefaultDimensionSpec("dimA")], AGGS,
                           granularity="day", context=_ctx(**extra))


@pytest.fixture()
def harness(segments):
    h = DataPlaneChaosHarness(segments, n_nodes=3, replication=2, seed=11)
    yield h
    h.stop()


# ---------------------------------------------------------------------------
# the scenario matrix: one faulted node, replication covers it → EXACT
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("spec", [
    FaultSpec("dead"),
    FaultSpec("flap", flap_period=1),
    FaultSpec("error"),
    FaultSpec("shed", retry_after_s=0.01),
    FaultSpec("slow", delay_ms=120),
    FaultSpec("slow", delay_ms=60, heavy_tail_ms=250, tail_prob=0.4),
], ids=["dead", "flap", "error", "shed", "slow", "slow-heavy-tail"])
def test_single_fault_recovers_exact(harness, spec):
    """One sick replica out of two must never cost correctness: the
    query completes within its deadline with bit-exact results."""
    harness.fault("chaos0", spec)
    for q in (_ts(), _gb()):
        o = harness.run_classified(q)
        assert o.kind == "exact", (o.kind, o.error)
        assert o.elapsed_s < 15.0
        harness.verify(q, o)


def test_scenarios_are_seeded_deterministic():
    """The harness's randomness is per-node seeded: two gates built with
    the same seed replay identical latency draws (the heavy tail hits
    the same calls)."""
    import druid_tpu.cluster.chaos as chaos_mod
    spec = FaultSpec("slow", delay_ms=1, heavy_tail_ms=50, tail_prob=0.3)
    q = _ts()

    def draws(seed):
        node = ChaosDataNode(DataNode("x"), seed=seed)
        node.fault(spec)
        seen = []
        real_sleep = time.sleep
        chaos_mod.time.sleep = lambda s: seen.append(round(s, 6))
        try:
            for _ in range(30):
                node.run_partials(q, [])
        finally:
            chaos_mod.time.sleep = real_sleep
        return seen

    a, b = draws(5), draws(5)
    assert a == b
    assert len(set(a)) == 2, "both the fixed and the heavy-tail delay hit"
    assert draws(6) != a


# ---------------------------------------------------------------------------
# hang: the no-hang contract
# ---------------------------------------------------------------------------

def test_hang_node_hedge_rescues_within_deadline(segments):
    """A hung replica's segments are hedged onto the other replica; the
    query completes exactly — and the hung loser is cancelled through
    the remote-cancel hook, releasing it."""
    pol = ResiliencePolicy(hedge_min_delay_ms=40,
                           hedge_latency_multiplier=2.0)
    h = DataPlaneChaosHarness(segments, seed=3, policy=pol)
    try:
        warm = _ts()
        h.verify(warm, h.run_classified(warm))     # warm compile + EWMA
        h.fault("chaos0", FaultSpec("hang", max_hang_s=30.0))
        q = _ts(timeout=5_000)
        o = h.run_classified(q)
        assert o.kind == "exact", (o.kind, o.error)
        assert o.elapsed_s < 5.0
        h.verify(q, o)
        stats = h.broker.resilience.stats.snapshot()
        assert stats["hedges_issued"] >= 1
        assert stats["hedges_won"] >= 1
        # loser cancellation observed at the hung node (remote-cancel)
        deadline = time.monotonic() + 2.0
        while time.monotonic() < deadline \
                and not h.nodes["chaos0"].cancel_calls:
            time.sleep(0.01)
        assert h.nodes["chaos0"].cancel_calls
    finally:
        h.heal()
        h.stop()


def test_hang_everywhere_degrades_to_typed_partial(segments):
    h = DataPlaneChaosHarness(segments, seed=4)
    try:
        warm = _ts()
        h.verify(warm, h.run_classified(warm))
        for name in h.nodes:
            h.fault(name, FaultSpec("hang", max_hang_s=30.0))
        q = _ts(timeout=900, allowPartialResults=True, hedge=False)
        t0 = time.monotonic()
        o = h.run_classified(q)
        assert o.kind == "partial", (o.kind, o.error)
        assert time.monotonic() - t0 < 3.0, "no hang: deadline bounds it"
        assert set(o.missing) == {str(s.id) for s in segments}
        h.verify(q, o)
    finally:
        h.heal()
        h.stop()


def test_hang_everywhere_strict_is_typed_timeout(segments):
    h = DataPlaneChaosHarness(segments, seed=5)
    try:
        warm = _ts()
        h.verify(warm, h.run_classified(warm))
        for name in h.nodes:
            h.fault(name, FaultSpec("hang", max_hang_s=30.0))
        q = _ts(timeout=900, hedge=False)
        t0 = time.monotonic()
        o = h.run_classified(q)
        assert o.kind == "error"
        assert isinstance(o.error, QueryTimeoutError)
        assert time.monotonic() - t0 < 3.0
    finally:
        h.heal()
        h.stop()


# ---------------------------------------------------------------------------
# storms on EVERY replica: typed error, or typed partial when allowed
# ---------------------------------------------------------------------------

def test_429_storm_surfaces_typed_capacity_error(harness):
    for name in harness.nodes:
        harness.fault(name, FaultSpec("shed", retry_after_s=0.01))
    o = harness.run_classified(_ts())
    assert o.kind == "error"
    assert isinstance(o.error, QueryCapacityError)


def test_429_storm_with_partials_degrades(harness):
    for name in harness.nodes:
        harness.fault(name, FaultSpec("shed", retry_after_s=0.01))
    q = _ts(allowPartialResults=True)
    o = harness.run_classified(q)
    assert o.kind == "partial"
    harness.verify(q, o)


def test_error_storm_surfaces_the_node_error(harness):
    for name in harness.nodes:
        harness.fault(name, FaultSpec("error"))
    o = harness.run_classified(_ts())
    assert o.kind == "error"
    assert "error storm" in str(o.error)


def test_dead_cluster_with_partials_returns_typed_empty(harness, segments):
    for name in harness.nodes:
        harness.fault(name, FaultSpec("dead"))
    q = _ts(allowPartialResults=True)
    o = harness.run_classified(q)
    assert o.kind == "partial" and o.rows == []
    assert set(o.missing) == {str(s.id) for s in segments}
    harness.verify(q, o)


# ---------------------------------------------------------------------------
# the hedge parity gate
# ---------------------------------------------------------------------------

def test_hedge_parity_gate(segments):
    """Hedging forced on under a slow-replica fault: merged results are
    bit-identical to unhedged execution AND to the oracle (a double-
    merged segment partial would break both), the hedge win and the
    loser's remote cancellation are observed."""
    slow = FaultSpec("slow", delay_ms=400)
    hedge_on = ResiliencePolicy(hedge_min_delay_ms=30,
                                hedge_latency_multiplier=1.0)
    hedge_off = ResiliencePolicy(hedge_enabled=False)
    results = {}
    for label, pol in (("hedged", hedge_on), ("unhedged", hedge_off)):
        h = DataPlaneChaosHarness(segments, seed=21, policy=pol)
        try:
            warm = _gb()
            h.verify(warm, h.run_classified(warm))
            h.fault("chaos0", slow)
            q = _gb(timeout=20_000)
            o = h.run_classified(q)
            assert o.kind == "exact", (label, o.kind, o.error)
            h.verify(q, o)                 # bit-parity vs the oracle
            results[label] = o.rows
            if label == "hedged":
                stats = h.broker.resilience.stats.snapshot()
                assert stats["hedges_issued"] >= 1
                assert stats["hedges_won"] >= 1
                # the loser (slow straggler) was cancelled via the
                # remote-cancel hook and observed at the node
                deadline = time.monotonic() + 2.0
                while time.monotonic() < deadline and not any(
                        n.cancel_calls for n in h.nodes.values()):
                    time.sleep(0.01)
                assert any(n.cancel_calls for n in h.nodes.values())
                assert stats["hedges_cancelled"] >= 1
            else:
                assert h.broker.resilience.stats.snapshot()[
                    "hedges_issued"] == 0
        finally:
            h.heal()
            h.stop()
    assert results["hedged"] == results["unhedged"], \
        "hedged merge diverged from unhedged execution"


# ---------------------------------------------------------------------------
# row path under fault
# ---------------------------------------------------------------------------

def test_scan_rows_path_survives_dead_replica(harness, segments):
    harness.fault("chaos0", FaultSpec("dead"))
    q = ScanQuery.of("test", [WEEK], columns=("dimA", "metLong"),
                     context=_ctx())
    rows = harness.broker.run(q)
    expect = harness.oracle(q)
    assert sum(len(b["events"]) for b in rows) == \
        sum(len(b["events"]) for b in expect)


def test_scan_partial_reports_missing(segments):
    h = DataPlaneChaosHarness(segments, n_nodes=1, replication=1, seed=9)
    try:
        h.fault("chaos0", FaultSpec("dead"))
        q = ScanQuery.of("test", [WEEK], columns=("dimA",),
                         context=_ctx(allowPartialResults=True))
        o = h.run_classified(q)
        assert o.kind == "partial" and o.rows == []
        assert set(o.missing) == {str(s.id) for s in segments}
    finally:
        h.stop()


# ---------------------------------------------------------------------------
# flap + heal: the cluster converges back
# ---------------------------------------------------------------------------

def test_chaos_gate_wraps_checkless_clients():
    """Review regression: remote clients (RemoteDataNodeClient) take no
    check kwarg — the gate must not forward one it wasn't given."""

    class _ChecklessClient:
        name, tier, alive = "remote", "_default_tier", True

        def run_partials(self, query, segment_ids):
            return "ap", set(segment_ids)

    node = ChaosDataNode(_ChecklessClient(), seed=0)
    assert node.run_partials(_ts(), ["s1"]) == ("ap", {"s1"})

    def checked(query, segment_ids, check=None):
        return ("checked", check)

    node.inner.run_partials = checked
    probe = object()
    assert node.run_partials(_ts(), [], check=probe) == ("checked", probe)


def test_node_side_interrupt_surfaces_typed(segments):
    """Review regression: a node-side cancellation (not our loser-cancel,
    not a broker DELETE) must abort with the interrupt — never degrade
    into MissingSegmentsError blaming replica availability."""
    from druid_tpu.server.querymanager import QueryInterruptedError

    class _InterruptedNode(DataNode):
        def run_partials(self, query, segment_ids, check=None):
            raise QueryInterruptedError("cancelled node-side")

    from druid_tpu.cluster import Broker, InventoryView, descriptor_for
    view = InventoryView()
    n = _InterruptedNode("n1")
    view.register(n)
    for s in segments:
        n.load_segment(s)
        view.announce("n1", descriptor_for(s))
    broker = Broker(view)
    with pytest.raises(QueryInterruptedError):
        broker.run(_ts(hedge=False))
    broker.stop()


def test_heal_restores_exact_service_and_closes_circuits(harness):
    harness.fault("chaos0", FaultSpec("dead"))
    q1 = _ts()
    for _ in range(4):                    # enough failures to trip
        o = harness.run_classified(q1)
        assert o.kind == "exact"
    harness.heal("chaos0")
    # cooldown is policy-default seconds; the probe path needs no wait
    # when the other replicas keep serving — assert service stays exact
    q2 = _gb()
    o = harness.run_classified(q2)
    assert o.kind == "exact"
    harness.verify(q2, o)


# ---------------------------------------------------------------------------
# long-poll hang scenarios: the subscription fan-out under slow/stalled/
# torn-down consumers. The query scatter above proves the request path
# stays bounded when NODES wedge; these prove it when the CLIENT side of
# a standing subscription wedges — a poll must park bounded by the hub's
# clamp (never the wire's ask), a mid-poll teardown must free the waiter
# with a typed error, and the tick driver must wake parked polls within
# their deadline. Under DRUID_TPU_STALL_WITNESS=1 every park these tests
# provoke is additionally checked to be timed.
# ---------------------------------------------------------------------------

def _sub_rig():
    import numpy as np

    from druid_tpu.cluster.metadata import MetadataStore
    from druid_tpu.ingest import (Appenderator, RowBatch, SegmentAllocator,
                                  StreamAppenderatorDriver)
    from druid_tpu.query.aggregators import LongSumAggregator
    from druid_tpu.server.subscriptions import SubscriptionHub

    day = Interval.of("2026-03-01", "2026-03-02")
    md = MetadataStore()
    app = Appenderator("rt", [CountAggregator("rows"),
                              LongSumAggregator("v", "value")],
                       query_granularity="none")
    driver = StreamAppenderatorDriver(app, SegmentAllocator(md, "day"), md)
    hub = SubscriptionHub(idle_timeout_s=0)
    hub.attach(app)
    rng = np.random.default_rng(7)

    def feed(n, off=0):
        ts = [int(day.start + (off + i) * 1000) for i in range(n)]
        driver.add_batch(RowBatch(ts, {
            "page": [f"p{int(x)}" for x in rng.integers(5, size=n)],
            "value": [int(x) for x in rng.integers(10, size=n)]}))

    q = TimeseriesQuery.of(
        "rt", [day],
        [LongSumAggregator("rows", "rows"), LongSumAggregator("v", "v")],
        granularity="all")
    return hub, feed, q


def test_slow_consumer_poll_parks_clamped_not_wire_bounded():
    """A consumer that asks for an hour of long-poll parks for the hub's
    clamp, not the hour: the 304 path re-arms in bounded quanta and
    returns unchanged at MAX_POLL_TIMEOUT_S — the PR 14 regression gate,
    now driven through a live hub."""
    hub, feed, q = _sub_rig()
    try:
        sid, etag = hub.subscribe(q)
        feed(100)
        hub.tick()
        _rows, etag, _ch = hub.poll(sid, etag=None)
        hub.MAX_POLL_TIMEOUT_S = 0.5      # instance override: fast test
        t0 = time.monotonic()
        rows, new_etag, changed = hub.poll(sid, etag=etag,
                                           timeout_s=3600.0)
        elapsed = time.monotonic() - t0
        assert not changed and rows is None and new_etag == etag
        assert 0.4 <= elapsed < 5.0, (
            f"poll parked {elapsed:.2f}s against a 0.5s clamp")
    finally:
        hub.stop()


def test_mid_poll_hub_teardown_frees_waiter_with_typed_error():
    """stop() while a consumer is parked mid-poll must wake the waiter
    promptly with UnknownSubscriptionError (the subscription is being
    torn down), never leave it parked out the rest of its timeout — and
    the waiter thread must be joinable immediately after."""
    from druid_tpu.server.subscriptions import UnknownSubscriptionError

    hub, feed, q = _sub_rig()
    sid, etag = hub.subscribe(q)
    feed(50)
    hub.tick()
    _rows, etag, _ch = hub.poll(sid, etag=None)
    outcome = []

    def poller():
        try:
            outcome.append(hub.poll(sid, etag=etag, timeout_s=30.0))
        except UnknownSubscriptionError as e:
            outcome.append(e)

    t = threading.Thread(target=poller, name="chaos-slow-poller")
    t.start()
    time.sleep(0.2)                       # let the poller park on the 304
    t0 = time.monotonic()
    hub.stop()
    t.join(timeout=5.0)
    elapsed = time.monotonic() - t0
    assert not t.is_alive(), "mid-poll teardown leaked the waiter"
    assert elapsed < 5.0, f"teardown took {elapsed:.2f}s to free the waiter"
    assert len(outcome) == 1
    assert isinstance(outcome[0], UnknownSubscriptionError)


def test_tick_hook_wakes_parked_poll_within_deadline():
    """The scheduler-driven tick path: a poll parked on an unchanged etag
    is woken by the tick hook observing new data — well inside its
    deadline, not at quantum granularity × retries. Teardown removes the
    hook from the driver (the standing tick-hook leak gate)."""
    class _TickDriver:
        def __init__(self):
            self.hooks = []
            self._stop = threading.Event()
            self._t = None

        def add_tick_hook(self, fn):
            self.hooks.append(fn)

        def remove_tick_hook(self, fn):
            self.hooks.remove(fn)

        def start(self):
            def loop():
                while not self._stop.wait(0.05):
                    for fn in list(self.hooks):
                        fn()
            self._t = threading.Thread(target=loop, name="chaos-ticker")
            self._t.start()

        def stop(self):
            self._stop.set()
            self._t.join(timeout=5.0)
            assert not self._t.is_alive()

    hub, feed, q = _sub_rig()
    driver = _TickDriver()
    hub.drive_with(driver)
    driver.start()
    try:
        sid, etag = hub.subscribe(q)
        feed(60)
        deadline_wait = time.monotonic() + 10.0
        while time.monotonic() < deadline_wait:
            rows, etag, changed = hub.poll(sid, etag=etag, timeout_s=0.0)
            if changed:
                break
            time.sleep(0.05)
        # parked poll now: the NEXT feed must wake it through the hook
        t0 = time.monotonic()
        feed(40, off=60)
        rows, new_etag, changed = hub.poll(sid, etag=etag, timeout_s=10.0)
        elapsed = time.monotonic() - t0
        assert changed and rows is not None
        assert elapsed < 5.0, (
            f"tick hook took {elapsed:.2f}s to wake a 10s poll")
    finally:
        hub.stop()
        driver.stop()
        assert driver.hooks == [], "hub.stop() left its tick hook behind"
