"""Autoscaling strategy, push firehose, replica selection strategies, and
per-segment query metrics (reference: PendingTaskBasedWorker
ProvisioningStrategy, EventReceiverFirehoseFactory,
ConnectionCountServerSelectorStrategy, MetricsEmittingQueryRunner)."""
import json
import urllib.request

import pytest

from druid_tpu.cluster import (Broker, DataNode, InventoryView,
                               descriptor_for)
from druid_tpu.cluster.view import (ConnectionCountServerSelectorStrategy,
                                    TierPreferenceStrategy)
from druid_tpu.engine import QueryExecutor
from druid_tpu.indexing import (IndexTask, Overlord,
                                PendingTaskProvisioningStrategy,
                                ProvisioningConfig, ScalingMonitor,
                                WorkerInfo)
from druid_tpu.ingest import EventReceiverFirehose
from druid_tpu.query.aggregators import CountAggregator, LongSumAggregator
from druid_tpu.query.model import TimeseriesQuery
from druid_tpu.utils.intervals import Interval

WEEK = Interval.of("2026-01-01", "2026-01-08")
AGGS = [CountAggregator("rows"), LongSumAggregator("ls", "metLong")]


# ---------------------------------------------------------------------------
# Autoscaling
# ---------------------------------------------------------------------------

def test_provision_on_pending_pressure():
    strat = PendingTaskProvisioningStrategy(ProvisioningConfig(
        max_workers=4, worker_capacity=2, scale_up_step=2))
    workers = [WorkerInfo("w0", capacity=2, running_tasks=2)]
    d = strat.compute(pending_tasks=5, workers=workers, now=1000.0)
    assert d.provision == 2 and d.terminate == []
    # spare capacity absorbs pending → no scaling
    idle = [WorkerInfo("w0", capacity=2, running_tasks=0,
                       last_task_time=999.0)]
    d2 = strat.compute(pending_tasks=1, workers=idle, now=1000.0)
    assert d2.provision == 0 and d2.terminate == []


def test_terminate_idle_respects_min_and_cooldown():
    cfg = ProvisioningConfig(min_workers=1, max_workers=4,
                             idle_seconds_before_terminate=600.0)
    strat = PendingTaskProvisioningStrategy(cfg)
    now = 10_000.0
    workers = [WorkerInfo("w0", running_tasks=0, last_task_time=now - 700),
               WorkerInfo("w1", running_tasks=0, last_task_time=now - 800),
               WorkerInfo("w2", running_tasks=0, last_task_time=now - 10)]
    d = strat.compute(0, workers, now=now)
    # w2 inside cooldown; min_workers=1 keeps one of the idle pair
    assert set(d.terminate) == {"w0", "w1"}
    cfg.min_workers = 2
    d2 = strat.compute(0, workers, now=now)
    assert d2.terminate == ["w1"]      # oldest-idle first


def test_scaling_monitor_applies_decisions():
    created, killed = [], []
    workers = []
    strat = PendingTaskProvisioningStrategy(ProvisioningConfig(
        max_workers=2, worker_capacity=1, scale_up_step=2))
    mon = ScalingMonitor(strat, pending=lambda: 3,
                         workers=lambda: list(workers),
                         provision=lambda n: created.append(n),
                         terminate=lambda ids: killed.extend(ids))
    d = mon.run_once(now=0.0)
    assert created == [2] and d.provision == 2
    assert len(mon.history) == 1


# ---------------------------------------------------------------------------
# Push firehose
# ---------------------------------------------------------------------------

def test_event_receiver_firehose_end_to_end():
    from druid_tpu.cluster import MetadataStore
    from druid_tpu.storage.deep import InMemoryDeepStorage
    fh = EventReceiverFirehose("svc1")
    try:
        t0 = WEEK.start
        events = [{"timestamp": int(t0 + i * 1000), "page": f"p{i % 3}",
                   "value": 1} for i in range(500)]
        for i in range(0, 500, 100):
            body = json.dumps(events[i:i + 100]).encode()
            req = urllib.request.Request(
                fh.url + "/push-events", data=body,
                headers={"Content-Type": "application/json"}, method="POST")
            r = json.loads(urllib.request.urlopen(req, timeout=30).read())
            assert r["eventCount"] == 100
        assert fh.events_received == 500
        # producer signals completion over HTTP
        req = urllib.request.Request(fh.url + "/shutdown", data=b"{}",
                                     method="POST")
        urllib.request.urlopen(req, timeout=30)

        md = MetadataStore()
        ov = Overlord(md, InMemoryDeepStorage())
        task = IndexTask("push_ds", fh, None,
                         [CountAggregator("rows"),
                          LongSumAggregator("v", "value")],
                         segment_granularity="day")
        assert ov.run_task(task).state == "SUCCESS"
        segs = [ov.deep_storage.pull(d) for d in md.used_segments("push_ds")]
        rows = QueryExecutor(segs).run(TimeseriesQuery.of(
            "push_ds", [WEEK],
            [LongSumAggregator("rows", "rows")]))
        assert rows[0]["result"]["rows"] == 500
    finally:
        fh.stop()


def test_event_receiver_rejects_after_close():
    fh = EventReceiverFirehose("svc2")
    try:
        fh.close()
        req = urllib.request.Request(
            fh.url + "/push-events", data=b"[{}]",
            headers={"Content-Type": "application/json"}, method="POST")
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=30)
        assert e.value.code == 409
    finally:
        fh.stop()


# ---------------------------------------------------------------------------
# Selection strategies + per-segment metrics
# ---------------------------------------------------------------------------

def test_connection_count_strategy_prefers_idle(segments):
    view = InventoryView()
    a, b = DataNode("a"), DataNode("b")
    for n in (a, b):
        view.register(n)
        for s in segments:
            n.load_segment(s)
            view.announce(n.name, descriptor_for(s))
    view.connection_started("a")
    view.connection_started("a")
    broker = Broker(view,
                    selector_strategy=ConnectionCountServerSelectorStrategy())
    q = TimeseriesQuery.of("test", [WEEK], AGGS)
    rows = broker.run(q)
    assert rows[0]["result"]["rows"] == sum(s.n_rows for s in segments)
    # with 'a' loaded, 'b' must have been chosen for every segment
    sid = descriptor_for(segments[0]).id
    rs = view.replica_set(sid)
    assert rs.pick(broker.rng,
                   strategy=ConnectionCountServerSelectorStrategy(),
                   view=view) == "b"


def test_tier_preference_strategy(segments):
    view = InventoryView()
    hot = DataNode("hot0", tier="hot")
    cold = DataNode("cold0", tier="cold")
    for n in (hot, cold):
        view.register(n)
        for s in segments:
            n.load_segment(s)
            view.announce(n.name, descriptor_for(s))
    import random
    rs = view.replica_set(descriptor_for(segments[0]).id)
    strat = TierPreferenceStrategy(["hot", "cold"])
    assert rs.pick(random.Random(0), strategy=strat, view=view) == "hot0"
    view.remove_node("hot0")
    rs = view.replica_set(descriptor_for(segments[0]).id)
    assert rs.pick(random.Random(0), strategy=strat, view=view) == "cold0"


def test_per_segment_metrics_emitted(segments):
    from druid_tpu.utils.emitter import Emitter, ServiceEmitter

    class Collect(Emitter):
        def __init__(self):
            self.events = []

        def emit(self, e):
            self.events.append(e)

    sink = Collect()
    node = DataNode("h0", emitter=ServiceEmitter("druid/historical", "h0",
                                                 sink),
                    per_segment_metrics=True)
    view = InventoryView()
    view.register(node)
    for s in segments:
        node.load_segment(s)
        view.announce("h0", descriptor_for(s))
    broker = Broker(view)
    broker.run(TimeseriesQuery.of("test", [WEEK], AGGS))
    names = [e.metric for e in sink.events]
    assert names.count("query/segment/time") == len(segments)
    assert names.count("query/cpu/time") == len(segments)
    segs_seen = {e.dims["segment"] for e in sink.events}
    assert segs_seen == {str(descriptor_for(s).id) for s in segments}
