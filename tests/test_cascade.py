"""Cascaded encodings + code-domain aggregation (data/cascade.py).

The acceptance bar of the cascade PR: cascade-encoded execution is
bit-identical (floats included) to the decoded oracle over groupBy /
timeseries / topN / virtual-column / batched / megakernel paths; the
code-domain paths perform ZERO unpack (trace-time decode counter) and
stage no row-width column; and the fixed-budget residency test holds ≥3x
more segments than packed-only staging on an RLE-friendly shape."""
import numpy as np
import pytest

import druid_tpu.engine  # noqa: F401  (x64 on before jax numerics)
from druid_tpu.data import cascade, devicepool, packed
from druid_tpu.data.devicepool import DeviceSegmentPool, entry_cascade_bytes
from druid_tpu.data.segment import SegmentBuilder
from druid_tpu.engine.executor import QueryExecutor
from druid_tpu.native import lz4block
from druid_tpu.utils.intervals import Interval

IV = Interval.of("2026-06-01", "2026-06-02")


@pytest.fixture
def fresh_pool(monkeypatch):
    pool = DeviceSegmentPool(budget_bytes=1 << 40)
    monkeypatch.setattr(devicepool, "_POOL", pool)
    return pool


def rollup_segments(n=3, rows=2048, card=8, n_dims=2, n_mets=2,
                    float_col=False, seed=0):
    """Rollup-shaped segments: dimension-sorted rows, near-constant time,
    a constant `cnt` metric, run-aligned small-range `mN` metrics, a
    row-random `noise` metric, and optionally a compressible float."""
    rng = np.random.default_rng(seed)
    reps = -(-rows // card)
    segs = []
    for si in range(n):
        b = SegmentBuilder("casc", IV, version="v0", partition=si)
        dims = {f"d{i}": np.repeat(
            [f"v{i}_{j:03d}" for j in range(card)], reps)[:rows].tolist()
            for i in range(n_dims)}
        mets = {"cnt": np.ones(rows, dtype=np.int64),
                "noise": rng.integers(0, 500, rows).astype(np.int64)}
        for i in range(n_mets):
            mets[f"m{i}"] = np.repeat(
                (np.arange(card) * (7 + i)) % 13, reps)[:rows].astype(
                    np.int64)
        if float_col:
            mets["f"] = (np.arange(rows) % 16).astype(np.float32)
        time = IV.start + (np.arange(rows, dtype=np.int64) // 64)
        b.add_columns(time, dims, mets)
        segs.append(b.build())
    return segs


def _run_modes(query_json, segments):
    """(decoded-oracle results, cascade results) — the oracle runs with
    BOTH cascade and packing off (fully decoded staging)."""
    ex = QueryExecutor(segments)
    pc, pk = cascade.set_enabled(False), packed.set_enabled(False)
    try:
        oracle = ex.run_json(query_json)
    finally:
        cascade.set_enabled(pc)
        packed.set_enabled(pk)
    return oracle, ex.run_json(query_json)


# ---------------------------------------------------------------------------
# encoder unit level
# ---------------------------------------------------------------------------

def test_rle_roundtrip_device():
    import jax
    v = np.repeat(np.arange(11, dtype=np.int32), 97)[:1000]
    values, ends = cascade.rle_encode(v)
    assert values.shape == ends.shape and ends[-1] == 1000
    rpad = cascade.pad_pow2(values.shape[0])
    pv = np.zeros(rpad, np.int32)
    pv[: values.shape[0]] = values
    pe = np.full(rpad, 1000, np.int32)
    pe[: ends.shape[0]] = ends
    rc = cascade.RleColumn(jax.device_put(pv), jax.device_put(pe),
                           1000, 1024)
    out = np.asarray(jax.jit(cascade.rle_decode_device)(rc))
    np.testing.assert_array_equal(out[:1000], v)
    np.testing.assert_array_equal(out[1000:], 0)   # staging pad fill


def test_delta_roundtrip_device():
    import jax
    v = np.cumsum(np.random.default_rng(1).integers(
        0, 13, 2048)).astype(np.int32)
    padded = np.zeros(4096, np.int32)
    padded[:2048] = v
    w = packed.width_for(12, 0)
    words, first = cascade.delta_encode(padded, 2048, w)
    dc = cascade.DeltaColumn(jax.device_put(words), jax.device_put(first),
                             w, 4096)
    out = np.asarray(jax.jit(cascade.delta_decode_device)(dc))
    np.testing.assert_array_equal(out[:2048], v)
    np.testing.assert_array_equal(out[2048:], v[-1])  # pad repeats last


@pytest.mark.parametrize("codec", ["python", "best"])
def test_lz4_block_roundtrip(codec):
    rng = np.random.default_rng(2)
    for data in (b"", b"abc", b"a" * 5000,
                 bytes(rng.integers(0, 4, 400).astype(np.uint8)),
                 (np.arange(999, dtype=np.float32) % 7).tobytes(),
                 bytes(rng.integers(0, 256, 256).astype(np.uint8))):
        comp = lz4block.py_compress(data) if codec == "python" \
            else lz4block.compress(data)
        assert lz4block.py_decompress(comp, len(data)) == data
        lits, ll, ml, off = lz4block.tokenize(comp)
        assert int(ll.sum()) + int(ml.sum()) == len(data)
        assert int(ll.sum()) == lits.shape[0]


@pytest.mark.parametrize("dtype", [np.float32, np.float64])
def test_lz4_device_decode_bit_identical(dtype):
    import jax
    vals = ((np.arange(3000) % 21) * 0.5).astype(dtype)
    comp = lz4block.compress(vals.tobytes())
    lits, ll, ml, off = lz4block.tokenize(comp)
    tp = cascade.pad_pow2(ll.shape[0])
    lp = cascade.pad_pow2(max(lits.shape[0], 1))

    def padto(a, n, dt):
        out = np.zeros(n, dtype=dt)
        out[: a.shape[0]] = a
        return jax.device_put(out)
    col = cascade.Lz4Column(padto(lits, lp, np.uint8),
                            padto(ll, tp, np.int32),
                            padto(ml, tp, np.int32),
                            padto(off, tp, np.int32),
                            3000, 4096, np.dtype(dtype).name)
    out = np.asarray(jax.jit(cascade.lz4_decode_device)(col))
    np.testing.assert_array_equal(out[:3000], vals)   # exact, bit-level
    np.testing.assert_array_equal(out[3000:], 0)


def test_plan_is_pure_and_claims_are_exclusive(fresh_pool):
    seg = rollup_segments(1, rows=2048, float_col=True)[0]
    cols = ["d0", "d1", "cnt", "m0", "noise", "f"]
    cascades, packs = cascade.plan_pair(seg, cols)
    by_name = {e[0]: e for e in cascades}
    assert by_name["d0"][1] == "rle"            # sorted dim: RLE
    assert by_name["cnt"][1] == "rle"           # constant metric: 1 run
    assert by_name["m0"][1] == "rle"
    assert by_name["__time_offset"][1] in ("delta", "for")
    assert by_name["f"][1] == "lz4"             # compressible float
    assert "noise" not in by_name               # row-random: no runs
    packed_names = {p[0] for p in packs}
    assert packed_names.isdisjoint(by_name)     # one encoding per column
    assert "noise" in packed_names              # small range still packs
    # purity: identical stats -> identical descriptors, every call
    assert cascade.plan_pair(seg, cols) == (cascades, packs)
    # permuted staging never cascades (a row permutation destroys runs)
    assert cascade.plan_columns(seg, cols, permuted=True) == ()
    prev = cascade.set_enabled(False)
    try:
        assert cascade.plan_columns(seg, cols) == ()
    finally:
        cascade.set_enabled(prev)


def test_wide_time_spread_does_not_cascade(fresh_pool):
    b = SegmentBuilder("casc", IV)
    rng = np.random.default_rng(5)
    t = np.sort(rng.integers(IV.start, IV.end, 512))
    b.add_columns(t, {"d": [f"x{i}" for i in range(512)]},
                  {"m": rng.integers(0, 100, 512).astype(np.int64)})
    seg = b.build()
    assert cascade.plan_column(seg, "__time_offset") is None


# ---------------------------------------------------------------------------
# engine parity (the acceptance bar: exact equality, floats included)
# ---------------------------------------------------------------------------

GROUPBY = {
    "queryType": "groupBy", "dataSource": "casc", "intervals": [str(IV)],
    "granularity": "all", "dimensions": ["d0"],
    "aggregations": [
        {"type": "count", "name": "n"},
        {"type": "longSum", "name": "c", "fieldName": "cnt"},
        {"type": "longSum", "name": "s", "fieldName": "m0"},
        {"type": "longMin", "name": "lm", "fieldName": "noise"},
    ],
    "filter": {"type": "in", "dimension": "d1",
               "values": [f"v1_{j:03d}" for j in range(0, 8, 2)]},
}

#: the fully run-aligned variant: every referenced column (group dim,
#: filter dim, summed/min'd metrics) is constant within the shared run
#: partition, so granularity-"all" executions go code-domain
RUN_GROUPBY = dict(GROUPBY)
RUN_GROUPBY["aggregations"] = [
    {"type": "count", "name": "n"},
    {"type": "longSum", "name": "c", "fieldName": "cnt"},
    {"type": "longSum", "name": "s", "fieldName": "m0"},
    {"type": "longMin", "name": "lm", "fieldName": "m1"},
]


@pytest.mark.parametrize("granularity", ["all", "hour"],
                         ids=["all", "hour"])
def test_groupby_parity(fresh_pool, granularity):
    # GROUPBY aggregates the row-random `noise` column, so even the
    # granularity-all variant stays a ROW program (the joint run
    # partition is too fine) — the code-domain variant is RUN_GROUPBY
    q = dict(GROUPBY, granularity=granularity)
    oracle, casc = _run_modes(q, rollup_segments())
    assert oracle == casc


def test_groupby_run_domain_parity(fresh_pool):
    oracle, casc = _run_modes(RUN_GROUPBY, rollup_segments())
    assert oracle == casc


def test_timeseries_and_topn_parity(fresh_pool):
    segs = rollup_segments(float_col=True)
    ts = {"queryType": "timeseries", "dataSource": "casc",
          "intervals": [str(IV)], "granularity": "hour",
          "aggregations": [
              {"type": "count", "name": "n"},
              {"type": "longSum", "name": "s", "fieldName": "m0"},
              {"type": "doubleSum", "name": "fs", "fieldName": "f"},
          ]}
    oracle, casc = _run_modes(ts, segs)
    assert oracle == casc
    topn = {"queryType": "topN", "dataSource": "casc",
            "intervals": [str(IV)], "granularity": "all",
            "dimension": "d0", "metric": "s", "threshold": 5,
            "aggregations": [
                {"type": "count", "name": "n"},
                {"type": "longSum", "name": "s", "fieldName": "m1"}]}
    oracle, casc = _run_modes(topn, segs)
    assert oracle == casc


def test_virtual_column_parity_reads_cascade_input(fresh_pool):
    q = dict(GROUPBY)
    q["virtualColumns"] = [{"type": "expression", "name": "v",
                            "expression": "m0 * 2 + 1",
                            "outputType": "long"}]
    q["aggregations"] = GROUPBY["aggregations"] + [
        {"type": "longSum", "name": "vs", "fieldName": "v"}]
    oracle, casc = _run_modes(q, rollup_segments())
    assert oracle == casc


def test_batched_path_parity_and_shared_buckets(fresh_pool):
    from druid_tpu.engine import batching
    from druid_tpu.query.aggregators import (CountAggregator,
                                             LongSumAggregator)
    from druid_tpu.utils.granularity import Granularity

    segs = rollup_segments(4, rows=1500)
    # pin the ROW program: the near-constant time column makes even the
    # hour query run-domain eligible since the uniform-granularity rung —
    # this test measures the BATCHED staging path
    prev_rd = cascade.set_run_domain_enabled(False)
    try:
        q = dict(GROUPBY, granularity="hour")   # row program: batchable
        oracle, casc = _run_modes(q, segs)
        assert oracle == casc
        # chunk-mates agree on the cascade descriptor: same-stats segments
        # share one shape bucket, and the descriptor is present in it
        aggs = [CountAggregator("n"), LongSumAggregator("s", "m0")]
        plans = [batching._plan_for(s, [], i, [IV],
                                    Granularity.of("hour"),
                                    aggs, None, [])
                 for i, s in enumerate(segs)]
        assert all(p.eligible for p in plans)
        assert len({p.cascades for p in plans}) == 1
        assert plans[0].cascades
        assert len({p.digest for p in plans}) == 1
    finally:
        cascade.set_run_domain_enabled(prev_rd)


def test_megakernel_path_parity(fresh_pool):
    """Single-segment cold query with a bitmap-eligible filter: the
    megakernel (one-dispatch) path over cascade-staged columns."""
    from druid_tpu.engine import megakernel
    assert megakernel.enabled()
    segs = rollup_segments(1, rows=4096)
    q = dict(GROUPBY, granularity="hour",
             filter={"type": "or", "fields": [
                 {"type": "selector", "dimension": "d1",
                  "value": "v1_001"},
                 {"type": "selector", "dimension": "d1",
                  "value": "v1_005"}]})
    oracle, casc = _run_modes(q, segs)
    assert oracle == casc


def test_staged_bitmap_runs_leaf_parity(fresh_pool):
    """The staged (fill-wave) device-bitmap path with the RLE-run-aware
    leaf representation: a sorted dim's leaf ships as a run table and the
    expanded words match the row-built oracle bit-for-bit."""
    from druid_tpu.engine import filters as filters_mod
    from druid_tpu.engine import megakernel
    seg = rollup_segments(1, rows=4096)[0]
    lut = np.zeros(seg.dims["d1"].cardinality, dtype=bool)
    lut[1::2] = True
    payload = filters_mod._run_leaf_payload(seg, "d1", lut, 4096)
    assert payload is not None and payload.shape[1] == 2
    prev = megakernel.set_enabled(False)   # pin the staged fill path
    try:
        oracle, casc = _run_modes(
            dict(GROUPBY, granularity="hour"), [seg])
    finally:
        megakernel.set_enabled(prev)
    assert oracle == casc


# ---------------------------------------------------------------------------
# code-domain: zero unpack, zero row-width staging
# ---------------------------------------------------------------------------

def test_run_domain_zero_unpack_and_parity(fresh_pool):
    segs = rollup_segments(2, rows=4096)
    oracle, _ = _run_modes(RUN_GROUPBY, segs)  # oracle decodes; then reset
    fresh_pool.clear()
    cascade.reset_decode_stats()
    h0 = cascade.code_domain_stats().snapshot()
    from druid_tpu.obs import dispatch as dispatch_mod
    d0 = dispatch_mod.stats().snapshot().get("runDomain", 0)
    got = QueryExecutor(segs).run_json(RUN_GROUPBY)
    assert got == oracle
    # ZERO unpack: no decode of any kind entered any program
    assert cascade.decode_stats() == {}
    h1 = cascade.code_domain_stats().snapshot()
    assert h1["hits"] - h0["hits"] == len(segs)
    assert h1["rows"] - h0["rows"] == sum(s.n_rows for s in segs)
    assert dispatch_mod.stats().snapshot()["runDomain"] - d0 == len(segs)
    # zero row-width staging: every pool entry is run-table sized
    assert fresh_pool.snapshot().resident_bytes < 4096 * 4


def test_const_sum_column_never_stages(fresh_pool):
    """sum-over-dictionary-constant: the constant column contributes NO
    staged column even on the row program path (required_device_columns
    = {}), and the sum is exact."""
    segs = rollup_segments(2, rows=2048)
    q = {"queryType": "timeseries", "dataSource": "casc",
         "intervals": [str(IV)], "granularity": "hour",
         "aggregations": [{"type": "count", "name": "n"},
                          {"type": "longSum", "name": "c",
                           "fieldName": "cnt"}]}
    oracle, casc_rows = _run_modes(q, segs)
    assert oracle == casc_rows
    for row in casc_rows:
        assert row["result"]["c"] == row["result"]["n"]  # cnt ≡ 1
    from druid_tpu.engine.kernels import SumKernel, make_kernel
    from druid_tpu.query.aggregators import LongSumAggregator
    k = make_kernel(LongSumAggregator("c", "cnt"), segs[0])
    assert isinstance(k, SumKernel) and k.const_value == 1
    assert k.required_device_columns() == set()
    prev = cascade.set_enabled(False)
    try:
        k2 = make_kernel(LongSumAggregator("c", "cnt"), segs[0])
    finally:
        cascade.set_enabled(prev)
    assert k2.const_value is None              # opt-out restores old world


def test_run_domain_respects_optout(fresh_pool):
    segs = rollup_segments(2, rows=2048)
    prev = cascade.set_enabled(False)
    try:
        h0 = cascade.code_domain_stats().snapshot()["hits"]
        QueryExecutor(segs).run_json(RUN_GROUPBY)
        assert cascade.code_domain_stats().snapshot()["hits"] == h0
    finally:
        cascade.set_enabled(prev)


# ---------------------------------------------------------------------------
# residency: ≥3x more segments than packed-only at a fixed budget
# ---------------------------------------------------------------------------

def test_pool_holds_3x_more_segments_than_packed_only(fresh_pool):
    """The acceptance bar on the RLE-friendly shape: cascade staging must
    fit ≥ 3x the segments packed-only staging fits at one byte budget."""
    n_segments, rows = 12, 2048
    segs = rollup_segments(n_segments, rows=rows, card=8, n_dims=5,
                           n_mets=3, seed=3)
    q = {"queryType": "groupBy", "dataSource": "casc",
         "intervals": [str(IV)], "granularity": "hour",
         "dimensions": ["d0", "d1"],
         "filter": {"type": "and", "fields": [
             {"type": "in", "dimension": d,
              "values": [f"v{d[1]}_{j:03d}" for j in range(4)]}
             for d in ("d2", "d3", "d4")]},
         "aggregations": [{"type": "count", "name": "n"},
                          {"type": "longSum", "name": "s0",
                           "fieldName": "m0"},
                          {"type": "longSum", "name": "s1",
                           "fieldName": "m1"},
                          {"type": "longMin", "name": "s2",
                           "fieldName": "m2"}]}
    ex = QueryExecutor(segs)
    # pin the column paths: this measures STAGED bytes, so the device
    # bitmap path (which stops staging filter columns) is disabled in
    # both modes, exactly like test_packed's ≥3x test
    from druid_tpu.engine import filters as _filters
    prev_bmp = _filters.set_device_bitmap_enabled(False)
    # ...and the run-domain path, which since the uniform-granularity rung
    # would serve this aligned shape from run tables with no column
    # staging at all — this test measures STAGED column bytes
    prev_rd = cascade.set_run_domain_enabled(False)
    prev_c = cascade.set_enabled(False)
    try:
        packed_only = ex.run_json(q)
        packed_per_seg = fresh_pool.snapshot().resident_bytes / n_segments
        fresh_pool.clear()
        cascade.set_enabled(True)
        casc_rows = ex.run_json(q)
        s = fresh_pool.snapshot()
        assert packed_only == casc_rows            # parity rides along
        casc_per_seg = s.resident_bytes / n_segments
        multiplier = packed_per_seg / casc_per_seg
        assert multiplier >= 3.0, (
            f"cascade staging only {multiplier:.2f}x over packed-only "
            f"({packed_per_seg:.0f}B -> {casc_per_seg:.0f}B per segment)")
        assert s.cascade_ratio >= 3.0
        # a budget sized for ~4 packed-only stagings holds every segment
        budget = int(packed_per_seg * 4)
        fresh_pool.clear()
        fresh_pool.configure(budget)
        ex.run_json(q)
        s = fresh_pool.snapshot()
        assert s.entries >= n_segments
        assert s.resident_bytes <= budget
    finally:
        cascade.set_enabled(prev_c)
        cascade.set_run_domain_enabled(prev_rd)
        _filters.set_device_bitmap_enabled(prev_bmp)


# ---------------------------------------------------------------------------
# pool accounting + monitors
# ---------------------------------------------------------------------------

def test_pool_cascade_accounting(fresh_pool):
    segs = rollup_segments(1, rows=2048)
    q = dict(GROUPBY, granularity="hour")
    QueryExecutor(segs).run_json(q)
    s = fresh_pool.snapshot()
    assert s.cascade_bytes > 0
    assert s.cascade_logical_bytes > s.cascade_bytes
    assert s.cascade_ratio > 1.0
    assert s.cascade_bytes <= s.resident_bytes
    # the walker counts cascade-marked leaves only
    import jax
    rc = cascade.RleColumn(jax.device_put(np.zeros(8, np.int32)),
                           jax.device_put(np.zeros(8, np.int32)), 64, 1024)
    actual, logical = entry_cascade_bytes({"a": rc, "b": np.zeros(16)})
    assert (actual, logical) == (64, 4096)


def test_code_domain_monitor_emits_cataloged_names(fresh_pool):
    from druid_tpu.obs import catalog
    from druid_tpu.utils.emitter import InMemoryEmitter, ServiceEmitter
    sink = InMemoryEmitter()
    em = ServiceEmitter("s", "h", sink)
    mon = cascade.CodeDomainMonitor(cascade.CodeDomainStats())
    mon.source.record(1234)
    mon.do_monitor(em)
    names = {e.metric for e in sink.events}
    assert names == {"query/codeDomain/hits", "query/codeDomain/rows"}
    assert catalog.validate_emitted(names) == []


# ---------------------------------------------------------------------------
# hyperUnique/cardinality at non-default registers (log2m != 11 rider)
# ---------------------------------------------------------------------------

def test_hyperunique_log2m12_parity(fresh_pool):
    from druid_tpu.engine import batching
    segs = rollup_segments(4, rows=1500, card=8)
    q = {"queryType": "groupBy", "dataSource": "casc",
         "intervals": [str(IV)], "granularity": "all",
         "dimensions": ["d0"],
         "aggregations": [
             {"type": "count", "name": "n"},
             {"type": "hyperUnique", "name": "u", "fieldName": "d1",
              "log2m": 12}]}
    oracle, casc_rows = _run_modes(q, segs)
    assert oracle == casc_rows
    prev = batching.set_enabled(False)
    try:
        per_seg = QueryExecutor(segs).run_json(q)
    finally:
        batching.set_enabled(prev)
    assert per_seg == oracle


# ---------------------------------------------------------------------------
# run-domain over uniform granularities (bucket boundaries join the joint
# run partition — the ROADMAP item-3 follow-on rung)
# ---------------------------------------------------------------------------

HOUR_MS = 3_600_000


def hour_run_segments(n=2, rows=2048, card=8):
    """Rollup shape whose TIME advances one hour per dimension block: the
    hour-granularity bucket boundaries provably align with the run
    boundaries of every referenced column."""
    reps = -(-rows // card)
    segs = []
    for si in range(n):
        b = SegmentBuilder("casc", IV, version="v0", partition=si)
        dims = {f"d{i}": np.repeat(
            [f"v{i}_{j:03d}" for j in range(card)], reps)[:rows].tolist()
            for i in range(2)}
        mets = {"cnt": np.ones(rows, dtype=np.int64),
                "m0": np.repeat((np.arange(card) * 7) % 13,
                                reps)[:rows].astype(np.int64),
                "m1": np.repeat((np.arange(card) * 8) % 13,
                                reps)[:rows].astype(np.int64)}
        time = IV.start + (np.arange(rows, dtype=np.int64) // reps) * HOUR_MS
        b.add_columns(time, dims, mets)
        segs.append(b.build())
    return segs


def test_run_domain_uniform_granularity_parity_zero_unpack(fresh_pool):
    """Hour-granularity execution over hour-aligned runs goes fully
    code-domain: bit-identical to the decoded oracle, zero unpack, one
    runDomain dispatch per segment — per-bucket rows now ride run space,
    not just granularity-'all' covering-interval queries."""
    from druid_tpu.obs import dispatch as dispatch_mod
    segs = hour_run_segments()
    q = dict(RUN_GROUPBY, granularity="hour")
    oracle, _ = _run_modes(q, segs)
    fresh_pool.clear()
    cascade.reset_decode_stats()
    h0 = cascade.code_domain_stats().snapshot()
    d0 = dispatch_mod.stats().snapshot().get("runDomain", 0)
    got = QueryExecutor(segs).run_json(q)
    assert got == oracle
    assert cascade.decode_stats() == {}
    h1 = cascade.code_domain_stats().snapshot()
    assert h1["hits"] - h0["hits"] == len(segs)
    assert dispatch_mod.stats().snapshot()["runDomain"] - d0 == len(segs)
    # timeseries rides the same rung (no dims: key = the run's bucket id)
    ts = {"queryType": "timeseries", "dataSource": "casc",
          "intervals": [str(IV)], "granularity": "hour",
          "aggregations": RUN_GROUPBY["aggregations"]}
    o2, c2 = _run_modes(ts, segs)
    assert o2 == c2
    assert cascade.code_domain_stats().snapshot()["hits"] > h1["hits"]


def test_run_domain_uniform_eligibility_boundaries(fresh_pool):
    """The alignment proof is the joint run count: bucket boundaries that
    split runs too fine price the segment out of run space (row program,
    still bit-identical); a non-covering interval likewise."""
    segs = hour_run_segments()

    # minute granularity over hour-blocked time: bucket ids change every
    # row block of 1 minute... time advances in whole hours, so minute
    # buckets ALIGN; break alignment with per-row minute steps instead
    reps = -(-2048 // 8)
    b = SegmentBuilder("casc", IV, version="vx", partition=9)
    n = 2048
    dims = {"d0": np.repeat([f"v0_{j:03d}" for j in range(8)],
                            reps)[:n].tolist(),
            "d1": np.repeat([f"v1_{j:03d}" for j in range(8)],
                            reps)[:n].tolist()}
    mets = {"cnt": np.ones(n, dtype=np.int64),
            "m0": np.repeat((np.arange(8) * 7) % 13, reps)[:n].astype(
                np.int64),
            "m1": np.repeat((np.arange(8) * 8) % 13, reps)[:n].astype(
                np.int64)}
    b.add_columns(IV.start + np.arange(n, dtype=np.int64) * 60_000,
                  dims, mets)
    fine = b.build()

    h0 = cascade.code_domain_stats().snapshot()["hits"]
    q = dict(RUN_GROUPBY, granularity="minute")
    oracle, got = _run_modes(q, [fine])
    assert oracle == got
    # per-row bucket changes -> joint runs == rows -> priced out
    assert cascade.code_domain_stats().snapshot()["hits"] == h0

    # a query interval that does NOT cover the segment keeps the row
    # program (the time mask is not all-true), results identical
    half = Interval(IV.start, IV.start + 4 * HOUR_MS)
    qh = dict(RUN_GROUPBY, granularity="hour", intervals=[str(half)])
    h1 = cascade.code_domain_stats().snapshot()["hits"]
    oracle, got = _run_modes(qh, segs)
    assert oracle == got
    assert cascade.code_domain_stats().snapshot()["hits"] == h1

    # and the aligned shape DOES run code-domain under the same budget
    qa = dict(RUN_GROUPBY, granularity="hour")
    oracle, got = _run_modes(qa, segs)
    assert oracle == got
    assert cascade.code_domain_stats().snapshot()["hits"] > h1
