"""Ingestion tests: parsers, IncrementalIndex rollup, merger — the analog of
the reference's IncrementalIndexTest / IndexMergerTestBase / parser tests."""
import json

import numpy as np
import pytest

from druid_tpu.engine import QueryExecutor
from druid_tpu.ingest import (IncrementalIndex, InlineFirehose,
                              InputRowParser, LocalFirehose, TimestampSpec,
                              TransformSpec, merge_segments)
from druid_tpu.ingest.input import DimensionsSpec, ExpressionTransform
from druid_tpu.query.aggregators import (CountAggregator, DoubleSumAggregator,
                                         FirstAggregator,
                                         HyperUniqueAggregator,
                                         LastAggregator, LongMaxAggregator,
                                         LongSumAggregator)
from druid_tpu.query.filters import BoundFilter, SelectorFilter
from druid_tpu.query.model import DefaultDimensionSpec, GroupByQuery, \
    TimeseriesQuery
from druid_tpu.utils.intervals import Interval

IV = Interval.of("2026-03-01", "2026-03-02")
T0 = IV.start


def _mk_index(**kw):
    defaults = dict(
        datasource="ing",
        interval=IV,
        metric_specs=[CountAggregator("count"),
                      LongSumAggregator("val_sum", "val")],
        dimensions=["d1", "d2"],
        query_granularity="hour",
    )
    defaults.update(kw)
    return IncrementalIndex(**defaults)


def test_rollup_basic():
    idx = _mk_index(flush_rows=4)  # force multiple compactions
    for i in range(100):
        idx.add({"timestamp": T0 + (i % 3) * 3_600_000,
                 "d1": f"a{i % 2}", "d2": "z", "val": 1})
    # 3 hours x 2 d1 values = 6 groups
    assert idx.n_rows == 6
    seg = idx.to_segment()
    assert seg.n_rows == 6
    assert int(seg.metrics["count"].values.sum()) == 100
    assert int(seg.metrics["val_sum"].values.sum()) == 100
    # rolled-up count queries back as longSum of the count column
    q = TimeseriesQuery.of(
        "ing", [IV], [LongSumAggregator("rows", "count"),
                      LongSumAggregator("v", "val_sum")],
        granularity="all")
    res = QueryExecutor([seg]).run(q)
    assert res[0]["result"] == {"rows": 100, "v": 100}


def test_no_rollup_keeps_rows():
    idx = _mk_index(rollup=False, flush_rows=7)
    for i in range(50):
        idx.add({"timestamp": T0 + i, "d1": "a", "d2": "b", "val": 2})
    assert idx.to_segment().n_rows == 50


def test_rollup_matches_recomputed_golden():
    rng = np.random.default_rng(0)
    idx = _mk_index(
        metric_specs=[CountAggregator("count"),
                      LongSumAggregator("s", "val"),
                      LongMaxAggregator("mx", "val"),
                      FirstAggregator("first_v", "val", "long"),
                      LastAggregator("last_v", "val", "long")],
        flush_rows=13)
    rows = []
    for i in range(500):
        r = {"timestamp": T0 + int(rng.integers(0, 4)) * 3_600_000 + i,
             "d1": f"k{int(rng.integers(0, 3))}", "d2": "c",
             "val": int(rng.integers(0, 100))}
        rows.append(r)
        idx.add(r)
    seg = idx.to_segment()
    # golden: group rows by (hour, d1, d2)
    golden = {}
    for r in rows:
        hour = (r["timestamp"] // 3_600_000) * 3_600_000
        k = (hour, r["d1"], r["d2"])
        g = golden.setdefault(k, {"count": 0, "s": 0, "mx": -1,
                                  "ft": None, "fv": None, "lt": None,
                                  "lv": None})
        g["count"] += 1
        g["s"] += r["val"]
        g["mx"] = max(g["mx"], r["val"])
        if g["ft"] is None or r["timestamp"] < g["ft"]:
            g["ft"], g["fv"] = r["timestamp"], r["val"]
        if g["lt"] is None or r["timestamp"] > g["lt"]:
            g["lt"], g["lv"] = r["timestamp"], r["val"]
    assert seg.n_rows == len(golden)
    d1 = seg.dims["d1"]
    for i in range(seg.n_rows):
        k = (int(seg.time_ms[i]), d1.dictionary.value_of(int(d1.ids[i])), "c")
        g = golden[k]
        assert int(seg.metrics["count"].values[i]) == g["count"]
        assert int(seg.metrics["s"].values[i]) == g["s"]
        assert int(seg.metrics["mx"].values[i]) == g["mx"]
        assert int(seg.metrics["first_v"].values[i]) == g["fv"]
        assert int(seg.metrics["last_v"].values[i]) == g["lv"]


def test_schemaless_dimension_discovery():
    idx = _mk_index(dimensions=None, flush_rows=3)
    idx.add({"timestamp": T0, "d1": "x", "val": 1})
    idx.add({"timestamp": T0 + 1, "newdim": "y", "val": 2})
    idx.add({"timestamp": T0 + 2, "d1": "x", "newdim": "y", "val": 3})
    idx.add({"timestamp": T0 + 3, "d1": "x", "newdim": "y", "val": 4})
    seg = idx.to_segment()
    assert set(seg.dims) == {"d1", "newdim"}
    # missing values encode as null ("")
    assert "" in seg.dims["newdim"].dictionary.values


def test_out_of_interval_rows_dropped():
    idx = _mk_index()
    idx.add({"timestamp": T0 - 1, "d1": "x", "val": 1})
    idx.add({"timestamp": T0, "d1": "x", "val": 1})
    idx.add({"timestamp": IV.end, "d1": "x", "val": 1})
    assert idx.n_rows == 1
    assert idx.rows_out_of_interval == 2


def test_hyperunique_ingest_metric_roundtrip(tmp_path):
    from druid_tpu.storage import load_segment, persist_segment
    idx = _mk_index(
        metric_specs=[CountAggregator("count"),
                      HyperUniqueAggregator("uniq", "user")],
        dimensions=["d1"], flush_rows=11)
    for i in range(300):
        idx.add({"timestamp": T0 + i % 2, "d1": f"g{i % 2}",
                 "user": f"user_{i % 57}"})
    seg = idx.to_segment()
    assert seg.metrics["uniq"].values.ndim == 2
    q = TimeseriesQuery.of(
        "ing", [IV], [HyperUniqueAggregator("u", "uniq")], granularity="all")
    est = QueryExecutor([seg]).run(q)[0]["result"]["u"]
    assert 50 <= est <= 64  # HLL estimate of 57 uniques
    # survives persist/load
    d = str(tmp_path / "hll_seg")
    persist_segment(seg, d)
    est2 = QueryExecutor([load_segment(d)]).run(q)[0]["result"]["u"]
    assert est2 == est
    # groupBy over the complex metric
    gq = GroupByQuery.of("ing", [IV], [DefaultDimensionSpec("d1")],
                         [HyperUniqueAggregator("u", "uniq")],
                         granularity="all")
    rows = QueryExecutor([seg]).run(gq)
    assert len(rows) == 2
    for r in rows:
        # gcd(2,57)=1 so each d1 group still sees all 57 users
        assert 50 <= r["event"]["u"] <= 64


def test_merge_segments_equals_single_index():
    specs = [CountAggregator("count"), LongSumAggregator("s", "val"),
             DoubleSumAggregator("d", "dval")]
    idx_all = _mk_index(metric_specs=specs, flush_rows=17)
    idx_a = _mk_index(metric_specs=specs, flush_rows=17)
    idx_b = _mk_index(metric_specs=specs, flush_rows=17)
    rng = np.random.default_rng(7)
    for i in range(400):
        row = {"timestamp": T0 + int(rng.integers(0, 5)) * 3_600_000,
               "d1": f"v{int(rng.integers(0, 4))}",
               "d2": f"w{int(rng.integers(0, 3))}",
               "val": int(rng.integers(0, 10)),
               "dval": float(rng.normal())}
        idx_all.add(row)
        (idx_a if i % 2 else idx_b).add(row)
    merged = merge_segments([idx_a.to_segment(), idx_b.to_segment()],
                            specs, query_granularity="hour")
    single = idx_all.to_segment()
    assert merged.n_rows == single.n_rows
    # compare via a query (canonical ordering)
    q = GroupByQuery.of(
        "ing", [IV],
        [DefaultDimensionSpec("d1"), DefaultDimensionSpec("d2")],
        [LongSumAggregator("c", "count"), LongSumAggregator("s", "s")],
        granularity="hour")
    ra = QueryExecutor([merged]).run(q)
    rb = QueryExecutor([single]).run(q)
    assert ra == rb


def test_merge_heterogeneous_dims():
    specs = [CountAggregator("count")]
    a = IncrementalIndex("m", IV, specs, dimensions=["x"])
    a.add({"timestamp": T0, "x": "1"})
    b = IncrementalIndex("m", IV, specs, dimensions=["y"])
    b.add({"timestamp": T0, "y": "2"})
    merged = merge_segments([a.to_segment(), b.to_segment()], specs,
                            rollup=False)
    assert set(merged.dims) == {"x", "y"}
    assert merged.n_rows == 2
    vals = {(merged.dims["x"].dictionary.value_of(int(merged.dims["x"].ids[i])),
             merged.dims["y"].dictionary.value_of(int(merged.dims["y"].ids[i])))
            for i in range(2)}
    assert vals == {("1", ""), ("", "2")}


# ---------------------------------------------------------------------------
# Parsers / firehoses / transforms
# ---------------------------------------------------------------------------

def test_json_parser():
    p = InputRowParser(TimestampSpec("ts", "iso"), DimensionsSpec(("a",)),
                       fmt="json")
    batch = p.parse_batch([json.dumps({"ts": "2026-03-01T00:00:00Z",
                                       "a": "x", "m": 5})])
    assert batch.timestamps == [T0]
    assert batch.columns["a"] == ["x"]


def test_csv_tsv_regex_parsers():
    csv_p = InputRowParser(TimestampSpec("t", "millis"), DimensionsSpec(),
                           fmt="csv", columns=["t", "a", "b"])
    b = csv_p.parse_batch([f"{T0},x,3", f"{T0 + 1},y,4"])
    assert b.columns["a"] == ["x", "y"]
    tsv_p = InputRowParser(TimestampSpec("t", "millis"), DimensionsSpec(),
                           fmt="tsv", columns=["t", "a"])
    b = tsv_p.parse_batch([f"{T0}\tz"])
    assert b.columns["a"] == ["z"]
    rx_p = InputRowParser(TimestampSpec("t", "millis"), DimensionsSpec(),
                          fmt="regex", columns=["t", "w"],
                          pattern=r"(\d+) (\w+)")
    b = rx_p.parse_batch([f"{T0} hello"])
    assert b.columns["w"] == ["hello"]


def test_timestamp_formats():
    assert TimestampSpec(format="millis").parse(T0) == T0
    assert TimestampSpec(format="posix").parse(T0 // 1000) == T0
    assert TimestampSpec(format="auto").parse(str(T0)) == T0
    assert TimestampSpec(format="auto").parse("2026-03-01") == T0
    assert TimestampSpec(format="%d/%m/%Y %H:%M").parse("01/03/2026 00:00") == T0
    with pytest.raises(ValueError):
        TimestampSpec().parse(None)
    assert TimestampSpec(missing_value=123).parse(None) == 123


def test_transform_spec():
    from druid_tpu.ingest.input import RowBatch
    ts = TransformSpec(
        transforms=(ExpressionTransform("doubled", "v * 2"),),
        filter=BoundFilter("v", lower="3", ordering="numeric"))
    batch = RowBatch([T0, T0 + 1, T0 + 2],
                     {"v": [2, 3, 10], "d": ["a", "b", "c"]})
    out = ts.apply(batch)
    assert len(out) == 2  # v>=3 kept
    assert out.columns["d"] == ["b", "c"]
    assert [float(x) for x in out.columns["doubled"]] == [6.0, 20.0]


def test_local_firehose(tmp_path):
    import gzip
    (tmp_path / "a.json").write_text('{"t": 1, "d": "x"}\n{"t": 2, "d": "y"}\n')
    with gzip.open(tmp_path / "b.json.gz", "wt") as f:
        f.write('{"t": 3, "d": "z"}\n')
    fh = LocalFirehose(str(tmp_path), "*.json*")
    lines = [l for batch in fh.batches() for l in batch]
    assert len(lines) == 3


def test_firehose_to_index_end_to_end():
    records = [json.dumps({"ts": T0 + i, "d1": f"p{i % 3}", "val": i})
               for i in range(100)]
    parser = InputRowParser(TimestampSpec("ts", "millis"),
                            DimensionsSpec(("d1",)))
    idx = _mk_index(dimensions=["d1"], query_granularity="all")
    for raw in InlineFirehose(records).batches(batch_size=16):
        idx.add_batch(parser.parse_batch(raw))
    seg = idx.to_segment()
    assert seg.n_rows == 3  # 3 d1 values, granularity all
    assert int(seg.metrics["val_sum"].values.sum()) == sum(range(100))


def test_schemaless_backfill_is_null():
    """Rows ingested before a dim is discovered must read as null, not as
    the first-seen value of the new dimension."""
    idx = _mk_index(dimensions=None, flush_rows=2)
    idx.add({"timestamp": T0, "d1": "a", "val": 1})
    idx.add({"timestamp": T0 + 1, "d1": "b", "val": 1})      # compaction 1
    idx.add({"timestamp": T0 + 2, "newdim": "y", "val": 1})
    idx.add({"timestamp": T0 + 3, "newdim": "y", "val": 1})  # compaction 2
    seg = idx.to_segment()
    # rows 3+4 roll up (same hour, same dims) → 3 rows; the two pre-discovery
    # rows read newdim as null, NOT as "y"
    nd = seg.dims["newdim"]
    vals = sorted(nd.dictionary.value_of(int(i)) for i in nd.ids)
    assert vals == ["", "", "y"]


def test_first_last_merge_uses_event_time():
    """Cross-segment first/last must pick by true event time, not
    concatenation order (pair-time column semantics)."""
    specs = [FirstAggregator("fv", "val", "long"),
             LastAggregator("lv", "val", "long")]
    H = T0  # one hour bucket
    a = IncrementalIndex("fl", IV, specs, dimensions=["d"],
                         query_granularity="hour")
    a.add({"timestamp": H + 10, "d": "g", "val": 1})
    b = IncrementalIndex("fl", IV, specs, dimensions=["d"],
                         query_granularity="hour")
    b.add({"timestamp": H + 5, "d": "g", "val": 2})
    b.add({"timestamp": H + 20, "d": "g", "val": 3})
    merged = merge_segments([a.to_segment(), b.to_segment()], specs,
                            query_granularity="hour")
    assert merged.n_rows == 1
    assert int(merged.metrics["fv"].values[0]) == 2   # t=H+5 wins first
    assert int(merged.metrics["lv"].values[0]) == 3   # t=H+20 wins last
    # combining keeps the long kind
    assert merged.metrics["fv"].values.dtype == np.int64
    # query over rolled-up segments also orders by pair time
    q = TimeseriesQuery.of("fl", [IV],
                           [FirstAggregator("f", "fv", "long"),
                            LastAggregator("l", "lv", "long")],
                           granularity="all")
    res = QueryExecutor([a.to_segment(), b.to_segment()]).run(q)
    assert res[0]["result"] == {"f": 2, "l": 3}


def test_sharded_complex_column_falls_back():
    """hyperUnique complex columns can't stack [K,R]; the mesh path must
    fall back to per-segment execution, matching plain results."""
    from druid_tpu.parallel import make_mesh, use_mesh
    specs = [CountAggregator("count"), HyperUniqueAggregator("uu", "user")]
    segs = []
    for p in range(2):
        idx = IncrementalIndex("hc", IV, specs, dimensions=["d"],
                               query_granularity="hour")
        for i in range(100):
            idx.add({"timestamp": T0 + i, "d": f"x{i % 3}",
                     "user": f"u{p}_{i % 20}"})
        segs.append(idx.to_segment(partition=p))
    q = TimeseriesQuery.of("hc", [IV], [HyperUniqueAggregator("u", "uu")],
                           granularity="all")
    plain = QueryExecutor(segs).run(q)
    with use_mesh(make_mesh()):
        sharded = QueryExecutor(segs).run(q)
    assert plain == sharded
    assert 36 <= plain[0]["result"]["u"] <= 44  # 40 uniques


def test_sharded_dtype_mismatch_falls_back():
    from druid_tpu.data.segment import SegmentBuilder
    from druid_tpu.parallel import make_mesh, use_mesh
    from druid_tpu.query.aggregators import DoubleSumAggregator
    b1 = SegmentBuilder("dm", IV, partition=0)
    for i in range(10):
        b1.add_row(T0 + i, {"d": "x"}, {"m": i})        # long metric
    b2 = SegmentBuilder("dm", IV, partition=1)
    for i in range(10):
        b2.add_row(T0 + i, {"d": "x"}, {"m": i + 0.5})  # double metric
    segs = [b1.build(), b2.build()]
    q = TimeseriesQuery.of("dm", [IV], [DoubleSumAggregator("s", "m")],
                           granularity="all")
    plain = QueryExecutor(segs).run(q)
    with use_mesh(make_mesh()):
        sharded = QueryExecutor(segs).run(q)
    assert abs(plain[0]["result"]["s"] - (45 + 50)) < 1e-9
    assert plain == sharded
