"""SQL UNION ALL chains and IN (SELECT ...) semi-joins.

Reference: sql/src/main/java/org/apache/druid/sql/calcite/rel/
DruidUnionRel.java (arms execute independently, results concatenate) and
DruidSemiJoin.java (inner query materialized into a filter on the outer,
capped by PlannerConfig.maxSemiJoinRowsInMemory).
"""
import pytest

from druid_tpu.engine import QueryExecutor
from druid_tpu.sql import PlannerError, SqlExecutor
from tests.conftest import rows_as_frame


@pytest.fixture(scope="module")
def sql(segments):
    return SqlExecutor(QueryExecutor(segments))


@pytest.fixture(scope="module")
def frames(segments):
    return [rows_as_frame(s) for s in segments]


# ---------------------------------------------------------------------------
# UNION ALL
# ---------------------------------------------------------------------------

def test_union_all_concatenates(sql, frames):
    cols, rows = sql.execute(
        "SELECT dimA, COUNT(*) n FROM test GROUP BY dimA "
        "UNION ALL "
        "SELECT dimB, COUNT(*) n FROM test GROUP BY dimB")
    n_a = len({v for f in frames for v in f["dimA"]})
    n_b = len({v for f in frames for v in f["dimB"]})
    assert cols == ["dimA", "n"]          # names come from the first arm
    assert len(rows) == n_a + n_b
    total = sum(len(f["dimA"]) for f in frames)
    assert sum(r[1] for r in rows) == 2 * total


def test_union_order_and_limit_bind_to_whole_union(sql):
    cols, rows = sql.execute(
        "SELECT dimA v, SUM(metLong) s FROM test GROUP BY dimA "
        "UNION ALL "
        "SELECT dimB v, SUM(metLong) s FROM test GROUP BY dimB "
        "ORDER BY s DESC LIMIT 5")
    assert len(rows) == 5
    assert [r[1] for r in rows] == sorted((r[1] for r in rows), reverse=True)


def test_union_order_by_ordinal_offset(sql):
    cols, all_rows = sql.execute(
        "SELECT dimA FROM test GROUP BY dimA "
        "UNION ALL SELECT dimB FROM test GROUP BY dimB ORDER BY 1")
    cols, page = sql.execute(
        "SELECT dimA FROM test GROUP BY dimA "
        "UNION ALL SELECT dimB FROM test GROUP BY dimB "
        "ORDER BY 1 LIMIT 3 OFFSET 2")
    assert page == all_rows[2:5]


def test_union_three_arms_scalar(sql, frames):
    cols, rows = sql.execute(
        "SELECT COUNT(*) FROM test UNION ALL "
        "SELECT COUNT(*) FROM test UNION ALL SELECT COUNT(*) FROM test")
    total = sum(len(f["dimA"]) for f in frames)
    assert [r[0] for r in rows] == [total, total, total]


def test_union_arity_mismatch_rejected(sql):
    with pytest.raises(PlannerError, match="same number of columns"):
        sql.execute("SELECT dimA, COUNT(*) FROM test GROUP BY dimA "
                    "UNION ALL SELECT dimB FROM test GROUP BY dimB")


def test_union_arm_order_by_rejected(sql):
    from druid_tpu.sql.parser import SqlParseError
    with pytest.raises(SqlParseError, match="UNION"):
        sql.execute("SELECT dimA FROM test GROUP BY dimA ORDER BY dimA "
                    "UNION ALL SELECT dimB FROM test GROUP BY dimB")


def test_union_explain_lists_arms(sql):
    plan = sql.explain("SELECT COUNT(*) FROM test "
                       "UNION ALL SELECT COUNT(*) FROM test")
    assert plan["queryType"] == "unionAll"
    assert len(plan["arms"]) == 2
    assert all(a["queryType"] == "timeseries" for a in plan["arms"])


# ---------------------------------------------------------------------------
# IN (SELECT ...) semi-joins
# ---------------------------------------------------------------------------

def top_dims(frames, dim, metric, k):
    sums = {}
    for f in frames:
        for d, v in zip(f[dim], f[metric]):
            sums[d] = sums.get(d, 0) + int(v)
    return [d for d, _ in
            sorted(sums.items(), key=lambda kv: -kv[1])[:k]]


def test_in_subquery_filters_outer(sql, frames):
    cols, rows = sql.execute(
        "SELECT dimA, COUNT(*) n FROM test WHERE dimA IN "
        "(SELECT dimA FROM test GROUP BY dimA ORDER BY SUM(metLong) DESC "
        "LIMIT 2) GROUP BY dimA ORDER BY dimA")
    want = sorted(top_dims(frames, "dimA", "metLong", 2))
    assert [r[0] for r in rows] == want


def test_not_in_subquery(sql, frames):
    cols, rows = sql.execute(
        "SELECT COUNT(DISTINCT dimA) FROM test WHERE dimA NOT IN "
        "(SELECT dimA FROM test GROUP BY dimA ORDER BY SUM(metLong) DESC "
        "LIMIT 2)")
    n_a = len({v for f in frames for v in f["dimA"]})
    assert rows[0][0] == n_a - 2


def test_in_subquery_composes_with_other_predicates(sql, frames):
    cols, rows = sql.execute(
        "SELECT COUNT(*) FROM test WHERE metLong > 3 AND dimA IN "
        "(SELECT dimA FROM test GROUP BY dimA ORDER BY SUM(metLong) DESC "
        "LIMIT 2)")
    top = set(top_dims(frames, "dimA", "metLong", 2))
    want = sum(1 for f in frames
               for a, v in zip(f["dimA"], f["metLong"])
               if a in top and int(v) > 3)
    assert rows[0][0] == want


def test_in_subquery_must_be_single_column(sql):
    with pytest.raises(PlannerError, match="exactly one column"):
        sql.execute("SELECT COUNT(*) FROM test WHERE dimA IN "
                    "(SELECT dimA, dimB FROM test GROUP BY dimA, dimB)")


def test_empty_in_subquery_matches_nothing(sql):
    cols, rows = sql.execute(
        "SELECT COUNT(*) FROM test WHERE dimA IN "
        "(SELECT dimA FROM test WHERE dimA = 'no_such_value' "
        "GROUP BY dimA)")
    assert rows[0][0] == 0


def test_not_in_subquery_with_null_matches_nothing(sql, monkeypatch):
    """Three-valued logic: `x NOT IN (..., NULL)` is never true, so a NULL
    in the materialized inner result must empty the outer result."""
    real = SqlExecutor._execute_select

    def fake(self, sel, depth, context=None):
        names, rows = real(self, sel, depth, context)
        if depth > 0:
            rows = rows + [[None]]
        return names, rows

    monkeypatch.setattr(SqlExecutor, "_execute_select", fake)
    cols, rows = sql.execute(
        "SELECT COUNT(*) FROM test WHERE dimA NOT IN "
        "(SELECT dimA FROM test GROUP BY dimA ORDER BY SUM(metLong) DESC "
        "LIMIT 2)")
    assert rows[0][0] == 0


def test_explain_does_not_execute_semijoin(sql, monkeypatch):
    """EXPLAIN is plan-only: inner SELECTs are planned, never run."""
    def boom(self, sub, depth):
        raise AssertionError("explain executed a subquery")

    monkeypatch.setattr(SqlExecutor, "_materialize_semijoin", boom)
    plan = sql.explain(
        "SELECT COUNT(*) FROM test WHERE dimA IN "
        "(SELECT dimA FROM test GROUP BY dimA)")
    assert plan["queryType"] == "timeseries"
    assert len(plan["semiJoinSubPlans"]) == 1
    assert plan["semiJoinSubPlans"][0]["queryType"] == "groupBy"


def test_mixed_meta_statement_still_authorizes_real_tables(segments):
    """A statement mixing INFORMATION_SCHEMA with a real table must not
    bypass the real table's READ check (is_meta alone is not a grant)."""
    from druid_tpu.server.http import QueryHttpServer
    from druid_tpu.server.security import (AuthChain, Permission, READ,
                                           AuthenticationResult,
                                           RoleBasedAuthorizer)
    qe = QueryExecutor(segments)
    server = QueryHttpServer.__new__(QueryHttpServer)
    server.sql_executor = SqlExecutor(qe)
    server.auth_chain = AuthChain(authorizers={"rbac": RoleBasedAuthorizer(
        {"meta_only": [Permission("INFORMATION_SCHEMA", actions=(READ,))]},
        {"bob": ["meta_only"]})})
    bob = AuthenticationResult("bob", "rbac")
    assert server._authorize_sql(
        bob, "SELECT TABLE_NAME FROM INFORMATION_SCHEMA.TABLES")
    assert not server._authorize_sql(
        bob, "SELECT dimA FROM test UNION ALL "
             "SELECT TABLE_NAME FROM INFORMATION_SCHEMA.TABLES")
    assert not server._authorize_sql(
        bob, "SELECT COUNT(*) FROM test WHERE dimA IN "
             "(SELECT TABLE_NAME FROM INFORMATION_SCHEMA.TABLES)")


def test_in_subquery_outside_where_rejected_before_execution(sql,
                                                             monkeypatch):
    """IN (SELECT ...) outside WHERE raises cleanly WITHOUT running the
    inner query."""
    def boom(self, sub, depth):
        raise AssertionError("rejected position executed its subquery")

    monkeypatch.setattr(SqlExecutor, "_materialize_semijoin", boom)
    with pytest.raises(PlannerError, match="only supported in WHERE"):
        sql.execute("SELECT dimA, COUNT(*) FROM test GROUP BY dimA "
                    "HAVING COUNT(*) IN (SELECT metLong FROM test LIMIT 1)")


def test_tables_of_sees_subquery_and_union_tables(sql):
    tables, is_meta = sql.tables_of(
        "SELECT COUNT(*) FROM test WHERE dimA IN "
        "(SELECT dimA FROM test GROUP BY dimA)")
    assert tables == ["test"]
    assert not is_meta
    tables, is_meta = sql.tables_of(
        "SELECT dimA FROM test UNION ALL "
        "SELECT TABLE_NAME FROM INFORMATION_SCHEMA.TABLES")
    assert tables == ["test"]
    assert is_meta


def test_zero_row_scalar_paths_agree(sql):
    """The synthesized identity row (time bound prunes all segments) must
    match the engine's covered-but-empty row (filter matches nothing) for
    EVERY aggregator type, including approximate ones."""
    q1 = ("SELECT COUNT(*) c, SUM(metLong) s, MAX(metFloat) mx, "
          "APPROX_COUNT_DISTINCT(dimA) u FROM test WHERE ")
    _, pruned = sql.execute(q1 + "__time >= TIMESTAMP '3000-01-01'")
    _, nomatch = sql.execute(q1 + "dimA = 'no_such_value'")
    assert pruned == nomatch
