"""TopN + GroupBy engines vs numpy golden results (reference:
TopNQueryRunnerTest / GroupByQueryRunnerTest patterns)."""
import numpy as np
import pytest

from druid_tpu.engine.executor import QueryExecutor
from druid_tpu.query import (AndFilter, BoundFilter, CountAggregator,
                             DoubleSumAggregator, InFilter, LongSumAggregator,
                             OrFilter, SelectorFilter)
from druid_tpu.query.model import (DefaultLimitSpec, ExtractionDimensionSpec,
                                   GreaterThanHaving, GroupByQuery,
                                   OrderByColumnSpec, SubstringExtractionFn,
                                   TopNQuery)
from druid_tpu.utils.intervals import Interval

from conftest import DAY, rows_as_frame

AGGS = [CountAggregator("rows"), LongSumAggregator("sumLong", "metLong"),
        DoubleSumAggregator("sumDouble", "metDouble")]


def golden_groupby(frames, masks, dims):
    groups = {}
    for frame, mask in zip(frames, masks):
        idx = np.flatnonzero(mask)
        for i in idx:
            key = tuple(frame[d][i] for d in dims)
            g = groups.setdefault(key, {"rows": 0, "sumLong": 0, "sumDouble": 0.0})
            g["rows"] += 1
            g["sumLong"] += int(frame["metLong"][i])
            g["sumDouble"] += float(frame["metDouble"][i])
    return groups


def test_topn_basic(segment):
    ex = QueryExecutor([segment])
    q = TopNQuery.of("test", DAY, "dimB", metric="sumLong", threshold=5,
                     aggregations=AGGS)
    rows = ex.run(q)
    assert len(rows) == 1
    result = rows[0]["result"]
    assert len(result) == 5
    frame = rows_as_frame(segment)
    groups = golden_groupby([frame], [np.ones(segment.n_rows, bool)], ["dimB"])
    expected_order = sorted(groups.items(), key=lambda kv: -kv[1]["sumLong"])[:5]
    for entry, (key, g) in zip(result, expected_order):
        assert entry["dimB"] == key[0]
        assert entry["rows"] == g["rows"]
        assert entry["sumLong"] == g["sumLong"]
        assert entry["sumDouble"] == pytest.approx(g["sumDouble"])


def test_topn_with_filter_and_inverted(segment):
    ex = QueryExecutor([segment])
    flt = InFilter("dimA", ("v00000001", "v00000002", "v00000003"))
    q = TopNQuery.of("test", DAY, "dimA", metric="rows", threshold=2,
                     aggregations=AGGS, filter=flt, metric_ordering="inverted")
    rows = ex.run(q)
    result = rows[0]["result"]
    frame = rows_as_frame(segment)
    mask = np.isin(frame["dimA"], ["v00000001", "v00000002", "v00000003"])
    groups = golden_groupby([frame], [mask], ["dimA"])
    expected = sorted(groups.items(), key=lambda kv: kv[1]["rows"])[:2]
    assert [e["dimA"] for e in result] == [k[0] for k, _ in expected]


def test_topn_lexicographic(segment):
    ex = QueryExecutor([segment])
    q = TopNQuery.of("test", DAY, "dimA", metric="", threshold=3,
                     aggregations=[CountAggregator("rows")],
                     metric_ordering="lexicographic")
    rows = ex.run(q)
    vals = [e["dimA"] for e in rows[0]["result"]]
    assert vals == sorted(vals)
    assert len(vals) == 3


def test_topn_multi_segment_merge(segments):
    ex = QueryExecutor(segments)
    iv = Interval.of("2026-01-01", "2026-01-05")
    q = TopNQuery.of("test", iv, "dimB", metric="sumDouble", threshold=10,
                     aggregations=AGGS)
    rows = ex.run(q)
    result = rows[0]["result"]
    frames = [rows_as_frame(s) for s in segments]
    masks = [np.ones(s.n_rows, bool) for s in segments]
    groups = golden_groupby(frames, masks, ["dimB"])
    expected = sorted(groups.items(), key=lambda kv: -kv[1]["sumDouble"])[:10]
    for entry, (key, g) in zip(result, expected):
        assert entry["dimB"] == key[0]
        assert entry["sumDouble"] == pytest.approx(g["sumDouble"])
        assert entry["rows"] == g["rows"]


def test_groupby_two_dims(segment):
    ex = QueryExecutor([segment])
    q = GroupByQuery.of("test", DAY, ["dimA", "dimB"], AGGS)
    rows = ex.run(q)
    frame = rows_as_frame(segment)
    groups = golden_groupby([frame], [np.ones(segment.n_rows, bool)],
                            ["dimA", "dimB"])
    assert len(rows) == len(groups)
    for row in rows:
        ev = row["event"]
        g = groups[(ev["dimA"], ev["dimB"])]
        assert ev["rows"] == g["rows"]
        assert ev["sumLong"] == g["sumLong"]
        assert ev["sumDouble"] == pytest.approx(g["sumDouble"])


def test_groupby_filtered_or(segment):
    ex = QueryExecutor([segment])
    flt = OrFilter((SelectorFilter("dimA", "v00000001"),
                    AndFilter((SelectorFilter("dimA", "v00000002"),
                               BoundFilter("metLong", lower="50",
                                           ordering="numeric")))))
    q = GroupByQuery.of("test", DAY, ["dimA"], AGGS, filter=flt)
    rows = ex.run(q)
    frame = rows_as_frame(segment)
    mask = (frame["dimA"] == "v00000001") | (
        (frame["dimA"] == "v00000002") & (frame["metLong"] >= 50))
    groups = golden_groupby([frame], [mask], ["dimA"])
    assert len(rows) == len(groups)
    for row in rows:
        ev = row["event"]
        assert ev["rows"] == groups[(ev["dimA"],)]["rows"]


def test_groupby_high_cardinality_host_path(segment):
    """dimHi (5000) x dimB (100) exceeds the dense grid limit -> host path."""
    ex = QueryExecutor([segment])
    q = GroupByQuery.of("test", DAY, ["dimHi", "dimB"], [CountAggregator("rows")],
                        granularity="hour")
    rows = ex.run(q)
    frame = rows_as_frame(segment)
    # spot-check totals
    assert sum(r["event"]["rows"] for r in rows) == segment.n_rows
    # spot-check one group
    ev = rows[0]["event"]
    st = rows[0]["timestamp"]
    mask = ((frame["dimHi"] == ev["dimHi"]) & (frame["dimB"] == ev["dimB"])
            & (frame["__time"] >= st) & (frame["__time"] < st + 3600_000))
    assert ev["rows"] == int(mask.sum())


def test_groupby_having_and_limit(segment):
    ex = QueryExecutor([segment])
    limit = DefaultLimitSpec((OrderByColumnSpec("sumLong", "descending",
                                                "numeric"),), limit=3)
    q = GroupByQuery.of("test", DAY, ["dimA"], AGGS,
                        having=GreaterThanHaving("rows", 100),
                        limit_spec=limit)
    rows = ex.run(q)
    assert len(rows) <= 3
    vals = [r["event"]["sumLong"] for r in rows]
    assert vals == sorted(vals, reverse=True)
    assert all(r["event"]["rows"] > 100 for r in rows)


def test_groupby_extraction_dimension(segment):
    ex = QueryExecutor([segment])
    # substring(0,9) of dimB "v000000xx" collapses values by prefix
    fn = SubstringExtractionFn(0, 9)
    spec = ExtractionDimensionSpec("dimB", "prefix", fn)
    q = GroupByQuery.of("test", DAY, [spec], [CountAggregator("rows")])
    rows = ex.run(q)
    frame = rows_as_frame(segment)
    expected = {}
    for v in frame["dimB"]:
        expected[v[:9]] = expected.get(v[:9], 0) + 1
    assert {r["event"]["prefix"]: r["event"]["rows"] for r in rows} == expected


def test_groupby_multi_segment(segments):
    ex = QueryExecutor(segments)
    iv = Interval.of("2026-01-01", "2026-01-05")
    q = GroupByQuery.of("test", iv, ["dimA"], AGGS, granularity="day")
    rows = ex.run(q)
    frames = [rows_as_frame(s) for s in segments]
    for row in rows:
        st = row["timestamp"]
        ev = row["event"]
        total = 0
        for f in frames:
            m = ((f["__time"] >= st) & (f["__time"] < st + 86400_000)
                 & (f["dimA"] == ev["dimA"]))
            total += int(m.sum())
        assert ev["rows"] == total


def test_groupby_numeric_long_dimension(segment):
    """Grouping by a LONG metric column (numeric dimension handler): keys
    are the numeric VALUES, exact."""
    ex = QueryExecutor([segment])
    q = GroupByQuery.of("test", DAY, ["metLong"], [CountAggregator("rows")])
    rows = ex.run(q)
    frame = rows_as_frame(segment)
    vals, counts = np.unique(frame["metLong"], return_counts=True)
    got = {r["event"]["metLong"]: r["event"]["rows"] for r in rows}
    assert got == {int(v): int(c) for v, c in zip(vals, counts)}
    assert all(isinstance(k, int) for k in got)


def test_groupby_numeric_double_dimension(segment):
    ex = QueryExecutor([segment])
    q = GroupByQuery.of("test", DAY, ["metDouble"],
                        [CountAggregator("rows")])
    rows = ex.run(q)
    frame = rows_as_frame(segment)
    vals, counts = np.unique(frame["metDouble"], return_counts=True)
    got = {r["event"]["metDouble"]: r["event"]["rows"] for r in rows}
    assert len(got) == len(vals)
    assert got == {float(v): int(c) for v, c in zip(vals, counts)}


def test_groupby_mixed_string_numeric_dims(segment):
    ex = QueryExecutor([segment])
    q = GroupByQuery.of("test", DAY, ["dimA", "metLong"], AGGS)
    rows = ex.run(q)
    frame = rows_as_frame(segment)
    want = golden_groupby([frame], [np.ones(segment.n_rows, bool)],
                          ["dimA", "metLong"])
    assert len(rows) == len(want)
    for r in rows:
        e = r["event"]
        g = want[(e["dimA"], e["metLong"])]
        assert e["rows"] == g["rows"] and e["sumLong"] == g["sumLong"]


def test_groupby_numeric_multi_segment_merge(segments):
    """Per-segment numeric value dictionaries differ; the host merge must
    reconcile them by VALUE."""
    ex = QueryExecutor(segments)
    iv = Interval.of("2026-01-01", "2026-01-05")
    q = GroupByQuery.of("test", iv, ["metLong"],
                        [CountAggregator("rows"),
                         LongSumAggregator("sumLong", "metLong")])
    rows = ex.run(q)
    frames = [rows_as_frame(s) for s in segments]
    allv = np.concatenate([f["metLong"] for f in frames])
    vals, counts = np.unique(allv, return_counts=True)
    got = {r["event"]["metLong"]: r["event"]["rows"] for r in rows}
    assert got == {int(v): int(c) for v, c in zip(vals, counts)}
    for r in rows:
        e = r["event"]
        assert e["sumLong"] == e["metLong"] * e["rows"]


def test_topn_numeric_dimension(segment):
    ex = QueryExecutor([segment])
    q = TopNQuery.of("test", DAY, "metLong", metric="rows", threshold=5,
                     aggregations=[CountAggregator("rows")])
    rows = ex.run(q)
    frame = rows_as_frame(segment)
    vals, counts = np.unique(frame["metLong"], return_counts=True)
    order = np.argsort(-counts, kind="stable")
    want_top = int(counts[order[0]])
    got = rows[0]["result"]
    assert len(got) == 5
    assert got[0]["rows"] == want_top
    assert all(isinstance(e["metLong"], int) for e in got)


def test_sql_group_by_numeric(segment):
    from druid_tpu.sql import SqlExecutor
    sql = SqlExecutor(QueryExecutor([segment]))
    cols, rows = sql.execute(
        "SELECT metLong, COUNT(*) c FROM test GROUP BY metLong "
        "ORDER BY c DESC LIMIT 3")
    frame = rows_as_frame(segment)
    vals, counts = np.unique(frame["metLong"], return_counts=True)
    assert rows[0][1] == int(counts.max())


def test_groupby_missing_dimension(segment):
    ex = QueryExecutor([segment])
    q = GroupByQuery.of("test", DAY, ["nonexistent"], [CountAggregator("rows")])
    rows = ex.run(q)
    assert len(rows) == 1
    assert rows[0]["event"]["nonexistent"] == ""
    assert rows[0]["event"]["rows"] == segment.n_rows
