"""tracecheck rule tests: positive/negative/suppression snippets per rule,
the unused-suppression audit, the --only subset flag, the scan cache, and
real-tree mutation gates (the acceptance contract: editing a BlockSpec
shape, an accumulator identity dtype, or a fold kernel's device_combine in
a fixture must fail `python -m tools.druidlint --fail-on-new`)."""
import json
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

from tools.druidlint import check_source  # noqa: E402
from tools.druidlint.core import LintConfig  # noqa: E402
from tools.druidlint.tracecheck import Sym, SymEval, load_contracts  # noqa: E402

PALLAS = "druid_tpu/engine/pallas_agg.py"
MEGA = "druid_tpu/engine/megakernel.py"
ENGINE = "druid_tpu/engine/foo.py"
KMOD = "druid_tpu/engine/kernels.py"


def cfg(**kw):
    return LintConfig(root=str(REPO_ROOT), **kw)


def rules_hit(source, path=ENGINE, config=None):
    return {f.rule for f in check_source(textwrap.dedent(source), path,
                                         config or cfg())}


# ---- the Sym domain -------------------------------------------------------

def test_sym_interval_and_stride_arithmetic():
    contracts = load_contracts(str(REPO_ROOT))
    env = {"BLK": Sym(1024, 2048, 128), "num_total": Sym(1, 131072, 1)}
    ev = SymEval(env, contracts)
    import ast as _ast

    def e(src):
        return ev.eval(_ast.parse(src, mode="eval").body)

    r = e("BLK // 128")
    assert (r.lo, r.hi) == (8, 16)
    g2 = e("_round_up(num_total, 128) + 1024")
    assert g2.multiple_of(128) and g2.hi == 131072 + 1024
    rows = e("(_round_up(num_total, 128) + 1024) // 128")
    assert rows.hi == (131072 + 1024) // 128
    assert e("MAX_W").value == contracts["MAX_W"]   # contract constant
    assert e("unknown_name") is None
    # stride of min/max must divide EVERY argument, not the first two
    env["u"] = Sym(100, 300, 1)
    assert not e("max(BLK, BLK, u)").multiple_of(128)


def test_rank0_blockspec_does_not_crash():
    src = """
    from jax.experimental import pallas as pl
    spec = pl.BlockSpec((), lambda: ())
    """
    check_source(textwrap.dedent(src), PALLAS, cfg())   # no IndexError


# ---- pallas-tile-shape ----------------------------------------------------

def test_unaligned_last_dim_flagged():
    src = """
    from jax.experimental import pallas as pl
    grid_spec = pl.GridSpec(
        grid=(8,),
        in_specs=[pl.BlockSpec((8, 64), lambda i: (i, 0))],
    )
    """
    assert "pallas-tile-shape" in rules_hit(src, PALLAS)


def test_aligned_contract_constant_shape_ok():
    src = """
    from jax.experimental import pallas as pl
    from druid_tpu.engine.contracts import LANE
    grid_spec = pl.GridSpec(
        grid=(8,),
        in_specs=[pl.BlockSpec((8, LANE), lambda i: (i, 0))],
    )
    """
    assert "pallas-tile-shape" not in rules_hit(src, PALLAS)


def test_symbolic_shape_resolves_through_declared_bounds():
    # BLK/W/num_total come from SYMBOL_BOUNDS (plan_window is opaque);
    # the derived (R, 128) and (G2 // 128, 128) must be accepted
    src = """
    from jax.experimental import pallas as pl

    def build(span, num_total):
        BLK, W = plan_window(span)
        R = BLK // 128
        G2 = _round_up(num_total, 128) + W
        return pl.GridSpec(
            grid=(8,),
            in_specs=[pl.BlockSpec((R, 128), lambda i: (i, 0))],
            out_specs=[pl.BlockSpec((G2 // 128, 128), lambda i: (0, 0))],
        )
    """
    assert "pallas-tile-shape" not in rules_hit(src, PALLAS)


def test_unresolvable_shape_flagged():
    src = """
    from jax.experimental import pallas as pl

    def build(mystery):
        return pl.GridSpec(
            grid=(8,),
            in_specs=[pl.BlockSpec((mystery, 128), lambda i: (i, 0))],
        )
    """
    hits = check_source(textwrap.dedent(src), PALLAS, cfg())
    assert any(f.rule == "pallas-tile-shape" and "resolvable" in f.message
               for f in hits)


def test_index_map_arity_mismatch_flagged():
    src = """
    from jax.experimental import pallas as pl
    grid_spec = pl.GridSpec(
        grid=(8, 4),
        in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
    )
    """
    hits = check_source(textwrap.dedent(src), PALLAS, cfg())
    assert any(f.rule == "pallas-tile-shape" and "grid" in f.message
               for f in hits)


def test_index_map_rank_mismatch_flagged():
    src = """
    from jax.experimental import pallas as pl
    grid_spec = pl.GridSpec(
        grid=(8,),
        in_specs=[pl.BlockSpec((8, 128), lambda i: (i,))],
    )
    """
    hits = check_source(textwrap.dedent(src), PALLAS, cfg())
    assert any(f.rule == "pallas-tile-shape" and "coordinate" in f.message
               for f in hits)


def test_out_spec_out_shape_drift_flagged():
    src = """
    import jax
    from jax.experimental import pallas as pl

    def build(num_total):
        G2 = _round_up(num_total, 128)
        out_shapes = [jax.ShapeDtypeStruct((G2 // 64, 128), int)]
        return pl.GridSpec(
            grid=(8,),
            out_specs=[pl.BlockSpec((G2 // 128, 128), lambda i: (0, 0))],
        ), out_shapes
    """
    hits = check_source(textwrap.dedent(src), PALLAS, cfg())
    assert any(f.rule == "pallas-tile-shape" and "out_shape" in f.message
               for f in hits)


def test_tile_shape_outside_pallas_modules_ignored():
    src = """
    from jax.experimental import pallas as pl
    grid_spec = pl.GridSpec(
        grid=(8,),
        in_specs=[pl.BlockSpec((8, 64), lambda i: (i, 0))],
    )
    """
    assert "pallas-tile-shape" not in rules_hit(src, ENGINE)


def test_tile_shape_suppression():
    src = """
    from jax.experimental import pallas as pl
    grid_spec = pl.GridSpec(
        grid=(8,),
        in_specs=[pl.BlockSpec((8, 64), lambda i: (i, 0))],  # druidlint: disable=pallas-tile-shape
    )
    """
    assert "pallas-tile-shape" not in rules_hit(src, PALLAS)


# ---- pallas-accum-dtype ---------------------------------------------------

def test_int_identity_with_float_ctor_flagged():
    src = """
    import jax.numpy as jnp
    ident = jnp.float32(2**31 - 1)
    """
    assert "pallas-accum-dtype" in rules_hit(src, PALLAS)


def test_identities_with_contract_dtypes_ok():
    src = """
    import jax.numpy as jnp
    a = jnp.int32(2**31 - 1)
    b = jnp.int32(-(2**31))
    c = jnp.float32(jnp.inf)
    d = jnp.float32(-jnp.inf)
    e = jnp.int32(0)
    """
    assert "pallas-accum-dtype" not in rules_hit(src, PALLAS)


def test_float_identity_with_int_ctor_flagged():
    src = """
    import jax.numpy as jnp
    ident = jnp.int32(jnp.inf)
    """
    assert "pallas-accum-dtype" in rules_hit(src, PALLAS)


def test_x64_dtype_inside_kernel_body_flagged():
    src = """
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def kernel(ref, out):
        out[:, :] = ref[:, :].astype(jnp.int64)

    def run(x):
        return pl.pallas_call(kernel, out_shape=None)(x)
    """
    hits = check_source(textwrap.dedent(src), PALLAS, cfg())
    assert any(f.rule == "pallas-accum-dtype" and "kernel body" in f.message
               for f in hits)


def test_x64_widening_outside_kernel_ok_for_accum_rule():
    src = """
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def kernel(ref, out):
        out[:, :] = ref[:, :]

    def run(x):
        outs = pl.pallas_call(kernel, out_shape=None)(x)
        return outs.astype(jnp.int64)  # druidlint: disable=x64-dtype
    """
    assert "pallas-accum-dtype" not in rules_hit(src, PALLAS)


# ---- vmem-budget ----------------------------------------------------------

def test_over_budget_tiles_flagged():
    src = """
    from jax.experimental import pallas as pl
    grid_spec = pl.GridSpec(
        grid=(8,),
        in_specs=[pl.BlockSpec((32768, 128), lambda i: (i, 0))],
    )
    """
    assert "vmem-budget" in rules_hit(src, PALLAS)


def test_within_budget_tiles_ok():
    src = """
    from jax.experimental import pallas as pl
    grid_spec = pl.GridSpec(
        grid=(8,),
        in_specs=[pl.BlockSpec((16, 128), lambda i: (i, 0))],
    )
    """
    assert "vmem-budget" not in rules_hit(src, PALLAS)


def test_vmem_cap_config_override():
    src = """
    from jax.experimental import pallas as pl
    grid_spec = pl.GridSpec(
        grid=(8,),
        in_specs=[pl.BlockSpec((16, 128), lambda i: (i, 0))],
    )
    """
    # 16*128*4 = 8192 bytes > a 4096-byte cap
    assert "vmem-budget" in rules_hit(src, PALLAS,
                                      cfg(vmem_cap_bytes=4096))


def test_unbounded_multiplicity_flagged():
    src = """
    from jax.experimental import pallas as pl

    def build(things):
        return pl.GridSpec(
            grid=(8,),
            in_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))] * len(things),
        )
    """
    hits = check_source(textwrap.dedent(src), PALLAS, cfg())
    assert any(f.rule == "vmem-budget" and "multiplicity" in f.message
               for f in hits)


def test_filter_bitmap_word_tiles_budgeted():
    """Device filter-bitmap words (engine/filters.py): the worst-case word
    tile is (Rw32, 128) with Rw32 ≤ contracts.FILTER_WORDS_PER_BLOCK —
    SYMBOL_BOUNDS covers it, so a kernel streaming bitmap words stays
    under the vmem budget without per-site annotations."""
    src = """
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def build(span, Rw32):
        BLK, W = plan_window(span)
        R = BLK // 128
        return pl.GridSpec(
            grid=(8,),
            in_specs=[pl.BlockSpec((R, 128), lambda i: (i, jnp.int32(0))),
                      pl.BlockSpec((Rw32, 128),
                                   lambda i: (i, jnp.int32(0)))],
        )
    """
    hits = check_source(textwrap.dedent(src), PALLAS, cfg())
    assert not [f for f in hits if f.rule in ("vmem-budget",
                                              "pallas-tile-shape")], hits


def test_filter_bitmap_word_tiles_oversize_flagged():
    """...and an unboundedly-scaled word tile still blows the cap — the
    bound is a ceiling, not a waiver."""
    src = """
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def build(Rw32):
        return pl.GridSpec(
            grid=(8,),
            in_specs=[pl.BlockSpec((Rw32 * 65536, 128),
                                   lambda i: (i, jnp.int32(0)))],
        )
    """
    assert "vmem-budget" in rules_hit(src, PALLAS)


# ---- x64-dtype ------------------------------------------------------------

def test_x64_in_traced_fn_flagged():
    src = """
    import jax
    import jax.numpy as jnp

    def f(x):
        return x.astype(jnp.int64)

    fn = jax.jit(f)
    """
    assert "x64-dtype" in rules_hit(src, ENGINE)


def test_x64_gated_fn_ok():
    src = """
    import jax
    import jax.numpy as jnp

    def f(x):
        dt = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32
        return x.astype(dt)

    fn = jax.jit(f)
    """
    assert "x64-dtype" not in rules_hit(src, ENGINE)


def test_x64_in_untraced_host_fn_ok():
    src = """
    import jax.numpy as jnp

    def host_post(x):
        return x.astype(jnp.int64)
    """
    assert "x64-dtype" not in rules_hit(src, ENGINE)


def test_x64_outside_device_modules_ok():
    src = """
    import jax
    import jax.numpy as jnp

    def f(x):
        return x.astype(jnp.int64)

    fn = jax.jit(f)
    """
    assert "x64-dtype" not in rules_hit(src, "druid_tpu/cluster/foo.py")


def test_x64_suppression_with_rationale():
    src = """
    import jax
    import jax.numpy as jnp

    def f(x):
        # exactness contract, x64 globally on
        return x.astype(jnp.int64)  # druidlint: disable=x64-dtype

    fn = jax.jit(f)
    """
    assert "x64-dtype" not in rules_hit(src, ENGINE)


# ---- agg-contract ---------------------------------------------------------

AGG_BODY = """
    def signature(self):
        return "{sig}"

    def update(self, cols, mask, keys, num, aux):
        return None

    def combine(self, a, b):
        return a

    def empty_state(self, n):
        return None
"""


def _agg(name, sig, extra="", rk=None):
    rk_line = f"    reduce_kind = \"{rk}\"\n" if rk else ""
    return (f"class {name}(AggKernel):\n" + rk_line
            + AGG_BODY.format(sig=sig) + extra)


def test_fold_kernel_without_device_combine_flagged():
    src = "from druid_tpu.engine.kernels import AggKernel\n" \
        + _agg("BadKernel", "bad")
    assert "agg-contract" in rules_hit(src, KMOD)


def test_fold_kernel_with_device_combine_ok():
    src = "from druid_tpu.engine.kernels import AggKernel\n" \
        + _agg("GoodKernel", "good",
               "\n    def device_combine(self, a, b):\n        return a\n")
    assert "agg-contract" not in rules_hit(src, KMOD)


def test_sum_kernel_without_device_combine_ok():
    src = "from druid_tpu.engine.kernels import AggKernel\n" \
        + _agg("SumLike", "sumlike", rk="sum")
    assert "agg-contract" not in rules_hit(src, KMOD)


def test_dynamic_reduce_kind_skips_fold_check():
    src = ("from druid_tpu.engine.kernels import AggKernel\n"
           + _agg("DynKernel", "dyn",
                  "\n    def __init__(self, child):\n"
                  "        self.reduce_kind = child.reduce_kind\n"))
    assert "agg-contract" not in rules_hit(src, KMOD)


def test_missing_required_method_flagged():
    src = ("from druid_tpu.engine.kernels import AggKernel\n"
           "class NoUpdate(AggKernel):\n"
           "    reduce_kind = \"sum\"\n"
           "    def signature(self):\n"
           "        return \"nu\"\n"
           "    def combine(self, a, b):\n"
           "        return a\n"
           "    def empty_state(self, n):\n"
           "        return None\n")
    hits = check_source(src, KMOD, cfg())
    assert any(f.rule == "agg-contract" and "update" in f.message
               for f in hits)


def test_duplicate_signatures_flagged():
    src = ("from druid_tpu.engine.kernels import AggKernel\n"
           + _agg("KernA", "same", rk="sum")
           + _agg("KernB", "same", rk="sum"))
    hits = check_source(src, KMOD, cfg())
    assert any(f.rule == "agg-contract" and "duplicated" in f.message
               for f in hits)


def test_distinct_signatures_ok():
    src = ("from druid_tpu.engine.kernels import AggKernel\n"
           + _agg("KernA", "a", rk="sum") + _agg("KernB", "b", rk="sum"))
    assert "agg-contract" not in rules_hit(src, KMOD)


def test_agg_contract_covers_ext_modules():
    src = "from druid_tpu.engine.kernels import AggKernel\n" \
        + _agg("ExtKernel", "ext")
    assert "agg-contract" in rules_hit(src, "druid_tpu/ext/custom.py")


# ---- preferred-element-type -----------------------------------------------

def test_dot_general_without_preferred_flagged():
    src = """
    from jax import lax

    def f(a, b):
        return lax.dot_general(a, b, (((1,), (0,)), ((), ())))
    """
    assert "preferred-element-type" in rules_hit(src, ENGINE)


def test_dot_general_with_preferred_ok():
    src = """
    import jax.numpy as jnp
    from jax import lax

    def f(a, b):
        return lax.dot_general(a, b, (((1,), (0,)), ((), ())),
                               preferred_element_type=jnp.int32)
    """
    assert "preferred-element-type" not in rules_hit(src, ENGINE)


def test_host_numpy_matmul_not_flagged():
    src = """
    import numpy as np

    def f(a, b):
        return np.matmul(a, b)
    """
    assert "preferred-element-type" not in rules_hit(src, ENGINE)


# ---- unused-suppression ---------------------------------------------------

def test_dead_pragma_reported_with_audit_on():
    src = "x = 1  # druidlint: disable=swallowed-exception\n"
    hits = check_source(src, ENGINE, cfg(report_unused_suppressions=True))
    assert any(f.rule == "unused-suppression" for f in hits)


def test_dead_pragma_silent_without_audit():
    src = "x = 1  # druidlint: disable=swallowed-exception\n"
    assert "unused-suppression" not in rules_hit(src)


def test_live_pragma_not_reported():
    src = textwrap.dedent("""
    def f():
        try:
            g()
        except Exception:  # druidlint: disable=swallowed-exception
            pass
    """)
    hits = check_source(src, ENGINE, cfg(report_unused_suppressions=True))
    assert not any(f.rule == "unused-suppression" for f in hits)
    assert not any(f.rule == "swallowed-exception" for f in hits)


def test_typoed_rule_name_reported():
    src = "x = 1  # druidlint: disable=swalloed-exception\n"
    hits = check_source(src, ENGINE, cfg(report_unused_suppressions=True))
    assert any(f.rule == "unused-suppression"
               and "no registered rule" in f.message for f in hits)


def test_unused_suppression_rule_not_audited_under_only_subset():
    # with a rule subset the unheld pragmas' usage is unknowable — no noise
    src = "x = 1  # druidlint: disable=swallowed-exception\n"
    hits = check_source(src, ENGINE, cfg(
        report_unused_suppressions=True,
        rules=["jit-in-hot-path", "unused-suppression"]))
    assert not any(f.rule == "unused-suppression" for f in hits)


# ---- CLI: --only, cache, real-tree mutation gates -------------------------

def _run_cli(*args, cwd=REPO_ROOT):
    return subprocess.run(
        [sys.executable, "-m", "tools.druidlint", *args],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120)


def test_only_flag_runs_subset(tmp_path):
    target = tmp_path / "druid_tpu" / "engine" / "bad.py"
    target.parent.mkdir(parents=True)
    target.write_text(
        "import jax\nimport jax.numpy as jnp\n"
        "def f(x):\n"
        "    try:\n"
        "        return x.astype(jnp.int64)\n"
        "    except Exception:\n"
        "        pass\n"
        "fn = jax.jit(f)\n")
    both = _run_cli("--root", str(tmp_path), "--json", "--no-cache",
                    "druid_tpu")
    rules = {f["rule"] for f in json.loads(both.stdout)["findings"]}
    assert {"x64-dtype", "swallowed-exception"} <= rules
    only = _run_cli("--root", str(tmp_path), "--json", "--no-cache",
                    "--only", "x64-dtype", "druid_tpu")
    rules = {f["rule"] for f in json.loads(only.stdout)["findings"]}
    assert rules == {"x64-dtype"}


def test_only_flag_rejects_unknown_rule(tmp_path):
    (tmp_path / "druid_tpu").mkdir()
    p = _run_cli("--root", str(tmp_path), "--only", "no-such-rule",
                 "druid_tpu")
    assert p.returncode == 2
    assert "unknown rules" in p.stderr


def test_scan_cache_hits_and_invalidates(tmp_path):
    target = tmp_path / "druid_tpu" / "mod.py"
    target.parent.mkdir(parents=True)
    target.write_text("def f():\n    try:\n        g()\n"
                      "    except Exception:\n        pass\n")
    cold = _run_cli("--root", str(tmp_path), "--json", "druid_tpu")
    cache = tmp_path / ".druidlint-cache.json"
    assert cache.exists()
    warm = _run_cli("--root", str(tmp_path), "--json", "druid_tpu")
    assert json.loads(cold.stdout)["findings"] == \
        json.loads(warm.stdout)["findings"]
    # edit the file: the cached findings must be dropped, not resurrected
    target.write_text("def f():\n    return 1\n")
    fixed = _run_cli("--root", str(tmp_path), "--json", "druid_tpu")
    assert json.loads(fixed.stdout)["findings"] == []


def test_restricted_scan_does_not_truncate_cache(tmp_path):
    bad = "def f():\n    try:\n        g()\n    except Exception:\n        pass\n"
    (tmp_path / "druid_tpu").mkdir()
    (tmp_path / "druid_tpu" / "a.py").write_text(bad)
    (tmp_path / "tools").mkdir()
    (tmp_path / "tools" / "b.py").write_text(bad)
    _run_cli("--root", str(tmp_path), "--json")               # full scan
    _run_cli("--root", str(tmp_path), "--json", "druid_tpu")  # restricted
    cached = json.loads((tmp_path / ".druidlint-cache.json").read_text())
    assert set(cached["files"]) == {"druid_tpu/a.py", "tools/b.py"}


def test_update_baseline_rejects_only_subset(tmp_path):
    (tmp_path / "druid_tpu").mkdir()
    p = _run_cli("--root", str(tmp_path), "--update-baseline",
                 "--only", "vmem-budget")
    assert p.returncode == 2
    assert "full scan" in p.stderr


MUTATIONS = {
    "blockspec-shape": (
        "druid_tpu/engine/pallas_agg.py", "pl.BlockSpec((R, 128)",
        "pl.BlockSpec((R, 120)", "pallas-tile-shape"),
    "accum-identity-dtype": (
        "druid_tpu/engine/pallas_agg.py", "ident = jnp.int32(2**31 - 1)",
        "ident = jnp.float32(2**31 - 1)", "pallas-accum-dtype"),
    "out-grid-rows": (
        "druid_tpu/engine/pallas_agg.py",
        "jax.ShapeDtypeStruct((G2 // 128, 128), dt)",
        "jax.ShapeDtypeStruct((G2 // 64, 128), dt)", "pallas-tile-shape"),
    "drop-device-combine": (
        # FirstLastKernel is fold-kind: renaming ITS device_combine (the
        # base-class raise-stub keeps its name) breaks the fold contract
        "druid_tpu/engine/kernels.py",
        "    def device_combine(self, a, b):\n"
        "        import jax.numpy as jnp\n"
        "        at, av, ah = a",
        "    def renamed_combine(self, a, b):\n"
        "        import jax.numpy as jnp\n"
        "        at, av, ah = a", "agg-contract"),
    "drop-preferred-element-type": (
        "druid_tpu/engine/mmagg.py",
        "preferred_element_type=jnp.int32)", "),",
        "preferred-element-type"),
    "mega-mask-tile-unaligned": (
        # the megakernel's (1, 128) mask word tile: an unaligned last dim
        # compiles on the interpreter but fails on-chip — lint must catch
        "druid_tpu/engine/megakernel.py", "pl.BlockSpec((1, 128),",
        "pl.BlockSpec((1, 120),", "pallas-tile-shape"),
    "mega-key-sentinel-dtype": (
        # the in-kernel masked-key sentinel must stay the int32 identity
        "druid_tpu/engine/megakernel.py", "kb, jnp.int32(2**31 - 1))",
        "kb, jnp.float32(2**31 - 1))", "pallas-accum-dtype"),
}


@pytest.mark.parametrize("mutation", sorted(MUTATIONS))
def test_real_tree_mutation_fails_gate(mutation, tmp_path):
    """Mutating a real engine contract in a fixture copy of the tree is
    caught by --fail-on-new (the acceptance criterion for tracecheck)."""
    rel, old, new, expect_rule = MUTATIONS[mutation]
    src = (REPO_ROOT / rel).read_text()
    assert old in src, f"mutation anchor missing from {rel}"
    target = tmp_path / rel
    target.parent.mkdir(parents=True, exist_ok=True)
    target.write_text(src.replace(old, new, 1))
    proc = _run_cli("--root", str(tmp_path), "--fail-on-new", "--json",
                    "--no-cache", "druid_tpu")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    rules = {f["rule"] for f in json.loads(proc.stdout)["findings"]}
    assert expect_rule in rules, (mutation, rules)


def test_real_tree_scans_clean_with_tracecheck():
    """The shipped engine passes every tracecheck rule with no baseline
    entries (strict gate, no grandfathering)."""
    proc = _run_cli("--fail-on-new", "--no-cache", "--only",
                    "pallas-tile-shape,pallas-accum-dtype,vmem-budget,"
                    "x64-dtype,agg-contract,preferred-element-type")
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---- shard-spec -----------------------------------------------------------

SHARD = "druid_tpu/parallel/speclayout.py"

_SHARD_OK = """
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    def body(stacked, time0s, aux):
        counts = stacked
        merged = aux
        return counts, merged

    def run(mesh, xs, t0s, aux):
        axis = mesh.axis_names[0]
        f = shard_map(body, mesh=mesh, in_specs=(P(axis, None), P(axis), P()),
                      out_specs=(P(), P()))
        return f(xs, t0s, aux)
"""


def test_shard_spec_ok_passes():
    assert "shard-spec" not in rules_hit(_SHARD_OK, SHARD)


def test_shard_spec_in_arity_mismatch_flagged():
    src = _SHARD_OK.replace("in_specs=(P(axis, None), P(axis), P())",
                            "in_specs=(P(axis, None), P(axis))")
    assert "shard-spec" in rules_hit(src, SHARD)


def test_shard_spec_out_arity_mismatch_flagged():
    src = _SHARD_OK.replace("out_specs=(P(), P())",
                            "out_specs=(P(), P(), P())")
    assert "shard-spec" in rules_hit(src, SHARD)


def test_shard_spec_unknown_axis_flagged():
    src = _SHARD_OK.replace("in_specs=(P(axis, None), P(axis), P())",
                            "in_specs=(P('seg', None), P(axis), P())")
    assert "shard-spec" in rules_hit(src, SHARD)


def test_shard_spec_mesh_literal_axis_ok():
    src = """
    from jax import shard_map
    from jax.sharding import Mesh, PartitionSpec as P

    def body(xs):
        return (xs,)

    def run(devices, xs):
        mesh = Mesh(devices, ("seg",))
        f = shard_map(body, mesh=mesh, in_specs=(P("seg"),),
                      out_specs=(P("seg"),))
        return f(xs)
    """
    assert "shard-spec" not in rules_hit(src, SHARD)


def test_shard_spec_opaque_axis_module_skips_axis_check():
    """No mesh.axis_names binding and no Mesh construction in the module:
    axis provenance cannot be judged, so only arity is checked."""
    src = """
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    def body(xs):
        return (xs,)

    def run(mesh, axis, xs):
        f = shard_map(body, mesh=mesh, in_specs=(P(axis),),
                      out_specs=(P(axis),))
        return f(xs)
    """
    assert "shard-spec" not in rules_hit(src, SHARD)


def test_shard_spec_vararg_body_skips_in_arity():
    src = """
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    def body(*xs):
        return (xs,)

    def run(mesh, xs):
        axis = mesh.axis_names[0]
        f = shard_map(body, mesh=mesh, in_specs=(P(axis), P(axis)),
                      out_specs=(P(axis),))
        return f(xs, xs)
    """
    assert "shard-spec" not in rules_hit(src, SHARD)


def test_shard_spec_only_in_shard_modules():
    src = _SHARD_OK.replace("in_specs=(P(axis, None), P(axis), P())",
                            "in_specs=(P(axis),)")
    assert "shard-spec" not in rules_hit(src, ENGINE)


def test_shard_spec_suppression():
    src = _SHARD_OK.replace(
        "in_specs=(P(axis, None), P(axis), P()),",
        "in_specs=(P(axis, None), P(axis)),  # druidlint: disable=shard-spec")
    assert "shard-spec" not in rules_hit(src, SHARD)


def test_shard_spec_defaulted_params_tolerated():
    src = """
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    def body(xs, t0s, scale=2):
        return (xs,)

    def run(mesh, xs, t0s):
        axis = mesh.axis_names[0]
        f = shard_map(body, mesh=mesh, in_specs=(P(axis), P(axis)),
                      out_specs=(P(axis),))
        return f(xs, t0s)
    """
    assert "shard-spec" not in rules_hit(src, SHARD)


# ---- spec-literal-outside-layout ------------------------------------------

def test_spec_literal_call_outside_layout_flagged():
    src = """
    def place(mesh, axis, arr):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec
        return jax.device_put(arr, NamedSharding(mesh, PartitionSpec(axis)))
    """
    hit = rules_hit(src, "druid_tpu/parallel/distributed.py")
    assert "spec-literal-outside-layout" in hit


def test_spec_literal_alias_outside_layout_flagged():
    src = """
    from jax.sharding import PartitionSpec as P

    def specs(axis):
        return (P(axis, None), P())
    """
    assert "spec-literal-outside-layout" in rules_hit(src, ENGINE)


def test_spec_literal_attribute_call_flagged():
    src = """
    import jax.sharding

    def spec(axis):
        return jax.sharding.PartitionSpec(axis)
    """
    assert "spec-literal-outside-layout" in rules_hit(src, ENGINE)


def test_spec_literal_inside_layout_module_ok():
    src = """
    from jax.sharding import NamedSharding, PartitionSpec

    def column_rows(axis):
        return PartitionSpec(axis, None)

    def sharding(mesh, spec):
        return NamedSharding(mesh, spec)
    """
    assert "spec-literal-outside-layout" not in rules_hit(src, SHARD)


def test_spec_literal_unrelated_module_clean():
    src = """
    def harmless(xs):
        return [x + 1 for x in xs]
    """
    assert "spec-literal-outside-layout" not in rules_hit(src, ENGINE)


def test_real_tree_spec_literals_only_in_layout():
    """The stock tree constructs partition specs in speclayout.py ONLY —
    the sharded rewrite left no stray literals behind."""
    proc = _run_cli("--fail-on-new", "--no-cache", "--only",
                    "spec-literal-outside-layout,shard-spec")
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---- pallas-accum-dtype: index-map i64 regression (BENCH_r04) -------------

def test_untyped_index_map_constant_flagged():
    """REGRESSION for the BENCH_r04 on-TPU break: the offending kernel
    shape — a BlockSpec index_map returning a bare Python int — promotes
    that constant to i64 under the repo-global x64 flag, and Mosaic fails
    to legalize the lowered `func.return (i32, i64)`. The rule must flag
    exactly this shape so the break dies at lint time, not on the chip."""
    src = """
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def build(num_total):
        BLK, W = plan_window(span)
        R = BLK // 128
        return pl.GridSpec(
            grid=(8,),
            in_specs=[pl.BlockSpec((R, 128), lambda i: (i, 0))],
        )
    """
    hits = check_source(textwrap.dedent(src), PALLAS, cfg())
    matches = [f for f in hits if f.rule == "pallas-accum-dtype"]
    assert matches, "the BENCH_r04 index-map shape must be flagged"
    assert any("i64" in f.message and "func.return" in f.message
               for f in matches)


def test_typed_index_map_constants_ok():
    """The fixed shape (constants built typed inside the lambda) passes."""
    src = """
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def build(num_total):
        BLK, W = plan_window(span)
        R = BLK // 128
        return pl.GridSpec(
            grid=(8,),
            in_specs=[pl.BlockSpec((R, 128),
                                   lambda i: (i, jnp.int32(0)))],
        )
    """
    assert "pallas-accum-dtype" not in rules_hit(src, PALLAS)


def test_index_map_i64_check_only_in_pallas_modules():
    src = """
    from jax.experimental import pallas as pl
    spec = pl.BlockSpec((8, 128), lambda i: (i, 0))
    """
    assert "pallas-accum-dtype" not in rules_hit(src, ENGINE)


# ---- vmem-budget over the packed-input spec shapes ------------------------

def test_concatenated_and_comprehension_specs_budgeted():
    """The packed-input kernel builds in_specs as `[dense] * n + [packed
    for Rw in packed_rws]` — the vmem rule must see BOTH sides: dense
    multiplicity through len(dense_fields), packed through a synthesized
    len(packed_rws), and the comprehension variable Rw through
    SYMBOL_BOUNDS. Within budget here; no multiplicity complaint."""
    src = """
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def build(span, num_total, dense_fields, packed_rws):
        BLK, W = plan_window(span)
        R = BLK // 128
        return pl.GridSpec(
            grid=(8,),
            in_specs=([pl.BlockSpec((R, 128),
                                    lambda i: (i, jnp.int32(0)))]
                      * (1 + len(dense_fields))
                      + [pl.BlockSpec((Rw, 128),
                                      lambda i: (i, jnp.int32(0)))
                         for Rw in packed_rws]),
        )
    """
    hits = check_source(textwrap.dedent(src), PALLAS, cfg())
    assert not [f for f in hits if f.rule in ("vmem-budget",
                                              "pallas-tile-shape")], hits


def test_comprehension_specs_count_toward_budget():
    """A comprehension's tiles participate in the worst-case sum: an
    oversized per-entry tile over a bounded iterable must blow the cap."""
    src = """
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def build(packed_rws):
        return pl.GridSpec(
            grid=(8,),
            in_specs=[pl.BlockSpec((32768 * 64, 128),
                                   lambda i: (i, jnp.int32(0)))
                      for Rw in packed_rws],
        )
    """
    assert "vmem-budget" in rules_hit(src, PALLAS)


def test_megakernel_full_program_shape_within_budget():
    """The megakernel's whole in/out spec shape — key tile + (1, 128) mask
    word tile + dense value tiles + packed word tiles + the full accum
    grids — must fit the VMEM budget with every dim statically bounded
    (the gate that made the BENCH_r04 class unrepeatable covers the new
    kernel too)."""
    src = """
    import jax
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    from druid_tpu.engine.contracts import MEGA_MASK_VPW

    def build(span, num_total, dense_fields, packed_rws, out_defs):
        BLK, W = plan_window(span)
        R = BLK // 128
        BPW = MEGA_MASK_VPW // R
        G2 = _round_up(num_total, 128) + W
        out_shapes = [jax.ShapeDtypeStruct((G2 // 128, 128), int)
                      for _ in out_defs]
        return pl.GridSpec(
            grid=(8,),
            in_specs=([pl.BlockSpec((R, 128), lambda i: (i, jnp.int32(0)))]
                      + [pl.BlockSpec((1, 128),
                                      lambda i: (i // BPW, jnp.int32(0)))]
                      + [pl.BlockSpec((R, 128),
                                      lambda i: (i, jnp.int32(0)))]
                      * len(dense_fields)
                      + [pl.BlockSpec((Rw, 128),
                                      lambda i: (i, jnp.int32(0)))
                         for Rw in packed_rws]),
            out_specs=[pl.BlockSpec((G2 // 128, 128),
                                    lambda i: (jnp.int32(0), jnp.int32(0)))]
            * len(out_defs),
        ), out_shapes
    """
    hits = check_source(textwrap.dedent(src), MEGA, cfg())
    assert not [f for f in hits if f.rule in ("vmem-budget",
                                              "pallas-tile-shape",
                                              "pallas-accum-dtype")], hits


def test_megakernel_oversized_mask_tile_flagged():
    """A mask word tile scaled past the budget must still blow the cap —
    the (1, 128) tile is a measured bound, not a waiver."""
    src = """
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def build(num_total):
        G2 = _round_up(num_total, 128) + 1024
        return pl.GridSpec(
            grid=(8,),
            in_specs=[pl.BlockSpec((G2 // 128 * 64, 128),
                                   lambda i: (i, jnp.int32(0)))],
        )
    """
    assert "vmem-budget" in rules_hit(src, MEGA)


def test_megakernel_accum_dtype_rules_active():
    """pallas-accum-dtype covers the megakernel module: a drifted identity
    dtype or an untyped index-map constant fails there exactly like in
    pallas_agg."""
    src = """
    import jax.numpy as jnp
    ident = jnp.float32(-(2**31))
    """
    assert "pallas-accum-dtype" in rules_hit(src, MEGA)
    src2 = """
    import jax.numpy as jnp
    from jax.experimental import pallas as pl
    spec = pl.BlockSpec((8, 128), lambda i: (i, 0))
    """
    assert "pallas-accum-dtype" in rules_hit(src2, MEGA)


def test_megakernel_x64_banned_in_kernel_body():
    src = """
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def kernel(ref, out):
        out[:, :] = ref[:, :].astype(jnp.int64)

    def run(x):
        return pl.pallas_call(kernel, out_shape=None)(x)
    """
    hits = check_source(textwrap.dedent(src), MEGA, cfg())
    assert any(f.rule == "pallas-accum-dtype" and "kernel body" in f.message
               for f in hits)


def test_opaque_comprehension_multiplicity_flagged():
    """Iterating anything but a bare name cannot be bounded — the rule
    must complain rather than silently under-count."""
    src = """
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def build(things):
        return pl.GridSpec(
            grid=(8,),
            in_specs=[pl.BlockSpec((8, 128), lambda i: (i, jnp.int32(0)))
                      for t in things if t],
        )
    """
    hits = check_source(textwrap.dedent(src), PALLAS, cfg())
    assert any(f.rule == "vmem-budget" and "multiplicity" in f.message
               for f in hits)


# ---- cascade run tiles (data/cascade.py run metadata) ---------------------

def test_cascade_run_tile_shapes_within_bounds():
    """Run-metadata tiles resolve through the declared run-count/run-length
    SYMBOL_BOUNDS (contracts: n_runs/Rrun ≤ CASCADE_MAX_RUNS, run_len ≤ a
    batched segment): a kernel streaming run values/ends as (Rrun, 128)
    tiles — the full CASCADE_MAX_RUNS table resident at once — passes
    pallas-tile-shape and stays inside the VMEM budget without per-site
    annotations."""
    src = """
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def build(n_runs, Rrun, run_len):
        rpad = _round_up(n_runs, 128)
        return pl.GridSpec(
            grid=(8,),
            in_specs=[pl.BlockSpec((Rrun, 128),
                                   lambda i: (i, jnp.int32(0))),
                      pl.BlockSpec((rpad // 128, 128),
                                   lambda i: (jnp.int32(0), jnp.int32(0))),
                      pl.BlockSpec((max(run_len // 128, 1), 128),
                                   lambda i: (i, jnp.int32(0)))],
        )
    """
    hits = check_source(textwrap.dedent(src), PALLAS, cfg())
    assert not [f for f in hits if f.rule in ("vmem-budget",
                                              "pallas-tile-shape")], hits


def test_cascade_run_tile_oversized_flagged():
    """Scaling a run tile past the contract cap must blow the VMEM budget
    — the n_runs/Rrun bounds are measured contracts, not waivers."""
    src = """
    import jax.numpy as jnp
    from jax.experimental import pallas as pl

    def build(Rrun):
        return pl.GridSpec(
            grid=(8,),
            in_specs=[pl.BlockSpec((Rrun * 8192, 128),
                                   lambda i: (i, jnp.int32(0)))],
        )
    """
    assert "vmem-budget" in rules_hit(src, PALLAS)


def test_cascade_unbounded_run_symbol_still_flagged():
    """A run-shaped name OUTSIDE the declared bounds stays unresolvable —
    the bounds cover exactly the contract symbols, nothing else."""
    src = ("from jax.experimental import pallas as pl\n"
           "grid_spec = pl.GridSpec(grid=(8,), in_specs=[" +
           "pl.BlockSpec((mystery_runs, 128), lambda i: (i, 0))])\n")
    assert "pallas-tile-shape" in rules_hit(src, PALLAS)
