"""Standing-query subsystem tests (engine/standing.py).

THE gate is incremental parity: every emitted snapshot must be
bit-identical (floats included) to a from-scratch re-scan of the same
sinks, under randomized append/persist/publish schedules, including the
exactly-once publish cutover — with DRUID_TPU_STANDING=0 restoring the
re-scan world.
"""
import threading

import numpy as np
import pytest

from druid_tpu.cluster.metadata import MetadataStore
from druid_tpu.engine import QueryExecutor
from druid_tpu.engine import standing as standing_mod
from druid_tpu.engine.standing import (StandingIneligible,
                                       StandingMetricsMonitor,
                                       StandingQuery)
from druid_tpu.ingest import (Appenderator, RowBatch, SegmentAllocator,
                              StreamAppenderatorDriver)
from druid_tpu.query.aggregators import (CountAggregator,
                                         DoubleSumAggregator,
                                         LongMaxAggregator,
                                         LongSumAggregator)
from druid_tpu.query.model import (GroupByQuery, ScanQuery, TimeseriesQuery,
                                   TopNQuery)
from druid_tpu.utils.intervals import Interval

SPECS = [CountAggregator("rows"), LongSumAggregator("v", "value"),
         DoubleSumAggregator("d", "dvalue")]
# rolled-up data re-queries through the combining forms
QSPECS = [LongSumAggregator("rows", "rows"), LongSumAggregator("v", "v"),
          DoubleSumAggregator("d", "d"), LongMaxAggregator("mx", "v")]
DAY = Interval.of("2026-03-01", "2026-03-02")
T0 = DAY.start
HOUR = 3_600_000


def _batch(rng, n, t_lo=0, t_hi=24 * HOUR, card=5):
    ts = (T0 + rng.integers(t_lo, t_hi, size=n)).astype(np.int64)
    return RowBatch(ts.tolist(), {
        "page": [f"p{int(x)}" for x in rng.integers(card, size=n)],
        "value": [int(x) for x in rng.integers(0, 100, size=n)],
        "dvalue": [float(x) for x in rng.random(n)]})


def _rig(max_rows_per_hydrant=200, granularity="day"):
    md = MetadataStore()
    app = Appenderator("rt", SPECS, query_granularity="none",
                       max_rows_per_hydrant=max_rows_per_hydrant)
    driver = StreamAppenderatorDriver(app, SegmentAllocator(md, granularity),
                                     md)
    return md, app, driver


QUERIES = [
    TimeseriesQuery.of("rt", [DAY], QSPECS, granularity="hour"),
    TimeseriesQuery.of("rt", [DAY], QSPECS, granularity="all"),
    GroupByQuery.of("rt", [DAY], ["page"],
                    [LongSumAggregator("rows", "rows"),
                     DoubleSumAggregator("d", "d")], granularity="hour"),
    TopNQuery.of("rt", [DAY], "page", "rows", 3,
                 [LongSumAggregator("rows", "rows"),
                  DoubleSumAggregator("d", "d")]),
]


@pytest.mark.parametrize("qi", range(len(QUERIES)))
def test_incremental_parity_randomized_schedule(qi):
    """Randomized append/persist/publish churn: after every mutation the
    standing tick's snapshot must equal BOTH the standing module's own
    from-scratch re-scan AND an ordinary executor run over the same world
    — exact equality, floats included (dict == compares float bits)."""
    rng = np.random.default_rng(100 + qi)
    md, app, driver = _rig()
    q = QUERIES[qi]
    sq = StandingQuery(q, [app])
    publishes = 0
    try:
        for step in range(30):
            op = rng.random()
            if op < 0.70:
                driver.add_batch(_batch(rng, int(rng.integers(20, 120))))
            elif op < 0.88:
                app.persist_all()
            else:
                cur = md.datasource_metadata("rt")
                ok = driver.publish_all(
                    cur, {"partitions": {"0": publishes + 1}})
                assert ok
                publishes += 1
            sq.tick()
            rows = sq.rows()
            world = sq.world_segments()
            assert rows == sq.rescan_rows()
            assert rows == QueryExecutor().run(q, segments=world)
    finally:
        sq.close()


def test_standing_disabled_restores_rescan_world(monkeypatch):
    """DRUID_TPU_STANDING=0: every tick recomputes from scratch — results
    identical, but the fold counter shows the whole world refolding."""
    rng = np.random.default_rng(7)
    md, app, driver = _rig(max_rows_per_hydrant=50)
    q = QUERIES[0]
    sq = StandingQuery(q, [app])
    try:
        for _ in range(4):                   # several hydrants
            driver.add_batch(_batch(rng, 60))
            app.persist_all()
        sq.tick()
        baseline = sq.rows()
        n_world = len(sq.world_segments())
        assert n_world > 2

        prev = standing_mod.set_enabled(False)
        try:
            s0 = standing_mod.stats().snapshot()
            sq.tick()
            s1 = standing_mod.stats().snapshot()
            # the whole world refolded (no incremental caching)...
            assert s1["folds"] - s0["folds"] >= n_world
            # ...to the identical result
            assert sq.rows() == baseline
        finally:
            standing_mod.set_enabled(prev)

        # re-enabled: the next tick rebuilds the incremental caches once,
        # then quiet ticks are free again
        sq.tick()
        s2 = standing_mod.stats().snapshot()
        sq.tick()
        s3 = standing_mod.stats().snapshot()
        assert s3["folds"] == s2["folds"]
        assert sq.rows() == baseline
    finally:
        sq.close()


def test_ticks_fold_only_the_delta():
    """The incremental contract: after the first full fold, a tick pays
    device folds only for NEW data — sealed hydrants never refold, and a
    quiet tick folds nothing."""
    rng = np.random.default_rng(3)
    md, app, driver = _rig(max_rows_per_hydrant=100)
    q = QUERIES[0]
    sq = StandingQuery(q, [app])
    try:
        for _ in range(5):                   # 5 sealed hydrants
            driver.add_batch(_batch(rng, 120))
            app.persist_all()
        sq.tick()
        assert len(sq.world_segments()) >= 5
        stats0 = standing_mod.stats().snapshot()

        # quiet tick: zero folds
        assert sq.tick() is None
        stats1 = standing_mod.stats().snapshot()
        assert stats1["folds"] == stats0["folds"]

        # small append: exactly ONE fold (the live hydrant), regardless of
        # how many sealed hydrants exist
        driver.add_batch(_batch(rng, 10))
        snap = sq.tick()
        assert snap is not None
        stats2 = standing_mod.stats().snapshot()
        assert stats2["folds"] - stats1["folds"] == 1
        assert sq.rows() == sq.rescan_rows()

        # the tick right after a LIVE fold is quiet again: the stored
        # high-water marker is the POST-compaction one the snapshot
        # describes (snapshotting compacts the index, bumping its
        # generation — a pre-compaction marker would refold the whole
        # live hydrant here and spuriously emit)
        assert sq.tick() is None
        stats2b = standing_mod.stats().snapshot()
        assert stats2b["folds"] == stats2["folds"]

        # a persist that seals the already-folded snapshot costs NOTHING:
        # the live fold is promoted to hydrant rank verbatim
        app.persist_all()
        sq.tick()
        stats3 = standing_mod.stats().snapshot()
        assert stats3["folds"] == stats2["folds"]
        assert sq.rows() == sq.rescan_rows()
    finally:
        sq.close()


def test_publish_cutover_exactly_once():
    """Across the publish boundary every emission counts each row exactly
    once: pre-cutover from the sink's incremental partials, post-cutover
    from the published segment — never both, never neither."""
    rng = np.random.default_rng(11)
    md, app, driver = _rig()
    q = TimeseriesQuery.of("rt", [DAY],
                           [LongSumAggregator("rows", "rows")],
                           granularity="all")
    sq = StandingQuery(q, [app])
    try:
        driver.add_batch(_batch(rng, 300))
        sq.tick()
        assert sq.rows()[0]["result"]["rows"] == 300

        c0 = standing_mod.stats().snapshot()["cutovers"]
        assert driver.publish_all(None, {"partitions": {"0": 1}})
        snap = sq.tick()
        assert snap is not None
        assert standing_mod.stats().snapshot()["cutovers"] == c0 + 1
        assert sq.rows()[0]["result"]["rows"] == 300
        # the world is now exactly the published segment
        world = sq.world_segments()
        assert len(world) == 1
        assert sq.rows() == QueryExecutor().run(q, segments=world)

        # appends after the cutover allocate a NEW sink alongside it
        driver.add_batch(_batch(rng, 50))
        sq.tick()
        assert sq.rows()[0]["result"]["rows"] == 350
        assert sq.rows() == sq.rescan_rows()
    finally:
        sq.close()


def test_dropped_without_publish_removes_contribution():
    rng = np.random.default_rng(13)
    md, app, driver = _rig()
    q = TimeseriesQuery.of("rt", [DAY],
                           [LongSumAggregator("rows", "rows")],
                           granularity="all")
    sq = StandingQuery(q, [app])
    try:
        idents = driver.add_batch(_batch(rng, 100))
        sq.tick()
        assert sq.rows()[0]["result"]["rows"] == 100
        app.drop(idents)                 # discarded task, no publish
        sq.tick()
        assert sq.rows() == []
        assert sq.world_segments() == []
    finally:
        sq.close()


def test_standing_program_compiles_once(monkeypatch):
    """Repeated same-shape ticks serve from the jit cache: the standing
    program compiles once, later folds only dispatch it (the TiLT
    compile-once contract, asserted on the builder counter)."""
    import collections

    from druid_tpu.engine import grouping

    monkeypatch.setattr(grouping, "_JIT_CACHE", collections.OrderedDict())
    builds = []
    real = grouping._build_device_fn

    def counted(*a, **k):
        builds.append(1)
        return real(*a, **k)
    monkeypatch.setattr(grouping, "_build_device_fn", counted)

    rng = np.random.default_rng(17)
    md, app, driver = _rig()
    # fixed-cardinality dim values so hydrant dictionaries agree and the
    # structure signature is stable across ticks
    q = TimeseriesQuery.of("rt", [DAY],
                           [LongSumAggregator("rows", "rows"),
                            LongSumAggregator("v", "v")],
                           granularity="hour")
    sq = StandingQuery(q, [app])
    try:
        driver.add_batch(_batch(rng, 120))
        sq.tick()
        first = len(builds)
        assert first >= 1
        for _ in range(4):
            driver.add_batch(_batch(rng, 120))
            sq.tick()
        assert len(builds) == first, \
            "later same-shape ticks must not rebuild the program"
        assert sq.rows() == sq.rescan_rows()
    finally:
        sq.close()


def test_watermark_bucket_emission():
    """standingEmit=bucket: appends inside the open granularity bucket do
    not emit; the watermark crossing a bucket boundary seals it and emits;
    late data into a sealed bucket emits a correction."""
    md, app, driver = _rig()
    q = TimeseriesQuery.of("rt", [DAY],
                           [LongSumAggregator("rows", "rows")],
                           granularity="hour",
                           context={"standingEmit": "bucket"})
    sq = StandingQuery(q, [app])
    try:
        def add_at(ms, n=5):
            ts = [int(T0 + ms + i) for i in range(n)]
            driver.add_batch(RowBatch(ts, {
                "page": ["a"] * n, "value": [1] * n, "dvalue": [0.0] * n}))

        add_at(10 * HOUR)
        snap = sq.tick()                  # first data seals hour 10's start
        assert snap is not None
        assert snap.sealed_through == T0 + 10 * HOUR

        add_at(10 * HOUR + 1000)          # same bucket: data, no emission
        assert sq.tick() is None

        add_at(11 * HOUR)                 # watermark crosses into hour 11
        snap = sq.tick()
        assert snap is not None
        assert snap.sealed_through == T0 + 11 * HOUR
        assert snap.rows == sq.rescan_rows()   # snapshots stay consistent

        add_at(2 * HOUR)                  # LATE data into a sealed bucket
        snap = sq.tick()
        assert snap is not None and snap.rows == sq.rescan_rows()
    finally:
        sq.close()


def test_eligibility_rejections():
    md, app, driver = _rig()
    with pytest.raises(StandingIneligible):
        StandingQuery(ScanQuery.of("rt", [DAY]), [app])
    with pytest.raises(StandingIneligible):
        StandingQuery(
            TimeseriesQuery.of("rt", [DAY], QSPECS,
                               context={"bySegment": True}), [app])
    with pytest.raises(StandingIneligible):
        # unbounded bucket space: a century of minutes
        StandingQuery(TimeseriesQuery.of(
            "rt", [Interval.of("2000-01-01", "2100-01-01")], QSPECS,
            granularity="minute"), [app])
    with pytest.raises(StandingIneligible):
        # ETERNITY at fine granularity must be a cheap rejection, never
        # an attempt to materialize the bucket array (MemoryError/OOM on
        # the subscribe endpoint)
        StandingQuery(TimeseriesQuery.of(
            "rt", [Interval.eternity()], QSPECS, granularity="minute"),
            [app])
    with pytest.raises(StandingIneligible):
        # same for calendar granularities (counted by bounded walk)
        StandingQuery(TimeseriesQuery.of(
            "rt", [Interval.eternity()], QSPECS, granularity="month"),
            [app])
    with pytest.raises(ValueError):
        StandingQuery(TimeseriesQuery.of("other_ds", [DAY], QSPECS), [app])


def test_carry_bridge_across_live_generations():
    """Successive live-hydrant snapshots hand their parked megakernel
    carry grids forward (Segment.adopt_carries_from): the pool holds ONE
    carry entry per program across ticks instead of accumulating one per
    snapshot generation."""
    from druid_tpu.data.segment import Segment, SegmentId
    from druid_tpu.engine import megakernel

    a = Segment(SegmentId("cb", DAY, "v1"),
                np.asarray([T0, T0 + 1], dtype=np.int64), {}, {})
    b = Segment(SegmentId("cb", DAY, "v1"),
                np.asarray([T0, T0 + 1, T0 + 2], dtype=np.int64), {}, {})
    sentinel = ("grid",)
    a.device_cached(("megacarry", "sig-x"), lambda: sentinel)
    b.adopt_carries_from(a)
    assert b.carry_donor() is a
    # the bridge pops the donor's entry exactly once (the donated-carry
    # handoff: buffers must leave the pool before donation invalidates)
    assert a.device_take(("megacarry", "sig-x")) is sentinel
    assert a.device_take(("megacarry", "sig-x")) is None
    # a collected donor degrades to None, never a dangling ref
    del a
    import gc
    gc.collect()
    assert b.carry_donor() is None


def test_concurrent_reader_exactly_once_through_persist_publish():
    """The persist/publish boundary race (ISSUE satellite): a reader
    hammering the sink's query surface while persist_hydrant/publish_all
    churn must count each row exactly once in EVERY observation — pre- and
    post-handoff worlds both serve the full row set through the broker's
    replica view."""
    from druid_tpu.cluster import (Broker, DataNode, InventoryView,
                                   descriptor_for)
    from druid_tpu.cluster.realtime import RealtimeServer

    rng = np.random.default_rng(23)
    md = MetadataStore()
    app = Appenderator("rt", SPECS, query_granularity="none",
                       max_rows_per_hydrant=64)
    view = InventoryView()
    rts = RealtimeServer("rt-node", view)
    rts.attach(app)
    historical = DataNode("hist")
    view.register(historical)

    def handoff(pairs):
        for desc, seg in pairs:
            historical.load_segment(seg, desc)
            view.announce(historical.name, desc)

    driver = StreamAppenderatorDriver(
        app, SegmentAllocator(md, "day"), md, handoff=handoff)
    broker = Broker(view)

    n = 600
    driver.add_batch(_batch(rng, n))
    q = TimeseriesQuery.of("rt", [DAY],
                           [LongSumAggregator("rows", "rows")],
                           granularity="all")

    errors = []
    counts = []
    stop = threading.Event()

    def reader():
        try:
            while not stop.is_set():
                rows = broker.run(q)
                counts.append(rows[0]["result"]["rows"] if rows else 0)
        except Exception as e:            # pragma: no cover - must not
            errors.append(e)

    threads = [threading.Thread(target=reader) for _ in range(3)]
    for t in threads:
        t.start()
    try:
        # churn the boundary the readers race
        for _ in range(3):
            app.persist_all()
        assert driver.publish_all(None, {"partitions": {"0": 1}})
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=60)
        broker.stop()

    assert errors == []
    assert counts, "readers never completed a query"
    bad = [c for c in counts if c != n]
    assert not bad, f"row-count drift through the boundary: {set(bad)}"
    # and the post-handoff world still serves exactly once
    assert broker_count(broker, q) == n


def broker_count(broker, q):
    rows = broker.run(q)
    return rows[0]["result"]["rows"] if rows else 0


def test_standing_monitor_names_in_catalog():
    from druid_tpu.obs.catalog import validate_emitted
    from druid_tpu.utils.emitter import InMemoryEmitter, ServiceEmitter

    sink = InMemoryEmitter()
    emitter = ServiceEmitter("t", "h", sink)
    StandingMetricsMonitor().do_monitor(emitter)
    names = {e.metric for e in sink.events}
    assert names, "monitor emitted nothing"
    assert validate_emitted(names) == []
