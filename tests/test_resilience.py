"""Unit tests for the broker's data-plane fault-tolerance layer
(cluster/resilience.py): circuit breakers, decorrelated jitter, typed
partial results, latency EWMA feedback, metrics monitor, and the wire /
HTTP / SQL surfaces of the partial-result contract."""
import json
import random
import threading
import time

import numpy as np
import pytest

from druid_tpu.cluster import (Broker, DataNode, InventoryView,
                               PartialResult, ResiliencePolicy,
                               descriptor_for)
from druid_tpu.cluster.resilience import (CLOSED, HALF_OPEN, OPEN,
                                          BrokerResilience, CircuitBreaker,
                                          CircuitRegistry,
                                          ResilienceMetricsMonitor,
                                          decorrelated_jitter)
from druid_tpu.engine import QueryExecutor
from druid_tpu.query.aggregators import CountAggregator, LongSumAggregator
from druid_tpu.query.model import TimeseriesQuery
from druid_tpu.utils.intervals import Interval

WEEK = Interval.of("2026-01-01", "2026-01-08")
AGGS = [CountAggregator("rows"), LongSumAggregator("ls", "metLong")]


# ---------------------------------------------------------------------------
# decorrelated jitter
# ---------------------------------------------------------------------------

def test_jitter_within_bounds_and_decorrelated():
    rng = random.Random(0)
    prev = 1.0
    sleeps = []
    for _ in range(200):
        s = decorrelated_jitter(rng, 1.0, prev, 30.0)
        assert 1.0 <= s <= 30.0
        sleeps.append(s)
        prev = s
    # decorrelation: the sleeps spread out instead of repeating one value
    assert len({round(s, 6) for s in sleeps}) > 100
    assert max(sleeps) > 2.0


def test_jitter_respects_cap_and_base():
    rng = random.Random(1)
    for _ in range(100):
        assert decorrelated_jitter(rng, 5.0, 100.0, 8.0) <= 8.0
        assert decorrelated_jitter(rng, 5.0, 0.0, 8.0) >= 5.0
    # base above cap clamps to cap, never negative range
    assert decorrelated_jitter(rng, 50.0, 1.0, 8.0) == pytest.approx(8.0)


def test_jitter_deterministic_under_seed():
    a = [decorrelated_jitter(random.Random(7), 1.0, 1.0, 10.0)
         for _ in range(3)]
    b = [decorrelated_jitter(random.Random(7), 1.0, 1.0, 10.0)
         for _ in range(3)]
    assert a == b


# ---------------------------------------------------------------------------
# circuit breakers
# ---------------------------------------------------------------------------

def _clocked_registry(threshold=3, cooldown=5.0):
    now = [0.0]
    reg = CircuitRegistry(
        ResiliencePolicy(circuit_failure_threshold=threshold,
                         circuit_cooldown_s=cooldown,
                         circuit_cooldown_cap_s=cooldown * 6),
        seed=0, clock=lambda: now[0])
    return reg, now


def test_breaker_opens_after_consecutive_failures():
    reg, now = _clocked_registry(threshold=3)
    for _ in range(2):
        reg.on_failure("s1")
    assert reg.state_of("s1") == CLOSED and reg.closed("s1")
    reg.on_failure("s1")
    assert reg.state_of("s1") == OPEN and not reg.closed("s1")
    assert reg.snapshot() == {"open": 1, "trips": 1, "probes": 0}


def test_success_resets_consecutive_count():
    reg, _ = _clocked_registry(threshold=3)
    reg.on_failure("s1")
    reg.on_failure("s1")
    reg.on_success("s1")
    reg.on_failure("s1")
    reg.on_failure("s1")
    assert reg.state_of("s1") == CLOSED   # never 3 consecutive


def test_half_open_probe_cycle():
    reg, now = _clocked_registry(threshold=1, cooldown=5.0)
    reg.on_failure("s1")
    assert reg.state_of("s1") == OPEN
    assert not reg.probe_candidate("s1"), "cooldown not elapsed"
    now[0] = 100.0                        # jittered cooldown ≤ 6x base
    assert reg.probe_candidate("s1")
    reg.begin_probe("s1")
    assert reg.state_of("s1") == HALF_OPEN
    assert not reg.probe_candidate("s1"), "one probe in flight"
    reg.on_success("s1")
    assert reg.state_of("s1") == CLOSED
    assert reg.snapshot()["probes"] == 1


def test_half_open_failure_reopens_with_fresh_cooldown():
    reg, now = _clocked_registry(threshold=1, cooldown=5.0)
    reg.on_failure("s1")
    now[0] = 100.0
    reg.begin_probe("s1")
    reg.on_failure("s1")                  # the probe failed
    assert reg.state_of("s1") == OPEN
    assert not reg.probe_candidate("s1"), "fresh cooldown started"
    assert reg.snapshot()["trips"] == 2


def test_cooldown_is_jittered_decorrelated():
    """Successive trips draw different cooldowns in [base, cap]."""
    pol = ResiliencePolicy(circuit_failure_threshold=1,
                           circuit_cooldown_s=1.0,
                           circuit_cooldown_cap_s=30.0)
    b = CircuitBreaker(pol, random.Random(3), clock=lambda: 0.0)
    spans = []
    for _ in range(20):
        b.trip()
        assert 1.0 <= b._cooldown_until <= 30.0
        spans.append(b._cooldown_until)
    assert len(set(spans)) > 10


def test_disabled_policy_keeps_everything_closed():
    reg = CircuitRegistry(ResiliencePolicy(circuit_enabled=False), seed=0)
    for _ in range(10):
        reg.on_failure("s1")
    assert reg.closed("s1")


# ---------------------------------------------------------------------------
# view: latency EWMA + circuit-aware pick (unit)
# ---------------------------------------------------------------------------

def test_view_latency_ewma():
    view = InventoryView()
    assert view.latency_ms("a") is None
    view.note_latency("a", 100.0, alpha=0.5)
    assert view.latency_ms("a") == 100.0
    view.note_latency("a", 50.0, alpha=0.5)
    assert view.latency_ms("a") == pytest.approx(75.0)


def test_hedge_delay_derives_from_ewma():
    view = InventoryView()
    res = BrokerResilience(ResiliencePolicy(hedge_min_delay_ms=50,
                                            hedge_latency_multiplier=3.0))
    assert res.hedge_delay_s(view, "a") == pytest.approx(0.05)
    view.note_latency("a", 200.0, alpha=1.0)
    assert res.hedge_delay_s(view, "a") == pytest.approx(0.6)


# ---------------------------------------------------------------------------
# typed partial results
# ---------------------------------------------------------------------------

def test_partial_result_is_a_typed_list():
    rows = [{"a": 1}, {"a": 2}]
    p = PartialResult(rows, ["seg2", "seg1", "seg2"])
    assert list(p) == rows and len(p) == 2
    assert p.missing_segments == ["seg1", "seg2"], "sorted AND deduped"
    assert p.response_context() == {"partial": True,
                                    "missingSegments": ["seg1", "seg2"]}
    assert json.dumps(p)                  # serializes like a plain list


# ---------------------------------------------------------------------------
# wire surface of the partial contract
# ---------------------------------------------------------------------------

def test_wire_round_trips_missing_report(segments):
    from druid_tpu.cluster import wire
    from druid_tpu.engine.engines import make_aggregate_partials
    q = TimeseriesQuery.of("test", [WEEK], AGGS)
    ap = make_aggregate_partials(q, segments[:1])
    data = wire.dumps_partials(ap, served=[str(segments[0].id)],
                               missing=["lost-b", "lost-a"])
    payload = wire.loads_partials(data)
    got_ap, served, spans = payload       # 3-tuple unpack preserved
    assert served == {str(segments[0].id)}
    assert payload.missing == ["lost-a", "lost-b"]
    # a pre-missing-field payload still loads (empty report)
    legacy = wire.dumps_partials(ap, served=[str(segments[0].id)])
    assert wire.loads_partials(legacy).missing == []


# ---------------------------------------------------------------------------
# broker integration: circuits + partials + EWMA feedback
# ---------------------------------------------------------------------------

class _DeadNode(DataNode):
    def __init__(self, name):
        super().__init__(name)
        self.calls = 0

    def run_partials(self, query, segment_ids, check=None):
        self.calls += 1
        raise ConnectionError(f"[{self.name}] down")


def _two_replica_cluster(segments, policy=None, seed=0):
    view = InventoryView()
    dead = _DeadNode("dead")
    good = DataNode("good")
    for n in (dead, good):
        view.register(n)
        for s in segments:
            n.load_segment(s)
            view.announce(n.name, descriptor_for(s))
    return view, dead, good, Broker(view, seed=seed,
                                    resilience_policy=policy)


def test_broker_opens_circuit_and_stops_paying_the_dead_node(segments):
    pol = ResiliencePolicy(circuit_failure_threshold=2,
                           circuit_cooldown_s=60.0,
                           circuit_cooldown_cap_s=60.0,
                           hedge_enabled=False)
    view, dead, good, broker = _two_replica_cluster(segments, pol)
    q = TimeseriesQuery.of("test", [WEEK], AGGS)
    expect = QueryExecutor(segments).run(q)
    for _ in range(12):
        assert broker.run(q) == expect
    # once the circuit trips, replica selection skips the dead server —
    # call volume stays at the handful it took to trip, not one per query
    assert broker.resilience.circuits.state_of("dead") == OPEN
    calls_at_trip = dead.calls
    for _ in range(5):
        assert broker.run(q) == expect
    assert dead.calls == calls_at_trip
    broker.stop()


def test_broker_half_open_probe_recovers(segments):
    pol = ResiliencePolicy(circuit_failure_threshold=1,
                           circuit_cooldown_s=0.01,
                           circuit_cooldown_cap_s=0.02,
                           hedge_enabled=False)
    view, dead, good, broker = _two_replica_cluster(segments, pol)
    q = TimeseriesQuery.of("test", [WEEK], AGGS)
    expect = QueryExecutor(segments).run(q)
    for _ in range(3):
        assert broker.run(q) == expect
    assert broker.resilience.circuits.state_of("dead") == OPEN
    # heal the node; after the (tiny) cooldown a probe rides through and
    # closes the circuit
    dead.run_partials = lambda query, sids, check=None: \
        DataNode.run_partials(dead, query, sids, check=check)
    time.sleep(0.05)
    for _ in range(20):
        assert broker.run(q) == expect
        if broker.resilience.circuits.state_of("dead") == CLOSED:
            break
    assert broker.resilience.circuits.state_of("dead") == CLOSED
    assert broker.resilience.circuits.snapshot()["probes"] >= 1
    broker.stop()


def test_broker_partial_results_on_exhausted_replicas(segments):
    view = InventoryView()
    only = _DeadNode("only")
    live = DataNode("live")
    view.register(only)
    view.register(live)
    # half the segments ONLY on the dead node, half on the live one
    for i, s in enumerate(segments):
        n = only if i % 2 == 0 else live
        n.load_segment(s)
        view.announce(n.name, descriptor_for(s))
    broker = Broker(view)
    q = TimeseriesQuery.of("test", [WEEK], AGGS,
                           context={"allowPartialResults": True})
    rows = broker.run(q)
    assert isinstance(rows, PartialResult)
    lost = {str(s.id) for i, s in enumerate(segments) if i % 2 == 0}
    assert set(rows.missing_segments) == lost
    # bit-parity over the SURVIVING path: rows == oracle minus missing
    survivors = [s for i, s in enumerate(segments) if i % 2 == 1]
    assert list(rows) == QueryExecutor(survivors).run(q)
    # partials are counted, exactly once
    snap = broker.resilience.stats.snapshot()
    assert snap["partial_queries"] == 1
    assert snap["partial_missing_segments"] == len(lost)
    broker.stop()


def test_partial_never_populates_result_cache(segments):
    from druid_tpu.cluster import LruCache
    view = InventoryView()
    flaky = _DeadNode("flaky")
    view.register(flaky)
    for s in segments:
        flaky.load_segment(s)
        view.announce("flaky", descriptor_for(s))
    broker = Broker(view, cache=LruCache())
    q = TimeseriesQuery.of("test", [WEEK], AGGS,
                           context={"allowPartialResults": True})
    rows = broker.run(q)
    assert isinstance(rows, PartialResult) and list(rows) == []
    # heal: the next run must NOT be served the cached hole
    flaky.run_partials = lambda query, sids, check=None: \
        DataNode.run_partials(flaky, query, sids, check=check)
    # circuit may still be open — probe fallback serves it
    expect = QueryExecutor(segments).run(q)
    got = None
    for _ in range(10):
        got = broker.run(q)
        if not getattr(got, "missing_segments", None):
            break
    assert list(got) == expect
    assert getattr(got, "missing_segments", None) is None
    broker.stop()


def test_strict_mode_unchanged_without_context_flag(segments):
    from druid_tpu.cluster import MissingSegmentsError
    view = InventoryView()
    only = _DeadNode("only")
    view.register(only)
    for s in segments:
        only.load_segment(s)
        view.announce("only", descriptor_for(s))
    broker = Broker(view)
    with pytest.raises(MissingSegmentsError):
        broker.run(TimeseriesQuery.of("test", [WEEK], AGGS))
    broker.stop()


def test_broker_feeds_latency_ewma(segments):
    view = InventoryView()
    node = DataNode("n1")
    view.register(node)
    for s in segments:
        node.load_segment(s)
        view.announce("n1", descriptor_for(s))
    broker = Broker(view)
    assert view.latency_ms("n1") is None
    broker.run(TimeseriesQuery.of("test", [WEEK], AGGS))
    assert view.latency_ms("n1") is not None and view.latency_ms("n1") > 0
    broker.stop()


def test_broker_pool_is_hoisted_and_released(segments):
    view, dead, good, broker = _two_replica_cluster(segments)
    q = TimeseriesQuery.of("test", [WEEK], AGGS)
    broker.run(q)
    pool1 = broker._pool
    assert pool1 is not None, "scatter created the broker-owned pool"
    broker.run(q)
    assert broker._pool is pool1, "retry rounds reuse ONE pool"
    broker.stop()
    assert broker._pool is None
    assert pool1._shutdown
    # the broker stays usable after stop(): the pool is recreated
    expect = QueryExecutor(segments).run(q)
    assert broker.run(q) == expect
    broker.stop()


# ---------------------------------------------------------------------------
# client Retry-After jitter wiring
# ---------------------------------------------------------------------------

def test_client_retry_after_sleep_is_jittered(monkeypatch):
    from druid_tpu.cluster import resilience as R
    from druid_tpu.cluster.dataserver import RemoteDataNodeClient
    seen = {}

    def fake_jitter(rng, base, prev, cap):
        seen["args"] = (base, prev, cap)
        return 0.0                        # no real sleep in the test

    monkeypatch.setattr(R, "decorrelated_jitter", fake_jitter)
    monkeypatch.setattr(RemoteDataNodeClient, "MAX_RETRY_AFTER_SLEEP", 0.05)
    import tests.test_scheduler as TS
    from druid_tpu.data.generator import DataGenerator
    from tests.conftest import TEST_SCHEMA
    segs = DataGenerator(TEST_SCHEMA, seed=42).segments(
        1, 512, Interval.of("2026-01-01", "2026-01-02"),
        datasource="test")
    httpd, handler, q = TS._stub_shedding_server(segs, shed_n=1)
    try:
        client = RemoteDataNodeClient(
            "stub", f"http://127.0.0.1:{httpd.server_address[1]}",
            jitter_seed=0)
        client.run_partials(q, [str(segs[0].id)])
        base, prev, cap = seen["args"]
        assert base == prev > 0           # seeded from the Retry-After
        assert cap == 0.05
    finally:
        httpd.shutdown()
        httpd.server_close()


# ---------------------------------------------------------------------------
# metrics monitor
# ---------------------------------------------------------------------------

def test_resilience_monitor_emits_declared_deltas():
    from druid_tpu.obs import catalog
    res = BrokerResilience(ResiliencePolicy(circuit_failure_threshold=1))
    res.circuits.on_failure("s1")
    res.stats.note_hedge_issued()
    res.stats.note_hedge_won()
    res.stats.note_partial(3)
    events = []

    class _Emitter:
        def metric(self, name, value, **dims):
            events.append((name, value))

    mon = ResilienceMetricsMonitor(res)
    mon.do_monitor(_Emitter())
    got = dict(events)
    assert catalog.validate_emitted(got) == []
    assert got["broker/circuit/open"] == 1
    assert got["broker/circuit/trips"] == 1
    assert got["query/hedge/issued"] == 1
    assert got["query/hedge/won"] == 1
    assert got["query/partial/missingSegments"] == 3
    events.clear()
    mon.do_monitor(_Emitter())
    got = dict(events)
    # second tick: deltas drop to zero, the open gauge stays live
    assert got["broker/circuit/trips"] == 0
    assert got["query/partial/missingSegments"] == 0
    assert got["broker/circuit/open"] == 1


def test_partial_contract_over_http_and_sql(segments):
    """End-to-end surface test: the missing-segments report rides the
    X-Druid-Response-Context header on native HTTP queries AND on SQL
    (context passthrough added for the data-plane flags), exactly once,
    with the body rows equal to the surviving oracle."""
    import http.client
    from druid_tpu.server.http import QueryHttpServer
    from druid_tpu.server.lifecycle import QueryLifecycle
    from druid_tpu.sql.executor import SqlExecutor
    view = InventoryView()
    dead = _DeadNode("dead")
    live = DataNode("live")
    view.register(dead)
    view.register(live)
    for i, s in enumerate(segments):
        n = dead if i % 2 == 0 else live
        n.load_segment(s)
        view.announce(n.name, descriptor_for(s))
    broker = Broker(view)
    srv = QueryHttpServer(QueryLifecycle(broker),
                          sql_executor=SqlExecutor(broker)).start()
    lost = {str(s.id) for i, s in enumerate(segments) if i % 2 == 0}
    survivors = [s for i, s in enumerate(segments) if i % 2 == 1]
    try:
        q = TimeseriesQuery.of("test", [WEEK], AGGS,
                               context={"allowPartialResults": True})
        c = http.client.HTTPConnection("127.0.0.1", srv.port)
        c.request("POST", "/druid/v2", json.dumps(q.to_json()),
                  {"Content-Type": "application/json"})
        r = c.getresponse()
        body = json.loads(r.read())
        assert r.status == 200
        rc = json.loads(r.headers["X-Druid-Response-Context"])
        assert rc["partial"] is True
        assert set(rc["missingSegments"]) == lost
        assert body == QueryExecutor(survivors).run(q)
        # review regression: a partial must NOT carry the complete
        # result's ETag — a client caching the partial body against it
        # would be 304-confirmed forever after the cluster heals
        assert r.headers.get("X-Druid-ETag") is None
        # a strict query over the same cluster keeps the 500-class error
        strict = TimeseriesQuery.of("test", [WEEK], AGGS)
        c.request("POST", "/druid/v2", json.dumps(strict.to_json()),
                  {"Content-Type": "application/json"})
        r2 = c.getresponse()
        r2.read()
        assert r2.status == 500
        assert r2.headers.get("X-Druid-Response-Context") is None
        # SQL surface: the context object reaches the native query and
        # the report reaches the header
        c.request("POST", "/druid/v2/sql", json.dumps({
            "query": "SELECT COUNT(*) AS c FROM test",
            "context": {"allowPartialResults": True}}),
            {"Content-Type": "application/json"})
        r3 = c.getresponse()
        sql_rows = json.loads(r3.read())
        assert r3.status == 200
        rc3 = json.loads(r3.headers["X-Druid-Response-Context"])
        assert set(rc3["missingSegments"]) == lost
        assert sql_rows[0]["c"] == sum(s.n_rows for s in survivors)
        c.close()
    finally:
        srv.stop()
        broker.stop()


def test_http_server_wires_resilience_monitor(segments):
    """A broker-backed QueryHttpServer surfaces broker/circuit/* on its
    /metrics registry after a tick."""
    from druid_tpu.server.http import QueryHttpServer
    from druid_tpu.server.lifecycle import QueryLifecycle
    view = InventoryView()
    node = DataNode("n1")
    view.register(node)
    for s in segments:
        node.load_segment(s)
        view.announce("n1", descriptor_for(s))
    broker = Broker(view)
    srv = QueryHttpServer(QueryLifecycle(broker)).start()
    try:
        broker.resilience.circuits.on_failure("n1")
        srv.metrics_tick()
        expo = srv.registry.exposition()
        assert "broker_circuit_open" in expo
        assert "query_hedge_issued" in expo
    finally:
        srv.stop()
        broker.stop()
