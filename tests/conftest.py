"""Test configuration: force an 8-device virtual CPU mesh BEFORE jax imports,
mirroring the reference's single-JVM simulated-cluster testing strategy
(SURVEY §4: CachingClusteredClientTest-style tests without sockets)."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# Opt-in whole-suite lock witness (DRUID_TPU_LOCK_WITNESS=1): must install
# BEFORE the first druid_tpu import below — module-level locks (jit caches,
# native registry) are constructed at import time and would otherwise stay
# unwrapped, blinding the sweep to the hot-path engine locks. The install
# is a process-wide singleton (lockwitness.session_witness): this file
# executes twice per session (`conftest` plugin + `from tests.conftest
# import ...`), and a second install would shadow the first witness.
# Validation and reporting happen in pytest_unconfigure.
if os.environ.get("DRUID_TPU_LOCK_WITNESS") == "1":
    import sys as _sys
    from pathlib import Path as _Path
    _sys.path.insert(0, str(_Path(__file__).resolve().parent.parent))
    from tools.druidlint.lockwitness import session_witness as _session_witness
    _session_witness(str(_Path(__file__).resolve().parent.parent))

# Opt-in whole-suite leak witness (DRUID_TPU_LEAK_WITNESS=1): installed
# BEFORE the first druid_tpu import so every project thread start is
# attributed, with the session baseline captured at the SAME point — the
# suite must return to its post-install resource state (threads, fds,
# device-pool resident bytes) by pytest_unconfigure. Same process-wide
# singleton rationale as the lock witness above.
if os.environ.get("DRUID_TPU_LEAK_WITNESS") == "1":
    import sys as _sys
    from pathlib import Path as _Path
    _root = str(_Path(__file__).resolve().parent.parent)
    if _root not in _sys.path:
        _sys.path.insert(0, _root)
    from tools.druidlint.leakwitness import session_witness as _leak_witness
    _leak_witness(_root)

# Opt-in whole-suite stall witness (DRUID_TPU_STALL_WITNESS=1): the
# dynamic side of stallguard. Installed BEFORE the first druid_tpu import
# so `from time import sleep`-style early bindings cannot escape the
# wrappers — it patches the blocking primitives themselves (Event/
# Condition.wait, Thread.join, Queue.get, Popen.wait, time.sleep) and
# times every park issued from a druid_tpu call site. An untimed park
# outside a shutdown scope fails the session in pytest_unconfigure. Same
# process-wide singleton rationale as the other witnesses.
if os.environ.get("DRUID_TPU_STALL_WITNESS") == "1":
    import sys as _sys
    from pathlib import Path as _Path
    _root = str(_Path(__file__).resolve().parent.parent)
    if _root not in _sys.path:
        _sys.path.insert(0, _root)
    from tools.druidlint.stallwitness import session_witness as _stall_witness
    _stall_witness(_root)

import jax

# The environment's sitecustomize may have force-registered a TPU plugin and
# overridden jax_platforms ("axon,cpu") at interpreter startup. Backends
# initialize lazily, so flipping the config back here (before any jax op)
# still wins — tests always run on the 8-device virtual CPU mesh.
jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest

import druid_tpu.engine  # noqa: F401  (enables x64 before any jax use)
from druid_tpu.data.generator import ColumnSpec, DataGenerator
from druid_tpu.utils.intervals import Interval

# Opt-in whole-suite key witness (DRUID_TPU_KEY_WITNESS=1): the dynamic
# side of keyguard. Unlike the lock/leak witnesses above it patches
# module GLOBALS (jit caches, builders, the device pool), so it installs
# AFTER the engine import — and it records a structural fingerprint of
# every cache build next to its key, failing the session on any
# same-key/different-structure collision in pytest_unconfigure. Same
# process-wide singleton rationale as the other witnesses.
if os.environ.get("DRUID_TPU_KEY_WITNESS") == "1":
    import sys as _sys
    from pathlib import Path as _Path
    _root = str(_Path(__file__).resolve().parent.parent)
    if _root not in _sys.path:
        _sys.path.insert(0, _root)
    from tools.druidlint.keywitness import session_witness as _key_witness
    _key_witness(_root)

# Opt-in whole-suite donation/ownership witness (DRUID_TPU_DONOR_WITNESS=1):
# the dynamic side of donorguard. Like the key witness it patches module
# globals (the pool take/get_or_build methods, the donating builder, the
# discard helper), so it installs AFTER the engine import — it tracks
# array identity across the take→dispatch→re-park cycle, SIMULATES
# donation invalidation on CPU by deleting donated carry buffers after a
# successful dispatch, and fails the session on a cached-entry donation
# or an un-reparked take in pytest_unconfigure. Same process-wide
# singleton rationale as the other witnesses.
if os.environ.get("DRUID_TPU_DONOR_WITNESS") == "1":
    import sys as _sys
    from pathlib import Path as _Path
    _root = str(_Path(__file__).resolve().parent.parent)
    if _root not in _sys.path:
        _sys.path.insert(0, _root)
    from tools.druidlint.donorwitness import session_witness as _donor_witness
    _donor_witness(_root)

DAY = Interval.of("2026-01-01", "2026-01-02")
WEEK = Interval.of("2026-01-01", "2026-01-08")

TEST_SCHEMA = (
    ColumnSpec("dimA", "string", cardinality=10, distribution="uniform"),
    ColumnSpec("dimB", "string", cardinality=100, distribution="zipf"),
    ColumnSpec("dimHi", "string", cardinality=5000, distribution="uniform"),
    ColumnSpec("metLong", "long", low=0, high=100),
    ColumnSpec("metFloat", "float", distribution="normal", mean=10.0, std=3.0),
    ColumnSpec("metDouble", "double", low=0.0, high=1.0),
)


@pytest.fixture(scope="session")
def generator():
    return DataGenerator(TEST_SCHEMA, seed=42)


def persist_roundtrip(seg, directory: str):
    """Persist to the on-disk format and reload (exercises codecs, smoosh,
    lazy bitmap parts, dictionary serde on every engine test)."""
    from druid_tpu.storage.format import load_segment, persist_segment
    persist_segment(seg, directory)
    return load_segment(directory)


@pytest.fixture(scope="session")
def _base_segment():
    # a DEDICATED generator: the shared `generator` fixture's RNG is
    # stateful, and both `segment` params must see the SAME rows
    return DataGenerator(TEST_SCHEMA, seed=42).segment(
        20_000, DAY, datasource="test")


@pytest.fixture(scope="session", params=("generated", "persisted"))
def segment(request, _base_segment, tmp_path_factory):
    """Engine tests run against BOTH the in-memory and the
    persisted+reloaded form of the SAME segment (reference:
    QueryRunnerTestHelper.makeQueryRunners parameterizes every query test
    over incremental/mmapped/merged forms). The order-changing forms
    (merged-from-spills, rollup-incremental) get their own equivalence
    battery in test_representations.py."""
    if request.param == "persisted":
        return persist_roundtrip(
            _base_segment, str(tmp_path_factory.mktemp("seg") / "test"))
    return _base_segment


@pytest.fixture(scope="session")
def segments(generator):
    """4 segments over a 4-day range sharing dictionaries."""
    return generator.segments(4, 5_000, Interval.of("2026-01-01", "2026-01-05"),
                              datasource="test")


def rows_as_frame(segment):
    """Decode a segment to python-level rows for golden-result computation."""
    out = {"__time": segment.time_ms.copy()}
    for name, col in segment.dims.items():
        vals = np.asarray(col.dictionary.values, dtype=object)
        out[name] = vals[col.ids]
    for name, m in segment.metrics.items():
        out[name] = m.values.copy()
    return out


# ---------------------------------------------------------------------------
# opt-in whole-suite lock witness: installed at the TOP of this module (see
# the header block — module-level locks are constructed at import time);
# every project lock constructed during the session is wrapped, and the
# observed acquisition-order graph is checked against raceguard's static
# one at session end. The dedicated stress run in test_raceguard_witness.py
# asserts this per-test; the session-wide mode sweeps the full suite's lock
# behavior before scaling work.
# ---------------------------------------------------------------------------


def pytest_collection_finish(session):
    """Re-baseline the leak witness AFTER collection: importing the test
    modules pulls in nearly all of druid_tpu (module singletons, jax
    backend side effects), and those one-time allocations are process
    state, not suite leaks. The return-to-baseline contract starts here."""
    if os.environ.get("DRUID_TPU_LEAK_WITNESS") != "1":
        return
    from tools.druidlint.leakwitness import session_witness
    w = session_witness()
    if w is not None:
        w.baseline = w.snapshot()


def pytest_unconfigure(config):
    # a lock-witness violation must not skip the stall/key/donor/leak
    # checks (or leave hooks monkeypatched): run all five even if an
    # earlier raises
    try:
        _unconfigure_lock_witness()
    finally:
        try:
            _unconfigure_stall_witness()
        finally:
            try:
                _unconfigure_key_witness()
            finally:
                try:
                    _unconfigure_donor_witness()
                finally:
                    _unconfigure_leak_witness()


def _unconfigure_stall_witness():
    if os.environ.get("DRUID_TPU_STALL_WITNESS") != "1":
        return
    from tools.druidlint.stallwitness import end_session_witness
    w = end_session_witness()
    if w is None:
        return
    print(f"stallwitness: {w.summary()}")
    for v in w.violations:
        print(f"stallwitness: UNTIMED PARK {v}")
    if w.violations:
        raise pytest.UsageError(
            "stall witness found untimed non-shutdown parks (see lines "
            "above)")


def _unconfigure_key_witness():
    if os.environ.get("DRUID_TPU_KEY_WITNESS") != "1":
        return
    from tools.druidlint.keywitness import end_session_witness
    w = end_session_witness()
    if w is None:
        return
    print(f"keywitness: {w.summary()}")
    for c in w.collisions:
        print(f"keywitness: COLLISION {c}")
    if w.collisions:
        raise pytest.UsageError(
            "key witness found cache-key collisions (see lines above)")


def _unconfigure_donor_witness():
    if os.environ.get("DRUID_TPU_DONOR_WITNESS") != "1":
        return
    from tools.druidlint.donorwitness import end_session_witness
    w = end_session_witness()
    if w is None:
        return
    violations = w.all_violations()
    print(f"donorwitness: {w.summary()}")
    for v in violations:
        print(f"donorwitness: VIOLATION {v}")
    if violations:
        raise pytest.UsageError(
            "donor witness found buffer-ownership violations (see lines "
            "above)")


def _unconfigure_leak_witness():
    if os.environ.get("DRUID_TPU_LEAK_WITNESS") != "1":
        return
    from tools.druidlint.leakwitness import end_session_witness
    w = end_session_witness()
    if w is None or w.baseline is None:
        return
    # deliberately-pinned cache state is not a leak: drop the engine's
    # device caches (stack cache pins whole segment sets) so the pool
    # axis measures unreleased OWNERSHIP, not cache policy. The pool
    # itself is NOT cleared — entries must die with their segments.
    from druid_tpu.engine import release_device_caches
    release_device_caches()
    leaks = w.leaks(grace_s=10.0)
    print(f"leakwitness: {len(w._started)} project thread start(s) "
          f"witnessed, {len(leaks)} leak(s) vs the post-collection "
          f"baseline")
    for l in leaks:
        print(f"leakwitness: LEAK {l}")
    if leaks:
        raise pytest.UsageError(
            "leak witness found resource leaks (see lines above)")


def _unconfigure_lock_witness():
    if os.environ.get("DRUID_TPU_LOCK_WITNESS") != "1":
        return
    from tools.druidlint.lockwitness import end_session_witness
    w = end_session_witness()
    if w is None:
        return
    from pathlib import Path
    from tools.druidlint.core import load_config
    from tools.druidlint.raceguard import analyze_tree
    root = Path(__file__).resolve().parent.parent
    prog = analyze_tree(root, load_config(root))
    lines = [f"lockwitness: {len(w.constructed)} wrapped construction "
             f"site(s), {len(w.observed_edges())} observed order edge(s)"]
    violations = w.order_violations()
    unexplained = w.unexplained_edges(prog)
    for v in violations:
        lines.append(f"lockwitness: ORDER VIOLATION (both directions "
                     f"observed): {v}")
    for u in unexplained:
        lines.append(f"lockwitness: UNEXPLAINED {u}")
    for m in w.mutation_violations:
        lines.append(f"lockwitness: UNGUARDED MUTATION {m}")
    print("\n".join(lines))
    if violations or unexplained or w.mutation_violations:
        raise pytest.UsageError(
            "lock witness found inconsistencies (see lines above)")
