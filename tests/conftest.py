"""Test configuration: force an 8-device virtual CPU mesh BEFORE jax imports,
mirroring the reference's single-JVM simulated-cluster testing strategy
(SURVEY §4: CachingClusteredClientTest-style tests without sockets)."""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax

# The environment's sitecustomize may have force-registered a TPU plugin and
# overridden jax_platforms ("axon,cpu") at interpreter startup. Backends
# initialize lazily, so flipping the config back here (before any jax op)
# still wins — tests always run on the 8-device virtual CPU mesh.
jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest

import druid_tpu.engine  # noqa: F401  (enables x64 before any jax use)
from druid_tpu.data.generator import ColumnSpec, DataGenerator
from druid_tpu.utils.intervals import Interval

DAY = Interval.of("2026-01-01", "2026-01-02")
WEEK = Interval.of("2026-01-01", "2026-01-08")

TEST_SCHEMA = (
    ColumnSpec("dimA", "string", cardinality=10, distribution="uniform"),
    ColumnSpec("dimB", "string", cardinality=100, distribution="zipf"),
    ColumnSpec("dimHi", "string", cardinality=5000, distribution="uniform"),
    ColumnSpec("metLong", "long", low=0, high=100),
    ColumnSpec("metFloat", "float", distribution="normal", mean=10.0, std=3.0),
    ColumnSpec("metDouble", "double", low=0.0, high=1.0),
)


@pytest.fixture(scope="session")
def generator():
    return DataGenerator(TEST_SCHEMA, seed=42)


def persist_roundtrip(seg, directory: str):
    """Persist to the on-disk format and reload (exercises codecs, smoosh,
    lazy bitmap parts, dictionary serde on every engine test)."""
    from druid_tpu.storage.format import load_segment, persist_segment
    persist_segment(seg, directory)
    return load_segment(directory)


@pytest.fixture(scope="session")
def _base_segment():
    # a DEDICATED generator: the shared `generator` fixture's RNG is
    # stateful, and both `segment` params must see the SAME rows
    return DataGenerator(TEST_SCHEMA, seed=42).segment(
        20_000, DAY, datasource="test")


@pytest.fixture(scope="session", params=("generated", "persisted"))
def segment(request, _base_segment, tmp_path_factory):
    """Engine tests run against BOTH the in-memory and the
    persisted+reloaded form of the SAME segment (reference:
    QueryRunnerTestHelper.makeQueryRunners parameterizes every query test
    over incremental/mmapped/merged forms). The order-changing forms
    (merged-from-spills, rollup-incremental) get their own equivalence
    battery in test_representations.py."""
    if request.param == "persisted":
        return persist_roundtrip(
            _base_segment, str(tmp_path_factory.mktemp("seg") / "test"))
    return _base_segment


@pytest.fixture(scope="session")
def segments(generator):
    """4 segments over a 4-day range sharing dictionaries."""
    return generator.segments(4, 5_000, Interval.of("2026-01-01", "2026-01-05"),
                              datasource="test")


def rows_as_frame(segment):
    """Decode a segment to python-level rows for golden-result computation."""
    out = {"__time": segment.time_ms.copy()}
    for name, col in segment.dims.items():
        vals = np.asarray(col.dictionary.values, dtype=object)
        out[name] = vals[col.ids]
    for name, m in segment.metrics.items():
        out[name] = m.values.copy()
    return out
