"""Soak gate (tier-1 fast mode): repeated query waves and full server
start/stop cycles must return the process to its resource baseline —
stable project-thread set, stable open-fd table, device-pool resident
bytes back where they started. The leak witness is the measurement
substrate; bench.py's DRUID_TPU_BENCH_SOAK mode runs the same shape at
scale and reports drift in its JSON line.

The point is the millions-of-cycles story: a service absorbing heavy
traffic does exactly this loop forever, so ANY per-cycle residue — a
serve_forever thread stop() never reaped, a segment whose device blocks
outlive it, an emitter file handle — is a linear leak in production. The
wedged bench runs (rc=124) are this failure class at full size.
"""
import gc
import sys
import urllib.request
from pathlib import Path

import pytest

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

from tools.druidlint.leakwitness import LeakWitness  # noqa: E402

from druid_tpu.cluster.dataserver import DataNodeServer  # noqa: E402
from druid_tpu.cluster.view import DataNode  # noqa: E402
from druid_tpu.data import devicepool  # noqa: E402
from druid_tpu.data.generator import ColumnSpec, DataGenerator  # noqa: E402
from druid_tpu.engine import QueryExecutor  # noqa: E402
from druid_tpu.query.aggregators import (CountAggregator,  # noqa: E402
                                         LongSumAggregator)
from druid_tpu.query.model import (DefaultDimensionSpec,  # noqa: E402
                                   GroupByQuery, TimeseriesQuery)
from druid_tpu.utils.intervals import Interval

DAY = Interval.of("2026-01-01", "2026-01-02")
SCHEMA = (ColumnSpec("d", "string", cardinality=8),
          ColumnSpec("m", "long", low=0, high=100))


def _segments(n=2, rows=512):
    return DataGenerator(SCHEMA, seed=7).segments(
        n, rows, DAY, datasource="soak")


def _queries():
    return [
        TimeseriesQuery.of("soak", [DAY],
                           [CountAggregator("n"),
                            LongSumAggregator("s", "m")],
                           granularity="all"),
        GroupByQuery.of("soak", [DAY], [DefaultDimensionSpec("d")],
                        [CountAggregator("n")], granularity="all"),
    ]


@pytest.fixture()
def witness():
    w = LeakWitness(str(REPO_ROOT)).install()
    try:
        yield w
    finally:
        w.uninstall()


def test_server_start_stop_cycles_return_to_baseline(witness):
    """N full DataNodeServer lifecycles (serve thread, handler requests,
    scheduler-less stop path) + query waves leave no thread, fd, or pool
    residue. This is the exact loop whose per-cycle thread leak the
    leakguard burn-clean pass fixed in five server classes."""
    queries = _queries()

    def cycle():
        segments = _segments()
        node = DataNode("soak-node")
        for s in segments:
            node.load_segment(s)
        srv = DataNodeServer(node).start()
        try:
            # one real HTTP round-trip so the handler path runs too
            with urllib.request.urlopen(f"{srv.url}/status", timeout=10) \
                    as resp:
                resp.read()
            sids = [str(s.id) for s in segments]
            for q in queries:
                node.run_partials(q, sids)
        finally:
            srv.stop()

    cycle()                               # warmup: lazy init + compiles
    base = witness.snapshot()
    for _ in range(3):
        cycle()
    assert witness.leaks(base, grace_s=10.0) == []


def test_query_waves_return_pool_to_baseline(witness, monkeypatch):
    """Repeated executor waves over FRESH segments each wave: when the
    wave's segments die, their device-pool entries must die with them
    (weakref purge + drain) — resident bytes return to baseline instead
    of compounding wave over wave."""
    pool = devicepool.DeviceSegmentPool(budget_bytes=1 << 30)
    monkeypatch.setattr(devicepool, "_POOL", pool)
    queries = _queries()

    def wave():
        segments = _segments()
        ex = QueryExecutor(segments)
        for q in queries:
            ex.run(q)
        assert pool.snapshot().resident_bytes > 0, (
            "wave staged nothing — the measurement is vacuous")

    wave()                                # warmup wave
    gc.collect()
    base = witness.snapshot()
    assert base.pool_resident == 0, (
        "warmup wave's segments still resident at baseline")
    for _ in range(3):
        wave()
    assert witness.leaks(base, grace_s=10.0) == []
    stats = pool.snapshot()
    assert stats.resident_bytes == 0 and stats.entries == 0


def test_release_device_caches_unpins_stacked_segments(witness,
                                                       monkeypatch):
    """The sharded stack cache DELIBERATELY pins whole segment sets in
    HBM (the mmap analog) — which also pins their device-pool entries
    long after the view dropped the segments. That is cache policy, not a
    leak, but a months-long process still needs a way to reclaim it:
    engine.release_device_caches() is that surface, and the session-wide
    leak witness calls it so pinned cache state and real leaks stay
    distinguishable (the full-suite witness first flagged 19MB / 177
    entries of exactly this shape)."""
    from druid_tpu.engine import release_device_caches
    from druid_tpu.parallel import make_mesh

    pool = devicepool.DeviceSegmentPool(budget_bytes=1 << 30)
    monkeypatch.setattr(devicepool, "_POOL", pool)
    base = witness.snapshot()
    segments = _segments()
    # non-mesh wave stages pool entries; mesh wave pins the set in the
    # stack cache
    QueryExecutor(segments).run(_queries()[1])
    QueryExecutor(segments, mesh=make_mesh(2)).run(_queries()[1])
    assert pool.snapshot().resident_bytes > 0
    del segments
    gc.collect()
    assert pool.snapshot().resident_bytes > 0, (
        "expected the stack cache to pin the segments' pool entries — "
        "if this now self-clears, the witness workaround can go too")
    dropped = release_device_caches()
    assert dropped["stack_entries"] >= 1
    assert witness.leaks(base, grace_s=10.0) == []
    assert pool.snapshot().resident_bytes == 0


def test_thread_count_is_stable_across_cycles(witness):
    """Belt-and-braces on the coarsest axis: the absolute thread count
    after the cycles equals the post-warmup baseline (the witness's
    per-site attribution is the diagnostic; this is the invariant)."""
    import threading

    def cycle():
        segments = _segments()
        node = DataNode("soak-node")
        for s in segments:
            node.load_segment(s)
        srv = DataNodeServer(node).start()
        try:
            node.run_partials(_queries()[0], [str(segments[0].id)])
        finally:
            srv.stop()

    cycle()
    base = witness.snapshot()
    base_count = threading.active_count()
    for _ in range(3):
        cycle()
    assert witness.leaks(base, grace_s=10.0) == []
    assert threading.active_count() <= base_count, (
        f"thread count grew {base_count} -> {threading.active_count()}")
