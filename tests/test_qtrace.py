"""qtrace: end-to-end distributed query tracing.

The load-bearing assertions: one distributed query against a broker
fronting 2 REAL DataNodeServers (own TraceStores, so node spans can only
reach the broker over the wire) yields ONE assembled trace with correct
cross-process parentage; the first run of a query shows an engine/compile
span where the second (jit-cache-hit) run shows none; {"trace": false}
yields no spans anywhere; the store is a bounded ring."""
import json
import threading
import urllib.error
import urllib.request

import pytest

from druid_tpu.cluster import (Broker, DataNode, DataNodeServer,
                               InventoryView, RemoteDataNodeClient,
                               descriptor_for)
from druid_tpu.engine import QueryExecutor, batching, grouping
from druid_tpu.obs import trace as qtrace
from druid_tpu.query.aggregators import CountAggregator, LongSumAggregator
from druid_tpu.query.model import DefaultDimensionSpec, GroupByQuery, \
    TimeseriesQuery
from druid_tpu.utils.intervals import Interval

WEEK = Interval.of("2026-01-01", "2026-01-08")
AGGS = [CountAggregator("rows"), LongSumAggregator("ls", "metLong")]


def _clear_jit_caches():
    """Fresh compile state so compile-vs-cached attribution is
    deterministic regardless of what earlier tests jitted."""
    with grouping._JIT_CACHE_LOCK:
        grouping._JIT_CACHE.clear()
    with batching._JIT_CACHE_LOCK:
        batching._JIT_CACHE.clear()


# ---------------------------------------------------------------------------
# Span model unit behavior
# ---------------------------------------------------------------------------

def test_span_noop_without_root():
    """No open root → span() must yield None and record nothing (the
    untraced hot path pays one thread-local read)."""
    with qtrace.span("engine/dispatch") as s:
        assert s is None
    assert qtrace.current_span() is None


def test_root_and_children_nest():
    store = qtrace.TraceStore()
    with qtrace.root_span("query", service="svc", store=store,
                          queryId="t-nest") as root:
        assert root is not None and qtrace.current_span() is root
        with qtrace.span("child", k=1) as c:
            assert c.parent_id == root.span_id
            assert c.trace_id == root.trace_id
            assert c.service == "svc"
    got = store.get(root.trace_id)
    # get() sorts by start time: the root starts before its child
    assert [s["name"] for s in got["spans"]] == ["query", "child"]
    assert all(s["durationMs"] >= 0 for s in got["spans"])


def test_attach_propagates_across_threads():
    store = qtrace.TraceStore()
    seen = {}
    with qtrace.root_span("query", service="svc", store=store) as root:
        def worker():
            with qtrace.attach(root), qtrace.span("worker") as s:
                seen["span"] = s
        t = threading.Thread(target=worker)
        t.start()
        t.join()
    assert seen["span"].parent_id == root.span_id


def test_traceparent_reroot_and_opt_out():
    store = qtrace.TraceStore()
    q = TimeseriesQuery.of("t", [WEEK], AGGS,
                           context={"queryId": "qq",
                                    "traceparent": "remote-trace:abc123"})
    with qtrace.root_span("datanode/query", q, service="n",
                          store=store) as root:
        assert root.trace_id == "remote-trace"
        assert root.parent_id == "abc123"
    off = TimeseriesQuery.of("t", [WEEK], AGGS,
                             context={"queryId": "qq", "trace": False})
    with qtrace.root_span("datanode/query", off, service="n",
                          store=store) as root:
        assert root is None


def test_trace_store_ring_eviction():
    store = qtrace.TraceStore(max_traces=3, max_spans_per_trace=2)
    for i in range(5):
        store.add_json({"traceId": f"t{i}", "spanId": f"s{i}", "name": "x",
                        "startMs": i})
    assert store.trace_ids() == ["t2", "t3", "t4"]
    assert store.get("t0") is None
    # span cap: extra spans counted, not kept; duplicates deduped
    for j in range(4):
        store.add_json({"traceId": "t4", "spanId": f"extra{j}", "name": "y",
                        "startMs": j})
    store.add_json({"traceId": "t4", "spanId": "s4", "name": "dup",
                    "startMs": 0})
    got = store.get("t4")
    assert got["spanCount"] == 2 and got["droppedSpans"] == 3


# ---------------------------------------------------------------------------
# End-to-end: broker fronting 2 remote data nodes over real sockets
# ---------------------------------------------------------------------------

@pytest.fixture()
def traced_cluster(segments):
    """2 DataNodeServers with their OWN TraceStores: their spans can reach
    the broker's process store only via the response payload — the test
    proves wire propagation, not shared-memory accident."""
    view = InventoryView()
    nodes = [DataNode(f"tnode{i}") for i in range(2)]
    servers = []
    node_stores = []
    for node in nodes:
        st = qtrace.TraceStore()
        node_stores.append(st)
        srv = DataNodeServer(node, trace_store=st).start()
        servers.append(srv)
        view.register(RemoteDataNodeClient(node.name, srv.url))
    for i, s in enumerate(segments):
        nodes[i % 2].load_segment(s)
        view.announce(nodes[i % 2].name, descriptor_for(s))
    broker = Broker(view)
    yield nodes, servers, node_stores, broker
    for srv in servers:
        srv.stop()


def _groupby(qid, **ctx):
    return GroupByQuery.of(
        "test", [WEEK], [DefaultDimensionSpec("dimA")], AGGS,
        granularity="day", context={"queryId": qid, **ctx})


def test_distributed_trace_assembly(traced_cluster):
    nodes, servers, node_stores, broker = traced_cluster
    _clear_jit_caches()
    broker.run(_groupby("trace-e2e-1"))
    tr = qtrace.trace_store().get("trace-e2e-1")
    assert tr is not None and tr["traceId"] == "trace-e2e-1"
    spans = tr["spans"]
    by_id = {s["spanId"]: s for s in spans}
    names = [s["name"] for s in spans]

    # broker phases present
    for phase in ("broker/query", "broker/plan", "broker/scatter",
                  "broker/node", "broker/merge"):
        assert phase in names, f"missing {phase} in {sorted(set(names))}"
    # BOTH nodes' remote spans made it back over the wire
    node_roots = [s for s in spans if s["name"] == "datanode/query"]
    assert {s["service"] for s in node_roots} == {"tnode0", "tnode1"}
    # parentage: every span except the single root resolves to a parent in
    # the SAME assembled trace; node roots hang off broker/node spans
    roots = [s for s in spans if s["parentId"] is None]
    assert len(roots) == 1 and roots[0]["name"] == "broker/query"
    for s in spans:
        if s["parentId"] is not None:
            assert s["parentId"] in by_id, f"orphan span {s['name']}"
    for nr in node_roots:
        assert by_id[nr["parentId"]]["name"] == "broker/node"
    # engine phases attributed under the nodes (pool/h2d is asserted in
    # test_lifecycle_emits_phase_metrics with FRESH segments — the session
    # fixtures' segments may already be HBM-resident here)
    assert "engine/partials" in names
    # compile happened somewhere on the first run (jit caches cleared)
    assert "engine/compile" in names

    # node-local store only ever saw that node's own spans
    for st, node in zip(node_stores, nodes):
        local = st.spans("trace-e2e-1")
        assert local and all(s["service"] == node.name for s in local)


def test_compile_vs_cached_attribution(traced_cluster):
    """First run of an identical query compiles; the second hits the jit
    caches — its trace must contain NO engine/compile span (and emit no
    query/compile/time)."""
    nodes, servers, node_stores, broker = traced_cluster
    _clear_jit_caches()
    broker.run(_groupby("compile-1"))
    broker.run(_groupby("compile-2"))
    first = [s["name"] for s in qtrace.trace_store().spans("compile-1")]
    second = [s["name"] for s in qtrace.trace_store().spans("compile-2")]
    assert "engine/compile" in first
    assert "engine/compile" not in second
    # both still executed (dispatch spans present)
    assert any(n.startswith("engine/") for n in second)


def test_trace_false_yields_no_spans(traced_cluster):
    nodes, servers, node_stores, broker = traced_cluster
    broker.run(_groupby("trace-off-1", trace=False))
    assert qtrace.trace_store().get("trace-off-1") is None
    for st in node_stores:
        assert st.get("trace-off-1") is None


def test_trace_endpoint_on_data_node(traced_cluster, segments):
    """GET /druid/v2/trace/<queryId> on a data node serves its span tree."""
    nodes, servers, node_stores, broker = traced_cluster
    broker.run(_groupby("node-endpoint-1"))
    with urllib.request.urlopen(
            servers[0].url + "/druid/v2/trace/node-endpoint-1") as r:
        got = json.loads(r.read())
    assert got["traceId"] == "node-endpoint-1"
    assert all(s["service"] == nodes[0].name for s in got["spans"])
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(
            servers[0].url + "/druid/v2/trace/no-such-query")
    assert ei.value.code == 404


def test_trace_endpoint_on_broker_http(traced_cluster):
    """The broker's QueryHttpServer serves the ASSEMBLED trace — broker
    spans AND both nodes' remote spans — for a query run through it."""
    from druid_tpu.server import QueryHttpServer, QueryLifecycle
    nodes, servers, node_stores, broker = traced_cluster
    http = QueryHttpServer(QueryLifecycle(broker)).start()
    try:
        payload = _groupby("http-trace-1").to_json()
        req = urllib.request.Request(
            f"http://127.0.0.1:{http.port}/druid/v2",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req) as r:
            assert r.status == 200
        with urllib.request.urlopen(
                f"http://127.0.0.1:{http.port}/druid/v2/trace/http-trace-1"
                ) as r:
            got = json.loads(r.read())
        names = {s["name"] for s in got["spans"]}
        assert "query" in names          # the lifecycle root
        assert "broker/node" in names
        assert "datanode/query" in names
        services = {s["service"] for s in got["spans"]}
        assert {"tnode0", "tnode1"} <= services
    finally:
        http.stop()


# ---------------------------------------------------------------------------
# Local (single-process) tracing + per-query phase metrics
# ---------------------------------------------------------------------------

def test_lifecycle_emits_phase_metrics():
    """query/compile/time + query/stage/h2d/time emit on the compiling
    first run and NOT on the cache-hit second run; broker/node spans feed
    query/node/time. FRESH segments so the device pool is cold (the
    session fixtures' segments are already HBM-resident)."""
    from druid_tpu.data.generator import ColumnSpec, DataGenerator
    from druid_tpu.server import QueryLifecycle
    from druid_tpu.utils.emitter import InMemoryEmitter, ServiceEmitter
    gen = DataGenerator((ColumnSpec("dimA", "string", cardinality=10),
                         ColumnSpec("metLong", "long", low=0, high=100)),
                        seed=99)
    fresh = gen.segments(2, 1000, Interval.of("2026-01-01", "2026-01-03"),
                         datasource="test")
    view = InventoryView()
    node = DataNode("mnode")
    view.register(node)
    for s in fresh:
        node.load_segment(s)
        view.announce(node.name, descriptor_for(s))
    broker = Broker(view)
    sink = InMemoryEmitter()
    lc = QueryLifecycle(broker, ServiceEmitter("broker", "h", sink))
    _clear_jit_caches()
    lc.run(_groupby("metrics-1"))
    lc.run(_groupby("metrics-2"))
    compile_events = sink.metrics("query/compile/time")
    assert [e.dims["id"] for e in compile_events] == ["metrics-1"]
    h2d_events = sink.metrics("query/stage/h2d/time")
    assert [e.dims["id"] for e in h2d_events] == ["metrics-1"]
    node_events = sink.metrics("query/node/time")
    assert {e.dims["id"] for e in node_events} == {"metrics-1", "metrics-2"}
    assert all(e.dims["server"] == "mnode" for e in node_events)


def test_slow_query_log_threshold(segments):
    """Queries over the threshold emit an alert with the full phase
    breakdown; under it, nothing."""
    from druid_tpu.server import QueryLifecycle
    from druid_tpu.utils.emitter import InMemoryEmitter, ServiceEmitter
    sink = InMemoryEmitter()
    lc = QueryLifecycle(QueryExecutor(list(segments)),
                        ServiceEmitter("broker", "h", sink),
                        slow_query_ms=0.0)     # everything is slow
    lc.run(_groupby("slow-1"))
    alerts = [e for e in sink.events if e.kind == "alert"]
    assert len(alerts) == 1
    a = alerts[0]
    assert a.dims["queryId"] == "slow-1"
    assert isinstance(a.dims["breakdown"], dict) and a.dims["breakdown"]
    assert all(v >= 0 for v in a.dims["breakdown"].values())

    sink2 = InMemoryEmitter()
    lc2 = QueryLifecycle(QueryExecutor(list(segments)),
                         ServiceEmitter("broker", "h", sink2),
                         slow_query_ms=1e9)    # nothing is slow
    lc2.run(_groupby("slow-2"))
    assert not [e for e in sink2.events if e.kind == "alert"]

    # opting out of TRACING must not opt out of the slow-query alert —
    # it fires from the wall clock, just with an empty breakdown
    sink3 = InMemoryEmitter()
    lc3 = QueryLifecycle(QueryExecutor(list(segments)),
                         ServiceEmitter("broker", "h", sink3),
                         slow_query_ms=0.0)
    lc3.run(_groupby("slow-3", trace=False))
    alerts3 = [e for e in sink3.events if e.kind == "alert"]
    assert len(alerts3) == 1
    assert alerts3[0].dims["queryId"] == "slow-3"
    assert alerts3[0].dims["breakdown"] == {}
