"""Timeline MVCC + shard spec tests — the analog of the reference's
VersionedIntervalTimelineTest scenarios."""
import pytest

from druid_tpu.cluster import (HashBasedNumberedShardSpec, NoneShardSpec,
                               NumberedShardSpec, PartitionChunk,
                               SingleDimensionShardSpec,
                               VersionedIntervalTimeline, shardspec_from_json)
from druid_tpu.utils.intervals import Interval


def IV(a, b):
    return Interval.of(f"2026-01-{a:02d}", f"2026-01-{b:02d}")


def chunk(obj, spec=None):
    return PartitionChunk(spec or NoneShardSpec(), obj)


def lookup_objs(tl, iv):
    return [(str(h.interval), h.version, sorted(h.payloads()))
            for h in tl.lookup(iv)]


def test_basic_add_lookup():
    tl = VersionedIntervalTimeline()
    tl.add(IV(1, 2), "v1", chunk("a"))
    tl.add(IV(2, 3), "v1", chunk("b"))
    out = tl.lookup(IV(1, 3))
    assert [h.payloads() for h in out] == [["a"], ["b"]]
    # clipping to query interval
    out = tl.lookup(Interval.of("2026-01-01T06:00:00Z", "2026-01-02"))
    assert len(out) == 1 and out[0].payloads() == ["a"]
    assert out[0].interval == Interval.of("2026-01-01T06:00:00Z", "2026-01-02")


def test_higher_version_overshadows():
    tl = VersionedIntervalTimeline()
    tl.add(IV(1, 3), "v1", chunk("old"))
    tl.add(IV(1, 3), "v2", chunk("new"))
    assert lookup_objs(tl, IV(1, 3)) == [
        ("2026-01-01T00:00:00.000Z/2026-01-03T00:00:00.000Z", "v2", ["new"])]
    # removing v2 resurrects v1
    tl.remove(IV(1, 3), "v2", 0)
    assert lookup_objs(tl, IV(1, 3)) == [
        ("2026-01-01T00:00:00.000Z/2026-01-03T00:00:00.000Z", "v1", ["old"])]


def test_partial_overshadow_splits():
    tl = VersionedIntervalTimeline()
    tl.add(IV(1, 5), "v1", chunk("wide"))
    tl.add(IV(2, 3), "v2", chunk("narrow"))
    out = lookup_objs(tl, IV(1, 5))
    assert out == [
        ("2026-01-01T00:00:00.000Z/2026-01-02T00:00:00.000Z", "v1", ["wide"]),
        ("2026-01-02T00:00:00.000Z/2026-01-03T00:00:00.000Z", "v2", ["narrow"]),
        ("2026-01-03T00:00:00.000Z/2026-01-05T00:00:00.000Z", "v1", ["wide"]),
    ]


def test_incomplete_partition_set_invisible():
    tl = VersionedIntervalTimeline()
    tl.add(IV(1, 2), "v2", chunk("p0", NumberedShardSpec(0, 2)))
    tl.add(IV(1, 2), "v1", chunk("whole"))
    # v2 has 1 of 2 partitions: invisible, v1 shows
    assert lookup_objs(tl, IV(1, 2))[0][1] == "v1"
    tl.add(IV(1, 2), "v2", chunk("p1", NumberedShardSpec(1, 2)))
    out = tl.lookup(IV(1, 2))
    assert out[0].version == "v2"
    assert sorted(out[0].payloads()) == ["p0", "p1"]
    # incomplete entries visible through lookup_with_incomplete
    tl2 = VersionedIntervalTimeline()
    tl2.add(IV(1, 2), "v1", chunk("x", NumberedShardSpec(0, 3)))
    assert tl2.lookup(IV(1, 2)) == []
    assert len(tl2.lookup_with_incomplete(IV(1, 2))) == 1


def test_is_overshadowed_and_find_fully():
    tl = VersionedIntervalTimeline()
    tl.add(IV(1, 3), "v1", chunk("old"))
    tl.add(IV(1, 2), "v2", chunk("n1"))
    assert not tl.is_overshadowed(IV(1, 3), "v1")  # only half covered
    tl.add(IV(2, 3), "v3", chunk("n2"))
    assert tl.is_overshadowed(IV(1, 3), "v1")      # covered by v2+v3 union
    shadowed = tl.find_fully_overshadowed()
    assert [h.version for h in shadowed] == ["v1"]
    # newer versions are not overshadowed
    assert not tl.is_overshadowed(IV(1, 2), "v2")


def test_version_comparison_is_lexicographic():
    tl = VersionedIntervalTimeline()
    tl.add(IV(1, 2), "2026-01-01T00:00:00Z", chunk("older"))
    tl.add(IV(1, 2), "2026-01-02T00:00:00Z", chunk("newer"))
    assert tl.lookup(IV(1, 2))[0].payloads() == ["newer"]


def test_adjacent_same_entry_merges():
    tl = VersionedIntervalTimeline()
    tl.add(IV(1, 5), "v1", chunk("w"))
    # lookup over a range with an internal boundary from another datasource's
    # perspective must not split the holder
    out = tl.lookup(IV(1, 5))
    assert len(out) == 1


# -- shard specs --------------------------------------------------------

def test_numbered_shardspec_completeness():
    s0, s1 = NumberedShardSpec(0, 2), NumberedShardSpec(1, 2)
    assert not s0.complete_set([s0])
    assert s0.complete_set([s0, s1])
    # open-ended (streaming) sets are always complete
    assert NumberedShardSpec(3, 0).complete_set([NumberedShardSpec(3, 0)])


def test_hashed_shardspec_routing_and_pruning():
    specs = [HashBasedNumberedShardSpec(i, 4, ("user",)) for i in range(4)]
    rows = [{"user": f"u{i}"} for i in range(100)]
    counts = [0] * 4
    for r in rows:
        owners = [s for s in specs if s.is_in_chunk(r)]
        assert len(owners) == 1  # exactly one shard owns each row
        counts[owners[0].partition_num] += 1
    assert all(c > 10 for c in counts)  # roughly balanced
    # pruning: a pinned value hits exactly one shard
    domain = {"user": ["u7"]}
    possible = [s for s in specs if s.possible_in_domain(domain)]
    assert len(possible) == 1
    assert possible[0].is_in_chunk({"user": "u7"})
    # unconstrained dim: no pruning
    assert all(s.possible_in_domain({}) for s in specs)


def test_single_dimension_shardspec():
    a = SingleDimensionShardSpec("d", None, "m", 0)
    b = SingleDimensionShardSpec("d", "m", None, 1)
    assert a.is_in_chunk({"d": "apple"})
    assert not a.is_in_chunk({"d": "zebra"})
    assert b.is_in_chunk({"d": "zebra"})
    assert a.complete_set([a, b])
    assert not a.complete_set([a])
    gap = SingleDimensionShardSpec("d", "x", None, 1)
    assert not a.complete_set([a, gap])
    assert a.possible_in_domain({"d": ["apple"]})
    assert not a.possible_in_domain({"d": ["zebra"]})


def test_shardspec_json_roundtrip():
    for s in [NoneShardSpec(), NumberedShardSpec(1, 3),
              HashBasedNumberedShardSpec(2, 4, ("a", "b")),
              SingleDimensionShardSpec("d", "a", "b", 1)]:
        assert shardspec_from_json(s.to_json()) == s
