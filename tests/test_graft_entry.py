"""Driver entry contract (VERDICT round 5, weak spot #5): the suite was
structurally blind to backend-init hangs because conftest pins platforms
before jax loads. These tests run `__graft_entry__` the way the DRIVER
does — subprocess, no conftest, env unpinned — and unit-test the
backend-init watchdog that turns a wedged TPU tunnel into a fast,
actionable error instead of an rc=124 hang."""
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# watchdog unit tests (in-process, fake init)
# ---------------------------------------------------------------------------

def test_watchdog_times_out_hanging_backend_init(monkeypatch):
    """A blocking plugin init (the axon tunnel wedge) must surface as a
    RuntimeError within the deadline, not hang."""
    import jax

    import __graft_entry__ as g

    def hang(*a, **k):
        time.sleep(60)

    monkeypatch.setattr(jax, "devices", hang)
    t0 = time.monotonic()
    with pytest.raises(RuntimeError, match="did not complete"):
        g._init_cpu_backend(1, timeout_s=0.3)
    assert time.monotonic() - t0 < 5.0


def test_watchdog_propagates_init_errors(monkeypatch):
    import jax

    import __graft_entry__ as g

    def boom(*a, **k):
        raise ValueError("plugin exploded")

    monkeypatch.setattr(jax, "devices", boom)
    with pytest.raises(ValueError, match="plugin exploded"):
        g._init_cpu_backend(1, timeout_s=5.0)


def test_watchdog_reports_device_shortfall(monkeypatch):
    import jax

    import __graft_entry__ as g

    monkeypatch.setattr(jax, "devices", lambda *a, **k: [object()])
    with pytest.raises(RuntimeError, match="need 4 cpu devices, have 1"):
        g._init_cpu_backend(4, timeout_s=5.0)


# ---------------------------------------------------------------------------
# the driver contract, end to end
# ---------------------------------------------------------------------------

def test_dryrun_multichip_subprocess_like_the_driver():
    """dryrun_multichip in a fresh interpreter with NO platform pinning
    from the environment — the entry point itself must pin cpu + the
    virtual device count before backend init and complete quickly
    (MULTICHIP_r05 hung for 10 minutes here)."""
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, "-c",
         "import __graft_entry__ as g; g.dryrun_multichip(2)"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=240)
    elapsed = time.monotonic() - t0
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "dryrun_multichip(2)" in proc.stdout
    assert "sharded == host-merged" in proc.stdout
    assert elapsed < 180, f"dryrun took {elapsed:.0f}s — hang regression?"


def test_dryrun_fails_fast_when_backend_init_hangs():
    """Simulated wedged tunnel: jax is pre-imported (driver-style) with
    jax.devices replaced by a blocker AFTER the entry's config pins are
    already too late to matter — the watchdog must turn this into a
    clean, fast error with an actionable message, never a hang."""
    code = (
        "import jax\n"
        "import time as _t\n"
        "jax.devices = lambda *a, **k: _t.sleep(600)\n"
        "import __graft_entry__ as g\n"
        "g.dryrun_multichip(2)\n"
    )
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    env["DRUID_TPU_BACKEND_INIT_TIMEOUT_S"] = "2"
    t0 = time.monotonic()
    proc = subprocess.run([sys.executable, "-c", code], cwd=REPO, env=env,
                          capture_output=True, text=True, timeout=120)
    elapsed = time.monotonic() - t0
    assert proc.returncode != 0
    assert "did not complete within 2s" in proc.stderr
    assert "JAX_PLATFORMS=cpu" in proc.stderr      # actionable remedy
    assert elapsed < 60, f"failure took {elapsed:.0f}s — not fail-fast"
