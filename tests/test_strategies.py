"""Reduction-strategy equivalence: mm / windowed / blocked / mixed must all
produce identical results (reference semantics are strategy-independent —
GroupByQueryEngineV2 vs vectorized engines return the same rows)."""
import numpy as np
import pytest

from druid_tpu.data.generator import ColumnSpec, DataGenerator
from druid_tpu.engine import QueryExecutor
from druid_tpu.engine import grouping
from druid_tpu.query.aggregators import (CountAggregator, DoubleSumAggregator,
                                         FloatMaxAggregator,
                                         FloatSumAggregator,
                                         LongMinAggregator, LongSumAggregator)
from druid_tpu.query.filters import BoundFilter
from druid_tpu.query.model import DefaultDimensionSpec, GroupByQuery
from druid_tpu.utils.intervals import Interval

INTERVAL = Interval.of("2026-01-01", "2026-01-02")


def _gen(sort_by_dims, card_a=30, card_b=200, n=40_000, lo=-500, hi=9_000):
    schema = (
        ColumnSpec("dimA", "string", cardinality=card_a),
        ColumnSpec("dimB", "string", cardinality=card_b, distribution="zipf"),
        ColumnSpec("metLong", "long", low=lo, high=hi),
        ColumnSpec("metFloat", "float", distribution="normal", mean=10.0,
                   std=400.0),
    )
    gen = DataGenerator(schema, seed=77)
    return gen.segments(2, n // 2, INTERVAL, sort_by_dims=sort_by_dims)


AGGS = [CountAggregator("rows"),
        LongSumAggregator("lsum", "metLong"),
        FloatSumAggregator("fsum", "metFloat"),
        FloatMaxAggregator("fmax", "metFloat"),
        LongMinAggregator("lmin", "metLong")]

MM_AGGS = AGGS[:3]   # sum-decomposable only


def _run(segments, aggs, dims, flt=None, force=None, monkeypatch=None,
         mesh=None):
    if force is not None:
        orig = grouping.select_strategy

        def fake(spec, kernels, col_dtypes, padded_rows, windowed_w):
            s, w = orig(spec, kernels, col_dtypes, padded_rows, windowed_w)
            if force == "mixed":
                return "mixed", 0
            assert s == force, f"expected strategy {force}, selected {s}"
            return s, w
        monkeypatch.setattr(grouping, "select_strategy", fake)
    try:
        q = GroupByQuery.of(
            "bench", [INTERVAL], [DefaultDimensionSpec(d) for d in dims],
            aggs, granularity="all", filter=flt)
        ex = QueryExecutor(segments, mesh=mesh)
        rows = ex.run(q)
    finally:
        if force is not None:
            monkeypatch.setattr(grouping, "select_strategy", orig)
    out = {}
    for r in rows:
        e = r["event"]
        out[tuple(e[d] for d in dims)] = {
            k: e[k] for k in e if k not in dims}
    return out


def _compare(a, b, float_keys=("fsum", "fmax")):
    assert set(a) == set(b)
    for k in a:
        for m in a[k]:
            va, vb = a[k][m], b[k][m]
            if m in float_keys:
                assert va == pytest.approx(vb, rel=1e-4, abs=1e-2), (k, m)
            else:
                assert va == vb, (k, m)


def test_mm_matches_mixed_small_group(monkeypatch):
    segments = _gen(sort_by_dims=False, card_b=40)
    flt = BoundFilter("metLong", lower=-100, upper=8_000, ordering="numeric")
    got = _run(segments, MM_AGGS, ["dimB"], flt)          # auto → mm
    want = _run(segments, MM_AGGS, ["dimB"], flt, force="mixed",
                monkeypatch=monkeypatch)
    _compare(got, want)


def test_mm_negative_longs_exact(monkeypatch):
    segments = _gen(sort_by_dims=False, card_b=40, lo=-4_000, hi=-1)
    got = _run(segments, MM_AGGS, ["dimB"])
    want = _run(segments, MM_AGGS, ["dimB"], force="mixed",
                monkeypatch=monkeypatch)
    _compare(got, want)


def test_windowed_matches_mixed_big_group(monkeypatch):
    segments = _gen(sort_by_dims=True)
    # 30 x 200 = 6000 groups > 2048 → windowed on the sorted layout
    flt = BoundFilter("metLong", lower=0, upper=8_500, ordering="numeric")
    got = _run(segments, AGGS, ["dimA", "dimB"], flt, force="windowed",
               monkeypatch=monkeypatch)
    want = _run(segments, AGGS, ["dimA", "dimB"], flt, force="mixed",
                monkeypatch=monkeypatch)
    _compare(got, want)


def test_windowed_ineligible_on_unsorted():
    segments = _gen(sort_by_dims=False)
    spec = grouping.make_group_spec(
        segments[0], [INTERVAL],
        __import__("druid_tpu.utils.granularity",
                   fromlist=["Granularity"]).Granularity.of("all"),
        [grouping.KeyDim("dimA", 30, None),
         grouping.KeyDim("dimB", 200, None)])
    from druid_tpu.utils.granularity import Granularity
    w = grouping.windowed_window(segments[0], [INTERVAL],
                                 Granularity.of("all"), spec)
    assert w == 0


def test_windowed_eligible_on_sorted():
    segments = _gen(sort_by_dims=True)
    from druid_tpu.utils.granularity import Granularity
    spec = grouping.make_group_spec(
        segments[0], [INTERVAL], Granularity.of("all"),
        [grouping.KeyDim("dimA", 30, None),
         grouping.KeyDim("dimB", 200, None)])
    w = grouping.windowed_window(segments[0], [INTERVAL],
                                 Granularity.of("all"), spec)
    assert w in grouping.WINDOW_CHOICES


def test_mm_float_nan_confined_to_its_group():
    """A single NaN float row must only NaN its OWN group (reference
    FloatSumAggregator semantics) — the mm one-hot contraction would spread
    it to every group, so non-finite columns must be mm-ineligible."""
    segments = _gen(sort_by_dims=False, card_b=40)
    s0 = segments[0]
    vals = s0.metrics["metFloat"].values
    poison_row = 7
    vals[poison_row] = np.nan
    poison_group = None
    col = s0.dims["dimB"]
    poison_group = col.dictionary.values[col.ids[poison_row]]

    got = _run(segments, MM_AGGS, ["dimB"])
    assert np.isnan(got[(poison_group,)]["fsum"])
    for k, v in got.items():
        if k != (poison_group,):
            assert np.isfinite(v["fsum"]), k


def test_mm_float_nan_column_not_mm(monkeypatch):
    segments = _gen(sort_by_dims=False, card_b=40)
    segments[0].metrics["metFloat"].values[3] = np.inf
    seen = []
    orig = grouping.select_strategy

    def spy(spec, kernels, col_dtypes, padded_rows, windowed_w):
        s, w = orig(spec, kernels, col_dtypes, padded_rows, windowed_w)
        seen.append(s)
        return s, w
    monkeypatch.setattr(grouping, "select_strategy", spy)
    _run(segments, MM_AGGS, ["dimB"])
    assert seen and all(s != "mm" for s in seen)


def test_mesh_forced_mm_matches_mixed(monkeypatch):
    from druid_tpu.parallel import make_mesh
    # card 200 pads to 256: above the ≤64 blocked cut, inside mm range
    segments = _gen(sort_by_dims=False, card_b=200)
    flt = BoundFilter("metLong", lower=-100, upper=8_000, ordering="numeric")
    mesh = make_mesh(2)
    got = _run(segments, MM_AGGS, ["dimB"], flt, force="mm",
               monkeypatch=monkeypatch, mesh=mesh)
    want = _run(segments, MM_AGGS, ["dimB"], flt, force="mixed",
                monkeypatch=monkeypatch, mesh=mesh)
    _compare(got, want)


def test_mesh_forced_windowed_matches_mixed(monkeypatch):
    from druid_tpu.parallel import make_mesh
    segments = _gen(sort_by_dims=True)
    flt = BoundFilter("metLong", lower=0, upper=8_500, ordering="numeric")
    mesh = make_mesh(2)
    got = _run(segments, AGGS, ["dimA", "dimB"], flt, force="windowed",
               monkeypatch=monkeypatch, mesh=mesh)
    want = _run(segments, AGGS, ["dimA", "dimB"], flt, force="mixed",
                monkeypatch=monkeypatch, mesh=mesh)
    _compare(got, want)


def test_mm_double_sum_falls_back(monkeypatch):
    # doubleSum has no mm decomposition → strategy must not be "mm"
    segments = _gen(sort_by_dims=False, card_b=40)
    aggs = [CountAggregator("rows"), DoubleSumAggregator("dsum", "metFloat")]
    seen = []
    orig = grouping.select_strategy

    def spy(spec, kernels, col_dtypes, padded_rows, windowed_w):
        s, w = orig(spec, kernels, col_dtypes, padded_rows, windowed_w)
        seen.append(s)
        return s, w
    monkeypatch.setattr(grouping, "select_strategy", spy)
    _run(segments, aggs, ["dimB"])
    assert seen and all(s != "mm" for s in seen)
