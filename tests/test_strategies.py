"""Reduction-strategy equivalence: mm / windowed / blocked / mixed must all
produce identical results (reference semantics are strategy-independent —
GroupByQueryEngineV2 vs vectorized engines return the same rows)."""
import collections

import numpy as np
import pytest

from druid_tpu.data.generator import ColumnSpec, DataGenerator
from druid_tpu.engine import QueryExecutor
from druid_tpu.engine import grouping
from druid_tpu.query.aggregators import (CountAggregator, DoubleSumAggregator,
                                         FloatMaxAggregator,
                                         FloatSumAggregator,
                                         LongMinAggregator, LongSumAggregator)
from druid_tpu.query.filters import BoundFilter
from druid_tpu.query.model import DefaultDimensionSpec, GroupByQuery
from druid_tpu.utils.intervals import Interval

INTERVAL = Interval.of("2026-01-01", "2026-01-02")


def _gen(sort_by_dims, card_a=30, card_b=200, n=40_000, lo=-500, hi=9_000):
    schema = (
        ColumnSpec("dimA", "string", cardinality=card_a),
        ColumnSpec("dimB", "string", cardinality=card_b, distribution="zipf"),
        ColumnSpec("metLong", "long", low=lo, high=hi),
        ColumnSpec("metFloat", "float", distribution="normal", mean=10.0,
                   std=400.0),
    )
    gen = DataGenerator(schema, seed=77)
    return gen.segments(2, n // 2, INTERVAL, sort_by_dims=sort_by_dims)


AGGS = [CountAggregator("rows"),
        LongSumAggregator("lsum", "metLong"),
        FloatSumAggregator("fsum", "metFloat"),
        FloatMaxAggregator("fmax", "metFloat"),
        LongMinAggregator("lmin", "metLong")]

MM_AGGS = AGGS[:3]   # sum-decomposable only


def _run(segments, aggs, dims, flt=None, force=None, monkeypatch=None,
         mesh=None):
    if force is not None:
        orig = grouping.select_strategy

        def fake(spec, kernels, col_dtypes, padded_rows, windowed_w):
            s, w = orig(spec, kernels, col_dtypes, padded_rows, windowed_w)
            if force == "mixed":
                return "mixed", 0
            assert s == force, f"expected strategy {force}, selected {s}"
            return s, w
        monkeypatch.setattr(grouping, "select_strategy", fake)
    try:
        q = GroupByQuery.of(
            "bench", [INTERVAL], [DefaultDimensionSpec(d) for d in dims],
            aggs, granularity="all", filter=flt)
        ex = QueryExecutor(segments, mesh=mesh)
        rows = ex.run(q)
    finally:
        if force is not None:
            monkeypatch.setattr(grouping, "select_strategy", orig)
    out = {}
    for r in rows:
        e = r["event"]
        out[tuple(e[d] for d in dims)] = {
            k: e[k] for k in e if k not in dims}
    return out


def _compare(a, b, float_keys=("fsum", "fmax")):
    assert set(a) == set(b)
    for k in a:
        for m in a[k]:
            va, vb = a[k][m], b[k][m]
            if m in float_keys:
                assert va == pytest.approx(vb, rel=1e-4, abs=1e-2), (k, m)
            else:
                assert va == vb, (k, m)


def test_mm_matches_mixed_small_group(monkeypatch):
    segments = _gen(sort_by_dims=False, card_b=40)
    flt = BoundFilter("metLong", lower=-100, upper=8_000, ordering="numeric")
    got = _run(segments, MM_AGGS, ["dimB"], flt)          # auto → mm
    want = _run(segments, MM_AGGS, ["dimB"], flt, force="mixed",
                monkeypatch=monkeypatch)
    _compare(got, want)


def test_mm_negative_longs_exact(monkeypatch):
    segments = _gen(sort_by_dims=False, card_b=40, lo=-4_000, hi=-1)
    got = _run(segments, MM_AGGS, ["dimB"])
    want = _run(segments, MM_AGGS, ["dimB"], force="mixed",
                monkeypatch=monkeypatch)
    _compare(got, want)


def test_windowed_matches_mixed_big_group(monkeypatch):
    segments = _gen(sort_by_dims=True)
    # 30 x 200 = 6000 groups > 2048 → windowed on the sorted layout
    flt = BoundFilter("metLong", lower=0, upper=8_500, ordering="numeric")
    got = _run(segments, AGGS, ["dimA", "dimB"], flt, force="windowed",
               monkeypatch=monkeypatch)
    want = _run(segments, AGGS, ["dimA", "dimB"], flt, force="mixed",
                monkeypatch=monkeypatch)
    _compare(got, want)


def test_windowed_ineligible_on_unsorted():
    segments = _gen(sort_by_dims=False)
    spec = grouping.make_group_spec(
        segments[0], [INTERVAL],
        __import__("druid_tpu.utils.granularity",
                   fromlist=["Granularity"]).Granularity.of("all"),
        [grouping.KeyDim("dimA", 30, None),
         grouping.KeyDim("dimB", 200, None)])
    from druid_tpu.utils.granularity import Granularity
    w = grouping.windowed_window(segments[0], [INTERVAL],
                                 Granularity.of("all"), spec)
    assert w == 0


def test_windowed_eligible_on_sorted():
    segments = _gen(sort_by_dims=True)
    from druid_tpu.utils.granularity import Granularity
    spec = grouping.make_group_spec(
        segments[0], [INTERVAL], Granularity.of("all"),
        [grouping.KeyDim("dimA", 30, None),
         grouping.KeyDim("dimB", 200, None)])
    w = grouping.windowed_window(segments[0], [INTERVAL],
                                 Granularity.of("all"), spec)
    assert w in grouping.WINDOW_CHOICES


def test_mm_float_nan_confined_to_its_group():
    """A single NaN float row must only NaN its OWN group (reference
    FloatSumAggregator semantics) — the mm one-hot contraction would spread
    it to every group, so non-finite columns must be mm-ineligible."""
    segments = _gen(sort_by_dims=False, card_b=40)
    s0 = segments[0]
    vals = s0.metrics["metFloat"].values
    poison_row = 7
    vals[poison_row] = np.nan
    poison_group = None
    col = s0.dims["dimB"]
    poison_group = col.dictionary.values[col.ids[poison_row]]

    got = _run(segments, MM_AGGS, ["dimB"])
    assert np.isnan(got[(poison_group,)]["fsum"])
    for k, v in got.items():
        if k != (poison_group,):
            assert np.isfinite(v["fsum"]), k


def test_mm_float_nan_column_not_mm(monkeypatch):
    segments = _gen(sort_by_dims=False, card_b=40)
    segments[0].metrics["metFloat"].values[3] = np.inf
    seen = []
    orig = grouping.select_strategy

    def spy(spec, kernels, col_dtypes, padded_rows, windowed_w):
        s, w = orig(spec, kernels, col_dtypes, padded_rows, windowed_w)
        seen.append(s)
        return s, w
    monkeypatch.setattr(grouping, "select_strategy", spy)
    _run(segments, MM_AGGS, ["dimB"])
    assert seen and all(s != "mm" for s in seen)


def test_mesh_forced_mm_matches_mixed(monkeypatch):
    from druid_tpu.parallel import make_mesh
    # card 200 pads to 256: above the ≤64 blocked cut, inside mm range
    segments = _gen(sort_by_dims=False, card_b=200)
    flt = BoundFilter("metLong", lower=-100, upper=8_000, ordering="numeric")
    mesh = make_mesh(2)
    got = _run(segments, MM_AGGS, ["dimB"], flt, force="mm",
               monkeypatch=monkeypatch, mesh=mesh)
    want = _run(segments, MM_AGGS, ["dimB"], flt, force="mixed",
                monkeypatch=monkeypatch, mesh=mesh)
    _compare(got, want)


def test_mesh_forced_windowed_matches_mixed(monkeypatch):
    from druid_tpu.parallel import make_mesh
    segments = _gen(sort_by_dims=True)
    flt = BoundFilter("metLong", lower=0, upper=8_500, ordering="numeric")
    mesh = make_mesh(2)
    got = _run(segments, AGGS, ["dimA", "dimB"], flt, force="windowed",
               monkeypatch=monkeypatch, mesh=mesh)
    want = _run(segments, AGGS, ["dimA", "dimB"], flt, force="mixed",
                monkeypatch=monkeypatch, mesh=mesh)
    _compare(got, want)


def _spy_strategies(monkeypatch):
    seen = []
    orig = grouping.select_strategy

    def spy(spec, kernels, col_dtypes, padded_rows, windowed_w):
        s, w = orig(spec, kernels, col_dtypes, padded_rows, windowed_w)
        seen.append(s)
        return s, w
    monkeypatch.setattr(grouping, "select_strategy", spy)
    return seen


def test_projection_pallas_interpret_matches_mixed(monkeypatch):
    """The fused pallas kernel (via the interpreter on CPU) must agree with
    the mixed path exactly — count, exact int64 sums through the lo/hi limb
    pair, float sums, and min/max."""
    from druid_tpu.engine import pallas_agg
    segments = _gen(sort_by_dims=False)   # 30 x 200 = 6000 > MM_GROUP_LIMIT
    monkeypatch.setattr(grouping, "PROJECTION_MIN_ROWS", 0)
    monkeypatch.setattr(pallas_agg, "_FORCE_INTERPRET", True)
    inner = []
    orig_inner = grouping._projection_strategy

    def spy(proj, kernels, col_dtypes, num_total):
        s, w = orig_inner(proj, kernels, col_dtypes, num_total)
        inner.append(s)
        return s, w
    monkeypatch.setattr(grouping, "_projection_strategy", spy)
    got = _run(segments, AGGS, ["dimA", "dimB"])
    assert inner and all(s == "pallas" for s in inner)
    monkeypatch.setattr(pallas_agg, "_FORCE_INTERPRET", False)
    want = _run(segments, AGGS, ["dimA", "dimB"], force="mixed",
                monkeypatch=monkeypatch)
    _compare(got, want)


def test_projection_windowed_matches_mixed(monkeypatch):
    """With pallas gated off, the projection strategy reduces through the XLA
    windowed path over the sorted layout; results must match mixed."""
    monkeypatch.setenv("DRUID_TPU_PALLAS", "0")
    segments = _gen(sort_by_dims=False)
    monkeypatch.setattr(grouping, "PROJECTION_MIN_ROWS", 0)
    inner = []
    orig_inner = grouping._projection_strategy

    def spy(proj, kernels, col_dtypes, num_total):
        s, w = orig_inner(proj, kernels, col_dtypes, num_total)
        inner.append(s)
        return s, w
    monkeypatch.setattr(grouping, "_projection_strategy", spy)
    flt = BoundFilter("metLong", lower=0, upper=8_500, ordering="numeric")
    got = _run(segments, AGGS, ["dimA", "dimB"], flt)
    assert inner and all(s == "windowed" for s in inner)
    want = _run(segments, AGGS, ["dimA", "dimB"], flt, force="mixed",
                monkeypatch=monkeypatch)
    _compare(got, want)


def test_pallas_limb_sum_exact_across_flushes(monkeypatch):
    """int32 long sums ride a lo/hi limb pair flushed every K blocks; with
    values near the chunk_rows bound and >> chunk_rows rows per group the
    total exceeds int32 and must still be bit-exact int64."""
    from druid_tpu.engine import pallas_agg
    segments = _gen(sort_by_dims=False, card_a=2, card_b=3, n=40_000,
                    lo=200_000, hi=260_000)
    monkeypatch.setattr(grouping, "PROJECTION_MIN_ROWS", 0)
    monkeypatch.setattr(pallas_agg, "_FORCE_INTERPRET", True)
    orig = grouping.select_strategy

    def force_proj(spec, kernels, col_dtypes, padded_rows, windowed_w):
        return "projection", 0
    monkeypatch.setattr(grouping, "select_strategy", force_proj)
    aggs = [CountAggregator("rows"), LongSumAggregator("lsum", "metLong")]
    got = _run(segments, aggs, ["dimA", "dimB"])
    monkeypatch.setattr(pallas_agg, "_FORCE_INTERPRET", False)
    monkeypatch.setattr(grouping, "select_strategy", orig)
    want = _run(segments, aggs, ["dimA", "dimB"], force="mixed",
                monkeypatch=monkeypatch)
    # per-group totals ~ 40000/6 * 230000 ≈ 1.5e9, sums overflow across limbs
    assert any(v["lsum"] > 2**30 for v in want.values())
    _compare(got, want)


def test_pallas_fully_masked_blocks(monkeypatch):
    """A selective filter leaves whole sorted blocks masked; those blocks
    must contribute nothing (their keys read as the sentinel)."""
    from druid_tpu.engine import pallas_agg
    from druid_tpu.query.filters import SelectorFilter
    segments = _gen(sort_by_dims=False)
    monkeypatch.setattr(grouping, "PROJECTION_MIN_ROWS", 0)
    monkeypatch.setattr(pallas_agg, "_FORCE_INTERPRET", True)
    flt = SelectorFilter("dimA", "v00000003")
    got = _run(segments, AGGS, ["dimA", "dimB"], flt)
    monkeypatch.setattr(pallas_agg, "_FORCE_INTERPRET", False)
    want = _run(segments, AGGS, ["dimA", "dimB"], flt, force="mixed",
                monkeypatch=monkeypatch)
    _compare(got, want)


def test_pallas_compile_failure_falls_back(monkeypatch):
    """A Mosaic compile failure must not fail the query: the executor latches
    pallas off and re-runs the same plan on the XLA windowed/mixed path."""
    from druid_tpu.engine import pallas_agg
    segments = _gen(sort_by_dims=False)
    monkeypatch.setattr(grouping, "PROJECTION_MIN_ROWS", 0)
    monkeypatch.setattr(pallas_agg, "_FORCE_INTERPRET", True)
    monkeypatch.setattr(pallas_agg, "_BROKEN", None)
    monkeypatch.setattr(grouping, "_JIT_CACHE", collections.OrderedDict())

    def boom(*a, **k):
        raise RuntimeError("Mosaic failed to compile TPU kernel")
    monkeypatch.setattr(pallas_agg, "pallas_reduce", boom)
    got = _run(segments, AGGS, ["dimA", "dimB"])
    assert pallas_agg._BROKEN is not None
    monkeypatch.setattr(pallas_agg, "_FORCE_INTERPRET", False)
    want = _run(segments, AGGS, ["dimA", "dimB"], force="mixed",
                monkeypatch=monkeypatch)
    _compare(got, want)


def test_mm_double_sum_falls_back(monkeypatch):
    # doubleSum has no mm decomposition → strategy must not be "mm"
    segments = _gen(sort_by_dims=False, card_b=40)
    aggs = [CountAggregator("rows"), DoubleSumAggregator("dsum", "metFloat")]
    seen = []
    orig = grouping.select_strategy

    def spy(spec, kernels, col_dtypes, padded_rows, windowed_w):
        s, w = orig(spec, kernels, col_dtypes, padded_rows, windowed_w)
        seen.append(s)
        return s, w
    monkeypatch.setattr(grouping, "select_strategy", spy)
    _run(segments, aggs, ["dimB"])
    assert seen and all(s != "mm" for s in seen)


def test_force_strategy_override_equivalence(segments, monkeypatch):
    """DRUID_TPU_STRATEGY / grouping.FORCE_STRATEGY forces an eligible
    strategy (the chip-suite measurement hook); results stay identical."""
    from druid_tpu.engine import QueryExecutor, grouping
    from druid_tpu.query.aggregators import CountAggregator, LongSumAggregator
    from druid_tpu.query.model import DefaultDimensionSpec, GroupByQuery
    from druid_tpu.utils.intervals import Interval
    iv = Interval.of("2026-01-01", "2026-01-08")
    q = GroupByQuery.of(
        "test", [iv],
        [DefaultDimensionSpec("dimA"), DefaultDimensionSpec("dimB")],
        [CountAggregator("n"), LongSumAggregator("s", "metLong")],
        granularity="all")
    base = QueryExecutor(segments).run(q)
    key = lambda rows: {(r["event"]["dimA"], r["event"]["dimB"]):
                        (r["event"]["n"], r["event"]["s"]) for r in rows}
    want = key(base)
    real_select = grouping.select_strategy
    chosen = []

    def spy(*a, **kw):
        out = real_select(*a, **kw)
        chosen.append(out[0])
        return out

    monkeypatch.setattr(grouping, "select_strategy", spy)
    # mixed/projection are always eligible; windowed may legitimately fall
    # through when the span check refuses (results must still match)
    for strat, strict in (("mixed", True), ("projection", True),
                          ("windowed", False)):
        chosen.clear()
        monkeypatch.setattr(grouping, "FORCE_STRATEGY", strat)
        got = key(QueryExecutor(segments).run(q))
        assert got == want, f"strategy {strat} diverged"
        assert chosen
        if strict:
            # the force must actually select it, not fall through
            assert all(c == strat for c in chosen), (strat, chosen)
