"""Sharded (shard_map + collectives) execution must be result-identical to
the per-segment host-merged path.

Reference analog: CachingClusteredClientTest.java:171 — scatter-gather over
fake servers asserted against direct execution, no sockets. Here: an 8-way
virtual CPU mesh (conftest) stands in for the pod.
"""
import numpy as np
import pytest

from druid_tpu.engine import QueryExecutor
from druid_tpu.parallel import make_mesh, use_mesh
from druid_tpu.query.aggregators import (CardinalityAggregator, CountAggregator,
                                         DoubleMaxAggregator,
                                         DoubleSumAggregator, FilteredAggregator,
                                         FirstAggregator, LastAggregator,
                                         LongMinAggregator, LongSumAggregator)
from druid_tpu.query.filters import (AndFilter, BoundFilter, InFilter,
                                     NotFilter, SelectorFilter)
from druid_tpu.query.model import (DefaultDimensionSpec, GroupByQuery,
                                   TimeseriesQuery, TopNQuery)
from tests.conftest import WEEK

AGGS = [
    CountAggregator("rows"),
    LongSumAggregator("lsum", "metLong"),
    DoubleSumAggregator("dsum", "metDouble"),
    LongMinAggregator("lmin", "metLong"),
    DoubleMaxAggregator("dmax", "metFloat"),
]


@pytest.fixture(scope="module")
def mesh():
    return make_mesh()


def _run_both(query, segments, mesh):
    plain = QueryExecutor(segments).run(query)
    with use_mesh(mesh):
        sharded = QueryExecutor(segments).run(query)
    return plain, sharded


def _value_close(a, b):
    if isinstance(a, float) or isinstance(b, float):
        return abs(float(a) - float(b)) <= 1e-6 * (1 + abs(float(a)))
    return a == b


def _assert_rows_equal(a, b):
    assert len(a) == len(b)
    for ra, rb in zip(a, b):
        assert ra.keys() == rb.keys()
        for k in ra:
            va, vb = ra[k], rb[k]
            if isinstance(va, dict):
                assert va.keys() == vb.keys()
                for f in va:
                    assert _value_close(va[f], vb[f]), (k, f, va[f], vb[f])
            elif isinstance(va, list):
                assert len(va) == len(vb), k
                for ea, eb in zip(va, vb):
                    assert ea.keys() == eb.keys()
                    for f in ea:
                        assert _value_close(ea[f], eb[f]), (k, f, ea[f], eb[f])
            else:
                assert va == vb, (k, va, vb)


def test_timeseries_sharded_matches(segments, mesh):
    q = TimeseriesQuery.of("test", [WEEK], AGGS, granularity="day",
                           filter=BoundFilter("metLong", lower=10, upper=80,
                                              ordering="numeric"))
    plain, sharded = _run_both(q, segments, mesh)
    _assert_rows_equal(plain, sharded)


def test_timeseries_first_last_sharded(segments, mesh):
    q = TimeseriesQuery.of(
        "test", [WEEK],
        [FirstAggregator("f", "metLong", "long"),
         LastAggregator("l", "metDouble", "double")],
        granularity="day")
    plain, sharded = _run_both(q, segments, mesh)
    _assert_rows_equal(plain, sharded)


def test_timeseries_hll_sharded(segments, mesh):
    q = TimeseriesQuery.of(
        "test", [WEEK],
        [CardinalityAggregator("card", ["dimHi"]), CountAggregator("rows")],
        granularity="all")
    plain, sharded = _run_both(q, segments, mesh)
    _assert_rows_equal(plain, sharded)


def test_topn_sharded_matches(segments, mesh):
    q = TopNQuery.of("test", [WEEK], "dimB", "lsum", 10, AGGS,
                     granularity="all",
                     filter=InFilter("dimA", ["v0", "v1", "v2", "v3"]))
    plain, sharded = _run_both(q, segments, mesh)
    _assert_rows_equal(plain, sharded)


def test_groupby_sharded_matches(segments, mesh):
    q = GroupByQuery.of(
        "test", [WEEK],
        [DefaultDimensionSpec("dimA"), DefaultDimensionSpec("dimB")],
        AGGS + [FilteredAggregator("fsum",
                                   LongSumAggregator("fsum", "metLong"),
                                   SelectorFilter("dimA", "v1"))],
        granularity="day",
        filter=AndFilter([NotFilter(SelectorFilter("dimA", "v9")),
                          BoundFilter("metLong", lower=5, ordering="numeric")]))
    plain, sharded = _run_both(q, segments, mesh)
    # groupBy rows are sorted by the engine's limit path; compare as sets
    key = lambda r: (r["timestamp"], r["event"]["dimA"], r["event"]["dimB"])
    _assert_rows_equal(sorted(plain, key=key), sorted(sharded, key=key))


def test_groupby_uneven_segments(generator, mesh):
    """Segment count not divisible by mesh size → padded empty shards."""
    segs = generator.segments(5, 3_000, WEEK, datasource="uneven")
    q = GroupByQuery.of("uneven", [WEEK], [DefaultDimensionSpec("dimA")],
                        [CountAggregator("rows"),
                         LongSumAggregator("lsum", "metLong")],
                        granularity="all")
    plain, sharded = _run_both(q, segs, mesh)
    key = lambda r: r["event"]["dimA"]
    _assert_rows_equal(sorted(plain, key=key), sorted(sharded, key=key))


def test_heterogeneous_column_presence(mesh):
    """A filter column existing in only SOME segments must not shortcut to a
    whole-query zero (const-false plan on segment 0 only)."""
    from druid_tpu.data.segment import SegmentBuilder
    from druid_tpu.utils.intervals import Interval

    iv = Interval.of("2026-01-01", "2026-01-02")
    b1 = SegmentBuilder("het", iv, partition=0)
    for i in range(100):
        b1.add_row(iv.start + i, {"common": f"c{i % 3}"}, {"m": i})
    b2 = SegmentBuilder("het", iv, partition=1)
    for i in range(100):
        b2.add_row(iv.start + i, {"common": f"c{i % 3}", "extra": f"e{i % 2}"},
                   {"m": i})
    segs = [b1.build(), b2.build()]
    q = TimeseriesQuery.of("het", [iv],
                           [CountAggregator("rows"),
                            LongSumAggregator("ms", "m")],
                           granularity="all",
                           filter=SelectorFilter("extra", "e0"))
    plain, sharded = _run_both(q, segs, mesh)
    assert plain[0]["result"]["rows"] == 50
    _assert_rows_equal(plain, sharded)


def test_differing_dictionaries_fall_back(mesh):
    """Equal-cardinality but different dictionaries must NOT fuse ids in the
    sharded path — values would decode through the wrong dictionary."""
    from druid_tpu.data.segment import SegmentBuilder
    from druid_tpu.utils.intervals import Interval

    iv = Interval.of("2026-01-01", "2026-01-02")
    b1 = SegmentBuilder("dicts", iv, partition=0)
    for i, v in enumerate(["apple", "berry"] * 4):
        b1.add_row(iv.start + i, {"d": v}, {"m": 1})
    b2 = SegmentBuilder("dicts", iv, partition=1)
    for i, v in enumerate(["cherry", "date"] * 4):
        b2.add_row(iv.start + i, {"d": v}, {"m": 1})
    segs = [b1.build(), b2.build()]
    q = GroupByQuery.of("dicts", [iv], [DefaultDimensionSpec("d")],
                        [CountAggregator("rows")], granularity="all")
    plain, sharded = _run_both(q, segs, mesh)
    key = lambda r: r["event"]["d"]
    plain, sharded = sorted(plain, key=key), sorted(sharded, key=key)
    assert [r["event"]["d"] for r in plain] == ["apple", "berry", "cherry",
                                               "date"]
    _assert_rows_equal(plain, sharded)


def test_executor_mesh_arg(segments, mesh):
    q = TimeseriesQuery.of("test", [WEEK], AGGS, granularity="hour")
    plain = QueryExecutor(segments).run(q)
    sharded = QueryExecutor(segments, mesh=mesh).run(q)
    _assert_rows_equal(plain, sharded)


def test_missing_metric_column_in_later_segment(mesh):
    """A metric present only in segment 0 must not crash the sharded path —
    it falls back and matches the plain path (missing aggregates as zero)."""
    from druid_tpu.data.segment import SegmentBuilder
    from druid_tpu.utils.intervals import Interval

    iv = Interval.of("2026-01-01", "2026-01-02")
    b1 = SegmentBuilder("mm", iv, partition=0)
    for i in range(50):
        b1.add_row(iv.start + i, {"d": "x"}, {"m": 1, "m2": i})
    b2 = SegmentBuilder("mm", iv, partition=1)
    for i in range(50):
        b2.add_row(iv.start + i, {"d": "x"}, {"m": 1})
    segs = [b1.build(), b2.build()]
    q = TimeseriesQuery.of("mm", [iv],
                           [CountAggregator("rows"),
                            LongSumAggregator("s", "m2")],
                           granularity="all")
    plain, sharded = _run_both(q, segs, mesh)
    assert plain[0]["result"] == {"rows": 100, "s": 1225}
    _assert_rows_equal(plain, sharded)


def test_rebuilt_segments_not_served_stale(generator, mesh):
    """Segments rebuilt with identical SegmentIds must not hit a stale
    stacked-HBM cache entry (cache is keyed by object identity)."""
    from tests.conftest import TEST_SCHEMA
    from druid_tpu.data.generator import DataGenerator
    from druid_tpu.utils.intervals import Interval

    iv = Interval.of("2026-01-01", "2026-01-05")
    q = TimeseriesQuery.of("test", [iv],
                           [LongSumAggregator("s", "metLong")],
                           granularity="all")
    for seed in (1, 2):
        gen = DataGenerator(TEST_SCHEMA, seed=seed)
        segs = gen.segments(4, 2_000, iv, datasource="test")
        plain, sharded = _run_both(q, segs, mesh)
        _assert_rows_equal(plain, sharded)


def test_two_cardinality_aggs_different_columns(segments, mesh):
    """Different-field HLL aggs must not collide in the jit program caches."""
    for field in ("dimA", "dimB"):
        q = TimeseriesQuery.of(
            "test", [WEEK], [CardinalityAggregator("c", [field])],
            granularity="all")
        plain, sharded = _run_both(q, segments, mesh)
        _assert_rows_equal(plain, sharded)
        # dimA card=10, dimB card=100: estimates must differ between fields
        if field == "dimA":
            assert 8 <= plain[0]["result"]["c"] <= 12
        else:
            assert 80 <= plain[0]["result"]["c"] <= 120
