"""Schema evolution: queries spanning segments whose schemas differ
(columns added/removed over time) must behave like the reference —
missing dimensions group as null, missing metrics aggregate their
identity, filters on absent columns match selector-null semantics
(reference: processing/src/test/.../query/SchemaEvolutionTest.java).
"""
import numpy as np
import pytest

from druid_tpu.data.segment import SegmentBuilder, ValueType
from druid_tpu.engine import QueryExecutor
from druid_tpu.query.aggregators import (CountAggregator, LongSumAggregator)
from druid_tpu.query.filters import BoundFilter, SelectorFilter
from druid_tpu.query.model import (DefaultDimensionSpec, GroupByQuery,
                                   ScanQuery, TimeseriesQuery)
from druid_tpu.utils.intervals import Interval, parse_ts

IV = Interval.of("2026-03-01", "2026-03-03")
T0 = parse_ts("2026-03-01")
DAY = 86_400_000


@pytest.fixture(scope="module")
def evolving():
    """Old segment: dims (page); metrics (hits). New segment adds a
    `country` dim and a `bytes` metric."""
    old = SegmentBuilder("evo", Interval(T0, T0 + DAY), version="v1")
    old.add_columns(
        [T0 + i * 1000 for i in range(6)],
        dims={"page": ["a", "b", "a", "c", "b", "a"]},
        metrics={"hits": np.asarray([1, 2, 3, 4, 5, 6], np.int64)},
        metric_types={"hits": ValueType.LONG})
    new = SegmentBuilder("evo", Interval(T0 + DAY, T0 + 2 * DAY),
                         version="v1")
    new.add_columns(
        [T0 + DAY + i * 1000 for i in range(4)],
        dims={"page": ["a", "d", "d", "b"],
              "country": ["US", "DE", "US", "DE"]},
        metrics={"hits": np.asarray([10, 20, 30, 40], np.int64),
                 "bytes": np.asarray([7, 8, 9, 10], np.int64)},
        metric_types={"hits": ValueType.LONG, "bytes": ValueType.LONG})
    return [old.build(), new.build()]


def test_sum_of_late_metric_counts_only_where_present(evolving):
    rows = QueryExecutor(evolving).run(TimeseriesQuery.of(
        "evo", [IV], [CountAggregator("n"),
                      LongSumAggregator("b", "bytes"),
                      LongSumAggregator("h", "hits")], granularity="all"))
    r = rows[0]["result"]
    assert r["n"] == 10
    assert r["h"] == 21 + 100
    assert r["b"] == 34          # identity (0) contribution from old


def test_group_by_late_dimension_nulls_old_rows(evolving):
    rows = QueryExecutor(evolving).run(GroupByQuery.of(
        "evo", [IV], [DefaultDimensionSpec("country")],
        [CountAggregator("n"), LongSumAggregator("h", "hits")],
        granularity="all"))
    got = {r["event"]["country"]: (r["event"]["n"], r["event"]["h"])
           for r in rows}
    assert got["US"] == (2, 40) and got["DE"] == (2, 60)
    # the 6 old rows land in the null group
    null_keys = [k for k in got if k in (None, "")]
    assert len(null_keys) == 1
    assert got[null_keys[0]] == (6, 21)


def test_filter_on_late_dimension(evolving):
    ex = QueryExecutor(evolving)
    rows = ex.run(TimeseriesQuery.of(
        "evo", [IV], [CountAggregator("n")], granularity="all",
        filter=SelectorFilter("country", "US")))
    assert rows[0]["result"]["n"] == 2
    # selector null matches every old-segment row plus none of the new
    rows = ex.run(TimeseriesQuery.of(
        "evo", [IV], [CountAggregator("n")], granularity="all",
        filter=SelectorFilter("country", None)))
    assert rows[0]["result"]["n"] == 6


def test_numeric_filter_on_late_metric(evolving):
    rows = QueryExecutor(evolving).run(TimeseriesQuery.of(
        "evo", [IV], [CountAggregator("n")], granularity="all",
        filter=BoundFilter("bytes", lower="8", ordering="numeric")))
    assert rows[0]["result"]["n"] == 3          # 8, 9, 10


def test_scan_projects_missing_columns_as_null(evolving):
    batches = QueryExecutor(evolving).run(ScanQuery.of(
        "evo", [IV], columns=("page", "country", "bytes"),
        order="ascending"))
    events = [e for b in batches for e in b["events"]]
    assert len(events) == 10
    old_events = events[:6]
    # pinned: missing columns project as null/absent, NEVER zero-fill
    assert all(e.get("country") is None for e in old_events)
    assert all(e.get("bytes") is None for e in old_events)
    assert events[6]["country"] == "US"


def test_group_by_dim_absent_from_every_queried_segment(evolving):
    rows = QueryExecutor(evolving).run(GroupByQuery.of(
        "evo", [Interval(T0, T0 + DAY)],
        [DefaultDimensionSpec("country")], [CountAggregator("n")],
        granularity="all"))
    # only the old segment participates: all rows in the null group
    assert len(rows) == 1
    assert rows[0]["event"]["n"] == 6


def test_group_by_dim_dropped_in_new_segment():
    """The reverse evolution: a dim the OLD segment has and the NEW one
    dropped — new rows fall in the null group, old groups survive."""
    old = SegmentBuilder("rev", Interval(T0, T0 + DAY), version="v1")
    old.add_columns(
        [T0 + i * 1000 for i in range(4)],
        dims={"page": ["a", "b", "a", "b"],
              "legacy": ["x", "y", "x", "y"]},
        metrics={"hits": np.asarray([1, 2, 3, 4], np.int64)},
        metric_types={"hits": ValueType.LONG})
    new = SegmentBuilder("rev", Interval(T0 + DAY, T0 + 2 * DAY),
                         version="v1")
    new.add_columns(
        [T0 + DAY + i * 1000 for i in range(3)],
        dims={"page": ["a", "b", "a"]},
        metrics={"hits": np.asarray([10, 20, 30], np.int64)},
        metric_types={"hits": ValueType.LONG})
    rows = QueryExecutor([old.build(), new.build()]).run(GroupByQuery.of(
        "rev", [IV], [DefaultDimensionSpec("legacy")],
        [CountAggregator("n"), LongSumAggregator("h", "hits")],
        granularity="all"))
    got = {r["event"]["legacy"]: (r["event"]["n"], r["event"]["h"])
           for r in rows}
    assert got["x"] == (2, 4) and got["y"] == (2, 6)
    null_keys = [k for k in got if k in (None, "")]
    assert len(null_keys) == 1
    assert got[null_keys[0]] == (3, 60)
