"""Incremental scan streaming: lazy engine batches, broker early
termination, chunked NDJSON over HTTP.

Reference: the Sequence result pipeline (java-util/.../guava/Sequence.java)
— every QueryRunner returns a lazy stream; ScanQueryEngine yields
ScanResultValue batches of `batchSize` events and QueryResource writes
them to the response as they arrive.
"""
import json
import urllib.request

import pytest

from druid_tpu.cluster import (Broker, DataNode, InventoryView,
                               descriptor_for)
from druid_tpu.engine import QueryExecutor
from druid_tpu.query.model import ScanQuery, query_from_json
from druid_tpu.utils.intervals import Interval

WEEK = Interval.of("2026-01-01", "2026-01-08")


def test_iter_scan_is_lazy(segments, monkeypatch):
    """Pulling the first batch must not decode later segments."""
    from druid_tpu.engine import engines
    decoded = []
    real = engines._decode_rows

    def spy(seg, row_ids, columns):
        decoded.append(str(seg.id))
        return real(seg, row_ids, columns)

    monkeypatch.setattr(engines, "_decode_rows", spy)
    ex = QueryExecutor(segments)
    q = ScanQuery.of("test", [WEEK], columns=("dimA", "metLong"),
                     order="ascending")
    gen = ex.run_streaming(q)
    next(gen)
    assert len(set(decoded)) == 1
    assert len(segments) > 1


def test_batch_size_bounds_events(segments):
    ex = QueryExecutor(segments)
    q = ScanQuery.of("test", [WEEK], columns=("dimA",))
    q = q.__class__(**{**q.__dict__, "batch_size": 100})
    batches = list(ex.run_streaming(q))
    assert all(len(b["events"]) <= 100 for b in batches)
    total_small = sum(len(b["events"]) for b in batches)
    total_default = sum(
        len(b["events"]) for b in
        ex.run(ScanQuery.of("test", [WEEK], columns=("dimA",))))
    assert total_small == total_default


def test_streaming_matches_materialized(segments):
    ex = QueryExecutor(segments)
    q = ScanQuery.of("test", [WEEK], columns=("dimA", "metLong"),
                     order="ascending", limit=500, offset=37)
    streamed = [e for b in ex.run_streaming(q) for e in b["events"]]
    materialized = [e for b in ex.run(q) for e in b["events"]]
    assert streamed == materialized


def test_scan_batchsize_wire_roundtrip():
    q = query_from_json({
        "queryType": "scan", "dataSource": "x",
        "intervals": [str(WEEK)], "batchSize": 777})
    assert q.batch_size == 777
    assert query_from_json(q.to_json()).batch_size == 777


@pytest.fixture()
def scan_cluster(segments):
    view = InventoryView()
    nodes = [DataNode(f"node{i}") for i in range(2)]
    for n in nodes:
        view.register(n)
    half = len(segments) // 2 or 1
    for i, s in enumerate(segments):
        node = nodes[0] if i < half else nodes[1]
        node.load_segment(s)
        view.announce(node.name, descriptor_for(s))
    return view, nodes, Broker(view)


def test_broker_streaming_limit_short_circuits(scan_cluster, segments,
                                               monkeypatch):
    """A satisfied limit stops the scatter: later segments are never
    queried."""
    view, nodes, broker = scan_cluster
    scattered = []
    real = Broker._scatter

    def spy(self, query, segs, rows_mode):
        scattered.extend(d.id for d in segs)
        return real(self, query, segs, rows_mode)

    monkeypatch.setattr(Broker, "_scatter", spy)
    q = ScanQuery.of("test", [WEEK], columns=("dimA",),
                     order="ascending", limit=10)
    rows = [e for b in broker.run_streaming(q) for e in b["events"]]
    assert len(rows) == 10
    assert len(scattered) == 1          # first segment satisfied the limit
    # and the streamed rows equal the materialized broker run
    want = [e for b in broker.run(q) for e in b["events"]]
    assert rows == want


def test_broker_streaming_full_equality(scan_cluster):
    _, _, broker = scan_cluster
    q = ScanQuery.of("test", [WEEK], columns=("dimA", "metLong"),
                     order="ascending", offset=25)
    streamed = [e for b in broker.run_streaming(q) for e in b["events"]]
    want = [e for b in broker.run(q) for e in b["events"]]
    assert streamed == want


def test_http_ndjson_streaming(segments):
    from druid_tpu.server.http import QueryHttpServer
    from druid_tpu.server.lifecycle import QueryLifecycle
    ex = QueryExecutor(segments)
    srv = QueryHttpServer(QueryLifecycle(ex), port=0).start()
    try:
        payload = {"queryType": "scan", "dataSource": "test",
                   "intervals": [str(WEEK)], "columns": ["dimA"],
                   "batchSize": 1000, "limit": 3500}
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/druid/v2",
            json.dumps(payload).encode(),
            headers={"Content-Type": "application/json",
                     "Accept": "application/x-ndjson"})
        with urllib.request.urlopen(req) as r:
            assert r.headers["Content-Type"] == "application/x-ndjson"
            batches = [json.loads(line) for line in r if line.strip()]
        assert sum(len(b["events"]) for b in batches) == 3500
        assert len(batches) >= 4        # chunked, not one blob
        # plain Accept still gets the one-shot JSON array
        req2 = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/druid/v2",
            json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req2) as r2:
            arr = json.loads(r2.read())
        assert sum(len(b["events"]) for b in arr) == 3500
    finally:
        srv.stop()


def test_abandoned_stream_is_accounted(segments):
    """Client disconnect (generator close) still emits the request log and
    the failure count — streams must not vanish from metrics."""
    from druid_tpu.server.lifecycle import QueryLifecycle, RequestLogger
    results = []
    logger = RequestLogger()
    lc = QueryLifecycle(QueryExecutor(segments), request_logger=logger,
                        on_result=results.append)
    q = ScanQuery.of("test", [WEEK], columns=("dimA",))
    q = q.__class__(**{**q.__dict__, "batch_size": 10})
    gen = lc.run_streaming(q)
    next(gen)
    gen.close()
    assert results == [False]
    assert logger.entries and "abandoned" in str(logger.entries[-1])
    # a fully consumed stream counts success
    rows = list(lc.run_streaming(q))
    assert rows and results == [False, True]


def test_streaming_stamps_query_id_for_cancel(segments):
    """run_streaming must stamp its generated queryId into the query it
    executes, so cancel tokens act on the running scatter."""
    from druid_tpu.server.lifecycle import QueryLifecycle
    from druid_tpu.server.querymanager import QueryManager
    seen = {}

    class Probe:
        def run_streaming(self, query):
            seen["qid"] = query.context_map.get("queryId")
            yield {"events": []}

        def run(self, query):
            return []

    qm = QueryManager()
    lc = QueryLifecycle(Probe(), query_manager=qm)
    list(lc.run_streaming(ScanQuery.of("test", [WEEK])))
    assert seen["qid"]
