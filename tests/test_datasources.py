"""Polymorphic dataSources: union + query (subquery) — reference:
query/UnionDataSource, QueryDataSource + GroupByStrategyV2
.processSubqueryResult; UnionQueryRunner; CalciteQueryTest nested
groupBys."""
import numpy as np
import pytest

from druid_tpu.cluster import Broker, DataNode, InventoryView, descriptor_for
from druid_tpu.engine import QueryExecutor
from druid_tpu.query.aggregators import (CountAggregator, DoubleSumAggregator,
                                         LongSumAggregator)
from druid_tpu.query.model import (DefaultDimensionSpec, GroupByQuery,
                                   TimeseriesQuery, query_from_json)
from druid_tpu.utils.intervals import Interval
from tests.conftest import WEEK, rows_as_frame


def test_union_datasource(generator):
    a = generator.segment(5_000, Interval.of("2026-01-01", "2026-01-02"),
                          datasource="ds_a")
    b = generator.segment(7_000, Interval.of("2026-01-01", "2026-01-02"),
                          datasource="ds_b")
    ex = QueryExecutor([a, b])
    rows = ex.run_json({
        "queryType": "timeseries",
        "dataSource": {"type": "union", "dataSources": ["ds_a", "ds_b"]},
        "intervals": [str(WEEK)], "granularity": "all",
        "aggregations": [{"type": "count", "name": "n"}]})
    assert rows[0]["result"]["n"] == 12_000


def test_subquery_groupby(segment):
    """Outer groupBy over inner groupBy: count distinct dimB per dimA by
    re-grouping inner (dimA, dimB) rows."""
    ex = QueryExecutor([segment])
    frame = rows_as_frame(segment)
    inner = GroupByQuery.of(
        "test", [WEEK],
        [DefaultDimensionSpec("dimA"), DefaultDimensionSpec("dimB")],
        [CountAggregator("cnt")], granularity="all")
    outer_json = {
        "queryType": "groupBy",
        "dataSource": {"type": "query", "query": inner.to_json()},
        "intervals": [str(WEEK)], "granularity": "all",
        "dimensions": ["dimA"],
        "aggregations": [{"type": "count", "name": "pairs"},
                         {"type": "longSum", "name": "rows",
                          "fieldName": "cnt"}]}
    rows = ex.run_json(outer_json)
    got = {r["event"]["dimA"]: (r["event"]["pairs"], r["event"]["rows"])
           for r in rows}
    for v in sorted(set(frame["dimA"])):
        sel = frame["dimA"] == v
        want_pairs = len(set(frame["dimB"][sel]))
        assert got[v] == (want_pairs, int(sel.sum()))


def test_subquery_serde_round_trip(segment):
    inner = GroupByQuery.of("test", [WEEK], [DefaultDimensionSpec("dimA")],
                            [CountAggregator("c")])
    j = {"queryType": "timeseries",
         "dataSource": {"type": "query", "query": inner.to_json()},
         "intervals": [str(WEEK)], "granularity": "all",
         "aggregations": [{"type": "longSum", "name": "s",
                           "fieldName": "c"}]}
    q = query_from_json(j)
    assert q.inner_query is not None
    j2 = q.to_json()
    assert j2["dataSource"]["type"] == "query"
    assert query_from_json(j2).to_json() == j2


def test_subquery_requires_groupby(segment):
    ex = QueryExecutor([segment])
    ts = TimeseriesQuery.of("test", [WEEK], [CountAggregator("c")])
    with pytest.raises(ValueError):
        ex.run_json({
            "queryType": "timeseries",
            "dataSource": {"type": "query", "query": ts.to_json()},
            "intervals": [str(WEEK)], "granularity": "all",
            "aggregations": [{"type": "count", "name": "n"}]})


def test_subquery_and_union_over_broker(segments, generator):
    view = InventoryView()
    node = DataNode("n0")
    view.register(node)
    for s in segments:
        node.load_segment(s)
        view.announce("n0", descriptor_for(s))
    other = generator.segment(3_000, Interval.of("2026-01-01", "2026-01-02"),
                              datasource="other")
    node.load_segment(other)
    view.announce("n0", descriptor_for(other))
    broker = Broker(view)

    rows = broker.run_json({
        "queryType": "timeseries",
        "dataSource": {"type": "union", "dataSources": ["test", "other"]},
        "intervals": [str(WEEK)], "granularity": "all",
        "aggregations": [{"type": "count", "name": "n"}]})
    total = sum(s.n_rows for s in segments) + other.n_rows
    assert rows[0]["result"]["n"] == total

    inner = GroupByQuery.of("test", [WEEK], [DefaultDimensionSpec("dimA")],
                            [LongSumAggregator("s", "metLong")])
    rows = broker.run_json({
        "queryType": "timeseries",
        "dataSource": {"type": "query", "query": inner.to_json()},
        "intervals": [str(WEEK)], "granularity": "all",
        "aggregations": [{"type": "count", "name": "groups"},
                         {"type": "doubleSum", "name": "total",
                          "fieldName": "s"}]})
    local = QueryExecutor(segments)
    want_groups = len(local.run(inner))
    frames = [rows_as_frame(s) for s in segments]
    want_total = float(sum(int(f["metLong"].sum()) for f in frames))
    assert rows[0]["result"]["groups"] == want_groups
    assert rows[0]["result"]["total"] == pytest.approx(want_total)
