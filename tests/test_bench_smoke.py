"""Bench smoke gate: `python bench.py` must exit 0 on CPU and print ONE
valid JSON line with the headline + batch-comparison fields.

The benchmark zeroing a whole trajectory because of an environment wedge
(every BENCH_r0*.json rc=1, "backend init hung") is exactly the silent
breakage this tier-1 test exists to catch: tiny row counts keep it fast,
the CPU pin keeps it hermetic, and the assertion is on CONTRACT (rc=0,
parseable one-line JSON, fields present) — not on throughput, which this
shared CI hardware cannot promise."""
import json
import os
import subprocess
import sys
from pathlib import Path

import pytest

REPO_ROOT = Path(__file__).resolve().parent.parent

BENCH_ENV = {
    "DRUID_TPU_BENCH_PLATFORM": "cpu",
    "DRUID_TPU_BENCH_ROWS": "40000",
    "DRUID_TPU_BENCH_SEGMENTS": "2",
    "DRUID_TPU_BENCH_ITERS": "1",
    "DRUID_TPU_BENCH_BATCH_SEGMENTS": "4",
    "DRUID_TPU_BENCH_BATCH_ROWS": "1024",
    "DRUID_TPU_BENCH_INIT_TIMEOUT": "120",
    "DRUID_TPU_BENCH_CASCADE_SEGMENTS": "4",
    "DRUID_TPU_BENCH_CASCADE_ROWS": "2048",
    "DRUID_TPU_BENCH_SEGIO_ROWS": "4096",
    "DRUID_TPU_BENCH_CLIENTS": "4",
    "DRUID_TPU_BENCH_CLIENT_QUERIES": "3",
    "DRUID_TPU_BENCH_SCHED_ROWS": "1024",
    "DRUID_TPU_BENCH_SOAK": "2",
    "DRUID_TPU_BENCH_STANDING_ROWS": "3000",
    "DRUID_TPU_BENCH_STANDING_WAVES": "3",
    "DRUID_TPU_BENCH_STANDING_SUBS": "16",
}


def _run_bench(extra_env=None):
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)       # the bench must pin its own
    # conftest forces an 8-virtual-device CPU fleet for the mesh tests;
    # inheriting it would make the bench subprocess run every program on a
    # 1/8-size device and blow the smoke budget
    env.pop("XLA_FLAGS", None)
    env.update(BENCH_ENV)
    env.update(extra_env or {})
    return subprocess.run(
        [sys.executable, str(REPO_ROOT / "bench.py")],
        cwd=REPO_ROOT, env=env, capture_output=True, text=True, timeout=420)


def test_bench_exits_zero_with_one_json_line():
    proc = _run_bench()
    assert proc.returncode == 0, (
        f"bench.py rc={proc.returncode}\nstdout:{proc.stdout}\n"
        f"stderr:{proc.stderr[-2000:]}")
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    assert len(lines) == 1, f"expected ONE stdout JSON line, got {lines!r}"
    out = json.loads(lines[0])
    assert out["metric"] == "groupby+topn_scan_rate"
    assert out["value"] > 0 and "error" not in out
    # the batch-comparison fields the perf gate reads
    assert out["per_segment_rate"] > 0
    assert out["batched_rate"] > 0
    assert out["batch_speedup"] > 0
    assert out["batch_segments"] == 4
    # the sharded-mesh comparison (contract only: rates positive, both
    # merge tails timed, and the stack really held compressed bytes —
    # the bench env strips XLA_FLAGS so this usually runs on a 1-device
    # mesh; the ≥8-way ordering is asserted on real hardware and parity
    # in tests/test_sharded_parity.py)
    assert out["sharded_decoded_rate"] > 0
    assert out["sharded_packed_rate"] > 0
    assert out["sharded_merge_host_ms"] > 0
    assert out["sharded_merge_device_ms"] > 0
    assert out["sharded_devices"] >= 1
    assert out["sharded_stack_ratio"] > 1.0
    # the compressed-domain cold-miss comparison (contract only: rates
    # positive and the pool really held compressed bytes — the ≥3x
    # capacity bar lives in test_packed.py where the shape is controlled)
    assert out["packed_rate"] > 0
    assert out["decoded_rate"] > 0
    assert out["pack_ratio"] > 1.0
    # the device-bitmap filter comparison (contract only: rates positive
    # and the warm run really hit resident filter results — throughput
    # ordering is asserted on real hardware, not shared CI)
    assert out["filter_host_rate"] > 0
    assert out["filter_device_rate"] > 0
    assert out["filter_speedup"] > 0
    assert out["filter_cache_hit_rate"] > 0
    # the megakernel comparison. The HARD contract is the dispatch count:
    # a cold fused query is exactly ONE device dispatch, the staged path
    # pays the bitmap fill wave too. The rate gate is a noise floor only:
    # on shared-CI CPU the fill dispatch costs ~1% of a cold iteration, so
    # strict fused ≥ staged ordering is within timing noise — the ordering
    # is asserted on real hardware (BENCH_r*), the same discipline as the
    # filter-bench fields above.
    assert out["fused_rate"] > 0
    assert out["staged_rate"] > 0
    assert out["fused_rate"] >= 0.9 * out["staged_rate"]
    assert out["dispatch_count_fused"] == 1
    assert out["dispatch_count_staged"] >= 2
    assert out["donated_tick_rate"] > 0
    # the cascaded-encodings comparison (contract only: rates positive,
    # the pool really held cascade-encoded bytes, and the code-domain
    # run-space path really executed — throughput ordering is asserted on
    # real hardware, the filter-bench discipline)
    assert out["rle_rate"] > 0
    assert out["packed_only_rate"] > 0
    assert out["cascade_ratio"] > 1.0
    assert out["code_domain_rate"] > 0
    # the segment-format V1-vs-V2 comparison (contract only: rates
    # positive; disk_ratio > 1 needs rows where the fixed per-part
    # overheads amortize, which the smoke row count deliberately is not —
    # the size win is asserted in test_format_v2.py on a controlled
    # shape. The wire ordering IS hard: compressed partials must be
    # strictly smaller on this repeated-states shape at any size.)
    assert out["v1_load_rate"] > 0
    assert out["v2_load_rate"] > 0
    assert out["disk_ratio"] > 0
    assert 0 < out["wire_bytes_v2"] < out["wire_bytes_v1"]
    # the non-default-register sketch shape (log2m=12 rider)
    assert out["hll_log2m12_rate"] > 0
    # the qtrace-overhead fields tracked across BENCH_r* runs
    assert out["traced_rate"] > 0
    assert out["untraced_rate"] > 0
    # the concurrent-client scheduler comparison (contract only: this
    # shared CI hardware cannot promise the ≥1.3x the real bench shows)
    assert out["sched_clients"] == 4
    assert out["sched_off_rate"] > 0
    assert out["sched_on_rate"] > 0
    assert out["sched_speedup"] > 0
    for mode in ("off", "on"):
        assert out[f"sched_{mode}_p50_ms"] > 0
        assert out[f"sched_{mode}_p99_ms"] >= out[f"sched_{mode}_p50_ms"]
    # the standing-query comparison (contract only: rates positive, the
    # hub really deduped N subscribers onto ONE standing program; the
    # standing-vs-rescan throughput ordering is asserted on real hardware
    # like the other comparisons — shared CI cannot promise it)
    assert out["standing_rate"] > 0
    assert out["rescan_rate"] > 0
    assert out["standing_speedup"] > 0
    assert out["standing_fanout_subs"] == 16
    assert out["standing_fanout_hub_ms"] > 0
    assert out["standing_fanout_independent_ms"] > 0
    assert out["standing_fanout_speedup"] > 0
    assert out["standing_programs"] == 1
    # the soak-mode drift fields (contract: present and near-zero on the
    # countable axes; rss is allocator-noisy, so presence only)
    assert out["soak_waves"] == 2
    assert abs(out["soak_thread_drift"]) <= 1
    assert abs(out["soak_fd_drift"]) <= 4
    assert isinstance(out["soak_rss_drift_kb"], int)


def test_bench_falls_back_to_cpu_on_bad_backend():
    """An unavailable accelerator backend must not zero the run: the bench
    re-execs once on the CPU backend and still produces numbers."""
    proc = _run_bench({"DRUID_TPU_BENCH_PLATFORM": "nosuchplatform"})
    assert proc.returncode == 0, (
        f"rc={proc.returncode}\nstderr:{proc.stderr[-2000:]}")
    lines = [ln for ln in proc.stdout.splitlines() if ln.strip()]
    out = json.loads(lines[-1])
    assert out["value"] > 0 and "error" not in out
    assert "retrying once on the cpu backend" in proc.stderr
