"""Tier-1 druidlint gate: the shipped tree must be clean of new findings,
the analyzer must stay fast, and each rule must actually fire when its
invariant is violated (a gate whose rules never fire is no gate).

Reference for the pattern: the checkstyle/forbidden-apis gates the Java
reference runs in its build — mechanical invariants, not review memory.
"""
import json
import subprocess
import sys
import time
from pathlib import Path

import pytest

REPO_ROOT = Path(__file__).resolve().parent.parent

sys.path.insert(0, str(REPO_ROOT))

from tools.druidlint import (lint_paths, load_baseline,  # noqa: E402
                             load_config, registered_rules)
from tools.druidlint.core import split_by_baseline  # noqa: E402


def test_tree_is_clean_and_fast():
    """`python -m tools.druidlint --all --fail-on-new` — the UNIFIED gate:
    all seven analyzer families (druidlint/tracecheck/raceguard/leakguard/
    keyguard/stallguard/donorguard) in one process over the shared
    program/cache pass
    — exits 0 on the
    shipped tree under a single wall-clock budget. The first run may be
    cold (fresh checkout: no .druidlint-cache.json — the whole-program
    index alone costs several seconds); the budget is enforced on the
    mtime-cached scan, which is what every scan after the first is."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.druidlint", "--all", "--fail-on-new"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, (
        f"druidlint found new violations:\n{proc.stdout}{proc.stderr}")
    t0 = time.monotonic()
    proc = subprocess.run(
        [sys.executable, "-m", "tools.druidlint", "--all", "--fail-on-new",
         "--json"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120)
    elapsed = time.monotonic() - t0
    assert proc.returncode == 0, (
        f"druidlint found new violations:\n{proc.stdout}{proc.stderr}")
    assert elapsed < 10.0, (
        f"unified gate took {elapsed:.1f}s (budget 10s for all seven "
        f"families together)")
    payload = json.loads(proc.stdout)
    assert set(payload["families"]) == {"druidlint", "tracecheck",
                                        "raceguard", "leakguard",
                                        "keyguard", "stallguard",
                                        "donorguard"}
    for name, info in payload["families"].items():
        assert info["rules"] > 0, f"family {name} registered no rules"
        assert info["findings"] == 0


def test_all_rejects_only():
    """--all is the whole gate by definition; a rule subset would verify
    less than the unified contract claims."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.druidlint", "--all",
         "--only", "swallowed-exception"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=60)
    assert proc.returncode == 2
    assert "--only" in proc.stderr


def test_changed_mode_is_guarded_and_clean():
    """--changed (the pre-commit gate) exits clean on the shipped tree,
    and refuses the combinations that would under-scan: rewriting the
    baseline from a diff-scoped scan would drop every grandfathered
    finding the diff didn't re-find, and explicit paths contradict a
    git-derived scope."""
    proc = subprocess.run(
        [sys.executable, "-m", "tools.druidlint", "--changed",
         "--update-baseline"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=60)
    assert proc.returncode == 2
    assert "--changed" in proc.stderr
    proc = subprocess.run(
        [sys.executable, "-m", "tools.druidlint", "--changed",
         "druid_tpu"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=60)
    assert proc.returncode == 2
    assert "explicit paths" in proc.stderr
    proc = subprocess.run(
        [sys.executable, "-m", "tools.druidlint", "--changed",
         "--fail-on-new"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, (
        f"--changed found new violations:\n{proc.stdout}{proc.stderr}")
    assert "--changed" in proc.stdout


def test_baseline_is_near_empty():
    """Grandfathered findings must stay below 10 — the gate is strict."""
    baseline = load_baseline(REPO_ROOT / "tools/druidlint/baseline.json")
    assert len(baseline) < 10, (
        f"baseline grew to {len(baseline)} findings — fix them instead of "
        f"grandfathering")


def test_baseline_has_no_stale_entries():
    """Every baseline entry must still correspond to a real finding,
    else fixed code leaves dead grandfather slots a regression could
    silently reclaim."""
    config = load_config(REPO_ROOT)
    findings = lint_paths(REPO_ROOT, config)
    baseline = load_baseline(REPO_ROOT / config.baseline)
    _, _, stale = split_by_baseline(findings, baseline)
    assert not stale, f"stale baseline entries: {stale}"


# one canonical violation per rule: the gate must fail when any of these
# patterns lands in the tree
VIOLATIONS = {
    "unfenced-metadata-write": (
        "druid_tpu/cluster/coordinator.py",
        "def duty(self):\n    self.metadata.publish_segments(descs)\n"),
    "jit-in-hot-path": (
        "druid_tpu/engine/hot.py",
        "import jax\n"
        "def per_segment(arrays):\n"
        "    return jax.jit(lambda x: x + 1)(arrays)\n"),
    "host-device-sync": (
        "druid_tpu/engine/hot.py",
        "import jax\n"
        "def kernel(x):\n"
        "    return float(x.sum())\n"
        "fn = jax.jit(kernel)\n"),
    "no-executable-deserialization": (
        "druid_tpu/cluster/wire.py",
        "import pickle\n"
        "def decode(b):\n"
        "    return pickle.loads(b)\n"),
    "wire-decoded-rows": (
        "druid_tpu/cluster/wire.py",
        "import numpy as np\n"
        "def enc(col):\n"
        "    return np.asarray(col.values).tolist()\n"),
    "swallowed-exception": (
        "druid_tpu/cluster/anything.py",
        "def f():\n"
        "    try:\n"
        "        g()\n"
        "    except Exception:\n"
        "        pass\n"),
    "lock-scope": (
        "druid_tpu/cluster/anything.py",
        "import time\n"
        "def f(self):\n"
        "    with self._lock:\n"
        "        time.sleep(1)\n"),
    "metric-name": (
        "druid_tpu/cluster/anything.py",
        "def f(emitter):\n"
        "    emitter.metric(\"query/typo/time\", 1.0)\n"),
    "unbounded-retry": (
        "druid_tpu/cluster/anything.py",
        "def fetch(self):\n"
        "    while True:\n"
        "        try:\n"
        "            return self._get()\n"
        "        except ConnectionError:\n"
        "            continue\n"),
    # ---- tracecheck rules ----
    "pallas-tile-shape": (
        "druid_tpu/engine/pallas_agg.py",
        "from jax.experimental import pallas as pl\n"
        "grid_spec = pl.GridSpec(\n"
        "    grid=(8,),\n"
        "    in_specs=[pl.BlockSpec((8, 64), lambda i: (i, 0))],\n"
        ")\n"),
    "pallas-accum-dtype": (
        "druid_tpu/engine/pallas_agg.py",
        "import jax.numpy as jnp\n"
        "ident = jnp.float32(2**31 - 1)\n"),
    "vmem-budget": (
        "druid_tpu/engine/pallas_agg.py",
        "from jax.experimental import pallas as pl\n"
        "grid_spec = pl.GridSpec(\n"
        "    grid=(8,),\n"
        "    in_specs=[pl.BlockSpec((32768, 128), lambda i: (i, 0))],\n"
        ")\n"),
    "x64-dtype": (
        "druid_tpu/engine/hot.py",
        "import jax\n"
        "import jax.numpy as jnp\n"
        "def f(x):\n"
        "    return x.astype(jnp.int64)\n"
        "fn = jax.jit(f)\n"),
    "agg-contract": (
        "druid_tpu/ext/badkernel.py",
        "from druid_tpu.engine.kernels import AggKernel\n"
        "class BadKernel(AggKernel):\n"        # fold default, no
        "    def signature(self):\n"           # device_combine
        "        return \"bad\"\n"
        "    def update(self, cols, mask, keys, num, aux):\n"
        "        return None\n"
        "    def combine(self, a, b):\n"
        "        return a\n"
        "    def empty_state(self, n):\n"
        "        return None\n"),
    "preferred-element-type": (
        "druid_tpu/engine/hot.py",
        "from jax import lax\n"
        "def f(a, b):\n"
        "    return lax.dot_general(a, b, (((1,), (0,)), ((), ())))\n"),
    "spec-literal-outside-layout": (
        "druid_tpu/parallel/distributed.py",
        "from jax.sharding import PartitionSpec\n"
        "SPEC = PartitionSpec('seg')\n"),
    "shard-spec": (
        "druid_tpu/parallel/speclayout.py",
        "from jax import shard_map\n"
        "from jax.sharding import PartitionSpec as P\n"
        "CACHE = {}\n"
        "def body(a, b):\n"
        "    return (a,)\n"
        "def build(mesh):\n"
        "    axis = mesh.axis_names[0]\n"
        "    CACHE['f'] = shard_map(body, mesh=mesh, in_specs=(P(axis),),\n"
        "                           out_specs=(P(),))\n"
        "    return CACHE['f']\n"),
    # ---- raceguard rules ----
    "unguarded-shared-write": (
        "druid_tpu/cluster/racy.py",
        "import threading\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.n = 0\n"
        "    def bump(self):\n"
        "        with self._lock:\n"
        "            self.n += 1\n"
        "    def reset(self):\n"
        "        self.n = 0\n"),
    "lock-order-cycle": (
        "druid_tpu/cluster/deadlocky.py",
        "import threading\n"
        "class A:\n"
        "    def __init__(self, b: 'B'):\n"
        "        self._lock = threading.Lock()\n"
        "        self.b = b\n"
        "    def cross(self):\n"
        "        with self._lock:\n"
        "            self.b.poke()\n"
        "    def poke(self):\n"
        "        with self._lock:\n"
        "            pass\n"
        "class B:\n"
        "    def __init__(self, a: A):\n"
        "        self._lock = threading.Lock()\n"
        "        self.a = a\n"
        "    def cross(self):\n"
        "        with self._lock:\n"
        "            self.a.poke()\n"
        "    def poke(self):\n"
        "        with self._lock:\n"
        "            pass\n"),
    "guard-consistency": (
        "druid_tpu/cluster/leaky.py",
        "import threading\n"
        "class R:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.entries = {}\n"
        "    def add(self, k):\n"
        "        with self._lock:\n"
        "            self.entries[k] = 1\n"
        "    def peek(self):\n"
        "        return len(self.entries)\n"
        "    def start(self):\n"
        "        threading.Thread(target=self.add).start()\n"
        "        threading.Thread(target=self.peek).start()\n"),
    "lock-in-traced": (
        "druid_tpu/engine/hot.py",
        "import threading\n"
        "import jax\n"
        "_lock = threading.Lock()\n"
        "def kernel(x):\n"
        "    with _lock:\n"
        "        return x + 1\n"
        "fn = jax.jit(kernel)\n"),
    # ---- leakguard rules ----
    "unjoined-thread": (
        "druid_tpu/cluster/leakything.py",
        "import threading\n"
        "class Pump:\n"
        "    def __init__(self):\n"
        "        self._t = threading.Thread(target=self._run)\n"
        "        self._t.start()\n"
        "    def _run(self):\n"
        "        pass\n"
        "    def stop(self):\n"
        "        pass\n"),
    "unreleased-resource": (
        "druid_tpu/cluster/leakything.py",
        "from concurrent.futures import ThreadPoolExecutor\n"
        "class Fan:\n"
        "    def __init__(self):\n"
        "        self._pool = ThreadPoolExecutor(4)\n"
        "    def stop(self):\n"
        "        pass\n"),
    "leak-on-error-path": (
        "druid_tpu/storage/leakything.py",
        "import json\n"
        "def load(path, meta):\n"
        "    fh = open(path)\n"
        "    parsed = json.loads(meta)\n"
        "    return fh, parsed\n"),
    "finalizer-unsafe": (
        "druid_tpu/data/leakything.py",
        "import threading\n"
        "import weakref\n"
        "class Pool:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def _purge(self):\n"
        "        with self._lock:\n"
        "            pass\n"
        "    def track(self, obj):\n"
        "        weakref.finalize(obj, self._purge)\n"),
    "stop-start-pairing": (
        "druid_tpu/server/leakything.py",
        "class Lifecycle:\n"
        "    def __init__(self):\n"
        "        self.on_result = None\n"
        "class Chainer:\n"
        "    def __init__(self, life: Lifecycle):\n"
        "        self.life = life\n"
        "    def start(self):\n"
        "        self.life.on_result = self._cb\n"
        "    def _cb(self):\n"
        "        pass\n"
        "    def stop(self):\n"
        "        pass\n"),
    # ---- keyguard rules (entries may list EXTRA files: the env-flag
    # rules read the on-disk flags catalog next to the violating module)
    "unkeyed-trace-input": (
        "druid_tpu/engine/cachey.py",
        "_JIT_CACHE = {}\n"
        "def run(spec, extra):\n"
        "    sig = f's={spec}'\n"
        "    fn = _JIT_CACHE.get(sig)\n"
        "    if fn is None:\n"
        "        fn = _build(spec, extra)\n"
        "        _JIT_CACHE[sig] = fn\n"
        "    return fn\n"),
    "impure-eligibility": (
        # the default config pins standing.py::check_eligible
        "druid_tpu/engine/standing.py",
        "import os\n"
        "def check_eligible(query):\n"
        "    return os.environ.get('DRUID_TPU_STANDING') != '0'\n"),
    "env-flag-latch": (
        "druid_tpu/engine/flaggy.py",
        "import os\n"
        "def plan(col):\n"
        "    return os.environ.get('DRUID_TPU_LATCHY') == '1'\n",
        ("druid_tpu/config/flags.py",
         "class Flag:\n"
         "    def __init__(self, default='', semantics='latch', doc='',\n"
         "                 key_member=False):\n"
         "        pass\n"
         "FLAGS = {\n"
         "    'DRUID_TPU_LATCHY': Flag(default='', semantics='latch',\n"
         "                             doc='x'),\n"
         "}\n")),
    "flag-name": (
        # no catalog file in the synthetic root: every read is undeclared
        "druid_tpu/engine/flaggy.py",
        "import os\n"
        "def plan(col):\n"
        "    return os.environ.get('DRUID_TPU_NO_SUCH_FLAG') == '1'\n"),
    # ---- stallguard rules (request-path classification in the synthetic
    # root comes from the built-in HTTP-handler heuristic)
    "unbounded-blocking-call": (
        "druid_tpu/server/parky.py",
        "from http.server import BaseHTTPRequestHandler\n"
        "class H(BaseHTTPRequestHandler):\n"
        "    def do_GET(self):\n"
        "        self.server.ready.wait()\n"),
    "deadline-not-propagated": (
        "druid_tpu/server/droppy.py",
        "def fetch(ev, timeout):\n"
        "    ev.wait()\n"),
    "unclamped-external-timeout": (
        "druid_tpu/server/clampy.py",
        "from http.server import BaseHTTPRequestHandler\n"
        "class H(BaseHTTPRequestHandler):\n"
        "    def do_GET(self):\n"
        "        self._poll(float(self.headers['x-t']))\n"
        "    def _poll(self, timeout_s):\n"
        "        self.cond.wait(timeout_s)\n"),
    "sleep-on-request-path": (
        "druid_tpu/server/sleepy.py",
        "import time\n"
        "from http.server import BaseHTTPRequestHandler\n"
        "class H(BaseHTTPRequestHandler):\n"
        "    def do_GET(self):\n"
        "        time.sleep(1.0)\n"),
    "stop-signal-coverage": (
        "druid_tpu/server/spinny.py",
        "import threading\n"
        "class Pump:\n"
        "    def start(self):\n"
        "        self._t = threading.Thread(target=self._loop)\n"
        "        self._t.start()\n"
        "    def _loop(self):\n"
        "        while True:\n"
        "            self._step()\n"
        "    def _step(self):\n"
        "        pass\n"),
    # ---- donorguard rules ----
    "read-after-donate": (
        "druid_tpu/engine/donatey.py",
        "import jax\n"
        "def build():\n"
        "    def fn(arrays, aux, carries):\n"
        "        return carries\n"
        "    return jax.jit(fn, donate_argnums=(2,))\n"
        "def run(pool, arrays, aux):\n"
        "    fn = build()\n"
        "    carried = pool.take('o', ('k',))\n"
        "    out = fn(arrays, aux, carried)\n"
        "    return out, sum(a.nbytes for a in carried)\n"),
    "donate-cached-entry": (
        "druid_tpu/engine/donatey.py",
        "import jax\n"
        "def build():\n"
        "    def fn(arrays, aux, carries):\n"
        "        return carries\n"
        "    return jax.jit(fn, donate_argnums=(2,))\n"
        "def run(pool, arrays, aux, make):\n"
        "    fn = build()\n"
        "    carried = pool.get_or_build('o', ('k',), make)\n"
        "    return fn(arrays, aux, carried)\n"),
    "take-without-repark": (
        "druid_tpu/engine/donatey.py",
        "def run(pool, log):\n"
        "    carried = pool.take('o', ('k',))\n"
        "    log(carried)\n"),
    "donate-platform-gate": (
        "druid_tpu/engine/donatey.py",
        "import jax\n"
        "def enabled():\n"
        "    return jax.default_backend() in ('tpu', 'gpu')\n"),
    "carry-grid-init": (
        "druid_tpu/engine/donatey.py",
        "import jax\n"
        "from jax.experimental import pallas as pl\n"
        "def agg(arrays):\n"
        "    def kernel(ref):\n"
        "        ref[0] = ref[0] + 1\n"
        "    return pl.pallas_call(kernel)(arrays)\n"
        "def build():\n"
        "    return jax.jit(agg, donate_argnums=(0,))\n"),
}


@pytest.mark.parametrize("rule_name", sorted(VIOLATIONS))
def test_each_rule_fails_a_synthetic_violation(rule_name, tmp_path):
    """Introducing a violation of each rule makes the CLI exit non-zero."""
    rel, source, *extra = VIOLATIONS[rule_name]
    for erel, esrc in ((rel, source), *extra):
        target = tmp_path / erel
        target.parent.mkdir(parents=True, exist_ok=True)
        target.write_text(esrc)
    empty_baseline = tmp_path / "baseline.json"
    empty_baseline.write_text(json.dumps({"version": 1, "findings": []}))
    proc = subprocess.run(
        [sys.executable, "-m", "tools.druidlint", "--root", str(tmp_path),
         "--baseline", str(empty_baseline), "--fail-on-new", "--json",
         "druid_tpu"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=60)
    assert proc.returncode == 1, (
        f"{rule_name}: expected failure, got rc={proc.returncode}\n"
        f"{proc.stdout}{proc.stderr}")
    rules_hit = {f["rule"] for f in json.loads(proc.stdout)["findings"]}
    assert rule_name in rules_hit, (
        f"expected {rule_name} among {rules_hit}")


def test_rule_registry_is_complete():
    """All project rules (nine control-plane incl. metric-name,
    wire-decoded-rows and flag-name + seven tracecheck + four raceguard
    + five leakguard + three keyguard + five stallguard + five
    donorguard) plus the unused-suppression audit are registered with
    severities."""
    rules = registered_rules()
    assert set(VIOLATIONS) <= set(rules)
    assert "unused-suppression" in rules
    for r in rules.values():
        assert r.severity in ("error", "warning")


def test_pycache_artifacts_are_ignored(tmp_path):
    """A stale module under __pycache__ (or a .pyc) never produces
    findings — scans must reflect the live tree only."""
    bad = ("def f():\n"
           "    try:\n"
           "        g()\n"
           "    except Exception:\n"
           "        pass\n")
    cachedir = tmp_path / "druid_tpu" / "__pycache__"
    cachedir.mkdir(parents=True)
    (cachedir / "stale.py").write_text(bad)
    (tmp_path / "druid_tpu" / "stale.cpython-310.pyc").write_text(bad)
    config = load_config(tmp_path)
    findings = lint_paths(tmp_path, config, ["druid_tpu"])
    assert findings == []
