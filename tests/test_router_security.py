"""Router tier selection/forwarding, security SPI chain, and cluster-wide
lookup management (reference: AsyncQueryForwardingServlet,
TieredBrokerHostSelector, Authenticator/Authorizer/Escalator,
LookupCoordinatorManager)."""
import base64
import json
import urllib.error
import urllib.request

import pytest

from druid_tpu.cluster import MetadataStore
from druid_tpu.cluster.lookups import (LookupCoordinatorManager,
                                       LookupNodeSync)
from druid_tpu.engine import QueryExecutor
from druid_tpu.query.lookup import LookupReferencesManager
from druid_tpu.server.http import QueryHttpServer
from druid_tpu.server.lifecycle import QueryLifecycle, Unauthorized
from druid_tpu.server.router import (Router, RouterHttpServer,
                                     TieredBrokerSelector)
from druid_tpu.server.security import (AllowAllAuthorizer, AuthChain,
                                       BasicHTTPAuthenticator, Escalator,
                                       Permission, READ, RoleBasedAuthorizer,
                                       authorizer_for_query)
from druid_tpu.utils.intervals import Interval

TS_Q = {"queryType": "timeseries", "dataSource": "test",
        "intervals": ["2026-01-01/2026-01-08"], "granularity": "all",
        "aggregations": [{"type": "count", "name": "n"}]}


class FakeBroker:
    def __init__(self, name):
        self.name = name
        self.calls = []

    def run_json(self, payload):
        self.calls.append(payload)
        return [{"broker": self.name}]


# ---------------------------------------------------------------------------
# Router
# ---------------------------------------------------------------------------

def test_router_manual_and_default_tier():
    hot, cold = FakeBroker("hot"), FakeBroker("cold")
    sel = TieredBrokerSelector({"hot": [hot], "_default": [cold]},
                               default_tier="_default")
    router = Router(sel)
    assert router.run_json(TS_Q) == [{"broker": "cold"}]
    q2 = {**TS_Q, "context": {"brokerService": "hot"}}
    assert router.run_json(q2) == [{"broker": "hot"}]


def test_router_priority_tier():
    hot, low = FakeBroker("hot"), FakeBroker("low")
    sel = TieredBrokerSelector({"hot": [hot], "low": [low]},
                               default_tier="hot", min_priority=0,
                               priority_tier="low")
    router = Router(sel)
    assert router.run_json(
        {**TS_Q, "context": {"priority": -5}}) == [{"broker": "low"}]
    assert router.run_json(TS_Q) == [{"broker": "hot"}]


def test_router_datasource_period_rule():
    hot, cold = FakeBroker("hot"), FakeBroker("cold")
    sel = TieredBrokerSelector(
        {"hot": [hot], "_default": [cold]}, default_tier="_default",
        rules={"test": [{"periodMs": 30 * 86_400_000, "tier": "hot"}]})
    now = Interval.of("2026-01-07", "2026-01-08").start
    tier, b = sel.pick(TS_Q, now_ms=now)        # recent interval → hot
    assert tier == "hot"
    old_q = {**TS_Q, "intervals": ["2020-01-01/2020-01-02"]}
    tier, b = sel.pick(old_q, now_ms=now)
    assert tier == "_default"


def test_router_round_robin_within_tier():
    b1, b2 = FakeBroker("a"), FakeBroker("b")
    sel = TieredBrokerSelector({"_default": [b1, b2]},
                               default_tier="_default")
    router = Router(sel)
    seen = {router.run_json(TS_Q)[0]["broker"] for _ in range(4)}
    assert seen == {"a", "b"}
    assert len(b1.calls) == len(b2.calls) == 2


def test_router_http_proxies_to_broker_http(segments):
    """Full proxy path: router HTTP → broker HTTP → engine."""
    ex = QueryExecutor(segments)
    lc = QueryLifecycle(ex)
    broker_http = QueryHttpServer(lc).start()
    sel = TieredBrokerSelector(
        {"_default": [f"http://127.0.0.1:{broker_http.port}"]},
        default_tier="_default")
    router_http = RouterHttpServer(sel).start()
    try:
        body = json.dumps(TS_Q).encode()
        req = urllib.request.Request(
            router_http.url + "/druid/v2", data=body,
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=60) as r:
            rows = json.loads(r.read())
        assert rows[0]["result"]["n"] == sum(s.n_rows for s in segments)
    finally:
        router_http.stop()
        broker_http.stop()


# ---------------------------------------------------------------------------
# Security chain
# ---------------------------------------------------------------------------

def _chain():
    authz = RoleBasedAuthorizer(
        role_permissions={
            "analyst": [Permission("test", actions=(READ,))],
            "admin": [Permission("*")]},
        user_roles={"alice": ["analyst"], "root": ["admin"]})
    return AuthChain(
        authenticators=[BasicHTTPAuthenticator(
            {"alice": "pw1", "root": "pw2"}, authorizer_name="rbac")],
        authorizers={"rbac": authz, "allowAll": AllowAllAuthorizer()})


def _basic(user, pw):
    return {"Authorization":
            "Basic " + base64.b64encode(f"{user}:{pw}".encode()).decode()}


def test_authenticator_chain():
    chain = _chain()
    assert chain.authenticate(_basic("alice", "pw1")).identity == "alice"
    assert chain.authenticate(_basic("alice", "wrong")) is None
    assert chain.authenticate({}) is None
    # escalated internal identity bypasses user ACLs via its own authorizer
    assert chain.escalator.escalate().authorizer_name == "allowAll"


def test_rbac_authorization_per_datasource(segments):
    chain = _chain()
    lc = QueryLifecycle(QueryExecutor(segments),
                        authorizer=authorizer_for_query(chain))
    alice = chain.authenticate(_basic("alice", "pw1"))
    rows = lc.run_json(TS_Q, identity=alice)
    assert rows[0]["result"]["n"] > 0
    with pytest.raises(Unauthorized):
        lc.run_json({**TS_Q, "dataSource": "secret"}, identity=alice)
    root = chain.authenticate(_basic("root", "pw2"))
    assert lc.run_json(TS_Q, identity=root)
    with pytest.raises(Unauthorized):
        lc.run_json(TS_Q, identity=None)


def test_http_auth_401_and_403(segments):
    chain = _chain()
    lc = QueryLifecycle(QueryExecutor(segments),
                        authorizer=authorizer_for_query(chain))
    srv = QueryHttpServer(lc, auth_chain=chain).start()
    url = f"http://127.0.0.1:{srv.port}/druid/v2"
    try:
        body = json.dumps(TS_Q).encode()

        def post(headers):
            req = urllib.request.Request(
                url, data=body,
                headers={"Content-Type": "application/json", **headers},
                method="POST")
            return urllib.request.urlopen(req, timeout=30)

        with pytest.raises(urllib.error.HTTPError) as e:
            post({})                               # no credentials
        assert e.value.code == 401
        with pytest.raises(urllib.error.HTTPError) as e:
            post(_basic("alice", "nope"))          # bad credentials
        assert e.value.code == 401
        rows = json.loads(post(_basic("alice", "pw1")).read())
        assert rows[0]["result"]["n"] > 0
        bad = json.dumps({**TS_Q, "dataSource": "secret"}).encode()
        req = urllib.request.Request(
            url, data=bad, headers={"Content-Type": "application/json",
                                    **_basic("alice", "pw1")},
            method="POST")
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=30)
        assert e.value.code == 403                 # authenticated, denied
    finally:
        srv.stop()


def test_bad_basic_credentials_do_not_fall_through():
    """Wrong password on a PRESENT Basic header must deny the request, not
    launder into a weaker downstream authenticator."""
    from druid_tpu.server.security import AllowAllAuthenticator
    chain = AuthChain(
        authenticators=[BasicHTTPAuthenticator({"alice": "pw1"}),
                        AllowAllAuthenticator()],
        authorizers={"allowAll": AllowAllAuthorizer()})
    assert chain.authenticate(_basic("alice", "WRONG")) is None
    assert chain.authenticate({}).identity == "allowAll"  # truly anonymous


def test_sql_endpoint_authorizes_tables(segments):
    chain = _chain()
    from druid_tpu.sql import SqlExecutor
    ex = QueryExecutor(segments)
    lc = QueryLifecycle(ex, authorizer=authorizer_for_query(chain))
    srv = QueryHttpServer(lc, sql_executor=SqlExecutor(ex),
                          auth_chain=chain).start()
    url = f"http://127.0.0.1:{srv.port}/druid/v2/sql"
    try:
        def post_sql(stmt, headers):
            body = json.dumps({"query": stmt}).encode()
            req = urllib.request.Request(
                url, data=body,
                headers={"Content-Type": "application/json", **headers},
                method="POST")
            return urllib.request.urlopen(req, timeout=30)

        rows = json.loads(
            post_sql("SELECT COUNT(*) c FROM test",
                     _basic("alice", "pw1")).read())
        assert rows[0]["c"] > 0
        # alice has no grant on any other table → 403, same as native path
        with pytest.raises(urllib.error.HTTPError) as e:
            post_sql("SELECT COUNT(*) FROM test2",
                     _basic("alice", "pw1"))
        assert e.value.code in (400, 403)
    finally:
        srv.stop()


def test_get_and_delete_require_auth(segments):
    chain = _chain()
    lc = QueryLifecycle(QueryExecutor(segments),
                        authorizer=authorizer_for_query(chain))
    srv = QueryHttpServer(lc, auth_chain=chain).start()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(base + "/druid/v2/datasources",
                                   timeout=30)
        assert e.value.code == 401
        req = urllib.request.Request(base + "/druid/v2/qid1",
                                     method="DELETE")
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=30)
        assert e.value.code == 401
        # /status stays open for health checks
        assert urllib.request.urlopen(base + "/status",
                                      timeout=30).status == 200
    finally:
        srv.stop()


def test_router_priority_tier_without_brokers_falls_back():
    hot = FakeBroker("hot")
    sel = TieredBrokerSelector({"hot": [hot]}, default_tier="hot",
                               min_priority=0, priority_tier="cold")
    router = Router(sel)
    assert router.run_json(
        {**TS_Q, "context": {"priority": -5}}) == [{"broker": "hot"}]


def test_lookup_version_ordering_past_v9():
    reg = LookupReferencesManager()
    for i in range(12):
        assert reg.add("x", {"n": str(i)}, version=f"v{i}")
    assert reg.get("x").mapping == {"n": "11"}
    assert not reg.add("x", {"n": "stale"}, version="v9")


# ---------------------------------------------------------------------------
# Lookup cluster management
# ---------------------------------------------------------------------------

def test_lookup_coordinator_push_and_node_sync():
    md = MetadataStore()
    mgr = LookupCoordinatorManager(md)
    mgr.set_lookup("_default", "country_names", {"us": "United States"})
    reg = LookupReferencesManager()
    sync = LookupNodeSync(mgr, "_default", reg)
    assert sync.poll() == 1
    assert reg.get("country_names").mapping == {"us": "United States"}

    # version-gated update propagates; unchanged spec is a no-op
    assert sync.poll() == 0
    mgr.set_lookup("_default", "country_names",
                   {"us": "USA", "fr": "France"})
    assert sync.poll() == 1
    assert reg.get("country_names").mapping["fr"] == "France"

    # deletion converges
    mgr.delete_lookup("_default", "country_names")
    assert sync.poll() == 1
    assert reg.get("country_names") is None

    # a freshly-started node converges from an empty registry
    mgr.set_lookup("_default", "x", {"1": "one"})
    reg2 = LookupReferencesManager()
    assert LookupNodeSync(mgr, "_default", reg2).poll() == 1
    assert reg2.get("x").mapping == {"1": "one"}


def test_lookup_tiers_are_isolated():
    md = MetadataStore()
    mgr = LookupCoordinatorManager(md)
    mgr.set_lookup("hot", "a", {"k": "hotval"})
    mgr.set_lookup("cold", "a", {"k": "coldval"})
    hot_reg, cold_reg = LookupReferencesManager(), LookupReferencesManager()
    LookupNodeSync(mgr, "hot", hot_reg).poll()
    LookupNodeSync(mgr, "cold", cold_reg).poll()
    assert hot_reg.get("a").mapping == {"k": "hotval"}
    assert cold_reg.get("a").mapping == {"k": "coldval"}
    assert mgr.tiers() == ["cold", "hot"]
