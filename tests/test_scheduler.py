"""Data-node scheduler (server/scheduler.py): cross-query fusion parity,
admission control (429s, lanes, deadline shed), queue accounting, and the
broker's 429 handling.

Parity assertions are EXACT (`==` on finished rows, floats included): a
cross-query chunk runs the same traced body over the same staged columns as
each query's own serial execution, so which flush a query lands in may
never change its bits. Saturation/lane assertions are on CONTRACT (shed vs
admitted, 429 vs hang) — never on wall-clock throughput, which this shared
CI hardware cannot promise."""
import json
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from druid_tpu.cluster import (Broker, DataNode, DataNodeServer,
                               InventoryView, RemoteDataNodeClient,
                               descriptor_for)
from druid_tpu.cluster import wire
from druid_tpu.data.generator import ColumnSpec, DataGenerator
from druid_tpu.engine import engines
from druid_tpu.obs import trace as qtrace
from druid_tpu.query.model import query_from_json
from druid_tpu.server.querymanager import QueryCapacityError
from druid_tpu.server.scheduler import (BACKGROUND_LANE, DataNodeScheduler,
                                        SchedulerConfig,
                                        SchedulerMetricsMonitor, lane_of)
from druid_tpu.utils.emitter import InMemoryEmitter, ServiceEmitter
from druid_tpu.utils.intervals import Interval

IV = Interval.of("2026-03-01", "2026-03-03")

SCHEMA = (
    ColumnSpec("dimA", "string", cardinality=8, distribution="uniform"),
    ColumnSpec("dimB", "string", cardinality=40, distribution="zipf"),
    ColumnSpec("metLong", "long", low=0, high=1000),
    ColumnSpec("metFloat", "float", distribution="normal", mean=5.0, std=2.0),
    ColumnSpec("metDouble", "double", low=0.0, high=1.0),
)

AGGS = [{"type": "count", "name": "n"},
        {"type": "longSum", "name": "ls", "fieldName": "metLong"},
        {"type": "doubleSum", "name": "ds", "fieldName": "metDouble"},
        {"type": "floatMax", "name": "fx", "fieldName": "metFloat"}]


@pytest.fixture(scope="module")
def sched_segments():
    gen = DataGenerator(SCHEMA, seed=11)
    return gen.segments(8, 1500, IV, datasource="hot")


@pytest.fixture()
def node(sched_segments):
    n = DataNode("sched-node")
    for s in sched_segments:
        n.load_segment(s)
    return n


def _groupby(qid, ctx=None):
    return query_from_json({
        "queryType": "groupBy", "dataSource": "hot", "intervals": [str(IV)],
        "granularity": "all", "dimensions": ["dimA"], "aggregations": AGGS,
        "context": {"queryId": qid, **(ctx or {})}})


def _timeseries(qid, ctx=None):
    return query_from_json({
        "queryType": "timeseries", "dataSource": "hot",
        "intervals": [str(IV)], "granularity": "hour", "aggregations": AGGS,
        "context": {"queryId": qid, **(ctx or {})}})


def _topn(qid, ctx=None):
    return query_from_json({
        "queryType": "topN", "dataSource": "hot", "intervals": [str(IV)],
        "granularity": "all", "dimension": "dimB", "metric": "ls",
        "threshold": 7, "aggregations": AGGS,
        "context": {"queryId": qid, **(ctx or {})}})


def _finish(query, ap):
    qt = query.query_type
    if qt == "groupBy":
        return engines.finish_groupby(query, ap)
    if qt == "timeseries":
        return engines.finish_timeseries(query, ap)
    return engines.finish_topn(query, ap)


# ---------------------------------------------------------------------------
# cross-query fusion parity
# ---------------------------------------------------------------------------

def test_concurrent_mixed_queries_bit_identical_to_serial(node,
                                                          sched_segments):
    """The acceptance gate: a mixed concurrent workload — different query
    types, overlapping segment sets, float/double aggregations — produces
    EXACTLY the rows serial per-query execution produces."""
    sids = [str(s.id) for s in sched_segments]
    workload = (
        [( _groupby(f"g{i}"), [sids[i % 8]]) for i in range(6)]
        + [(_timeseries(f"t{i}"), sids[i:i + 3]) for i in range(3)]
        + [(_topn(f"n{i}"), [sids[i], sids[(i + 4) % 8]]) for i in range(3)]
    )
    serial = [node.run_partials(q, s) for q, s in workload]

    sched = DataNodeScheduler(
        node, SchedulerConfig(batch_window_ms=40.0, lane_depths={})).start()
    try:
        results = [None] * len(workload)
        errors = []

        def client(i):
            q, s = workload[i]
            try:
                results[i] = sched.submit(q, s)
            except Exception as e:           # pragma: no cover - must not
                errors.append(e)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(len(workload))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
    finally:
        sched.stop()
    assert errors == []

    for (q, _), (ap_s, served_s), got in zip(workload, serial, results):
        ap_g, served_g = got
        assert served_g == served_s
        # partial-state parity, bitwise (counts + every kernel state)
        assert len(ap_g.partials) == len(ap_s.partials)
        for ps, pg in zip(ap_s.partials, ap_g.partials):
            assert np.array_equal(ps.counts, pg.counts)
            for k in ps.states:
                assert np.array_equal(np.asarray(ps.states[k]),
                                      np.asarray(pg.states[k]))
        # finished-row parity, exact (floats included)
        assert _finish(q, ap_g) == _finish(q, ap_s)


def test_flush_actually_fuses_across_queries(node, sched_segments):
    """The point of the scheduler: concurrent plan-compatible queries land
    in ONE device dispatch (crossBatch queries > 1), not one each."""
    sids = [str(s.id) for s in sched_segments]
    sched = DataNodeScheduler(
        node, SchedulerConfig(batch_window_ms=60.0, lane_depths={})).start()
    try:
        barrier = threading.Barrier(6)

        def client(i):
            barrier.wait()
            sched.submit(_groupby(f"fuse{i}"), [sids[i % 8]])

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
    finally:
        sched.stop()
    events, _, _ = sched.stats.drain_events()
    assert sched.stats.snapshot()["crossBatches"] >= 1
    assert any(nq >= 2 for nq, _, _ in events), events


# ---------------------------------------------------------------------------
# admission control: saturation, lanes, deadline
# ---------------------------------------------------------------------------

def test_flood_beyond_queue_depth_sheds_not_hangs(node, sched_segments):
    sids = [str(s.id) for s in sched_segments]
    sched = DataNodeScheduler(
        node, SchedulerConfig(batch_window_ms=300.0, max_queue_depth=2,
                              lane_depths={})).start()
    ok, shed, other = [], [], []
    try:
        barrier = threading.Barrier(8)

        def client(i):
            barrier.wait()
            try:
                ok.append(sched.submit(_groupby(f"flood{i}"), [sids[0]]))
            except QueryCapacityError as e:
                assert e.retry_after_s > 0
                shed.append(e)
            except Exception as e:           # pragma: no cover - must not
                other.append(e)

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
    finally:
        sched.stop()
    assert other == []
    assert len(ok) + len(shed) == 8
    assert len(shed) >= 2, "a flood beyond depth 2 must shed"
    assert len(ok) >= 2, "admitted queries must still complete"
    assert sched.stats.snapshot()["shed"] == len(shed)


def test_background_flood_cannot_starve_interactive(node, sched_segments):
    """Priority lanes: with the background lane capped, a background flood
    sheds BACKGROUND queries while every interactive query is admitted and
    completes — bounded interactive latency by construction."""
    sids = [str(s.id) for s in sched_segments]
    sched = DataNodeScheduler(
        node, SchedulerConfig(batch_window_ms=300.0, max_queue_depth=100,
                              lane_depths={BACKGROUND_LANE: 2})).start()
    bg_ok, bg_shed, inter_ok, errors = [], [], [], []
    try:
        barrier = threading.Barrier(9)

        def background(i):
            barrier.wait()
            try:
                bg_ok.append(sched.submit(
                    _groupby(f"bg{i}", {"lane": "background"}), [sids[0]]))
            except QueryCapacityError:
                bg_shed.append(i)
            except Exception as e:           # pragma: no cover - must not
                errors.append(e)

        def interactive(i):
            barrier.wait()
            time.sleep(0.05)        # arrive INTO the flood
            try:
                inter_ok.append(sched.submit(
                    _groupby(f"int{i}", {"priority": 10}), [sids[i]]))
            except Exception as e:           # pragma: no cover - must not
                errors.append(e)

        threads = [threading.Thread(target=background, args=(i,))
                   for i in range(6)] \
            + [threading.Thread(target=interactive, args=(i,))
               for i in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
    finally:
        sched.stop()
    assert errors == []
    assert len(bg_shed) >= 1, "the background flood must shed"
    assert len(inter_ok) == 3, "no interactive query may be shed"


def test_deadline_infeasible_sheds_upfront(node, sched_segments):
    """With a measured service rate and a queue of work, a query whose
    timeout the queue provably cannot meet is shed at admission (429 with
    the drain estimate as Retry-After) instead of timing out late."""
    sids = [str(s.id) for s in sched_segments]
    sched = DataNodeScheduler(
        node, SchedulerConfig(batch_window_ms=1.0, lane_depths={}))
    sched.start()
    # establish a service-rate estimate
    sched.submit(_groupby("warm"), sids[:2])
    assert sched._rate_rows_per_s is not None
    sched.stop()
    # a stopped dispatcher keeps the queue static: stack up cost, then ask
    # for a 1ms deadline — infeasible against the measured rate
    with sched._cond:
        sched._stopping = False   # allow enqueue without a live dispatcher
    big = [_groupby(f"q{i}") for i in range(3)]
    with sched._cond:
        for i, q in enumerate(big):
            sched._seq += 1
            from druid_tpu.server.scheduler import _Item
            sched._queue.append(_Item(q, sids, None, "interactive", 0,
                                      10_000_000, sched._seq))
    with pytest.raises(QueryCapacityError, match="deadline infeasible"):
        with sched._cond:
            sched._admit_locked(_groupby("late", {"timeout": 1}),
                                "interactive", 1000)
    assert sched.stats.snapshot()["shed"] == 1


def test_lane_derivation():
    assert lane_of(_groupby("a")) == "interactive"
    assert lane_of(_groupby("b", {"priority": -1})) == "background"
    assert lane_of(_groupby("c", {"lane": "reporting"})) == "reporting"
    assert lane_of(_groupby("d", {"priority": 10})) == "interactive"


def test_stop_fails_queued_waiters_fast(node, sched_segments):
    """stop() with queued work must release the waiters with an error —
    never leave an HTTP handler thread hung on a dead dispatcher."""
    sids = [str(s.id) for s in sched_segments]
    sched = DataNodeScheduler(
        node, SchedulerConfig(batch_window_ms=5000.0, lane_depths={}))
    sched.start()
    outcome = []

    def client():
        try:
            outcome.append(("ok", sched.submit(_groupby("q"), [sids[0]])))
        except Exception as e:
            outcome.append(("err", e))

    t = threading.Thread(target=client)
    t.start()
    deadline = time.monotonic() + 5.0
    while sched.depth() == 0 and time.monotonic() < deadline:
        time.sleep(0.01)
    sched.stop()
    t.join(timeout=10)
    assert not t.is_alive(), "waiter hung across scheduler stop"
    assert outcome and outcome[0][0] == "err"


def test_submit_after_stop_raises_fast(node, sched_segments):
    """A submit racing (or following) stop() must fail fast — never
    resurrect the dispatcher of a deliberately stopped scheduler. Only an
    explicit start() brings it back."""
    sids = [str(s.id) for s in sched_segments]
    sched = DataNodeScheduler(
        node, SchedulerConfig(batch_window_ms=1.0, lane_depths={})).start()
    sched.submit(_groupby("warm"), [sids[0]])
    sched.stop()
    with pytest.raises(RuntimeError, match="scheduler stopped"):
        sched.submit(_groupby("late"), [sids[0]])
    assert sched._thread is None or not sched._thread.is_alive(), \
        "submit resurrected a stopped dispatcher"
    sched.start()
    try:
        ap, served = sched.submit(_groupby("again"), [sids[0]])
        assert served == {sids[0]}
    finally:
        sched.stop()


def test_group_path_keeps_segment_time_metrics(sched_segments):
    """query/segment/time must not disappear when the scheduler fronts an
    emitter-bearing node: the fused group path emits one aggregate timing
    per request (run_partials' batched-set shape), and a
    per_segment_metrics node routes through run_partials so every segment
    keeps its own timing — the serial path's observability trade."""
    sink = InMemoryEmitter()
    em = ServiceEmitter("druid/historical", "emit-node", sink)
    n = DataNode("emit-node", emitter=em)
    for s in sched_segments:
        n.load_segment(s)
    sids = [str(s.id) for s in sched_segments]
    out = n.run_partials_group([(_groupby("ga"), sids[:2], None),
                                (_groupby("gb"), sids[2:4], None)])
    assert all(not isinstance(r, BaseException) for r in out)
    evs = sink.metrics("query/segment/time")
    assert {e.dims["id"] for e in evs} == {"ga", "gb"}
    assert all(e.dims["segment"] == "2-segments" for e in evs)

    sink2 = InMemoryEmitter()
    n2 = DataNode("emit-node2",
                  emitter=ServiceEmitter("druid/historical", "emit-node2",
                                         sink2),
                  per_segment_metrics=True)
    for s in sched_segments:
        n2.load_segment(s)
    out2 = n2.run_partials_group([(_groupby("gc"), sids[:2], None)])
    assert all(not isinstance(r, BaseException) for r in out2)
    segs_seen = {e.dims["segment"]
                 for e in sink2.metrics("query/segment/time")}
    assert segs_seen == set(sids[:2])


# ---------------------------------------------------------------------------
# queue accounting: span + metric reflect the scheduler hold
# ---------------------------------------------------------------------------

def _held_submit(node, sids, window_ms, ctx=None):
    """Submit ONE query into an idle scheduler with the given batching
    window — its queue/wait hold is ≈ the window — and return
    (emitted metrics, trace spans, hold lower bound ms)."""
    sink = InMemoryEmitter()
    emitter = ServiceEmitter("druid/historical", "t", sink)
    sched = DataNodeScheduler(
        node, SchedulerConfig(batch_window_ms=window_ms, lane_depths={}),
        emitter=emitter).start()
    store = qtrace.TraceStore()
    q = _groupby("held", ctx)
    try:
        with qtrace.root_span("datanode/query", q, service="t",
                              store=store):
            sched.submit(q, sids[:1])
    finally:
        sched.stop()
    return sink, store.spans("held"), window_ms * 0.5


def test_queue_wait_span_and_metric_reflect_hold(node, sched_segments):
    """Under a saturated/held scheduler the qtrace queue/wait span AND the
    query/queue/wait metric must carry the actual hold — not the
    (previously only-exercised) unqueued near-zero path."""
    sids = [str(s.id) for s in sched_segments]
    sink, spans, floor_ms = _held_submit(node, sids, window_ms=150.0)
    waits = [e for e in sink.metrics("query/queue/wait")]
    assert len(waits) == 1
    assert waits[0].value >= floor_ms, \
        f"metric {waits[0].value}ms does not reflect a ~150ms hold"
    assert waits[0].dims.get("lane") == "interactive"
    qspans = [s for s in spans if s["name"] == "queue/wait"]
    assert len(qspans) == 1
    assert qspans[0]["durationMs"] >= floor_ms
    # the hold ended when the flush STARTED: execution is attributed to
    # engine spans, not to queue time
    flush = [s for s in spans if s["name"] == "sched/flush"]
    assert flush, "flush span missing from the request trace"


def test_trace_false_still_gets_queue_metrics(node, sched_segments):
    """{"trace": false} opts out of SPANS, never of metrics: the
    query/queue/wait metric must still reflect the hold."""
    sids = [str(s.id) for s in sched_segments]
    sink, spans, floor_ms = _held_submit(node, sids, window_ms=120.0,
                                         ctx={"trace": False})
    waits = sink.metrics("query/queue/wait")
    assert len(waits) == 1 and waits[0].value >= floor_ms
    assert spans == [], "trace=false query must record no spans"


def test_scheduler_monitor_emits_catalog_metrics(node, sched_segments):
    sids = [str(s.id) for s in sched_segments]
    sched = DataNodeScheduler(
        node, SchedulerConfig(batch_window_ms=30.0, max_queue_depth=1,
                              lane_depths={})).start()
    try:
        barrier = threading.Barrier(4)

        def client(i):
            barrier.wait()
            try:
                sched.submit(_groupby(f"m{i}"), [sids[i % 8]])
            except QueryCapacityError:
                pass

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
    finally:
        sched.stop()
    sink = InMemoryEmitter()
    SchedulerMetricsMonitor(sched).do_monitor(
        ServiceEmitter("druid/historical", "t", sink))
    names = {e.metric for e in sink.metrics()}
    assert "query/queue/depth" in names
    assert "query/shed/count" in names
    shed = sink.metrics("query/shed/count")[0]
    assert shed.value == sched.stats.snapshot()["shed"]
    from druid_tpu.obs import catalog
    assert catalog.validate_emitted(names) == []


# ---------------------------------------------------------------------------
# the 429 contract over HTTP + the broker's handling
# ---------------------------------------------------------------------------

def test_http_flood_yields_429_with_retry_after(node, sched_segments):
    """A flood beyond queue depth at the HTTP layer: every response is a
    clean 200 or a 429 carrying Retry-After — no hangs, no 500s."""
    sids = [str(s.id) for s in sched_segments]
    srv = DataNodeServer(node, scheduler_config=SchedulerConfig(
        batch_window_ms=120.0, max_queue_depth=2, lane_depths={})).start()
    codes, retry_after = [], []
    body = json.dumps({"query": _groupby("warm").to_json(),
                       "segments": sids[:1]}).encode()

    def flood(i):
        b = json.dumps({"query": _groupby(f"h{i}").to_json(),
                        "segments": sids[:1]}).encode()
        req = urllib.request.Request(
            srv.url + "/druid/v2/partials", data=b,
            headers={"Content-Type": "application/json"}, method="POST")
        try:
            with urllib.request.urlopen(req, timeout=60) as r:
                codes.append(r.status)
        except urllib.error.HTTPError as e:
            codes.append(e.code)
            if e.code == 429:
                retry_after.append(e.headers.get("Retry-After"))
            e.read()

    try:
        # warm one through (establishes the fused path compiles)
        req = urllib.request.Request(
            srv.url + "/druid/v2/partials", data=body,
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=120) as r:
            assert r.status == 200
        threads = [threading.Thread(target=flood, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
    finally:
        srv.stop()
    assert sorted(set(codes)) in ([200, 429], [429], [200]), codes
    assert 429 in codes, "a flood beyond depth must shed with 429"
    assert all(ra and int(ra) >= 1 for ra in retry_after), retry_after


def test_non_fusable_requests_bypass_scheduler(sched_segments):
    """Work the node cannot fuse (per-segment metrics here; mesh likewise)
    must run on the request thread, not serialize on the single dispatcher
    thread — DataNodeServer routes it straight to run_partials and the
    scheduler never sees it. (Segment-cache queries, by contrast, DO fuse
    — see the scheduler × segment-cache section below.)"""
    n = DataNode("bypass-node",
                 emitter=ServiceEmitter("druid/historical", "t",
                                        InMemoryEmitter()),
                 per_segment_metrics=True)
    for s in sched_segments:
        n.load_segment(s)
    q = _groupby("bypass")
    assert not n.fusable(q)
    sids = [str(s.id) for s in sched_segments]
    expect = _finish(q, n.run_partials(q, sids)[0])
    srv = DataNodeServer(n, scheduler_config=SchedulerConfig(
        batch_window_ms=50.0)).start()
    submits = []
    real_submit = srv.scheduler.submit
    srv.scheduler.submit = lambda *a, **k: (submits.append(a),
                                            real_submit(*a, **k))[1]
    try:
        body = json.dumps({"query": q.to_json(),
                           "segments": sids}).encode()
        req = urllib.request.Request(
            srv.url + "/druid/v2/partials", data=body,
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req, timeout=120) as r:
            assert r.status == 200
            ap, served, _ = wire.loads_partials(r.read())
    finally:
        srv.stop()
    assert submits == [], "non-fusable request must not enter the queue"
    assert _finish(q, ap) == expect
    assert served == {str(s.id) for s in sched_segments}


def test_batch_opted_out_queries_are_not_fusable(node):
    """{"batchSegments": false} (and the process switch) means the fused
    path would only run the query per-segment on the dispatcher thread —
    such queries must bypass the scheduler entirely."""
    from druid_tpu.engine import batching
    assert node.fusable(_groupby("plain"))
    assert not node.fusable(_groupby("opt", {"batchSegments": False}))
    assert not node.fusable(_groupby("opt2", {"batchSegments": "false"}))
    prev = batching.set_enabled(False)
    try:
        assert not node.fusable(_groupby("global-off"))
    finally:
        batching.set_enabled(prev)
    assert node.fusable(_groupby("back-on"))


def test_stop_without_dispatcher_fails_queued_waiters(node, sched_segments):
    """A submit that races stop() when NO dispatcher thread is alive
    (scheduler constructed but never started) must still fail fast —
    stop() itself fails the queue, not only the dispatcher loop."""
    sched = DataNodeScheduler(node, SchedulerConfig(batch_window_ms=500.0))
    sched._ensure_dispatcher = lambda: None      # no dispatcher, ever
    sids = [str(s.id) for s in sched_segments]
    errs = []

    def go():
        try:
            sched.submit(_groupby("stranded"), sids)
        except Exception as e:
            errs.append(e)

    t = threading.Thread(target=go)
    t.start()
    deadline = time.monotonic() + 10
    while sched.depth() == 0 and time.monotonic() < deadline:
        time.sleep(0.005)
    assert sched.depth() == 1
    sched.stop()
    t.join(timeout=5)
    assert not t.is_alive(), "waiter stranded after stop()"
    assert len(errs) == 1 and isinstance(errs[0], RuntimeError) \
        and "stopped" in str(errs[0])


def test_run_partials_group_backstop_for_non_fusable(sched_segments):
    """The robustness backstop: a non-fusable request that does reach
    run_partials_group (eligibility changed between admission and flush)
    runs via the normal run_partials path with identical semantics."""
    n = DataNode("backstop-node",
                 emitter=ServiceEmitter("druid/historical", "t",
                                        InMemoryEmitter()),
                 per_segment_metrics=True)
    for s in sched_segments:
        n.load_segment(s)
    q = _groupby("backstop")
    sids = [str(s.id) for s in sched_segments]
    expect = _finish(q, n.run_partials(q, sids)[0])
    out = n.run_partials_group([(q, sids, None),
                                (_timeseries("mate"), sids, None)])
    assert not isinstance(out[0], BaseException)
    ap, served = out[0]
    assert _finish(q, ap) == expect
    assert served == {str(s.id) for s in sched_segments}
    assert not isinstance(out[1], BaseException)


class _SheddingHandler(BaseHTTPRequestHandler):
    """Stub data node: sheds the first `shed_n` POSTs with 429 (carrying
    `retry_after`), then serves a canned partials bundle."""
    shed_n = 1
    retry_after = "0.05"
    payload = b""
    calls = []

    def log_message(self, fmt, *args):
        pass

    def do_POST(self):
        self.rfile.read(int(self.headers.get("Content-Length", 0)))
        type(self).calls.append(self.path)
        if len(type(self).calls) <= type(self).shed_n:
            body = b'{"error": "Query capacity exceeded"}'
            self.send_response(429)
            self.send_header("Retry-After", type(self).retry_after)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        self.send_response(200)
        self.send_header("Content-Type", wire.CONTENT_TYPE)
        self.send_header("Content-Length", str(len(type(self).payload)))
        self.end_headers()
        self.wfile.write(type(self).payload)


def _stub_shedding_server(sched_segments, shed_n, retry_after="0.05"):
    q = _groupby("stub")
    ap = engines.make_aggregate_partials(q, sched_segments[:1], clamp=False)
    payload = wire.dumps_partials(
        ap, served=[str(sched_segments[0].id)], trace=[])
    handler = type("H", (_SheddingHandler,), {
        "shed_n": shed_n, "retry_after": retry_after,
        "payload": payload, "calls": []})
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, handler, q


def test_client_retries_once_after_retry_after(sched_segments,
                                               monkeypatch):
    """Satellite fix: a single 429 is retried once after Retry-After and
    the query succeeds — previously any non-200 was an opaque
    RemoteQueryError."""
    httpd, handler, q = _stub_shedding_server(sched_segments, shed_n=1)
    monkeypatch.setattr(RemoteDataNodeClient, "MAX_RETRY_AFTER_SLEEP", 0.05)
    try:
        client = RemoteDataNodeClient(
            "stub", f"http://127.0.0.1:{httpd.server_address[1]}")
        ap, served = client.run_partials(q, [str(sched_segments[0].id)])
        assert served == {str(sched_segments[0].id)}
        assert len(handler.calls) == 2, "exactly one retry after the 429"
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_persistent_shed_raises_capacity_error(sched_segments, monkeypatch):
    """Shed twice → QueryCapacityError with the node's Retry-After, NOT a
    RemoteQueryError/MissingSegmentsError."""
    httpd, handler, q = _stub_shedding_server(sched_segments, shed_n=99)
    monkeypatch.setattr(RemoteDataNodeClient, "MAX_RETRY_AFTER_SLEEP", 0.05)
    try:
        client = RemoteDataNodeClient(
            "stub", f"http://127.0.0.1:{httpd.server_address[1]}")
        with pytest.raises(QueryCapacityError) as ei:
            client.run_partials(q, [str(sched_segments[0].id)])
        assert ei.value.retry_after_s == 0.05
        assert ei.value.server == "stub"
        assert len(handler.calls) == 2
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_long_retry_after_fails_fast_without_retry(sched_segments):
    """A drain estimate past MAX_RETRY_AFTER_SLEEP means the one retry is
    near-certain to shed again — the client must fail fast with the
    node's Retry-After, not sleep the cap and reissue a doomed request."""
    httpd, handler, q = _stub_shedding_server(sched_segments, shed_n=99,
                                              retry_after="10")
    try:
        client = RemoteDataNodeClient(
            "stub", f"http://127.0.0.1:{httpd.server_address[1]}")
        t0 = time.monotonic()
        with pytest.raises(QueryCapacityError) as ei:
            client.run_partials(q, [str(sched_segments[0].id)])
        assert time.monotonic() - t0 < 2.0, "slept toward a doomed retry"
        assert ei.value.retry_after_s == 10.0
        assert len(handler.calls) == 1, "no retry on a long drain estimate"
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_broker_http_surface_propagates_429(sched_segments, monkeypatch):
    """End of the chain: the ORIGINAL client sees the same 429 +
    Retry-After contract from the broker's own HTTP resource."""
    from druid_tpu.server.http import QueryHttpServer
    from druid_tpu.server.lifecycle import QueryLifecycle

    httpd, handler, q = _stub_shedding_server(sched_segments, shed_n=99)
    monkeypatch.setattr(RemoteDataNodeClient, "MAX_RETRY_AFTER_SLEEP", 0.05)
    client = RemoteDataNodeClient(
        "stub", f"http://127.0.0.1:{httpd.server_address[1]}")
    view = InventoryView()
    view.register(client)
    for s in sched_segments:
        view.announce("stub", descriptor_for(s))
    http = QueryHttpServer(QueryLifecycle(Broker(view))).start()
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{http.port}/druid/v2",
            data=json.dumps(q.to_json()).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=60)
        assert ei.value.code == 429
        assert int(ei.value.headers.get("Retry-After")) >= 1
        body = json.loads(ei.value.read())
        assert body["error"] == "Query capacity exceeded"
    finally:
        http.stop()
        httpd.shutdown()
        httpd.server_close()


def test_broker_fails_fast_with_clear_shed_error(sched_segments,
                                                 monkeypatch):
    """The broker surfaces a persistent shed as QueryCapacityError — a
    clear, typed saturation signal (429 at its own resource layer) instead
    of opaquely erroring the whole query."""
    httpd, handler, q = _stub_shedding_server(sched_segments, shed_n=99)
    monkeypatch.setattr(RemoteDataNodeClient, "MAX_RETRY_AFTER_SLEEP", 0.05)
    try:
        client = RemoteDataNodeClient(
            "stub", f"http://127.0.0.1:{httpd.server_address[1]}")
        view = InventoryView()
        view.register(client)
        for s in sched_segments:
            view.announce("stub", descriptor_for(s))
        broker = Broker(view)
        with pytest.raises(QueryCapacityError):
            broker.run(q)
    finally:
        httpd.shutdown()
        httpd.server_close()


# ---------------------------------------------------------------------------
# scheduler × segment cache (PR 7 follow-on: cache-hit partials resolve
# inside the batched wave instead of routing per-query in a flush)
# ---------------------------------------------------------------------------

def _cached_node(sched_segments, name="cache-node"):
    from druid_tpu.cluster.cache import CacheConfig, LruCache
    n = DataNode(name, cache=LruCache(max_entries=256),
                 cache_config=CacheConfig())
    for s in sched_segments:
        n.load_segment(s)
    return n


def _parts_equal(a, b):
    assert len(a.partials) == len(b.partials)
    for pa, pb in zip(a.partials, b.partials):
        assert np.array_equal(pa.counts, pb.counts)
        assert set(pa.states) == set(pb.states)
        for k in pa.states:
            sa, sb = pa.states[k], pb.states[k]
            if isinstance(sa, dict):
                for kk in sa:
                    assert np.array_equal(np.asarray(sa[kk]),
                                          np.asarray(sb[kk]))
            else:
                assert np.array_equal(np.asarray(sa), np.asarray(sb))


def test_cached_query_is_fusable(node, sched_segments):
    """The composition gate: segment-cache-active queries now fuse — a hot
    datasource's cached queries must not serialize per-query in a flush."""
    n = _cached_node(sched_segments)
    q = _groupby("cache-fusable")
    assert n._segment_cache_active(q)
    assert n.fusable(q)


def test_fused_cache_population_identical_to_serial(sched_segments):
    """One run_partials_group flush over a cold cache must produce the
    SAME results and the SAME per-segment cache entries the serial
    run_partials path produces."""
    sids = [str(s.id) for s in sched_segments]
    q = _groupby("cache-pop")

    serial_node = _cached_node(sched_segments, "serial-cache-node")
    ap_serial, served_serial = serial_node.run_partials(q, sids)

    fused_node = _cached_node(sched_segments, "fused-cache-node")
    out = fused_node.run_partials_group([(q, sids, None)])
    assert not isinstance(out[0], BaseException)
    ap_fused, served_fused = out[0]
    assert served_fused == served_serial
    _parts_equal(ap_fused, ap_serial)
    assert _finish(q, ap_fused) == _finish(q, ap_serial)

    # entry-for-entry cache identity (counts + every kernel state)
    from druid_tpu.cluster.cache import query_cache_key
    qkey = query_cache_key(q)
    for sid in sids:
        es = serial_node.cache.get("segment", f"{sid}|{qkey}")
        ef = fused_node.cache.get("segment", f"{sid}|{qkey}")
        assert es is not None and ef is not None
        _parts_equal(ef, es)


def test_fully_cached_query_resolves_without_any_compute(sched_segments,
                                                         monkeypatch):
    """All-hit queries resolve inline during the flush: the fused wave is
    never entered for them (no device work, no dispatcher serialization)."""
    sids = [str(s.id) for s in sched_segments]
    q = _groupby("cache-hot")
    n = _cached_node(sched_segments)
    first = n.run_partials_group([(q, sids, None)])[0]
    assert not isinstance(first, BaseException)

    calls = []
    real = engines.make_aggregate_partials_multi

    def counting(items, on_batch=None):
        calls.append(len(items))
        return real(items, on_batch=on_batch)

    monkeypatch.setattr(engines, "make_aggregate_partials_multi", counting)
    second = n.run_partials_group([(q, sids, None)])[0]
    assert calls == [], "an all-hit query must not enter the fused wave"
    assert not isinstance(second, BaseException)
    _parts_equal(second[0], first[0])
    assert second[1] == first[1]


def test_partial_hits_fuse_only_the_miss_set(sched_segments, monkeypatch):
    """A query with some cached segments sends ONLY its misses into the
    fused wave; results concatenate hits + computed exactly like the
    serial cached path, and the misses get cached."""
    from druid_tpu.cluster.cache import query_cache_key
    sids = [str(s.id) for s in sched_segments]
    q = _groupby("cache-mix")
    n = _cached_node(sched_segments)
    warm = sids[:3]
    n.run_partials(q, warm)                      # pre-cache 3 segments
    qkey = query_cache_key(q)
    assert all(n.cache.get("segment", f"{sid}|{qkey}") for sid in warm)

    submitted = []
    real = engines.make_aggregate_partials_multi

    def spying(items, on_batch=None):
        submitted.extend(len(segs) for _, segs, _ in items)
        return real(items, on_batch=on_batch)

    monkeypatch.setattr(engines, "make_aggregate_partials_multi", spying)
    mate = _timeseries("cache-mate")
    out = n.run_partials_group([(q, sids, None), (mate, sids, None)])
    assert submitted == [len(sids) - 3, len(sids)], \
        "cached query must submit only its miss set"
    assert not isinstance(out[0], BaseException)
    assert not isinstance(out[1], BaseException)

    # every miss is now cached, and the result matches the serial path
    assert all(n.cache.get("segment", f"{sid}|{qkey}") for sid in sids)
    serial_node = _cached_node(sched_segments, "mix-serial-node")
    ap_serial, _ = serial_node.run_partials(q, sids)
    assert _finish(q, out[0][0]) == _finish(q, ap_serial)
    assert _finish(mate, out[1][0]) == _finish(
        mate, serial_node.run_partials(mate, sids)[0])


def test_cached_queries_fuse_through_the_scheduler(sched_segments):
    """End to end through DataNodeScheduler.submit: concurrent cache-active
    queries ride the flush (hits inline, misses fused) and return exactly
    the serial results."""
    sids = [str(s.id) for s in sched_segments]
    n = _cached_node(sched_segments)
    plain = _cached_node(sched_segments, "plain-node")
    queries = [_groupby(f"sc{i}") for i in range(4)]
    serial = [_finish(q, plain.run_partials(q, sids)[0]) for q in queries]

    sched = DataNodeScheduler(
        n, SchedulerConfig(batch_window_ms=40.0, lane_depths={})).start()
    try:
        for wave in range(2):                    # cold wave, then hot wave
            results = [None] * len(queries)
            errors = []

            def client(i):
                try:
                    results[i] = sched.submit(queries[i], sids)
                except Exception as e:           # pragma: no cover
                    errors.append(e)

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(len(queries))]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert errors == []
            for q, expect, got in zip(queries, serial, results):
                ap, served = got
                assert served == {str(s.id) for s in sched_segments}
                assert _finish(q, ap) == expect
    finally:
        sched.stop()
