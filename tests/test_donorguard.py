"""donorguard unit battery: each buffer-ownership rule must fire on its
positive shape, stay quiet on the disciplined shapes, honor per-line
suppressions — and the REAL tree must fail when a verified ownership bug
is planted back in (and pass stock): an analyzer whose rules never fire
on the exact bugs it was built to catch is no gate.

Pattern mirrors tests/test_stallguard.py: check_source with a root-less
config analyzes each snippet standalone through the real rule registry,
so suppression/baseline behavior is exactly the shipped one. The
real-tree gates run donorguard's findings pass directly over
raceguard.analyze_sources of the in-memory druid_tpu tree with surgical
string mutations — each one the historical bug shape the rule exists
for (the pre-fix grouping dispatch, an inline backend check, a skipped
step-0 re-init, a cached-entry donation).

The DonorWitness tests drive the dynamic leg at two layers: the
registry protocol directly (take/park/dispatch/discard transitions,
violation shapes) and an installed witness against a fresh
DeviceSegmentPool bound as the process singleton.
"""
import gc
import sys
from pathlib import Path

import numpy as np
import pytest

REPO_ROOT = Path(__file__).resolve().parent.parent

sys.path.insert(0, str(REPO_ROOT))

from tools.druidlint import load_config  # noqa: E402
from tools.druidlint.core import LintConfig, check_source  # noqa: E402
from tools.druidlint.donorguard import donor_findings  # noqa: E402
from tools.druidlint.donorwitness import DonorWitness, _leaves  # noqa: E402
from tools.druidlint.raceguard import analyze_sources  # noqa: E402


def cfg(*rules) -> LintConfig:
    c = LintConfig(rules=list(rules) if rules else [])
    c.root = "/nonexistent-donorguard-root"
    return c


def findings_of(source: str, rule: str, path: str = "druid_tpu/mod.py",
                config: LintConfig = None):
    return [f for f in check_source(source, path, config or cfg(rule))
            if f.rule == rule]


#: the donating-builder shape every dispatch fixture leans on — a
#: function RETURNING a jit-with-donate, grouping._build_device_fn's form
_BUILDER = """\
import jax


def build():
    def fn(arrays, aux, carries):
        return carries
    return jax.jit(fn, donate_argnums=(2,))

"""


# ---------------------------------------------------------------------------
# read-after-donate
# ---------------------------------------------------------------------------

def test_read_after_donate_fires():
    src = _BUILDER + """
def run(pool, arrays, aux):
    fn = build()
    carried = pool.take("o", ("k",))
    out = fn(arrays, aux, carried)
    nbytes = sum(a.nbytes for a in carried)
    return out, nbytes
"""
    got = findings_of(src, "read-after-donate")
    assert len(got) == 1
    assert "no longer exists" in got[0].message


def test_read_before_dispatch_is_quiet():
    src = _BUILDER + """
def run(pool, arrays, aux):
    fn = build()
    carried = pool.take("o", ("k",))
    nbytes = sum(a.nbytes for a in carried)
    out = fn(arrays, aux, carried)
    return out, nbytes
"""
    assert findings_of(src, "read-after-donate") == []


def test_rebind_after_dispatch_is_quiet():
    # a Store kills the donated binding: later reads see the new value
    src = _BUILDER + """
def run(pool, arrays, aux, fresh):
    fn = build()
    carried = pool.take("o", ("k",))
    out = fn(arrays, aux, carried)
    carried = fresh()
    return out, carried
"""
    assert findings_of(src, "read-after-donate") == []


def test_post_dispatch_discard_is_quiet():
    # routing the reference through an explicit discard helper is the
    # blessed failure-path shape, not a read of donated content
    src = _BUILDER + """
def run(pool, arrays, aux, discard_carries):
    fn = build()
    carried = pool.take("o", ("k",))
    try:
        out = fn(arrays, aux, carried)
    except Exception:
        discard_carries(carried)
        raise
    return out
"""
    assert findings_of(src, "read-after-donate") == []


def test_read_after_donate_suppression():
    src = _BUILDER + """
def run(pool, arrays, aux):
    fn = build()
    carried = pool.take("o", ("k",))
    out = fn(arrays, aux, carried)
    nbytes = sum(a.nbytes
                 for a in carried)  # druidlint: disable=read-after-donate
    return out, nbytes
"""
    assert findings_of(src, "read-after-donate") == []


# ---------------------------------------------------------------------------
# donate-cached-entry
# ---------------------------------------------------------------------------

def test_cached_entry_into_donated_argnum_fires():
    src = _BUILDER + """
def run(pool, arrays, aux, make):
    fn = build()
    carried = pool.get_or_build("o", ("k",), make)
    return fn(arrays, aux, carried)
"""
    got = findings_of(src, "donate-cached-entry")
    assert len(got) == 1
    assert "take" in got[0].message


def test_cached_entry_derived_value_fires():
    # derivation propagates the taint: tuple(cached) is still the
    # pool-referenced buffers
    src = _BUILDER + """
def run(pool, arrays, aux, make):
    fn = build()
    cached = pool.device_cached(("k",), make)
    carried = tuple(cached)
    return fn(arrays, aux, carried)
"""
    assert len(findings_of(src, "donate-cached-entry")) == 1


def test_conditional_fallback_does_not_launder():
    # the `if carried is None` fresh-grids fallback does NOT dominate the
    # dispatch: the other branch still feeds the peeked entry in
    src = _BUILDER + """
def run(pool, arrays, aux, fresh):
    fn = build()
    carried = pool.peek("o", ("k",))
    if carried is None:
        carried = fresh()
    return fn(arrays, aux, carried)
"""
    assert len(findings_of(src, "donate-cached-entry")) == 1


def test_dominating_take_clears_taint():
    src = _BUILDER + """
def run(pool, arrays, aux):
    fn = build()
    carried = pool.peek("o", ("k",))
    carried = pool.take("o", ("k",))
    return fn(arrays, aux, carried)
"""
    assert findings_of(src, "donate-cached-entry") == []


def test_cached_entry_suppression():
    src = _BUILDER + """
def run(pool, arrays, aux, make):
    fn = build()
    carried = pool.get_or_build("o", ("k",), make)
    return fn(arrays, aux,
              carried)  # druidlint: disable=donate-cached-entry
"""
    assert findings_of(src, "donate-cached-entry") == []


# ---------------------------------------------------------------------------
# take-without-repark
# ---------------------------------------------------------------------------

def test_take_never_discharged_fires():
    # log() mentions the popped name but is no park/discard/dispatch —
    # mentioning ownership is not discharging it
    src = """\
def run(pool, log):
    carried = pool.take("o", ("k",))
    log(carried)
"""
    got = findings_of(src, "take-without-repark")
    assert len(got) == 1
    assert "no path" in got[0].message


def test_dispatch_in_try_without_handler_discharge_fires():
    src = _BUILDER + """
def run(pool, arrays, aux):
    fn = build()
    carried = pool.take("o", ("k",))
    try:
        out = fn(arrays, aux, carried)
    except Exception:
        out = None
    return out
"""
    got = findings_of(src, "take-without-repark")
    assert len(got) == 1
    assert "dispatch" in got[0].message


def test_handler_discard_covers_the_dispatch():
    src = _BUILDER + """
def run(pool, arrays, aux, discard_carries):
    fn = build()
    carried = pool.take("o", ("k",))
    try:
        out = fn(arrays, aux, carried)
    except Exception:
        discard_carries(carried)
        raise
    return out
"""
    assert findings_of(src, "take-without-repark") == []


def test_unprotected_dispatch_is_quiet():
    # no try around the dispatch: an exception unwinds out of run()
    # entirely — the caller owns the failure, not this frame
    src = _BUILDER + """
def run(pool, arrays, aux):
    fn = build()
    carried = pool.take("o", ("k",))
    out = fn(arrays, aux, carried)
    pool.put("o", ("k",), out)
"""
    assert findings_of(src, "take-without-repark") == []


def test_park_discharges_the_take():
    src = """\
def run(pool):
    carried = pool.take("o", ("k",))
    pool.put("o", ("k",), carried)
"""
    assert findings_of(src, "take-without-repark") == []


def test_take_without_repark_suppression():
    src = """\
def run(pool, log):
    c = pool.take("o", ("k",))  # druidlint: disable=take-without-repark
    log(c)
"""
    assert findings_of(src, "take-without-repark") == []


# ---------------------------------------------------------------------------
# donate-platform-gate
# ---------------------------------------------------------------------------

def test_inline_backend_check_fires():
    src = """\
import jax


def enabled():
    return jax.default_backend() in ("tpu", "gpu")
"""
    got = findings_of(src, "donate-platform-gate")
    assert len(got) == 1
    assert "donation_supported" in got[0].message


def test_platform_attribute_compare_fires():
    src = """\
def probe(dev):
    return dev.platform == "tpu"
"""
    assert len(findings_of(src, "donate-platform-gate")) == 1


def test_blessed_gate_is_quiet():
    # the shipped default pins contracts.donation_supported as THE gate
    src = """\
import jax


def donation_supported():
    return jax.default_backend() in ("tpu", "gpu")
"""
    assert findings_of(src, "donate-platform-gate",
                       path="druid_tpu/engine/contracts.py") == []


def test_sys_platform_is_not_a_backend_probe():
    src = """\
import sys


def f():
    return sys.platform == "linux"
"""
    assert findings_of(src, "donate-platform-gate") == []


def test_platform_gate_config_extension():
    c = cfg("donate-platform-gate")
    c.donorguard_platform_gate = list(c.donorguard_platform_gate) + [
        "druid_tpu/mod.py::my_gate"]
    src = """\
import jax


def my_gate():
    return jax.default_backend() == "tpu"
"""
    assert findings_of(src, "donate-platform-gate", config=c) == []


def test_platform_gate_suppression():
    src = """\
import jax


def enabled(t):
    ok = jax.default_backend() in t  # druidlint: disable=donate-platform-gate
    return ok
"""
    assert findings_of(src, "donate-platform-gate") == []


# ---------------------------------------------------------------------------
# carry-grid-init
# ---------------------------------------------------------------------------

def test_donated_pallas_without_step0_init_fires():
    src = """\
import jax
from jax.experimental import pallas as pl


def agg(arrays):
    def kernel(ref):
        ref[0] = ref[0] + 1
    return pl.pallas_call(kernel)(arrays)


def build():
    return jax.jit(agg, donate_argnums=(0,))
"""
    got = findings_of(src, "carry-grid-init")
    assert len(got) == 1
    assert "step 0" in got[0].message


def test_step0_init_reached_through_helper_fires():
    # whole-program: the pallas host sits one call edge below the
    # donated entry point and is still reached
    src = """\
import jax
from jax.experimental import pallas as pl


def leaf(arrays):
    def kernel(ref):
        ref[0] = ref[0] + 1
    return pl.pallas_call(kernel)(arrays)


def agg(arrays):
    return leaf(arrays)


def build():
    return jax.jit(agg, donate_argnums=(0,))
"""
    got = findings_of(src, "carry-grid-init")
    assert len(got) == 1
    assert "leaf" in got[0].message


def test_step0_init_present_is_quiet():
    src = """\
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def agg(arrays):
    def kernel(ref):
        i = pl.program_id(0)

        @pl.when(i == jnp.int32(0))
        def _init():
            ref[0] = 0
    return pl.pallas_call(kernel)(arrays)


def build():
    return jax.jit(agg, donate_argnums=(0,))
"""
    assert findings_of(src, "carry-grid-init") == []


def test_non_donating_jit_is_quiet():
    src = """\
import jax
from jax.experimental import pallas as pl


def agg(arrays):
    def kernel(ref):
        ref[0] = ref[0] + 1
    return pl.pallas_call(kernel)(arrays)


def build():
    return jax.jit(agg)
"""
    assert findings_of(src, "carry-grid-init") == []


def test_carry_grid_init_suppression():
    # a fresh-init-by-design kernel declares itself on the pallas_call
    src = """\
import jax
from jax.experimental import pallas as pl


def agg(arrays):
    def kernel(ref):
        ref[0] = ref[0] + 1
    return pl.pallas_call(  # druidlint: disable=carry-grid-init
        kernel)(arrays)
"""
    # the jit sits in another module shape — keep it in this one
    src += """

def build():
    return jax.jit(agg, donate_argnums=(0,))
"""
    assert findings_of(src, "carry-grid-init") == []


# ---------------------------------------------------------------------------
# real-tree mutation gates: plant each rule's historical bug shape back
# into the ACTUAL druid_tpu sources and donorguard must catch it; the
# stock tree must be clean
# ---------------------------------------------------------------------------

def _tree_sources():
    return {p.relative_to(REPO_ROOT).as_posix(): p.read_text()
            for p in sorted((REPO_ROOT / "druid_tpu").rglob("*.py"))}


def _tree_findings(sources):
    config = load_config(REPO_ROOT)
    return donor_findings(analyze_sources(sources, config), config)


def _mutate(sources, path, old, new, count=1):
    src = sources[path]
    assert src.count(old) == count, (
        f"mutation anchor drifted in {path}: {old!r} found "
        f"{src.count(old)}x, expected {count}")
    sources[path] = src.replace(old, new)
    return sources


def test_real_tree_is_donorguard_clean():
    assert _tree_findings(_tree_sources()) == {}


def test_prefix_dispatch_shape_fires_read_after_donate_and_repark():
    # the pre-PR shape: no exception-path discard, donated bytes summed
    # AFTER the dispatch — both ownership bugs donorguard was built for
    sources = _mutate(
        _tree_sources(), "druid_tpu/engine/grouping.py",
        """                    donated_nbytes = sum(
                        int(getattr(a, "nbytes", 0))
                        for a in carried) if donated else 0
                    try:
                        counts, states, raw = fn(arrays, aux,
                                                 tuple(carried))
                    except BaseException:
                        # the take popped ownership; a dispatch failure
                        # (Mosaic compile error below) may have already
                        # invalidated the donated buffers mid-flight, so
                        # discharge them explicitly — the pool's resident
                        # bytes stay truthful and the next tick rebuilds
                        # fresh zeros (donorguard take-without-repark)
                        megakernel.discard_carries(carried)
                        raise
""",
        """                    counts, states, raw = fn(arrays, aux,
                                             tuple(carried))
                    donated_nbytes = sum(
                        int(getattr(a, "nbytes", 0))
                        for a in carried) if donated else 0
""")
    data = _tree_findings(sources)
    assert "druid_tpu/engine/grouping.py" in data.get("read-after-donate",
                                                      {})
    # BOTH takes (the pool pop and the standing-donor pop) now leak on
    # the Mosaic-retry exception path
    repark = data.get("take-without-repark", {}).get(
        "druid_tpu/engine/grouping.py", [])
    assert len(repark) == 2


def test_cached_entry_mutation_fires():
    # take→device_cached: the dispatch would donate buffers the pool
    # still references
    sources = _tree_sources()
    _mutate(sources, "druid_tpu/engine/grouping.py",
            'carried = segment.device_take(("megacarry", sig))',
            'carried = segment.device_cached(("megacarry", sig), '
            'lambda: None)')
    _mutate(sources, "druid_tpu/engine/grouping.py",
            'carried = donor.device_take(("megacarry", sig))',
            'carried = donor.device_cached(("megacarry", sig), '
            'lambda: None)')
    data = _tree_findings(sources)
    assert "druid_tpu/engine/grouping.py" in data.get("donate-cached-entry",
                                                      {})


def test_inline_platform_gate_mutation_fires():
    # scatter the donation-enable decision back inline: the CPU-segfault
    # class donate-platform-gate centralizes away
    sources = _mutate(
        _tree_sources(), "druid_tpu/engine/megakernel.py",
        "    return donation_supported()",
        '    return jax.default_backend() in ("tpu", "gpu")')
    data = _tree_findings(sources)
    assert "druid_tpu/engine/megakernel.py" in data.get(
        "donate-platform-gate", {})


def test_missing_step0_init_mutation_fires():
    # break the PR 11 bit-identity discipline: the init block no longer
    # runs at grid step 0, so donated reuse replays stale aggregates
    sources = _mutate(
        _tree_sources(), "druid_tpu/engine/megakernel.py",
        "@pl.when(i == jnp.int32(0))",
        "@pl.when(i == jnp.int32(1))")
    data = _tree_findings(sources)
    assert "druid_tpu/engine/megakernel.py" in data.get("carry-grid-init",
                                                        {})


# ---------------------------------------------------------------------------
# DonorWitness: the dynamic leg
# ---------------------------------------------------------------------------

class _Leaf:
    """Weakref-able array stand-in with a device-buffer delete()."""

    def __init__(self, shape=(4,)):
        self.dtype = "int32"
        self.shape = shape
        self.deleted = False

    def delete(self):
        self.deleted = True


def test_leaves_recurses_containers():
    a, b, c = _Leaf(), _Leaf(), _Leaf()
    got = _leaves(((a, [b]), {"x": c, "y": "not-an-array"}))
    assert got == [a, b, c]


def test_witness_clean_cycle_take_dispatch_repark():
    w = DonorWitness("r")
    leaf = _Leaf()
    w._note_park((leaf,))               # built fresh, parked
    assert id(leaf) in w.resident
    w._note_take((leaf,), "k")          # popped: caller owns it
    assert id(leaf) in w.outstanding and id(leaf) not in w.resident
    w._before_dispatch((leaf,))         # not resident: no violation
    w._after_dispatch((leaf,))          # donation consumed it
    assert leaf.deleted                 # simulated invalidation
    assert w.outstanding == {}
    assert w.all_violations() == []
    assert w.counts["donated-delete"] == 1


def test_witness_cached_entry_donation_violates():
    w = DonorWitness("r")
    leaf = _Leaf()
    w._note_park((leaf,))
    w._before_dispatch((leaf,))         # donated while still pool-resident
    assert any("cached-entry donation" in v for v in w.all_violations())
    w._after_dispatch((leaf,))
    assert not leaf.deleted             # never owned: witness won't touch it


def test_witness_gc_while_outstanding_violates():
    w = DonorWitness("r")
    leaf = _Leaf()
    w._note_take((leaf,), "k")
    del leaf
    gc.collect()
    assert any("garbage-collected while outstanding" in v
               for v in w.all_violations())


def test_witness_unreparked_at_teardown():
    w = DonorWitness("r")
    leaf = _Leaf()
    w._note_take((leaf,), "('o', 'k')")
    got = w.unreparked()
    assert len(got) == 1 and "still outstanding" in got[0]
    assert "('o', 'k')" in got[0]


def test_witness_explicit_discard_discharges():
    w = DonorWitness("r")
    leaf = _Leaf()
    w._note_take((leaf,), "k")
    w._discharge((leaf,), "discard")
    assert w.all_violations() == []
    assert w.counts["discard"] == 1


def test_witness_skips_numpy_leaves():
    # host ndarrays refuse weakrefs and carry no device buffer — the
    # protocol governs device buffers only
    w = DonorWitness("r")
    w._note_take((np.zeros(4, dtype=np.int32),), "k")
    assert w.outstanding == {}
    assert w.all_violations() == []


def test_witness_install_is_reversible():
    from druid_tpu.data import devicepool
    from druid_tpu.engine import grouping, megakernel
    before = (devicepool.DeviceSegmentPool.take,
              devicepool.DeviceSegmentPool.get_or_build,
              grouping._build_device_fn, megakernel.discard_carries)
    with DonorWitness("r") as w:
        assert devicepool.DeviceSegmentPool.take is not before[0]
        assert w._installed
    after = (devicepool.DeviceSegmentPool.take,
             devicepool.DeviceSegmentPool.get_or_build,
             grouping._build_device_fn, megakernel.discard_carries)
    assert after == before


def test_witness_end_to_end_on_singleton_pool(monkeypatch):
    # a fresh pool bound as the process singleton: real take/get_or_build
    # traffic is witnessed; other pool instances stay invisible
    import jax.numpy as jnp
    from druid_tpu.data import devicepool
    pool = devicepool.DeviceSegmentPool(budget_bytes=0)
    other = devicepool.DeviceSegmentPool(budget_bytes=0)
    monkeypatch.setattr(devicepool, "_POOL", pool)

    class _Anchor:                    # bare object() refuses weakrefs
        pass

    anchor, oanchor = _Anchor(), _Anchor()
    owner = pool.register_owner(anchor)
    oowner = other.register_owner(oanchor)
    with DonorWitness("r") as w:
        entry = pool.get_or_build(owner, ("k",),
                                  lambda: (jnp.zeros(4, jnp.int32),))
        assert len(w.resident) == 1
        other.get_or_build(oowner, ("k",),
                           lambda: (jnp.ones(4, jnp.int32),))
        assert len(w.resident) == 1          # non-singleton: unrecorded
        popped = pool.take(owner, ("k",))
        assert popped is entry
        assert len(w.outstanding) == 1 and w.resident == {}
        assert w.unreparked()                # owed until re-parked...
        pool.get_or_build(owner, ("k",), lambda: popped)
        assert w.unreparked() == []          # ...and discharged by it
    assert w.all_violations() == []
    assert w.counts == {"take": 1, "repark": 2}
