"""Indexing service tests: batch index, compaction, kill, locks
(reference: IndexTaskTest, CompactionTaskTest, TaskLockbox tests)."""
import os

import numpy as np
import pytest

from druid_tpu.cluster import MetadataStore
from druid_tpu.engine import QueryExecutor
from druid_tpu.indexing import (CompactionTask, IndexTask, KillTask, Overlord,
                                TaskLockbox, task_from_json)
from druid_tpu.indexing.task import IndexTuningConfig
from druid_tpu.ingest import InlineFirehose
from druid_tpu.query.aggregators import CountAggregator, LongSumAggregator
from druid_tpu.query.model import TimeseriesQuery
from druid_tpu.storage.deep import InMemoryDeepStorage, LocalDeepStorage
from druid_tpu.utils.intervals import Interval

SPECS = [CountAggregator("rows"), LongSumAggregator("v", "value")]
QSPECS = [LongSumAggregator("rows", "rows"), LongSumAggregator("v", "v")]
WEEK = Interval.of("2026-04-01", "2026-04-08")
T0 = WEEK.start


def _records(n, days=3, seed=0):
    rng = np.random.default_rng(seed)
    day = 86_400_000
    return [{"timestamp": int(T0 + (i % days) * day + i * 1000 % day),
             "page": f"p{int(rng.integers(10))}",
             "value": int(rng.integers(0, 10))} for i in range(n)]


def _overlord():
    md = MetadataStore()
    return md, Overlord(md, InMemoryDeepStorage())


def _pull_all(md, deep, ds):
    return [deep.pull(d) for d in md.used_segments(ds)]


def test_index_task_end_to_end():
    md, ov = _overlord()
    recs = _records(3000, days=3)
    task = IndexTask("batch_ds", InlineFirehose(recs), None, SPECS,
                     segment_granularity="day")
    status = ov.run_task(task)
    assert status.state == "SUCCESS", status.error
    descs = md.used_segments("batch_ds")
    assert len(descs) == 3                      # one segment per day
    assert all(d.version == descs[0].version for d in descs)
    segs = _pull_all(md, ov.deep_storage, "batch_ds")
    rows = QueryExecutor(segs).run(TimeseriesQuery.of("batch_ds", [WEEK], QSPECS))
    assert rows[0]["result"]["rows"] == 3000
    assert rows[0]["result"]["v"] == sum(r["value"] for r in recs)


def test_parallel_index_on_overlord_pool_of_one():
    """Sub-tasks run on dedicated threads, so a ParallelIndexTask must
    complete even when the overlord pool has a single worker (the
    supervisor occupies it for its whole run)."""
    from druid_tpu.indexing import ParallelIndexTask
    md = MetadataStore()
    ov = Overlord(md, InMemoryDeepStorage(), max_workers=1)
    recs = _records(1200, days=2)
    task = ParallelIndexTask("pov_ds", InlineFirehose(recs), None, SPECS,
                             segment_granularity="day", max_num_subtasks=3)
    status = ov.run_task(task, timeout=120)
    assert status.state == "SUCCESS", status.error
    segs = _pull_all(md, ov.deep_storage, "pov_ds")
    rows = QueryExecutor(segs).run(
        TimeseriesQuery.of("pov_ds", [WEEK], QSPECS))
    assert rows[0]["result"]["rows"] == 1200
    # appended sub-task locks are all released
    assert ov.lockbox.all_locks() == [] if hasattr(ov.lockbox, "all_locks") \
        else True


def test_index_task_partitions_large_buckets():
    md, ov = _overlord()
    recs = _records(2000, days=1)
    task = IndexTask("big_ds", InlineFirehose(recs), None, SPECS,
                     segment_granularity="day",
                     tuning=IndexTuningConfig(max_rows_per_segment=600))
    assert ov.run_task(task).state == "SUCCESS"
    descs = md.used_segments("big_ds")
    assert len(descs) >= 3                      # 2000/600 → ≥4 partitions
    assert sorted(d.partition for d in descs) == list(range(len(descs)))
    segs = _pull_all(md, ov.deep_storage, "big_ds")
    rows = QueryExecutor(segs).run(
        TimeseriesQuery.of("big_ds", [WEEK], QSPECS))
    assert rows[0]["result"]["rows"] == 2000


def test_index_replace_overshadows():
    """Re-indexing the same interval produces a newer version that
    overshadows the old one (MVCC batch replace)."""
    md, ov = _overlord()
    ov.run_task(IndexTask("r_ds", InlineFirehose(_records(500, days=1)),
                          None, SPECS, segment_granularity="day"))
    v1 = md.used_segments("r_ds")[0].version
    import time
    time.sleep(0.002)  # newer wall-clock version
    ov.run_task(IndexTask("r_ds", InlineFirehose(_records(200, days=1,
                                                          seed=9)),
                          None, SPECS, segment_granularity="day"))
    descs = md.used_segments("r_ds")
    versions = {d.version for d in descs}
    assert len(versions) == 2
    # coordinator cleanup marks the overshadowed version unused
    from druid_tpu.cluster import Coordinator, InventoryView
    coord = Coordinator(md, InventoryView(), lambda d: None)
    stats = coord.run_once()
    assert stats.overshadowed_marked == 1
    remaining = md.used_segments("r_ds")
    assert len(remaining) == 1 and remaining[0].version != v1


def test_compaction_task():
    md, ov = _overlord()
    # ingest day-granularity, three runs appending into one day via allocate
    day = Interval.of("2026-04-01", "2026-04-02")
    for seed in (1, 2, 3):
        t = IndexTask("c_ds", InlineFirehose(_records(300, days=1, seed=seed)),
                      None, SPECS, segment_granularity="day", appending=True)
        assert ov.run_task(t).state == "SUCCESS"
    assert len(md.used_segments("c_ds")) == 3
    before = QueryExecutor(_pull_all(md, ov.deep_storage, "c_ds")).run(
        TimeseriesQuery.of("c_ds", [WEEK], QSPECS))
    import time
    time.sleep(0.002)
    ct = CompactionTask("c_ds", day, QSPECS)   # combining specs re-aggregate
    assert ov.run_task(ct).state == "SUCCESS"
    # old segments overshadowed by compacted one
    from druid_tpu.cluster import Coordinator, InventoryView
    Coordinator(md, InventoryView(), lambda d: None).run_once()
    descs = md.used_segments("c_ds")
    assert len(descs) == 1
    after = QueryExecutor([ov.deep_storage.pull(descs[0])]).run(
        TimeseriesQuery.of("c_ds", [WEEK], QSPECS))
    assert after == before


def test_kill_task():
    md, ov = _overlord()
    ov.run_task(IndexTask("k_ds", InlineFirehose(_records(100, days=1)),
                          None, SPECS, segment_granularity="day"))
    desc = md.used_segments("k_ds")[0]
    md.mark_unused([desc.id])
    assert ov.run_task(KillTask("k_ds", WEEK)).state == "SUCCESS"
    assert md.used_segments("k_ds") == []
    assert ov.deep_storage.pull(desc) is None


def test_archive_move_restore_lifecycle(tmp_path):
    """Unused segments archive to a second location, restore back to base,
    and serve again — files follow, loadSpecs track them
    (reference ArchiveTask / MoveTask / RestoreTask)."""
    from druid_tpu.indexing import ArchiveTask, MoveTask, RestoreTask
    md = MetadataStore()
    deep = LocalDeepStorage(str(tmp_path / "base"))
    ov = Overlord(md, deep)
    ov.run_task(IndexTask("a_ds", InlineFirehose(_records(200, days=1)),
                          None, SPECS, segment_granularity="day"))
    desc = md.used_segments("a_ds")[0]
    live_path = desc.load_spec["path"]
    n_rows = deep.pull(desc).n_rows

    # archive is a no-op while the segment is still used
    assert ov.run_task(ArchiveTask("a_ds", WEEK)).state == "SUCCESS"
    assert md.used_segments("a_ds")[0].load_spec["path"] == live_path

    md.mark_unused([desc.id])
    assert ov.run_task(ArchiveTask("a_ds", WEEK)).state == "SUCCESS"
    archived = md.unused_segments("a_ds")[0]
    assert "base_archive" in archived.load_spec["path"]
    assert not os.path.isdir(live_path)
    assert os.path.isdir(archived.load_spec["path"])

    # move to an explicit third location
    cold = str(tmp_path / "cold")
    assert ov.run_task(MoveTask("a_ds", WEEK, cold)).state == "SUCCESS"
    moved = md.unused_segments("a_ds")[0]
    assert moved.load_spec["path"].startswith(cold)

    # restore: files return to base, segment is used again and pullable
    assert ov.run_task(RestoreTask("a_ds", WEEK)).state == "SUCCESS"
    assert md.unused_segments("a_ds") == []
    restored = md.used_segments("a_ds")[0]
    assert restored.load_spec["path"] == live_path
    assert deep.pull(restored).n_rows == n_rows


def test_archive_crash_idempotent_rerun(tmp_path):
    """Files moved but metadata not yet updated (crash window): re-running
    the archive completes the move instead of stranding the segment; a
    genuinely missing segment fails loudly instead of green-skipping."""
    import shutil
    from druid_tpu.indexing import ArchiveTask, MoveTask
    md = MetadataStore()
    deep = LocalDeepStorage(str(tmp_path / "base"))
    ov = Overlord(md, deep)
    ov.run_task(IndexTask("c_ds", InlineFirehose(_records(100, days=1)),
                          None, SPECS, segment_granularity="day"))
    desc = md.used_segments("c_ds")[0]
    md.mark_unused([desc.id])
    # simulate the crashed first run: files at the archive destination,
    # metadata still pointing at base
    src = desc.load_spec["path"]
    dst = src.replace(str(tmp_path / "base"), str(tmp_path / "base_archive"))
    os.makedirs(os.path.dirname(dst), exist_ok=True)
    shutil.move(src, dst)
    assert ov.run_task(ArchiveTask("c_ds", WEEK)).state == "SUCCESS"
    healed = md.unused_segments("c_ds")[0]
    assert healed.load_spec["path"] == dst
    assert deep.pull(healed).n_rows == 100
    # genuinely gone → FAILED, not silent success
    shutil.rmtree(dst)
    st = ov.run_task(MoveTask("c_ds", WEEK, str(tmp_path / "cold")))
    assert st.state == "FAILED" and "missing" in st.error


def test_task_json_roundtrip_move_archive_restore():
    from druid_tpu.indexing import ArchiveTask, MoveTask, RestoreTask
    from druid_tpu.indexing.task import task_from_json
    for t in (MoveTask("ds", WEEK, "cold"), ArchiveTask("ds", WEEK),
              RestoreTask("ds", WEEK)):
        rt = task_from_json(t.to_json())
        assert type(rt) is type(t)
        assert rt.id == t.id and rt.datasource == "ds"
        assert str(rt.interval) == str(WEEK)
    assert task_from_json(MoveTask("ds", WEEK, "cold").to_json()).target \
        == "cold"


def test_lockbox_priority_revocation():
    lb = TaskLockbox()
    day = Interval.of("2026-04-01", "2026-04-02")
    low = lb.acquire("compact1", "ds", day, priority=25)
    assert low is not None
    # equal priority conflicts
    assert lb.acquire("compact2", "ds", day, priority=25) is None
    # higher priority revokes
    high = lb.acquire("index1", "ds", day, priority=50)
    assert high is not None
    assert lb.is_revoked("compact1")
    lb.release_all("index1")
    lb.release_all("compact1")
    # disjoint intervals coexist
    a = lb.acquire("t1", "ds", Interval.of("2026-04-01", "2026-04-02"))
    b = lb.acquire("t2", "ds", Interval.of("2026-04-02", "2026-04-03"))
    assert a is not None and b is not None


def test_compaction_loses_lock_race_to_index():
    """A compaction holding a lock gets revoked by a batch index; its
    publish must be refused."""
    md, ov = _overlord()
    ov.run_task(IndexTask("race_ds", InlineFirehose(_records(100, days=1)),
                          None, SPECS, segment_granularity="day"))
    day = Interval.of("2026-04-01", "2026-04-02")
    tb = ov.toolbox()
    ct = CompactionTask("race_ds", day, QSPECS)
    lock = tb.lock(ct, [day])
    assert lock is not None
    it = IndexTask("race_ds", InlineFirehose(_records(50, days=1)), None,
                   SPECS, segment_granularity="day")
    assert tb.lock(it, [day]) is not None      # revokes compaction
    assert tb.lockbox.is_revoked(ct.id)
    assert not tb.publish(ct, [])              # refused


def test_local_deep_storage_round_trip(tmp_path):
    md = MetadataStore()
    ov = Overlord(md, LocalDeepStorage(str(tmp_path)))
    recs = _records(500, days=2)
    assert ov.run_task(
        IndexTask("disk_ds", InlineFirehose(recs), None, SPECS,
                  segment_granularity="day")).state == "SUCCESS"
    descs = md.used_segments("disk_ds")
    assert all(d.load_spec["type"] == "local" for d in descs)
    assert all(d.size_bytes > 0 for d in descs)
    segs = [ov.deep_storage.pull(d) for d in descs]
    rows = QueryExecutor(segs).run(
        TimeseriesQuery.of("disk_ds", [WEEK], QSPECS))
    assert rows[0]["result"]["rows"] == 500


def test_auto_compaction_scheduling():
    md, ov = _overlord()
    for seed in (1, 2):
        ov.run_task(IndexTask("ac_ds",
                              InlineFirehose(_records(200, days=2, seed=seed)),
                              None, SPECS, segment_granularity="day",
                              appending=True))
    from druid_tpu.cluster import Coordinator, InventoryView
    coord = Coordinator(md, InventoryView(), lambda d: None)
    import time
    time.sleep(0.002)
    task_ids = coord.schedule_compaction(ov, "ac_ds", QSPECS, max_tasks=2)
    assert len(task_ids) == 2
    for tid in task_ids:
        assert ov.await_task(tid).state == "SUCCESS"
    coord.run_once()
    descs = md.used_segments("ac_ds")
    assert len(descs) == 2      # one compacted segment per day
    rows = QueryExecutor([ov.deep_storage.pull(d) for d in descs]).run(
        TimeseriesQuery.of("ac_ds", [WEEK], QSPECS))
    assert rows[0]["result"]["rows"] == 400


def test_hash_partitioning_matches_shard_pruning():
    """Rows routed by IndexTask's hash MUST satisfy the published
    HashBasedNumberedShardSpec, or broker shard pruning drops data."""
    md, ov = _overlord()
    recs = _records(2000, days=1, seed=4)
    ov.run_task(IndexTask(
        "h_ds", InlineFirehose(recs), None, SPECS,
        segment_granularity="day",
        tuning=IndexTuningConfig(max_rows_per_segment=500,
                                 partition_dimensions=("page",))))
    descs = md.used_segments("h_ds")
    assert len(descs) >= 3
    # every row must be in the chunk its shard spec claims
    for d in descs:
        seg = ov.deep_storage.pull(d)
        if seg.n_rows == 0:     # empty partitions complete the numbered set
            continue
        col = seg.dims["page"]
        for vid in np.unique(col.ids):
            v = col.dictionary.values[vid]
            assert d.shard_spec.is_in_chunk({"page": v}), (d.id, v)
    # broker with pruning returns exact filtered counts
    from druid_tpu.cluster import Broker, DataNode, InventoryView
    from druid_tpu.query.filters import SelectorFilter
    view = InventoryView()
    node = DataNode("n0")
    view.register(node)
    for d in descs:
        node.load_segment(ov.deep_storage.pull(d))
        view.announce("n0", d)
    broker = Broker(view)
    for page in ("p0", "p7"):
        q = TimeseriesQuery.of("h_ds", [WEEK], QSPECS,
                               filter=SelectorFilter("page", page))
        got = broker.run(q)[0]["result"]["rows"]
        want = sum(1 for r in recs if r["page"] == page)
        assert got == want, (page, got, want)


def test_compaction_skips_overshadowed_versions():
    """Compacting while an overshadowed version is still marked used must
    NOT resurrect the replaced data."""
    md, ov = _overlord()
    ov.run_task(IndexTask("ov_ds", InlineFirehose(_records(400, days=1)),
                          None, SPECS, segment_granularity="day"))
    import time
    time.sleep(0.002)
    ov.run_task(IndexTask("ov_ds", InlineFirehose(_records(100, days=1,
                                                           seed=8)),
                          None, SPECS, segment_granularity="day"))
    assert len(md.used_segments("ov_ds")) == 2      # v1 not yet cleaned
    time.sleep(0.002)
    day = Interval.of("2026-04-01", "2026-04-02")
    assert ov.run_task(CompactionTask("ov_ds", day, QSPECS)).state == "SUCCESS"
    from druid_tpu.cluster import Coordinator, InventoryView
    Coordinator(md, InventoryView(), lambda d: None).run_once()
    descs = md.used_segments("ov_ds")
    assert len(descs) == 1
    rows = QueryExecutor([ov.deep_storage.pull(descs[0])]).run(
        TimeseriesQuery.of("ov_ds", [WEEK], QSPECS))
    assert rows[0]["result"]["rows"] == 100          # NOT 500


def test_streaming_publishes_to_deep_storage():
    """Streamed segments must be durably pushed so the coordinator can load
    them without the ingest process."""
    from druid_tpu.ingest import (SimulatedStream, StreamSupervisor,
                                  StreamSupervisorSpec, StreamTuningConfig)
    from druid_tpu.cluster import (Coordinator, DataNode, DynamicConfig,
                                   InventoryView)
    md = MetadataStore()
    deep = InMemoryDeepStorage()
    stream = SimulatedStream(n_partitions=1)
    stream.append(0, _records(150, days=1, seed=3))
    sup = StreamSupervisor(
        StreamSupervisorSpec("s_ds", SPECS, dimensions=["page"],
                             tuning=StreamTuningConfig(
                                 segment_granularity="day")),
        stream, md, deep_storage=deep)
    sup.run_once()
    assert sup.checkpoint_all()
    descs = md.used_segments("s_ds")
    assert descs and all(d.load_spec is not None for d in descs)
    # coordinator loads from deep storage with no ingest process involved
    view = InventoryView()
    node = DataNode("hist")
    view.register(node)
    md.set_rules("_default", [{"type": "loadForever",
                               "tieredReplicants": {"_default_tier": 1}}])
    coord = Coordinator(md, view, deep.pull,
                        DynamicConfig(replication_throttle_limit=100))
    stats = coord.run_once()
    assert stats.assigned == len(descs) and stats.unassigned == 0
    from druid_tpu.cluster import Broker
    rows = Broker(view).run(TimeseriesQuery.of("s_ds", [WEEK], QSPECS))
    assert rows[0]["result"]["rows"] == 150


def test_task_from_json():
    t = task_from_json({
        "type": "index",
        "spec": {"dataSchema": {
            "dataSource": "j_ds",
            "metricsSpec": [{"type": "count", "name": "rows"}],
            "granularitySpec": {"segmentGranularity": "hour"}},
            "ioConfig": {"firehose": {
                "type": "inline",
                "data": [{"timestamp": T0, "d": "x"}]}}}})
    assert isinstance(t, IndexTask)
    assert str(t.segment_granularity) == "hour"
    t2 = task_from_json({"type": "kill", "dataSource": "x",
                         "interval": str(WEEK)})
    assert isinstance(t2, KillTask)


def test_kill_task_takes_interval_lock():
    """KillTask must exclude concurrent move/restore over the interval
    (without the lock a kill interleaving with a move orphans the moved
    files)."""
    md, ov = _overlord()
    ov.run_task(IndexTask("kl_ds", InlineFirehose(_records(50, days=1)),
                          None, SPECS, segment_granularity="day"))
    desc = md.used_segments("kl_ds")[0]
    md.mark_unused([desc.id])
    blocker = ov.lockbox.acquire("someone_else", "kl_ds", WEEK, priority=99)
    assert blocker is not None
    st = ov.run_task(KillTask("kl_ds", WEEK))
    assert st.state == "FAILED" and "lock" in st.error
    ov.lockbox.release_all("someone_else")
    assert ov.run_task(KillTask("kl_ds", WEEK)).state == "SUCCESS"
    assert md.unused_segments("kl_ds") == []
