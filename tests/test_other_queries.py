"""Scan, select, search, timeBoundary, segmentMetadata, dataSourceMetadata,
HLL cardinality, and JSON wire-format round trips."""
import numpy as np
import pytest

from druid_tpu.engine.executor import QueryExecutor
from druid_tpu.query import (CardinalityAggregator, CountAggregator,
                             FilteredAggregator, HyperUniqueAggregator,
                             LongSumAggregator, SelectorFilter, agg_from_json,
                             filter_from_json)
from druid_tpu.query.model import (DataSourceMetadataQuery, ScanQuery,
                                   SearchQuery, SegmentMetadataQuery,
                                   SelectQuery, TimeBoundaryQuery,
                                   TimeseriesQuery, TopNQuery, GroupByQuery,
                                   query_from_json)
from druid_tpu.utils.intervals import Interval

from conftest import DAY, rows_as_frame


def test_scan_basic(segment):
    ex = QueryExecutor([segment])
    q = ScanQuery.of("test", DAY, columns=["__time", "dimA", "metLong"], limit=100)
    out = ex.run(q)
    assert out
    events = [e for batch in out for e in batch["events"]]
    assert len(events) == 100
    frame = rows_as_frame(segment)
    assert events[0]["dimA"] == frame["dimA"][0]
    assert events[0]["metLong"] == int(frame["metLong"][0])
    assert events[0]["__time"] == int(frame["__time"][0])


def test_scan_filtered_and_offset(segment):
    ex = QueryExecutor([segment])
    q = ScanQuery.of("test", DAY, columns=["dimA"], limit=10, offset=5,
                     filter=SelectorFilter("dimA", "v00000004"))
    out = ex.run(q)
    events = [e for batch in out for e in batch["events"]]
    assert len(events) == 10
    assert all(e["dimA"] == "v00000004" for e in events)


def test_select_paging(segment):
    ex = QueryExecutor([segment])
    q = SelectQuery.of("test", DAY, dimensions=["dimA"], metrics=["metLong"],
                       threshold=50)
    out = ex.run(q)
    res = out[0]["result"]
    assert len(res["events"]) == 50
    pid = res["pagingIdentifiers"]
    q2 = SelectQuery.of("test", DAY, dimensions=["dimA"], metrics=["metLong"],
                        threshold=50, paging_spec=pid)
    res2 = ex.run(q2)[0]["result"]
    assert len(res2["events"]) == 50
    assert res2["events"][0]["offset"] == res["events"][-1]["offset"] + 1


def test_search(segment):
    ex = QueryExecutor([segment])
    q = SearchQuery.of("test", DAY, value="0003",
                       search_dimensions=["dimA", "dimB"])
    out = ex.run(q)
    entries = out[0]["result"]
    assert {e["value"] for e in entries if e["dimension"] == "dimA"} == {"v00000003"}
    frame = rows_as_frame(segment)
    for e in entries:
        expected = int((frame[e["dimension"]] == e["value"]).sum())
        assert e["count"] == expected


def test_time_boundary(segments):
    ex = QueryExecutor(segments)
    out = ex.run(TimeBoundaryQuery.of("test"))
    res = out[0]["result"]
    assert res["minTime"] == min(s.min_time for s in segments)
    assert res["maxTime"] == max(s.max_time for s in segments)
    out2 = ex.run(TimeBoundaryQuery.of("test", bound="maxTime"))
    assert out2[0]["result"] == {"maxTime": res["maxTime"]}


def test_segment_metadata(segment):
    ex = QueryExecutor([segment])
    out = ex.run(SegmentMetadataQuery.of("test"))
    assert len(out) == 1
    a = out[0]
    assert a["numRows"] == segment.n_rows
    assert a["columns"]["dimA"]["cardinality"] == 10
    assert a["columns"]["metLong"]["type"] == "LONG"
    assert a["columns"]["__time"]["minValue"] == segment.min_time


def test_segment_metadata_merge(segments):
    ex = QueryExecutor(segments)
    out = ex.run(SegmentMetadataQuery.of("test", merge=True))
    assert len(out) == 1
    assert out[0]["numRows"] == sum(s.n_rows for s in segments)


def test_datasource_metadata(segments):
    ex = QueryExecutor(segments)
    out = ex.run(DataSourceMetadataQuery.of("test"))
    assert out[0]["result"]["maxIngestedEventTime"] == max(
        s.max_time for s in segments)


def test_cardinality_agg(segment):
    ex = QueryExecutor([segment])
    q = TimeseriesQuery.of("test", DAY, [
        CardinalityAggregator("cardB", ("dimB",)),
        CardinalityAggregator("cardHi", ("dimHi",)),
    ])
    rows = ex.run(q)
    frame = rows_as_frame(segment)
    truth_b = len(set(frame["dimB"]))
    truth_hi = len(set(frame["dimHi"]))
    assert rows[0]["result"]["cardB"] == pytest.approx(truth_b, rel=0.05)
    assert rows[0]["result"]["cardHi"] == pytest.approx(truth_hi, rel=0.05)


def test_cardinality_multi_segment_fold(segments):
    """HLL registers must fold across segments without double counting —
    the same value in two segments counts once (hashes are value-based)."""
    ex = QueryExecutor(segments)
    iv = Interval.of("2026-01-01", "2026-01-05")
    q = TimeseriesQuery.of("test", iv, [CardinalityAggregator("card", ("dimB",))])
    rows = ex.run(q)
    truth = len({v for s in segments for v in
                 np.asarray(s.dims["dimB"].dictionary.values, dtype=object)[
                     np.unique(s.dims["dimB"].ids)]})
    assert rows[0]["result"]["card"] == pytest.approx(truth, rel=0.05)


def test_cardinality_by_row(segment):
    ex = QueryExecutor([segment])
    q = TimeseriesQuery.of("test", DAY, [
        CardinalityAggregator("c", ("dimA", "dimB"), by_row=True)])
    rows = ex.run(q)
    frame = rows_as_frame(segment)
    truth = len(set(zip(frame["dimA"], frame["dimB"])))
    assert rows[0]["result"]["c"] == pytest.approx(truth, rel=0.07)


def test_filtered_aggregator(segment):
    ex = QueryExecutor([segment])
    agg = FilteredAggregator("f", LongSumAggregator("f", "metLong"),
                             SelectorFilter("dimA", "v00000001"))
    q = TimeseriesQuery.of("test", DAY, [CountAggregator("rows"), agg])
    rows = ex.run(q)
    frame = rows_as_frame(segment)
    mask = frame["dimA"] == "v00000001"
    assert rows[0]["result"]["f"] == int(frame["metLong"][mask].sum())
    assert rows[0]["result"]["rows"] == segment.n_rows


def test_query_json_roundtrip(segment):
    ex = QueryExecutor([segment])
    q = GroupByQuery.of("test", DAY, ["dimA"], [
        CountAggregator("rows"), LongSumAggregator("s", "metLong")],
        filter=SelectorFilter("dimB", "v00000001"), granularity="hour")
    j = q.to_json()
    q2 = query_from_json(j)
    assert ex.run(q) == ex.run(q2)


def test_filter_json_roundtrip():
    j = {"type": "and", "fields": [
        {"type": "selector", "dimension": "d", "value": "x"},
        {"type": "or", "fields": [
            {"type": "bound", "dimension": "m", "lower": "1", "upper": "2",
             "lowerStrict": True, "upperStrict": False, "ordering": "numeric"},
            {"type": "not", "field": {"type": "in", "dimension": "d",
                                      "values": ["a", "b"]}},
        ]},
        {"type": "like", "dimension": "d", "pattern": "foo%"},
        {"type": "regex", "dimension": "d", "pattern": "^x"},
    ]}
    f = filter_from_json(j)
    assert filter_from_json(f.to_json()) == f


def test_agg_json_roundtrip():
    specs = [
        {"type": "count", "name": "n"},
        {"type": "longSum", "name": "a", "fieldName": "m"},
        {"type": "doubleMax", "name": "b", "fieldName": "m"},
        {"type": "doubleFirst", "name": "c", "fieldName": "m"},
        {"type": "hyperUnique", "name": "d", "fieldName": "m"},
        {"type": "cardinality", "name": "e", "fields": ["x", "y"], "byRow": True},
        {"type": "filtered", "name": "f",
         "aggregator": {"type": "count", "name": "f"},
         "filter": {"type": "selector", "dimension": "d", "value": "v"}},
    ]
    for j in specs:
        a = agg_from_json(j)
        assert agg_from_json(a.to_json()).to_json() == a.to_json()


def test_topn_inverted_metric_spec_json(segment):
    """Wire-format {"metric": {"type": "inverted", ...}} returns bottom-N."""
    ex = QueryExecutor([segment])
    base = {"queryType": "topN", "dataSource": "test",
            "intervals": ["2026-01-01/2026-01-02"], "granularity": "all",
            "dimension": "dimA", "threshold": 3,
            "aggregations": [{"type": "count", "name": "cnt"}]}
    top = ex.run_json({**base, "metric": "cnt"})[0]["result"]
    bottom = ex.run_json({**base, "metric": {"type": "inverted",
                                             "metric": "cnt"}})[0]["result"]
    tops = [e["cnt"] for e in top]
    bots = [e["cnt"] for e in bottom]
    assert tops == sorted(tops, reverse=True)
    assert bots == sorted(bots)
    assert max(bots) <= min(tops)
    dim_sorted = ex.run_json({**base, "metric": {"type": "dimension"}})[0]["result"]
    vals = [e["dimA"] for e in dim_sorted]
    assert vals == sorted(vals)


def test_time_bound_filter_outside_segment(segment):
    """__time bound far outside the segment interval must not overflow int32."""
    ex = QueryExecutor([segment])
    from druid_tpu.query import BoundFilter
    q = TimeseriesQuery.of("test", DAY, [CountAggregator("rows")],
                           filter=BoundFilter("__time", lower="0",
                                              ordering="numeric"))
    rows = ex.run(q)
    assert rows[0]["result"]["rows"] == segment.n_rows


def test_all_granularity_disjoint_intervals(segment):
    """granularity=all over 2 disjoint intervals -> ONE row covering both."""
    ex = QueryExecutor([segment])
    ivs = [Interval.of("2026-01-01T00:00:00Z", "2026-01-01T02:00:00Z"),
           Interval.of("2026-01-01T10:00:00Z", "2026-01-01T12:00:00Z")]
    q = TimeseriesQuery.of("test", ivs, [CountAggregator("rows")])
    rows = ex.run(q)
    assert len(rows) == 1
    frame = rows_as_frame(segment)
    m = np.zeros(segment.n_rows, dtype=bool)
    for iv in ivs:
        m |= (frame["__time"] >= iv.start) & (frame["__time"] < iv.end)
    assert rows[0]["result"]["rows"] == int(m.sum())
    q2 = TopNQuery.of("test", ivs, "dimA", metric="rows", threshold=3,
                      aggregations=[CountAggregator("rows")])
    assert len(ex.run(q2)) == 1


def test_builder_type_widening():
    from druid_tpu.data.segment import SegmentBuilder
    from druid_tpu.utils.intervals import Interval as Iv
    b = SegmentBuilder("w", Iv.of("2026-01-01", "2026-01-02"))
    b.add_row(Iv.of("2026-01-01", "2026-01-02").start, {"d": "a"}, {"m": 0})
    b.add_row(Iv.of("2026-01-01", "2026-01-02").start + 1, {"d": "b"}, {"m": 2.5})
    seg = b.build()
    assert float(seg.metrics["m"].values.sum()) == 2.5


def test_by_segment_results(segments):
    """context.bySegment returns per-segment UNMERGED results wrapped with
    segment identity (BySegmentQueryRunner), locally and via the broker."""
    from druid_tpu.query.model import TimeseriesQuery
    from druid_tpu.query.aggregators import CountAggregator
    iv = Interval.of("2026-01-01", "2026-01-05")
    q = TimeseriesQuery.of("test", [iv], [CountAggregator("rows")],
                           granularity="all",
                           context={"bySegment": True})
    rows = QueryExecutor(segments).run(q)
    assert len(rows) == len(segments)
    assert all(r["bySegment"] for r in rows)
    by_id = {r["result"]["segment"]: r for r in rows}
    total = 0
    for s in segments:
        r = by_id[str(s.id)]
        assert r["result"]["results"][0]["result"]["rows"] == s.n_rows
        total += s.n_rows
    # merged result for comparison
    plain = QueryExecutor(segments).run(
        TimeseriesQuery.of("test", [iv], [CountAggregator("rows")],
                           granularity="all"))
    assert plain[0]["result"]["rows"] == total

    # broker path concatenates the nodes' per-segment wrappers
    from druid_tpu.cluster import (Broker, DataNode, InventoryView,
                                   descriptor_for)
    view = InventoryView()
    n1, n2 = DataNode("n1"), DataNode("n2")
    for i, s in enumerate(segments):
        node = (n1, n2)[i % 2]
        node.load_segment(s)
    for n in (n1, n2):
        view.register(n)
        for s in n.segments():
            view.announce(n.name, descriptor_for(s))
    brows = Broker(view).run(q)
    assert {r["result"]["segment"] for r in brows} == \
        {str(s.id) for s in segments}


def test_scan_filter_on_virtual_column(segment):
    from druid_tpu.query.model import ExpressionVirtualColumn
    from druid_tpu.query import BoundFilter
    ex = QueryExecutor([segment])
    vc = ExpressionVirtualColumn("doubled", "metLong * 2", "long")
    q = ScanQuery.of("test", DAY, columns=["metLong"], limit=50,
                     filter=BoundFilter("doubled", lower="100",
                                        ordering="numeric"),
                     virtual_columns=[vc])
    out = ex.run(q)
    events = [e for batch in out for e in batch["events"]]
    assert events and all(e["metLong"] * 2 >= 100 for e in events)


def test_timeseries_skip_empty_buckets_json(segment):
    ex = QueryExecutor([segment])
    q = {"queryType": "timeseries", "dataSource": "test",
         "intervals": ["2026-01-01/2026-01-02"], "granularity": "minute",
         "aggregations": [{"type": "count", "name": "n"}],
         "context": {"skipEmptyBuckets": True}}
    rows = ex.run_json(q)
    assert all(r["result"]["n"] > 0 for r in rows)
