"""Simulated-cluster tests (no sockets), modeled on the reference's
CachingClusteredClientTest (server/src/test/.../client/
CachingClusteredClientTest.java:171 — fake servers + hand-built timelines)
and DruidCoordinatorRuleRunnerTest."""
import numpy as np
import pytest

from druid_tpu.cluster import (Broker, CacheConfig, Coordinator, DataNode,
                               DynamicConfig, ForeverLoadRule, InventoryView,
                               LruCache, MetadataStore, MissingSegmentsError,
                               PeriodDropRule, descriptor_for)
from druid_tpu.engine import QueryExecutor
from druid_tpu.query.aggregators import (CardinalityAggregator,
                                         CountAggregator, LongSumAggregator)
from druid_tpu.query.filters import SelectorFilter
from druid_tpu.query.model import (DefaultDimensionSpec, GroupByQuery,
                                   ScanQuery, SearchQuery, TimeBoundaryQuery,
                                   TimeseriesQuery, TopNQuery)
from druid_tpu.utils.intervals import Interval
from tests.conftest import rows_as_frame


@pytest.fixture()
def cluster(segments):
    """3 data nodes, segments spread round-robin with replica 2."""
    view = InventoryView()
    nodes = [DataNode(f"node{i}", cache=LruCache()) for i in range(3)]
    for n in nodes:
        view.register(n)
    for i, s in enumerate(segments):
        for j in (i % 3, (i + 1) % 3):
            nodes[j].load_segment(s)
            view.announce(nodes[j].name, descriptor_for(s))
    broker = Broker(view, cache=LruCache())
    return view, nodes, broker


WEEK = Interval.of("2026-01-01", "2026-01-08")
AGGS = [CountAggregator("rows"), LongSumAggregator("ls", "metLong")]


def _local(segments, q):
    return QueryExecutor(segments).run(q)


def test_broker_timeseries_matches_local(cluster, segments):
    _, _, broker = cluster
    q = TimeseriesQuery.of("test", [WEEK], AGGS, granularity="day")
    assert broker.run(q) == _local(segments, q)


def test_broker_topn_matches_local(cluster, segments):
    _, _, broker = cluster
    q = TopNQuery.of("test", [WEEK], "dimB", "ls", 10, AGGS)
    assert broker.run(q) == _local(segments, q)


def test_broker_groupby_matches_local(cluster, segments):
    _, _, broker = cluster
    q = GroupByQuery.of("test", [WEEK],
                        [DefaultDimensionSpec("dimA")], AGGS,
                        granularity="day")
    assert broker.run(q) == _local(segments, q)


def test_broker_hll_exact_state_merge(cluster, segments):
    """Cardinality states (HLL registers) must merge across nodes exactly:
    broker result == single-process result."""
    _, _, broker = cluster
    q = TimeseriesQuery.of("test", [WEEK],
                           [CardinalityAggregator("u", ("dimHi",))])
    assert broker.run(q) == _local(segments, q)


def test_broker_row_queries(cluster, segments):
    _, _, broker = cluster
    tb = TimeBoundaryQuery.of("test", [WEEK])
    assert broker.run(tb) == _local(segments, tb)
    sc = ScanQuery.of("test", [WEEK], columns=("dimA", "metLong"), limit=17,
                      order="ascending")
    b = broker.run(sc)
    l = _local(segments, sc)
    assert sum(len(x["events"]) for x in b) == \
        sum(len(x["events"]) for x in l) == 17
    se = SearchQuery.of("test", [WEEK], "0000", limit=5)
    assert broker.run(se) == _local(segments, se)


def test_broker_retry_on_dead_server(cluster, segments):
    view, nodes, broker = cluster
    # kill one node AFTER announcement: broker must fail over to replicas
    nodes[0].alive = False
    q = TimeseriesQuery.of("test", [WEEK], AGGS)
    assert broker.run(q) == _local(segments, q)


def test_broker_missing_segments_error(segments):
    view = InventoryView()
    node = DataNode("only")
    view.register(node)
    for s in segments:
        node.load_segment(s)
        view.announce("only", descriptor_for(s))
    broker = Broker(view)
    node.alive = False
    with pytest.raises(MissingSegmentsError):
        broker.run(TimeseriesQuery.of("test", [WEEK], AGGS))


def test_server_removal_updates_view(cluster, segments):
    view, nodes, broker = cluster
    # removing a node drops it from replica sets; queries still complete
    view.remove_node("node1")
    q = TimeseriesQuery.of("test", [WEEK], AGGS)
    assert broker.run(q) == _local(segments, q)


def test_result_level_cache(cluster, segments):
    _, _, broker = cluster
    q = TopNQuery.of("test", [WEEK], "dimA", "ls", 5, AGGS)
    first = broker.run(q)
    assert broker.cache.stats.misses >= 1
    hits_before = broker.cache.stats.hits
    second = broker.run(q)
    assert second == first
    assert broker.cache.stats.hits == hits_before + 1


def test_hybrid_remote_cache_through_broker(segments):
    """A broker on a hybrid cache (local L1 + remote memcached-analog L2)
    serves repeat queries from cache; a second broker sharing only the
    remote tier hits it too; a dead remote degrades to misses, never
    errors (reference: HybridCache + MemcachedCache)."""
    from druid_tpu.cluster import (HybridCache, RemoteCacheClient,
                                   RemoteCacheServer)
    server = RemoteCacheServer().start()
    try:
        view = InventoryView()
        node = DataNode("n0")
        view.register(node)
        for s in segments:
            node.load_segment(s)
            view.announce(node.name, descriptor_for(s))
        mk = lambda: HybridCache(
            LruCache(), RemoteCacheClient("127.0.0.1", server.port))
        b1 = Broker(view, cache=mk())
        b2 = Broker(view, cache=mk())
        q = TopNQuery.of("test", [WEEK], "dimA", "ls", 5, AGGS)
        first = b1.run(q)
        assert b1.cache.stats.misses >= 1
        assert b1.run(q) == first
        assert b1.cache.stats.hits >= 1
        # b2 shares only the remote tier → its first run is an L2 hit
        assert b2.run(q) == first
        assert b2.cache.l2.stats.hits >= 1
        # and the L2 hit populated b2's L1
        assert b2.cache.l1.stats.puts >= 1
    finally:
        server.stop()
    # dead remote: misses, not errors
    dead = HybridCache(LruCache(),
                       RemoteCacheClient("127.0.0.1", server.port))
    b3 = Broker(view, cache=dead)
    assert b3.run(q) == first
    assert b3.run(q) == first    # L1 still works


def test_remote_cache_wire_is_data_only():
    """The remote cache protocol carries JSON frames only (ADVICE round 5:
    the pickle frames it replaces were remote code execution for anyone
    who could reach the port): values round-trip as data, non-serializable
    objects are dropped client-side, and a raw pickle payload is treated
    as a malformed frame — never interpreted."""
    import numpy as np
    from druid_tpu.cluster import RemoteCacheClient, RemoteCacheServer
    server = RemoteCacheServer().start()
    try:
        c = RemoteCacheClient("127.0.0.1", server.port)
        rows = {"rows": [1, 2.5, "x"], "nested": {"a": [True, None]}}
        c.put("ns", "k", rows)
        assert c.get("ns", "k") == rows
        # numpy values lower to plain JSON numbers on the wire
        c.put("ns", "np", {"v": np.int64(7), "arr": np.arange(3)})
        assert c.get("ns", "np") == {"v": 7, "arr": [0, 1, 2]}

        # arbitrary objects do NOT ship (a cache may forget; it may not
        # become a code channel) — the put degrades to a no-op
        class Opaque:
            pass
        c.put("ns", "bad", Opaque())
        assert c.get("ns", "bad") is None

        # a hostile/legacy pickle frame is malformed JSON: the connection
        # drops, nothing executes, and the server keeps serving others
        import pickle
        import socket
        import struct
        evil = pickle.dumps({"op": "get", "ns": "ns", "key": "k"})
        s = socket.create_connection(("127.0.0.1", server.port), timeout=2)
        s.sendall(struct.pack(">I", len(evil)) + evil)
        s.close()
        assert c.get("ns", "k") == rows
    finally:
        server.stop()


def test_remote_cache_warns_on_nonloopback_bind(caplog):
    import logging
    from druid_tpu.cluster import RemoteCacheServer
    with caplog.at_level(logging.WARNING, logger="druid_tpu.cluster.cache"):
        server = RemoteCacheServer(host="0.0.0.0")
        server._server.server_close()
    assert any("NON-LOOPBACK" in r.message for r in caplog.records)


def test_segment_level_cache(cluster, segments):
    view, nodes, broker = cluster
    broker.cache_config = CacheConfig(use_result_cache=False,
                                      populate_result_cache=False)
    q = GroupByQuery.of("test", [WEEK], [DefaultDimensionSpec("dimA")], AGGS)
    broker.run(q)
    puts = sum(n.cache.stats.puts for n in nodes)
    assert puts >= len(segments)  # every (segment, query) partial cached
    before_hits = sum(n.cache.stats.hits for n in nodes)
    assert broker.run(q) == _local(segments, q)
    assert sum(n.cache.stats.hits for n in nodes) > before_hits


def test_sql_over_broker(cluster, segments):
    from druid_tpu.sql import SqlExecutor
    _, _, broker = cluster
    sq = SqlExecutor(broker)
    cols, rows = sq.execute(
        "SELECT dimA, SUM(metLong) s FROM test GROUP BY dimA ORDER BY s DESC")
    frames = [rows_as_frame(s) for s in segments]
    a = np.concatenate([f["dimA"] for f in frames])
    m = np.concatenate([f["metLong"] for f in frames])
    want = sorted(((v, int(m[a == v].sum())) for v in set(a)),
                  key=lambda kv: -kv[1])
    assert [(r[0], int(r[1])) for r in rows] == want


def test_broker_scan_offset_without_limit(cluster, segments):
    _, _, broker = cluster
    q = ScanQuery.of("test", [WEEK], columns=("dimA",), offset=10,
                     order="ascending")
    total = sum(s.n_rows for s in segments)
    got = sum(len(b["events"]) for b in broker.run(q))
    assert got == total - 10  # offset applied exactly once


def test_broker_all_granularity_timestamp_matches_local(cluster, segments):
    _, _, broker = cluster
    wide = Interval.of("2020-01-01", "2030-01-01")
    q = TimeseriesQuery.of("test", [wide], AGGS)  # granularity all
    assert broker.run(q) == _local(segments, q)


def test_remove_last_holder_removes_from_timeline(segments):
    view = InventoryView()
    node = DataNode("only")
    view.register(node)
    for s in segments:
        node.load_segment(s)
        view.announce("only", descriptor_for(s))
    assert view.datasources() == ["test"]
    view.remove_node("only")
    assert view.datasources() == []
    broker = Broker(view)
    assert broker.run(TimeseriesQuery.of("test", [WEEK], AGGS)) == []


# ---------------------------------------------------------------------------
# Metadata store
# ---------------------------------------------------------------------------

def test_metadata_publish_and_cas(segments):
    md = MetadataStore()
    descs = [descriptor_for(s) for s in segments]
    assert md.publish_segments(descs[:2])
    assert len(md.used_segments("test")) == 2
    # CAS success: expected None → {"offset": 10}
    assert md.publish_segments(
        [descs[2]], ("test", None, {"offset": 10}))
    assert md.datasource_metadata("test") == {"offset": 10}
    # CAS failure: wrong expected — nothing committed
    assert not md.publish_segments(
        [descs[3]], ("test", {"offset": 99}, {"offset": 20}))
    assert md.datasource_metadata("test") == {"offset": 10}
    assert len(md.used_segments("test")) == 3
    # CAS success continues the chain
    assert md.publish_segments(
        [descs[3]], ("test", {"offset": 10}, {"offset": 20}))
    assert len(md.used_segments("test")) == 4


def test_metadata_unused_and_rules(segments):
    md = MetadataStore()
    descs = [descriptor_for(s) for s in segments]
    md.publish_segments(descs)
    assert md.mark_unused([descs[0].id]) == 1
    assert len(md.used_segments("test")) == len(descs) - 1
    md.set_rules("test", [{"type": "loadForever",
                           "tieredReplicants": {"_default_tier": 1}}])
    md.set_rules("_default", [{"type": "dropForever"}])
    assert [r["type"] for r in md.rules_for("test")] == \
        ["loadForever", "dropForever"]
    md.audit("rules", "rules", "admin", "set rules", {"x": 1})
    assert md.audit_log("rules")[0]["author"] == "admin"


# ---------------------------------------------------------------------------
# Coordinator
# ---------------------------------------------------------------------------

@pytest.fixture()
def coordinated(segments):
    md = MetadataStore()
    view = InventoryView()
    nodes = [DataNode(f"node{i}") for i in range(3)]
    for n in nodes:
        view.register(n)
    by_id = {descriptor_for(s).id: s for s in segments}
    md.publish_segments([descriptor_for(s) for s in segments])
    coord = Coordinator(md, view, lambda d: by_id.get(d.id),
                        DynamicConfig(max_segments_to_move=10,
                                      replication_throttle_limit=100))
    return md, view, nodes, coord


def test_coordinator_assigns_replicas(coordinated, segments):
    md, view, nodes, coord = coordinated
    md.set_rules("_default", [{"type": "loadForever",
                               "tieredReplicants": {"_default_tier": 2}}])
    stats = coord.run_once()
    assert stats.assigned == 2 * len(segments)
    for s in segments:
        rs = view.replica_set(descriptor_for(s).id)
        assert rs is not None and len(rs.servers) == 2
    # idempotent second run
    stats2 = coord.run_once()
    assert stats2.assigned == 0


def test_coordinator_queries_after_assignment(coordinated, segments):
    md, view, nodes, coord = coordinated
    coord.run_once()
    broker = Broker(view)
    q = TimeseriesQuery.of("test", [WEEK], AGGS)
    assert broker.run(q) == _local(segments, q)


def test_coordinator_drop_rule(coordinated, segments):
    md, view, nodes, coord = coordinated
    coord.run_once()
    # everything older than "now" by a hair → drop everything
    md.set_rules("_default", [{"type": "dropByPeriod", "periodMs": 1}])
    far_future = int(4e12)
    stats = coord.run_once(now_ms=far_future)
    assert stats.dropped > 0
    assert all(n.segment_count() == 0 for n in nodes)


def test_coordinator_overshadow_cleanup(coordinated, segments, generator):
    md, view, nodes, coord = coordinated
    # publish a v2 segment covering segment[0]'s interval → v1 overshadowed
    s0 = segments[0]
    v2 = generator.segment(1000, s0.id.interval, datasource="test",
                           version="v2")
    by_id_v2 = descriptor_for(v2)
    md.publish_segments([by_id_v2])
    coord.segment_source = (lambda orig: lambda d:
                            v2 if d.id == by_id_v2.id else orig(d)
                            )(coord.segment_source)
    stats = coord.run_once()
    assert stats.overshadowed_marked == 1
    used_ids = {d.id for d in md.used_segments("test")}
    assert descriptor_for(s0).id not in used_ids
    assert by_id_v2.id in used_ids
    assert coord.kill_unused("test") == 1


class _SickNode(DataNode):
    """Serves segments but fails queries N times with a server error (the
    HTTP-500 case — reachable, sick)."""

    def __init__(self, name, failures=1):
        super().__init__(name)
        self.failures = failures

    def run_partials(self, query, segment_ids, check=None):
        if self.failures > 0:
            self.failures -= 1
            raise RuntimeError("node exploded mid-query")
        return super().run_partials(query, segment_ids, check)


def test_broker_retries_sick_node_on_replica(segments):
    """An HTTP-500-style node error must fail over to another replica, not
    fail the query (RetryQueryRunner.java:71-80)."""
    view = InventoryView()
    sick = _SickNode("sick", failures=10**9)
    good = DataNode("good")
    for n in (sick, good):
        view.register(n)
        for s in segments:
            n.load_segment(s)
            view.announce(n.name, descriptor_for(s))
    broker = Broker(view)
    q = TimeseriesQuery.of("test", [WEEK], AGGS)
    assert broker.run(q) == _local(segments, q)


def test_broker_reports_node_error_when_replicas_exhausted(segments):
    view = InventoryView()
    sick = _SickNode("sick", failures=10**9)
    view.register(sick)
    for s in segments:
        sick.load_segment(s)
        view.announce("sick", descriptor_for(s))
    broker = Broker(view)
    with pytest.raises(RuntimeError, match="exploded"):
        broker.run(TimeseriesQuery.of("test", [WEEK], AGGS))


class _SheddingNode(DataNode):
    """Answers every partials request with a 429-style capacity shed (the
    admission-control path, stubbed — reachable, saturated)."""

    def __init__(self, name, sheds=10**9):
        super().__init__(name)
        self.sheds = sheds
        self.shed_calls = 0

    def run_partials(self, query, segment_ids, check=None):
        from druid_tpu.server.querymanager import QueryCapacityError
        if self.sheds > 0:
            self.sheds -= 1
            self.shed_calls += 1
            raise QueryCapacityError("stub shed", retry_after_s=0.01)
        return super().run_partials(query, segment_ids, check)


def test_broker_lane_aware_retry_on_429(segments):
    """A data-node 429 fails over ONCE to another replica of the segment
    set (same lane/context, remaining budget) before surfacing — a
    saturated node is not a saturated tier."""
    view = InventoryView()
    shedding = _SheddingNode("shedding")
    good = DataNode("good")
    for n in (shedding, good):
        view.register(n)
        for s in segments:
            n.load_segment(s)
            view.announce(n.name, descriptor_for(s))
    broker = Broker(view, seed=3)
    q = TimeseriesQuery.of("test", [WEEK], AGGS,
                           context={"lane": "interactive"})
    # run until the random replica pick hits the shedding node at least
    # once — every run must still produce the exact serial result
    hit_shed = False
    for _ in range(6):
        assert broker.run(q) == _local(segments, q)
        hit_shed = hit_shed or shedding.shed_calls > 0
        shedding.sheds = 10**9
    assert hit_shed
    assert view.capacity_sheds("shedding") > 0


def test_broker_surfaces_429_when_other_replica_sheds_too(segments):
    from druid_tpu.server.querymanager import QueryCapacityError
    view = InventoryView()
    for name in ("shed1", "shed2"):
        n = _SheddingNode(name)
        view.register(n)
        for s in segments:
            n.load_segment(s)
            view.announce(name, descriptor_for(s))
    broker = Broker(view)
    with pytest.raises(QueryCapacityError):
        broker.run(TimeseriesQuery.of("test", [WEEK], AGGS))


def test_broker_surfaces_429_with_no_other_replica(segments):
    from druid_tpu.server.querymanager import QueryCapacityError
    view = InventoryView()
    n = _SheddingNode("only")
    view.register(n)
    for s in segments:
        n.load_segment(s)
        view.announce("only", descriptor_for(s))
    broker = Broker(view)
    with pytest.raises(QueryCapacityError):
        broker.run(TimeseriesQuery.of("test", [WEEK], AGGS))


def test_liveness_failure_triggers_rereplication(coordinated, segments):
    """Kill one of two replicas: the coordinator's liveness probe removes
    the dead server and the SAME cycle restores replication on a live node
    while the broker keeps serving (Announcer ephemeral-expiry +
    ReplicationThrottler behavior)."""
    md, view, nodes, coord = coordinated
    md.set_rules("_default", [{"type": "loadForever",
                               "tieredReplicants": {"_default_tier": 2}}])
    coord.run_once()
    sid = descriptor_for(segments[0]).id
    victim_name = sorted(view.replica_set(sid).servers)[0]
    victim = view.node(victim_name)
    victim.alive = False

    broker = Broker(view)
    q = TimeseriesQuery.of("test", [WEEK], AGGS)
    assert broker.run(q) == _local(segments, q)   # mid-outage serving

    stats = coord.run_once()
    assert stats.nodes_removed == 1
    assert view.node(victim_name) is None
    for s in segments:
        rs = view.replica_set(descriptor_for(s).id)
        assert rs is not None and len(rs.servers) == 2
        assert victim_name not in rs.servers
    assert broker.run(q) == _local(segments, q)


def test_coordinator_balances(segments):
    md = MetadataStore()
    view = InventoryView()
    nodes = [DataNode("a"), DataNode("b")]
    for n in nodes:
        view.register(n)
    by_id = {descriptor_for(s).id: s for s in segments}
    md.publish_segments([descriptor_for(s) for s in segments])
    md.set_rules("_default", [{"type": "loadForever",
                               "tieredReplicants": {"_default_tier": 1}}])
    # preload everything onto node a
    for s in segments:
        nodes[0].load_segment(s)
        view.announce("a", descriptor_for(s))
    coord = Coordinator(md, view, lambda d: by_id.get(d.id),
                        DynamicConfig(max_segments_to_move=10))
    stats = coord.run_once()
    assert stats.moved >= 1
    assert abs(nodes[0].segment_count() - nodes[1].segment_count()) <= 1


def test_http_etag_and_not_modified(cluster, segments):
    """X-Druid-ETag on aggregate results; If-None-Match returns 304
    without executing; a timeline change (segment drop) changes the
    etag (reference: QueryResource + CachingClusteredClient etag)."""
    import http.client
    import json as _json
    from druid_tpu.server.http import QueryHttpServer
    from druid_tpu.server.lifecycle import QueryLifecycle
    view, nodes, broker = cluster
    srv = QueryHttpServer(QueryLifecycle(broker), port=0).start()
    try:
        payload = _json.dumps({
            "queryType": "timeseries", "dataSource": "test",
            "intervals": [str(WEEK)], "granularity": "all",
            "aggregations": [{"type": "count", "name": "n"}]})
        c = http.client.HTTPConnection("127.0.0.1", srv.port)
        c.request("POST", "/druid/v2", payload,
                  {"Content-Type": "application/json"})
        r1 = c.getresponse()
        etag = r1.headers.get("X-Druid-ETag")
        body1 = _json.loads(r1.read())
        assert r1.status == 200 and etag
        assert body1[0]["result"]["n"] == sum(s.n_rows for s in segments)
        # conditional re-request: 304, empty body
        c.request("POST", "/druid/v2", payload,
                  {"Content-Type": "application/json",
                   "If-None-Match": etag})
        r2 = c.getresponse()
        assert r2.status == 304
        assert r2.read() == b""
        assert r2.headers.get("X-Druid-ETag") == etag
        # timeline change invalidates: drop a segment from BOTH replicas
        # (one replica down leaves the segment set — and the etag — intact)
        view.unannounce(nodes[0].name, descriptor_for(segments[0]).id)
        view.unannounce(nodes[1].name, descriptor_for(segments[0]).id)
        c.request("POST", "/druid/v2", payload,
                  {"Content-Type": "application/json",
                   "If-None-Match": etag})
        r3 = c.getresponse()
        assert r3.status == 200
        new_etag = r3.headers.get("X-Druid-ETag")
        r3.read()
        assert new_etag and new_etag != etag
    finally:
        srv.stop()


def test_etag_denied_identity_gets_403_not_304(cluster, segments):
    """If-None-Match must not leak whether forbidden data changed: a
    denied identity gets 403 on the conditional request too, and 304s
    still hit the request log / success count."""
    import http.client
    import json as _json
    from druid_tpu.server.http import QueryHttpServer
    from druid_tpu.server.lifecycle import QueryLifecycle, RequestLogger
    _, _, broker = cluster
    results = []
    logger = RequestLogger()
    lc = QueryLifecycle(broker, request_logger=logger,
                        authorizer=lambda ident, q: ident != "evil",
                        on_result=results.append)
    srv = QueryHttpServer(lc, port=0).start()
    try:
        payload = _json.dumps({
            "queryType": "timeseries", "dataSource": "test",
            "intervals": [str(WEEK)], "granularity": "all",
            "aggregations": [{"type": "count", "name": "n"}]})
        c = http.client.HTTPConnection("127.0.0.1", srv.port)
        c.request("POST", "/druid/v2", payload,
                  {"Content-Type": "application/json"})
        r1 = c.getresponse()
        etag = r1.headers["X-Druid-ETag"]
        r1.read()
        # denied identity with a valid etag: 403, never 304
        c.request("POST", "/druid/v2", payload,
                  {"Content-Type": "application/json",
                   "If-None-Match": etag, "X-Druid-Identity": "evil"})
        r2 = c.getresponse()
        assert r2.status == 403, r2.status
        r2.read()
        # allowed conditional hit: 304 AND accounted
        n_logs = len(logger.entries)
        c.request("POST", "/druid/v2", payload,
                  {"Content-Type": "application/json",
                   "If-None-Match": etag})
        r3 = c.getresponse()
        assert r3.status == 304
        r3.read()
        assert len(logger.entries) == n_logs + 1
        assert results[-1] is True
        # bySegment context yields a DIFFERENT etag (different result shape)
        by_seg = _json.dumps({**_json.loads(payload),
                              "context": {"bySegment": True}})
        c.request("POST", "/druid/v2", by_seg,
                  {"Content-Type": "application/json",
                   "If-None-Match": etag})
        r4 = c.getresponse()
        assert r4.status == 200
        assert r4.headers.get("X-Druid-ETag") not in (None, etag)
        r4.read()
    finally:
        srv.stop()


def test_replica_pick_fuzz_exclusions_and_circuits():
    """Fuzz ReplicaSet.pick over random member/exclusion/breaker states:
    it never returns an excluded server; it never returns a still-cooling
    open-circuit server while a closed or cooled (probe-eligible)
    alternative exists; a cooled pick is tagged as the half-open probe;
    and only when ALL candidates are open-and-uncooled does it fall back
    to an open server — likewise tagged as a probe."""
    import random as _random

    from druid_tpu.cluster.resilience import (HALF_OPEN, CircuitRegistry,
                                              ResiliencePolicy)
    from druid_tpu.cluster.view import ReplicaSet

    rng = _random.Random(123)
    servers_all = [f"s{i}" for i in range(6)]
    for trial in range(400):
        now = [0.0]
        reg = CircuitRegistry(
            ResiliencePolicy(circuit_failure_threshold=1,
                             circuit_cooldown_s=5.0,
                             circuit_cooldown_cap_s=5.0),
            seed=trial, clock=lambda: now[0])
        rs = ReplicaSet(descriptor=None)
        members = set(rng.sample(servers_all, rng.randint(1, 6)))
        rs.servers = set(members)
        exclude = set(rng.sample(sorted(members),
                                 rng.randint(0, len(members))))
        cooled_open, cooling_open = set(), set()
        for s in sorted(members):
            r = rng.random()
            if r < 0.3:
                cooled_open.add(s)
            elif r < 0.55:
                cooling_open.add(s)
        now[0] = 0.0
        for s in sorted(cooled_open):
            reg.on_failure(s)            # cooldown ends at t=5
        now[0] = 6.0
        for s in sorted(cooling_open):
            reg.on_failure(s)            # cooldown ends at t=11
        # at t=6: cooled_open are probe candidates, cooling_open are not
        chosen = rs.pick(rng, exclude=exclude, circuits=reg)
        candidates = members - exclude
        if not candidates:
            assert chosen is None
            continue
        assert chosen in candidates, "picked an excluded/foreign server"
        closed_c = candidates - cooled_open - cooling_open
        cooled_c = candidates & cooled_open
        if closed_c or cooled_c:
            assert chosen in closed_c | cooled_c, \
                "picked a still-cooling open server over alternatives"
            if chosen in cooled_c:
                assert reg.state_of(chosen) == HALF_OPEN, \
                    "cooled-open pick not tagged as the probe"
        else:
            # every candidate's circuit is open and cooling: fallback,
            # tagged as a probe
            assert reg.state_of(chosen) == HALF_OPEN
            assert reg.snapshot()["probes"] >= 1
