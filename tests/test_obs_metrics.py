"""Observability metrics surfaces: the Prometheus registry + /metrics
endpoints, the metrics catalog contract, the BatchingEmitter background
flush fix, ComposingEmitter.close, and QueryCountStatsMonitor deltas."""
import json
import time
import urllib.request

import pytest

from druid_tpu.obs import catalog
from druid_tpu.obs.prometheus import MetricRegistry, metric_name
from druid_tpu.utils.emitter import (BatchingEmitter, ComposingEmitter,
                                     Event, FileEmitter, InMemoryEmitter,
                                     QueryCountStatsMonitor, ServiceEmitter)


# ---------------------------------------------------------------------------
# Prometheus exposition
# ---------------------------------------------------------------------------

def test_prometheus_exposition_golden():
    """Exact text-format output: HELP/TYPE from the catalog, sorted label
    sets, the high-cardinality `id` label dropped."""
    reg = MetricRegistry()
    em = ServiceEmitter("svc", "h1", reg)
    em.metric("query/time", 12.5, dataSource="d", type="timeseries",
              id="q-abc")
    em.metric("segment/devicePool/entries", 3)
    assert reg.exposition() == (
        '# HELP druid_query_time end-to-end query wall time (ms)\n'
        '# TYPE druid_query_time gauge\n'
        'druid_query_time{dataSource="d",host="h1",service="svc",'
        'type="timeseries"} 12.5\n'
        '# HELP druid_segment_devicePool_entries current pool entry count '
        '(count)\n'
        '# TYPE druid_segment_devicePool_entries gauge\n'
        'druid_segment_devicePool_entries{host="h1",service="svc"} 3\n')


def test_prometheus_last_value_and_escaping():
    reg = MetricRegistry()
    em = ServiceEmitter("s", "h", reg)
    em.metric("query/time", 1.0, dataSource='we"ird\nname')
    em.metric("query/time", 2.0, dataSource='we"ird\nname')
    text = reg.exposition()
    assert text.count("druid_query_time{") == 1     # last value wins
    assert r'dataSource="we\"ird\nname"' in text
    assert " 2\n" in text


def test_prometheus_series_cap():
    reg = MetricRegistry(max_series=2)
    em = ServiceEmitter("s", "h", reg)
    for i in range(5):
        em.metric("query/time", float(i), dataSource=f"d{i}")
    assert reg.series_count() == 2
    assert "druid_metric_registry_dropped_series 3" in reg.exposition()


def test_metric_name_sanitization():
    assert metric_name("query/batch/fillRatio") == \
        "druid_query_batch_fillRatio"
    assert metric_name("sys/mem-used") == "druid_sys_mem_used"


def test_every_monitor_metric_is_cataloged():
    """Drive every monitor against an in-memory sink and check the names it
    emits are all declared — the runtime counterpart of the AST-level
    metric-name lint rule."""
    from druid_tpu.cluster import LruCache
    from druid_tpu.data.cascade import CodeDomainMonitor, CodeDomainStats
    from druid_tpu.data.devicepool import DevicePoolMonitor
    from druid_tpu.engine.batching import BatchMetricsMonitor
    from druid_tpu.parallel.distributed import ShardedMonitor, ShardedStats
    from druid_tpu.utils.emitter import (CacheMonitor, MonitorScheduler,
                                         ProcessMonitor, SysMonitor)
    sink = InMemoryEmitter()
    em = ServiceEmitter("s", "h", sink)
    qc = QueryCountStatsMonitor()
    qc.on_query(True)
    cache = LruCache()
    cache.put("x", "k", 1)
    cds = CodeDomainStats()
    cds.record(100)
    shs = ShardedStats()
    shs.record(8)
    sched = MonitorScheduler(
        em, [SysMonitor(), ProcessMonitor(), qc, CacheMonitor(cache),
             DevicePoolMonitor(), BatchMetricsMonitor(),
             CodeDomainMonitor(cds), ShardedMonitor(stats=shs)], 999)
    sched.tick()
    sched.tick()
    missing = catalog.validate_emitted(e.metric for e in sink.metrics())
    assert not missing, f"monitors emit uncataloged metrics: {missing}"


# ---------------------------------------------------------------------------
# QueryCountStatsMonitor: per-period deltas alongside cumulative counts
# ---------------------------------------------------------------------------

def test_query_count_deltas_per_period():
    sink = InMemoryEmitter()
    em = ServiceEmitter("s", "h", sink)
    qc = QueryCountStatsMonitor()
    qc.on_query(True)
    qc.on_query(True)
    qc.on_query(False)
    qc.do_monitor(em)
    qc.on_query(True)
    qc.do_monitor(em)
    qc.do_monitor(em)       # idle tick: zero deltas, stable cumulatives
    assert [e.value for e in sink.metrics("query/count")] == [3, 4, 4]
    assert [e.value for e in sink.metrics("query/count/delta")] == [3, 1, 0]
    assert [e.value for e in
            sink.metrics("query/success/count/delta")] == [2, 1, 0]
    assert [e.value for e in
            sink.metrics("query/failed/count/delta")] == [1, 0, 0]


def test_broker_http_wires_query_counts(segments):
    """The broker server path calls on_query: a query through the HTTP
    resource shows up in the monitor's counts and on GET /metrics."""
    from druid_tpu.engine import QueryExecutor
    from druid_tpu.server import QueryHttpServer, QueryLifecycle
    lc = QueryLifecycle(QueryExecutor(list(segments)))
    http = QueryHttpServer(lc).start()
    try:
        payload = {"queryType": "timeseries", "dataSource": "test",
                   "intervals": ["2026-01-01/2026-01-08"],
                   "granularity": "all",
                   "aggregations": [{"type": "count", "name": "rows"}]}
        req = urllib.request.Request(
            f"http://127.0.0.1:{http.port}/druid/v2",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req) as r:
            assert r.status == 200
        assert http.query_counts.success == 1
        http.metrics_tick()
        with urllib.request.urlopen(
                f"http://127.0.0.1:{http.port}/metrics") as r:
            text = r.read().decode()
            ctype = r.headers.get("Content-Type", "")
        assert "text/plain" in ctype
        lines = text.splitlines()
        assert any(ln.startswith("druid_query_success_count{")
                   and ln.endswith(" 1") for ln in lines), text
        assert any(ln.startswith("druid_query_count_delta{")
                   and ln.endswith(" 1") for ln in lines), text
    finally:
        http.stop()


def test_broker_http_chains_existing_on_result(segments):
    """Wiring the monitor must not clobber a caller-supplied on_result."""
    from druid_tpu.engine import QueryExecutor
    from druid_tpu.server import QueryHttpServer, QueryLifecycle
    seen = []
    lc = QueryLifecycle(QueryExecutor(list(segments)),
                        on_result=seen.append)
    http = QueryHttpServer(lc).start()
    try:
        lc.run_json({"queryType": "timeseries", "dataSource": "test",
                     "intervals": ["2026-01-01/2026-01-08"],
                     "granularity": "all",
                     "aggregations": [{"type": "count", "name": "rows"}]})
        assert seen == [True]
        assert http.query_counts.success == 1
    finally:
        http.stop()


def test_data_node_metrics_endpoint(segments):
    """GET /metrics on a data node: Prometheus text including query/time
    and the devicePool gauges (the ISSUE's acceptance surface)."""
    from druid_tpu.cluster import (DataNode, DataNodeServer,
                                   RemoteDataNodeClient, descriptor_for)
    from druid_tpu.query.aggregators import CountAggregator
    from druid_tpu.query.model import TimeseriesQuery
    from druid_tpu.utils.intervals import Interval
    node = DataNode("promnode")
    srv = DataNodeServer(node).start()
    try:
        for s in segments:
            node.load_segment(s)
        client = RemoteDataNodeClient(node.name, srv.url)
        q = TimeseriesQuery.of(
            "test", [Interval.of("2026-01-01", "2026-01-08")],
            [CountAggregator("rows")],
            context={"queryId": "prom-1"})
        client.run_partials(q, [str(s.id) for s in segments])
        srv.metrics_tick()
        with urllib.request.urlopen(srv.url + "/metrics") as r:
            text = r.read().decode()
        assert 'druid_query_time{' in text
        assert 'success="true"' in text
        assert "druid_segment_devicePool_residentBytes" in text
        assert "druid_segment_devicePool_entries" in text
        assert any(ln.startswith("druid_query_count{")
                   and ln.endswith(" 1") for ln in text.splitlines()), text
    finally:
        srv.stop()


def test_data_node_composes_caller_emitter(segments):
    """A caller-supplied emitter keeps receiving events AND the registry
    sees them (the sink is composed, not replaced)."""
    from druid_tpu.cluster import DataNode, DataNodeServer
    sink = InMemoryEmitter()
    em = ServiceEmitter("historical", "h", sink)
    srv = DataNodeServer(DataNode("cnode"), emitter=em).start()
    try:
        srv.metrics_tick()
        assert sink.metrics("segment/devicePool/entries")
        assert "druid_segment_devicePool_entries" in \
            srv.registry.exposition()
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# BatchingEmitter background flush + ComposingEmitter.close
# ---------------------------------------------------------------------------

def test_batching_emitter_background_flush():
    """A trickle below batch_size must reach the sender WITHOUT further
    emits — the background timer fires on flush_seconds (the bug was that
    the time-based path only ran inside emit())."""
    sent = []
    be = BatchingEmitter(sent.append, batch_size=100, flush_seconds=0.05)
    try:
        be.emit(Event("metric", "query/time", 1.0, 0))
        deadline = time.monotonic() + 5.0
        while not sent and time.monotonic() < deadline:
            time.sleep(0.01)
        assert sent and sent[0][0]["metric"] == "query/time"
    finally:
        be.close()


def test_batching_emitter_close_joins_and_flushes():
    sent = []
    be = BatchingEmitter(sent.append, batch_size=100, flush_seconds=60.0)
    be.emit(Event("metric", "query/time", 1.0, 0))
    be.close()
    assert sent and len(sent[0]) == 1
    assert not be._flusher.is_alive()


def test_composing_emitter_closes_children(tmp_path):
    """close() must propagate: a composed FileEmitter's handle previously
    leaked open."""
    f1 = FileEmitter(str(tmp_path / "a.log"))
    f2 = FileEmitter(str(tmp_path / "b.log"))
    comp = ComposingEmitter([f1, f2])
    comp.emit(Event("metric", "query/time", 1.0, 0))
    comp.close()
    assert f1._fh.closed and f2._fh.closed
