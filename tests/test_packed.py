"""Compressed-domain device execution (data/packed.py + the engine decode
story): exact-equality parity between packed and decoded staging over mixed
dtypes and all execution paths, pure-stats pack planning, the ≥3x
effective-pool-capacity contract on the bench's small-segment shape, and
the pallas packed-input variant (interpret mode).

Parity assertions are EXACT (`==` on finished rows / array_equal on
states, floats included): bit-unpacking is exact reconstruction, so whether
a column staged packed or decoded may never change a result's bits."""
import numpy as np
import pytest

import druid_tpu.engine  # noqa: F401  (x64 on before jax numerics)
from druid_tpu.data import devicepool, packed
from druid_tpu.data.devicepool import DeviceSegmentPool
from druid_tpu.data.generator import ColumnSpec, DataGenerator
from druid_tpu.data.segment import SegmentBuilder, ValueType
from druid_tpu.engine import pallas_agg
from druid_tpu.engine.executor import QueryExecutor
from druid_tpu.utils.intervals import Interval

IV = Interval.of("2026-05-01", "2026-05-02")

SCHEMA = (
    ColumnSpec("dimA", "string", cardinality=12, distribution="uniform"),
    ColumnSpec("dimB", "string", cardinality=900, distribution="zipf"),
    ColumnSpec("metLong", "long", low=-50, high=9000),
    ColumnSpec("metFloat", "float", distribution="normal", mean=10.0,
               std=4.0),
    ColumnSpec("metDouble", "double", low=0.0, high=1.0),
)

GROUPBY = {
    "queryType": "groupBy", "dataSource": "pk", "intervals": [str(IV)],
    "granularity": "all",
    "dimensions": ["dimA", "dimB"],
    "aggregations": [
        {"type": "count", "name": "n"},
        {"type": "longSum", "name": "ls", "fieldName": "metLong"},
        {"type": "longMin", "name": "lm", "fieldName": "metLong"},
        {"type": "floatMax", "name": "fx", "fieldName": "metFloat"},
        {"type": "doubleSum", "name": "ds", "fieldName": "metDouble"},
    ],
    "filter": {"type": "bound", "dimension": "metLong", "lower": 0,
               "upper": 8000, "ordering": "numeric"},
}

TIMESERIES = {
    "queryType": "timeseries", "dataSource": "pk", "intervals": [str(IV)],
    "granularity": "hour",
    "aggregations": GROUPBY["aggregations"],
}

TOPN = {
    "queryType": "topN", "dataSource": "pk", "intervals": [str(IV)],
    "granularity": "all", "dimension": "dimB", "metric": "ls",
    "threshold": 9,
    "aggregations": [{"type": "count", "name": "n"},
                     {"type": "longSum", "name": "ls",
                      "fieldName": "metLong"}],
}


@pytest.fixture
def fresh_pool(monkeypatch):
    pool = DeviceSegmentPool(budget_bytes=1 << 40)
    monkeypatch.setattr(devicepool, "_POOL", pool)
    return pool


def _segments(n=4, rows=2500, seed=23):
    return DataGenerator(SCHEMA, seed=seed).segments(
        n, rows, IV, datasource="pk")


def _run_both(query_json, segments):
    """(decoded results, packed results) over fresh executions."""
    ex = QueryExecutor(segments)
    prev = packed.set_enabled(False)
    try:
        dec = ex.run_json(query_json)
        packed.set_enabled(True)
        pk = ex.run_json(query_json)
    finally:
        packed.set_enabled(prev)
    return dec, pk


# ---------------------------------------------------------------------------
# encoder unit level
# ---------------------------------------------------------------------------

def test_pack_roundtrip_all_widths():
    rng = np.random.default_rng(0)
    for width, lo, hi in ((4, 0, 15), (8, -100, 100), (16, -5000, 40000)):
        base = 0 if lo >= 0 else -(1 << ((-lo - 1).bit_length()))
        w = packed.width_for(hi, base)
        assert w == width
        v = rng.integers(lo, hi + 1, size=4096).astype(np.int32)
        words = packed.pack_padded(v, w, base)
        assert words.dtype == np.int32
        assert words.nbytes * (32 // w) == v.nbytes
        np.testing.assert_array_equal(
            packed.unpack_host(words, w, base, 4096, "int32"), v)


def test_unpack_device_matches_host():
    import jax
    rng = np.random.default_rng(1)
    v = rng.integers(-900, 900, size=2048).astype(np.int32)
    w = packed.width_for(900, -1024)
    pc = packed.PackedColumn(
        jax.device_put(packed.pack_padded(v, w, -1024)), w, -1024, 2048)
    np.testing.assert_array_equal(
        np.asarray(jax.jit(packed.unpack_device)(pc)), v)


def test_packed_column_is_a_pytree():
    import jax
    pc = packed.PackedColumn(np.zeros(256, np.int32), 8, -16, 1024)
    leaves, treedef = jax.tree_util.tree_flatten(pc)
    assert len(leaves) == 1
    back = jax.tree_util.tree_unflatten(treedef, leaves)
    assert back.descriptor() == pc.descriptor()
    # the descriptor rides the treedef: jit specializes per descriptor
    pc2 = packed.PackedColumn(np.zeros(512, np.int32), 4, 0, 4096)
    assert treedef != jax.tree_util.tree_flatten(pc2)[1]


def test_plan_column_is_pure_function_of_stats(fresh_pool):
    b = SegmentBuilder("pk", IV)
    for i in range(64):
        b.add_row(IV.start + i, {"low": f"v{i % 9}", "high": f"u{i}"},
                  {"small": i % 50, "neg": (i % 40) - 20,
                   "big": 2 ** 40 + i, "f": float(i)})
    s = b.build()
    assert packed.plan_column(s, "low") == (4, 0)        # card 9 -> 4 bits
    assert packed.plan_column(s, "high") == (8, 0)       # card 64 -> 8 bits
    assert packed.plan_column(s, "small") == (8, 0)      # [0, 49]
    w, base = packed.plan_column(s, "neg")               # [-20, 19]
    assert base == -32 and w == 8                        # pow2-quantized
    assert packed.plan_column(s, "big") is None          # int64-staged
    assert packed.plan_column(s, "f") is None            # float: decoded
    assert packed.plan_column(s, "__time_offset") is None
    # plan_columns is the ordered descriptor and respects the switch
    packs = packed.plan_columns(s, ["neg", "low", "f"])
    assert packs == (("low", 4, 0), ("neg", 8, -32))
    prev = packed.set_enabled(False)
    try:
        assert packed.plan_columns(s, ["low"]) == ()
    finally:
        packed.set_enabled(prev)


def test_complex_integer_columns_never_pack(fresh_pool):
    """REGRESSION (review finding): a 2-D ComplexColumn with an INTEGER
    state dtype (complex columns load with arbitrary dtypes) must not get
    a pack plan — the packer and both decoders are 1-D tile-planar only,
    so a packed 2-D column would crash every query reading it."""
    from druid_tpu.data.dictionary import Dictionary
    from druid_tpu.data.segment import (ComplexColumn, Segment, SegmentId,
                                        StringDimColumn)
    n = 64
    time_ms = np.arange(n, dtype=np.int64) + IV.start
    d = Dictionary(["a", "b"])
    seg = Segment(
        SegmentId("pk", IV, "v0"), time_ms,
        {"d": StringDimColumn((np.arange(n) % 2).astype(np.int32), d)},
        {"hll": ComplexColumn(np.zeros((n, 16), dtype=np.int32), "hu")})
    assert packed.plan_column(seg, "hll") is None
    prev = packed.set_enabled(True)
    try:
        block = seg.device_block(["d", "hll"])
    finally:
        packed.set_enabled(prev)
    assert not isinstance(block.arrays["hll"], packed.PackedColumn)
    assert isinstance(block.arrays["d"], packed.PackedColumn)


def test_high_cardinality_dim_falls_back_to_decoded(fresh_pool):
    n = (1 << 16) + 8                     # cardinality past the 16-bit cap
    b = SegmentBuilder("pk", IV)
    b.add_columns(np.arange(n, dtype=np.int64) + IV.start,
                  {"wide": [f"u{i:07d}" for i in range(n)]},
                  {"m": np.arange(n, dtype=np.int64)})
    s = b.build()
    assert s.dims["wide"].cardinality > 1 << 16
    assert packed.plan_column(s, "wide") is None
    assert packed.width_for((1 << 16) - 1, 0) == 16     # boundary: packs
    assert packed.width_for(1 << 16, 0) == 0            # one past: decoded


# ---------------------------------------------------------------------------
# engine parity (the acceptance bar: exact equality, floats included)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("qjson", [GROUPBY, TIMESERIES, TOPN],
                         ids=["groupBy", "timeseries", "topN"])
def test_packed_results_exactly_equal_decoded(fresh_pool, qjson):
    dec, pk = _run_both(qjson, _segments())
    assert dec == pk


def test_parity_holds_with_batching_disabled(fresh_pool):
    from druid_tpu.engine import batching
    prev = batching.set_enabled(False)
    try:
        dec, pk = _run_both(GROUPBY, _segments())
    finally:
        batching.set_enabled(prev)
    assert dec == pk


def test_parity_with_virtual_column_reading_packed_input(fresh_pool):
    q = dict(GROUPBY)
    q["virtualColumns"] = [{"type": "expression", "name": "v",
                            "expression": "metLong * 2 + 1",
                            "outputType": "long"}]
    q["aggregations"] = GROUPBY["aggregations"] + [
        {"type": "longSum", "name": "vs", "fieldName": "v"}]
    dec, pk = _run_both(q, _segments())
    assert dec == pk


def test_packed_staging_actually_engages(fresh_pool):
    """Guard against silently testing nothing: the packed run must hold
    compressed bytes in the pool (ratio > 1) and stage strictly fewer
    bytes than the decoded staging of the same segments."""
    segs = _segments()
    ex = QueryExecutor(segs)
    prev = packed.set_enabled(False)
    try:
        ex.run_json(GROUPBY)
        decoded_resident = fresh_pool.snapshot().resident_bytes
        fresh_pool.clear()
        packed.set_enabled(True)
        ex.run_json(GROUPBY)
    finally:
        packed.set_enabled(prev)
    s = fresh_pool.snapshot()
    assert s.packed_ratio > 1.3, s
    assert s.resident_bytes < decoded_resident


def test_pack_descriptor_keys_the_batching_digest(fresh_pool):
    """Chunk-mates must agree on the pack descriptor: same-stats segments
    share one shape bucket (the pow2 quantization contract), and a segment
    whose value range needs a wider width lands in a DIFFERENT bucket —
    never in a mixed-treedef batch."""
    from druid_tpu.engine import batching
    from druid_tpu.query.aggregators import CountAggregator, LongSumAggregator
    from druid_tpu.utils.granularity import Granularity

    # non-negative metric range: base stays 0 for every segment, so the
    # stats-derived plan constants (mm_base, chunk_rows, pack width) agree
    # across segments — the shape that MUST share one bucket
    schema = (SCHEMA[0], SCHEMA[1],
              ColumnSpec("metLong", "long", low=0, high=9000),
              SCHEMA[3], SCHEMA[4])
    segs = DataGenerator(schema, seed=23).segments(
        4, 1500, IV, datasource="pk")
    aggs = [CountAggregator("n"), LongSumAggregator("ls", "metLong")]
    plans = [batching._plan_for(s, [], i, [IV], Granularity.of("all"),
                                aggs, None, [])
             for i, s in enumerate(segs)]
    assert all(p.eligible for p in plans)
    assert len({p.packs for p in plans}) == 1
    assert len({p.digest for p in plans}) == 1
    assert plans[0].packs                      # descriptor actually present

    # a wider-range segment: same structure, different pack width
    wide = DataGenerator(
        (SCHEMA[0], SCHEMA[1],
         ColumnSpec("metLong", "long", low=0, high=200_000),
         SCHEMA[3], SCHEMA[4]), seed=29).segments(
            1, 1500, IV, datasource="pk")[0]
    p_wide = batching._plan_for(wide, [], 0, [IV], Granularity.of("all"),
                                aggs, None, [])
    assert p_wide.eligible
    assert p_wide.packs != plans[0].packs
    assert p_wide.digest != plans[0].digest


# ---------------------------------------------------------------------------
# pallas packed-input variant (interpret mode)
# ---------------------------------------------------------------------------

def test_pallas_packed_input_bit_identical(monkeypatch):
    import jax.numpy as jnp
    from druid_tpu.engine.kernels import (CountKernel, MinMaxKernel,
                                          SumKernel)
    from druid_tpu.query.aggregators import (CountAggregator,
                                             FloatSumAggregator,
                                             LongMaxAggregator,
                                             LongMinAggregator,
                                             LongSumAggregator)

    monkeypatch.setattr(pallas_agg, "_FORCE_INTERPRET", True)
    rng = np.random.default_rng(3)
    n, groups, num_total = 20480, 300, 512
    key = np.sort(rng.integers(0, groups, size=n)).astype(np.int32)
    mask = rng.random(n) < 0.9
    vlong = rng.integers(-1000, 1000, size=n).astype(np.int32)
    vfloat = rng.normal(0.0, 100.0, size=n).astype(np.float32)
    kb = key.reshape(-1, pallas_agg.SPAN_BLOCK)
    span = int((kb.max(axis=1) - kb.min(axis=1) + 1).max())

    ks = SumKernel(LongSumAggregator("ls", "vlong"), ValueType.LONG)
    ks.chunk_rows = 1 << 20
    kernels = [CountKernel(CountAggregator("n")), ks,
               SumKernel(FloatSumAggregator("fs", "vfloat"),
                         ValueType.FLOAT),
               MinMaxKernel(LongMinAggregator("lm", "vlong"),
                            ValueType.LONG, False),
               MinMaxKernel(LongMaxAggregator("lx", "vlong"),
                            ValueType.LONG, True)]
    arrays = {"vlong": jnp.asarray(vlong), "vfloat": jnp.asarray(vfloat)}
    c0, s0 = pallas_agg.pallas_reduce(
        arrays, jnp.asarray(mask), jnp.asarray(key), kernels, num_total,
        span)

    base = -1024
    w = packed.width_for(1000, base)
    pc = packed.PackedColumn(
        jnp.asarray(packed.pack_padded(vlong, w, base)), w, base, n)
    c1, s1 = pallas_agg.pallas_reduce(
        arrays, jnp.asarray(mask), jnp.asarray(key), kernels, num_total,
        span, packed_cols={"vlong": pc})
    np.testing.assert_array_equal(np.asarray(c0), np.asarray(c1))
    for a, b in zip(s0, s1):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_pallas_rejects_mismatched_packed_descriptor(monkeypatch):
    """A descriptor whose rows disagree with the block falls back to the
    dense view — correctness never depends on packing."""
    import jax.numpy as jnp
    from druid_tpu.engine.kernels import CountKernel, SumKernel
    from druid_tpu.query.aggregators import (CountAggregator,
                                             LongSumAggregator)

    monkeypatch.setattr(pallas_agg, "_FORCE_INTERPRET", True)
    rng = np.random.default_rng(5)
    n = 4096
    key = np.sort(rng.integers(0, 50, size=n)).astype(np.int32)
    mask = np.ones(n, bool)
    vlong = rng.integers(0, 100, size=n).astype(np.int32)
    ks = SumKernel(LongSumAggregator("ls", "vlong"), ValueType.LONG)
    ks.chunk_rows = 1 << 20
    kernels = [CountKernel(CountAggregator("n")), ks]
    arrays = {"vlong": jnp.asarray(vlong)}
    wrong = packed.PackedColumn(
        jnp.asarray(packed.pack_padded(vlong[:2048], 8, 0)), 8, 0, 2048)
    c0, s0 = pallas_agg.pallas_reduce(
        arrays, jnp.asarray(mask), jnp.asarray(key), kernels, 64, 64)
    c1, s1 = pallas_agg.pallas_reduce(
        arrays, jnp.asarray(mask), jnp.asarray(key), kernels, 64, 64,
        packed_cols={"vlong": wrong})
    np.testing.assert_array_equal(np.asarray(c0), np.asarray(c1))
    np.testing.assert_array_equal(np.asarray(s0[1]), np.asarray(s1[1]))


def test_projection_pallas_path_parity_with_packing(fresh_pool, monkeypatch):
    """Executor-level: force the projection/pallas strategy (interpret
    mode) and assert packed staging keeps exact parity through the fused
    kernel — the full compressed-domain path from pool to kernel."""
    from druid_tpu.engine import grouping
    monkeypatch.setattr(pallas_agg, "_FORCE_INTERPRET", True)
    monkeypatch.setattr(grouping, "PROJECTION_MIN_ROWS", 1)
    monkeypatch.setattr(grouping, "FORCE_STRATEGY", "projection")
    segs = _segments(2, rows=3000)
    q = {
        "queryType": "groupBy", "dataSource": "pk",
        "intervals": [str(IV)], "granularity": "all",
        "dimensions": ["dimB"],          # bigger group space
        # no double aggs: the projection force needs blocked-eligible
        # kernels, and the point here is the pallas packed-input path
        "aggregations": [
            {"type": "count", "name": "n"},
            {"type": "longSum", "name": "ls", "fieldName": "metLong"},
            {"type": "longMin", "name": "lm", "fieldName": "metLong"},
            {"type": "floatSum", "name": "fs", "fieldName": "metFloat"},
        ],
    }
    dec, pk = _run_both(q, segs)
    assert dec == pk


# ---------------------------------------------------------------------------
# effective pool capacity (the ≥3x acceptance bar)
# ---------------------------------------------------------------------------

def test_pool_holds_3x_more_segments_at_fixed_budget(fresh_pool):
    """The acceptance bar on the bench's small-segment H2D-bound shape:
    narrow dims + small-range long metrics dominate the staged bytes. At a
    byte budget sized for N decoded segment stagings, packed staging must
    keep ≥ 3N segments resident."""
    n_segments, rows = 12, 2048
    schema = (ColumnSpec("dimA", "string", cardinality=12),
              ColumnSpec("dimB", "string", cardinality=12),
              ColumnSpec("dimC", "string", cardinality=12),
              ColumnSpec("dimD", "string", cardinality=12),
              ColumnSpec("dimE", "string", cardinality=12),
              ColumnSpec("m1", "long", low=0, high=15),
              ColumnSpec("m2", "long", low=0, high=200),
              ColumnSpec("m3", "long", low=0, high=200))
    segs = DataGenerator(schema, seed=9).segments(
        n_segments, rows, IV, datasource="pk")
    dvals = {d: segs[0].dims[d].dictionary.values[:6]
             for d in ("dimC", "dimD", "dimE")}
    q = {"queryType": "groupBy", "dataSource": "pk",
         "intervals": [str(IV)], "granularity": "all",
         "dimensions": ["dimA", "dimB"],
         "filter": {"type": "and", "fields": [
             {"type": "in", "dimension": d, "values": list(v)}
             for d, v in dvals.items()]},
         "aggregations": [{"type": "count", "name": "n"},
                          {"type": "longSum", "name": "s1",
                           "fieldName": "m1"},
                          {"type": "longSum", "name": "s2",
                           "fieldName": "m2"},
                          {"type": "longMin", "name": "s3",
                           "fieldName": "m3"}]}
    ex = QueryExecutor(segs)
    # pin the COLUMN filter path: this test measures the packed-staging
    # multiplier over staged filter columns; the device-bitmap filter path
    # (engine/filters.py) would stop staging dimC/D/E entirely (1 bit/row
    # resident instead of packed ids — a separate, larger win measured by
    # tests/test_filter_bitmap.py)
    from druid_tpu.engine import filters as _filters
    prev_bmp = _filters.set_device_bitmap_enabled(False)
    prev = packed.set_enabled(False)
    try:
        dec_rows = ex.run_json(q)
        decoded_per_seg = fresh_pool.snapshot().resident_bytes / n_segments
        fresh_pool.clear()
        packed.set_enabled(True)
        pk_rows = ex.run_json(q)
        s_pk = fresh_pool.snapshot()
        assert dec_rows == pk_rows                  # parity rides along
        packed_per_seg = s_pk.resident_bytes / n_segments
        multiplier = decoded_per_seg / packed_per_seg
        assert multiplier >= 3.0, (
            f"packed staging only {multiplier:.2f}x "
            f"({decoded_per_seg:.0f}B -> {packed_per_seg:.0f}B per segment)")
        assert s_pk.packed_ratio >= 3.0
        # the budget itself now holds >= 3x the segments: sized for ~4
        # decoded stagings, every packed staging stays resident at once
        budget = int(decoded_per_seg * 4)
        fresh_pool.clear()
        fresh_pool.configure(budget)
        ex.run_json(q)
        s = fresh_pool.snapshot()
        assert s.entries >= n_segments
        assert s.resident_bytes <= budget
    finally:
        packed.set_enabled(prev)
        _filters.set_device_bitmap_enabled(prev_bmp)
