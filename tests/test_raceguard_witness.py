"""Dynamic lock witness: unit tests for the recording machinery, then a
multi-threaded stress run driving broker fan-out + device-pool eviction +
metrics ticks CONCURRENTLY with every project lock wrapped — asserting

  (a) no acquisition-order violation was observed (no ABBA ran),
  (b) every observed acquisition order is an edge of raceguard's STATIC
      order graph (the analyzer's model covers reality), and
  (c) no witness-detected unguarded mutation of the watched device-pool
      counters happened (the guard discipline holds under load).

The witness is installed BEFORE the cluster objects are constructed —
instance locks are wrapped at construction time; module-level locks
imported earlier in the session stay raw (the subgraph assertion is over
whatever was observed, so unwrapped locks only shrink the sample, never
falsify it)."""
import os
import sys
import threading
import time
from pathlib import Path

import pytest

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

from tools.druidlint.core import load_config  # noqa: E402
from tools.druidlint.keywitness import KeyWitness  # noqa: E402
from tools.druidlint.lockwitness import LockWitness, WitnessLock  # noqa: E402
from tools.druidlint.raceguard import analyze_tree  # noqa: E402


# ---------------------------------------------------------------------------
# unit: recording machinery
# ---------------------------------------------------------------------------

def _wrapped_pair(w):
    a = WitnessLock(w, threading.Lock(), ("druid_tpu/a.py", 1),
                    reentrant=False)
    b = WitnessLock(w, threading.Lock(), ("druid_tpu/b.py", 2),
                    reentrant=False)
    return a, b


def test_nested_acquisition_records_edge():
    w = LockWitness(str(REPO_ROOT))
    a, b = _wrapped_pair(w)
    with a:
        with b:
            pass
    assert list(w.observed_edges()) == [(a.site, b.site)]
    assert w.order_violations() == []


def test_reentrant_acquisition_records_no_edge():
    w = LockWitness(str(REPO_ROOT))
    r = WitnessLock(w, threading.RLock(), ("druid_tpu/a.py", 1),
                    reentrant=True)
    with r:
        with r:
            pass
    assert w.observed_edges() == {}


def test_abba_is_an_order_violation():
    w = LockWitness(str(REPO_ROOT))
    a, b = _wrapped_pair(w)
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    assert w.order_violations() == [(a.site, b.site)]


def test_release_out_of_order_keeps_stack_sane():
    w = LockWitness(str(REPO_ROOT))
    a, b = _wrapped_pair(w)
    a.acquire()
    b.acquire()
    a.release()                 # hand-over-hand: release in FIFO order
    with a:                     # b still held → records (b, a)
        pass
    b.release()
    assert (b.site, a.site) in w.observed_edges()
    assert w._stack() == []


def test_condition_on_witnessed_lock_balances_stack():
    w = LockWitness(str(REPO_ROOT))
    lock = WitnessLock(w, threading.Lock(), ("druid_tpu/a.py", 1),
                       reentrant=False)
    cond = threading.Condition(lock)
    hits = []

    def waiter():
        with cond:
            cond.wait(timeout=5)
            hits.append(True)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.1)
    with cond:
        cond.notify()
    t.join(timeout=5)
    assert hits == [True]
    assert w._stack() == []     # this thread's stack drained
    assert w.order_violations() == []


def test_mutation_watch_flags_unlocked_writes():
    w = LockWitness(str(REPO_ROOT))
    lock = WitnessLock(w, threading.Lock(), ("druid_tpu/a.py", 1),
                       reentrant=False)

    class Box:
        def __init__(self):
            self.n = 0

    box = Box()
    w.watch(box, ("n",), lock)
    with lock:
        box.n = 1               # disciplined
    assert w.mutation_violations == []
    box.n = 2                   # unguarded
    assert len(w.mutation_violations) == 1
    w.uninstall()
    assert type(box).__name__ == "Box"


def test_install_uninstall_restores_factories():
    """uninstall() restores whatever install() displaced — so a per-test
    witness nested inside a session-wide one (DRUID_TPU_LOCK_WITNESS=1)
    hands control back to the OUTER witness, not the raw builtin."""
    prev_lock, prev_rlock = threading.Lock, threading.RLock
    w = LockWitness(str(REPO_ROOT)).install()
    try:
        assert threading.Lock is not prev_lock
        # constructions OUTSIDE druid_tpu (this test file) stay raw
        raw = threading.Lock()
        assert not isinstance(raw, WitnessLock)
    finally:
        w.uninstall()
    assert threading.Lock is prev_lock and threading.RLock is prev_rlock


@pytest.mark.skipif(
    __import__("os").environ.get("DRUID_TPU_LOCK_WITNESS") == "1",
    reason="session witness wrapped module locks at import; nothing to rewrap")
def test_rewrap_module_locks_covers_preinstall_globals():
    """A witness installed MID-SESSION (every per-test witness) misses
    locks constructed at import time — the jit-cache locks and the native
    registry, i.e. exactly the compile-cache edges. rewrap_module_locks
    swaps the module globals for wrappers keyed on the static assignment
    site, and uninstall() puts the raw locks back."""
    import druid_tpu.engine.batching as batching
    import druid_tpu.engine.grouping as grouping
    import druid_tpu.native as native
    import druid_tpu.parallel.distributed as distributed

    w = LockWitness(str(REPO_ROOT)).install()
    try:
        n = w.rewrap_module_locks()
        assert n >= 4, f"expected the known module locks wrapped, got {n}"
        for lk in (grouping._JIT_CACHE_LOCK, batching._JIT_CACHE_LOCK,
                   distributed._CACHE_LOCK, native._lock):
            assert isinstance(lk, WitnessLock)
        # the rewrap site IS the static identity raceguard derives, so
        # observed compile-cache edges can be checked against the graph
        cfg = load_config(REPO_ROOT)
        prog = analyze_tree(REPO_ROOT, cfg)
        sites = prog.lock_sites()
        assert grouping._JIT_CACHE_LOCK.site in sites
        assert batching._JIT_CACHE_LOCK.site in sites
        # acquisition through the module global records an edge
        outer = WitnessLock(w, threading.Lock(), ("druid_tpu/t.py", 1),
                            reentrant=False)
        with outer:
            with batching._JIT_CACHE_LOCK:
                pass
        assert (outer.site, batching._JIT_CACHE_LOCK.site) \
            in w.observed_edges()
        # idempotent: a second pass wraps nothing
        assert w.rewrap_module_locks([batching, grouping]) == 0
    finally:
        w.uninstall()
    # raw locks restored: a later witness (or none) owns them again
    assert not isinstance(grouping._JIT_CACHE_LOCK, WitnessLock)
    assert not isinstance(native._lock, WitnessLock)


def test_unexplained_edges_subgraph_check():
    from tools.druidlint.core import LintConfig
    from tools.druidlint.raceguard import analyze_sources
    src = """\
import threading

class A:
    def __init__(self, b: "B"):
        self._lock = threading.Lock()
        self.b = b

    def go(self):
        with self._lock:
            self.b.poke()

class B:
    def __init__(self):
        self._lock = threading.Lock()

    def poke(self):
        with self._lock:
            pass
"""
    cfg = LintConfig()
    cfg.root = "/nonexistent"
    prog = analyze_sources({"druid_tpu/m.py": src}, cfg)
    w = LockWitness(str(REPO_ROOT))
    a = WitnessLock(w, threading.Lock(), ("druid_tpu/m.py", 5), False)
    b = WitnessLock(w, threading.Lock(), ("druid_tpu/m.py", 14), False)
    with a:                     # A held while taking B: statically predicted
        with b:
            pass
    assert w.unexplained_edges(prog) == []
    with b:                     # B held while taking A: NOT in the graph
        with a:
            pass
    out = w.unexplained_edges(prog)
    assert len(out) == 1 and "B._lock -> " in out[0]


# ---------------------------------------------------------------------------
# the stress run (broker fan-out × pool eviction × metric ticks)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def stress_run():
    """Build a witnessed mini-cluster and hammer it from three directions
    at once; yields (witness, errors, pool, emitter)."""
    witness = LockWitness(str(REPO_ROOT)).install()
    # module-level locks (jit caches, native registry) predate this
    # install — re-wrap them so the sweep sees the compile-cache edges
    witness.rewrap_module_locks()
    key_witness = None
    try:
        from druid_tpu.cluster.broker import Broker
        from druid_tpu.cluster.view import (DataNode, InventoryView,
                                            descriptor_for)
        from druid_tpu.server.scheduler import (DataNodeScheduler,
                                                SchedulerConfig)
        from druid_tpu.data import ColumnSpec, DataGenerator
        from druid_tpu.data import devicepool as dp_mod
        from druid_tpu.data.devicepool import (DevicePoolMonitor,
                                               DeviceSegmentPool)
        from druid_tpu.engine.batching import BatchMetricsMonitor
        from druid_tpu.utils.emitter import (InMemoryEmitter,
                                             MonitorScheduler,
                                             ServiceEmitter)
        from druid_tpu.utils.intervals import Interval

        # tiny budget (a couple of staged blocks) → query rounds keep
        # evicting (the purge/evict churn PRs 2 and 4 fixed races in)
        pool = DeviceSegmentPool(budget_bytes=1 << 15)
        old_pool = dp_mod._POOL
        dp_mod._POOL = pool
        assert isinstance(pool._lock, WitnessLock)
        witness.watch(pool, ("_resident", "_hits", "_misses", "_evictions",
                             "_evicted_bytes", "_budget"), pool._lock)

        # the key-churn leg: a per-test KeyWitness rides the same stress.
        # Installed AFTER the pool swap above so it binds the stress pool
        # as its witnessed singleton (real segment keys flow through it;
        # the tiny budget forces evict→rebuild, which must reproduce each
        # key's first structural fingerprint).
        key_witness = KeyWitness(str(REPO_ROOT)).install()

        gen = DataGenerator((ColumnSpec("d", "string", cardinality=5),
                             ColumnSpec("m", "long", low=0, high=10)),
                            seed=7)
        view = InventoryView()
        nodes = [DataNode(f"n{i}") for i in range(3)]
        for n in nodes:
            view.register(n)
        for i in range(12):
            seg = gen.segment(512, Interval.of("2026-07-01", "2026-07-02"),
                              datasource="x")
            nodes[i % 3].load_segment(seg)
            view.announce(nodes[i % 3].name, descriptor_for(seg))
        broker = Broker(view)
        emitter = ServiceEmitter("stress", "local", InMemoryEmitter())
        sched = MonitorScheduler(
            emitter, [DevicePoolMonitor(pool), BatchMetricsMonitor()],
            period_seconds=60.0)

        group_q = {"queryType": "groupBy", "dataSource": "x",
                   "granularity": "all",
                   "intervals": ["2026-07-01/2026-07-02"],
                   "dimensions": ["d"],
                   "aggregations": [{"type": "longSum", "name": "s",
                                     "fieldName": "m"}]}
        ts_q = {"queryType": "timeseries", "dataSource": "x",
                "granularity": "all",
                "intervals": ["2026-07-01/2026-07-02"],
                "aggregations": [{"type": "doubleSum", "name": "s",
                                  "fieldName": "m"}]}

        errors = []
        stop = threading.Event()

        # the data-node scheduler joins the stress: submit threads (HTTP
        # handler stand-ins) racing its dispatcher exercises the
        # queue/flush handoff and the scheduler→node→engine lock chain
        scheduler = DataNodeScheduler(
            nodes[0], SchedulerConfig(batch_window_ms=2.0,
                                      max_queue_depth=64))

        # the subscription hub joins too: ingest appends + standing ticks
        # + long-poll fan-out + subscribe/unsubscribe churn drive the
        # hub↔standing↔appenderator lock chains under real concurrency
        from druid_tpu.cluster.metadata import MetadataStore
        from druid_tpu.ingest import (Appenderator, RowBatch,
                                      SegmentAllocator,
                                      StreamAppenderatorDriver)
        from druid_tpu.query.aggregators import (CountAggregator,
                                                 LongSumAggregator)
        from druid_tpu.query.model import TimeseriesQuery
        from druid_tpu.server.subscriptions import SubscriptionHub

        rt_iv = Interval.of("2026-07-01", "2026-07-02")
        app = Appenderator(
            "rtstress",
            [CountAggregator("rows"), LongSumAggregator("v", "m")],
            query_granularity="none")
        rt_driver = StreamAppenderatorDriver(
            app, SegmentAllocator(MetadataStore(), "day"), MetadataStore())
        hub = SubscriptionHub(idle_timeout_s=0)
        hub.attach(app)
        standing_q = TimeseriesQuery.of(
            "rtstress", [rt_iv],
            [LongSumAggregator("rows", "rows")], granularity="all")

        def fan_out(q, rounds):
            try:
                for _ in range(rounds):
                    broker.run_json(q)
            except Exception as e:          # pragma: no cover - must not
                errors.append(e)

        def sched_loop(rounds):
            try:
                from druid_tpu.query.model import query_from_json
                sids = [str(s.id) for s in nodes[0].segments()]
                for _ in range(rounds):
                    scheduler.submit(query_from_json(group_q), sids[:2])
            except Exception as e:          # pragma: no cover - must not
                errors.append(e)

        def tick_loop():
            try:
                while not stop.is_set():
                    sched.tick()
                    view.sync_all()
                    time.sleep(0.005)
            except Exception as e:          # pragma: no cover - must not
                errors.append(e)

        def ingest_loop():
            try:
                t0 = rt_iv.start
                n = 0
                while not stop.is_set():
                    rt_driver.add_batch(RowBatch(
                        [t0 + n * 1000 + i for i in range(8)],
                        {"m": list(range(8))}))
                    n += 1
                    if n % 7 == 0:
                        app.persist_all()
                    time.sleep(0.002)
            except Exception as e:          # pragma: no cover - must not
                errors.append(e)

        def subscribe_loop(rounds):
            try:
                for _ in range(rounds):
                    subs = [hub.subscribe(standing_q) for _ in range(4)]
                    hub.tick()
                    for sid, etag in subs:
                        hub.poll(sid, etag=etag, timeout_s=0.05)
                    for sid, _ in subs:
                        hub.unsubscribe(sid)
            except Exception as e:          # pragma: no cover - must not
                errors.append(e)

        def key_churn(rounds):
            # keyguard's dynamic leg: descriptor variety (each agg combo
            # is its own structure sig) plus live key-member flag flips —
            # DRUID_TPU_PALLAS shifts select_strategy, and the selected
            # strategy is folded into _structure_sig, so a flip must mint
            # NEW jit-cache keys, never alias builds under old ones
            try:
                variants = []
                for aggs in (
                        [{"type": "longSum", "name": "s",
                          "fieldName": "m"}],
                        [{"type": "doubleSum", "name": "s",
                          "fieldName": "m"}],
                        [{"type": "count", "name": "c"},
                         {"type": "longMax", "name": "x",
                          "fieldName": "m"}]):
                    variants.append(dict(group_q, aggregations=aggs))
                    variants.append(dict(ts_q, aggregations=aggs))
                prev = os.environ.get("DRUID_TPU_PALLAS")
                try:
                    for i in range(rounds):
                        if i % 2:
                            os.environ["DRUID_TPU_PALLAS"] = "interpret"
                        else:
                            os.environ.pop("DRUID_TPU_PALLAS", None)
                        for q in variants:
                            broker.run_json(q)
                finally:
                    if prev is None:
                        os.environ.pop("DRUID_TPU_PALLAS", None)
                    else:
                        os.environ["DRUID_TPU_PALLAS"] = prev
            except Exception as e:          # pragma: no cover - must not
                errors.append(e)

        def churn_loop():
            # segment churn: dropped generations GC while queries run,
            # driving the finalizer path concurrently with eviction
            try:
                while not stop.is_set():
                    s = gen.segment(512,
                                    Interval.of("2026-07-01", "2026-07-02"),
                                    datasource="churn")
                    s.device_block(["m"])
                    del s
                    time.sleep(0.002)
            except Exception as e:          # pragma: no cover - must not
                errors.append(e)

        workers = [threading.Thread(target=fan_out, args=(group_q, 6)),
                   threading.Thread(target=fan_out, args=(group_q, 6)),
                   threading.Thread(target=fan_out, args=(ts_q, 6)),
                   threading.Thread(target=fan_out, args=(ts_q, 6)),
                   threading.Thread(target=sched_loop, args=(6,)),
                   threading.Thread(target=sched_loop, args=(6,)),
                   threading.Thread(target=subscribe_loop, args=(4,)),
                   threading.Thread(target=subscribe_loop, args=(4,)),
                   threading.Thread(target=key_churn, args=(2,)),
                   threading.Thread(target=tick_loop, daemon=True),
                   threading.Thread(target=ingest_loop, daemon=True),
                   threading.Thread(target=churn_loop, daemon=True)]
        for t in workers:
            t.start()
        for t in workers[:9]:
            t.join(timeout=300)
        stop.set()
        scheduler.stop()
        hub.stop()
        for t in workers[9:]:
            t.join(timeout=10)

        yield witness, errors, pool, emitter, key_witness
        dp_mod._POOL = old_pool
    finally:
        # inner-out: the key witness was installed after (and may wrap)
        # the session-wide one's hooks; restore before the lock witness
        try:
            if key_witness is not None:
                key_witness.uninstall()
        finally:
            witness.uninstall()


def test_stress_completes_without_errors(stress_run):
    witness, errors, pool, emitter, _ = stress_run
    assert errors == []
    s = pool.snapshot()
    assert s.hits + s.misses > 0, "the pool was never exercised"
    assert s.evictions > 0, "the byte budget never forced eviction"


def test_stress_no_order_violation(stress_run):
    witness, errors, *_ = stress_run
    assert witness.order_violations() == []


def test_stress_observed_orders_are_statically_predicted(stress_run):
    """Acceptance: the acquisition-order graph OBSERVED under real
    concurrency is a subgraph of raceguard's static MAY graph."""
    witness, *_ = stress_run
    prog = analyze_tree(REPO_ROOT, load_config(REPO_ROOT))
    assert witness.unexplained_edges(prog) == []


def test_stress_no_unguarded_pool_mutation(stress_run):
    """Every mutation of the watched pool counters happened under the pool
    lock — the dynamic confirmation of the unguarded-shared-write burn."""
    witness, *_ = stress_run
    assert witness.mutation_violations == []


def test_stress_emitted_pool_metrics(stress_run):
    emitter = stress_run[3]
    names = {e.metric for e in emitter.sink.events}
    assert "segment/devicePool/residentBytes" in names


def test_stress_key_witness_no_collisions(stress_run):
    """The key-churn leg: descriptor variety + live-flag flips churned
    the jit caches while eviction churn forced pool rebuilds — every
    same-key build must reproduce its first structural fingerprint."""
    *_, kw = stress_run
    assert kw.collisions == []
    builds = sum(c.get("build", 0) for c in kw.counts.values())
    assert builds > 0, "the key churn never drove a witnessed cache build"
