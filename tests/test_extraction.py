"""Extraction fns + registered lookups (reference: query/extraction/*,
query/lookup/LookupReferencesManager)."""
import numpy as np

from druid_tpu.engine import QueryExecutor
from druid_tpu.query.lookup import lookup_manager, register_lookup
from druid_tpu.query.model import (CascadeExtractionFn, ExtractionDimensionSpec,
                                   GroupByQuery, RegisteredLookupExtractionFn,
                                   StringFormatExtractionFn, StrlenExtractionFn,
                                   SubstringExtractionFn, TimeFormatExtractionFn,
                                   extractionfn_from_json)
from druid_tpu.query.aggregators import CountAggregator
from tests.conftest import DAY, rows_as_frame


def test_serde_round_trip():
    fns = [
        StrlenExtractionFn(),
        StringFormatExtractionFn("[%s]"),
        TimeFormatExtractionFn("yyyy-MM-dd", "day"),
        CascadeExtractionFn((SubstringExtractionFn(0, 2),)),
        RegisteredLookupExtractionFn("x", False, "?"),
    ]
    for fn in fns:
        j = fn.to_json()
        assert extractionfn_from_json(j).to_json() == j


def test_time_format():
    fn = TimeFormatExtractionFn("EEEE")
    assert fn.apply("2026-01-02") == "Friday"
    fn = TimeFormatExtractionFn(None, "month")
    assert fn.apply("2026-01-15T10:00:00Z") == "2026-01-01T00:00:00.000Z"


def test_registered_lookup_versioning():
    m = lookup_manager()
    assert register_lookup("tl", {"a": "1"}, "v1")
    assert not m.add("tl", {"a": "2"}, "v0")  # stale version rejected
    assert m.add("tl", {"a": "2"}, "v2")
    assert m.get("tl").mapping == {"a": "2"}
    snap = m.snapshot()
    assert any(s["name"] == "tl" and s["version"] == "v2" for s in snap)


def test_groupby_with_registered_lookup(segment):
    dict_vals = list(segment.dims["dimA"].dictionary.values)
    m = {dict_vals[0]: "ZERO", dict_vals[1]: "ONE"}
    register_lookup("dimA-names", m, "v9")
    q = GroupByQuery.of(
        "test", [DAY],
        [ExtractionDimensionSpec("dimA", "named",
                                 RegisteredLookupExtractionFn("dimA-names"))],
        [CountAggregator("rows")], granularity="all")
    rows = QueryExecutor([segment]).run(q)
    frame = rows_as_frame(segment)
    got = {r["event"]["named"]: r["event"]["rows"] for r in rows}
    assert "ZERO" in got and "ONE" in got
    vals, counts = np.unique(frame["dimA"], return_counts=True)
    want = {}
    for v, c in zip(vals, counts):
        want[m.get(v, v)] = want.get(m.get(v, v), 0) + int(c)
    assert got == want
