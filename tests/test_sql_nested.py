"""Nested SQL: FROM (SELECT ...) subqueries planning onto the native
inner_query mechanism (reference: DruidOuterQueryRel +
GroupByStrategyV2.processSubqueryResult)."""
import numpy as np
import pytest

from druid_tpu.engine import QueryExecutor
from druid_tpu.sql import PlannerError, SqlExecutor
from tests.conftest import rows_as_frame


@pytest.fixture(scope="module")
def sql(segments):
    return SqlExecutor(QueryExecutor(segments))


@pytest.fixture(scope="module")
def frames(segments):
    return [rows_as_frame(s) for s in segments]


def test_avg_of_grouped_sums(sql, frames):
    """The canonical nested aggregate: average per-dimA total."""
    cols, rows = sql.execute(
        "SELECT AVG(s) a, COUNT(*) n FROM "
        "(SELECT dimA, SUM(metLong) s FROM test GROUP BY dimA)")
    sums = {}
    for f in frames:
        for d, v in zip(f["dimA"], f["metLong"]):
            sums[d] = sums.get(d, 0) + int(v)
    want_avg = sum(sums.values()) / len(sums)
    assert rows[0][1] == len(sums)
    assert rows[0][0] == pytest.approx(want_avg, rel=1e-9)


def test_regroup_inner_dims(sql, frames):
    """Outer GROUP BY over a projected inner dimension with aliasing."""
    cols, rows = sql.execute(
        "SELECT p, COUNT(*) n, SUM(total) t FROM "
        "(SELECT SUBSTRING(dimB, 1, 3) p2, dimA p, SUM(metLong) total "
        " FROM test GROUP BY 1, 2) "
        "GROUP BY p ORDER BY p")
    per_a = {}
    for f in frames:
        for a, v in zip(f["dimA"], f["metLong"]):
            per_a[a] = per_a.get(a, 0) + int(v)
    got = {r[0]: (r[1], r[2]) for r in rows}
    assert set(got) == set(per_a)
    for a, (n, t) in got.items():
        assert t == per_a[a]


def test_filter_on_inner_aggregate(sql, frames):
    """WHERE over the inner's aggregate output (the HAVING-as-outer-filter
    pattern)."""
    cols, rows = sql.execute(
        "SELECT COUNT(*) FROM "
        "(SELECT dimB, COUNT(*) c FROM test GROUP BY dimB) "
        "WHERE c > 100")
    counts = {}
    for f in frames:
        for b in f["dimB"]:
            counts[b] = counts.get(b, 0) + 1
    want = sum(1 for v in counts.values() if v > 100)
    assert rows[0][0] == want > 0


def test_nested_requires_group_by(sql):
    with pytest.raises(PlannerError):
        sql.execute("SELECT COUNT(*) FROM "
                    "(SELECT __time, dimA FROM test LIMIT 5)")


def test_nested_explain_shows_query_datasource(sql):
    plan = sql.explain(
        "SELECT AVG(s) FROM "
        "(SELECT dimA, SUM(metLong) s FROM test GROUP BY dimA)")
    assert plan["dataSource"]["type"] == "query"
    assert plan["dataSource"]["query"]["queryType"] == "groupBy"


def test_nested_with_alias_and_deeper_nesting(sql, frames):
    cols, rows = sql.execute(
        "SELECT MAX(a) FROM "
        "(SELECT p, AVG(s) a FROM "
        " (SELECT dimA p, dimB, SUM(metLong) s FROM test GROUP BY 1, 2) t1 "
        " GROUP BY p) AS t2")
    per = {}
    for f in frames:
        for a, b, v in zip(f["dimA"], f["dimB"], f["metLong"]):
            per.setdefault(a, {}).setdefault(b, 0)
            per[a][b] += int(v)
    want = max(sum(d.values()) / len(d) for d in per.values())
    assert rows[0][0] == pytest.approx(want, rel=1e-9)


def test_nested_numeric_expression_dim_sums_correctly(sql, frames):
    """Numeric inner dimension outputs materialize as numeric columns —
    the outer SUM must be arithmetic, not a sum over stringified values."""
    cols, rows = sql.execute(
        "SELECT SUM(e) FROM "
        "(SELECT MOD(metLong, 10) e, dimA FROM test GROUP BY 1, 2)")
    per = set()
    for f in frames:
        for a, v in zip(f["dimA"], f["metLong"]):
            per.add((int(v) % 10, a))
    want = sum(e for e, _ in per)
    assert rows[0][0] == want


def test_nested_duplicate_alias_rejected(sql):
    with pytest.raises(PlannerError, match="two aliases"):
        sql.execute(
            "SELECT SUM(a) sa, SUM(b) sb FROM "
            "(SELECT dimA, SUM(metLong) a, SUM(metLong) b FROM test "
            " GROUP BY dimA)")


def test_nested_authorization_uses_real_tables(segments):
    from druid_tpu.server.security import (AuthChain, Permission, READ,
                                           AuthenticationResult,
                                           RoleBasedAuthorizer,
                                           authorizer_for_query,
                                           resource_actions_for_query)
    sql2 = SqlExecutor(QueryExecutor(segments))
    tables, is_meta = sql2.tables_of(
        "SELECT SUM(s) FROM "
        "(SELECT dimA, SUM(metLong) s FROM test GROUP BY dimA)")
    assert tables == ["test"]
    chain = AuthChain(authorizers={"rbac": RoleBasedAuthorizer(
        {"r": [Permission("test", actions=(READ,))]}, {"alice": ["r"]}),
        "allowAll": __import__(
            "druid_tpu.server.security",
            fromlist=["AllowAllAuthorizer"]).AllowAllAuthorizer()})
    check = authorizer_for_query(chain)
    plan = sql2._plan(__import__(
        "druid_tpu.sql.parser", fromlist=["parse_sql"]).parse_sql(
        "SELECT SUM(s) FROM "
        "(SELECT dimA, SUM(metLong) s FROM test GROUP BY dimA)"))
    alice = AuthenticationResult("alice", "rbac")
    assert check(alice, plan.native)
