"""Unit-level pallas_reduce parity in interpret mode: the fused kernel run
through `pl.pallas_call(..., interpret=True)` on CPU must match the XLA
segment-reduction semantics exactly for count/sum/min/max over the same
synthetic sorted projection — so contract violations the static tracecheck
pass cannot see (arithmetic bugs, limb-flush drift) still fail off-chip in
tier-1, not on the chip suite.

The executor-level equivalents live in test_strategies.py; these tests call
pallas_reduce directly so a failure pinpoints the kernel, not the plan."""
import numpy as np
import pytest

import druid_tpu.engine  # noqa: F401  (x64 on before jax numerics)
from druid_tpu.data.segment import ValueType
from druid_tpu.engine import pallas_agg
from druid_tpu.engine.kernels import (CountKernel, MinMaxKernel, SumKernel,
                                      make_kernel)
from druid_tpu.query.aggregators import (CountAggregator,
                                         FloatSumAggregator,
                                         LongMaxAggregator,
                                         LongMinAggregator,
                                         LongSumAggregator)

INT32_MAX = 2 ** 31 - 1
INT32_MIN = -(2 ** 31)


def _sorted_projection(rng, n, groups, lo, hi):
    """Sorted compact keys (the Projection layout) + value columns."""
    key = np.sort(rng.integers(0, groups, size=n)).astype(np.int32)
    mask = rng.random(n) < 0.9
    vlong = rng.integers(lo, hi, size=n).astype(np.int32)
    vfloat = rng.normal(0.0, 100.0, size=n).astype(np.float32)
    # span exactly as Projection measures it: max key spread per
    # SPAN_BLOCK-row block
    pad = (-n) % pallas_agg.SPAN_BLOCK
    kp = np.concatenate([key, np.full(pad, key[-1], np.int32)]) if pad else key
    kb = kp.reshape(-1, pallas_agg.SPAN_BLOCK)
    span = int((kb.max(axis=1) - kb.min(axis=1) + 1).max())
    return key, mask, vlong, vfloat, span


def _ground_truth(key, mask, vlong, vfloat, num_total):
    counts = np.zeros(num_total, np.int64)
    lsum = np.zeros(num_total, np.int64)
    fsum = np.zeros(num_total, np.float64)
    lmin = np.full(num_total, INT32_MAX, np.int64)
    lmax = np.full(num_total, INT32_MIN, np.int64)
    np.add.at(counts, key[mask], 1)
    np.add.at(lsum, key[mask], vlong[mask].astype(np.int64))
    np.add.at(fsum, key[mask], vfloat[mask].astype(np.float64))
    np.minimum.at(lmin, key[mask], vlong[mask].astype(np.int64))
    np.maximum.at(lmax, key[mask], vlong[mask].astype(np.int64))
    return counts, lsum, fsum, lmin, lmax


def _kernels(chunk_rows):
    kc = CountKernel(CountAggregator("rows"))
    ks = SumKernel(LongSumAggregator("lsum", "vlong"), ValueType.LONG)
    ks.chunk_rows = chunk_rows        # what segment staging derives on-disk
    kf = SumKernel(FloatSumAggregator("fsum", "vfloat"), ValueType.FLOAT)
    kmin = MinMaxKernel(LongMinAggregator("lmin", "vlong"),
                        ValueType.LONG, False)
    kmax = MinMaxKernel(LongMaxAggregator("lmax", "vlong"),
                        ValueType.LONG, True)
    return [kc, ks, kf, kmin, kmax]


def _run_pallas(key, mask, vlong, vfloat, kernels, num_total, span,
                monkeypatch):
    import jax.numpy as jnp
    monkeypatch.setattr(pallas_agg, "_FORCE_INTERPRET", True)
    col_dtypes = {"vlong": np.dtype(np.int32), "vfloat": np.dtype(np.float32)}
    assert pallas_agg.usable(kernels, col_dtypes, span, num_total)
    counts, states = pallas_agg.pallas_reduce(
        {"vlong": jnp.asarray(vlong), "vfloat": jnp.asarray(vfloat)},
        jnp.asarray(mask), jnp.asarray(key), kernels, num_total, span)
    return np.asarray(counts), [np.asarray(s) for s in states]


def test_interpret_parity_count_sum_min_max(monkeypatch):
    rng = np.random.default_rng(11)
    key, mask, vlong, vfloat, span = _sorted_projection(
        rng, 20_000, 300, -1000, 1000)
    num_total = 512
    counts, states = _run_pallas(key, mask, vlong, vfloat,
                                 _kernels(chunk_rows=1 << 20), num_total,
                                 span, monkeypatch)
    gt_counts, gt_lsum, gt_fsum, gt_lmin, gt_lmax = _ground_truth(
        key, mask, vlong, vfloat, num_total)
    np.testing.assert_array_equal(counts.astype(np.int64), gt_counts)
    np.testing.assert_array_equal(np.asarray(states[0], np.int64), gt_counts)
    np.testing.assert_array_equal(np.asarray(states[1], np.int64), gt_lsum)
    np.testing.assert_allclose(states[2], gt_fsum, rtol=1e-5, atol=1e-2)
    # min/max states carry int32 identities for empty groups — exactly the
    # contract identities declared in engine/contracts.py
    np.testing.assert_array_equal(states[3].astype(np.int64), gt_lmin)
    np.testing.assert_array_equal(states[4].astype(np.int64), gt_lmax)


def test_interpret_limb_flush_exact_over_int32(monkeypatch):
    """Totals far above int32 must survive the lo/hi limb flushes exactly
    (chunk_rows small → flush every couple of blocks)."""
    rng = np.random.default_rng(7)
    key, mask, vlong, vfloat, span = _sorted_projection(
        rng, 80_000, 6, 200_000, 260_000)
    num_total = 8
    counts, states = _run_pallas(key, mask, vlong, vfloat,
                                 _kernels(chunk_rows=4096), num_total,
                                 span, monkeypatch)
    gt_counts, gt_lsum, *_ = _ground_truth(key, mask, vlong, vfloat,
                                           num_total)
    assert gt_lsum.max() > 2 ** 31          # the sums genuinely overflow
    np.testing.assert_array_equal(counts.astype(np.int64), gt_counts)
    np.testing.assert_array_equal(np.asarray(states[1], np.int64), gt_lsum)


def test_usable_rejects_contract_cap_violations():
    """usable() enforces the same caps contracts.py declares for the static
    pass — group cap and ineligible dtypes fall back to XLA strategies."""
    from druid_tpu.engine import contracts
    kernels = _kernels(chunk_rows=1 << 20)
    dts = {"vlong": np.dtype(np.int32), "vfloat": np.dtype(np.float32)}
    pallas_agg.force_interpret(True)
    try:
        assert pallas_agg.usable(kernels, dts, 16, 512)
        assert not pallas_agg.usable(kernels, dts, 16,
                                     contracts.MAX_PALLAS_GROUPS + 1)
        assert not pallas_agg.usable(kernels, dts, pallas_agg.MAX_W + 1, 512)
        # float64 column: SumKernel(FLOAT) has no pallas op for it
        assert not pallas_agg.usable(
            kernels, {"vlong": np.dtype(np.int32),
                      "vfloat": np.dtype(np.float64)}, 16, 512)
    finally:
        pallas_agg.force_interpret(False)


def test_make_kernel_chunked_long_sum_matches_unit_setup():
    """The chunk_rows the unit tests pin by hand is what segment staging
    actually derives for an int32-staged long column (keeps the fixture
    honest against the SumKernel analysis)."""
    from druid_tpu.data.generator import ColumnSpec, DataGenerator
    from druid_tpu.utils.intervals import Interval
    seg = DataGenerator(
        (ColumnSpec("metLong", "long", low=-1000, high=1000),),
        seed=3).segment(4096, Interval.of("2026-01-01", "2026-01-02"))
    k = make_kernel(LongSumAggregator("lsum", "metLong"), seg)
    assert isinstance(k, SumKernel)
    assert k.chunk_rows >= 2048         # pallas-eligible per pallas_op
    assert k.pallas_op({"metLong": np.dtype(np.int32)}) is not None
