"""SQL layer golden tests, modeled on the reference's CalciteQueryTest
(sql/src/test/.../calcite/CalciteQueryTest.java:139 — every feature asserted
as (expected native plan, expected results) against in-process segments)."""
import json

import numpy as np
import pytest

from druid_tpu.engine import QueryExecutor
from druid_tpu.sql import PlannerError, SqlExecutor, parse_sql, plan_sql
from tests.conftest import rows_as_frame


@pytest.fixture(scope="module")
def sql(segments):
    return SqlExecutor(QueryExecutor(segments))


@pytest.fixture(scope="module")
def frames(segments):
    return [rows_as_frame(s) for s in segments]


def _concat(frames, col):
    return np.concatenate([f[col] for f in frames])


# ---------------------------------------------------------------------------
# plan goldens (query-type selection mirrors DruidQuery.toDruidQuery)
# ---------------------------------------------------------------------------

PLAN_GOLDENS = [
    ("SELECT COUNT(*) FROM test", "timeseries"),
    ("SELECT dimA, COUNT(*) FROM test GROUP BY dimA", "groupBy"),
    ("SELECT dimA, COUNT(*) c FROM test GROUP BY dimA ORDER BY c DESC LIMIT 5",
     "topN"),
    ("SELECT __time, dimA FROM test LIMIT 3", "scan"),
    ("SELECT MAX(__time) FROM test", "timeBoundary"),
    ("SELECT FLOOR(__time TO DAY), COUNT(*) FROM test GROUP BY 1",
     "timeseries"),
    ("SELECT DISTINCT dimA FROM test", "groupBy"),
    # ORDER BY dim (not metric) must NOT become topN
    ("SELECT dimA, COUNT(*) c FROM test GROUP BY dimA ORDER BY dimA LIMIT 5",
     "groupBy"),
    # HAVING forces groupBy
    ("SELECT dimA, COUNT(*) c FROM test GROUP BY dimA HAVING COUNT(*) > 1 "
     "ORDER BY c DESC LIMIT 5", "groupBy"),
]


@pytest.mark.parametrize("stmt,qtype", PLAN_GOLDENS)
def test_plan_golden(sql, stmt, qtype):
    plan = sql.explain(stmt)
    assert plan["queryType"] == qtype, json.dumps(plan, indent=1)


def test_plan_filter_shape(sql):
    plan = sql.explain("SELECT COUNT(*) FROM test WHERE dimA = 'x' "
                       "AND metLong >= 5 AND dimB IN ('a','b')")
    f = plan["filter"]
    assert f["type"] == "and"
    types = sorted(x["type"] for x in f["fields"])
    assert types == ["bound", "in", "selector"]


def test_plan_time_interval(sql):
    plan = sql.explain(
        "SELECT COUNT(*) FROM test WHERE __time >= TIMESTAMP '2026-01-01' "
        "AND __time < TIMESTAMP '2026-01-02'")
    assert plan["intervals"] == ["2026-01-01T00:00:00.000Z/2026-01-02T00:00:00.000Z"]
    assert plan["filter"] is None


# ---------------------------------------------------------------------------
# result goldens
# ---------------------------------------------------------------------------

def test_count_star(sql, frames):
    cols, rows = sql.execute("SELECT COUNT(*) n FROM test")
    assert cols == ["n"]
    assert rows == [[sum(len(f["__time"]) for f in frames)]]


def test_filtered_sum(sql, frames):
    cols, rows = sql.execute(
        "SELECT SUM(metLong) s FROM test WHERE dimA = ?",
        parameters=[frames[0]["dimA"][0]])
    v = frames[0]["dimA"][0]
    want = sum(int(f["metLong"][f["dimA"] == v].sum()) for f in frames)
    assert rows == [[want]]


def test_groupby_results(sql, frames):
    cols, rows = sql.execute(
        "SELECT dimA, COUNT(*) n, SUM(metLong) s FROM test "
        "GROUP BY dimA ORDER BY dimA")
    a = _concat(frames, "dimA")
    m = _concat(frames, "metLong")
    want = []
    for v in sorted(set(a)):
        sel = a == v
        want.append([v, int(sel.sum()), int(m[sel].sum())])
    assert rows == want


def test_topn_matches_groupby(sql):
    _, t = sql.execute("SELECT dimB, SUM(metLong) s FROM test "
                       "GROUP BY dimB ORDER BY s DESC LIMIT 7")
    plan = sql.explain("SELECT dimB, SUM(metLong) s FROM test "
                       "GROUP BY dimB ORDER BY s DESC LIMIT 7")
    assert plan["queryType"] == "topN"
    # same statement forced down the groupBy path via HAVING no-op
    _, g = sql.execute("SELECT dimB, SUM(metLong) s FROM test GROUP BY dimB "
                       "HAVING SUM(metLong) > -1 ORDER BY s DESC LIMIT 7")
    assert [r[0] for r in t] == [r[0] for r in g]
    assert [pytest.approx(r[1]) for r in t] == [r[1] for r in g]


def test_avg_postagg(sql, frames):
    _, rows = sql.execute("SELECT AVG(metFloat) a FROM test")
    m = _concat(frames, "metFloat")
    assert rows[0][0] == pytest.approx(float(m.sum()) / len(m), rel=1e-5)


def test_time_floor_day(sql, frames):
    _, rows = sql.execute("SELECT FLOOR(__time TO DAY) d, COUNT(*) n "
                          "FROM test GROUP BY 1 ORDER BY d")
    t = _concat(frames, "__time")
    days = (t // 86400000) * 86400000
    want_counts = [int((days == d).sum()) for d in sorted(set(days))]
    assert [r[1] for r in rows] == want_counts
    assert rows[0][0].endswith("T00:00:00.000Z")


def test_having(sql, frames):
    _, rows = sql.execute("SELECT dimB, COUNT(*) n FROM test GROUP BY dimB "
                          "HAVING COUNT(*) > 500 ORDER BY n DESC")
    b = _concat(frames, "dimB")
    vals, counts = np.unique(b, return_counts=True)
    want = sorted([int(c) for c in counts if c > 500], reverse=True)
    assert [r[1] for r in rows] == want


def test_scan_with_filter_and_limit(sql, frames):
    _, rows = sql.execute(
        "SELECT __time, dimA, metLong FROM test WHERE metLong > 90 "
        "ORDER BY __time LIMIT 10")
    assert len(rows) == 10
    assert all(r[2] > 90 for r in rows)
    times = [r[0] for r in rows]
    assert times == sorted(times)


def test_count_distinct_approx(sql, frames):
    _, rows = sql.execute("SELECT COUNT(DISTINCT dimHi) u FROM test")
    exact = len(set(_concat(frames, "dimHi")))
    assert rows[0][0] == pytest.approx(exact, rel=0.05)


def test_case_expression_aggregate(sql, frames):
    _, rows = sql.execute(
        "SELECT SUM(CASE WHEN metLong > 50 THEN 1 ELSE 0 END) hi FROM test")
    m = _concat(frames, "metLong")
    assert rows[0][0] == pytest.approx(int((m > 50).sum()))


def test_filter_clause_aggregate(sql, frames):
    _, rows = sql.execute(
        "SELECT COUNT(*) FILTER (WHERE metLong > 50) hi, COUNT(*) n FROM test")
    m = _concat(frames, "metLong")
    assert rows[0] == [int((m > 50).sum()), len(m)]


def test_between_and_bounds(sql, frames):
    _, rows = sql.execute(
        "SELECT COUNT(*) n FROM test WHERE metLong BETWEEN 10 AND 20")
    m = _concat(frames, "metLong")
    assert rows[0][0] == int(((m >= 10) & (m <= 20)).sum())


def test_arithmetic_over_aggs(sql, frames):
    _, rows = sql.execute("SELECT SUM(metLong) / COUNT(*) r FROM test")
    m = _concat(frames, "metLong")
    assert rows[0][0] == pytest.approx(float(m.sum()) / len(m))


def test_substring_group(sql, frames):
    _, rows = sql.execute("SELECT SUBSTRING(dimA, 1, 6) p, COUNT(*) n "
                          "FROM test GROUP BY 1 ORDER BY p")
    a = _concat(frames, "dimA")
    pre = np.asarray([v[:6] for v in a])
    want = [[v, int((pre == v).sum())] for v in sorted(set(pre))]
    assert rows == want


def test_min_max_time_boundary(sql, frames):
    _, rows = sql.execute("SELECT MIN(__time) mn, MAX(__time) mx FROM test")
    t = _concat(frames, "__time")
    from druid_tpu.utils.intervals import ts_to_iso
    assert rows == [[ts_to_iso(int(t.min())), ts_to_iso(int(t.max()))]]


def test_information_schema(sql):
    _, rows = sql.execute("SELECT TABLE_NAME FROM INFORMATION_SCHEMA.TABLES")
    assert rows == [["test"]]
    _, rows = sql.execute(
        "SELECT COLUMN_NAME, DATA_TYPE FROM INFORMATION_SCHEMA.COLUMNS "
        "WHERE TABLE_NAME = 'test' AND DATA_TYPE = 'VARCHAR'")
    names = [r[0] for r in rows]
    assert "dimA" in names and "dimB" in names and "metLong" not in names


def test_planner_errors(sql):
    with pytest.raises(PlannerError):
        sql.execute("SELECT nosuchcol FROM test")
    with pytest.raises(PlannerError):
        sql.execute("SELECT * FROM nosuchtable")
    with pytest.raises(PlannerError):
        sql.execute("SELECT dimA FROM test ORDER BY dimA")  # scan orders by time only


def test_count_col_with_filter_clause(sql, frames):
    # COUNT(col) FILTER (WHERE ...) must AND both predicates
    _, rows = sql.execute(
        "SELECT COUNT(dimA) FILTER (WHERE metLong > 50) c FROM test")
    m = _concat(frames, "metLong")
    a = _concat(frames, "dimA")
    want = int(((m > 50) & (a != "")).sum())
    assert rows[0][0] == want


def test_timeseries_order_by_agg(sql, frames):
    _, rows = sql.execute(
        "SELECT FLOOR(__time TO DAY) d, SUM(metLong) s FROM test "
        "GROUP BY 1 ORDER BY s DESC LIMIT 1")
    t = _concat(frames, "__time")
    m = _concat(frames, "metLong")
    days = (t // 86400000) * 86400000
    best = max(sorted(set(days)), key=lambda d: m[days == d].sum())
    from druid_tpu.utils.intervals import ts_to_iso
    assert rows == [[ts_to_iso(int(best)),
                     pytest.approx(int(m[days == best].sum()))]]


def test_time_between(sql, frames):
    _, rows = sql.execute(
        "SELECT COUNT(*) n FROM test WHERE __time BETWEEN "
        "TIMESTAMP '2026-01-01' AND TIMESTAMP '2026-01-02'")
    t = _concat(frames, "__time")
    lo, hi = 1767225600000, 1767312000000
    assert rows[0][0] == int(((t >= lo) & (t <= hi)).sum())


def test_time_bound_under_or(sql, frames):
    # __time comparison that can't become an interval → numeric bound filter
    _, rows = sql.execute(
        "SELECT COUNT(*) n FROM test WHERE "
        "__time >= TIMESTAMP '2026-01-03' OR dimA = 'nope'")
    t = _concat(frames, "__time")
    assert rows[0][0] == int((t >= 1767398400000).sum())


def test_contradictory_time_range_zero_count(sql):
    """A scalar aggregate always yields its one row — a contradictory time
    range counts 0, matching the filter-matches-nothing case."""
    _, rows = sql.execute(
        "SELECT COUNT(*) n FROM test WHERE __time >= TIMESTAMP '2026-02-01' "
        "AND __time < TIMESTAMP '2026-01-01'")
    assert rows == [[0]]


def test_floor_to_unit_in_where(sql, frames):
    """Uniform FLOOR..TO units translate to timestamp_floor millis math in
    WHERE; calendar units (non-uniform in millis) still reject."""
    cols, rows = sql.execute(
        "SELECT COUNT(*) FROM test "
        "WHERE FLOOR(__time TO DAY) = TIMESTAMP '2026-01-01'")
    t = _concat(frames, "__time")
    day0 = (t // 86_400_000) * 86_400_000
    from druid_tpu.utils.intervals import parse_ts
    want = int((day0 == parse_ts("2026-01-01")).sum())
    assert rows[0][0] == want > 0
    with pytest.raises(PlannerError):
        sql.execute("SELECT COUNT(*) FROM test "
                    "WHERE FLOOR(__time TO MONTH) = TIMESTAMP '2026-01-01'")


def test_parse_errors():
    from druid_tpu.sql.parser import SqlParseError
    with pytest.raises(SqlParseError):
        parse_sql("SELECT FROM x")
    with pytest.raises(SqlParseError):
        parse_sql("SELECT a FROM t WHERE")
    with pytest.raises(SqlParseError):
        parse_sql("SELECT a FROM t extra garbage ,")
