"""Seeded query fuzz vs a pure-numpy oracle.

The reference's per-type suites (TopNQueryRunnerTest, GroupByQueryRunnerTest,
TimeseriesQueryRunnerTest — thousands of handwritten cases) pin engine
semantics by sheer breadth. Here breadth comes from a DETERMINISTIC fuzzer:
random-but-seeded (filter, aggregations, granularity, dimensions) combos run
through the real engine AND through an independent numpy reimplementation;
results must match exactly (counts/sums) or to float tolerance.
"""
import numpy as np
import pytest

from druid_tpu.engine import QueryExecutor
from druid_tpu.query import aggregators as A
from druid_tpu.query import filters as F
from druid_tpu.query.model import (DefaultDimensionSpec, GroupByQuery,
                                   TimeseriesQuery)
from druid_tpu.utils.intervals import Interval
from tests.conftest import rows_as_frame

WEEK = Interval.of("2026-01-01", "2026-01-08")
N_CASES = 30


@pytest.fixture(scope="module")
def frames(segments):
    return [rows_as_frame(s) for s in segments]


def _rand_filter(rng, frames):
    """A random filter tree (depth ≤ 2) + its oracle mask function."""
    dims = ["dimA", "dimB"]
    kind = rng.integers(0, 6)
    if kind == 0:
        d = dims[rng.integers(0, 2)]
        vals = sorted({v for f in frames for v in f[d]})
        v = vals[rng.integers(0, len(vals))]
        return F.SelectorFilter(d, v), lambda f: f[d] == v
    if kind == 1:
        d = dims[rng.integers(0, 2)]
        vals = sorted({v for f in frames for v in f[d]})
        pick = [vals[i] for i in
                rng.choice(len(vals), size=min(3, len(vals)), replace=False)]
        return F.InFilter(d, tuple(pick)), \
            lambda f: np.isin(f[d], pick)
    if kind == 2:
        lo = int(rng.integers(0, 50))
        hi = lo + int(rng.integers(1, 60))
        flt = F.BoundFilter("metLong", lower=str(lo), upper=str(hi),
                            ordering="numeric")
        return flt, lambda f: (f["metLong"] >= lo) & (f["metLong"] <= hi)
    if kind == 3:
        sub, fn = _rand_filter(rng, frames)
        return F.NotFilter(sub), lambda f: ~fn(f)
    if kind == 4:
        a, fa = _rand_filter(rng, frames)
        b, fb = _rand_filter(rng, frames)
        return F.AndFilter((a, b)), lambda f: fa(f) & fb(f)
    a, fa = _rand_filter(rng, frames)
    b, fb = _rand_filter(rng, frames)
    return F.OrFilter((a, b)), lambda f: fa(f) | fb(f)


def _rand_aggs(rng):
    """(specs, oracle fns name → (frame, mask) → value)."""
    pool = [
        (lambda i: A.CountAggregator(f"a{i}"),
         lambda f, m: int(m.sum())),
        (lambda i: A.LongSumAggregator(f"a{i}", "metLong"),
         lambda f, m: int(f["metLong"][m].sum())),
        (lambda i: A.DoubleSumAggregator(f"a{i}", "metDouble"),
         lambda f, m: float(f["metDouble"][m].astype(np.float64).sum())),
        (lambda i: A.FloatMaxAggregator(f"a{i}", "metFloat"),
         lambda f, m: float(f["metFloat"][m].max()) if m.any()
         else float("-inf")),
        (lambda i: A.LongMinAggregator(f"a{i}", "metLong"),
         lambda f, m: int(f["metLong"][m].min()) if m.any()
         else np.iinfo(np.int64).max),
    ]
    picks = rng.choice(len(pool), size=int(rng.integers(1, 4)),
                       replace=True)
    specs, oracles = [], {}
    for i, p in enumerate(picks):
        mk, oracle = pool[p]
        spec = mk(i)
        specs.append(spec)
        oracles[spec.name] = oracle
    return specs, oracles


def _approx_eq(a, b):
    if isinstance(a, float) or isinstance(b, float):
        if a in (float("inf"), float("-inf")) or b in (float("inf"),
                                                       float("-inf")):
            return a == b
        return a == pytest.approx(b, rel=1e-5, abs=1e-6)
    return a == b


@pytest.mark.parametrize("case", range(N_CASES))
def test_fuzz_groupby_vs_oracle(case, segments, frames):
    rng = np.random.default_rng(1000 + case)
    flt, mask_fn = _rand_filter(rng, frames)
    specs, oracles = _rand_aggs(rng)
    n_dims = int(rng.integers(0, 3))
    dims = [["dimA", "dimB"][i] for i in range(n_dims)]

    if dims:
        q = GroupByQuery.of("test", [WEEK],
                            [DefaultDimensionSpec(d) for d in dims],
                            specs, granularity="all", filter=flt)
        rows = QueryExecutor(segments).run(q)
        got = {tuple(r["event"][d] for d in dims):
               {s.name: r["event"][s.name] for s in specs} for r in rows}
        # oracle
        want = {}
        for f in frames:
            m = mask_fn(f)
            keys = list(zip(*(f[d] for d in dims)))
            for key in set(k for k, ok in zip(keys, m) if ok):
                sel = m & np.asarray([k == key for k in keys])
                acc = want.setdefault(key, {})
                for s in specs:
                    v = oracles[s.name](f, sel)
                    if s.name in acc:
                        v0 = acc[s.name]
                        if isinstance(s, (A.CountAggregator,
                                          A.LongSumAggregator,
                                          A.DoubleSumAggregator)):
                            v = v0 + v
                        elif isinstance(s, A.FloatMaxAggregator):
                            v = max(v0, v)
                        else:
                            v = min(v0, v)
                    acc[s.name] = v
        assert set(got) == set(want), f"group keys diverge (case {case})"
        for key in want:
            for s in specs:
                assert _approx_eq(got[key][s.name], want[key][s.name]), \
                    (case, key, s.name, got[key][s.name], want[key][s.name])
    else:
        q = TimeseriesQuery.of("test", [WEEK], specs, granularity="all",
                               filter=flt)
        rows = QueryExecutor(segments).run(q)
        got = rows[0]["result"] if rows else {}
        total_mask = [mask_fn(f) for f in frames]
        for s in specs:
            parts = [oracles[s.name](f, m)
                     for f, m in zip(frames, total_mask)]
            if isinstance(s, (A.CountAggregator, A.LongSumAggregator,
                              A.DoubleSumAggregator)):
                want_v = sum(parts)
            elif isinstance(s, A.FloatMaxAggregator):
                want_v = max(parts)
            else:
                want_v = min(parts)
            assert _approx_eq(got.get(s.name), want_v), \
                (case, s.name, got.get(s.name), want_v)


# ---------------------------------------------------------------------------
# TopN + granularity fuzz
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("case", range(12))
def test_fuzz_topn_vs_oracle(case, segments, frames):
    from druid_tpu.query.model import TopNQuery
    rng = np.random.default_rng(5000 + case)
    flt, mask_fn = _rand_filter(rng, frames)
    dim = ["dimA", "dimB"][int(rng.integers(0, 2))]
    threshold = int(rng.integers(1, 12))
    q = TopNQuery.of(
        "test", [WEEK], dim, "metric", threshold,
        [A.LongSumAggregator("metric", "metLong"),
         A.CountAggregator("n")],
        granularity="all", filter=flt)
    rows = QueryExecutor(segments).run(q)
    entries = rows[0]["result"] if rows else []
    # oracle: per-value sums over all segments
    sums, counts = {}, {}
    for f in frames:
        m = mask_fn(f)
        for v, x in zip(np.asarray(f[dim])[m], f["metLong"][m]):
            sums[v] = sums.get(v, 0) + int(x)
            counts[v] = counts.get(v, 0) + 1
    want = sorted(sums.items(), key=lambda kv: (-kv[1], kv[0]))[:threshold]
    got = [(e[dim], e["metric"]) for e in entries]
    # ties may order differently; compare value multisets per metric rank
    assert [v for _, v in got] == [v for _, v in want], (case, got, want)
    assert {g[0] for g in got if g[1] != 0} <= set(sums), case
    for name, metric in got:
        if name in sums:
            assert metric == sums[name], (case, name)


@pytest.mark.parametrize("case", range(8))
def test_fuzz_day_granularity_vs_oracle(case, segments, frames):
    rng = np.random.default_rng(9000 + case)
    flt, mask_fn = _rand_filter(rng, frames)
    q = TimeseriesQuery.of(
        "test", [WEEK],
        [A.CountAggregator("n"), A.LongSumAggregator("s", "metLong")],
        granularity="day", filter=flt)
    rows = QueryExecutor(segments).run(q)
    got = {r["timestamp"]: (r["result"]["n"], r["result"]["s"])
           for r in rows}
    DAY_MS = 86_400_000
    want = {}
    for f in frames:
        m = mask_fn(f)
        buckets = (f["__time"] // DAY_MS) * DAY_MS
        for b in np.unique(buckets[m]):
            sel = m & (buckets == b)
            n0, s0 = want.get(int(b), (0, 0))
            want[int(b)] = (n0 + int(sel.sum()),
                            s0 + int(f["metLong"][sel].sum()))
    # engine emits empty covered buckets too; compare the non-empty ones
    non_empty = {t: v for t, v in got.items() if v[0] != 0}
    assert non_empty == want, (case, non_empty, want)


# ---------------------------------------------------------------------------
# Extraction dims + HAVING + limitSpec fuzz
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("case", range(10))
def test_fuzz_extraction_groupby_vs_oracle(case, segments, frames):
    from druid_tpu.query.model import (DefaultLimitSpec,
                                       ExtractionDimensionSpec,
                                       GreaterThanHaving, OrderByColumnSpec,
                                       SubstringExtractionFn,
                                       UpperExtractionFn)
    rng = np.random.default_rng(7000 + case)
    flt, mask_fn = _rand_filter(rng, frames)
    use_upper = bool(rng.integers(0, 2))
    # generated values are zero-padded ("v00000012"): a substring at the
    # units digit PARTIALLY collapses keys (100 values → 10 groups) — the
    # interesting extraction+having+limit merge; a prefix substring would
    # collapse everything to one vacuous group, and start=7 would be a
    # bijective rename (no merge at all)
    start = 8
    if use_upper:
        dimspec = ExtractionDimensionSpec("dimB", "d", UpperExtractionFn())
        ex_fn = lambda v: v.upper()
    else:
        dimspec = ExtractionDimensionSpec(
            "dimB", "d", SubstringExtractionFn(start, 2))
        ex_fn = lambda v: v[start:start + 2]
    threshold = int(rng.integers(0, 30))
    limit = int(rng.integers(1, 8)) if rng.integers(0, 2) else None

    q = GroupByQuery.of(
        "test", [WEEK], [dimspec],
        [A.CountAggregator("n"), A.LongSumAggregator("s", "metLong")],
        granularity="all", filter=flt,
        having=GreaterThanHaving("n", threshold),
        limit_spec=DefaultLimitSpec(
            [OrderByColumnSpec("s", "descending", "numeric")], limit)
        if limit else None)
    rows = QueryExecutor(segments).run(q)
    got = [(r["event"]["d"], r["event"]["n"], r["event"]["s"])
           for r in rows]

    want = {}
    for f in frames:
        m = mask_fn(f)
        for v, x in zip(np.asarray(f["dimB"])[m], f["metLong"][m]):
            k = ex_fn(v)
            n0, s0 = want.get(k, (0, 0))
            want[k] = (n0 + 1, s0 + int(x))
    want = {k: v for k, v in want.items() if v[0] > threshold}
    if limit:
        top = sorted(want.items(), key=lambda kv: -kv[1][1])[:limit]
        assert len(got) == min(limit, len(want)), (case, got)
        # compare sums at each rank (key ties may reorder)
        assert [g[2] for g in got] == [v[1] for _, v in top], (case,)
        for k, n, s in got:
            assert want.get(k) == (n, s), (case, k)
    else:
        assert {g[0]: (g[1], g[2]) for g in got} == want, (case,)


# ---------------------------------------------------------------------------
# Distribution fuzz: the SAME random queries through the broker scatter and
# the 8-device sharded mesh must equal the local engine exactly
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def fuzz_cluster(segments):
    from druid_tpu.cluster import (Broker, DataNode, InventoryView,
                                   descriptor_for)
    view = InventoryView()
    nodes = [DataNode(f"fz{i}") for i in range(3)]
    for n in nodes:
        view.register(n)
    for i, s in enumerate(segments):
        for j in (i % 3, (i + 1) % 3):
            nodes[j].load_segment(s)
            view.announce(nodes[j].name, descriptor_for(s))
    return Broker(view)


def _rand_query(case, frames):
    rng = np.random.default_rng(20_000 + case)
    flt, _ = _rand_filter(rng, frames)
    specs, _ = _rand_aggs(rng)
    n_dims = int(rng.integers(0, 3))
    dims = [["dimA", "dimB"][i] for i in range(n_dims)]
    gran = ["all", "day"][int(rng.integers(0, 2))]
    if dims:
        return GroupByQuery.of(
            "test", [WEEK], [DefaultDimensionSpec(d) for d in dims],
            specs, granularity=gran, filter=flt)
    return TimeseriesQuery.of("test", [WEEK], specs, granularity=gran,
                              filter=flt)


def _norm(rows):
    """Order-insensitive comparison form (groupBy row order may differ
    between merge paths for equal keys)."""
    import json

    def default(x):
        return repr(x)

    return sorted(json.dumps(r, sort_keys=True, default=default)
                  for r in rows)


@pytest.mark.parametrize("case", range(12))
def test_fuzz_broker_matches_local(case, segments, frames, fuzz_cluster):
    q = _rand_query(case, frames)
    local = QueryExecutor(segments).run(q)
    assert _norm(fuzz_cluster.run(q)) == _norm(local), (case, q.query_type)


@pytest.mark.parametrize("case", range(12))
def test_fuzz_sharded_mesh_matches_local(case, segments, frames):
    from druid_tpu.parallel import make_mesh
    q = _rand_query(case, frames)
    local = QueryExecutor(segments).run(q)
    mesh = make_mesh()
    sharded = QueryExecutor(segments, mesh=mesh).run(q)
    assert _norm(sharded) == _norm(local), (case, q.query_type)


@pytest.mark.parametrize("case", range(8))
def test_fuzz_disjoint_intervals_broker_and_mesh(case, segments, frames,
                                                 fuzz_cluster):
    """Random DISJOINT sub-intervals: interval clamping and bucket index
    spaces must agree across local, broker-merged, and sharded-mesh
    execution."""
    from druid_tpu.parallel import make_mesh
    rng = np.random.default_rng(40_000 + case)
    flt, _ = _rand_filter(rng, frames)
    specs, _ = _rand_aggs(rng)
    DAY_MS = 86_400_000
    # two disjoint day-aligned windows inside the week
    a = int(rng.integers(0, 3))
    b = int(rng.integers(a + 2, 7))
    ivs = [Interval(WEEK.start + a * DAY_MS, WEEK.start + (a + 1) * DAY_MS),
           Interval(WEEK.start + b * DAY_MS,
                    WEEK.start + min(b + 2, 7) * DAY_MS)]
    gran = ["all", "day"][int(rng.integers(0, 2))]
    q = TimeseriesQuery.of("test", ivs, specs, granularity=gran, filter=flt)
    local = QueryExecutor(segments).run(q)
    assert _norm(fuzz_cluster.run(q)) == _norm(local), ("broker", case)
    sharded = QueryExecutor(segments, mesh=make_mesh()).run(q)
    assert _norm(sharded) == _norm(local), ("mesh", case)
