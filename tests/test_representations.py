"""Segment-representation equivalence: the same queries must return the
same results no matter which physical form the rows live in (reference:
QueryRunnerTestHelper.makeQueryRunners — every query test runs over
incremental / mmapped / merged forms; dictionary-remap and lazy-bitmap bugs
only surface in reloaded/merged segments)."""
import numpy as np
import pytest

from druid_tpu.data.segment import Segment, SegmentId
from druid_tpu.engine import QueryExecutor
from druid_tpu.ingest.incremental import IncrementalIndex
from druid_tpu.ingest.input import RowBatch
from druid_tpu.ingest.merger import merge_segments
from druid_tpu.query.aggregators import (CountAggregator,
                                         DoubleSumAggregator,
                                         FloatMaxAggregator,
                                         LongMaxAggregator,
                                         LongSumAggregator)
from druid_tpu.query.filters import (BoundFilter, InFilter, OrFilter,
                                     SelectorFilter)
from druid_tpu.query.model import (DefaultDimensionSpec, GroupByQuery,
                                   ScanQuery, SearchQuery, TimeseriesQuery,
                                   TopNQuery)
from tests.conftest import DAY, persist_roundtrip, rows_as_frame

AGGS = [CountAggregator("rows"), LongSumAggregator("ls", "metLong"),
        FloatMaxAggregator("fm", "metFloat"),
        DoubleSumAggregator("ds", "metDouble")]
INGEST_SPECS = [LongSumAggregator("metLong", "metLong"),
                FloatMaxAggregator("metFloat", "metFloat"),
                DoubleSumAggregator("metDouble", "metDouble")]


def _to_incremental(seg: Segment) -> Segment:
    """Rebuild through the IncrementalIndex write path (rollup off keeps
    the row multiset)."""
    frame = rows_as_frame(seg)
    n = len(frame["__time"])
    idx = IncrementalIndex(seg.id.datasource, seg.interval, INGEST_SPECS,
                           dimensions=list(seg.dims),
                           query_granularity="none", rollup=False,
                           max_rows_in_memory=10 ** 12)
    idx.add_batch(RowBatch(
        frame["__time"].tolist(),
        {c: list(frame[c]) for c in frame if c != "__time"}))
    return idx.to_segment(seg.id.version, seg.id.partition)


def _to_merged(seg: Segment, tmp_path) -> Segment:
    """Split into 3 persisted spills, reload each, n-way merge (exercises
    dictionary reconciliation across spills)."""
    frame = rows_as_frame(seg)
    n = len(frame["__time"])
    cuts = [0, n // 3, 2 * n // 3, n]
    spills = []
    for i in range(3):
        lo, hi = cuts[i], cuts[i + 1]
        idx = IncrementalIndex(seg.id.datasource, seg.interval, INGEST_SPECS,
                               dimensions=list(seg.dims),
                               query_granularity="none", rollup=False,
                               max_rows_in_memory=10 ** 12)
        idx.add_batch(RowBatch(
            frame["__time"][lo:hi].tolist(),
            {c: list(frame[c][lo:hi]) for c in frame if c != "__time"}))
        spill = idx.to_segment("spill", i)
        spills.append(persist_roundtrip(
            spill, str(tmp_path / f"spill{i}")))
    return merge_segments(spills, INGEST_SPECS,
                          datasource=seg.id.datasource, interval=seg.interval,
                          version=seg.id.version, partition=seg.id.partition,
                          rollup=False, query_granularity="none")


@pytest.fixture(scope="module")
def forms(generator, tmp_path_factory):
    base = generator.segment(12_000, DAY, datasource="test")
    tmp = tmp_path_factory.mktemp("reprs")
    return {
        "generated": base,
        "persisted": persist_roundtrip(base, str(tmp / "persisted")),
        "incremental": _to_incremental(base),
        "merged": _to_merged(base, tmp),
    }


def _sorted_rows(rows, keys):
    out = []
    for r in rows:
        e = r.get("event", r.get("result", r))
        out.append(tuple((k, e.get(k)) for k in keys))
    return sorted(out)


QUERIES = [
    ("timeseries", lambda: TimeseriesQuery.of(
        "test", [DAY], AGGS, granularity="hour"),
     lambda rows: rows),
    ("topn", lambda: TopNQuery.of(
        "test", [DAY], "dimB", "ls", 10, AGGS, granularity="all",
        filter=BoundFilter("metLong", lower=10, upper=90,
                           ordering="numeric")),
     lambda rows: rows),
    ("groupby_filtered", lambda: GroupByQuery.of(
        "test", [DAY],
        [DefaultDimensionSpec("dimA"), DefaultDimensionSpec("dimB")], AGGS,
        granularity="all",
        filter=OrFilter([SelectorFilter("dimA", "v00000003"),
                         InFilter("dimA", ["v00000001", "v00000005"])])),
     lambda rows: _sorted_rows(rows, ("dimA", "dimB", "rows", "ls"))),
    ("groupby_hicard", lambda: GroupByQuery.of(
        "test", [DAY], [DefaultDimensionSpec("dimHi")],
        [CountAggregator("rows"), LongMaxAggregator("lm", "metLong")],
        granularity="all"),
     lambda rows: _sorted_rows(rows, ("dimHi", "rows", "lm"))),
    ("search", lambda: SearchQuery.of(
        "test", [DAY], "v0000000", search_dimensions=["dimA"], limit=20),
     lambda rows: rows),
    ("scan_multiset", lambda: ScanQuery.of(
        "test", [DAY], columns=["dimA", "metLong"]),
     lambda rows: sorted(
         (e["dimA"], e["metLong"]) for b in rows for e in b["events"])),
]


@pytest.mark.parametrize("name,make_q,norm", QUERIES,
                         ids=[q[0] for q in QUERIES])
def test_query_equivalence_across_representations(forms, name, make_q, norm):
    q = make_q()
    want = None
    for form, seg in forms.items():
        got = norm(QueryExecutor([seg]).run(q))
        if want is None:
            want = got
            continue
        assert got == want, f"{name}: {form} diverges from generated"


def test_representation_row_counts(forms):
    n = forms["generated"].n_rows
    for form, seg in forms.items():
        assert seg.n_rows == n, form
