"""Byte-budgeted device segment pool (data/devicepool.py): budget
enforcement, LRU eviction by actual bytes, re-staging after eviction,
owner purge on segment GC, and the DevicePoolMonitor metrics."""
import gc

import numpy as np
import pytest

from druid_tpu.data import devicepool
from druid_tpu.data.devicepool import DeviceSegmentPool, entry_bytes
from druid_tpu.data.generator import ColumnSpec, DataGenerator
from druid_tpu.engine.executor import QueryExecutor
from druid_tpu.utils.emitter import InMemoryEmitter, ServiceEmitter
from druid_tpu.utils.intervals import Interval

IV = Interval.of("2026-04-01", "2026-04-02")
SCHEMA = (ColumnSpec("dimA", "string", cardinality=5),
          ColumnSpec("metLong", "long", low=0, high=50))


@pytest.fixture
def fresh_pool(monkeypatch):
    """Isolated pool; segments built after this bind to it."""
    pool = DeviceSegmentPool(budget_bytes=1 << 40)
    monkeypatch.setattr(devicepool, "_POOL", pool)
    return pool


def _segments(n, rows=2000, seed=5):
    return DataGenerator(SCHEMA, seed=seed).segments(
        n, rows, IV, datasource="pool")


COUNT_Q = {"queryType": "timeseries", "dataSource": "pool",
           "intervals": [str(IV)], "granularity": "all",
           "aggregations": [{"type": "count", "name": "n"},
                            {"type": "longSum", "name": "s",
                             "fieldName": "metLong"}]}


def test_entry_bytes_accounts_arrays():
    a = np.zeros(100, dtype=np.int32)
    assert entry_bytes(a) == 400
    assert entry_bytes({"x": a, "y": a}) == 800
    assert entry_bytes((a, [a, a])) == 1200
    assert entry_bytes(None) == 0

    class FakeBlock:
        arrays = {"c": np.zeros(10, np.int64)}
    assert entry_bytes(FakeBlock()) == 80


def test_staging_is_pooled_and_counted(fresh_pool):
    segs = _segments(2)
    ex = QueryExecutor(segs)
    r1 = ex.run_json(COUNT_Q)
    s1 = fresh_pool.snapshot()
    assert s1.misses > 0 and s1.resident_bytes > 0
    r2 = ex.run_json(COUNT_Q)
    s2 = fresh_pool.snapshot()
    assert r1 == r2
    assert s2.hits > s1.hits, "repeat query must hit the pooled blocks"
    assert s2.misses == s1.misses, "repeat query must not re-stage"


def test_byte_budget_evicts_lru_and_restages(fresh_pool):
    segs = _segments(6, rows=4000)
    ex = QueryExecutor(segs)
    ex.run_json(COUNT_Q)
    baseline = fresh_pool.snapshot()
    per_entry = baseline.resident_bytes // max(baseline.entries, 1)
    # room for ~2 entries: the other stagings must evict, budget respected
    budget = int(per_entry * 2.5)
    fresh_pool.configure(budget)
    s = fresh_pool.snapshot()
    assert s.resident_bytes <= budget
    assert s.evicted_bytes > 0 and s.evictions > 0
    # evicted blocks re-stage transparently and results stay correct
    r = ex.run_json(COUNT_Q)
    assert r[0]["result"]["n"] == sum(seg.n_rows for seg in segs)
    s2 = fresh_pool.snapshot()
    assert s2.misses > s.misses, "evicted entries must re-stage as misses"
    assert s2.resident_bytes <= budget


def test_single_oversized_entry_survives(fresh_pool):
    """The entry just staged for the running query is never evicted from
    under it, even when it alone exceeds the budget."""
    fresh_pool.configure(1)            # absurd: nothing fits
    segs = _segments(2)
    r = QueryExecutor(segs).run_json(COUNT_Q)
    assert r[0]["result"]["n"] == sum(s.n_rows for s in segs)
    s = fresh_pool.snapshot()
    assert s.entries >= 1              # the last-used entry survives


def test_zero_budget_means_unbounded(fresh_pool):
    fresh_pool.configure(0)
    segs = _segments(4)
    QueryExecutor(segs).run_json(COUNT_Q)
    s = fresh_pool.snapshot()
    assert s.evictions == 0 and s.entries > 0


def test_segment_gc_purges_entries(fresh_pool):
    segs = _segments(2)
    QueryExecutor(segs).run_json(COUNT_Q)
    assert fresh_pool.snapshot().resident_bytes > 0
    del segs
    gc.collect()
    s = fresh_pool.snapshot()
    assert s.resident_bytes == 0, "collected segments must release HBM"
    assert s.entries == 0


def test_pool_monitor_emits_metrics(fresh_pool):
    segs = _segments(2)
    ex = QueryExecutor(segs)
    sink = InMemoryEmitter()
    emitter = ServiceEmitter("historical", "host1", sink)
    mon = devicepool.DevicePoolMonitor(fresh_pool)
    ex.run_json(COUNT_Q)               # misses (cold)
    ex.run_json(COUNT_Q)               # hits (warm)
    mon.do_monitor(emitter)
    names = {e.metric for e in sink.metrics()}
    assert {"segment/devicePool/hitRate", "segment/devicePool/hits",
            "segment/devicePool/misses", "segment/devicePool/evictedBytes",
            "segment/devicePool/residentBytes",
            "segment/devicePool/entries"} <= names
    rate = sink.metrics("segment/devicePool/hitRate")[-1].value
    assert 0.0 < rate <= 1.0
    # second tick with no traffic: deltas go quiet, no rate emitted
    sink.events.clear()
    mon.do_monitor(emitter)
    assert not sink.metrics("segment/devicePool/hitRate")
    assert sink.metrics("segment/devicePool/hits")[-1].value == 0


def test_finalizer_never_takes_the_pool_lock(fresh_pool):
    """REGRESSION (raceguard witness finding): the owner finalizer runs at
    arbitrary allocation points — including while the CURRENT thread holds
    the pool lock. A finalizer that acquired the lock would self-deadlock;
    it must only enqueue the dead token, leaving the purge to the next
    locked pool operation."""
    class Owner:
        pass

    owner_obj = Owner()
    token = fresh_pool.register_owner(owner_obj)
    fresh_pool.get_or_build(token, ("k",),
                            lambda: np.zeros(64, dtype=np.int64))
    assert fresh_pool.snapshot().resident_bytes == 64 * 8

    acquired = fresh_pool._lock.acquire(timeout=5)
    assert acquired
    try:
        del owner_obj
        gc.collect()       # finalizer fires HERE, with the lock held by us
        assert list(fresh_pool._dead_owners) == [token]
    finally:
        fresh_pool._lock.release()
    # the next locked operation drains the dead owner
    s = fresh_pool.snapshot()
    assert s.resident_bytes == 0 and s.entries == 0
    assert not fresh_pool._dead_owners


def test_purge_during_build_does_not_resurrect(fresh_pool):
    """REGRESSION: get_or_build runs build() OUTSIDE the lock. If the owner
    dies during the build, the insert must NOT cache the value — the
    finalizer already ran, so a cached entry would pin device memory until
    process exit."""
    class Owner:
        pass

    owner_obj = Owner()
    token = fresh_pool.register_owner(owner_obj)
    holder = {"obj": owner_obj}
    del owner_obj

    def build():
        # the segment is dropped (and collected) mid-build
        del holder["obj"]
        gc.collect()
        return np.zeros(32, dtype=np.int64)

    value = fresh_pool.get_or_build(token, ("k",), build)
    assert value.nbytes == 32 * 8         # caller still gets its value
    s = fresh_pool.snapshot()
    assert s.entries == 0 and s.resident_bytes == 0, (
        "a dead owner's entry must not be cached")


def test_clear_keeps_live_owners_cacheable(fresh_pool):
    """clear() drops entries but must keep live owners registered — a
    cleared pool that refused live segments' inserts would never cache
    again."""
    class Owner:
        pass

    owner_obj = Owner()
    token = fresh_pool.register_owner(owner_obj)
    fresh_pool.get_or_build(token, ("k",),
                            lambda: np.zeros(8, dtype=np.int64))
    fresh_pool.clear()
    assert fresh_pool.snapshot().entries == 0
    fresh_pool.get_or_build(token, ("k",),
                            lambda: np.zeros(8, dtype=np.int64))
    assert fresh_pool.snapshot().entries == 1


def test_entry_bytes_counts_packed_entries_compressed():
    """Satellite contract: pool accounting must not undercount (or
    double-count) the packed-staging entry shapes — PackedColumns alone,
    inside DeviceBlock-style dicts, and in tuples/pytrees mixed with aux
    arrays. entry_bytes counts the COMPRESSED words; entry_logical_bytes
    the decoded equivalent."""
    from druid_tpu.data import packed
    from druid_tpu.data.devicepool import entry_logical_bytes

    rows = 2048
    vals = np.arange(rows, dtype=np.int32) % 200          # width 8, base 0
    pc = packed.PackedColumn(packed.pack_padded(vals, 8, 0), 8, 0, rows)
    assert pc.vpw == 4
    assert entry_bytes(pc) == rows // 4 * 4               # words bytes
    assert entry_logical_bytes(pc) == rows * 4            # decoded bytes

    # DeviceBlock-style dict mixing packed and dense columns
    dense = np.zeros(rows, dtype=np.int32)
    class FakeBlock:
        arrays = {"packed": pc, "dense": dense}
    assert entry_bytes(FakeBlock()) == pc.nbytes + dense.nbytes
    assert entry_logical_bytes(FakeBlock()) == rows * 4 + dense.nbytes

    # tuples/pytrees of packed words + aux (derived-entry shapes)
    aux = np.zeros(16, dtype=np.int64)
    assert entry_bytes((pc, aux)) == pc.nbytes + aux.nbytes
    assert entry_bytes([pc, {"a": aux}, (pc,)]) \
        == 2 * pc.nbytes + aux.nbytes
    assert entry_logical_bytes((pc, aux)) == rows * 4 + aux.nbytes
    assert entry_logical_bytes(None) == 0


def test_pool_accounts_packed_entries_and_ratio(fresh_pool):
    """Inserting packed pytree entries: resident tracks compressed bytes,
    logical tracks decoded bytes, packed_ratio reports the multiplier, and
    eviction/purge keep both in sync."""
    from druid_tpu.data import packed

    class Owner:
        pass

    owner_obj = Owner()
    token = fresh_pool.register_owner(owner_obj)
    rows = 4096
    vals = (np.arange(rows) % 16).astype(np.int32)        # width 4 -> 8x
    pc = packed.PackedColumn(packed.pack_padded(vals, 4, 0), 4, 0, rows)
    aux = np.zeros(128, dtype=np.int32)
    fresh_pool.get_or_build(token, ("p",), lambda: (pc, aux))
    s = fresh_pool.snapshot()
    assert s.resident_bytes == pc.nbytes + aux.nbytes
    assert s.logical_bytes == rows * 4 + aux.nbytes
    assert s.packed_ratio > 3.0                           # 8x words + aux
    fresh_pool.clear()
    s2 = fresh_pool.snapshot()
    assert s2.resident_bytes == 0 and s2.logical_bytes == 0
    assert s2.packed_ratio == 1.0


def test_pool_monitor_emits_packed_ratio(fresh_pool):
    sink = InMemoryEmitter()
    emitter = ServiceEmitter("historical", "host1", sink)
    mon = devicepool.DevicePoolMonitor(fresh_pool)
    mon.do_monitor(emitter)
    ratios = sink.metrics("segment/devicePool/packedRatio")
    assert ratios and ratios[-1].value == 1.0             # empty pool


# ---------------------------------------------------------------------------
# Stacked sharded blocks: budget-governed pool state, not a private cache
# ---------------------------------------------------------------------------

def test_stacked_kind_accounting_unit(fresh_pool):
    """Entries keyed under STACKED_KIND flow into the stacked_* counters on
    insert / take / eviction, and a LogicalBytes leaf inflates the logical
    side only (actual bytes stay honest)."""
    class Anchor:
        pass

    anchor = Anchor()
    token = fresh_pool.register_owner(anchor)
    arr = np.zeros(256, dtype=np.int64)                   # 2048 actual
    val = (arr, devicepool.LogicalBytes(4096))
    fresh_pool.get_or_build(
        token, (devicepool.STACKED_KIND, "k1"), lambda: val)
    s = fresh_pool.snapshot()
    assert s.stacked_entries == 1
    assert s.stacked_bytes == 2048
    assert s.stacked_logical_bytes == 2048 + 4096
    assert s.stacked_ratio == pytest.approx(3.0)
    # non-stacked entries do not touch the stacked counters
    fresh_pool.get_or_build(token, ("plain", "k2"),
                            lambda: np.zeros(16, np.int8))
    assert fresh_pool.snapshot().stacked_bytes == 2048
    fresh_pool.take(token, (devicepool.STACKED_KIND, "k1"))
    s2 = fresh_pool.snapshot()
    assert s2.stacked_entries == 0 and s2.stacked_bytes == 0
    assert s2.stacked_logical_bytes == 0
    assert s2.stacked_ratio == 1.0


def test_stacked_blocks_evict_under_byte_pressure(fresh_pool):
    """The sharded stack cache is device-pool state: stacked bytes count
    against DEVICE_POOL_BUDGET_BYTES, evict LRU under pressure, and
    restage transparently — the ISSUE's `_STACK_CACHE` replacement."""
    from druid_tpu.parallel import distributed, make_mesh, use_mesh
    distributed.clear_stack_cache()   # re-home the owner token on this pool
    try:
        segs_a = _segments(8, rows=3000, seed=11)
        segs_b = DataGenerator(SCHEMA, seed=12).segments(
            8, 3000, IV, datasource="pool")
        mesh = make_mesh()
        with use_mesh(mesh):
            r1 = QueryExecutor(segs_a).run_json(COUNT_Q)
            s1 = fresh_pool.snapshot()
            assert s1.stacked_entries == 1
            assert 0 < s1.stacked_bytes <= s1.resident_bytes
            # squeeze: room for ~one stack, so stacking segs_b must evict
            # the segs_a stack instead of growing without bound
            budget = s1.resident_bytes + s1.stacked_bytes // 2
            fresh_pool.configure(budget)
            QueryExecutor(segs_b).run_json(COUNT_Q)
            s2 = fresh_pool.snapshot()
            assert s2.evictions > s1.evictions
            assert s2.stacked_entries == 1
            assert s2.resident_bytes <= budget
            # the evicted stack restages transparently, results unchanged
            assert QueryExecutor(segs_a).run_json(COUNT_Q) == r1
            assert fresh_pool.snapshot().stacked_entries == 1
    finally:
        distributed.clear_stack_cache()
