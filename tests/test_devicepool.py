"""Byte-budgeted device segment pool (data/devicepool.py): budget
enforcement, LRU eviction by actual bytes, re-staging after eviction,
owner purge on segment GC, and the DevicePoolMonitor metrics."""
import gc

import numpy as np
import pytest

from druid_tpu.data import devicepool
from druid_tpu.data.devicepool import DeviceSegmentPool, entry_bytes
from druid_tpu.data.generator import ColumnSpec, DataGenerator
from druid_tpu.engine.executor import QueryExecutor
from druid_tpu.utils.emitter import InMemoryEmitter, ServiceEmitter
from druid_tpu.utils.intervals import Interval

IV = Interval.of("2026-04-01", "2026-04-02")
SCHEMA = (ColumnSpec("dimA", "string", cardinality=5),
          ColumnSpec("metLong", "long", low=0, high=50))


@pytest.fixture
def fresh_pool(monkeypatch):
    """Isolated pool; segments built after this bind to it."""
    pool = DeviceSegmentPool(budget_bytes=1 << 40)
    monkeypatch.setattr(devicepool, "_POOL", pool)
    return pool


def _segments(n, rows=2000, seed=5):
    return DataGenerator(SCHEMA, seed=seed).segments(
        n, rows, IV, datasource="pool")


COUNT_Q = {"queryType": "timeseries", "dataSource": "pool",
           "intervals": [str(IV)], "granularity": "all",
           "aggregations": [{"type": "count", "name": "n"},
                            {"type": "longSum", "name": "s",
                             "fieldName": "metLong"}]}


def test_entry_bytes_accounts_arrays():
    a = np.zeros(100, dtype=np.int32)
    assert entry_bytes(a) == 400
    assert entry_bytes({"x": a, "y": a}) == 800
    assert entry_bytes((a, [a, a])) == 1200
    assert entry_bytes(None) == 0

    class FakeBlock:
        arrays = {"c": np.zeros(10, np.int64)}
    assert entry_bytes(FakeBlock()) == 80


def test_staging_is_pooled_and_counted(fresh_pool):
    segs = _segments(2)
    ex = QueryExecutor(segs)
    r1 = ex.run_json(COUNT_Q)
    s1 = fresh_pool.snapshot()
    assert s1.misses > 0 and s1.resident_bytes > 0
    r2 = ex.run_json(COUNT_Q)
    s2 = fresh_pool.snapshot()
    assert r1 == r2
    assert s2.hits > s1.hits, "repeat query must hit the pooled blocks"
    assert s2.misses == s1.misses, "repeat query must not re-stage"


def test_byte_budget_evicts_lru_and_restages(fresh_pool):
    segs = _segments(6, rows=4000)
    ex = QueryExecutor(segs)
    ex.run_json(COUNT_Q)
    baseline = fresh_pool.snapshot()
    per_entry = baseline.resident_bytes // max(baseline.entries, 1)
    # room for ~2 entries: the other stagings must evict, budget respected
    budget = int(per_entry * 2.5)
    fresh_pool.configure(budget)
    s = fresh_pool.snapshot()
    assert s.resident_bytes <= budget
    assert s.evicted_bytes > 0 and s.evictions > 0
    # evicted blocks re-stage transparently and results stay correct
    r = ex.run_json(COUNT_Q)
    assert r[0]["result"]["n"] == sum(seg.n_rows for seg in segs)
    s2 = fresh_pool.snapshot()
    assert s2.misses > s.misses, "evicted entries must re-stage as misses"
    assert s2.resident_bytes <= budget


def test_single_oversized_entry_survives(fresh_pool):
    """The entry just staged for the running query is never evicted from
    under it, even when it alone exceeds the budget."""
    fresh_pool.configure(1)            # absurd: nothing fits
    segs = _segments(2)
    r = QueryExecutor(segs).run_json(COUNT_Q)
    assert r[0]["result"]["n"] == sum(s.n_rows for s in segs)
    s = fresh_pool.snapshot()
    assert s.entries >= 1              # the last-used entry survives


def test_zero_budget_means_unbounded(fresh_pool):
    fresh_pool.configure(0)
    segs = _segments(4)
    QueryExecutor(segs).run_json(COUNT_Q)
    s = fresh_pool.snapshot()
    assert s.evictions == 0 and s.entries > 0


def test_segment_gc_purges_entries(fresh_pool):
    segs = _segments(2)
    QueryExecutor(segs).run_json(COUNT_Q)
    assert fresh_pool.snapshot().resident_bytes > 0
    del segs
    gc.collect()
    s = fresh_pool.snapshot()
    assert s.resident_bytes == 0, "collected segments must release HBM"
    assert s.entries == 0


def test_pool_monitor_emits_metrics(fresh_pool):
    segs = _segments(2)
    ex = QueryExecutor(segs)
    sink = InMemoryEmitter()
    emitter = ServiceEmitter("historical", "host1", sink)
    mon = devicepool.DevicePoolMonitor(fresh_pool)
    ex.run_json(COUNT_Q)               # misses (cold)
    ex.run_json(COUNT_Q)               # hits (warm)
    mon.do_monitor(emitter)
    names = {e.metric for e in sink.metrics()}
    assert {"segment/devicePool/hitRate", "segment/devicePool/hits",
            "segment/devicePool/misses", "segment/devicePool/evictedBytes",
            "segment/devicePool/residentBytes",
            "segment/devicePool/entries"} <= names
    rate = sink.metrics("segment/devicePool/hitRate")[-1].value
    assert 0.0 < rate <= 1.0
    # second tick with no traffic: deltas go quiet, no rate emitted
    sink.events.clear()
    mon.do_monitor(emitter)
    assert not sink.metrics("segment/devicePool/hitRate")
    assert sink.metrics("segment/devicePool/hits")[-1].value == 0
