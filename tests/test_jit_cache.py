"""Compile-counter tests: repeated queries must NOT rebuild (and hence
retrace/recompile) the jitted device programs — the hazard druidlint's
jit-in-hot-path rule guards statically, asserted dynamically here.

The counter wraps the builder functions (_build_device_fn /
_build_sharded_fn): a second identical query must be served entirely from
_JIT_CACHE / _FN_CACHE."""
import collections

import pytest

from druid_tpu.engine import grouping
from druid_tpu.engine.executor import QueryExecutor
from druid_tpu.parallel import distributed, make_mesh, use_mesh
from druid_tpu.query import CountAggregator, LongSumAggregator
from druid_tpu.query.model import (DefaultDimensionSpec, GroupByQuery,
                                   TimeseriesQuery)
from druid_tpu.utils.granularity import Granularity
from druid_tpu.utils.intervals import Interval

from conftest import DAY

AGGS = [CountAggregator("rows"), LongSumAggregator("sumLong", "metLong")]


class BuildCounter:
    def __init__(self, fn):
        self.fn = fn
        self.count = 0

    def __call__(self, *args, **kwargs):
        self.count += 1
        return self.fn(*args, **kwargs)


@pytest.fixture
def device_counter(monkeypatch):
    """Fresh per-segment jit cache + counted builder."""
    monkeypatch.setattr(grouping, "_JIT_CACHE", collections.OrderedDict())
    counter = BuildCounter(grouping._build_device_fn)
    monkeypatch.setattr(grouping, "_build_device_fn", counter)
    return counter


@pytest.fixture
def sharded_counter(monkeypatch):
    """Fresh sharded fn cache + counted builder."""
    monkeypatch.setattr(distributed, "_FN_CACHE", collections.OrderedDict())
    counter = BuildCounter(distributed._build_sharded_fn)
    monkeypatch.setattr(distributed, "_build_sharded_fn", counter)
    return counter


def test_repeated_timeseries_compiles_once(segment, device_counter):
    qe = QueryExecutor([segment])
    q = TimeseriesQuery(datasource="test", intervals=[DAY],
                        granularity=Granularity.HOUR, aggregations=AGGS)
    first = qe.run(q)
    assert device_counter.count == 1, "first query must build the program"
    for _ in range(3):
        assert qe.run(q) == first
    assert device_counter.count == 1, (
        f"repeated identical queries rebuilt the jitted program "
        f"{device_counter.count - 1} extra time(s) — _JIT_CACHE regressed")


def test_repeated_groupby_compiles_once(segment, device_counter):
    qe = QueryExecutor([segment])
    q = GroupByQuery(datasource="test", intervals=[DAY],
                     granularity=Granularity.ALL,
                     dimensions=[DefaultDimensionSpec("dimA", "dimA")],
                     aggregations=AGGS)
    first = qe.run(q)
    built = device_counter.count
    assert built >= 1
    for _ in range(3):
        assert qe.run(q) == first
    assert device_counter.count == built, (
        "repeated identical groupBys rebuilt the jitted program")


def test_different_structure_builds_again_same_structure_reuses(
        segment, device_counter):
    """The cache key is the plan STRUCTURE: a different shape builds a new
    program; re-running either shape reuses its entry."""
    qe = QueryExecutor([segment])
    q_hour = TimeseriesQuery(datasource="test", intervals=[DAY],
                             granularity=Granularity.HOUR, aggregations=AGGS)
    q_all = TimeseriesQuery(datasource="test", intervals=[DAY],
                            granularity=Granularity.ALL, aggregations=AGGS)
    qe.run(q_hour)
    assert device_counter.count == 1
    qe.run(q_all)
    assert device_counter.count == 2
    qe.run(q_hour)
    qe.run(q_all)
    assert device_counter.count == 2


def test_repeated_sharded_query_compiles_once(segments, sharded_counter):
    """The shard_map program (distributed.py) is likewise built exactly
    once for repeated identical queries over the mesh."""
    mesh = make_mesh()
    q = TimeseriesQuery(datasource="test",
                        intervals=[Interval.of("2026-01-01", "2026-01-05")],
                        granularity=Granularity.DAY, aggregations=AGGS)
    with use_mesh(mesh):
        qe = QueryExecutor(segments)
        first = qe.run(q)
        assert sharded_counter.count == 1, (
            "sharded path did not run (or built more than once)")
        for _ in range(3):
            assert qe.run(q) == first
        assert sharded_counter.count == 1, (
            "repeated identical sharded queries rebuilt the shard_map "
            "program — _FN_CACHE regressed")


def test_jit_cache_is_bounded(segment, device_counter, monkeypatch):
    """The LRU cap evicts oldest structures instead of growing without
    bound (compiled executables pin memory)."""
    monkeypatch.setattr(grouping, "_JIT_CACHE_CAP", 1)
    qe = QueryExecutor([segment])
    q_hour = TimeseriesQuery(datasource="test", intervals=[DAY],
                             granularity=Granularity.HOUR, aggregations=AGGS)
    q_all = TimeseriesQuery(datasource="test", intervals=[DAY],
                            granularity=Granularity.ALL, aggregations=AGGS)
    qe.run(q_hour)
    qe.run(q_all)
    assert len(grouping._JIT_CACHE) == 1
    qe.run(q_hour)   # evicted by q_all: must rebuild
    assert device_counter.count == 3
