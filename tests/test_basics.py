"""Foundations: intervals, granularities, dictionaries, bitmaps, expressions."""
import numpy as np
import pytest

from druid_tpu.data.bitmap import Bitmap, BitmapIndex
from druid_tpu.data.dictionary import Dictionary, merge_dictionaries
from druid_tpu.utils.expression import parse_expression
from druid_tpu.utils.granularity import Granularity
from druid_tpu.utils.intervals import Interval, condense, parse_ts


def test_interval_parse_and_ops():
    iv = Interval.parse("2026-01-01/2026-01-02")
    assert iv.width == 86400000
    assert iv.contains(parse_ts("2026-01-01T12:00:00Z"))
    assert not iv.contains(parse_ts("2026-01-02"))
    other = Interval.of("2026-01-01T18:00:00Z", "2026-01-03")
    assert iv.overlaps(other)
    assert iv.intersect(other).width == 6 * 3600 * 1000


def test_condense():
    a = Interval.of("2026-01-01", "2026-01-03")
    b = Interval.of("2026-01-02", "2026-01-04")
    c = Interval.of("2026-01-05", "2026-01-06")
    out = condense([c, a, b])
    assert out == [Interval.of("2026-01-01", "2026-01-04"), c]


def test_granularity_uniform():
    g = Granularity.of("hour")
    ts = parse_ts("2026-01-01T05:30:12Z")
    assert g.bucket_start(ts) == parse_ts("2026-01-01T05:00:00Z")
    iv = Interval.of("2026-01-01", "2026-01-02")
    assert g.num_buckets(iv) == 24
    ids = g.bucket_ids(np.asarray([ts, parse_ts("2026-01-02T00:00:00Z")]), iv)
    assert list(ids) == [5, -1]


def test_granularity_calendar():
    g = Granularity.of("month")
    ts = parse_ts("2026-03-15T10:00:00Z")
    assert g.bucket_start(ts) == parse_ts("2026-03-01")
    assert g.next_bucket(parse_ts("2026-12-01")) == parse_ts("2027-01-01")
    q = Granularity.of("quarter")
    assert q.bucket_start(ts) == parse_ts("2026-01-01")
    y = Granularity.of("year")
    iv = Interval.of("2025-06-01", "2027-02-01")
    assert list(y.bucket_starts(iv)) == [parse_ts("2025-01-01"),
                                         parse_ts("2026-01-01"),
                                         parse_ts("2027-01-01")]


def test_granularity_week_starts_monday():
    g = Granularity.of("week")
    # 2026-01-01 is a Thursday; its week starts Monday 2025-12-29
    assert g.bucket_start(parse_ts("2026-01-01")) == parse_ts("2025-12-29")


def test_dictionary():
    d = Dictionary.from_values(["b", "a", "c", "a", None])
    assert d.values == ["", "a", "b", "c"]
    assert d.id_of("b") == 2
    assert d.id_of("zzz") == -1
    ids = d.encode(["a", "c", None])
    assert list(ids) == [1, 3, 0]
    lo, hi = d.id_range("a", "b")
    assert (lo, hi) == (1, 3)
    lo, hi = d.id_range("a", "b", lower_strict=True)
    assert (lo, hi) == (2, 3)


def test_merge_dictionaries():
    d1 = Dictionary(["a", "c"])
    d2 = Dictionary(["b", "c"])
    merged, remaps = merge_dictionaries([d1, d2])
    assert merged.values == ["a", "b", "c"]
    assert list(remaps[0]) == [0, 2]
    assert list(remaps[1]) == [1, 2]


def test_bitmap_algebra():
    a = Bitmap.from_indices(np.asarray([0, 5, 9]), 10)
    b = Bitmap.from_indices(np.asarray([5, 6]), 10)
    assert sorted((a & b).to_indices()) == [5]
    assert sorted((a | b).to_indices()) == [0, 5, 6, 9]
    assert sorted((~a).to_indices()) == [1, 2, 3, 4, 6, 7, 8]
    assert a.cardinality() == 3


def test_bitmap_index():
    ids = np.asarray([0, 1, 2, 1, 0, 2, 2], dtype=np.int32)
    idx = BitmapIndex.build(ids, 3)
    assert sorted(idx.bitmap(2).to_indices()) == [2, 5, 6]
    assert idx.union_of(np.asarray([0, 1])).cardinality() == 4


def test_bitmap_density_adaptive_and_budgeted():
    """High-cardinality dims must not materialize card · n/8 bytes: sparse
    values store row-id lists, the LRU budget bounds resident bitmaps, and
    many-value unions never materialize per-value bitmaps at all
    (capability of CONCISE/Roaring, ImmutableConciseSet.java:79)."""
    from druid_tpu.data.bitmap import SparseBitmap
    rng = np.random.default_rng(3)
    n, card = 200_000, 5000
    ids = rng.integers(0, card, n).astype(np.int32)
    idx = BitmapIndex.build(ids, card)
    # ~40 rows per value << n/32: sparse representation
    b = idx.bitmap(7)
    assert isinstance(b, SparseBitmap)
    assert sorted(b.to_indices()) == sorted(np.flatnonzero(ids == 7))
    # sparse algebra densifies transparently
    dense = Bitmap.from_indices(np.flatnonzero(ids < 3), n)
    assert (b & dense).cardinality() == 0
    assert (b | dense).cardinality() == b.cardinality() + dense.cardinality()
    # a full-cardinality union touches every row once, exactly
    u = idx.union_of(np.arange(card))
    assert u.cardinality() == n
    # resident memory stays near the sorted-order cost, not card*n/8 (125MB)
    for v in range(0, card, 7):
        idx.bitmap(v)
    assert idx.size_bytes() < 2 * ids.nbytes
    # a dominant value goes dense
    ids2 = np.zeros(n, dtype=np.int32)
    ids2[::100] = 1
    idx2 = BitmapIndex.build(ids2, 2)
    assert isinstance(idx2.bitmap(0), Bitmap)
    assert isinstance(idx2.bitmap(1), SparseBitmap)
    assert idx2.bitmap(0).cardinality() + idx2.bitmap(1).cardinality() == n


def test_timestamp_extract_matches_datetime():
    """The device-safe integer calendar math must agree with python's
    datetime over a wide range (incl. leap years, century boundaries)."""
    import datetime as dt
    from druid_tpu.utils.expression import parse_expression
    rng = np.random.default_rng(9)
    ts = rng.integers(-5_000_000_000_000, 4_000_000_000_000, 2000)
    b = {"t": ts}
    golden = [dt.datetime.fromtimestamp(int(x) / 1000, dt.timezone.utc)
              for x in ts]
    for unit, fn in [("YEAR", lambda d: d.year), ("MONTH", lambda d: d.month),
                     ("DAY", lambda d: d.day), ("HOUR", lambda d: d.hour),
                     ("MINUTE", lambda d: d.minute),
                     ("SECOND", lambda d: d.second),
                     ("DOW", lambda d: d.isoweekday()),
                     ("DOY", lambda d: d.timetuple().tm_yday),
                     ("QUARTER", lambda d: (d.month + 2) // 3)]:
        got = parse_expression(f"timestamp_extract(t, '{unit}')").evaluate(b)
        want = np.asarray([fn(d) for d in golden])
        assert np.array_equal(np.asarray(got), want), unit


def test_timestamp_floor_shift_and_math_fns():
    from druid_tpu.utils.expression import parse_expression
    day = 86_400_000
    t = np.asarray([3 * day + 5, 3 * day, -day + 1, -1], dtype=np.int64)
    out = parse_expression(f"timestamp_floor(t, {day})").evaluate({"t": t})
    assert list(out) == [3 * day, 3 * day, -day, -day]
    out = parse_expression(
        f"timestamp_shift(t, {day}, 2)").evaluate({"t": t})
    assert list(out) == [x + 2 * day for x in t]
    b = {"x": np.asarray([-2.5, 0.0, 7.0])}
    assert list(parse_expression("sign(x)").evaluate(b)) == [-1, 0, 1]
    assert list(parse_expression("greatest(x, 1, 3)").evaluate(b)) == \
        [3, 3, 7]
    assert list(parse_expression("least(x, 0)").evaluate(b)) == [-2.5, 0, 0]
    assert list(parse_expression("safe_divide(x, 0)").evaluate(b)) == \
        [0, 0, 0]
    # Druid semantics: MOD keeps the dividend's sign; ROUND is half-away-
    # from-zero with optional places; div() is truncated long division
    iv = {"v": np.asarray([-5, 5, -7], dtype=np.int64)}
    assert list(parse_expression("mod(v, 3)").evaluate(iv)) == [-2, 2, -1]
    fv = {"f": np.asarray([2.5, -2.5, 2.345])}
    assert list(parse_expression("round(f)").evaluate(fv)) == [3, -3, 2]
    assert list(parse_expression("round(f, 2)").evaluate(fv)) == \
        [2.5, -2.5, 2.35]
    assert list(parse_expression("div(v, 2)").evaluate(iv)) == [-2, 2, -3]
    # longs above 2^53 must not round-trip through float64
    big = {"v": np.asarray([2**60 + 1, -(2**60 + 1)], dtype=np.int64)}
    assert list(parse_expression("div(v, 1)").evaluate(big)) == \
        [2**60 + 1, -(2**60 + 1)]
    assert list(parse_expression("round(v)").evaluate(big)) == \
        [2**60 + 1, -(2**60 + 1)]
    assert list(parse_expression("mod(v, 1000)").evaluate(big)) == \
        [(2**60 + 1) % 1000, -((2**60 + 1) % 1000)]
    assert parse_expression(f"mod({2**60 + 1}, {2**60})").evaluate({}) == 1
    # negative places round to tens/hundreds exactly
    assert list(parse_expression("round(v, -2)").evaluate(
        {"v": np.asarray([1251, -1250], dtype=np.int64)})) == [1300, -1300]


def test_expression_eval():
    e = parse_expression("metA * 2 + 1")
    out = e.evaluate({"metA": np.asarray([1.0, 2.0])})
    assert list(out) == [3.0, 5.0]
    e2 = parse_expression("(a > 2) && (b < 1)")
    out2 = e2.evaluate({"a": np.asarray([1, 3, 5]), "b": np.asarray([0, 0, 2])})
    assert list(out2) == [False, True, False]
    e3 = parse_expression("max(a, 3)")
    assert list(e3.evaluate({"a": np.asarray([1, 5])})) == [3, 5]
    assert parse_expression("if(1 > 0, 'yes', 'no')").evaluate({}) == "yes"
    assert parse_expression("abs(0 - 7) % 3").evaluate({}) == 1
