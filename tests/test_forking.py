"""Process-isolated task execution (ForkingTaskRunner / peon / action server
— reference: ForkingTaskRunnerTest, RemoteTaskRunner dead-worker restart)."""
import numpy as np
import pytest

from druid_tpu.cluster import MetadataStore
from druid_tpu.engine import QueryExecutor
from druid_tpu.indexing import ForkingTaskRunner, IndexTask, KillTask
from druid_tpu.indexing.task import task_from_json
from druid_tpu.ingest import InlineFirehose
from druid_tpu.query.aggregators import CountAggregator, LongSumAggregator
from druid_tpu.query.model import TimeseriesQuery
from druid_tpu.utils.intervals import Interval

SPECS = [CountAggregator("rows"), LongSumAggregator("v", "value")]
QSPECS = [LongSumAggregator("rows", "rows"), LongSumAggregator("v", "v")]
WEEK = Interval.of("2026-04-01", "2026-04-08")
T0 = WEEK.start


def _records(n, days=3, seed=0):
    rng = np.random.default_rng(seed)
    day = 86_400_000
    return [{"timestamp": int(T0 + (i % days) * day + i * 1000 % day),
             "page": f"p{int(rng.integers(10))}",
             "value": int(rng.integers(0, 10))} for i in range(n)]


@pytest.fixture()
def runner(tmp_path):
    md = MetadataStore()
    r = ForkingTaskRunner(md, deep_storage_dir=str(tmp_path / "deep"))
    yield md, r
    r.shutdown()


def test_task_json_roundtrip():
    recs = _records(10)
    task = IndexTask("rt_ds", InlineFirehose(recs), None, SPECS,
                     dimensions=["page"], segment_granularity="day",
                     query_granularity="hour", rollup=False)
    j = task.to_json()
    back = task_from_json(j)
    assert back.id == task.id
    assert back.datasource == "rt_ds"
    assert back.dimensions == ["page"]
    assert back.query_granularity == "hour"
    assert back.rollup is False
    assert list(back.firehose.batches(100))[0] == recs


def test_forked_index_task_end_to_end(runner):
    """The task runs in a REAL child process: lock/publish actions flow over
    HTTP to the parent, segment bytes land in shared deep storage."""
    md, r = runner
    recs = _records(3000, days=3)
    task = IndexTask("fork_ds", InlineFirehose(recs), None, SPECS,
                     segment_granularity="day")
    status = r.run_task(task, timeout=120)
    assert status.state == "SUCCESS", status.error
    descs = md.used_segments("fork_ds")
    assert len(descs) == 3
    # the peon really was a separate process
    proc = r.processes[task.id]
    import os
    assert proc.pid != os.getpid() and proc.returncode == 0
    # actions arrived over the wire
    kinds = [a["action"] for a in r.actions.actions if a["task"] == task.id]
    assert "lock" in kinds and "publish" in kinds
    segs = [r.deep_storage.pull(d) for d in descs]
    rows = QueryExecutor(segs).run(
        TimeseriesQuery.of("fork_ds", [WEEK], QSPECS))
    assert rows[0]["result"]["rows"] == 3000
    assert rows[0]["result"]["v"] == sum(x["value"] for x in recs)


def test_peon_killed_mid_task_reruns_to_success(runner):
    """Kill the peon right as it acquires its lock (OOM-kill stand-in): the
    runner must release the dead task's locks, re-fork, and the retry must
    publish exactly once — while the parent keeps serving."""
    md, r = runner
    recs = _records(2000, days=2)
    task = IndexTask("kill_ds", InlineFirehose(recs), None, SPECS,
                     segment_granularity="day")
    state = {"killed": False}
    orig = r.actions._do_action

    def hook(payload):
        if payload["action"] == "lock" and not state["killed"]:
            state["killed"] = True
            proc = r.processes[payload["task"]]
            proc.kill()
            proc.wait()
        return orig(payload)

    r.actions._do_action = hook
    status = r.run_task(task, timeout=120)
    assert status.state == "SUCCESS", status.error
    assert state["killed"] and r.attempts[task.id] == 2
    # exactly-once: one publish, correct totals
    descs = md.used_segments("kill_ds")
    assert len(descs) == 2
    segs = [r.deep_storage.pull(d) for d in descs]
    rows = QueryExecutor(segs).run(
        TimeseriesQuery.of("kill_ds", [WEEK], QSPECS))
    assert rows[0]["result"]["rows"] == 2000


def test_peon_killed_after_publish_does_not_duplicate(runner):
    """A peon that dies AFTER its transactional publish but BEFORE
    reporting status is re-forked; the retry's publish must be a no-op
    (exactly-once for crash-retried appends)."""
    md, r = runner
    recs = _records(800, days=1)
    task = IndexTask("dup_ds", InlineFirehose(recs), None, SPECS,
                     segment_granularity="day", appending=True)
    state = {"killed": False}
    orig = r.actions._do_action

    def hook(payload):
        out = orig(payload)
        if payload["action"] == "publish" and not state["killed"]:
            state["killed"] = True
            proc = r.processes[payload["task"]]
            proc.kill()     # dies before the response reaches it
            proc.wait()
        return out

    r.actions._do_action = hook
    status = r.run_task(task, timeout=120)
    assert status.state == "SUCCESS", status.error
    assert state["killed"] and r.attempts[task.id] == 2
    descs = md.used_segments("dup_ds")
    segs = [r.deep_storage.pull(d) for d in descs]
    rows = QueryExecutor(segs).run(
        TimeseriesQuery.of("dup_ds", [WEEK], QSPECS))
    assert rows[0]["result"]["rows"] == 800      # not 1600


def test_peon_that_always_dies_reports_failure(runner):
    md, r = runner
    task = IndexTask("dead_ds", InlineFirehose(_records(500)), None, SPECS)
    orig = r.actions._do_action

    def hook(payload):
        if payload["action"] == "lock":
            proc = r.processes[payload["task"]]
            proc.kill()
            proc.wait()
        return orig(payload)

    r.actions._do_action = hook
    status = r.run_task(task, timeout=120)
    assert status.state == "FAILED"
    assert "died" in status.error
    assert md.used_segments("dead_ds") == []


def test_parallel_index_fans_out_over_peons(runner):
    """ParallelIndexTask's supervisor peon submits sub-tasks back to the
    overlord, which forks one peon per sub-task
    (ParallelIndexSupervisorTask dynamic-partitioning mode)."""
    from druid_tpu.indexing import ParallelIndexTask
    md, r = runner
    recs = _records(4000, days=2)
    task = ParallelIndexTask("par_ds", InlineFirehose(recs), None, SPECS,
                             segment_granularity="day", max_num_subtasks=3)
    status = r.run_task(task, timeout=180)
    assert status.state == "SUCCESS", status.error
    sub_ids = {a["task"] for a in r.actions.actions
               if a["task"].startswith(f"{task.id}_sub")}
    assert len(sub_ids) == 3
    # every sub-task ran in its own forked peon
    assert all(f"{task.id}_sub{i}" in r.processes for i in range(3))
    descs = md.used_segments("par_ds")
    assert len(descs) >= 2      # ≥ one appended partition per day bucket
    segs = [r.deep_storage.pull(d) for d in descs]
    rows = QueryExecutor(segs).run(
        TimeseriesQuery.of("par_ds", [WEEK], QSPECS))
    assert rows[0]["result"]["rows"] == 4000
    assert rows[0]["result"]["v"] == sum(x["value"] for x in recs)


def test_task_log_captured(runner):
    md, r = runner
    task = IndexTask("log_ds", InlineFirehose(_records(100, days=1)), None,
                     SPECS, segment_granularity="day")
    assert r.run_task(task, timeout=120).state == "SUCCESS"
    log = r.task_log(task.id)
    assert "attempt 1" in log    # attempts are 1-based


def test_monitor_wait_is_bounded_and_escalates_on_shutdown(tmp_path):
    """The monitor's park on a live peon is a bounded-quantum loop, not a
    bare proc.wait(): a shutdown observed between quanta must escalate
    terminate → kill and return promptly even when the peon is wedged —
    stop() can never hang behind a peon that stopped answering."""
    import subprocess
    import sys
    import time

    md = MetadataStore()
    r = ForkingTaskRunner(md, deep_storage_dir=str(tmp_path / "deep"))
    r.PROC_WAIT_POLL_S = 0.05
    r.PROC_KILL_GRACE_S = 2.0
    proc = subprocess.Popen([sys.executable, "-c",
                             "import time; time.sleep(600)"])
    try:
        r._shutdown = True
        t0 = time.monotonic()
        r._await_proc(proc)
        elapsed = time.monotonic() - t0
        # one poll quantum to notice the shutdown + the terminate grace,
        # never the peon's 600s sleep
        assert elapsed < 5.0, f"_await_proc parked {elapsed:.1f}s"
        assert proc.poll() is not None, "wedged peon was not terminated"
    finally:
        if proc.poll() is None:
            proc.kill()
        r.shutdown()


def test_forked_kill_task(runner):
    md, r = runner
    recs = _records(400, days=1)
    t1 = IndexTask("purge_ds", InlineFirehose(recs), None, SPECS,
                   segment_granularity="day")
    assert r.run_task(t1, timeout=120).state == "SUCCESS"
    ids = [d.id for d in md.used_segments("purge_ds")]
    md.mark_unused(ids)
    t2 = KillTask("purge_ds", WEEK)
    assert r.run_task(t2, timeout=120).state == "SUCCESS"
    assert md.unused_segments("purge_ds", WEEK) == []
