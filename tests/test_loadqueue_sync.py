"""Async load queues + inventory sync loop (reference: LoadQueuePeon,
HttpServerInventoryView poll)."""
import pytest

from druid_tpu.cluster import (Broker, Coordinator, DataNode,
                               DataNodeServer, DynamicConfig, InventoryView,
                               MetadataStore, RemoteDataNodeClient,
                               descriptor_for)
from druid_tpu.query.aggregators import CountAggregator
from druid_tpu.query.model import TimeseriesQuery
from druid_tpu.utils.intervals import Interval

WEEK = Interval.of("2026-01-01", "2026-01-08")


def test_async_loading_assigns_through_peons(segments):
    md = MetadataStore()
    view = InventoryView()
    nodes = [DataNode(f"n{i}") for i in range(2)]
    for n in nodes:
        view.register(n)
    by_id = {descriptor_for(s).id: s for s in segments}
    md.publish_segments([descriptor_for(s) for s in segments])
    md.set_rules("_default", [{"type": "loadForever",
                               "tieredReplicants": {"_default_tier": 2}}])
    coord = Coordinator(md, view, lambda d: by_id.get(d.id),
                        DynamicConfig(replication_throttle_limit=100),
                        async_loading=True)
    stats = coord.run_once()
    assert stats.assigned == 2 * len(segments)    # enqueued counts
    assert coord.wait_loads(30.0)
    for s in segments:
        rs = view.replica_set(descriptor_for(s).id)
        assert rs is not None and len(rs.servers) == 2
    # convergence: a second cycle (workers done) assigns nothing more
    stats2 = coord.run_once()
    assert stats2.assigned == 0
    # queries serve what the peons loaded
    rows = Broker(view).run(
        TimeseriesQuery.of("test", [WEEK], [CountAggregator("rows")]))
    assert rows[0]["result"]["rows"] == sum(s.n_rows for s in segments)
    coord.stop()


def test_async_loading_pending_counts_as_holder(segments):
    """While a load sits in one node's queue, the same cycle must not pile
    the replica onto other nodes (currentlyLoading accounting)."""
    md = MetadataStore()
    view = InventoryView()
    nodes = [DataNode(f"n{i}") for i in range(3)]
    for n in nodes:
        view.register(n)
    by_id = {descriptor_for(s).id: s for s in segments}
    md.publish_segments([descriptor_for(s) for s in segments])
    md.set_rules("_default", [{"type": "loadForever",
                               "tieredReplicants": {"_default_tier": 1}}])

    import time

    def slow_source(d):
        time.sleep(0.2)
        return by_id.get(d.id)

    coord = Coordinator(md, view, slow_source, async_loading=True)
    coord.run_once()
    coord.run_once()       # workers still busy: pending must block re-assign
    assert coord.wait_loads(30.0)
    for s in segments:
        rs = view.replica_set(descriptor_for(s).id)
        assert rs is not None and len(rs.servers) == 1, rs.servers
    coord.stop()


def test_load_queue_bound_defers(segments):
    md = MetadataStore()
    view = InventoryView()
    node = DataNode("n0")
    view.register(node)
    by_id = {descriptor_for(s).id: s for s in segments}
    md.publish_segments([descriptor_for(s) for s in segments])
    md.set_rules("_default", [{"type": "loadForever",
                               "tieredReplicants": {"_default_tier": 1}}])

    import threading
    gate = threading.Event()

    def gated_source(d):
        gate.wait(10.0)
        return by_id.get(d.id)

    coord = Coordinator(md, view, gated_source,
                        DynamicConfig(max_segments_in_node_loading_queue=1),
                        async_loading=True)
    stats = coord.run_once()
    # queue bound 1: one enqueued (maybe one more already taken by the
    # worker), the rest deferred to later cycles
    assert 0 < stats.assigned <= 2
    assert stats.unassigned >= len(segments) - 2
    gate.set()
    assert coord.wait_loads(30.0)
    for _ in range(len(segments)):
        coord.run_once()
        coord.wait_loads(30.0)
    assert sum(1 for s in segments
               if view.replica_set(descriptor_for(s).id)) == len(segments)
    coord.stop()


def test_async_balance_never_leaves_zero_replicas(segments):
    """Balancing under async loading drops the source replica only AFTER
    the destination's worker finishes — at every instant each segment has
    >= 1 announced replica."""
    import threading
    md = MetadataStore()
    view = InventoryView()
    a, b = DataNode("a"), DataNode("b")
    view.register(a)
    view.register(b)
    by_id = {descriptor_for(s).id: s for s in segments}
    md.publish_segments([descriptor_for(s) for s in segments])
    md.set_rules("_default", [{"type": "loadForever",
                               "tieredReplicants": {"_default_tier": 1}}])
    for s in segments:      # preload everything on 'a'
        a.load_segment(s)
        view.announce("a", descriptor_for(s))

    gate = threading.Event()
    violations = []

    def gated_source(d):
        # while the move is in flight, the source must still be announced
        rs = view.replica_set(d.id)
        if rs is None or not rs.servers:
            violations.append(d.id)
        gate.wait(10.0)
        return by_id.get(d.id)

    coord = Coordinator(md, view, gated_source,
                        DynamicConfig(max_segments_to_move=10),
                        async_loading=True)
    coord.run_once()
    gate.set()
    assert coord.wait_loads(30.0)
    assert violations == []
    for s in segments:
        rs = view.replica_set(descriptor_for(s).id)
        assert rs is not None and len(rs.servers) == 1
    assert abs(a.segment_count() - b.segment_count()) <= 1
    coord.stop()


def test_status_descriptors_keep_real_shard_specs(segments):
    """Inventory sync must carry the REAL shard spec — the timeline
    completeness check depends on it (a numbered set must not read as
    complete with half its partitions)."""
    from druid_tpu.cluster.shardspec import NumberedShardSpec
    from druid_tpu.cluster.metadata import SegmentDescriptor
    s = segments[0]
    d = SegmentDescriptor(s.id.datasource, s.id.interval, s.id.version,
                          0, NumberedShardSpec(0, 2))
    node = DataNode("n0")
    node.load_segment(s, d)
    srv = DataNodeServer(node).start()
    try:
        client = RemoteDataNodeClient("n0", srv.url)
        descs = client.served_descriptors()
        assert len(descs) == 1
        spec = descs[0].shard_spec
        assert isinstance(spec, NumberedShardSpec)
        assert spec.partitions == 2
    finally:
        srv.stop()


def test_sync_blip_does_not_mass_unannounce(segments):
    """A transient /status failure aborts that server's sync round; it
    must NOT read as 'serves nothing'."""
    node = DataNode("r0")
    for s in segments:
        node.load_segment(s)
    srv = DataNodeServer(node).start()
    client = RemoteDataNodeClient("r0", srv.url, connect_timeout=0.5)
    view = InventoryView()
    view.register(client)
    view.sync_all()
    assert len(view.served_segments("r0")) == len(segments)
    srv.stop()                       # blip: server gone for one round
    added, removed = view.sync_all()
    assert removed == 0              # nothing retracted
    assert len(view.served_segments("r0")) == len(segments)


def test_inventory_sync_loop_over_http(segments):
    """A broker's view discovers remote segments via /status descriptors —
    no hand-registration — and retracts dropped ones on the next sync."""
    node = DataNode("remote0")
    for s in segments:
        node.load_segment(s)
    srv = DataNodeServer(node).start()
    try:
        client = RemoteDataNodeClient("remote0", srv.url)
        view = InventoryView()
        view.register(client)
        added, removed = view.sync_all()
        assert added == len(segments) and removed == 0
        broker = Broker(view)
        rows = broker.run(
            TimeseriesQuery.of("test", [WEEK], [CountAggregator("rows")]))
        assert rows[0]["result"]["rows"] == sum(s.n_rows for s in segments)
        # drop on the node; the next sync retracts the announcement
        dropped = segments[0]
        node.drop_segment(str(dropped.id))
        added, removed = view.sync_all()
        assert removed == 1
        rows = broker.run(
            TimeseriesQuery.of("test", [WEEK], [CountAggregator("rows")]))
        want = sum(s.n_rows for s in segments) - dropped.n_rows
        assert rows[0]["result"]["rows"] == want
    finally:
        srv.stop()
