"""raceguard rule tests: each concurrency rule fires on its hazard, stays
quiet on the disciplined equivalent, and honors rationale suppressions —
plus the whole-program machinery (binder, dataflow, thread roots, lock-order
graph, cross-module cache soundness, --dot CLI)."""
import json
import subprocess
import sys
from pathlib import Path

import pytest

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

from tools.druidlint.core import (LintConfig, check_source,  # noqa: E402
                                  lint_paths, load_config)
from tools.druidlint.raceguard import (analyze_sources,  # noqa: E402
                                       analyze_tree, render_dot)

RULES = ("unguarded-shared-write", "lock-order-cycle", "guard-consistency",
         "lock-in-traced")


def cfg(*rules) -> LintConfig:
    """Config scoped to the given rules with NO on-disk program (root
    points nowhere), so check_source analyzes the module standalone."""
    c = LintConfig(rules=list(rules) if rules else [])
    c.root = "/nonexistent-raceguard-root"
    return c


def findings_of(source: str, rule: str, path: str = "druid_tpu/mod.py"):
    return [f for f in check_source(source, path, cfg(rule))
            if f.rule == rule]


# ---------------------------------------------------------------------------
# unguarded-shared-write
# ---------------------------------------------------------------------------

MIXED_WRITE = """\
import threading

class Counter:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0

    def bump(self):
        with self._lock:
            self.n += 1

    def reset(self):
        self.n = 0
"""


def test_unguarded_write_mixed_discipline_fires():
    got = findings_of(MIXED_WRITE, "unguarded-shared-write")
    assert len(got) == 1
    assert got[0].line == 13                 # the reset() write


def test_unguarded_write_all_locked_is_quiet():
    src = MIXED_WRITE.replace("    def reset(self):\n        self.n = 0\n",
                              "    def reset(self):\n"
                              "        with self._lock:\n"
                              "            self.n = 0\n")
    assert findings_of(src, "unguarded-shared-write") == []


def test_unguarded_write_init_is_exempt():
    src = """\
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0
        self.n = 1

    def bump(self):
        with self._lock:
            self.n += 1
"""
    assert findings_of(src, "unguarded-shared-write") == []


def test_unguarded_write_suppression():
    src = MIXED_WRITE.replace(
        "        self.n = 0\n",
        "        self.n = 0  "
        "# druidlint: disable=unguarded-shared-write  # reset is test-only\n",
        1).replace("    def reset(self):\n        self.n = 0\n",
                   "    def reset(self):\n        self.n = 0  "
                   "# druidlint: disable=unguarded-shared-write\n")
    assert findings_of(src, "unguarded-shared-write") == []


def test_unguarded_write_mutator_counts_as_write():
    src = """\
import threading

class Q:
    def __init__(self):
        self._lock = threading.Lock()
        self.items = []

    def put(self, x):
        with self._lock:
            self.items.append(x)

    def drop(self):
        self.items.clear()
"""
    got = findings_of(src, "unguarded-shared-write")
    assert len(got) == 1 and got[0].line == 13


TWO_ROOT_WRITE = """\
import threading

class Shared:
    def __init__(self):
        self._lock = threading.Lock()
        self.state = {}

    def writer_a(self):
        self.state["a"] = 1

    def writer_b(self):
        self.state["b"] = 2

    def start(self):
        threading.Thread(target=self.writer_a).start()
        threading.Thread(target=self.writer_b).start()
"""


def test_two_thread_roots_no_common_lock_fires():
    got = findings_of(TWO_ROOT_WRITE, "unguarded-shared-write")
    assert len(got) == 1                     # one finding per state
    assert "thread roots" in got[0].message


def test_lockless_class_from_roots_is_quiet():
    # a class without any lock is treated as per-request state: flagging
    # every plan/builder object reachable from a handler would drown signal
    src = TWO_ROOT_WRITE.replace(
        "        self._lock = threading.Lock()\n", "")
    assert findings_of(src, "unguarded-shared-write") == []


def test_module_global_mixed_discipline_fires():
    src = """\
import threading

_LOCK = threading.Lock()
_CACHE = {}

def insert(k, v):
    with _LOCK:
        _CACHE[k] = v

def wipe():
    _CACHE.clear()
"""
    got = findings_of(src, "unguarded-shared-write")
    assert len(got) == 1 and got[0].line == 11


# ---------------------------------------------------------------------------
# guard-consistency
# ---------------------------------------------------------------------------

GUARDED_READ = """\
import threading

class Registry:
    def __init__(self):
        self._lock = threading.Lock()
        self.entries = {}

    def add(self, k, v):
        with self._lock:
            self.entries[k] = v

    def peek(self):
        return len(self.entries)

    def start(self):
        threading.Thread(target=self.add).start()
        threading.Thread(target=self.peek).start()
"""


def test_guard_consistency_unlocked_read_on_root_path_fires():
    got = findings_of(GUARDED_READ, "guard-consistency")
    assert len(got) == 1
    assert got[0].line == 13


def test_guard_consistency_locked_read_is_quiet():
    src = GUARDED_READ.replace(
        "    def peek(self):\n        return len(self.entries)\n",
        "    def peek(self):\n"
        "        with self._lock:\n"
        "            return len(self.entries)\n")
    assert findings_of(src, "guard-consistency") == []


def test_guard_consistency_off_root_read_is_quiet():
    # nothing spawns a thread that reaches peek(): single-threaded read
    src = GUARDED_READ.replace(
        "        threading.Thread(target=self.peek).start()\n", "")
    assert findings_of(src, "guard-consistency") == []


def test_guard_consistency_locked_helper_is_quiet():
    """Interprocedural MUST-held: a _locked helper invoked only under the
    lock holds it by intersection over call sites."""
    src = """\
import threading

class Pool:
    def __init__(self):
        self._lock = threading.Lock()
        self.entries = {}

    def insert(self, k, v):
        with self._lock:
            self.entries[k] = v
            self._trim_locked()

    def _trim_locked(self):
        while len(self.entries) > 8:
            self.entries.popitem()

    def start(self):
        threading.Thread(target=self.insert).start()
"""
    assert findings_of(src, "guard-consistency") == []


def test_guard_consistency_suppression():
    src = GUARDED_READ.replace(
        "        return len(self.entries)\n",
        "        return len(self.entries)  "
        "# druidlint: disable=guard-consistency\n")
    assert findings_of(src, "guard-consistency") == []


# ---------------------------------------------------------------------------
# lock-order-cycle
# ---------------------------------------------------------------------------

ABBA = """\
import threading

class A:
    def __init__(self, b: "B"):
        self._lock = threading.Lock()
        self.b = b

    def cross(self):
        with self._lock:
            self.b.poke()

    def poke(self):
        with self._lock:
            pass

class B:
    def __init__(self, a: A):
        self._lock = threading.Lock()
        self.a = a

    def cross(self):
        with self._lock:
            self.a.poke()

    def poke(self):
        with self._lock:
            pass
"""


def test_lock_order_cycle_abba_fires():
    got = findings_of(ABBA, "lock-order-cycle")
    assert len(got) == 1
    assert "cycle" in got[0].message


def test_lock_order_consistent_order_is_quiet():
    one_way = ABBA.replace(
        "    def cross(self):\n"
        "        with self._lock:\n"
        "            self.a.poke()\n", "    def cross(self):\n"
                                       "        self.a.poke()\n")
    assert findings_of(one_way, "lock-order-cycle") == []


def test_self_deadlock_through_self_call_fires():
    src = """\
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()

    def outer(self):
        with self._lock:
            self.inner()

    def inner(self):
        with self._lock:
            pass
"""
    got = findings_of(src, "lock-order-cycle")
    assert len(got) == 1
    assert "non-reentrant" in got[0].message


def test_lock_order_cycle_suppression():
    """A rationale pragma on the cycle's anchor line silences it (e.g. a
    cycle that a runtime mode flag makes unreachable)."""
    got = findings_of(ABBA, "lock-order-cycle")
    assert len(got) == 1
    lines = ABBA.splitlines()
    lines[got[0].line - 1] += "  # druidlint: disable=lock-order-cycle"
    assert findings_of("\n".join(lines) + "\n", "lock-order-cycle") == []


def test_rlock_self_reentry_is_quiet():
    src = """\
import threading

class C:
    def __init__(self):
        self._lock = threading.RLock()

    def outer(self):
        with self._lock:
            self.inner()

    def inner(self):
        with self._lock:
            pass
"""
    assert findings_of(src, "lock-order-cycle") == []


def test_condition_alias_shares_identity():
    """Condition(self._lock) IS self._lock: nesting them is reentrancy of
    one lock (a runtime bug on a plain Lock, but not an ABBA cycle), and
    split guard attribution would be wrong."""
    src = """\
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self.jobs = []

    def put(self, j):
        with self._cond:
            self.jobs.append(j)

    def flush(self):
        with self._lock:
            self.jobs.clear()
"""
    # both writes hold the SAME lock id — no mixed-discipline finding
    assert findings_of(src, "unguarded-shared-write") == []


# ---------------------------------------------------------------------------
# lock-in-traced
# ---------------------------------------------------------------------------

LOCK_IN_JIT = """\
import threading
import jax

_lock = threading.Lock()

def kernel(x):
    with _lock:
        return x + 1

fn = jax.jit(kernel)
"""


def test_lock_in_traced_fires():
    got = findings_of(LOCK_IN_JIT, "lock-in-traced")
    assert len(got) == 1 and got[0].line == 7


def test_lock_acquire_in_traced_fires():
    src = LOCK_IN_JIT.replace("    with _lock:\n        return x + 1\n",
                              "    _lock.acquire()\n    return x + 1\n")
    got = findings_of(src, "lock-in-traced")
    assert len(got) == 1


def test_lock_outside_traced_is_quiet():
    src = """\
import threading
import jax

_lock = threading.Lock()

def kernel(x):
    return x + 1

def dispatch(x):
    with _lock:
        return jax.jit(kernel)(x)
"""
    assert findings_of(src, "lock-in-traced") == []


def test_lock_in_traced_suppression():
    src = LOCK_IN_JIT.replace(
        "    with _lock:\n",
        "    with _lock:  # druidlint: disable=lock-in-traced\n")
    assert findings_of(src, "lock-in-traced") == []


# ---------------------------------------------------------------------------
# whole-program machinery
# ---------------------------------------------------------------------------

def test_cross_module_root_reaches_write(tmp_path):
    """The hazard spans two modules: the thread root lives in a.py, the
    mixed-discipline class in b.py — only a whole-program view connects
    them."""
    pkg = tmp_path / "druid_tpu"
    pkg.mkdir()
    (pkg / "b.py").write_text("""\
import threading

class Store:
    def __init__(self):
        self._lock = threading.Lock()
        self.rows = {}

    def write_a(self):
        self.rows["a"] = 1

    def write_b(self):
        self.rows["b"] = 2
""")
    (pkg / "a.py").write_text("""\
import threading
from druid_tpu.b import Store

def launch():
    s = Store()
    threading.Thread(target=s.write_a).start()
    threading.Thread(target=s.write_b).start()
""")
    config = load_config(tmp_path)
    config.rules = ["unguarded-shared-write"]
    findings = lint_paths(tmp_path, config)
    assert [f.rule for f in findings] == ["unguarded-shared-write"]
    assert findings[0].path == "druid_tpu/b.py"


def test_cross_module_cache_is_dropped_on_any_program_edit(tmp_path):
    """Per-file mtime caching must NOT survive edits to OTHER program
    modules: adding a thread root in a.py changes b.py's findings."""
    import os
    pkg = tmp_path / "druid_tpu"
    pkg.mkdir()
    (pkg / "b.py").write_text("""\
import threading

class Store:
    def __init__(self):
        self._lock = threading.Lock()
        self.rows = {}

    def write_a(self):
        self.rows["a"] = 1

    def write_b(self):
        self.rows["b"] = 2
""")
    (pkg / "a.py").write_text("from druid_tpu.b import Store\n")
    cache = tmp_path / ".cache.json"
    config = load_config(tmp_path)
    config.rules = ["unguarded-shared-write"]
    assert lint_paths(tmp_path, config, cache_path=cache) == []
    # grow the root in a DIFFERENT file than the finding's
    (pkg / "a.py").write_text("""\
import threading
from druid_tpu.b import Store

def launch():
    s = Store()
    threading.Thread(target=s.write_a).start()
    threading.Thread(target=s.write_b).start()
""")
    os.utime(pkg / "b.py")        # keep b.py's own mtime-key identical
    config2 = load_config(tmp_path)
    config2.rules = ["unguarded-shared-write"]
    findings = lint_paths(tmp_path, config2, cache_path=cache)
    assert [f.path for f in findings] == ["druid_tpu/b.py"]


HANDLER_PROGRAM = """\
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

class Server:
    def __init__(self):
        self._lock = threading.Lock()
        self.hits = {}
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):
                outer.record()

        self.handler = Handler

    def record(self):
        self.hits["n"] = self.hits.get("n", 0) + 1
"""


def test_handler_outer_self_idiom_is_a_concurrent_root():
    """The nested-Handler `outer = self` closure types the call back into
    the server class; do_* methods are concurrent roots, so the unlocked
    dict write fires (variant b: no locked write exists — the root
    discovery alone must carry the finding)."""
    got = findings_of(HANDLER_PROGRAM, "unguarded-shared-write")
    assert len(got) == 1
    assert "thread roots" in got[0].message
    assert got[0].line == 17               # the record() dict write


def test_dict_element_annotation_types_lock_edges():
    """`self._tls: Dict[str, Timeline]` + .setdefault() resolves the
    element class — the acquisition inside Timeline lands in the order
    graph (the edge the dynamic witness observed in the real tree)."""
    src = '''\
import threading
from typing import Dict

class Timeline:
    def __init__(self):
        self._lock = threading.RLock()

    def add(self, x):
        with self._lock:
            pass

class View:
    def __init__(self):
        self._lock = threading.RLock()
        self._tls: Dict[str, Timeline] = {}

    def announce(self, ds, x):
        with self._lock:
            tl = self._tls.setdefault(ds, Timeline())
            tl.add(x)
'''
    prog = analyze_sources({"druid_tpu/m.py": src}, cfg())
    edges = {(a.split("::")[-1], b.split("::")[-1])
             for a, b in prog.order_edges}
    assert ("View._lock", "Timeline._lock") in edges


def test_iteration_element_typing_extends_order_graph():
    """`for rs in self._replicas.values():` types the loop variable from
    the Dict value annotation — acquisitions inside the element class
    join the order graph (the ROADMAP replica-set/timeline rider)."""
    src = '''\
import threading
from typing import Dict

class ReplicaSet:
    def __init__(self):
        self._lock = threading.Lock()

    def poke(self):
        with self._lock:
            pass

class View:
    def __init__(self):
        self._lock = threading.Lock()
        self._replicas: Dict[str, ReplicaSet] = {}

    def sweep(self):
        with self._lock:
            for rs in self._replicas.values():
                rs.poke()
'''
    prog = analyze_sources({"druid_tpu/m.py": src}, cfg())
    edges = {(a.split("::")[-1], b.split("::")[-1])
             for a, b in prog.order_edges}
    assert ("View._lock", "ReplicaSet._lock") in edges


def test_closure_rebinding_same_identity_keeps_type():
    """A closed-over local reassigned AFTER capture — to the SAME class —
    keeps its identity: the closure's call still resolves and the lock
    edge lands in the order graph (the PR 5 binder rider)."""
    src = '''\
import threading

class Worker:
    def __init__(self):
        self._lock = threading.Lock()

    def step(self):
        with self._lock:
            pass

class Driver:
    def __init__(self):
        self._lock = threading.Lock()

    def start(self):
        worker = Worker()

        def tick():
            with self._lock:
                worker.step()

        self._t = threading.Thread(target=tick)
        worker = Worker()       # rebound after capture, same class
        self._t.start()
'''
    prog = analyze_sources({"druid_tpu/m.py": src}, cfg())
    edges = {(a.split("::")[-1], b.split("::")[-1])
             for a, b in prog.order_edges}
    assert ("Driver._lock", "Worker._lock") in edges


def test_closure_rebinding_conflicting_identity_degrades():
    """Rebinding to a DIFFERENT class must still drop the binding — typing
    the capture as either class would fabricate (or miss) edges, so the
    conservative unknown wins and no Worker/Other edge appears."""
    src = '''\
import threading

class Worker:
    def __init__(self):
        self._lock = threading.Lock()

    def step(self):
        with self._lock:
            pass

class Other:
    def __init__(self):
        self._lock = threading.Lock()

    def step(self):
        with self._lock:
            pass

class Driver:
    def __init__(self):
        self._lock = threading.Lock()

    def start(self):
        worker = Worker()

        def tick():
            with self._lock:
                worker.step()

        self._t = threading.Thread(target=tick)
        worker = Other()        # conflicting rebinding: identity unknown
        self._t.start()
'''
    prog = analyze_sources({"druid_tpu/m.py": src}, cfg())
    edges = {(a.split("::")[-1], b.split("::")[-1])
             for a, b in prog.order_edges}
    assert ("Driver._lock", "Worker._lock") not in edges
    assert ("Driver._lock", "Other._lock") not in edges


def test_iteration_element_typing_items_and_list():
    """`for k, rs in d.items()` binds the SECOND target; plain iteration
    binds elements for List (sequence) annotations but NOT for Dict
    (plain mapping iteration yields keys, typing them as values would
    fabricate edges)."""
    src = '''\
import threading
from typing import Dict, List

class Node:
    def __init__(self):
        self._lock = threading.Lock()

    def poke(self):
        with self._lock:
            pass

class View:
    def __init__(self):
        self._lock = threading.Lock()
        self._by_name: Dict[str, Node] = {}
        self._all: List[Node] = []

    def sweep_items(self):
        with self._lock:
            for name, n in self._by_name.items():
                n.poke()

    def sweep_list(self):
        with self._lock:
            for n in self._all:
                n.poke()
'''
    prog = analyze_sources({"druid_tpu/m.py": src}, cfg())
    edges = {(a.split("::")[-1], b.split("::")[-1])
             for a, b in prog.order_edges}
    assert ("View._lock", "Node._lock") in edges
    # mapping keys must NOT be typed as elements
    prog2 = analyze_sources(
        {"druid_tpu/m.py": '''\
import threading
from typing import Dict

class Node:
    def __init__(self):
        self._lock = threading.Lock()

    def poke(self):
        with self._lock:
            pass

class View:
    def __init__(self):
        self._lock = threading.Lock()
        self._by_name: Dict[str, Node] = {}

    def sweep(self):
        with self._lock:
            for n in self._by_name:
                n.poke()
'''}, cfg())
    edges2 = {(a.split("::")[-1], b.split("::")[-1])
              for a, b in prog2.order_edges}
    assert ("View._lock", "Node._lock") not in edges2


def test_comprehension_target_does_not_clobber_typed_local():
    """Comprehension targets are their own scope in py3: a comprehension
    reusing a typed local's name must not invalidate that binding (the
    binder's reassigned-twice rule would otherwise silently drop the
    (View._lock, Node._lock) edge), and a comprehension over a typed
    List still types calls INSIDE its own body."""
    src = '''\
import threading
from typing import List

class Node:
    def __init__(self):
        self._lock = threading.Lock()

    def poke(self):
        with self._lock:
            pass

class Elem:
    def __init__(self):
        self._lock = threading.Lock()

    def poke(self):
        with self._lock:
            pass

class View:
    def __init__(self):
        self._lock = threading.Lock()
        self._elems: List[Elem] = []
        self.ids = ("a", "b")
        self.node = Node()

    def sweep(self):
        ids = [n for n in self.ids]       # untyped comp reuses the name
        n = self.node                     # ...of a typed local
        with self._lock:
            n.poke()
        return ids

    def names(self):
        with self._lock:
            return [e.poke() for e in self._elems]
'''
    prog = analyze_sources({"druid_tpu/m.py": src}, cfg())
    edges = {(a.split("::")[-1], b.split("::")[-1])
             for a, b in prog.order_edges}
    # sweep(): the statement binding survives the comprehension
    assert ("View._lock", "Node._lock") in edges
    # names(): the comp body itself still resolves via the List element
    assert ("View._lock", "Elem._lock") in edges


MANUAL_REGION = """\
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0

    def locked_bump(self):
        self._lock.acquire()
        try:
            self.n += 1
        finally:
            self._lock.release()

    def other(self):
        {other_body}
"""


def test_manual_acquire_release_region_counts_as_locked():
    """Positive/negative pair for manual held regions: a write inside an
    acquire()/try/finally-release() region is LOCKED (mixing it with an
    unlocked write fires; two manual regions are consistent)."""
    # negative: both writes inside manual regions → quiet
    quiet = MANUAL_REGION.format(other_body="""self._lock.acquire()
        try:
            self.n = 0
        finally:
            self._lock.release()""")
    assert findings_of(quiet, "unguarded-shared-write") == []
    # positive: one manual region + one bare write → the bare write fires
    noisy = MANUAL_REGION.format(other_body="self.n = 0")
    got = findings_of(noisy, "unguarded-shared-write")
    assert len(got) == 1
    assert got[0].line == 16                 # the bare write in other()


def test_manual_release_ends_the_held_region():
    """A write AFTER the statement-level release() is unlocked again —
    the region must not extend past the release."""
    src = """\
import threading

class C:
    def __init__(self):
        self._lock = threading.Lock()
        self.n = 0

    def bump(self):
        with self._lock:
            self.n += 1

    def sloppy(self):
        self._lock.acquire()
        self.n += 1
        self._lock.release()
        self.n = 0
"""
    got = findings_of(src, "unguarded-shared-write")
    assert len(got) == 1
    assert got[0].line == 16                 # only the post-release write


def test_manual_region_held_at_call_sites_joins_order_graph():
    """Calls made between acquire() and release() carry the lock in both
    dataflows — a nested acquisition inside the region is an order edge."""
    src = '''\
import threading

class Inner:
    def __init__(self):
        self._lock = threading.Lock()

    def poke(self):
        with self._lock:
            pass

class Outer:
    def __init__(self, inner: Inner):
        self._lock = threading.Lock()
        self.inner = inner

    def run(self):
        self._lock.acquire()
        try:
            self.inner.poke()
        finally:
            self._lock.release()
'''
    prog = analyze_sources({"druid_tpu/m.py": src}, cfg())
    edges = {(a.split("::")[-1], b.split("::")[-1])
             for a, b in prog.order_edges}
    assert ("Outer._lock", "Inner._lock") in edges


def test_thread_root_discovery_kinds():
    src = """\
import threading
import weakref
from concurrent.futures import ThreadPoolExecutor

class Svc:
    def __init__(self):
        self.pool = ThreadPoolExecutor(2)

    def tick(self):
        pass

    def probe(self):
        pass

    def cleanup(self):
        pass

    def start(self, obj):
        threading.Thread(target=self.tick).start()
        self.pool.submit(self.probe)
        weakref.finalize(obj, self.cleanup)
"""
    prog = analyze_sources({"druid_tpu/m.py": src}, cfg())
    kinds = {fid.split(".")[-1]: kind for fid, kind in prog.roots.items()}
    assert kinds == {"tick": "thread", "probe": "submit",
                     "cleanup": "finalizer"}


def test_extra_thread_roots_config():
    src = """\
import threading

class Mon:
    def __init__(self):
        self._lock = threading.Lock()
        self.last = {}

    def set_last(self, v):
        with self._lock:
            self.last["v"] = v

    def do_monitor(self, emitter):
        return len(self.last)
"""
    quiet = cfg("guard-consistency")
    assert [f for f in check_source(src, "druid_tpu/m.py", quiet)
            if f.rule == "guard-consistency"] == []
    rooted = cfg("guard-consistency")
    rooted.extra_thread_roots = ["druid_tpu/*::*.do_monitor",
                                 "druid_tpu/*::*.set_last"]
    got = [f for f in check_source(src, "druid_tpu/m.py", rooted)
           if f.rule == "guard-consistency"]
    assert len(got) == 1 and got[0].line == 13


def test_lock_sites_map_construction_lines():
    prog = analyze_sources({"druid_tpu/m.py": MIXED_WRITE}, cfg())
    sites = prog.lock_sites()
    assert sites == {("druid_tpu/m.py", 5):
                     "druid_tpu/m.py::Counter._lock"}


def test_real_tree_program_is_acyclic_and_indexed():
    """The shipped tree: locks indexed, thread roots found, order graph
    cycle-free (the gate would fail otherwise — this pins the numbers from
    drifting silently to zero, which would mean the analyzer went blind)."""
    config = load_config(REPO_ROOT)
    prog = analyze_tree(REPO_ROOT, config)
    assert len(prog.locks) >= 30
    assert len(prog.roots) >= 12
    assert any(kind == "handler" for kind in prog.roots.values())
    assert len(prog.order_edges) >= 5
    assert prog.findings.get("lock-order-cycle", {}) == {}


def test_dot_output(tmp_path):
    pkg = tmp_path / "druid_tpu"
    pkg.mkdir()
    (pkg / "m.py").write_text(ABBA)
    proc = subprocess.run(
        [sys.executable, "-m", "tools.druidlint", "--root", str(tmp_path),
         "--dot"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0
    out = proc.stdout
    assert out.startswith("digraph lock_order {")
    assert "A._lock" in out and "B._lock" in out
    assert "color=red" in out          # the ABBA pair is a cycle


def test_assume_edges_join_graph_and_cycle_check():
    """Config-declared edges (opaque callback contracts) enter the order
    graph: they render dashed in DOT and close cycles with discovered
    edges — so view code acquiring the driver lock would fail the gate."""
    src = """\
import threading

class Driver:
    def __init__(self, view: "View"):
        self._lock = threading.Lock()
        self.view = view

class View:
    def __init__(self):
        self._lock = threading.Lock()

    def attach(self, driver: Driver):
        self.driver = driver

    def poke(self):
        with self._lock:
            with self.driver._lock:
                pass
"""
    c = cfg("lock-order-cycle")
    c.raceguard_assume_edges = [
        "druid_tpu/m.py::Driver._lock -> druid_tpu/m.py::View._lock"]
    prog = analyze_sources({"druid_tpu/m.py": src}, c)
    assert ("druid_tpu/m.py::Driver._lock",
            "druid_tpu/m.py::View._lock") in prog.order_edges
    assert "style=dashed" in render_dot(prog)
    # the discovered View→Driver edge + the assumed Driver→View edge cycle
    got = [f for f in check_source(src, "druid_tpu/m.py", c)
           if f.rule == "lock-order-cycle"]
    assert len(got) == 1 and "cycle" in got[0].message


def test_assume_edges_invalidate_program_memo(tmp_path):
    """REGRESSION (review): analyze_tree memoizes per root — a config with
    different assume-edges must NOT be served the cached order graph."""
    pkg = tmp_path / "druid_tpu"
    pkg.mkdir()
    (pkg / "m.py").write_text("import threading\n"
                              "class C:\n"
                              "    def __init__(self):\n"
                              "        self._lock = threading.Lock()\n")
    c1 = load_config(tmp_path)
    p1 = analyze_tree(tmp_path, c1)
    assert p1.order_edges == {}
    c2 = load_config(tmp_path)
    c2.raceguard_assume_edges = ["a::X._lock -> b::Y._lock"]
    p2 = analyze_tree(tmp_path, c2)
    assert ("a::X._lock", "b::Y._lock") in p2.order_edges


def test_render_dot_empty_program():
    prog = analyze_sources({}, cfg())
    dot = render_dot(prog)
    assert dot.startswith("digraph lock_order {") and dot.endswith("}\n")
