"""Batched multi-segment execution (engine/batching.py): parity with the
per-segment path, shape-bucket formation, compile-count bounds, stragglers.

The parity assertions are EXACT (`==` on finished result rows, floats
included): the batched program runs the same traced body over the same
staged columns and post-processes with the same host_post, so results must
be bit-identical, not merely close."""
import collections

import numpy as np
import pytest

from druid_tpu.data.generator import ColumnSpec, DataGenerator
from druid_tpu.data.segment import SegmentBuilder, ValueType
from druid_tpu.engine import batching
from druid_tpu.engine.executor import QueryExecutor
from druid_tpu.utils.intervals import Interval

IV = Interval.of("2026-03-01", "2026-03-03")

SCHEMA = (
    ColumnSpec("dimA", "string", cardinality=8, distribution="uniform"),
    ColumnSpec("dimB", "string", cardinality=40, distribution="zipf"),
    ColumnSpec("metLong", "long", low=0, high=1000),
    ColumnSpec("metFloat", "float", distribution="normal", mean=5.0, std=2.0),
    ColumnSpec("metDouble", "double", low=0.0, high=1.0),
)


@pytest.fixture(autouse=True)
def _batching_on(monkeypatch):
    monkeypatch.setattr(batching, "_ENABLED", True)


@pytest.fixture(scope="module")
def mixed_segments():
    """Same schema, mixed sizes -> two ladder rungs (3000->4096, 9000->16384)."""
    gen = DataGenerator(SCHEMA, seed=7)
    return gen.segments(4, 3000, IV, datasource="mix") \
        + gen.segments(4, 9000, IV, datasource="mix")


def run_both(segments, query_json):
    ex = QueryExecutor(segments)
    prev = batching.set_enabled(False)
    try:
        plain = ex.run_json(query_json)
        batching.set_enabled(True)
        before = batching.stats().snapshot()
        batched = ex.run_json(query_json)
        after = batching.stats().snapshot()
    finally:
        batching.set_enabled(prev)
    return plain, batched, after["batches"] - before["batches"]


AGGS = [{"type": "count", "name": "n"},
        {"type": "longSum", "name": "ls", "fieldName": "metLong"},
        {"type": "doubleSum", "name": "ds", "fieldName": "metDouble"},
        {"type": "floatMax", "name": "fx", "fieldName": "metFloat"},
        {"type": "longMin", "name": "lm", "fieldName": "metLong"}]


def test_timeseries_parity_mixed_sizes(mixed_segments):
    q = {"queryType": "timeseries", "dataSource": "mix",
         "intervals": [str(IV)], "granularity": "hour", "aggregations": AGGS}
    plain, batched, n_batches = run_both(mixed_segments, q)
    assert n_batches >= 2          # one dispatch per rung at least
    assert plain == batched


def test_topn_parity(mixed_segments):
    q = {"queryType": "topN", "dataSource": "mix", "intervals": [str(IV)],
         "granularity": "all", "dimension": "dimB", "metric": "ls",
         "threshold": 9, "aggregations": AGGS}
    plain, batched, n_batches = run_both(mixed_segments, q)
    assert n_batches >= 2
    assert plain == batched


def test_groupby_parity_with_filter_and_virtual_column(mixed_segments):
    q = {"queryType": "groupBy", "dataSource": "mix", "intervals": [str(IV)],
         "granularity": "day",
         "virtualColumns": [
             {"type": "expression", "name": "v",
              "expression": "metLong * 2 + 1", "outputType": "long"},
             {"type": "expression", "name": "w",
              "expression": "if(dimA == 'v00000000', 10.0, 1.0)",
              "outputType": "double"}],
         "dimensions": ["dimA"],
         "filter": {"type": "bound", "dimension": "metLong", "lower": 10,
                    "upper": 900, "ordering": "numeric"},
         "aggregations": [{"type": "longSum", "name": "vs", "fieldName": "v"},
                          {"type": "doubleSum", "name": "ws", "fieldName": "w"},
                          {"type": "longFirst", "name": "lf",
                           "fieldName": "metLong"}]}
    plain, batched, n_batches = run_both(mixed_segments, q)
    assert n_batches >= 1
    assert plain == batched


def _long_segment(name_part, lo, hi, n=1500, partition=0):
    """Segment whose long column spans [lo, hi) — values past 2**31 stage
    int64, small ones narrow to int32 (staged_dtype)."""
    rng = np.random.default_rng(100 + partition)
    b = SegmentBuilder("longs", IV, version="v1", partition=partition)
    t = np.sort(rng.integers(IV.start, IV.end, n))
    b.add_columns(
        t,
        {"dimA": [f"a{int(x)}" for x in rng.integers(0, 5, n)]},
        {"big": rng.integers(lo, hi, n, dtype=np.int64)},
        metric_types={"big": ValueType.LONG})
    return b.build()


def test_int64_staged_long_parity():
    """Mixed staged dtypes: two int32-staged + two int64-staged segments
    form two shape buckets, both batch, and 64-bit sums stay exact."""
    segs = [_long_segment("small", 0, 1000, partition=i) for i in (0, 1)] \
        + [_long_segment("big", 2**40, 2**40 + 10**6, partition=i)
           for i in (2, 3)]
    assert segs[0].staged_dtype("big") == np.int32
    assert segs[2].staged_dtype("big") == np.int64
    q = {"queryType": "groupBy", "dataSource": "longs",
         "intervals": [str(IV)], "granularity": "all",
         "dimensions": ["dimA"],
         "aggregations": [{"type": "longSum", "name": "s",
                           "fieldName": "big"},
                          {"type": "longMax", "name": "m",
                           "fieldName": "big"}]}
    plain, batched, n_batches = run_both(segs, q)
    assert n_batches == 2          # one dispatch per staged-dtype bucket
    assert plain == batched
    total = sum(r["event"]["s"] for r in batched)
    expect = sum(int(s.metrics["big"].values.sum()) for s in segs)
    assert total == expect         # exactness across the int64 bucket


def test_straggler_falls_back_and_merges(mixed_segments):
    """A schema-divergent segment (extra column set) runs per-segment while
    the rest batch; the merged result equals the all-per-segment run."""
    rng = np.random.default_rng(9)
    b = SegmentBuilder("mix", IV, version="odd", partition=99)
    n = 500
    t = np.sort(rng.integers(IV.start, IV.end, n))
    b.add_columns(t, {"dimA": [f"dimA_{int(x)}" for x in rng.integers(0, 3, n)]},
                  {"metLong": rng.integers(0, 1000, n, dtype=np.int64)},
                  metric_types={"metLong": ValueType.LONG})
    odd = b.build()
    segs = list(mixed_segments) + [odd]
    before = batching.stats().snapshot()
    q = {"queryType": "groupBy", "dataSource": "mix", "intervals": [str(IV)],
         "granularity": "all", "dimensions": ["dimA"],
         "aggregations": [{"type": "longSum", "name": "ls",
                           "fieldName": "metLong"}]}
    plain, batched, n_batches = run_both(segs, q)
    after = batching.stats().snapshot()
    assert n_batches >= 1
    assert after["fallbackSegments"] > before["fallbackSegments"]
    assert plain == batched


def test_context_disables_batching(mixed_segments):
    q = {"queryType": "timeseries", "dataSource": "mix",
         "intervals": [str(IV)], "granularity": "all",
         "context": {"batchSegments": False},
         "aggregations": [{"type": "count", "name": "n"}]}
    before = batching.stats().snapshot()
    QueryExecutor(mixed_segments).run_json(q)
    after = batching.stats().snapshot()
    assert after["batches"] == before["batches"]


def test_repeated_batched_query_builds_once(mixed_segments, monkeypatch):
    """The batched program cache follows the _JIT_CACHE discipline: one
    build per (structure, K, R), repeats served from cache."""
    monkeypatch.setattr(batching, "_JIT_CACHE", collections.OrderedDict())
    calls = []
    real = batching._build_batched_fn

    def counted(*a, **kw):
        calls.append(1)
        return real(*a, **kw)

    monkeypatch.setattr(batching, "_build_batched_fn", counted)
    ex = QueryExecutor(mixed_segments)
    # run-domain pinned off: a pure count is code-domain eligible at ANY
    # aligned granularity since the uniform rung (data/cascade.py) and
    # deliberately bypasses batching — this test is about the batched
    # program cache
    from druid_tpu.data import cascade
    monkeypatch.setattr(cascade, "_RUN_DOMAIN", False)
    q = {"queryType": "timeseries", "dataSource": "mix",
         "intervals": [str(IV)], "granularity": "hour",
         "aggregations": [{"type": "count", "name": "n"}]}
    first = ex.run_json(q)
    built = len(calls)
    assert built >= 1
    for _ in range(3):
        assert ex.run_json(q) == first
    assert len(calls) == built, "repeat queries rebuilt the batched program"


def test_row_rung_ladder():
    assert batching.row_rung(0) == 1024
    assert batching.row_rung(1) == 1024
    assert batching.row_rung(1024) == 1024
    assert batching.row_rung(1025) == 2048
    assert batching.row_rung(3000) == 4096
    assert batching.row_rung(9000) == 16384
    for n in (1, 999, 4097, 100_000):
        assert batching.row_rung(n) >= n


def test_pow2_chunks():
    mk = lambda n: list(range(n))
    chunks, rem = batching._pow2_chunks(mk(13))
    assert [len(c) for c in chunks] == [8, 4] and len(rem) == 1
    chunks, rem = batching._pow2_chunks(mk(6))
    assert [len(c) for c in chunks] == [4, 2] and rem == []
    chunks, rem = batching._pow2_chunks(mk(1))
    assert chunks == [] and len(rem) == 1
    chunks, rem = batching._pow2_chunks(mk(130))
    assert [len(c) for c in chunks] == [64, 64, 2] and rem == []


def test_fill_ratio_recorded(mixed_segments):
    batching.stats().drain_events()
    # run-domain pinned off: pure counts run code-domain at any aligned
    # granularity since the uniform rung (data/cascade.py) instead of
    # batching — this test asserts the batched dispatch event stream
    from druid_tpu.data import cascade
    prev_rd = cascade.set_run_domain_enabled(False)
    q = {"queryType": "timeseries", "dataSource": "mix",
         "intervals": [str(IV)], "granularity": "hour",
         "aggregations": [{"type": "count", "name": "n"}]}
    try:
        QueryExecutor(mixed_segments).run_json(q)
    finally:
        cascade.set_run_domain_enabled(prev_rd)
    events, dropped = batching.stats().drain_events()
    assert events, "batched dispatches must record (segments, fillRatio)"
    assert dropped == 0
    for n_segments, fill in events:
        assert n_segments >= 2
        assert 0.0 < fill <= 1.0


def test_event_overflow_is_counted():
    stats = batching.BatchStats()
    for _ in range(stats.EVENT_CAP + 5):
        stats.record_batch(2, 100, 200)
    events, dropped = stats.drain_events()
    assert len(events) == stats.EVENT_CAP
    assert dropped == 5
    _, dropped2 = stats.drain_events()
    assert dropped2 == 0


def test_large_group_space_falls_back():
    """Group spaces past BLOCKED_GROUP_LIMIT keep the per-segment path:
    strategy selection there consults per-segment row clustering, which
    could reorder float accumulation between chunk-mates and break the
    bit-parity contract (they are also scatter-compute-bound, where
    dispatch amortization is noise)."""
    gen = DataGenerator(
        (ColumnSpec("hi", "string", cardinality=3000),
         ColumnSpec("metLong", "long", low=0, high=100)), seed=13)
    segs = gen.segments(4, 2000, IV, datasource="big")
    q = {"queryType": "groupBy", "dataSource": "big", "intervals": [str(IV)],
         "granularity": "all", "dimensions": ["hi"],
         "aggregations": [{"type": "longSum", "name": "s",
                           "fieldName": "metLong"}]}
    plain, batched, n_batches = run_both(segs, q)
    assert n_batches == 0
    assert plain == batched


# ---------------------------------------------------------------------------
# plan reuse (PR 5): one host-side planning pass per segment, stragglers
# included
# ---------------------------------------------------------------------------

def _counting_planner(monkeypatch):
    from druid_tpu.engine import grouping
    calls = collections.Counter()
    real = grouping.plan_grouped_aggregate

    def counted(segment, *a, **kw):
        calls[id(segment)] += 1
        return real(segment, *a, **kw)

    monkeypatch.setattr(grouping, "plan_grouped_aggregate", counted)
    # batching binds the name at import time — patch its reference too
    monkeypatch.setattr(batching, "plan_grouped_aggregate", counted)
    return calls


def test_stragglers_are_planned_once(monkeypatch):
    """A mixed set (one bucket of 4 + an incompatible straggler): every
    segment is planned EXACTLY once — the straggler's fallback execution
    reuses the plan built for bucket grouping instead of re-planning."""
    gen = DataGenerator(SCHEMA, seed=11)
    segs = gen.segments(4, 3000, IV, datasource="mix")
    # straggler: a long column beyond int32 stages int64 -> its own bucket
    b = SegmentBuilder("mix", IV)
    for i in range(256):
        b.add_row(IV.start + i * 1000, {"dimA": f"v{i % 3}"},
                  {"metLong": 2**40 + i})
    segs.append(b.build())
    calls = _counting_planner(monkeypatch)
    q = {"queryType": "timeseries", "dataSource": "mix",
         "intervals": [str(IV)], "granularity": "all",
         "aggregations": [{"type": "longSum", "name": "ls",
                           "fieldName": "metLong"}]}
    ex = QueryExecutor(segs)
    before = batching.stats().snapshot()
    ex.run_json(q)
    after = batching.stats().snapshot()
    assert after["batches"] > before["batches"], "the bucket must dispatch"
    assert after["fallbackSegments"] > before["fallbackSegments"]
    assert set(calls.values()) == {1}, (
        f"every segment plans exactly once, got {dict(calls)}")
    assert len(calls) == len(segs)


def test_nothing_batches_still_plans_once(monkeypatch):
    """When no bucket reaches BATCH_MIN_SEGMENTS, run_with_batching now
    executes the per-segment path ITSELF with the plans it already built —
    again exactly one planning pass per segment."""
    gen = DataGenerator(SCHEMA, seed=13)
    segs = []
    for i, rows in enumerate((1000, 3000, 9000, 17000)):
        segs += DataGenerator(SCHEMA, seed=20 + i).segments(
            1, rows, IV, datasource="mix")
    calls = _counting_planner(monkeypatch)
    q = {"queryType": "timeseries", "dataSource": "mix",
         "intervals": [str(IV)], "granularity": "all",
         "aggregations": [{"type": "doubleSum", "name": "ds",
                           "fieldName": "metDouble"}]}
    plain, batched, n_batches = run_both(segs, q)
    assert plain == batched
    assert n_batches == 0          # four distinct rungs: no bucket forms
    # run_both executes twice (batching off + on); each execution plans
    # each segment once
    assert set(calls.values()) == {2}, dict(calls)


def test_straggler_parity_with_plan_reuse():
    """Plan-carrying fallback is bit-identical to the plain path."""
    gen = DataGenerator(SCHEMA, seed=17)
    segs = gen.segments(5, 3000, IV, datasource="mix")
    b = SegmentBuilder("mix", IV)
    for i in range(300):
        b.add_row(IV.start + i * 777, {"dimA": f"v{i % 5}"},
                  {"metLong": 2**41 + 7 * i})
    segs.append(b.build())
    q = {"queryType": "groupBy", "dataSource": "mix",
         "intervals": [str(IV)], "granularity": "day",
         "dimensions": ["dimA"], "aggregations": AGGS}
    plain, batched, n_batches = run_both(segs, q)
    assert n_batches >= 1
    assert plain == batched


# ---------------------------------------------------------------------------
# batched segment-cache miss path (cluster/view.py run_partials)
# ---------------------------------------------------------------------------

def _cached_node(segs):
    from druid_tpu.cluster.cache import CacheConfig, LruCache
    from druid_tpu.cluster.view import DataNode
    node = DataNode("n1", cache=LruCache(),
                    cache_config=CacheConfig(use_segment_cache=True,
                                             populate_segment_cache=True))
    for s in segs:
        node.load_segment(s)
    return node


def _finish(query_json, ap):
    from druid_tpu.engine import engines
    from druid_tpu.query.model import query_from_json
    q = query_from_json(query_json)
    return engines.finish_timeseries(q, ap)


def test_cache_miss_set_runs_one_batched_wave():
    """The segment-cache miss path computes the whole miss set through
    make_partials_by_segment: shape-compatible misses fuse into batched
    dispatches, the split-back entries serve later queries as hits, and
    results are bit-identical to the uncached node."""
    from druid_tpu.query.model import query_from_json
    gen = DataGenerator(SCHEMA, seed=23)
    segs = gen.segments(6, 3000, IV, datasource="mix")
    q = {"queryType": "timeseries", "dataSource": "mix",
         "intervals": [str(IV)], "granularity": "hour",
         "aggregations": AGGS}
    node = _cached_node(segs)
    sids = [str(s.id) for s in segs]

    before = batching.stats().snapshot()
    ap_cold, served = node.run_partials(query_from_json(q), sids)
    after = batching.stats().snapshot()
    assert len(served) == 6
    assert after["batches"] > before["batches"], (
        "cold misses must go through the batched wave")
    assert node.cache.stats.misses >= 6     # six cache probes missed

    hits_before = node.cache.stats.hits
    ap_warm, _ = node.run_partials(query_from_json(q), sids)
    assert node.cache.stats.hits >= hits_before + 6

    from druid_tpu.cluster.view import DataNode
    plain_node = DataNode("plain")
    for s in segs:
        plain_node.load_segment(s)
    ap_plain, _ = plain_node.run_partials(query_from_json(q), sids)
    assert _finish(q, ap_cold) == _finish(q, ap_warm) == _finish(q, ap_plain)


def test_cache_partial_miss_mixes_hits_and_batched_misses():
    """Second query over a superset: cached segments hit, the new ones run
    through one wave; merged results stay exact."""
    from druid_tpu.query.model import query_from_json
    gen = DataGenerator(SCHEMA, seed=29)
    segs = gen.segments(8, 3000, IV, datasource="mix")
    q = {"queryType": "timeseries", "dataSource": "mix",
         "intervals": [str(IV)], "granularity": "all",
         "aggregations": [{"type": "longSum", "name": "ls",
                           "fieldName": "metLong"},
                          {"type": "doubleSum", "name": "ds",
                           "fieldName": "metDouble"}]}
    node = _cached_node(segs)
    first_four = [str(s.id) for s in segs[:4]]
    node.run_partials(query_from_json(q), first_four)
    misses_before = node.cache.stats.misses
    hits_before = node.cache.stats.hits
    ap_all, _ = node.run_partials(query_from_json(q),
                                  [str(s.id) for s in segs])
    assert node.cache.stats.hits == hits_before + 4
    assert node.cache.stats.misses == misses_before + 4

    from druid_tpu.cluster.view import DataNode
    plain_node = DataNode("plain")
    for s in segs:
        plain_node.load_segment(s)
    ap_plain, _ = plain_node.run_partials(query_from_json(q),
                                          [str(s.id) for s in segs])
    assert _finish(q, ap_all) == _finish(q, ap_plain)


def test_partials_by_segment_survives_sharded_fusion(monkeypatch):
    """REGRESSION (review): when the mesh path fuses the set into ONE
    merged partial, make_partials_by_segment must fall back to per-segment
    computation instead of mis-splitting (cache poisoning) or crashing."""
    from druid_tpu.engine import engines
    from druid_tpu.parallel import distributed
    from druid_tpu.query.model import query_from_json
    gen = DataGenerator(SCHEMA, seed=31)
    segs = gen.segments(3, 2000, IV, datasource="mix")
    q = query_from_json({"queryType": "timeseries", "dataSource": "mix",
                         "intervals": [str(IV)], "granularity": "all",
                         "aggregations": [{"type": "longSum", "name": "ls",
                                           "fieldName": "metLong"}]})
    expected = [engines.make_aggregate_partials(q, [s], clamp=False)
                for s in segs]

    real = distributed.try_sharded
    state = {"fused": 0}

    def fusing(segs_in, *a, **kw):
        # simulate the mesh fusing a MULTI-segment set into one partial
        if len(segs_in) > 1 and not state.get("busy"):
            state["fused"] += 1
            state["busy"] = True      # the inner run must not re-fuse
            try:
                ap = engines.make_aggregate_partials(q, list(segs_in),
                                                     clamp=False)
            finally:
                state["busy"] = False
            return ap.partials[0]
        return real(segs_in, *a, **kw)

    monkeypatch.setattr(distributed, "try_sharded", fusing)
    got = engines.make_partials_by_segment(q, segs, clamp=False)
    assert state["fused"] >= 1, "the fused path was not exercised"
    assert len(got) == len(segs)
    for g, e in zip(got, expected):
        assert len(g.partials) == 1
        assert _finish(q.to_json(), g) == _finish(q.to_json(), e)
