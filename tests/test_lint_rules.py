"""Per-rule druidlint unit tests: positive + negative synthetic snippets
for each rule, suppression-comment behavior, config parsing, and baseline
round-trip semantics."""
import json
import sys
import textwrap
from pathlib import Path

import pytest

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

from tools.druidlint import check_source  # noqa: E402
from tools.druidlint.core import (Finding, LintConfig, load_baseline,  # noqa: E402
                                  load_config, save_baseline,
                                  split_by_baseline, _read_druidlint_table)


def rules_hit(source, path="druid_tpu/x.py", config=None):
    return {f.rule for f in check_source(textwrap.dedent(source),
                                         path, config)}


# ---- unfenced-metadata-write ---------------------------------------------

DUTY = "druid_tpu/cluster/coordinator.py"


def test_unfenced_write_flagged():
    src = """
    def cycle(self):
        self.metadata.mark_unused(ids)
    """
    assert "unfenced-metadata-write" in rules_hit(src, DUTY)


def test_fenced_write_ok():
    src = """
    def cycle(self):
        self.metadata.mark_unused(ids, fence=self._fence())
    """
    assert "unfenced-metadata-write" not in rules_hit(src, DUTY)


def test_unfenced_write_outside_duty_module_ok():
    src = """
    def cycle(self):
        self.metadata.mark_unused(ids)
    """
    assert "unfenced-metadata-write" not in rules_hit(
        src, "druid_tpu/ingest/streaming.py")


@pytest.mark.parametrize("mutator", ["publish_segments", "delete_segments",
                                     "insert_task", "update_task_status",
                                     "mark_used"])
def test_every_fenced_mutator_is_checked(mutator):
    src = f"""
    def cycle(self):
        self.metadata.{mutator}(x)
    """
    assert "unfenced-metadata-write" in rules_hit(src, DUTY)


# ---- jit-in-hot-path ------------------------------------------------------

ENGINE = "druid_tpu/engine/foo.py"


def test_jit_per_call_flagged():
    src = """
    import jax
    def per_segment(arrays):
        return jax.jit(lambda x: x + 1)(arrays)
    """
    assert "jit-in-hot-path" in rules_hit(src, ENGINE)


def test_shard_map_per_call_flagged():
    src = """
    from jax.experimental.shard_map import shard_map
    def per_query(body, mesh):
        return shard_map(body, mesh=mesh)
    """
    assert "jit-in-hot-path" in rules_hit(src, ENGINE)


def test_jit_at_module_level_ok():
    src = """
    import jax
    compiled = jax.jit(lambda x: x + 1)
    """
    assert "jit-in-hot-path" not in rules_hit(src, ENGINE)


def test_jit_behind_module_cache_ok():
    """The grouping.py/distributed.py idiom: builder + module-level cache."""
    src = """
    import jax
    _CACHE = {}
    def _build(sig):
        return jax.jit(lambda x: x + 1)
    def run(sig, arrays):
        fn = _CACHE.get(sig)
        if fn is None:
            fn = _build(sig)
            _CACHE[sig] = fn
        return fn(arrays)
    """
    assert "jit-in-hot-path" not in rules_hit(src, ENGINE)


def test_jit_behind_lru_cache_ok():
    src = """
    import functools
    import jax
    @functools.lru_cache(maxsize=64)
    def _build(sig):
        return jax.jit(lambda x: x + 1)
    def run(sig, arrays):
        return _build(sig)(arrays)
    """
    assert "jit-in-hot-path" not in rules_hit(src, ENGINE)


def test_builder_with_unguarded_call_site_flagged():
    """One cached call site does not excuse an uncached one."""
    src = """
    import jax
    _CACHE = {}
    def _build(sig):
        return jax.jit(lambda x: x + 1)
    def cached(sig):
        _CACHE[sig] = _build(sig)
        return _CACHE[sig]
    def uncached(sig, arrays):
        return _build(sig)(arrays)
    """
    assert "jit-in-hot-path" in rules_hit(src, ENGINE)


# ---- host-device-sync -----------------------------------------------------

def test_item_in_traced_fn_flagged():
    src = """
    import jax
    def kernel(x):
        return x.sum().item()
    fn = jax.jit(kernel)
    """
    assert "host-device-sync" in rules_hit(src, ENGINE)


def test_np_asarray_in_traced_fn_flagged():
    src = """
    import jax
    import numpy as np
    def kernel(x):
        return np.asarray(x)
    fn = jax.jit(kernel)
    """
    assert "host-device-sync" in rules_hit(src, ENGINE)


def test_float_on_traced_value_flagged():
    src = """
    import jax
    def kernel(x):
        return float(x.sum())
    fn = jax.jit(kernel)
    """
    assert "host-device-sync" in rules_hit(src, ENGINE)


def test_traced_closure_is_transitively_checked():
    """A helper called from a traced body is itself traced."""
    src = """
    import jax
    def helper(x):
        return x.tolist()
    def kernel(x):
        return helper(x)
    fn = jax.jit(kernel)
    """
    assert "host-device-sync" in rules_hit(src, ENGINE)


def test_host_helper_ok():
    src = """
    import numpy as np
    def host_post(state):
        return np.asarray(state).item()
    """
    assert "host-device-sync" not in rules_hit(src, ENGINE)


def test_sync_outside_device_modules_ok():
    src = """
    import jax
    def kernel(x):
        return float(x.sum())
    fn = jax.jit(kernel)
    """
    assert "host-device-sync" not in rules_hit(
        src, "druid_tpu/cluster/broker.py")


# ---- no-executable-deserialization ---------------------------------------

WIRE = "druid_tpu/cluster/wire.py"


@pytest.mark.parametrize("src,needle", [
    ("import pickle\n", "import"),
    ("from pickle import loads\n", "import"),
    ("import marshal\n", "import"),
    ("def f(b):\n    return eval(b)\n", "eval"),
    ("def f(b):\n    exec(b)\n", "exec"),
    ("class C:\n    def __reduce__(self):\n        return (C, ())\n",
     "__reduce__"),
])
def test_executable_deserialization_flagged(src, needle):
    assert "no-executable-deserialization" in rules_hit(src, WIRE)


def test_server_modules_are_wire_facing():
    assert "no-executable-deserialization" in rules_hit(
        "import pickle\n", "druid_tpu/server/avatica.py")


def test_json_on_wire_ok():
    src = """
    import json
    def decode(b):
        return json.loads(b)
    """
    assert rules_hit(src, WIRE) == set()


def test_pickle_outside_wire_modules_ok():
    assert "no-executable-deserialization" not in rules_hit(
        "import pickle\n", "druid_tpu/storage/format.py")


# ---- wire-decoded-rows ----------------------------------------------------

@pytest.mark.parametrize("src", [
    "import numpy as np\ndef enc(col):\n    return np.asarray(col.values)\n",
    "import numpy as np\ndef enc(col):\n    return np.asarray(col.ids)\n",
    "def enc(col):\n    return col.values.tolist()\n",
    "def enc(self, name):\n    return self.metrics[name].values.tolist()\n",
])
def test_wire_decoded_rows_flagged(src):
    assert "wire-decoded-rows" in rules_hit(src, WIRE)


def test_wire_decoded_rows_in_format_v2():
    assert "wire-decoded-rows" in rules_hit(
        "import numpy as np\ndef f(col):\n    return np.asarray(col.ids)\n",
        "druid_tpu/storage/format_v2.py")


def test_wire_decoded_rows_benign_asarray_ok():
    src = """
    import numpy as np
    def enc(spec):
        return np.asarray(spec.bucket_starts)
    """
    assert "wire-decoded-rows" not in rules_hit(src, WIRE)


def test_wire_decoded_rows_outside_wire_modules_ok():
    assert "wire-decoded-rows" not in rules_hit(
        "import numpy as np\ndef f(col):\n    return np.asarray(col.values)\n",
        "druid_tpu/storage/format.py")


def test_wire_decoded_rows_suppressible():
    src = ("import numpy as np\n"
           "def compat(col):\n"
           "    return np.asarray(col.values)"
           "  # druidlint: disable=wire-decoded-rows\n")
    assert "wire-decoded-rows" not in rules_hit(src, WIRE)


# ---- swallowed-exception --------------------------------------------------

def test_silent_pass_flagged():
    src = """
    def f():
        try:
            g()
        except Exception:
            pass
    """
    assert "swallowed-exception" in rules_hit(src)


def test_bare_except_flagged():
    src = """
    def f():
        try:
            g()
        except:
            return None
    """
    assert "swallowed-exception" in rules_hit(src)


def test_logged_handler_ok():
    src = """
    import logging
    def f():
        try:
            g()
        except Exception:
            logging.getLogger(__name__).warning("ctx", exc_info=True)
    """
    assert "swallowed-exception" not in rules_hit(src)


def test_reraise_ok():
    src = """
    def f():
        try:
            g()
        except BaseException:
            cleanup()
            raise
    """
    assert "swallowed-exception" not in rules_hit(src)


def test_recorded_exception_ok():
    """Capturing `as e` and recording it observes the failure."""
    src = """
    def f(failures):
        try:
            g()
        except Exception as e:
            failures.append(str(e))
    """
    assert "swallowed-exception" not in rules_hit(src)


def test_narrow_except_ok():
    src = """
    def f():
        try:
            g()
        except (ValueError, KeyError):
            pass
    """
    assert "swallowed-exception" not in rules_hit(src)


# ---- lock-scope -----------------------------------------------------------

def test_sleep_under_lock_flagged():
    src = """
    import time
    def f(self):
        with self._lock:
            time.sleep(0.1)
    """
    assert "lock-scope" in rules_hit(src)


def test_emit_under_lock_flagged():
    src = """
    def f(self):
        with self._lock:
            self.emitter.emit_metric("m", 1.0)
    """
    assert "lock-scope" in rules_hit(src)


def test_sql_under_lock_flagged():
    src = """
    def f(self):
        with self._lock:
            self._conn.execute("SELECT 1")
    """
    assert "lock-scope" in rules_hit(src)


def test_metadata_store_sql_exempt():
    """metadata.py's lock serializes its sqlite conn — by design."""
    src = """
    def f(self):
        with self._lock:
            self._conn.execute("SELECT 1")
    """
    assert "lock-scope" not in rules_hit(src, "druid_tpu/cluster/metadata.py")


def test_deferred_body_under_lock_ok():
    """A def/lambda created under the lock runs later, outside it."""
    src = """
    import time
    def f(self):
        with self._lock:
            def later():
                time.sleep(1)
            self.hooks.append(later)
    """
    assert "lock-scope" not in rules_hit(src)


def test_compute_under_lock_ok():
    src = """
    def f(self):
        with self._lock:
            self.counter += 1
            snapshot = dict(self.state)
        self.emitter.emit_metric("m", 1.0)
    """
    assert "lock-scope" not in rules_hit(src)


# ---- suppression ----------------------------------------------------------

# ---- unbounded-retry ------------------------------------------------------

RETRY_MOD = "druid_tpu/cluster/client.py"


def test_unbounded_while_retry_flagged():
    src = """
    def fetch(self):
        while True:
            try:
                return self._get()
            except ConnectionError:
                continue
    """
    assert "unbounded-retry" in rules_hit(src, RETRY_MOD)


def test_unbounded_fallthrough_retry_flagged():
    """Retry by falling through (no explicit continue) is still a retry."""
    src = """
    import time
    def fetch(self):
        while True:
            try:
                return self._get()
            except OSError:
                time.sleep(0.1)
    """
    assert "unbounded-retry" in rules_hit(src, RETRY_MOD)


def test_unbounded_for_over_call_retry_flagged():
    src = """
    def fetch(self, plan):
        for attempt in plan():
            try:
                return self._get()
            except TimeoutError:
                continue
    """
    assert "unbounded-retry" in rules_hit(src, RETRY_MOD)


def test_bounded_range_retry_ok():
    src = """
    def fetch(self):
        for _ in range(self.max_retries + 1):
            try:
                return self._get()
            except ConnectionError:
                continue
    """
    assert "unbounded-retry" not in rules_hit(src, RETRY_MOD)


def test_bounded_literal_tuple_retry_ok():
    """The client's `for attempt in (0, 1)` idiom."""
    src = """
    def fetch(self):
        for attempt in (0, 1):
            try:
                return self._get()
            except ConnectionError:
                if attempt:
                    raise
    """
    assert "unbounded-retry" not in rules_hit(src, RETRY_MOD)


def test_deadline_consult_bounds_while_retry():
    src = """
    def fetch(self, deadline):
        while True:
            deadline.check()
            try:
                return self._get()
            except ConnectionError:
                continue
    """
    assert "unbounded-retry" not in rules_hit(src, RETRY_MOD)


def test_condition_bounded_while_retry_ok():
    src = """
    def fetch(self):
        attempt = 0
        while attempt < self.max_retries:
            attempt += 1
            try:
                return self._get()
            except ConnectionError:
                continue
    """
    assert "unbounded-retry" not in rules_hit(src, RETRY_MOD)


def test_handler_that_always_raises_is_not_a_retry():
    src = """
    def fetch(self):
        while True:
            try:
                self._step()
            except ConnectionError:
                raise RuntimeError("fatal")
    """
    assert "unbounded-retry" not in rules_hit(src, RETRY_MOD)


def test_nested_bounded_loop_does_not_shield_outer():
    """The retrying handler belongs to the INNER loop it sits in — a
    bounded inner loop must not excuse an unbounded outer, and vice
    versa the outer must not claim the inner's handler."""
    src = """
    def fetch(self):
        while True:
            for _ in range(2):
                try:
                    self._step()
                except ConnectionError:
                    continue
    """
    assert "unbounded-retry" not in rules_hit(src, RETRY_MOD)


def test_broad_except_is_not_this_rules_business():
    src = """
    def sync_all(self):
        while True:
            try:
                self._sync()
            except Exception:
                self.log.exception("sync failed")
    """
    assert "unbounded-retry" not in rules_hit(src, RETRY_MOD)


def test_unbounded_retry_outside_retry_modules_ok():
    src = """
    def fetch(self):
        while True:
            try:
                return self._get()
            except ConnectionError:
                continue
    """
    assert "unbounded-retry" not in rules_hit(src, "druid_tpu/engine/x.py")


def test_unbounded_retry_capacity_and_tuple_types():
    src = """
    def fetch(self):
        while True:
            try:
                return self._get()
            except (QueryCapacityError, socket.timeout):
                continue
    """
    assert "unbounded-retry" in rules_hit(src, RETRY_MOD)


def test_unbounded_retry_suppression():
    src = """
    def fetch(self):
        while True:
            try:
                return self._get()
            except ConnectionError:  # druidlint: disable=unbounded-retry
                continue
    """
    assert "unbounded-retry" not in rules_hit(src, RETRY_MOD)


def test_inline_suppression_silences_named_rule():
    src = """
    def f():
        try:
            g()
        except Exception:  # druidlint: disable=swallowed-exception
            pass
    """
    assert "swallowed-exception" not in rules_hit(src)


def test_inline_suppression_is_rule_specific():
    src = """
    def f():
        try:
            g()
        except Exception:  # druidlint: disable=lock-scope
            pass
    """
    assert "swallowed-exception" in rules_hit(src)


def test_disable_all_silences_line():
    src = """
    import time
    def f(self):
        with self._lock:
            time.sleep(1)  # druidlint: disable=all
    """
    assert rules_hit(src) == set()


# ---- baseline round-trip --------------------------------------------------

def test_baseline_round_trip(tmp_path):
    findings = [
        Finding("swallowed-exception", "druid_tpu/a.py", 10, 5, "m1",
                "warning"),
        Finding("lock-scope", "druid_tpu/b.py", 20, 9, "m2", "warning"),
    ]
    path = tmp_path / "baseline.json"
    save_baseline(path, findings)
    loaded = load_baseline(path)
    assert set(loaded) == {f.key for f in findings}

    # same findings: nothing new, nothing stale
    new, old, stale = split_by_baseline(findings, loaded)
    assert (new, stale) == ([], []) and len(old) == 2

    # one fixed, one fresh: fixed shows stale, fresh shows new
    fresh = Finding("lock-scope", "druid_tpu/c.py", 3, 1, "m3", "warning")
    new, old, stale = split_by_baseline([findings[0], fresh], loaded)
    assert new == [fresh]
    assert stale == [findings[1].key]
    assert old == [findings[0]]


def test_empty_baseline_file_means_everything_is_new(tmp_path):
    path = tmp_path / "baseline.json"
    path.write_text(json.dumps({"version": 1, "findings": []}))
    f = Finding("lock-scope", "druid_tpu/a.py", 1, 1, "m", "warning")
    new, old, stale = split_by_baseline([f], load_baseline(path))
    assert new == [f] and old == [] and stale == []


# ---- config ---------------------------------------------------------------

def test_pyproject_table_parsing(tmp_path):
    (tmp_path / "pyproject.toml").write_text(textwrap.dedent("""
        [project]
        name = "x"

        [tool.druidlint]
        include = ["druid_tpu", "tools"]
        duty-modules = [
            "druid_tpu/cluster/coordinator.py",
            "druid_tpu/indexing/overlord.py",
        ]
        baseline = "tools/druidlint/baseline.json"

        [tool.other]
        ignored = true
    """))
    cfg = load_config(tmp_path)
    assert cfg.include == ["druid_tpu", "tools"]
    assert cfg.duty_modules[1] == "druid_tpu/indexing/overlord.py"
    assert cfg.baseline == "tools/druidlint/baseline.json"


def test_unknown_config_key_rejected(tmp_path):
    (tmp_path / "pyproject.toml").write_text(
        "[tool.druidlint]\nrulez = [\"swallowed-exception\"]\n")
    with pytest.raises(ValueError, match="unknown"):
        load_config(tmp_path)


def test_unknown_rule_name_rejected():
    cfg = LintConfig(rules=["no-such-rule"])
    with pytest.raises(ValueError, match="unknown rules"):
        check_source("x = 1\n", "druid_tpu/x.py", cfg)


def test_repo_config_loads_and_enables_all_rules():
    cfg = load_config(REPO_ROOT)
    assert len(cfg.enabled_rules()) >= 6
    table = _read_druidlint_table(REPO_ROOT / "pyproject.toml")
    assert "include" in table
