"""Data-driven SQL golden suite over a LITERAL six-row dataset — every case
is (sql, hand-computed expected rows), modeled on the reference's
CalciteQueryTest.java:139 table-driven (plan, results) assertions.

Dataset `foo` (one row per day from 2026-02-01):

    day  dim1  dim2      l1   f1    d1
     1    a     x         7   1.0   1.7
     2    b     y    325323   0.1   1.7
     3    a     x         0   0.0   0.0
     4    c     y         3   2.5   3.3
     5    b     x         9   2.0   0.2
     6    c     z        10   5.5   6.6
"""
import numpy as np
import pytest

from druid_tpu.data.segment import SegmentBuilder, ValueType
from druid_tpu.engine import QueryExecutor
from druid_tpu.sql import SqlExecutor
from druid_tpu.utils.intervals import Interval, parse_ts

T0 = parse_ts("2026-02-01")
DAY = 86_400_000
IV = Interval.of("2026-02-01", "2026-02-08")

ROWS = [
    ("a", "x", 7,      1.0, 1.7),
    ("b", "y", 325323, 0.1, 1.7),
    ("a", "x", 0,      0.0, 0.0),
    ("c", "y", 3,      2.5, 3.3),
    ("b", "x", 9,      2.0, 0.2),
    ("c", "z", 10,     5.5, 6.6),
]


@pytest.fixture(scope="module")
def sql():
    b = SegmentBuilder("foo", IV)
    b.add_columns(
        np.asarray([T0 + i * DAY for i in range(6)], dtype=np.int64),
        {"dim1": [r[0] for r in ROWS], "dim2": [r[1] for r in ROWS]},
        {"l1": np.asarray([r[2] for r in ROWS], dtype=np.int64),
         "f1": np.asarray([r[3] for r in ROWS], dtype=np.float32),
         "d1": np.asarray([r[4] for r in ROWS], dtype=np.float64)},
        metric_types={"l1": ValueType.LONG, "f1": ValueType.FLOAT,
                      "d1": ValueType.DOUBLE})
    return SqlExecutor(QueryExecutor([b.build()]))


def iso(day: int) -> str:
    return f"2026-02-{day:02d}T00:00:00.000Z"


# (name, sql, expected rows, ordered?) — expected uses pytest.approx
# semantics for floats; ordered=False compares as multisets.
CASES = [
    # -- plain aggregates over the whole table ---------------------------
    ("count_star", "SELECT COUNT(*) FROM foo", [[6]], True),
    ("sum_long", "SELECT SUM(l1) FROM foo", [[325352]], True),
    ("sum_float", "SELECT SUM(f1) FROM foo", [[11.1]], True),
    ("sum_double", "SELECT SUM(d1) FROM foo", [[13.5]], True),
    ("min_max_long", "SELECT MIN(l1), MAX(l1) FROM foo",
     [[0, 325323]], True),
    ("min_max_float", "SELECT MIN(f1), MAX(f1) FROM foo",
     [[0.0, 5.5]], True),
    ("avg_long", "SELECT AVG(l1) FROM foo", [[325352 / 6]], True),
    ("avg_float", "SELECT AVG(f1) FROM foo", [[1.85]], True),
    ("count_column", "SELECT COUNT(dim1) FROM foo", [[6]], True),
    ("multiple_aggs",
     "SELECT COUNT(*), SUM(l1), MAX(f1), MIN(d1) FROM foo",
     [[6, 325352, 5.5, 0.0]], True),
    # -- WHERE -----------------------------------------------------------
    ("where_selector", "SELECT COUNT(*) FROM foo WHERE dim2 = 'x'",
     [[3]], True),
    ("where_not_equal", "SELECT COUNT(*) FROM foo WHERE dim1 <> 'a'",
     [[4]], True),
    ("where_numeric_gt",
     "SELECT COUNT(*), SUM(l1) FROM foo WHERE l1 > 5", [[4, 325349]], True),
    ("where_float_ge", "SELECT COUNT(*) FROM foo WHERE f1 >= 2.0",
     [[3]], True),
    ("where_and", "SELECT COUNT(*) FROM foo WHERE dim2 = 'x' AND l1 > 5",
     [[2]], True),
    ("where_or", "SELECT COUNT(*) FROM foo WHERE dim1 = 'a' OR l1 = 10",
     [[3]], True),
    ("where_not", "SELECT COUNT(*) FROM foo WHERE NOT (dim2 = 'x')",
     [[3]], True),
    ("where_in", "SELECT COUNT(*) FROM foo WHERE dim1 IN ('a','c')",
     [[4]], True),
    ("where_not_in", "SELECT COUNT(*) FROM foo WHERE dim1 NOT IN ('a','c')",
     [[2]], True),
    ("where_like", "SELECT COUNT(*) FROM foo WHERE dim1 LIKE 'a%'",
     [[2]], True),
    ("where_between", "SELECT COUNT(*), SUM(l1) FROM foo "
     "WHERE l1 BETWEEN 3 AND 10", [[4, 29]], True),
    ("where_is_not_null", "SELECT COUNT(*) FROM foo "
     "WHERE dim1 IS NOT NULL", [[6]], True),
    ("where_abs_expr", "SELECT COUNT(*) FROM foo WHERE ABS(l1 - 5) <= 2",
     [[2]], True),
    ("where_time_ge", "SELECT COUNT(*) FROM foo WHERE __time >= "
     "TIMESTAMP '2026-02-04 00:00:00'", [[3]], True),
    ("where_time_between", "SELECT COUNT(*) FROM foo WHERE __time BETWEEN "
     "TIMESTAMP '2026-02-02 00:00:00' AND TIMESTAMP '2026-02-04 00:00:00'",
     [[3]], True),
    # -- GROUP BY --------------------------------------------------------
    ("group_by_dim", "SELECT dim1, COUNT(*), SUM(l1) FROM foo GROUP BY dim1",
     [["a", 2, 7], ["b", 2, 325332], ["c", 2, 13]], False),
    ("group_by_two_dims",
     "SELECT dim1, dim2, COUNT(*) FROM foo GROUP BY dim1, dim2",
     [["a", "x", 2], ["b", "y", 1], ["c", "y", 1], ["b", "x", 1],
      ["c", "z", 1]], False),
    ("group_by_ordinal", "SELECT dim2, SUM(l1) FROM foo GROUP BY 1",
     [["x", 16], ["y", 325326], ["z", 10]], False),
    ("distinct_dim", "SELECT DISTINCT dim1 FROM foo",
     [["a"], ["b"], ["c"]], False),
    ("group_by_filtered",
     "SELECT dim2, COUNT(*) FROM foo WHERE l1 > 0 GROUP BY dim2",
     [["x", 2], ["y", 2], ["z", 1]], False),
    ("having", "SELECT dim1, SUM(l1) s FROM foo GROUP BY dim1 "
     "HAVING SUM(l1) > 10", [["b", 325332], ["c", 13]], False),
    ("order_by_agg_desc", "SELECT dim1, SUM(l1) s FROM foo GROUP BY dim1 "
     "ORDER BY s DESC", [["b", 325332], ["c", 13], ["a", 7]], True),
    ("order_by_agg_limit", "SELECT dim1, SUM(l1) s FROM foo GROUP BY dim1 "
     "ORDER BY s DESC LIMIT 2", [["b", 325332], ["c", 13]], True),
    ("order_by_offset", "SELECT dim1, SUM(l1) s FROM foo GROUP BY dim1 "
     "ORDER BY s DESC LIMIT 2 OFFSET 1", [["c", 13], ["a", 7]], True),
    ("group_substring",
     "SELECT SUBSTRING(dim2, 1, 1) p, COUNT(*) FROM foo GROUP BY 1",
     [["x", 3], ["y", 2], ["z", 1]], False),
    # -- time bucketing --------------------------------------------------
    ("time_floor_day",
     "SELECT FLOOR(__time TO DAY) d, COUNT(*) FROM foo GROUP BY 1",
     [[iso(i + 1), 1] for i in range(6)], True),
    ("time_floor_week_filtered",
     "SELECT FLOOR(__time TO WEEK) w, SUM(l1) FROM foo "
     "WHERE dim2 = 'x' GROUP BY 1",
     [["2026-01-26T00:00:00.000Z", 7], ["2026-02-02T00:00:00.000Z", 9]],
     True),
    # -- aggregate expressions -------------------------------------------
    ("agg_of_expression", "SELECT SUM(l1 * 2) FROM foo", [[650704]], True),
    ("arith_over_aggs",
     "SELECT SUM(l1) + COUNT(*), (SUM(l1) - 52) / 100.0 FROM foo",
     [[325358, 3253.0]], True),
    ("case_when_sum",
     "SELECT SUM(CASE WHEN dim2 = 'x' THEN l1 ELSE 0 END) FROM foo",
     [[16]], True),
    ("filtered_agg",
     "SELECT COUNT(*) FILTER (WHERE dim2 = 'x'), SUM(l1) FILTER "
     "(WHERE dim1 = 'b') FROM foo", [[3, 325332]], True),
    ("coalesce_fn", "SELECT SUM(COALESCE(l1, 0)) FROM foo",
     [[325352]], True),
    # -- time/math expression functions ----------------------------------
    ("where_extract_day",
     "SELECT COUNT(*) FROM foo WHERE EXTRACT(DAY FROM __time) <= 3",
     [[3]], True),
    ("where_extract_dow",
     # 2026-02-01 is a Sunday (ISO DOW 7); days 2..6 are Mon..Fri
     "SELECT COUNT(*) FROM foo WHERE EXTRACT(DOW FROM __time) <= 5",
     [[5]], True),
    ("extract_month_year_agg",
     "SELECT SUM(CASE WHEN EXTRACT(MONTH FROM __time) = 2 AND "
     "EXTRACT(YEAR FROM __time) = 2026 THEN 1 ELSE 0 END) FROM foo",
     [[6]], True),
    ("where_time_floor_fn",
     "SELECT COUNT(*) FROM foo WHERE TIME_FLOOR(__time, 'P1D') = "
     "TIMESTAMP '2026-02-03 00:00:00'", [[1]], True),
    ("where_time_shift",
     "SELECT COUNT(*) FROM foo WHERE TIME_SHIFT(__time, 'P1D', 1) > "
     "TIMESTAMP '2026-02-05 00:00:00'", [[2]], True),
    ("mod_round_sign",
     "SELECT SUM(MOD(l1, 2)), SUM(SIGN(l1)), SUM(ROUND(f1)) FROM foo",
     [[4, 5, 12.0]], True),
    ("greatest_least",
     "SELECT SUM(GREATEST(l1, 5)), SUM(LEAST(l1, 5)) FROM foo",
     [[325359, 23]], True),
    ("safe_divide",
     "SELECT SUM(SAFE_DIVIDE(10.0, l1)) FROM foo",
     [[10.0 / 7 + 10.0 / 325323 + 0.0 + 10.0 / 3 + 10.0 / 9 + 1.0]], True),
    ("group_by_extract_dow",
     "SELECT EXTRACT(DOW FROM __time) dow, COUNT(*) FROM foo "
     "GROUP BY 1 ORDER BY 1",
     # Feb 1 2026 = Sunday(7); Feb 2..6 = Mon..Fri (1..5)
     [[1, 1], [2, 1], [3, 1], [4, 1], [5, 1], [7, 1]], True),
    ("group_by_mod_expr",
     "SELECT MOD(l1, 2) parity, COUNT(*), SUM(l1) FROM foo "
     "GROUP BY 1 ORDER BY 1",
     [[0, 2, 10], [1, 4, 325342]], True),
    ("group_by_case_expr",
     "SELECT CASE WHEN l1 > 5 THEN 'big' ELSE 'small' END sz, COUNT(*) "
     "FROM foo GROUP BY 1",
     [["big", 4], ["small", 2]], False),
    # -- approximate -----------------------------------------------------
    ("approx_count_distinct", "SELECT APPROX_COUNT_DISTINCT(dim1) FROM foo",
     [[3]], True),
    ("count_distinct", "SELECT COUNT(DISTINCT dim2) FROM foo", [[3]], True),
    # -- scan ------------------------------------------------------------
    ("scan_columns", "SELECT dim1, l1 FROM foo WHERE l1 > 8",
     [["b", 325323], ["b", 9], ["c", 10]], True),
    ("scan_limit", "SELECT dim1 FROM foo LIMIT 2", [["a"], ["b"]], True),
    ("scan_offset", "SELECT dim1 FROM foo LIMIT 2 OFFSET 4",
     [["b"], ["c"]], True),
    ("scan_time_column", "SELECT __time, dim1 FROM foo WHERE dim2 = 'z'",
     [[iso(6), "c"]], True),
    # -- time boundary ---------------------------------------------------
    ("min_max_time", "SELECT MIN(__time), MAX(__time) FROM foo",
     [[iso(1), iso(6)]], True),
    # -- parameters ------------------------------------------------------
    ("parameterized", "SELECT COUNT(*) FROM foo WHERE dim1 = ? AND l1 >= ?",
     [[1]], True, ["a", 5]),
]


IDS = [c[0] for c in CASES]


@pytest.mark.parametrize("case", CASES, ids=IDS)
def test_sql_golden(sql, case):
    name, stmt, expected, ordered = case[0], case[1], case[2], case[3]
    params = case[4] if len(case) > 4 else ()
    cols, rows = sql.execute(stmt, params)

    def norm(row):
        return tuple(round(v, 6) if isinstance(v, float) else v for v in row)

    got = [norm(r) for r in rows]
    want = [norm(r) for r in expected]
    if not ordered:
        got, want = sorted(got, key=repr), sorted(want, key=repr)
    assert len(got) == len(want), (name, got)
    for g, w in zip(got, want):
        assert len(g) == len(w), (name, g, w)
        for gv, wv in zip(g, w):
            if isinstance(wv, float):
                assert gv == pytest.approx(wv, rel=1e-5, abs=1e-6), \
                    (name, g, w)
            else:
                assert gv == wv, (name, g, w)


def test_approx_quantile_bounded(sql):
    # the moments sketch is genuinely approximate at 6 points: assert the
    # estimate stays inside the data range and is monotone in the rank
    cols, rows = sql.execute(
        "SELECT APPROX_QUANTILE(f1, 0.1), APPROX_QUANTILE(f1, 0.9) FROM foo")
    lo, hi = rows[0]
    assert 0.0 <= lo <= hi <= 5.5


def test_explain_returns_plan(sql):
    cols, rows = sql.execute("EXPLAIN PLAN FOR SELECT COUNT(*) FROM foo")
    assert cols == ["PLAN"] and "timeseries" in rows[0][0]


def test_information_schema_tables(sql):
    cols, rows = sql.execute(
        "SELECT TABLE_NAME FROM INFORMATION_SCHEMA.TABLES")
    assert ["foo"] in rows


# ---------------------------------------------------------------------------
# String-function extraction filters + dims (Expressions.toSimpleExtraction)
# ---------------------------------------------------------------------------

def test_string_fn_filters(sql):
    cases = [
        ("SELECT COUNT(*) FROM foo WHERE UPPER(dim1) = 'A'", 2),
        ("SELECT COUNT(*) FROM foo WHERE LOWER(dim2) = 'x'", 3),
        ("SELECT COUNT(*) FROM foo WHERE SUBSTRING(dim1, 1, 1) = 'b'", 2),
        ("SELECT COUNT(*) FROM foo WHERE CHAR_LENGTH(dim1) >= 1", 6),
        ("SELECT COUNT(*) FROM foo WHERE CHAR_LENGTH(dim1) > 1", 0),
        ("SELECT COUNT(*) FROM foo WHERE "
         "REGEXP_EXTRACT(dim1, '(a|c)', 1) = 'c'", 2),
        ("SELECT COUNT(*) FROM foo WHERE "
         "UPPER(SUBSTRING(dim1, 1, 1)) LIKE 'A%'", 2),
        ("SELECT COUNT(*) FROM foo WHERE LEFT(dim1, 1) = 'c'", 2),
        ("SELECT COUNT(*) FROM foo WHERE RIGHT(dim2, 1) = 'y'", 2),
        ("SELECT COUNT(*) FROM foo WHERE TRIM(dim1) = 'a'", 2),
        ("SELECT COUNT(*) FROM foo WHERE UPPER(dim1) <> 'A'", 4),
        ("SELECT COUNT(*) FROM foo WHERE UPPER(dim1) IN ('A', 'C')", 4),
    ]
    for q, want in cases:
        cols, rows = sql.execute(q)
        assert rows[0][0] == want, (q, rows, want)


def test_string_fn_group_by(sql):
    cols, rows = sql.execute(
        "SELECT UPPER(dim1) u, COUNT(*) n, SUM(l1) s FROM foo "
        "GROUP BY UPPER(dim1) ORDER BY u")
    assert rows == [["A", 2, 7], ["B", 2, 325332], ["C", 2, 13]]


def test_string_fn_wire_roundtrip(sql):
    """The planned extraction filter survives JSON serde (native wire)."""
    from druid_tpu.query.model import query_from_json
    plan = sql.explain("SELECT COUNT(*) FROM foo WHERE UPPER(dim1) = 'A'")
    assert plan["filter"]["extractionFn"]["type"] == "upper"
    q = query_from_json(plan)
    assert q.filter.extraction_fn is not None


def test_non_literal_extraction_args_rejected_cleanly(sql):
    """SUBSTRING with a non-literal length must not silently plan a
    substring-to-end extraction — it errors cleanly instead of returning
    wrong rows (the numeric expression language cannot host it either)."""
    from druid_tpu.sql import PlannerError
    with pytest.raises(PlannerError, match="not translatable"):
        sql.execute("SELECT COUNT(*) FROM foo WHERE "
                    "SUBSTRING(dim1, 1, CHAR_LENGTH(dim2)) = 'a'")


def test_extractionfn_on_unsupported_filter_type_rejected(sql):
    from druid_tpu.query.filters import filter_from_json
    import pytest as _pytest
    with _pytest.raises(ValueError, match="unsupported"):
        filter_from_json({"type": "columnComparison",
                          "dimensions": ["a", "b"],
                          "extractionFn": {"type": "upper"}})


def test_regex_search_filters_carry_extraction(sql):
    """regex/search filters consume extractionFn instead of dropping it."""
    from druid_tpu.query.filters import filter_from_json
    f = filter_from_json({"type": "regex", "dimension": "dim1",
                          "pattern": "^A", "extractionFn": {"type": "upper"}})
    assert f.extraction_fn is not None
    # end to end: ^A on UPPER(dim1) matches the two 'a' rows
    from druid_tpu.query.model import query_from_json
    native = {"queryType": "timeseries", "dataSource": "foo",
              "intervals": ["2026-02-01/2026-02-08"], "granularity": "all",
              "filter": {"type": "regex", "dimension": "dim1",
                         "pattern": "^A",
                         "extractionFn": {"type": "upper"}},
              "aggregations": [{"type": "count", "name": "n"}]}
    rows = sql.qe.run(query_from_json(native))
    assert rows[0]["result"]["n"] == 2


def test_extended_math_functions(sql):
    import math
    cases = [
        ("SELECT MAX(ROUND(DEGREES(PI()), 3)) FROM foo", 180.0),
        ("SELECT MAX(ROUND(RADIANS(180) / PI(), 3)) FROM foo", 1.0),
        ("SELECT MAX(ROUND(ATAN2(1, 1) * 4 / PI(), 3)) FROM foo", 1.0),
        ("SELECT MAX(ROUND(ASIN(1) * 2 / PI(), 3)) FROM foo", 1.0),
        ("SELECT MAX(ROUND(ACOS(0) * 2 / PI(), 3)) FROM foo", 1.0),
        ("SELECT MAX(ROUND(LOG10(l1 * 0 + 1000), 3)) FROM foo", 3.0),
        ("SELECT MAX(ROUND(COT(ATAN(l1 * 0 + 1)), 3)) FROM foo", 1.0),
        ("SELECT SUM(ROUND(ATAN(l1 - l1), 3)) FROM foo", 0.0),
    ]
    for q, want in cases:
        cols, rows = sql.execute(q)
        assert rows[0][0] == pytest.approx(want, abs=1e-3), (q, rows)


def test_varchar_cast_keeps_column_identity(sql):
    """CAST(col AS VARCHAR) compared to literals must filter on the
    column's values (the expression path would compare a number to a
    string and silently match nothing)."""
    cases = [
        ("SELECT COUNT(*) FROM foo WHERE CAST(l1 AS VARCHAR) = '7'", 1),
        ("SELECT COUNT(*) FROM foo WHERE CAST(l1 AS VARCHAR) IN "
         "('3', '9', '10')", 3),
        ("SELECT COUNT(*) FROM foo WHERE CAST(dim1 AS VARCHAR) LIKE 'a%'",
         2),
        ("SELECT COUNT(*) FROM foo WHERE CAST(dim1 AS VARCHAR) = 'b'", 2),
    ]
    for q, want in cases:
        cols, rows = sql.execute(q)
        assert rows[0][0] == want, (q, rows)


def test_timestampadd_timestampdiff(sql):
    cases = [
        ("SELECT MAX(TIMESTAMPDIFF(DAY, TIMESTAMP '2026-02-01', __time)) "
         "FROM foo", 5),
        ("SELECT COUNT(*) FROM foo WHERE "
         "TIMESTAMPDIFF(HOUR, TIMESTAMP '2026-02-01', __time) >= 48", 4),
        ("SELECT COUNT(*) FROM foo WHERE "
         "TIMESTAMPADD(DAY, 2, __time) > TIMESTAMP '2026-02-06'", 2),
        ("SELECT COUNT(*) FROM foo WHERE "
         "TIMESTAMPADD(DAY, 2, __time) >= TIMESTAMP '2026-02-06'", 3),
    ]
    for q, want in cases:
        cols, rows = sql.execute(q)
        assert rows[0][0] == want, (q, rows)
    # calendar units reject cleanly instead of approximating
    from druid_tpu.sql import PlannerError
    with pytest.raises(PlannerError, match="calendar-variable"):
        sql.execute("SELECT MAX(TIMESTAMPDIFF(MONTH, "
                    "TIMESTAMP '2026-01-01', __time)) FROM foo")


def test_varchar_cast_unwrap_is_semantics_safe(sql):
    """Unwrap happens only where string-compare equals column-compare:
    non-canonical numeric literals ('07', '7a') and ordering comparisons
    must NOT numeric-match."""
    cases = [
        # '07' != '7' as strings: no match even though int('07') == 7
        ("SELECT COUNT(*) FROM foo WHERE CAST(l1 AS VARCHAR) = '07'", 0),
        ("SELECT COUNT(*) FROM foo WHERE CAST(l1 AS VARCHAR) = '7a'", 0),
        ("SELECT COUNT(*) FROM foo WHERE CAST(l1 AS VARCHAR) IN "
         "('07', '3')", 1),
    ]
    for q, want in cases:
        cols, rows = sql.execute(q)
        assert rows[0][0] == want, (q, rows)
    # ordering on a varchar-cast numeric column is lexicographic in SQL;
    # neither numeric-matching ('10' would wrongly pass > '5') nor a deep
    # crash is acceptable — clean plan-time rejection
    from druid_tpu.sql import PlannerError
    with pytest.raises(PlannerError, match="lexicographic ordering"):
        sql.execute(
            "SELECT COUNT(*) FROM foo WHERE CAST(l1 AS VARCHAR) > '5'")


def test_varchar_cast_canonicality_is_type_aware(sql):
    """The literal must round-trip the COLUMN TYPE's stringification:
    CAST(double AS VARCHAR) yields '0.0' never '0', CAST(long AS VARCHAR)
    yields '7' never '7.0' — cross-type canonical literals are statically
    false (zero rows), not numeric matches and not engine crashes
    (int('7.0') used to 500)."""
    cases = [
        # double column: d1 has a 0.0 row — '0' must NOT match it
        ("SELECT COUNT(*) FROM foo WHERE CAST(d1 AS VARCHAR) = '0'", 0),
        ("SELECT COUNT(*) FROM foo WHERE CAST(d1 AS VARCHAR) = '0.0'", 1),
        ("SELECT COUNT(*) FROM foo WHERE CAST(d1 AS VARCHAR) = '1.7'", 2),
        # long column: float-canonical literals can never match (and must
        # not crash the engine with int('7.0') → ValueError → 500)
        ("SELECT COUNT(*) FROM foo WHERE CAST(l1 AS VARCHAR) = '7.0'", 0),
        ("SELECT COUNT(*) FROM foo WHERE CAST(l1 AS VARCHAR) <> '7.0'", 6),
        ("SELECT COUNT(*) FROM foo WHERE CAST(l1 AS VARCHAR) IN "
         "('7.0', '9')", 1),
        ("SELECT COUNT(*) FROM foo WHERE CAST(l1 AS VARCHAR) IN "
         "('7.0')", 0),
        # float column: f1 has a 1.0 row — '1.0' matches, '1' cannot
        ("SELECT COUNT(*) FROM foo WHERE CAST(f1 AS VARCHAR) = '1.0'", 1),
        ("SELECT COUNT(*) FROM foo WHERE CAST(f1 AS VARCHAR) = '1'", 0),
        ("SELECT COUNT(*) FROM foo WHERE CAST(f1 AS VARCHAR) <> '1'", 6),
    ]
    for q, want in cases:
        cols, rows = sql.execute(q)
        assert rows[0][0] == want, (q, rows)


def test_trim_strips_spaces_only():
    """SQL TRIM semantics: space characters only — a tab survives, so
    TRIM(col) filters must not match values the reference would not."""
    b = SegmentBuilder("ws", IV)
    b.add_columns(
        np.asarray([T0, T0 + DAY, T0 + 2 * DAY], dtype=np.int64),
        {"s": [" x", "\tx", "x "]}, {})
    ws = SqlExecutor(QueryExecutor([b.build()]))
    cols, rows = ws.execute("SELECT COUNT(*) FROM ws WHERE TRIM(s) = 'x'")
    assert rows[0][0] == 2          # ' x' and 'x ' — NOT '\tx'
    # the extraction fn itself: spaces trimmed, tab preserved
    from druid_tpu.query.model import RegexExtractionFn
    fn = RegexExtractionFn("^ *(.*?) *$", 1)
    assert fn.apply(" x") == "x" and fn.apply("x ") == "x"
    assert fn.apply("\tx") == "\tx"          # tab is NOT trimmed
    assert fn.apply("  x  ") == "x"


def test_strlen_strpos_in_expressions(sql):
    """CHAR_LENGTH/STRPOS over string dims ride per-dictionary-value
    numeric LUT gathers — usable inside any aggregate expression."""
    cases = [
        ("SELECT MAX(CHAR_LENGTH(dim1)) FROM foo", 1),
        ("SELECT SUM(CHAR_LENGTH(dim1) + CHAR_LENGTH(dim2)) FROM foo", 12),
        ("SELECT SUM(STRPOS(dim1, 'a')) FROM foo", 2),      # 'a' rows only
        ("SELECT SUM(STRPOS(dim2, 'z')) FROM foo", 1),
        ("SELECT SUM(CASE WHEN STRPOS(dim1, 'b') > 0 THEN l1 ELSE 0 END) "
         "FROM foo", 325332),
        ("SELECT SUM(l1 * CHAR_LENGTH(dim2)) FROM foo", 325352),
    ]
    for q, want in cases:
        cols, rows = sql.execute(q)
        assert rows[0][0] == want, (q, rows)


def test_strpos_semantics_and_literals(sql):
    """SQL STRPOS is 1-based (0 absent); native expression strpos is
    Druid's 0-based/-1. Literal-only string fns evaluate host-side."""
    cases = [
        ("SELECT MAX(STRPOS(dim2, 'x')) FROM foo", 1),
        ("SELECT MIN(STRPOS(dim2, 'x')) FROM foo", 0),     # absent → 0
        ("SELECT MAX(CHAR_LENGTH('abc') + l1 * 0) FROM foo", 3),
        ("SELECT MAX(STRPOS('hello', 'll') + l1 * 0) FROM foo", 3),
    ]
    for q, want in cases:
        cols, rows = sql.execute(q)
        assert rows[0][0] == want, (q, rows)
    # native expression semantics preserved (0-based / -1)
    from druid_tpu.utils.expression import parse_expression
    from druid_tpu.utils.expression import rewrite_string_sites, lut_for_site
    expr, sites = rewrite_string_sites(
        parse_expression("strpos(d, 'b')"), {"d"})
    lut = lut_for_site(sites[0], ["abc", "xyz"])
    assert lut.tolist() == [1, -1]
