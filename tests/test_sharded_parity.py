"""≥8-way sharded parity, driven the way the DRIVER runs multichip: a
fresh interpreter with `XLA_FLAGS=--xla_force_host_platform_device_count=8`
(the `dryrun_multichip` idiom), asserting the sharded mesh path is
BIT-IDENTICAL — floats included, compared with `==`, no tolerance — to
the serial decoded oracle across groupBy / timeseries / topN.

Exactness is only contractual for exact-merge aggregators (count,
longSum in int64, long/double min/max): their device collectives
(widened psum, pmax/pmin) are order-insensitive, so the sharded merge
and the host merge compute literally the same values. Float SUMS are
deliberately absent — summation order differs between the tree merge
and the collective, and their parity is tolerance-based (covered by
tests/test_distributed.py).

The inner run also counter-asserts the tentpole's merge discipline:
exactly one sharded dispatch per query (distributed.sharded_stats()),
ZERO batched and ZERO per-segment dispatches while the mesh is active —
i.e. the broker-side host merge is gone, not just idle — and the stack
that fed it is compressed-resident in the device pool.

The opt-out cross-product (DRUID_TPU_PACKED=0 / DRUID_TPU_CASCADE=0 are
import-time latches, hence subprocess per variant) proves parity does
not depend on which slots happen to be compressed.
"""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

INNER = r"""
import os
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from druid_tpu.data import devicepool
from druid_tpu.data.generator import ColumnSpec, DataGenerator
from druid_tpu.engine import QueryExecutor
import druid_tpu.engine.batching as batching
import druid_tpu.engine.engines as engines
from druid_tpu.parallel import distributed, make_mesh, use_mesh
from druid_tpu.query.aggregators import (CountAggregator, DoubleMaxAggregator,
                                         DoubleMinAggregator,
                                         LongMinAggregator, LongSumAggregator)
from druid_tpu.query.filters import BoundFilter, InFilter
from druid_tpu.query.model import (DefaultDimensionSpec, GroupByQuery,
                                   TimeseriesQuery, TopNQuery)
from druid_tpu.utils.intervals import Interval

import jax
assert len(jax.devices()) >= 8, jax.devices()

IV = Interval.of("2026-03-01", "2026-03-09")
SCHEMA = (ColumnSpec("dimA", "string", cardinality=7),
          ColumnSpec("dimB", "string", cardinality=31),
          ColumnSpec("metLong", "long", low=0, high=1000),
          ColumnSpec("metDouble", "double", low=-5.0, high=5.0))
# 11 segments on an 8-device mesh: K pads to 16, so the zero-pad
# segments' all-invalid decode is part of what parity covers
SEGMENTS = DataGenerator(SCHEMA, seed=23).segments(
    11, 2000, IV, datasource="parity")

AGGS = [CountAggregator("rows"),
        LongSumAggregator("lsum", "metLong"),
        LongMinAggregator("lmin", "metLong"),
        DoubleMaxAggregator("dmax", "metDouble"),
        DoubleMinAggregator("dmin", "metDouble")]
FLT = InFilter("dimA", [f"v{i:08d}" for i in range(5)])

QUERIES = [
    ("groupby", GroupByQuery.of(
        "parity", [IV], [DefaultDimensionSpec("dimA"),
                         DefaultDimensionSpec("dimB")],
        AGGS, granularity="day", filter=FLT)),
    ("timeseries", TimeseriesQuery.of(
        "parity", [IV], AGGS, granularity="day",
        filter=BoundFilter("metLong", lower=10, upper=900,
                           ordering="numeric"))),
    ("topn", TopNQuery.of(
        "parity", [IV], DefaultDimensionSpec("dimB"), "lsum", 10,
        AGGS, granularity="all", filter=FLT)),
]

# serial decoded oracle first, with the dispatch shape unconstrained
oracle = {name: QueryExecutor(SEGMENTS).run(q) for name, q in QUERIES}

# sharded runs: count every non-sharded dispatch that sneaks through
calls = {"batched": 0, "per_segment": 0}
_orig_batch = batching.run_with_batching


def _count_batch(*a, **k):
    calls["batched"] += 1
    return _orig_batch(*a, **k)


def _count_per_segment(*a, **k):
    calls["per_segment"] += 1
    raise AssertionError("per-segment dispatch on the sharded path")


batching.run_with_batching = _count_batch
engines.run_grouped_aggregate = _count_per_segment

mesh = make_mesh(8)
before = distributed.sharded_stats().snapshot()
with use_mesh(mesh):
    sharded = {name: QueryExecutor(SEGMENTS).run(q) for name, q in QUERIES}
after = distributed.sharded_stats().snapshot()

assert calls["batched"] == 0, calls
assert calls["per_segment"] == 0, calls
assert after[0] - before[0] == len(QUERIES), (before, after)
assert after[1] - before[1] == len(QUERIES) * len(SEGMENTS), (before, after)
snap = devicepool.device_pool().snapshot()
assert snap.stacked_entries >= 1, snap
print(f"STACKED_RATIO {snap.stacked_ratio:.3f}")

for name, _ in QUERIES:
    a, b = oracle[name], sharded[name]
    assert len(a) > 0, name
    assert a == b, (name, a[:3], b[:3])   # bit-identical, floats included
    print(f"PARITY OK {name} rows={len(a)}")
print("ALL PARITY OK")
"""

VARIANTS = [
    pytest.param({}, id="packed+cascade+bitmap"),
    pytest.param({"DRUID_TPU_PACKED": "0"}, id="packed-off"),
    pytest.param({"DRUID_TPU_CASCADE": "0"}, id="cascade-off"),
]


@pytest.mark.parametrize("extra_env", VARIANTS)
def test_sharded_bit_identical_to_serial_oracle(extra_env):
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS",
                        "DRUID_TPU_PACKED", "DRUID_TPU_CASCADE")}
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env.update(extra_env)
    proc = subprocess.run([sys.executable, "-c", INNER], cwd=REPO, env=env,
                          capture_output=True, text=True, timeout=420)
    assert proc.returncode == 0, proc.stderr[-3000:]
    out = proc.stdout
    for name in ("groupby", "timeseries", "topn"):
        assert f"PARITY OK {name}" in out, out
    assert "ALL PARITY OK" in out, out
    if not extra_env:
        # everything on: the resident stack must actually be compressed
        ratio = float(out.split("STACKED_RATIO ")[1].split()[0])
        assert ratio > 1.0, out
