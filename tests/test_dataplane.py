"""Network data plane tests: wire serde round-trip, broker → data nodes over
real sockets, cancel, timeout.

Reference models: DirectDruidClientTest + QueryResourceTest
(server/src/test/.../client/DirectDruidClientTest.java,
server/QueryResourceTest.java — query over HTTP, cancellation DELETE)."""
import threading
import time

import numpy as np
import pytest

from druid_tpu.cluster import (Broker, DataNode, DataNodeServer,
                               InventoryView, RemoteDataNodeClient,
                               descriptor_for)
from druid_tpu.cluster import wire
from druid_tpu.engine import QueryExecutor, engines
from druid_tpu.query.aggregators import (CardinalityAggregator,
                                         CountAggregator,
                                         DoubleMaxAggregator,
                                         FilteredAggregator,
                                         LongSumAggregator)
from druid_tpu.query.filters import BoundFilter, SelectorFilter
from druid_tpu.query.model import (DefaultDimensionSpec, GroupByQuery,
                                   ScanQuery, SearchQuery, TimeBoundaryQuery,
                                   TimeseriesQuery, TopNQuery)
from druid_tpu.server.querymanager import (QueryInterruptedError,
                                           QueryTimeoutError)
from druid_tpu.utils.intervals import Interval

WEEK = Interval.of("2026-01-01", "2026-01-08")
AGGS = [CountAggregator("rows"), LongSumAggregator("ls", "metLong")]


def _local(segments, q):
    return QueryExecutor(segments).run(q)


# ---------------------------------------------------------------------------
# Wire format
# ---------------------------------------------------------------------------

def test_wire_roundtrip_groupby(segments):
    q = GroupByQuery.of(
        "test", [WEEK], [DefaultDimensionSpec("dimA")],
        [CountAggregator("rows"), LongSumAggregator("ls", "metLong"),
         DoubleMaxAggregator("dm", "metDouble"),
         CardinalityAggregator("u", ("dimHi",)),
         FilteredAggregator("f", CountAggregator("f"),
                            SelectorFilter("dimA", "v00000001"))],
        granularity="day")
    ap = engines.make_aggregate_partials(q, segments)
    data = wire.dumps_partials(ap, served=[str(s.id) for s in segments],
                               trace=[{"traceId": "t", "spanId": "s",
                                       "name": "datanode/query"}])
    ap2, served, trace = wire.loads_partials(data)
    assert served == {str(s.id) for s in segments}
    assert trace == [{"traceId": "t", "spanId": "s",
                      "name": "datanode/query"}]
    assert engines.finish_groupby(q, ap2) == engines.finish_groupby(q, ap)


def test_wire_rejects_garbage():
    with pytest.raises(wire.WireError):
        wire.loads_partials(b"NOPE" + b"\x00" * 16)


# ---------------------------------------------------------------------------
# Broker over real sockets
# ---------------------------------------------------------------------------

@pytest.fixture()
def http_cluster(segments):
    """2 data nodes behind real HTTP servers; the broker only sees
    RemoteDataNodeClients — every query crosses a socket."""
    servers, clients = [], []
    view = InventoryView()
    nodes = [DataNode(f"http-node{i}") for i in range(2)]
    for i, node in enumerate(nodes):
        srv = DataNodeServer(node).start()
        servers.append(srv)
        client = RemoteDataNodeClient(node.name, srv.url)
        clients.append(client)
        view.register(client)
    for i, s in enumerate(segments):
        for j in (i % 2, (i + 1) % 2):
            nodes[j].load_segment(s)
            view.announce(nodes[j].name, descriptor_for(s))
    broker = Broker(view)
    yield view, nodes, servers, broker
    for srv in servers:
        srv.stop()


def test_http_timeseries_matches_local(http_cluster, segments):
    *_, broker = http_cluster
    q = TimeseriesQuery.of("test", [WEEK], AGGS, granularity="day")
    assert broker.run(q) == _local(segments, q)


def test_http_topn_matches_local(http_cluster, segments):
    *_, broker = http_cluster
    q = TopNQuery.of("test", [WEEK], "dimB", "ls", 10, AGGS)
    assert broker.run(q) == _local(segments, q)


def test_http_groupby_with_filter_matches_local(http_cluster, segments):
    *_, broker = http_cluster
    q = GroupByQuery.of(
        "test", [WEEK], [DefaultDimensionSpec("dimA")], AGGS,
        granularity="day",
        filter=BoundFilter("metLong", lower=10, upper=90,
                           ordering="numeric"))
    assert broker.run(q) == _local(segments, q)


def test_http_hll_state_merge_exact(http_cluster, segments):
    """HLL registers must survive the wire: broker == single-process."""
    *_, broker = http_cluster
    q = TimeseriesQuery.of("test", [WEEK],
                           [CardinalityAggregator("u", ("dimHi",))])
    assert broker.run(q) == _local(segments, q)


def test_http_row_queries(http_cluster, segments):
    *_, broker = http_cluster
    tb = TimeBoundaryQuery.of("test", [WEEK])
    assert broker.run(tb) == _local(segments, tb)
    sc = ScanQuery.of("test", [WEEK], columns=("dimA", "metLong"), limit=17,
                      order="ascending")
    got = broker.run(sc)
    assert sum(len(b["events"]) for b in got) == 17
    se = SearchQuery.of("test", [WEEK], "v0000000", limit=10)
    assert broker.run(se) == _local(segments, se)


def test_http_node_death_fails_over(http_cluster, segments):
    view, nodes, servers, broker = http_cluster
    servers[0].stop()   # node 0's server goes dark; replicas live on node 1
    q = TimeseriesQuery.of("test", [WEEK], AGGS, granularity="day")
    assert broker.run(q) == _local(segments, q)


# ---------------------------------------------------------------------------
# Cancel + timeout
# ---------------------------------------------------------------------------

class _SlowNode(DataNode):
    """DataNode whose partials path stalls, to give cancel/timeout a window."""

    def __init__(self, name, delay=1.0):
        super().__init__(name)
        self.delay = delay

    def run_partials(self, query, segment_ids, check=None):
        time.sleep(self.delay)
        return super().run_partials(query, segment_ids, check=check)


@pytest.fixture()
def slow_http_cluster(segments):
    node = _SlowNode("slow-node", delay=1.0)
    srv = DataNodeServer(node).start()
    view = InventoryView()
    view.register(RemoteDataNodeClient(node.name, srv.url))
    for s in segments:
        node.load_segment(s)
        view.announce(node.name, descriptor_for(s))
    broker = Broker(view, max_retries=0)
    yield node, srv, broker
    srv.stop()


def test_http_timeout(slow_http_cluster, segments):
    _, _, broker = slow_http_cluster
    q = TimeseriesQuery.of("test", [WEEK], AGGS,
                           context={"timeout": 200, "queryId": "to-1"})
    t0 = time.monotonic()
    with pytest.raises(QueryTimeoutError):
        broker.run(q)
    assert time.monotonic() - t0 < 0.9   # did not wait out the full delay


def test_http_cancel_mid_flight(slow_http_cluster, segments):
    node, srv, broker = slow_http_cluster
    qid = "cancel-1"
    q = TimeseriesQuery.of("test", [WEEK], AGGS, context={"queryId": qid})
    broker.query_manager.register(qid)
    errors = []

    def run():
        try:
            broker.run(q)
        except Exception as e:
            errors.append(e)

    t = threading.Thread(target=run)
    t.start()
    time.sleep(0.3)          # request is in flight on the slow node
    assert broker.query_manager.cancel(qid)
    t.join(timeout=10)
    assert not t.is_alive()
    assert errors and isinstance(errors[0], QueryInterruptedError), errors


def test_cancel_before_scatter(segments):
    """A token tripped before execution stops the query at the first
    checkpoint, without touching any node."""
    view = InventoryView()
    node = DataNode("n0")
    view.register(node)
    for s in segments:
        node.load_segment(s)
        view.announce(node.name, descriptor_for(s))
    broker = Broker(view)
    qid = "pre-cancel"
    broker.query_manager.register(qid)
    broker.query_manager.cancel(qid)
    q = TimeseriesQuery.of("test", [WEEK], AGGS, context={"queryId": qid})
    with pytest.raises(QueryInterruptedError):
        broker.run(q)


def test_remote_query_error_propagates(segments):
    """A node-side query error (HTTP 500 from a kernel crash) must reach the
    caller with the node's message, not degrade into MissingSegmentsError."""
    from druid_tpu.cluster.dataserver import RemoteQueryError

    class BrokenNode(DataNode):
        def run_partials(self, query, segment_ids, check=None):
            raise RuntimeError("kernel exploded: device OOM")

    node = BrokenNode("broken")
    srv = DataNodeServer(node).start()
    view = InventoryView()
    view.register(RemoteDataNodeClient(node.name, srv.url))
    for s in segments:
        node.load_segment(s)
        view.announce(node.name, descriptor_for(s))
    broker = Broker(view)
    q = TimeseriesQuery.of("test", [WEEK], AGGS)
    try:
        with pytest.raises(RemoteQueryError, match="kernel exploded"):
            broker.run(q)
    finally:
        srv.stop()


def test_duplicate_queryid_refcounted():
    """Two in-flight registrations of one id share a token that survives
    the first unregister (a client retry reusing its queryId)."""
    from druid_tpu.server.querymanager import QueryManager
    qm = QueryManager()
    t1 = qm.register("dup")
    t2 = qm.register("dup")
    assert t1 is t2
    qm.unregister("dup")
    assert qm.cancel("dup")          # second flight still cancellable
    qm.unregister("dup")
    assert not qm.cancel("dup")      # fully released


def test_cancel_path_id_exactness():
    from druid_tpu.server.querymanager import cancel_path_id
    assert cancel_path_id("/druid/v2/abc-123") == "abc-123"
    assert cancel_path_id("/druid/v2/abc-123/") == "abc-123"
    assert cancel_path_id("/druid/v2/datasources") is None
    assert cancel_path_id("/druid/v2/") is None
    assert cancel_path_id("/druid/v2") is None
    assert cancel_path_id("/other/v2/abc") is None
    assert cancel_path_id("/druid/v2/a/b") is None


def test_http_delete_cancel_endpoint(segments):
    """DELETE /druid/v2/{id} at the broker's HTTP resource trips the broker
    token (QueryResource.cancelQuery analog)."""
    import urllib.request
    from druid_tpu.server import QueryHttpServer, QueryLifecycle

    node = _SlowNode("slow2", delay=1.0)
    srv = DataNodeServer(node).start()
    view = InventoryView()
    view.register(RemoteDataNodeClient(node.name, srv.url))
    for s in segments:
        node.load_segment(s)
        view.announce(node.name, descriptor_for(s))
    broker = Broker(view, max_retries=0)
    lifecycle = QueryLifecycle(broker)
    http = QueryHttpServer(lifecycle).start()
    try:
        payload = {"queryType": "timeseries", "dataSource": "test",
                   "intervals": ["2026-01-01/2026-01-08"],
                   "granularity": "all",
                   "aggregations": [{"type": "count", "name": "rows"}],
                   "context": {"queryId": "http-cancel"}}
        results = []

        def run():
            import json
            req = urllib.request.Request(
                f"http://127.0.0.1:{http.port}/druid/v2",
                data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req) as r:
                    results.append(("ok", r.read()))
            except urllib.error.HTTPError as e:
                results.append((e.code, e.read().decode()))

        t = threading.Thread(target=run)
        t.start()
        time.sleep(0.3)
        req = urllib.request.Request(
            f"http://127.0.0.1:{http.port}/druid/v2/http-cancel",
            method="DELETE")
        with urllib.request.urlopen(req) as r:
            assert r.status == 202
        t.join(timeout=10)
        assert not t.is_alive()
        code, body = results[0]
        assert code == 500 and "cancel" in body.lower(), results
    finally:
        http.stop()
        srv.stop()
