"""Bitmap algebra + selectivity-estimator edge cases (ROADMAP item 5
satellites): sparse containers survive AND/OR/XOR without densifying,
NOT-of-sparse / empty-dictionary / all-rows-match selectivities are EXACT,
and the packed-uint32 device representation round-trips bit-for-bit."""
import numpy as np
import pytest

import druid_tpu.engine  # noqa: F401  (x64 on before jax numerics)
from druid_tpu.data.bitmap import (Bitmap, BitmapIndex, SparseBitmap,
                                   bitmap_and, bitmap_or, bitmap_xor,
                                   device_repr, sparse_if_small, to_words32)
from druid_tpu.data.generator import ColumnSpec, DataGenerator
from druid_tpu.engine.filters import (bitmap_of, estimate_selectivity,
                                      filter_cardinality)
from druid_tpu.query import filters as F
from druid_tpu.utils.intervals import Interval

IV = Interval.of("2026-03-01", "2026-03-02")


def _segment(n_rows=3333, card=50, seed=3):
    """n_rows deliberately NOT a multiple of 32 (word-boundary coverage)."""
    gen = DataGenerator((ColumnSpec("d", "string", cardinality=card),
                        ColumnSpec("m", "long", low=0, high=9)), seed=seed)
    return gen.segment(n_rows, IV, datasource="bm")


@pytest.fixture()
def no_densify(monkeypatch):
    """Fail the test if ANY SparseBitmap is densified (words/_dense)."""
    def boom(self):
        raise AssertionError("SparseBitmap was densified")
    monkeypatch.setattr(SparseBitmap, "_dense", boom)
    monkeypatch.setattr(SparseBitmap, "words", property(boom))


# ---------------------------------------------------------------------------
# representation-aware algebra
# ---------------------------------------------------------------------------

def test_sparse_sparse_algebra_stays_sparse(no_densify):
    n = 3333
    a = SparseBitmap(np.array([1, 5, 40, 999, 3332], np.int32), n)
    b = SparseBitmap(np.array([5, 40, 100], np.int32), n)
    both = a & b
    assert isinstance(both, SparseBitmap)
    assert list(both.ids) == [5, 40]
    either = a | b
    assert isinstance(either, SparseBitmap)
    assert list(either.ids) == [1, 5, 40, 100, 999, 3332]
    diff = a ^ b
    assert isinstance(diff, SparseBitmap)
    assert list(diff.ids) == [1, 100, 999, 3332]


def test_sparse_dense_and_probes_words_without_densify(no_densify):
    n = 3333
    dense = Bitmap.from_indices(np.arange(0, n, 2), n)   # even rows
    sp = SparseBitmap(np.array([0, 1, 2, 31, 32, 33, 3332], np.int32), n)
    out = bitmap_and(sp, dense)
    assert isinstance(out, SparseBitmap)
    assert list(out.ids) == [0, 2, 32, 3332]
    # operator form (either operand order) routes the same way
    assert list((dense & sp).ids) == [0, 2, 32, 3332]


def test_sparse_dense_or_xor_fold_ids_into_words():
    n = 100
    dense = Bitmap.from_indices(np.array([0, 1, 2]), n)
    sp = SparseBitmap(np.array([2, 50, 99], np.int32), n)
    assert sorted((sp | dense).to_indices()) == [0, 1, 2, 50, 99]
    assert sorted((sp ^ dense).to_indices()) == [0, 1, 50, 99]
    assert sorted((dense ^ sp).to_indices()) == [0, 1, 50, 99]


def test_not_of_sparse_is_dense_and_exact():
    n = 3333
    sp = SparseBitmap(np.array([0, 5, 3332], np.int32), n)
    inv = ~sp
    assert inv.cardinality() == n - 3
    assert not inv.test_ids(np.array([0, 5, 3332])).any()


def test_sparse_if_small_demotes():
    n = 32 * 40
    few = Bitmap.from_indices(np.array([3, 700]), n)
    assert isinstance(sparse_if_small(few), SparseBitmap)
    many = Bitmap.from_indices(np.arange(0, n, 2), n)
    assert isinstance(sparse_if_small(many), Bitmap)


# ---------------------------------------------------------------------------
# selectivity / bitmap_of edge cases
# ---------------------------------------------------------------------------

def test_not_of_sparse_selectivity_exact_without_densify(monkeypatch):
    seg = _segment(n_rows=3333, card=400)   # ~8 rows/value: sparse leaves
    val = seg.dims["d"].dictionary.values[0]
    leaf = F.SelectorFilter("d", val)
    lb = bitmap_of(leaf, seg)
    assert isinstance(lb, SparseBitmap)
    k = lb.cardinality()
    # NOT computes as n - |child|: neither the complement words nor the
    # sparse child's words materialize
    def boom(self):
        raise AssertionError("SparseBitmap was densified")
    monkeypatch.setattr(SparseBitmap, "_dense", boom)
    monkeypatch.setattr(SparseBitmap, "words", property(boom))
    assert filter_cardinality(F.NotFilter(leaf), seg) == seg.n_rows - k
    assert estimate_selectivity(F.NotFilter(leaf), seg) == \
        (seg.n_rows - k) / seg.n_rows


def test_empty_dictionary_dim_exact():
    seg = _segment()
    # IN over values absent from the dictionary: the empty id set
    flt = F.InFilter("d", ("no-such-value", "also-missing"))
    bm = bitmap_of(flt, seg)
    assert bm.cardinality() == 0
    assert estimate_selectivity(flt, seg) == 0.0
    # and its complement is exactly everything
    assert filter_cardinality(F.NotFilter(flt), seg) == seg.n_rows
    assert estimate_selectivity(F.NotFilter(flt), seg) == 1.0


def test_zero_cardinality_index_and_empty_segment():
    idx = BitmapIndex.build(np.zeros(0, dtype=np.int32), 0)
    assert idx.union_of(np.array([], dtype=np.int64)).cardinality() == 0
    assert idx.union_of(np.array([0, 3])).cardinality() == 0  # out of range


def test_all_rows_match_exact():
    seg = _segment(card=1)                   # every row holds the one value
    val = seg.dims["d"].dictionary.values[0]
    flt = F.SelectorFilter("d", val)
    assert filter_cardinality(flt, seg) == seg.n_rows
    assert estimate_selectivity(flt, seg) == 1.0
    assert estimate_selectivity(F.TrueFilter(), seg) == 1.0
    assert estimate_selectivity(F.FalseFilter(), seg) == 0.0


def test_bitmap_of_matches_host_truth_on_mixed_tree():
    seg = _segment(n_rows=3333, card=30)
    vals = seg.dims["d"].dictionary.values
    flt = F.OrFilter((
        F.AndFilter((F.InFilter("d", tuple(vals[:3])),
                     F.NotFilter(F.SelectorFilter("d", vals[1])))),
        F.SelectorFilter("d", vals[7]),
    ))
    from druid_tpu.engine.filters import host_mask
    want = host_mask(flt, seg)
    got = bitmap_of(flt, seg)
    assert np.array_equal(got.to_bool(), want)
    assert filter_cardinality(flt, seg) == int(want.sum())


# ---------------------------------------------------------------------------
# packed uint32 device words
# ---------------------------------------------------------------------------

def test_words32_round_trip_lsb_first():
    n, padded = 3333, 3584          # padded: multiple of 32, not of 1024
    rng = np.random.default_rng(5)
    mask = rng.random(n) < 0.3
    bm = Bitmap.from_bool(mask)
    w = to_words32(bm, padded)
    assert w.dtype == np.uint32 and w.shape == (padded // 32,)
    rows = np.arange(padded)
    bits = (w[rows // 32] >> (rows % 32).astype(np.uint32)) & 1
    assert np.array_equal(bits[:n].astype(bool), mask)
    assert not bits[n:].any()       # padding rows stay clear


def test_device_repr_density_split():
    n = 4096
    kind, payload = device_repr(
        SparseBitmap(np.array([1, 2, 3], np.int32), n), n)
    assert kind == "sparse"
    assert payload.dtype == np.int32
    # pow2 rung, padded with the out-of-range sentinel
    assert payload.shape[0] == 8 and (payload[3:] == n).all()
    dense_bm = Bitmap.from_indices(np.arange(0, n, 3), n)
    kind, payload = device_repr(dense_bm, n)
    assert kind == "dense" and payload.dtype == np.uint32
    assert np.array_equal(payload, to_words32(dense_bm, n))


def test_union_of_stays_sparse_and_exact():
    seg = _segment(n_rows=4000, card=500, seed=11)
    col = seg.dims["d"]
    idx = col.bitmap_index()
    bm = idx.union_of(np.array([0, 1]))
    assert isinstance(bm, SparseBitmap)
    truth = np.isin(col.ids, [0, 1])
    assert np.array_equal(bm.to_bool(), truth)
    assert bm.cardinality() == int(truth.sum())
