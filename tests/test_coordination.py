"""Coordination subsystem: leader election, bounded failover under
injected faults (leader kill, heartbeat drop, registry partition),
single-writer safety via fencing terms, duty-loop gating, discovery/
redirect, and observability.

Reference analogs under test: DruidLeaderSelector / CuratorDruidLeader
Selector semantics (terms, listeners), DruidLeaderClient redirects, and
the TaskMaster/DruidCoordinator leadership gating — over the lease-row
latch in the SQL metadata store."""
import json
import urllib.error
import urllib.request

import pytest

from druid_tpu.cluster import (Coordinator, DataNode, InventoryView,
                               MetadataStore, SegmentDescriptor,
                               StaleTermError)
from druid_tpu.coordination import (ChaosHarness, LeaderClient, ManualClock,
                                    LeaderParticipant, MetadataLeaseStore,
                                    NoLeaderError, NotLeaderError)
from druid_tpu.utils.intervals import Interval

LEASE_MS = 1_000
DAY = Interval.of("2026-01-01", "2026-01-02")


def mk_fleet(n=3, service="coordinator"):
    md = MetadataStore()
    clock = ManualClock()
    h = ChaosHarness.over_metadata(md, service, lease_ms=LEASE_MS,
                                   clock=clock)
    ps = [h.participant(f"node{i}",
                        meta={"url": f"http://127.0.0.1:{9000 + i}"})
          for i in range(n)]
    return md, clock, h, ps


def leaders_of(ps):
    return [p.node_id for p in ps if p.is_leader()]


def assert_single_writer_per_term(md, service):
    """THE safety property: for every fencing term, all writes the store
    accepted came from one holder (no dual leader ever wrote)."""
    by_term = {}
    for e in md.fence_log(service):
        by_term.setdefault(e["term"], set()).add(e["holder"])
    for term, holders in sorted(by_term.items()):
        assert len(holders) == 1, \
            f"dual writer in term {term}: {sorted(holders)}"


# ---------------------------------------------------------------------------
# election basics
# ---------------------------------------------------------------------------

def test_first_heartbeat_elects_exactly_one_leader():
    md, clock, h, ps = mk_fleet()
    h.tick_all()
    assert len(leaders_of(ps)) == 1
    leader = h.leader()
    assert leader.term == 1
    # further rounds are stable: nobody steals a live lease
    for _ in range(5):
        clock.advance(LEASE_MS // 3)
        h.tick_all()
        assert leaders_of(ps) == [leader.node_id]
        assert leader.term == 1         # renewals never mint terms


def test_graceful_release_promotes_standby_immediately():
    md, clock, h, ps = mk_fleet()
    first = h.await_leader()[0]
    first.stop(release=True)            # voluntary step-down
    promoted, intervals = h.await_leader(max_intervals=1)
    assert promoted is not first
    assert intervals <= 1.0             # no expiry wait after a release
    assert promoted.term == 2


def test_terms_are_monotonic_across_failovers():
    md, clock, h, ps = mk_fleet()
    seen = []
    for _ in range(3):
        leader, _ = h.await_leader()
        seen.append(leader.term)
        h.kill_leader()
    assert seen == sorted(seen) and len(set(seen)) == 3


# ---------------------------------------------------------------------------
# the three injected faults: bounded failover + no dual leader
# ---------------------------------------------------------------------------

def _inject(h, fault):
    leader = h.leader()
    if fault == "kill":
        h.kill_leader()
    elif fault == "drop":
        h.drop_heartbeats(leader.node_id)
    elif fault == "partition":
        h.partition(leader.node_id)
    return leader


@pytest.mark.parametrize("fault", ["kill", "drop", "partition"])
def test_fault_promotes_standby_within_bounded_intervals(fault):
    md, clock, h, ps = mk_fleet()
    old = h.await_leader()[0]
    old_term = old.term
    _inject(h, fault)
    # bounded failover: expiry (1 interval) + takeover heartbeat slack
    promoted, intervals = h.await_leader(max_intervals=3, exclude=old)
    assert promoted is not old
    assert intervals <= 2.0, f"{fault}: promotion took {intervals} intervals"
    assert promoted.term == old_term + 1
    # the old leader self-fenced: a surviving-but-cut-off process must
    # read itself as non-leader once its lease lapsed locally
    assert not old.is_leader()
    assert leaders_of(ps) == [promoted.node_id]


@pytest.mark.parametrize("fault", ["kill", "drop", "partition"])
def test_no_two_accepted_writes_share_a_term_across_holders(fault):
    """Under every fault, drive BOTH the deposed leader and the promoted
    one to write — the store must accept each term's writes from exactly
    one holder, rejecting the zombie's with StaleTermError."""
    md, clock, h, ps = mk_fleet()
    old = h.await_leader()[0]
    md.insert_task("t-pre", "ds", "RUNNING", {}, fence=old.fence())
    stale_fence = old.fence()
    _inject(h, fault)
    promoted, _ = h.await_leader(max_intervals=3, exclude=old)
    md.insert_task("t-post", "ds", "RUNNING", {}, fence=promoted.fence())
    # the zombie's in-flight write (captured fence from its old term)
    with pytest.raises(StaleTermError):
        md.insert_task("t-zombie", "ds", "RUNNING", {}, fence=stale_fence)
    with pytest.raises(StaleTermError):
        md.publish_segments(
            [SegmentDescriptor("ds", DAY, "v1")], fence=stale_fence)
    assert_single_writer_per_term(md, "coordinator")
    # and the rejected write really did not land
    assert md.task("t-zombie") is None
    assert md.used_segments("ds") == []


def test_healed_node_rejoins_as_standby():
    md, clock, h, ps = mk_fleet()
    old = h.await_leader()[0]
    h.partition(old.node_id)
    promoted, _ = h.await_leader(max_intervals=3, exclude=old)
    h.heal(old.node_id)
    for _ in range(4):
        clock.advance(LEASE_MS // 3)
        h.tick_all()
        # the healed node must NOT depose the live leader
        assert leaders_of(ps) == [promoted.node_id]


def test_fenced_write_requires_current_term_not_just_any_term():
    md = MetadataStore()
    store = MetadataLeaseStore(md)
    clock = ManualClock()
    a = LeaderParticipant(store, "svc", "a", lease_ms=LEASE_MS, clock=clock)
    a.tick()
    # a term from the FUTURE (never minted) is rejected too
    with pytest.raises(StaleTermError):
        md.mark_unused([], fence=("svc", a.term + 5, "a"))
    # wrong holder under the right term is rejected
    with pytest.raises(StaleTermError):
        md.mark_unused([], fence=("svc", a.term, "impostor"))
    # unknown service has no lease → nobody was ever elected
    with pytest.raises(StaleTermError):
        md.mark_unused([], fence=("other-svc", 1, "a"))


# ---------------------------------------------------------------------------
# duty-loop gating: coordinator + overlord idle on non-leaders
# ---------------------------------------------------------------------------

class _ProbeCountingNode(DataNode):
    def __init__(self, name):
        super().__init__(name)
        self.pings = 0

    def ping(self):
        self.pings += 1
        return True


def test_coordinator_duty_loop_idles_on_non_leader():
    md, clock, h, ps = mk_fleet(2)
    leader, _ = h.await_leader()
    standby = next(p for p in ps if p is not leader)

    view = InventoryView()
    node = _ProbeCountingNode("n0")
    view.register(node)
    coord = Coordinator(md, view, lambda d: None, leader=standby)
    stats = coord.run_once(now_ms=clock())
    assert stats.skipped_not_leader
    assert stats.leader_term == -1
    assert node.pings == 0          # not even liveness probes ran
    assert md.fence_log("coordinator") == []

    # promote the standby → the SAME coordinator object starts working
    h.kill_leader()
    promoted, _ = h.await_leader(max_intervals=3)
    assert promoted is standby
    stats = coord.run_once(now_ms=clock())
    assert not stats.skipped_not_leader
    assert stats.leader_term == standby.term
    assert node.pings == 1


def test_coordinator_writes_carry_fencing_term():
    md, clock, h, ps = mk_fleet(1)
    leader, _ = h.await_leader()
    # two versions over one interval: v1 is fully overshadowed, so the
    # duty cycle's mark_unused write goes through the fence
    md.publish_segments([SegmentDescriptor("ds", DAY, "v1"),
                         SegmentDescriptor("ds", DAY, "v2")])
    coord = Coordinator(md, InventoryView(), lambda d: None, leader=leader)
    stats = coord.run_once(now_ms=clock())
    assert stats.overshadowed_marked == 1
    log = md.fence_log("coordinator")
    assert [e["op"] for e in log] == ["mark_unused"]
    assert log[0]["term"] == leader.term
    assert log[0]["holder"] == leader.node_id


def test_overlord_rejects_submission_on_non_leader():
    from druid_tpu.indexing import Overlord
    from druid_tpu.indexing.task import KillTask
    md, clock, h, ps = mk_fleet(2, service="overlord")
    leader, _ = h.await_leader()
    standby = next(p for p in ps if p is not leader)

    ov = Overlord(md, leader=standby)
    try:
        with pytest.raises(NotLeaderError) as ei:
            ov.submit(KillTask("ds", DAY))
        # the rejection carries the live leader's URL for redirect
        assert ei.value.leader_url == leader.meta["url"]
        assert md.tasks() == []          # provably idle: nothing persisted
    finally:
        ov.shutdown()

    ov2 = Overlord(md, leader=leader)
    try:
        tid = ov2.submit(KillTask("ds", DAY))
        assert ov2.await_task(tid).state == "SUCCESS"
        ops = [e["op"] for e in md.fence_log("overlord")]
        assert "insert_task" in ops and "update_task_status" in ops
        assert_single_writer_per_term(md, "overlord")
    finally:
        ov2.shutdown()


def test_zombie_overlord_task_cannot_publish():
    """A task started under overlord A publishes AFTER B took over: the
    toolbox reads the fence late, so the publish carries A's stale term
    and the store rejects it — the exactly-once boundary holds across
    failover."""
    from druid_tpu.indexing import Overlord
    md, clock, h, ps = mk_fleet(2, service="overlord")
    a_leader, _ = h.await_leader()
    a = Overlord(md, leader=a_leader)
    tb = a.toolbox()
    try:
        h.kill_leader()
        h.await_leader(max_intervals=3)

        class _T:                       # minimal task identity for publish
            id = "t-zombie"
        with pytest.raises(StaleTermError):
            tb.publish(_T(), [SegmentDescriptor("ds", DAY, "v1")])
        assert md.used_segments("ds") == []
        assert_single_writer_per_term(md, "overlord")
    finally:
        a.shutdown()


# ---------------------------------------------------------------------------
# discovery + redirect (DruidLeaderClient pattern)
# ---------------------------------------------------------------------------

def _get(url, expect_redirect=False):
    req = urllib.request.Request(url, method="GET")

    class _NoRedirect(urllib.request.HTTPRedirectHandler):
        def redirect_request(self, *a, **k):
            return None

    opener = urllib.request.build_opener(_NoRedirect)
    try:
        with opener.open(req, timeout=10) as r:
            return r.status, dict(r.headers), json.loads(r.read() or b"null")
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), \
            json.loads(e.read() or b"null") if not expect_redirect else None


@pytest.fixture
def http_pair():
    """Two QueryHttpServers fronting one overlord latch: s1's participant
    leads, s2's stands by."""
    from druid_tpu.indexing import Overlord
    from druid_tpu.server import QueryHttpServer, QueryLifecycle
    md = MetadataStore()
    clock = ManualClock()
    h = ChaosHarness.over_metadata(md, "overlord", lease_ms=LEASE_MS,
                                   clock=clock)
    p1, p2 = h.participant("node1"), h.participant("node2")
    servers, overlords = [], []
    try:
        for p in (p1, p2):
            ov = Overlord(md, leader=p)
            s = QueryHttpServer(QueryLifecycle(None),
                                coordination={"overlord": p}, overlord=ov)
            s.start()
            p.meta["url"] = f"http://127.0.0.1:{s.port}"
            servers.append(s)
            overlords.append(ov)
        p1.tick()                         # node1 wins
        p2.tick()
        assert p1.is_leader() and not p2.is_leader()
        yield md, clock, h, (p1, p2), servers
    finally:
        for s in servers:
            s.stop()
        for ov in overlords:
            ov.shutdown()


def test_http_leader_discovery_and_redirect(http_pair):
    md, clock, h, (p1, p2), (s1, s2) = http_pair
    u1 = f"http://127.0.0.1:{s1.port}"
    u2 = f"http://127.0.0.1:{s2.port}"
    # /leader answers on BOTH nodes with the leader's advertised URL
    for u in (u1, u2):
        code, _, body = _get(u + "/druid/indexer/v1/leader")
        assert code == 200 and body["leader"] == u1
        assert body["term"] == p1.term
    # isLeader: 200 on the leader, 404 on the standby (Druid semantics)
    assert _get(u1 + "/druid/indexer/v1/isLeader")[0] == 200
    code, _, body = _get(u2 + "/druid/indexer/v1/isLeader")
    assert code == 404 and body["leader"] is False
    # any other API path on the standby → 307 at the leader
    code, headers, _ = _get(u2 + "/druid/indexer/v1/task/x/status",
                            expect_redirect=True)
    assert code == 307
    assert headers["Location"] == u1 + "/druid/indexer/v1/task/x/status"


def test_http_task_submit_runs_on_leader_only(http_pair):
    md, clock, h, (p1, p2), (s1, s2) = http_pair
    u2 = f"http://127.0.0.1:{s2.port}"
    payload = {"type": "kill", "dataSource": "ds",
               "interval": str(DAY), "id": "kill-1"}
    # the LeaderClient resolves the leader from the lease row
    client = LeaderClient(h.store, "overlord", clock=clock)
    out = client.go("/druid/indexer/v1/task", payload)
    assert out["task"] == "kill-1"
    assert md.task("kill-1") is not None
    # a client whose cached leader is STALE (pointing at the standby)
    # follows the 307 to the real leader transparently
    stale = LeaderClient(h.store, "overlord", clock=clock)
    stale._cached_url = u2
    out = stale.go("/druid/indexer/v1/task",
                   {**payload, "id": "kill-2"})
    assert out["task"] == "kill-2"
    assert md.task("kill-2") is not None


def test_leader_client_no_leader():
    md = MetadataStore()
    clock = ManualClock()
    client = LeaderClient(MetadataLeaseStore(md), "overlord", clock=clock)
    assert client.leader() is None
    with pytest.raises(NoLeaderError):
        client.request(lambda url: url, retries=2, backoff_s=0)


def test_router_fronts_the_control_plane(http_pair):
    """One stable router URL across failovers: the router re-resolves the
    leader from the lease row (AsyncQueryForwardingServlet's /proxy)."""
    from druid_tpu.server.router import (RouterHttpServer,
                                         TieredBrokerSelector)
    md, clock, h, (p1, p2), (s1, s2) = http_pair
    selector = TieredBrokerSelector({"_default": ["http://127.0.0.1:1"]},
                                    "_default")
    router = RouterHttpServer(
        selector, leader_clients={
            "overlord": LeaderClient(h.store, "overlord", clock=clock)})
    router.start()
    try:
        code, _, body = _get(router.url + "/druid/indexer/v1/leader")
        assert code == 200
        assert body["leader"] == f"http://127.0.0.1:{s1.port}"
        # failover: kill node1's latch, node2 takes over; the SAME router
        # URL now answers from node2
        p1.kill()
        clock.advance(LEASE_MS + 1)
        p2.tick()
        assert p2.is_leader()
        code, _, body = _get(router.url + "/druid/indexer/v1/leader")
        assert code == 200
        assert body["leader"] == f"http://127.0.0.1:{s2.port}"
    finally:
        router.stop()


# ---------------------------------------------------------------------------
# observability + lifecycle stage
# ---------------------------------------------------------------------------

def test_emitter_reports_transitions_and_lease_age():
    from druid_tpu.utils.emitter import InMemoryEmitter, ServiceEmitter
    sink = InMemoryEmitter()
    emitter = ServiceEmitter("coordinator", "localhost", sink)
    md = MetadataStore()
    clock = ManualClock()
    h = ChaosHarness.over_metadata(md, "coordinator", lease_ms=LEASE_MS,
                                   clock=clock)
    p = h.participant("node0", emitter=emitter)
    p.tick()
    trans = sink.metrics("coordination/leader/transitions")
    assert len(trans) == 1
    assert trans[0].dims["event"] == "become" and trans[0].value == 1
    clock.advance(400)
    p.tick()
    ages = sink.metrics("coordination/lease/ageMs")
    assert ages and ages[-1].value == 400      # age at tick, pre-renew
    # losing the lease emits the stop transition
    p.drop_heartbeats = True
    clock.advance(LEASE_MS + 1)
    p.tick()
    trans = sink.metrics("coordination/leader/transitions")
    assert [e.dims["event"] for e in trans] == ["become", "stop"]
    assert p.transitions == 2

    # the MonitorScheduler-compatible monitor emits both observables
    from druid_tpu.coordination import LeaderMonitor
    LeaderMonitor(p).do_monitor(emitter)
    assert sink.metrics("coordination/leader/transitions")[-1].value == 2
    assert sink.metrics("coordination/lease/ageMs")[-1].dims["leader"] is False


def test_become_and_stop_listeners_fire():
    md, clock, h, ps = mk_fleet(1)
    p = ps[0]
    events = []
    p.register_listener(on_become=lambda term: events.append(("up", term)),
                        on_stop=lambda: events.append(("down", None)))
    p.tick()
    assert events == [("up", 1)]
    p.drop_heartbeats = True
    clock.advance(LEASE_MS + 1)
    p.tick()
    assert events == [("up", 1), ("down", None)]
    # healed: re-election fires become again with the NEW term
    p.drop_heartbeats = False
    clock.advance(LEASE_MS + 1)
    p.tick()
    assert events[-1] == ("up", 2)


def test_lifecycle_coordination_stage_ordering():
    """COORDINATION sits between SERVER and ANNOUNCEMENTS: a node starts
    competing for leadership only once its endpoint serves, and is
    discoverable only after the latch is live; stop reverses."""
    from druid_tpu.utils.lifecycle import Lifecycle, Stage
    events = []

    def h(name):
        return dict(start=lambda: events.append(f"+{name}"),
                    stop=lambda: events.append(f"-{name}"))

    lc = Lifecycle()
    lc.add(**h("announce"), stage=Stage.ANNOUNCEMENTS)
    lc.add(**h("latch"), stage=Stage.COORDINATION)
    lc.add(**h("http"), stage=Stage.SERVER)
    lc.add(**h("meta"), stage=Stage.INIT)
    lc.start()
    lc.stop()
    assert events == ["+meta", "+http", "+latch", "+announce",
                      "-announce", "-latch", "-http", "-meta"]
