"""Avatica JSON-RPC (JDBC) endpoint (reference: DruidMeta /
DruidAvaticaJsonHandler — the Calcite Avatica remote-driver protocol)."""
import json
import urllib.request

import pytest

from druid_tpu.engine import QueryExecutor
from druid_tpu.server.http import QueryHttpServer
from druid_tpu.server.lifecycle import QueryLifecycle
from druid_tpu.sql import SqlExecutor


@pytest.fixture()
def avatica_url(segments):
    ex = QueryExecutor(segments)
    srv = QueryHttpServer(QueryLifecycle(ex),
                          sql_executor=SqlExecutor(ex)).start()
    yield f"http://127.0.0.1:{srv.port}/druid/v2/sql/avatica/"
    srv.stop()


def _rpc(url, payload):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST")
    return json.loads(urllib.request.urlopen(req, timeout=30).read())


def test_avatica_statement_lifecycle(avatica_url, segments):
    url = avatica_url
    r = _rpc(url, {"request": "openConnection"})
    cid = r["connectionId"]
    r = _rpc(url, {"request": "createStatement", "connectionId": cid})
    sid = r["statementId"]
    r = _rpc(url, {"request": "prepareAndExecute", "connectionId": cid,
                   "statementId": sid,
                   "sql": "SELECT COUNT(*) c, SUM(metLong) s FROM test",
                   "maxRowCount": -1})
    assert r["response"] == "executeResults"
    rs = r["results"][0]
    assert rs["response"] == "resultSet" and rs["firstFrame"]["done"]
    cols = [c["columnName"] for c in rs["signature"]["columns"]]
    assert cols == ["c", "s"]
    assert rs["signature"]["columns"][0]["type"]["name"] == "BIGINT"
    total = sum(s.n_rows for s in segments)
    assert rs["firstFrame"]["rows"][0][0] == total
    _rpc(url, {"request": "closeStatement", "connectionId": cid,
               "statementId": sid})
    _rpc(url, {"request": "closeConnection", "connectionId": cid})
    # connection gone: further statements error
    r = _rpc(url, {"request": "createStatement", "connectionId": cid})
    assert r["response"] == "error"


def test_avatica_prepare_execute_with_params(avatica_url):
    url = avatica_url
    cid = _rpc(url, {"request": "openConnection"})["connectionId"]
    r = _rpc(url, {"request": "prepare", "connectionId": cid,
                   "sql": "SELECT dimA, COUNT(*) c FROM test "
                          "WHERE dimA = ? GROUP BY dimA"})
    handle = r["statement"]
    r2 = _rpc(url, {"request": "execute",
                    "statementHandle": {"connectionId": cid,
                                        "id": handle["id"]},
                    "parameterValues": [{"type": "STRING",
                                         "value": "v00000001"}],
                    "maxRowCount": -1})
    rows = r2["results"][0]["firstFrame"]["rows"]
    assert len(rows) == 1 and rows[0][0] == "v00000001"


def test_avatica_fetch_pagination(avatica_url, segments):
    url = avatica_url
    cid = _rpc(url, {"request": "openConnection"})["connectionId"]
    sid = _rpc(url, {"request": "createStatement",
                     "connectionId": cid})["statementId"]
    srv_frame = 7
    # shrink the frame size via the mounted server? exercise fetch with
    # explicit offsets instead: ask for everything, page with fetch
    r = _rpc(url, {"request": "prepareAndExecute", "connectionId": cid,
                   "statementId": sid,
                   "sql": "SELECT DISTINCT dimB FROM test",
                   "maxRowCount": -1})
    total_rows = len(r["results"][0]["firstFrame"]["rows"])
    assert total_rows > 10
    f = _rpc(url, {"request": "fetch", "connectionId": cid,
                   "statementId": sid, "offset": 5,
                   "fetchMaxRowCount": srv_frame})
    assert f["response"] == "fetch"
    assert len(f["frame"]["rows"]) == srv_frame
    assert f["frame"]["offset"] == 5 and not f["frame"]["done"]
    f2 = _rpc(url, {"request": "fetch", "connectionId": cid,
                    "statementId": sid, "offset": total_rows - 2,
                    "fetchMaxRowCount": 100})
    assert len(f2["frame"]["rows"]) == 2 and f2["frame"]["done"]


def test_avatica_errors_are_protocol_errors(avatica_url):
    url = avatica_url
    cid = _rpc(url, {"request": "openConnection"})["connectionId"]
    r = _rpc(url, {"request": "prepareAndExecute", "connectionId": cid,
                   "statementId": 0, "sql": "SELECT FROM nope"})
    assert r["response"] == "error" and r["errorMessage"]
    r = _rpc(url, {"request": "teleport"})
    assert r["response"] == "error"


def test_avatica_respects_authorization(segments):
    import base64
    from druid_tpu.server.security import (AuthChain,
                                           BasicHTTPAuthenticator,
                                           Permission, READ,
                                           RoleBasedAuthorizer)
    from druid_tpu.server import authorizer_for_query
    chain = AuthChain(
        authenticators=[BasicHTTPAuthenticator({"alice": "pw"},
                                               authorizer_name="rbac")],
        authorizers={"rbac": RoleBasedAuthorizer(
            {"r": [Permission("test", actions=(READ,))]},
            {"alice": ["r"]})})
    ex = QueryExecutor(segments)
    srv = QueryHttpServer(QueryLifecycle(ex,
                                         authorizer=authorizer_for_query(
                                             chain)),
                          sql_executor=SqlExecutor(ex),
                          auth_chain=chain).start()
    url = f"http://127.0.0.1:{srv.port}/druid/v2/sql/avatica/"
    hdr = {"Authorization": "Basic " + base64.b64encode(
        b"alice:pw").decode()}

    def rpc(payload):
        req = urllib.request.Request(
            url, data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json", **hdr},
            method="POST")
        return json.loads(urllib.request.urlopen(req, timeout=30).read())

    try:
        cid = rpc({"request": "openConnection"})["connectionId"]
        sid = rpc({"request": "createStatement",
                   "connectionId": cid})["statementId"]
        ok = rpc({"request": "prepareAndExecute", "connectionId": cid,
                  "statementId": sid, "sql": "SELECT COUNT(*) FROM test"})
        assert ok["response"] == "executeResults"
        denied = rpc({"request": "prepareAndExecute", "connectionId": cid,
                      "statementId": sid,
                      "sql": "SELECT COUNT(*) FROM secret"})
        assert denied["response"] == "error"
    finally:
        srv.stop()


def test_avatica_connection_bound_to_identity(segments):
    """bob cannot fetch alice's buffered rows by presenting her
    connection id (DruidMeta ties connections to the caller)."""
    import base64
    from druid_tpu.server import authorizer_for_query
    from druid_tpu.server.security import (AuthChain,
                                           BasicHTTPAuthenticator,
                                           Permission, READ,
                                           RoleBasedAuthorizer)
    chain = AuthChain(
        authenticators=[BasicHTTPAuthenticator(
            {"alice": "pw", "bob": "pw2"}, authorizer_name="rbac")],
        authorizers={"rbac": RoleBasedAuthorizer(
            {"r": [Permission("test", actions=(READ,))]},
            {"alice": ["r"]})})
    ex = QueryExecutor(segments)
    srv = QueryHttpServer(
        QueryLifecycle(ex, authorizer=authorizer_for_query(chain)),
        sql_executor=SqlExecutor(ex), auth_chain=chain).start()
    url = f"http://127.0.0.1:{srv.port}/druid/v2/sql/avatica/"

    def rpc(payload, user, pw):
        hdr = {"Authorization": "Basic " + base64.b64encode(
            f"{user}:{pw}".encode()).decode(),
            "Content-Type": "application/json"}
        req = urllib.request.Request(url, data=json.dumps(payload).encode(),
                                     headers=hdr, method="POST")
        return json.loads(urllib.request.urlopen(req, timeout=30).read())

    try:
        cid = rpc({"request": "openConnection"}, "alice",
                  "pw")["connectionId"]
        sid = rpc({"request": "createStatement", "connectionId": cid},
                  "alice", "pw")["statementId"]
        ok = rpc({"request": "prepareAndExecute", "connectionId": cid,
                  "statementId": sid, "sql": "SELECT COUNT(*) FROM test"},
                 "alice", "pw")
        assert ok["response"] == "executeResults"
        # bob presents alice's connection: denied for fetch AND re-open
        stolen = rpc({"request": "fetch", "connectionId": cid,
                      "statementId": sid, "offset": 0}, "bob", "pw2")
        assert stolen["response"] == "error"
        reopen = rpc({"request": "openConnection", "connectionId": cid},
                     "bob", "pw2")
        assert reopen["response"] == "error"
    finally:
        srv.stop()
