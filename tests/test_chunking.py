"""Interval chunking (chunkPeriod query context) — chunked must equal
unchunked for every query shape (IntervalChunkingQueryRunner.java:67-133)."""
import pytest

from druid_tpu.engine import QueryExecutor
from druid_tpu.query.aggregators import CountAggregator, LongSumAggregator
from druid_tpu.query.model import (DefaultDimensionSpec, GroupByQuery,
                                   TimeseriesQuery, TopNQuery)
from druid_tpu.utils.intervals import (Interval, parse_period_ms,
                                       split_by_period)

WEEK = Interval.of("2026-01-01", "2026-01-08")
AGGS = [CountAggregator("rows"), LongSumAggregator("ls", "metLong")]
CHUNK = {"chunkPeriod": "P1D"}


def test_parse_period_ms():
    assert parse_period_ms("P1D") == 86_400_000
    assert parse_period_ms("PT6H") == 6 * 3_600_000
    assert parse_period_ms("P1W") == 7 * 86_400_000
    assert parse_period_ms("PT30M") == 1_800_000
    assert parse_period_ms("P1DT12H") == 129_600_000
    assert parse_period_ms(5000) == 5000
    with pytest.raises(ValueError):
        parse_period_ms("1 day")


def test_split_by_period_aligned():
    iv = Interval.of("2026-01-01T06:00:00", "2026-01-03T18:00:00")
    chunks = split_by_period(iv, 86_400_000)
    # edges align to UTC midnights; union reproduces the interval exactly
    assert [str(c) for c in chunks] == [
        "2026-01-01T06:00:00.000Z/2026-01-02T00:00:00.000Z",
        "2026-01-02T00:00:00.000Z/2026-01-03T00:00:00.000Z",
        "2026-01-03T00:00:00.000Z/2026-01-03T18:00:00.000Z"]
    assert chunks[0].start == iv.start and chunks[-1].end == iv.end
    # short intervals pass through whole
    assert split_by_period(Interval.of("2026-01-01", "2026-01-01T02:00:00"),
                           86_400_000) == \
        [Interval.of("2026-01-01", "2026-01-01T02:00:00")]


@pytest.mark.parametrize("granularity", ["all", "day", "hour"])
def test_chunked_timeseries_equals_unchunked(segments, granularity):
    q = TimeseriesQuery.of("test", [WEEK], AGGS, granularity=granularity)
    qc = TimeseriesQuery.of("test", [WEEK], AGGS, granularity=granularity,
                            context=CHUNK)
    ex = QueryExecutor(segments)
    assert ex.run(qc) == ex.run(q)


def test_chunked_groupby_topn_equal_unchunked(segments):
    ex = QueryExecutor(segments)
    gb = GroupByQuery.of("test", [WEEK], [DefaultDimensionSpec("dimA")],
                         AGGS, granularity="day")
    gbc = GroupByQuery.of("test", [WEEK], [DefaultDimensionSpec("dimA")],
                          AGGS, granularity="day", context=CHUNK)
    key = lambda rows: sorted(
        (r["timestamp"], r["event"]["dimA"], r["event"]["rows"],
         r["event"]["ls"]) for r in rows)
    assert key(ex.run(gbc)) == key(ex.run(gb))
    tn = TopNQuery.of("test", [WEEK], "dimB", "ls", 5, AGGS,
                      granularity="all")
    tnc = TopNQuery.of("test", [WEEK], "dimB", "ls", 5, AGGS,
                       granularity="all", context=CHUNK)
    assert ex.run(tnc) == ex.run(tn)


def test_chunked_through_broker(segments):
    from druid_tpu.cluster import (Broker, DataNode, InventoryView,
                                   descriptor_for)
    view = InventoryView()
    node = DataNode("n0")
    view.register(node)
    for s in segments:
        node.load_segment(s)
        view.announce("n0", descriptor_for(s))
    broker = Broker(view)
    q = TimeseriesQuery.of("test", [WEEK], AGGS, granularity="day")
    qc = TimeseriesQuery.of("test", [WEEK], AGGS, granularity="day",
                            context=CHUNK)
    assert broker.run(qc) == broker.run(q)
