"""x64 regression gate: long aggregates must stay exact for values near
2**31. The engine force-enables jax_enable_x64 (engine/__init__.py) and
eval_virtual_columns gates its long/double dtype mapping on that flag — if
either regresses (x64 off, or the virtual-column "long" mapping drifting to
a 32-bit or float dtype), sums of values near 2**31 silently truncate or
round. These tests pin the exact-int64 contract end to end."""
import numpy as np

from druid_tpu.data.generator import ColumnSpec, DataGenerator
from druid_tpu.engine import QueryExecutor
from druid_tpu.query.aggregators import (CountAggregator, LongMaxAggregator,
                                         LongSumAggregator)
from druid_tpu.query.model import (DefaultDimensionSpec,
                                   ExpressionVirtualColumn, GroupByQuery,
                                   TimeseriesQuery)
from druid_tpu.utils.intervals import Interval

INTERVAL = Interval.of("2026-01-01", "2026-01-02")
NEAR_31 = 2 ** 31 - 9


def _segments(n=6_000):
    schema = (
        ColumnSpec("dimA", "string", cardinality=5),
        ColumnSpec("metBig", "long", low=NEAR_31 - 40, high=NEAR_31),
    )
    return DataGenerator(schema, seed=5).segments(2, n // 2, INTERVAL)


def test_x64_enabled_for_engine():
    import jax
    import druid_tpu.engine  # noqa: F401
    assert jax.config.jax_enable_x64, \
        "engine/__init__ must enable x64 before any trace"


def test_long_sum_exact_near_2_31():
    segments = _segments()
    q = GroupByQuery.of(
        "bench", [INTERVAL], [DefaultDimensionSpec("dimA")],
        [CountAggregator("n"), LongSumAggregator("s", "metBig"),
         LongMaxAggregator("mx", "metBig")], granularity="all")
    rows = QueryExecutor(segments).run(q)
    want_sum = {}
    want_max = {}
    for seg in segments:
        vals = seg.metrics["metBig"].values.astype(np.int64)
        col = seg.dims["dimA"]
        for gid, g in enumerate(col.dictionary.values):
            m = col.ids == gid
            want_sum[g] = want_sum.get(g, 0) + int(vals[m].sum())
            if m.any():
                want_max[g] = max(want_max.get(g, -2**63), int(vals[m].max()))
    assert rows
    for r in rows:
        e = r["event"]
        g = e["dimA"]
        # every per-group total exceeds int32 — int64 is load-bearing
        assert e["s"] > 2 ** 31
        assert e["s"] == want_sum[g], g
        assert e["mx"] == want_max[g], g


def test_virtual_column_long_cast_exact_near_2_31():
    """The eval_virtual_columns "long" dtype mapping (the x64-dtype true
    positive this PR fixed) must produce exact int64 values: summing a
    virtual long near 2**31 cannot truncate (int32 drift) or round
    (float32 drift rounds 2**31-odd to a multiple of 256)."""
    segments = _segments()
    vc = ExpressionVirtualColumn("vbig", "metBig + 1", "long")
    q = TimeseriesQuery.of(
        "bench", [INTERVAL],
        [CountAggregator("n"), LongSumAggregator("s", "vbig")],
        granularity="all", virtual_columns=[vc])
    rows = QueryExecutor(segments).run(q)
    want = sum(int(seg.metrics["metBig"].values.astype(np.int64).sum())
               + seg.n_rows for seg in segments)
    assert len(rows) == 1
    got = rows[0]["result"]["s"]
    assert got > 2 ** 31
    assert got == want
